"""Multi-stage engine: star joins as one fused shard_map program.

Reference parity: the MSE runtime path — QueryDispatcher.submitAndReduce
(pinot-query-runtime/.../service/dispatch/QueryDispatcher.java:189-211)
shipping plan fragments to workers, LeafOperator scanning segments,
HashJoinOperator build/probe (.../runtime/operator/HashJoinOperator.java),
Hash/BroadcastExchange mailboxes, AggregateOperator, and the broker-side
final reduce.

Re-design (SURVEY.md 2.6, section 7): there are no fragments-over-gRPC.  All
participating tables are resident sharded over ONE mesh, so the whole
multi-stage plan — leaf filters on every table, the exchange, the join
build/probe, and the aggregation — traces into a single jitted shard_map
kernel whose stage boundaries are XLA collectives:

  leaf:      per-device filter masks on fact + dimension shards
  exchange:  BROADCAST (lax.all_gather of the filtered build side) or
             HASH (bucketize + lax.all_to_all of both sides)
  join:      sorted-build + searchsorted probe (mse/join.py)
  aggregate: the existing fused dense group-table kernels + psum combine

Scope: star joins — FROM fact JOIN dim ON fact.fk = dim.pk — INNER/LEFT,
aggregation or group-by on fact and/or dim attributes; build sides may have
NON-unique keys up to a bounded multiplicity (range_join expansion,
joinMaxDup, broadcast strategy, at most one such join per query).
Snowflake chains (fact→dim→dim) and join-output selection of dimension
attributes are supported.  Cross-table predicates (WHERE mixing columns of
both sides outside the ON clause) raise JoinPlanError/NotImplementedError.
"""
from __future__ import annotations

import time
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from pinot_tpu.mse import exchange as ex
from pinot_tpu.mse.join import KEY_SENTINEL, lookup_join, range_join
from pinot_tpu.mse.plan import JoinPlanError, ResolvedQuery, resolve
from pinot_tpu.parallel import mesh as mesh_mod
from pinot_tpu.parallel.engine import (
    _psum_field,
    _ShardView,
    flatten_cols,
    make_agg_inputs,
)
from pinot_tpu.query import executor as sse_executor
from pinot_tpu.query import planner as planner_mod
from pinot_tpu.query import reduce as reduce_mod
from pinot_tpu.query.filter import FilterCompiler
from pinot_tpu.query.ir import Expr, QueryContext
from pinot_tpu.query.planner import GroupDim
from pinot_tpu.query.result import (
    AggSegmentResult,
    DenseGroupData,
    ExecutionStats,
    GroupBySegmentResult,
    ResultTable,
    SelectionSegmentResult,
)
from pinot_tpu.spi.schema import DataType
from types import SimpleNamespace

_INT_KEY_TYPES = (DataType.INT, DataType.LONG, DataType.TIMESTAMP, DataType.BOOLEAN)


def _order_pretrim(order_by, ord_cols, want: int, is_str: List[bool]):
    """Vectorized top-`want` row indices consistent with reduce._sorted_order
    (asc/desc + nulls placement, stable ties).  `is_str` comes from the
    DECLARED column types — numeric-LOOKING strings must rank
    lexicographically like the final Python `<` comparator, never
    numerically (review-caught).  Returns None when a column's values defy
    coding (caller falls back to the full sort).  int64 order values round
    through float64 here (>2^53 ties may trim the 'wrong' equal-ranked row —
    a row set the comparator deems equal)."""
    n = len(ord_cols[0])
    keys = []
    for ob, vals, s in zip(reversed(order_by), reversed(ord_cols), reversed(is_str)):
        a = np.asarray(vals, dtype=object)
        isnull = np.array([v is None for v in a], dtype=bool)
        body = a[~isnull]
        k = np.empty(n, dtype=np.float64)
        try:
            if s:
                # unique over the RAW objects: python `<` ordering (str AND
                # bytes alike) must match the final comparator —
                # astype(str) would rank bytes by their repr (review-caught)
                _, inv = np.unique(body, return_inverse=True)
                num = inv.astype(np.float64)
            else:
                num = body.astype(np.float64)
            k[~isnull] = num if ob.ascending else -num
        except (ValueError, TypeError):
            return None
        k[isnull] = -np.inf if not ob.nulls_last else np.inf
        keys.append(k)
    return np.lexsort(tuple(keys))[:want]


def _max_multiplicity(dim_st, dcol) -> int:
    """Max repeats of one key in the build column (flat order = input order,
    padding at the tail)."""
    arr = dcol.codes if dcol.has_dictionary else dcol.values
    flat = np.asarray(arr).reshape(-1)[: dim_st.num_docs]
    if dcol.has_dictionary:
        counts = np.bincount(flat.astype(np.int64), minlength=dcol.dictionary.cardinality)
    else:
        _, counts = np.unique(flat, return_counts=True)
    return int(counts.max()) if len(counts) else 1


@dataclass
class _JoinPlan:
    """Compile-time recipe for one join stage."""

    dim_table: str
    join_type: str
    fact_key: str
    dim_key: str
    build_key_fn: Callable  # (dim_cols) -> int64 keys
    probe_key_fn: Callable  # (fact_cols, params) -> int64 keys (fact probes)
    attrs: List[str]  # dim columns gathered through the join
    # max build-key multiplicity (1 = unique PK join; >1 = bounded M:N
    # expansion via range_join — see mse/join.py)
    max_dup: int = 1
    # snowflake chain (probe key owned by an earlier-joined dim): index of
    # the parent join whose gathered value array supplies the probe keys
    parent: Optional[int] = None
    # parent columns gathered as int64 VALUES for child probes (chains)
    val_attrs: List[str] = None
    # child-side translate param key (string chain keys: parent dict code ->
    # child build key space)
    trans_key: Optional[str] = None


@dataclass
class _MsePlan:
    kind: str  # "aggregation" | "groupby_dense"
    fn: Callable
    params: Dict[str, Any]
    fact_needed: List[str]
    dim_needed: Dict[str, List[str]]
    aggs: List[Any]
    group_dims: List[GroupDim]
    num_groups: int
    strategy: str  # "broadcast" | "shuffle"
    rq: ResolvedQuery
    # namespace -> param keys sharded on the device axis (index bitmaps)
    sharded_by_ns: Dict[str, frozenset] = None
    index_uses: Tuple = ()
    # selection kind: output columns + per-join (table, join_type) in topo
    # order + the M:N expansion join index (host-side row assembly)
    select_columns: List[str] = None
    joins_info: List[Tuple[str, str]] = None
    dup_idx: Optional[int] = None
    # kernel cost model (utils/perf.KernelCost), captured at first dispatch
    # and shared through the plan cache (hits copy it forward)
    cost: Optional[Any] = None
    # shuffle bucket slack this plan's kernel was TRACED with (cap_f bakes
    # into the program, so slack is part of the plan-cache key); the
    # overflow back-pressure loop doubles it and re-plans
    slack: float = 2.0


class ExchangeOverflowError(RuntimeError):
    """A hash exchange dropped rows (bucket capacity exceeded).  Carries the
    slack the failing plan ran with so the engine's back-pressure loop can
    re-plan with a doubled slack (execute's retry — the TPU analog of
    mailbox back-pressure)."""

    def __init__(self, overflow: int, slack: float):
        self.overflow = int(overflow)
        self.slack = float(slack)
        super().__init__(
            f"hash exchange dropped {self.overflow} rows at shuffleSlack="
            f"{self.slack} (bucket capacity exceeded)"
        )


class MultiStageEngine:
    """Join-capable engine over StackedTables sharing one mesh (1-D seg or
    2-D replica x shard — parallel/mesh.data_axes; on 2-D, exchanges span
    the axes tuple and combines reduce hierarchically, shard/ICI first)."""

    def __init__(self, mesh=None, axis="seg", tables: Optional[Dict[str, Any]] = None):
        if mesh is None:
            from pinot_tpu.parallel.mesh import default_mesh

            mesh = default_mesh(axis if isinstance(axis, str) else axis[0])
        from pinot_tpu.parallel import mesh as mesh_mod
        from pinot_tpu.query.planner import _plan_cache_entries
        from pinot_tpu.utils.cache import LruCache

        self.mesh = mesh
        self.axes = mesh_mod.data_axes(mesh)
        self.axis = self.axes[0] if len(self.axes) == 1 else self.axes
        self.tables: Dict[str, Any] = tables if tables is not None else {}
        # plan-cache bytes charge the process host ledger the admission
        # controller tracks (runtime import: admission is cluster-layer)
        from pinot_tpu.cluster.admission import process_host_budget

        self._plan_cache = LruCache(
            max_entries=_plan_cache_entries(), name="compile.mse", budget=process_host_budget()
        )

    @property
    def num_devices(self) -> int:
        return int(np.prod(self.mesh.devices.shape))

    def register_table(self, name: str, stacked) -> None:
        if stacked.num_shards % self.num_devices:
            raise ValueError(
                f"num_shards={stacked.num_shards} not divisible by mesh size {self.num_devices}"
            )
        self.tables[name] = stacked
        for k in [k for k in self.tables if k.startswith(name + "@")]:
            del self.tables[k]

    def query(self, sql: str) -> ResultTable:
        from pinot_tpu.sql.parser import parse_query

        return self.execute(parse_query(sql))

    # ------------------------------------------------------------------
    def execute(self, ctx: QueryContext) -> ResultTable:
        t0 = time.perf_counter()
        # Overflow back-pressure loop: a shuffle plan whose fixed-capacity
        # exchange buckets dropped rows re-plans with a DOUBLED slack
        # (bounded by _backoff_slack) and re-runs.  Results are exact after
        # the retry — dropped rows never fold into partials because the
        # host checks the psum'd overflow counter before consuming output.
        slack_override: Optional[float] = None
        while True:
            plan = self._plan(ctx, slack=slack_override)
            rq = plan.rq
            fact_st = self.tables[rq.fact]
            stats = ExecutionStats(
                num_segments_queried=fact_st.num_shards,
                num_segments_processed=fact_st.num_shards,
                num_docs_scanned=fact_st.num_docs
                + sum(self.tables[j.table].num_docs for j in rq.joins),
                total_docs=fact_st.num_docs,
            )
            fact_cols, fact_valid = fact_st.to_device(self.mesh, self.axis, plan.fact_needed)
            dim_cols, dim_valids = [], []
            for j in rq.joins:
                st = self.tables[j.table]
                c, v = st.to_device(self.mesh, self.axis, plan.dim_needed[j.table])
                dim_cols.append(c)
                dim_valids.append(v)
            stats.add_index_uses(plan.index_uses)
            rep = NamedSharding(self.mesh, P())
            row = NamedSharding(self.mesh, P(self.axis, None))
            params = {}
            for k, v in plan.params.items():
                if isinstance(v, dict):
                    ns = (plan.sharded_by_ns or {}).get(k, frozenset())
                    params[k] = {
                        k2: jax.device_put(v2, row if k2 in ns else rep) for k2, v2 in v.items()
                    }
                else:
                    params[k] = jax.device_put(v, rep)
            try:
                result = self._run(rq.ctx, plan, fact_cols, fact_valid, dim_cols, dim_valids, params, stats)
                break
            except ExchangeOverflowError as e:
                slack_override = self._backoff_slack(rq.ctx, e)
        out = reduce_mod.reduce_results(rq.ctx, [result], stats)
        out.stats.time_ms = (time.perf_counter() - t0) * 1000
        from pinot_tpu.query.shape import shape_digest
        from pinot_tpu.utils import perf

        perf.PERF_LEDGER.record(
            rq.fact,
            shape_digest(getattr(self, "_last_shape_fp", "")),
            rows=out.stats.num_docs_scanned,
            time_ms=out.stats.time_ms,
            kernel_bytes=out.stats.kernel_bytes,
            compile_ms=out.stats.compile_ms,
            cache_hit=getattr(self, "_last_plan_cache_hit", None),
            engine="mse",
        )
        return out

    # ------------------------------------------------------------------
    def _backoff_slack(self, ctx: QueryContext, err: ExchangeOverflowError) -> float:
        """Back-pressure response to a bucket overflow: double the slack,
        bounded by shuffleSlackCap (default ndev^2 — at that slack every
        bucket can hold the whole global row set, so a further overflow is
        impossible and anything still failing is a bug, not skew)."""
        ndev = self.num_devices
        cap = float(ctx.options.get("shuffleSlackCap", float(ndev * ndev)))
        if err.slack >= cap:
            raise RuntimeError(
                f"hash exchange still dropped {err.overflow} rows at "
                f"shuffleSlack={err.slack} (cap {cap}); raise the "
                "shuffleSlackCap query option if the key skew is expected"
            ) from err
        from pinot_tpu.utils.metrics import METRICS

        METRICS.counter("mse.exchangeOverflowRetries").inc()
        return min(err.slack * 2.0, cap)

    def _plan(self, ctx: QueryContext, slack: Optional[float] = None) -> _MsePlan:
        from pinot_tpu.analysis.compile_audit import MSE_AUDIT
        from pinot_tpu.query.shape import column_info_from, params_structure

        rq = resolve(ctx, self.tables)
        strategy = self._strategy(ctx, rq)
        if slack is None:
            slack = float(ctx.options.get("shuffleSlack", 2.0))
        if strategy != "shuffle":
            slack = 0.0  # broadcast plans never bucketize: one cache entry

        def _info(name: str):
            # column shapes resolve through the owning table (join queries
            # span several); unknown columns bake their literals into the key
            t = getattr(rq, "owner", {}).get(name)
            if t is None or t not in self.tables:
                return None
            return column_info_from(self.tables[t])(name)

        key = (
            rq.ctx.shape_fingerprint(_info),
            tuple(self.tables[t].signature() for t in [rq.fact] + [j.table for j in rq.joins]),
            strategy,
            self.axis,
            self.num_devices,
            # slack bakes into the traced kernel as the bucket capacity, so
            # a retry at doubled slack MUST miss here — reusing the old
            # kernel would silently re-drop the same rows
            slack,
        )
        cached = self._plan_cache.get(key)
        if cached is not None:
            # rebind literals into a fresh plan around the cached jitted
            # kernel; a params-structure mismatch means the shape audit was
            # wrong for this query — count it as the compile it would be
            plan = self._build_plan(rq, strategy, slack, compiled_fn=cached.fn)
            if (
                params_structure(plan.params) == params_structure(cached.params)
                and plan.sharded_by_ns == cached.sharded_by_ns
            ):
                # cost model rides the cache entry (captured once at the
                # cached plan's first dispatch, never re-lowered on hits)
                plan.cost = cached.cost
                MSE_AUDIT.record_hit(key[0])
                self._last_plan_cache_hit = True
                self._last_shape_fp = key[0]
                return plan
        MSE_AUDIT.record_compile(key[0])
        self._last_plan_cache_hit = False
        self._last_shape_fp = key[0]
        plan = self._build_plan(rq, strategy, slack)
        self._plan_cache.put(key, plan)
        return plan

    def _strategy(self, ctx: QueryContext, rq: ResolvedQuery) -> str:
        opt = ctx.options.get("joinStrategy")
        if opt is not None and opt not in ("broadcast", "shuffle"):
            raise ValueError(
                f"unknown joinStrategy {opt!r} (expected 'broadcast' or 'shuffle')"
            )
        if opt == "shuffle" and len(rq.joins) > 1:
            raise NotImplementedError(
                "hash-shuffle joins partition fact rows by one key; multi-join "
                "queries must use the broadcast strategy"
            )
        is_selection = not ctx.is_aggregate and not ctx.group_by
        chained = any(j.probe_owner and j.probe_owner != rq.fact for j in rq.joins)
        if chained or is_selection:
            # snowflake chains probe through gathered parent rows; selection
            # maps build rows back to host doc ids — both need every build
            # side replicated (broadcast)
            if opt == "shuffle":
                raise NotImplementedError(
                    "snowflake chains and join-output selection require the "
                    "broadcast strategy (build rows must be globally addressable)"
                )
            return "broadcast"
        # many-to-many build sides need the broadcast expansion path
        def _dup(j) -> bool:
            dcol = self.tables[j.table].column(j.dim_key)
            distinct = dcol.dictionary.cardinality if dcol.has_dictionary else dcol.stats.cardinality
            return distinct < self.tables[j.table].num_docs

        if any(_dup(j) for j in rq.joins):
            if opt == "shuffle":
                raise NotImplementedError(
                    "many-to-many joins ride the broadcast expansion; joinStrategy='shuffle' "
                    "requires unique build keys"
                )
            return "broadcast"
        if opt in ("broadcast", "shuffle"):
            return str(opt)
        if len(rq.joins) > 1:
            return "broadcast"
        # broadcast when every build side is small enough to replicate
        threshold = int(ctx.options.get("broadcastJoinRowThreshold", 1 << 22))
        if all(self.tables[j.table].num_docs <= threshold for j in rq.joins):
            return "broadcast"
        return "shuffle"

    # ------------------------------------------------------------------
    def _key_plan(self, idx: int, rq: ResolvedQuery, params: Dict[str, Any]) -> _JoinPlan:
        j = rq.joins[idx]
        probe_owner = j.probe_owner or rq.fact
        probe_st = self.tables[probe_owner]
        dim_st = self.tables[j.table]
        fcol = probe_st.column(j.fact_key)
        dcol = dim_st.column(j.dim_key)
        is_chain = probe_owner != rq.fact
        parent = (
            next(i for i, rj in enumerate(rq.joins[:idx]) if rj.table == probe_owner)
            if is_chain
            else None
        )

        distinct = dcol.dictionary.cardinality if dcol.has_dictionary else dcol.stats.cardinality
        max_dup = 1
        if distinct < dim_st.num_docs:
            # many-to-many: bound the expansion by the true max multiplicity
            # (host-side, unfiltered — a safe static upper bound)
            max_dup = _max_multiplicity(dim_st, dcol)
            cap = int(rq.ctx.options.get("joinMaxDup", 64))
            if max_dup > cap:
                raise NotImplementedError(
                    f"join build side {j.table}.{j.dim_key} has keys repeated up to "
                    f"{max_dup}x; the static expansion is capped at joinMaxDup={cap} "
                    "(raise the option or pre-aggregate the build side)"
                )

        fname, dname = j.fact_key, j.dim_key
        trans_key = None
        probe_key = None
        string_like = dcol.data_type.is_string_like or fcol.data_type.is_string_like
        if string_like:
            if not (dcol.has_dictionary and fcol.has_dictionary):
                raise NotImplementedError("string join keys require dictionaries on both sides")
            dvals, fvals = dcol.dictionary.values, fcol.dictionary.values
            pos = np.searchsorted(dvals, fvals)
            posc = np.clip(pos, 0, max(0, len(dvals) - 1))
            ok = (dvals[posc] == fvals) if len(dvals) else np.zeros(len(fvals), bool)
            trans = np.where(ok, posc, np.iinfo(np.int64).max).astype(np.int64)
            tkey = f"join{idx}.trans"
            params[tkey] = trans
            trans_key = tkey

            def build_key(dcols, _d=dname):
                return dcols[_d]["codes"].astype(jnp.int64)

            if not is_chain:

                def probe_key(fcols, p, _f=fname, _t=tkey):
                    return p[_t][fcols[_f]["codes"].astype(jnp.int32)]

        elif dcol.data_type in _INT_KEY_TYPES and fcol.data_type in _INT_KEY_TYPES:

            def _int_key(cols, name, col):
                if col.has_dictionary:
                    return cols[name]["dict"][cols[name]["codes"].astype(jnp.int32)].astype(jnp.int64)
                return cols[name]["values"].astype(jnp.int64)

            def build_key(dcols, _d=dname, _c=dcol):
                return _int_key(dcols, _d, _c)

            if not is_chain:

                def probe_key(fcols, p, _f=fname, _c=fcol):
                    return _int_key(fcols, _f, _c)

        else:
            raise NotImplementedError(
                f"join keys must be integer or string typed "
                f"(got {fcol.data_type.value} = {dcol.data_type.value})"
            )

        # null join keys never match (SQL equi-join semantics); chain probe
        # nulls are folded in at the parent's value gather instead
        if probe_key is not None and fcol.nulls is not None:
            inner_probe = probe_key

            def probe_key(fcols, p, _f=fname, _inner=inner_probe):
                k = _inner(fcols, p)
                return jnp.where(fcols[_f]["nulls"], KEY_SENTINEL, k)

        if dcol.nulls is not None:
            inner_build = build_key

            def build_key(dcols, _d=dname, _inner=inner_build):
                k = _inner(dcols)
                return jnp.where(dcols[_d]["nulls"], KEY_SENTINEL, k)

        return _JoinPlan(
            j.table, j.join_type, fname, dname, build_key, probe_key,
            attrs=[], max_dup=max_dup, parent=parent, val_attrs=[], trans_key=trans_key,
        )

    def _dim_group_dim(
        self, expr: Expr, table: str, left_join: bool, null_handling: bool
    ) -> Tuple[GroupDim, int]:
        """Returns (GroupDim, placeholder_code): placeholder_code >= 0 marks
        the dictionary code of the SQL-NULL placeholder when a LEFT JOIN
        forces the null slot to live PAST the dictionary — the kernel remaps
        placeholder-coded rows onto the no-match slot so the NULL group does
        not split in two."""
        c = self.tables[table].column(expr.op)
        if c.has_dictionary:
            card = c.dictionary.cardinality
            null_code = -1
            if c.nulls is not None and null_handling:
                nc = c.dictionary.index_of(c.data_type.null_placeholder)
                if nc >= 0:
                    null_code = nc
            if left_join:
                placeholder = null_code  # may be -1 (no nulls stored)
                null_code = card
                card += 1
                return (
                    GroupDim(expr, c.name, "dict", card, dictionary=c.dictionary, null_code=null_code),
                    placeholder,
                )
            return (
                GroupDim(expr, c.name, "dict", card, dictionary=c.dictionary, null_code=null_code),
                -1,
            )
        if c.data_type in _INT_KEY_TYPES and c.stats.min_value is not None:
            lo, hi = int(c.stats.min_value), int(c.stats.max_value)
            rng = hi - lo + 1
            if rng <= planner_mod.MAX_DENSE_RAW_INT_RANGE:
                card, null_code = (rng + 1, rng) if left_join else (rng, -1)
                return GroupDim(expr, c.name, "rawint", card, base=lo, null_code=null_code), -1
        raise NotImplementedError(f"group-by on dimension column {expr.op} (type/range unsupported)")

    # ------------------------------------------------------------------
    def _build_plan(
        self,
        rq: ResolvedQuery,
        strategy: str,
        slack: float,
        compiled_fn: Optional[Callable] = None,
    ) -> _MsePlan:
        ctx = rq.ctx
        axis = self.axis
        ndev = self.num_devices
        fact_st = self.tables[rq.fact]
        local_rows = (fact_st.num_shards // ndev) * fact_st.docs_per_shard
        fact_view = _ShardView(fact_st, local_rows, axis=axis, ndev=ndev)
        null_handling = ctx.null_handling

        params: Dict[str, Any] = {}
        sharded_by_ns: Dict[str, frozenset] = {}
        index_uses: List[Tuple[str, str]] = []
        fc_fact = FilterCompiler(fact_view, null_handling)
        fact_filter_fn = fc_fact.compile(rq.fact_filter)
        params["fact"] = fc_fact.params

        join_plans: List[_JoinPlan] = []
        dim_filter_fns: List[Callable] = []
        dim_views: List[Any] = []
        dim_used_columns: List[set] = []
        for i, rj in enumerate(rq.joins):
            dim_st = self.tables[rj.table]
            d_local = (dim_st.num_shards // ndev) * dim_st.docs_per_shard
            dview = _ShardView(dim_st, d_local, axis=axis, ndev=ndev)
            dim_views.append(dview)
            fc = FilterCompiler(dview, null_handling)
            dim_filter_fns.append(fc.compile(rq.dim_filters[rj.table]))
            params[f"dimf{i}"] = fc.params
            sharded_by_ns[f"dimf{i}"] = frozenset(fc.row_sharded_params)
            index_uses.extend(fc.index_uses)
            dim_used_columns.append(set(fc.used_columns))
            join_plans.append(self._key_plan(i, rq, params))

        # -- snowflake chains: parents gather probe-key VALUES -------------
        for i, jp in enumerate(join_plans):
            if jp.parent is not None:
                pjp = join_plans[jp.parent]
                if pjp.max_dup > 1:
                    raise NotImplementedError(
                        f"snowflake chain through many-to-many join {pjp.dim_table!r} "
                        "is unsupported (pre-aggregate the M:N build side)"
                    )
                if jp.fact_key not in pjp.val_attrs:
                    pjp.val_attrs.append(jp.fact_key)
                if jp.max_dup > 1:
                    raise NotImplementedError(
                        "a many-to-many build side must join to the fact table directly"
                    )

        # -- aggregations (fact-side inputs only) ------------------------
        agg_specs = list(ctx.aggregations)
        for s in agg_specs:
            for col in ([] if s.expr is None else s.expr.columns()) + (
                s.filter.columns() if s.filter is not None else []
            ):
                if col != "*" and rq.owner[col] != rq.fact:
                    raise NotImplementedError(
                        f"aggregation input {col!r} belongs to joined table "
                        f"{rq.owner[col]!r}; only fact-table measures are supported"
                    )
        aggs = planner_mod.bind_aggs(agg_specs, fact_st, ctx)
        agg_filter_fns = [
            fc_fact.compile(s.filter) if s.filter is not None else None for s in agg_specs
        ]
        agg_inputs_fn = make_agg_inputs(
            agg_specs, aggs, agg_filter_fns, fact_view, fact_st, null_handling
        )
        sharded_by_ns["fact"] = frozenset(fc_fact.row_sharded_params)
        index_uses.extend(fc_fact.index_uses)

        # -- group dimensions --------------------------------------------
        group_dims: List[GroupDim] = []
        dim_of_group: List[Optional[int]] = []  # join index or None (fact)
        group_placeholder: List[int] = []  # LEFT-JOIN placeholder remap code
        for g in ctx.group_by:
            if not g.is_column:
                raise NotImplementedError(f"group-by on expression {g} not yet supported")
            t = rq.owner[g.op]
            if t == rq.fact:
                group_dims.append(planner_mod._group_dim(g, fact_view, null_handling))
                dim_of_group.append(None)
                group_placeholder.append(-1)
            else:
                ji = next(i for i, jp in enumerate(join_plans) if jp.dim_table == t)
                left = join_plans[ji].join_type == "left"
                gd, placeholder = self._dim_group_dim(g, t, left, null_handling)
                group_dims.append(gd)
                dim_of_group.append(ji)
                group_placeholder.append(placeholder)
                if g.op not in join_plans[ji].attrs:
                    join_plans[ji].attrs.append(g.op)

        select_columns: List[str] = []
        if ctx.is_aggregate and not ctx.group_by:
            kind = "aggregation"
            num_groups = 0
        elif ctx.group_by:
            kind = "groupby_dense"
            num_groups = 1
            for gd in group_dims:
                num_groups *= max(1, gd.cardinality)
            if num_groups > ctx.max_dense_groups:
                raise NotImplementedError(
                    f"join group-by key space {num_groups} exceeds maxDenseGroups "
                    f"({ctx.max_dense_groups}); high-cardinality join group-by is unsupported"
                )
        else:
            # join-output selection (round 5, VERDICT r4 #7): return joined
            # ROWS — the kernel produces the match mask + build-row indices,
            # the host gathers/decodes columns through them
            # (HashJoinOperator + LookupJoinOperator output semantics)
            kind = "selection"
            num_groups = 0
            for s in ctx.select_list:
                if not (isinstance(s, Expr) and s.is_column):
                    raise NotImplementedError(
                        f"join selection supports bare columns only (got {s})"
                    )
                if s.op == "*":
                    raise NotImplementedError("SELECT * over joins is unsupported; list columns")
                select_columns.append(s.op)
            for ob in ctx.order_by:
                if not ob.expr.is_column:
                    raise NotImplementedError("join selection ORDER BY supports bare columns only")

        planner_mod.guard_sparse_vector_fields(kind, aggs)
        if any(fn.pairwise_merge for fn in aggs):
            raise NotImplementedError(
                "pairwise-merge aggregations cannot ride the in-graph psum combine"
            )
        vranges = planner_mod.agg_vranges(agg_specs, fact_st)

        # -- needed columns ----------------------------------------------
        fact_needed: List[str] = []

        def need_fact(cols):
            for c in cols:
                if c != "*" and c not in fact_needed:
                    fact_needed.append(c)

        # filter-scanned columns come from the compiler's used set — columns
        # whose predicates resolved through an index never ship to device
        need_fact(sorted(fc_fact.used_columns))
        for s in agg_specs:
            if s.expr is not None:
                need_fact(s.expr.columns())
        for jp in join_plans:
            if jp.parent is None:  # chain probes read the PARENT DIM's rows
                need_fact([jp.fact_key])
        for g, di in zip(ctx.group_by, dim_of_group):
            if di is None:
                need_fact([g.op])
        dim_needed: Dict[str, List[str]] = {}
        for i, (jp, dview) in enumerate(zip(join_plans, dim_views)):
            cols = [jp.dim_key] + list(jp.attrs)
            cols += [a for a in jp.val_attrs if a not in cols]
            cols += [c for c in sorted(dim_used_columns[i]) if c not in cols]
            dim_needed[jp.dim_table] = cols

        # -- dim attr array access (codes for dict, raw values otherwise) --
        # Raw values stay in their source dtype until the base subtraction:
        # casting first would wrap values beyond int32 (the code AFTER the
        # subtraction always fits — cardinality <= MAX_DENSE_RAW_INT_RANGE).
        def attr_array(dcols, table: str, name: str):
            c = self.tables[table].column(name)
            if c.has_dictionary:
                return dcols[name]["codes"].astype(jnp.int32)
            return dcols[name]["values"]

        def val_array(dcols, table: str, name: str):
            """int64 probe-key VALUES of a parent-dim column for snowflake
            chains: dict codes for string keys (children translate), decoded
            values for ints; stored nulls become the never-match sentinel."""
            c = self.tables[table].column(name)
            if c.data_type.is_string_like:
                v = dcols[name]["codes"].astype(jnp.int64)
            elif c.has_dictionary:
                v = dcols[name]["dict"][dcols[name]["codes"].astype(jnp.int32)].astype(jnp.int64)
            else:
                v = dcols[name]["values"].astype(jnp.int64)
            if c.nulls is not None:
                v = jnp.where(dcols[name]["nulls"], KEY_SENTINEL, v)
            return v

        def group_code(gd: GroupDim, arr):
            if gd.kind == "rawint":
                return (arr - np.asarray(gd.base, dtype=arr.dtype)).astype(jnp.int32)
            return arr

        def fact_group_code(gd: GroupDim, fcols):
            if gd.kind == "dict":
                return fcols[gd.name]["codes"].astype(jnp.int32)
            v = fcols[gd.name]["values"]
            return (v - np.asarray(gd.base, dtype=v.dtype)).astype(jnp.int32)

        # bounded M:N expansion (at most one non-unique build side)
        dup_idxs = [i for i, jp in enumerate(join_plans) if jp.max_dup > 1]
        if len(dup_idxs) > 1:
            raise NotImplementedError(
                "at most one join may have a many-to-many build side "
                f"(got {len(dup_idxs)}); pre-aggregate the other build sides"
            )
        dup_idx = dup_idxs[0] if dup_idxs else None
        if dup_idx is not None and strategy != "broadcast":
            raise NotImplementedError("many-to-many joins require the broadcast strategy")

        # ------------------------------------------------------------------
        def shard_kernel(fact_cols, fact_valid, dim_cols_list, dim_valids, params):
            fcols = flatten_cols(fact_cols)
            fmask, _ = fact_filter_fn(fcols, params["fact"])
            fmask = fmask & fact_valid.reshape(-1)
            overflow = jnp.int32(0)

            # leaf + exchange + probe per join (topological order: snowflake
            # parents run before their children)
            gathered: Dict[Tuple[int, str], Any] = {}
            gathered_vals: Dict[Tuple[int, str], Any] = {}  # chain probe keys
            matches: List[Any] = []
            brows: List[Any] = []

            if strategy == "broadcast":
                probe_cols = fcols
                probe_mask = fmask
                for i, jp in enumerate(join_plans):
                    dcols = flatten_cols(dim_cols_list[i])
                    dmask, _ = dim_filter_fns[i](dcols, params[f"dimf{i}"])
                    dmask = dmask & dim_valids[i].reshape(-1)
                    side = {"key": jp.build_key_fn(dcols), "ok": dmask}
                    for a in jp.attrs:
                        side[a] = attr_array(dcols, jp.dim_table, a)
                    for a in jp.val_attrs:
                        side["__val__" + a] = val_array(dcols, jp.dim_table, a)
                    g = ex.broadcast_rows(side, axis)
                    if jp.parent is None:
                        pk = jp.probe_key_fn(fcols, params)
                    else:
                        # chain probe: the parent's gathered value per fact row
                        pv = gathered_vals[(jp.parent, jp.fact_key)]
                        if jp.trans_key is not None:
                            t = params[jp.trans_key]
                            idx = jnp.clip(pv, 0, t.shape[0] - 1).astype(jnp.int32)
                            pk = jnp.where(pv == KEY_SENTINEL, KEY_SENTINEL, t[idx])
                        else:
                            pk = pv
                    if i == dup_idx:
                        # bounded M:N: [P, max_dup] expansion; validity folds
                        # into exp_mask below, not the 1-D probe_mask
                        brow, match = range_join(g["key"], g["ok"], pk, jp.max_dup)
                        matches.append(match)
                    else:
                        brow, match = lookup_join(g["key"], g["ok"], pk)
                        matches.append(match)
                        if jp.join_type == "inner":
                            probe_mask = probe_mask & match
                    brows.append(brow)
                    for a in jp.attrs:
                        gathered[(i, a)] = g[a][brow]
                    for a in jp.val_attrs:
                        gathered_vals[(i, a)] = jnp.where(
                            match, g["__val__" + a][brow], KEY_SENTINEL
                        )
            else:  # hash shuffle
                # fact payload: key per join, group codes, agg inputs
                payload: Dict[str, Any] = {}
                for i, jp in enumerate(join_plans):
                    payload[f"k{i}"] = jp.probe_key_fn(fcols, params)
                for gi, (gd, di) in enumerate(zip(group_dims, dim_of_group)):
                    if di is None:
                        payload[f"g{gi}"] = fact_group_code(gd, fcols)
                inputs = agg_inputs_fn(fcols, params["fact"], fmask)
                for ai, (v, m) in enumerate(inputs):
                    payload[f"av{ai}"] = jnp.broadcast_to(v, fmask.shape)
                    payload[f"am{ai}"] = m
                # partition fact rows by the join key's hash (single join
                # only — enforced in _strategy)
                dest = ex.hash_dest(payload["k0"], ndev)
                cap_f = max(1, int(-(-local_rows // ndev) * slack))
                recv, rvalid, ovf = ex.hash_repartition(payload, dest, fmask, ndev, cap_f, axis)
                overflow = overflow + ovf
                probe_cols = recv
                probe_mask = rvalid

                for i, jp in enumerate(join_plans):
                    dcols = flatten_cols(dim_cols_list[i])
                    dmask, _ = dim_filter_fns[i](dcols, params[f"dimf{i}"])
                    dmask = dmask & dim_valids[i].reshape(-1)
                    dkey = jp.build_key_fn(dcols)
                    side = {"key": dkey}
                    for a in jp.attrs:
                        side[a] = attr_array(dcols, jp.dim_table, a)
                    d_local = dkey.shape[0]
                    cap_d = max(1, int(-(-d_local // ndev) * slack))
                    drecv, dvalid_r, dovf = ex.hash_repartition(
                        side, ex.hash_dest(dkey, ndev), dmask, ndev, cap_d, axis
                    )
                    overflow = overflow + dovf
                    brow, match = lookup_join(drecv["key"], dvalid_r, recv[f"k{i}"])
                    matches.append(match)
                    if jp.join_type == "inner":
                        probe_mask = probe_mask & match
                    for a in jp.attrs:
                        gathered[(i, a)] = drecv[a][brow]

            # -- M:N expansion mask ([P, D] slot validity) -----------------
            exp_mask = None
            if dup_idx is not None:
                D = join_plans[dup_idx].max_dup
                m2 = matches[dup_idx]
                if join_plans[dup_idx].join_type == "left":
                    # LEFT with zero matches: one surviving slot (0) carrying
                    # the null dim code
                    nomatch = ~jnp.any(m2, axis=1)
                    slot0 = jnp.arange(D) == 0
                    m2 = m2 | (nomatch[:, None] & slot0[None, :])
                exp_mask = probe_mask[:, None] & m2

            def _expand_rows(v):
                """[P] row array -> flat [P*D] under the expansion."""
                return jnp.broadcast_to(v[:, None], exp_mask.shape).reshape(-1)

            # -- selection: ship match mask + build-row indices only --------
            if kind == "selection":
                out = {"mask": probe_mask}
                for i in range(len(join_plans)):
                    out[f"brow{i}"] = brows[i].astype(jnp.int32)
                    out[f"match{i}"] = matches[i]
                if exp_mask is not None:
                    out["exp"] = exp_mask
                return out, overflow

            # -- aggregate ------------------------------------------------
            if strategy == "broadcast":
                inputs = agg_inputs_fn(fcols, params["fact"], probe_mask)
            else:
                inputs = [
                    (probe_cols[f"av{ai}"], probe_cols[f"am{ai}"] & probe_mask)
                    for ai in range(len(agg_specs))
                ]
            if exp_mask is not None:
                flat_exp = exp_mask.reshape(-1)
                inputs = [
                    (
                        _expand_rows(jnp.broadcast_to(v, probe_mask.shape)),
                        _expand_rows(m) & flat_exp,
                    )
                    for v, m in inputs
                ]
                tmask = flat_exp
            else:
                tmask = probe_mask

            if kind == "aggregation":
                partials = [fn.partial(v, m) for fn, (v, m) in zip(aggs, inputs)]
                partials = [
                    {f: _psum_field(f, x, axis) for f, x in p.items()} for p in partials
                ]
                return partials, overflow

            # group key assembly
            key = None
            for gi, (gd, di) in enumerate(zip(group_dims, dim_of_group)):
                if di is None:
                    if strategy == "broadcast":
                        code = fact_group_code(gd, fcols)
                    else:
                        code = probe_cols[f"g{gi}"]
                    if exp_mask is not None:
                        code = _expand_rows(code)
                else:
                    code = group_code(gd, gathered[(di, gd.expr.op)])
                    match = matches[di]
                    if join_plans[di].join_type == "left":
                        code = jnp.where(match, code, jnp.int32(gd.null_code))
                        # stored-NULL placeholder joins the no-match NULL slot
                        ph = group_placeholder[gi]
                        if ph >= 0:
                            code = jnp.where(code == jnp.int32(ph), jnp.int32(gd.null_code), code)
                    else:
                        code = jnp.where(match, code, jnp.int32(0))
                    if exp_mask is not None:
                        code = code.reshape(-1) if di == dup_idx else _expand_rows(code)
                code = jnp.clip(code, 0, gd.cardinality - 1)
                key = code if key is None else key * jnp.int32(gd.cardinality) + code
            presence, partials = planner_mod.grouped_partials(
                aggs, inputs, tmask, key, num_groups, vranges
            )
            presence = mesh_mod.psum_hierarchical(presence, axis)
            partials = [
                {f: _psum_field(f, x, axis) for f, x in p.items()} for p in partials
            ]
            return (presence, partials), overflow

        # -- specs ----------------------------------------------------------
        def _col_specs(cols):
            out = {}
            for name, entry in cols.items():
                out[name] = {
                    k: (P(axis, None) if k in ("codes", "values", "nulls") else P())
                    for k in entry
                }
            return out

        mesh = self.mesh

        def _param_specs(params):
            out = {}
            for k, v in params.items():
                if isinstance(v, dict):
                    ns = sharded_by_ns.get(k, frozenset())
                    out[k] = {k2: (P(axis, None) if k2 in ns else P()) for k2 in v}
                else:
                    out[k] = P()
            return out

        if kind == "selection":
            sel_specs = {"mask": P(axis)}
            for i in range(len(join_plans)):
                two_d = i == dup_idx
                sel_specs[f"brow{i}"] = P(axis, None) if two_d else P(axis)
                sel_specs[f"match{i}"] = P(axis, None) if two_d else P(axis)
            if dup_idx is not None:
                sel_specs["exp"] = P(axis, None)
            out_spec = (sel_specs, P())
        else:
            out_spec = (P(), P())

        def run(fact_cols, fact_valid, dim_cols_list, dim_valids, params):
            from pinot_tpu.parallel.engine import shard_map_compat

            kern = shard_map_compat(
                shard_kernel,
                mesh=mesh,
                in_specs=(
                    _col_specs(fact_cols),
                    P(axis, None),
                    tuple(_col_specs(c) for c in dim_cols_list),
                    tuple(P(axis, None) for _ in dim_valids),
                    _param_specs(params),
                ),
                out_specs=out_spec,
            )
            return kern(fact_cols, fact_valid, tuple(dim_cols_list), tuple(dim_valids), params)

        fn = compiled_fn if compiled_fn is not None else jax.jit(run)
        return _MsePlan(
            kind=kind,
            fn=fn,
            params=params,
            fact_needed=fact_needed,
            dim_needed=dim_needed,
            aggs=aggs,
            group_dims=group_dims,
            num_groups=num_groups,
            strategy=strategy,
            rq=rq,
            sharded_by_ns=sharded_by_ns,
            index_uses=tuple(index_uses),
            select_columns=select_columns,
            joins_info=[(jp.dim_table, jp.join_type) for jp in join_plans],
            dup_idx=dup_idx,
            slack=slack,
        )

    # ------------------------------------------------------------------
    def _run(self, ctx, plan: _MsePlan, fact_cols, fact_valid, dim_cols, dim_valids, params, stats):
        from pinot_tpu.utils import perf

        first_dispatch = plan.cost is None
        if first_dispatch:
            # fact-side scan dominates the byte traffic; dim tables are
            # broadcast-small by strategy, so the analytic model reads the
            # fact columns only (the XLA source covers everything)
            fact_st = self.tables[plan.rq.fact]
            plan.cost = perf.capture_cost(
                plan.fn,
                (fact_cols, fact_valid, dim_cols, dim_valids, params),
                perf.analytic_cost(
                    fact_st.num_docs,
                    perf.analytic_bytes_per_row(
                        fact_st.column(n) for n in plan.fact_needed
                    ),
                    kind=plan.kind,
                    num_groups=plan.num_groups,
                    num_entries=len(plan.aggs) if plan.aggs else 1,
                ),
            )
        td0 = time.perf_counter()
        out, overflow = plan.fn(fact_cols, fact_valid, dim_cols, dim_valids, params)
        if first_dispatch:
            plan.cost.compile_ms = (time.perf_counter() - td0) * 1000.0
            stats.compile_ms += plan.cost.compile_ms + plan.cost.lower_ms
        stats.kernel_bytes += plan.cost.bytes_accessed
        stats.kernel_flops += plan.cost.flops
        stats.kernel_cost_source = plan.cost.source
        overflow = int(jax.device_get(overflow))
        if overflow:
            # execute()'s back-pressure loop catches this, doubles the slack
            # (bounded by shuffleSlackCap) and re-plans + re-runs
            raise ExchangeOverflowError(overflow, plan.slack)
        if plan.kind == "aggregation":
            return AggSegmentResult(partials=jax.device_get(out))
        if plan.kind == "selection":
            return self._gather_join_selection(ctx, plan, jax.device_get(out))
        presence, partials = jax.device_get(out)
        presence = np.asarray(presence)
        shim = SimpleNamespace(group_dims=plan.group_dims, aggs=plan.aggs)
        dense = DenseGroupData(
            presence=presence,
            partials=partials,
            key_space=sse_executor._key_space_id(shim),
            group_dims=plan.group_dims,
        )
        keys, sliced = sse_executor._dense_to_present(
            shim, presence, partials, ctx.num_groups_limit,
            order_trim=planner_mod.order_by_agg_index(ctx),
        )
        stats.num_groups = len(keys[0]) if keys else 0
        return GroupBySegmentResult(keys=keys, partials=sliced, dense=dense)

    # ------------------------------------------------------------------
    def _gather_join_selection(self, ctx, plan: _MsePlan, sel):
        """Join-output selection rows (HashJoinOperator output semantics):
        the kernel shipped [rows] match masks + build-row indices (global dim
        flat order — broadcast gathers in mesh order); columns decode host-
        side through them.  LEFT no-match rows yield SQL NULL dim values."""
        rq = plan.rq
        fact_st = self.tables[rq.fact]
        mask = np.asarray(sel["mask"]).reshape(-1)
        exp = np.asarray(sel["exp"]) if "exp" in sel else None
        if exp is not None:
            frow, slot = np.nonzero(exp)
        else:
            frow = np.nonzero(mask)[0]
            slot = None
        want = ctx.offset + ctx.limit

        def col_out(name: str, rows: np.ndarray, slots) -> np.ndarray:
            t = rq.owner[name]
            if t == rq.fact:
                c = fact_st.column(name)
                vals = fact_st.decoded_rows(name, rows)
                if c.nulls is not None and ctx.null_handling:
                    vals = np.asarray(vals, dtype=object)
                    vals[c.nulls.reshape(-1)[rows]] = None
                return vals
            ji = next(i for i, (tb, _) in enumerate(plan.joins_info) if tb == t)
            st = self.tables[t]
            if ji == plan.dup_idx:
                br = np.asarray(sel[f"brow{ji}"])[rows, slots]
                mt = np.asarray(sel[f"match{ji}"])[rows, slots]
            else:
                br = np.asarray(sel[f"brow{ji}"])[rows]
                mt = np.asarray(sel[f"match{ji}"])[rows]
            total = st.num_shards * st.docs_per_shard
            safe = np.clip(br, 0, max(0, total - 1))
            c = st.column(name)
            vals = np.asarray(st.decoded_rows(name, safe), dtype=object)
            if c.nulls is not None and ctx.null_handling:
                vals[c.nulls.reshape(-1)[safe]] = None
            vals[~mt] = None  # LEFT no-match: SQL NULL (inner rows always match)
            return vals

        if not ctx.order_by and len(frow) > want:
            frow = frow[:want]
            slot = slot[:want] if slot is not None else None
        elif ctx.order_by and len(frow) > want:
            # top-`want` pre-trim under the same comparator the reduce sort
            # applies — without it every matching row materializes host-side
            # as object arrays for a LIMIT-sized answer (review-caught)
            def _col_type(name: str):
                t = rq.owner[name]
                st = fact_st if t == rq.fact else self.tables[t]
                return st.column(name).data_type

            ord_cols = [col_out(ob.expr.op, frow, slot) for ob in ctx.order_by]
            is_str = [_col_type(ob.expr.op).is_string_like for ob in ctx.order_by]
            keep = _order_pretrim(ctx.order_by, ord_cols, want, is_str)
            if keep is not None:
                frow = frow[keep]
                slot = slot[keep] if slot is not None else None

        arrays: Dict[str, np.ndarray] = {}
        for name in plan.select_columns:
            arrays[name] = col_out(name, frow, slot)
        for i, ob in enumerate(ctx.order_by):
            arrays[f"__ord{i}"] = col_out(ob.expr.op, frow, slot)
        cols_out = plan.select_columns + [f"__ord{i}" for i in range(len(ctx.order_by))]
        return SelectionSegmentResult(columns=cols_out, arrays=arrays)
