"""pinot_tpu — a TPU-native real-time OLAP framework.

A ground-up re-design of Apache Pinot's capability set (reference surveyed in
/root/repo/SURVEY.md) for TPU hardware: immutable columnar segments pinned in
HBM as JAX device arrays, filter->project->aggregate compiled as jax.jit/Pallas
kernels, per-segment combine as psum/shard_map collectives over ICI, and the
surrounding system (stream ingestion, upsert, indexes, SQL, multi-stage joins,
cluster control plane) rebuilt idiomatically.

Layer map (mirrors SURVEY.md section 1, re-architected):
  spi/       - schema, table config, column types        (pinot-spi analog)
  segment/   - columnar segment format, build/load       (pinot-segment-* analog)
  indexes/   - inverted/range/bloom/star-tree/...        (index SPI analog)
  query/     - IR, planner, jit kernels, executor        (pinot-core SSE analog)
  sql/       - SQL parser -> IR                          (CalciteSqlParser analog)
  parallel/  - device mesh, shard_map combine            (scatter-gather analog)
  realtime/  - mutable segments, stream consumption      (realtime analog)
  mse/       - multi-stage engine: joins, exchanges      (pinot-query-* analog)
  cluster/   - coordinator, broker, server, minion, MVs  (controller/broker/server)
  timeseries/- bucketed series engine                    (pinot-timeseries analog)
  ingest/    - CSV/JSON record readers                   (input-format analog)
  tools/     - admin CLI                                 (pinot-tools analog)
(plus native/ at the repo root: first-party C++ bitmap codec + CSV scanner)
"""

# OLAP semantics require 64-bit LONG/DOUBLE (Pinot aggregates into long/double;
# golden tests compare against 64-bit sqlite). Hot-path code arrays stay int32/
# uint8/16; only reductions widen.  Must run before any jax array creation.
import os

import jax

jax.config.update("jax_enable_x64", True)

# Honor JAX_PLATFORMS even when an ambient sitecustomize pre-registered a
# hardware platform before this env var could take effect (the config path
# works where the env latch does not; no-op on normal installations).
if os.environ.get("JAX_PLATFORMS"):
    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

__version__ = "0.1.0"

from pinot_tpu.spi.schema import DataType, FieldSpec, FieldRole, Schema  # noqa: E402,F401
from pinot_tpu.spi.config import TableConfig  # noqa: E402,F401


def __getattr__(name):  # lazy top-level conveniences (avoid import cycles)
    if name == "QueryEngine":
        from pinot_tpu.query.engine import QueryEngine

        return QueryEngine
    if name == "build_segment":
        from pinot_tpu.segment.builder import build_segment

        return build_segment
    raise AttributeError(f"module 'pinot_tpu' has no attribute {name!r}")
