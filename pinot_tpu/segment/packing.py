"""Bit-packed forward indexes: dictionary codes in 4/8/16-bit lanes.

Codes for a dictionary column with cardinality C need only
ceil(log2(C)) bits each; storing them at int32 (or even uint8/16)
width wastes HBM bandwidth on the scan hot path.  This module packs
codes into little-endian lanes inside uint32 words:

    factor f = 32 // bits          lanes per word
    word w, lane l                 covers row w * f + l
    code  = (word >> (bits * l)) & ((1 << bits) - 1)

The layout deliberately generalizes the range-index bitmap layout
(bits=1: bit r of word w covers row 32*w + r), so the Pallas kernel's
word-unpack machinery serves both.

Only power-of-two lane widths that divide 32 are used (4/8/16); a
column whose cardinality needs >16 bits stays unpacked (32 means "no
packing").  Multi-value columns stay unpacked too: their padding code
equals the cardinality, which may not fit the lane width chosen from
cardinality alone.
"""
from __future__ import annotations

import numpy as np

LANE_WIDTHS = (4, 8, 16)


def lane_bits(cardinality: int) -> int:
    """Narrowest supported lane width for a dictionary of this size.

    Returns 32 when the column does not benefit (codes would need more
    than 16 bits), meaning "store unpacked".
    """
    for bits in LANE_WIDTHS:
        if cardinality <= (1 << bits):
            return bits
    return 32


def pack_codes(codes: np.ndarray, bits: int) -> np.ndarray:
    """Pack int codes into uint32 words, `32 // bits` lanes per word.

    The tail word is zero-padded (zero is always a valid in-range lane,
    and consumers mask rows >= n).
    """
    if bits not in LANE_WIDTHS:
        raise ValueError(f"unsupported lane width: {bits}")
    factor = 32 // bits
    n = int(codes.shape[0])
    words = -(-n // factor)
    lanes = np.zeros(words * factor, dtype=np.uint32)
    lanes[:n] = codes.astype(np.uint32, copy=False)
    lanes = lanes.reshape(words, factor)
    shifts = (np.arange(factor, dtype=np.uint32) * np.uint32(bits))[None, :]
    return np.bitwise_or.reduce(lanes << shifts, axis=1).astype(np.uint32)


def unpack_codes(words: np.ndarray, bits: int, n: int, dtype=np.uint32) -> np.ndarray:
    """Numpy inverse of pack_codes: first n lanes as an unpacked array."""
    if bits not in LANE_WIDTHS:
        raise ValueError(f"unsupported lane width: {bits}")
    factor = 32 // bits
    shifts = (np.arange(factor, dtype=np.uint32) * np.uint32(bits))[None, :]
    mask = np.uint32((1 << bits) - 1)
    lanes = (words.astype(np.uint32, copy=False)[:, None] >> shifts) & mask
    return lanes.reshape(-1)[:n].astype(dtype, copy=False)


def unpack_codes_jnp(words, bits: int, n: int, dtype=None):
    """Trace-time unpack with vectorized shifts (CPU/XLA fallback path).

    Unpacks along the LAST axis (1-D segment codes or [shards, words]
    stacked layouts alike).  `bits` and `n` (lanes kept per row of the
    last axis) must be static; `words` may be a traced uint32 array.
    Returns int32 by default — the width device readers expect from
    `.astype(jnp.int32)` anyway.
    """
    import jax.numpy as jnp
    from jax import lax

    if dtype is None:
        dtype = jnp.int32
    factor = 32 // bits
    w = words.astype(jnp.uint32)
    shifts = lax.broadcasted_iota(jnp.uint32, w.shape + (factor,), w.ndim) * jnp.uint32(bits)
    lanes = (w[..., None] >> shifts) & jnp.uint32((1 << bits) - 1)
    return lanes.reshape(w.shape[:-1] + (w.shape[-1] * factor,))[..., :n].astype(dtype)
