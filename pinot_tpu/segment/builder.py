"""Segment builder: rows -> immutable columnar segment.

Reference parity: pinot-segment-local SegmentIndexCreationDriverImpl.build
(SegmentIndexCreationDriverImpl.java:248) — stats pass, dictionary build,
per-column index creation, single-file packing — and SegmentColumnarIndexCreator.

Re-design: Pinot streams rows twice through per-row creators; here every phase
is a vectorized numpy pass over whole columns (np.unique fuses the stats pass
with dictionary build), and the output is written once via store.write_segment.

Encoding policy (delta from the reference, TPU-motivated):
  * STRING/BYTES/JSON: always dictionary-encoded — device sees int codes only.
  * Numeric DIMENSION / DATE_TIME: dictionary-encoded (sorted dict makes range
    predicates closed-form code compares) unless listed in
    no_dictionary_columns.
  * METRIC: raw storage by default (aggregation reads values directly; a
    dictionary gather would waste an HBM round-trip).  Pinot dict-encodes
    metrics by default; raw is the TPU-right default and Pinot supports the
    same via noDictionaryColumns.
"""
from __future__ import annotations

import time
from typing import Any, Dict, List, Optional, Sequence, Union

import numpy as np

from pinot_tpu.indexes.bloom import BloomFilter
from pinot_tpu.indexes.inverted import InvertedIndex, RangeEncodedIndex
from pinot_tpu.segment import packing
from pinot_tpu.segment.dictionary import Dictionary, min_code_dtype
from pinot_tpu.segment.segment import ColumnData, ImmutableSegment
from pinot_tpu.segment.stats import ColumnStats, collect_stats
from pinot_tpu.spi.config import IndexingConfig, TableConfig
from pinot_tpu.spi.schema import DataType, FieldRole, Schema
from pinot_tpu.utils.hashing import partition_of

# Above this cardinality, bitmap indexes stop paying for themselves vs a
# vectorized code scan (see indexes/inverted.py docstring).
MAX_BITMAP_INDEX_CARDINALITY = 1 << 16

ColumnInput = Union[np.ndarray, Sequence[Any]]


def _extract_nulls(field, raw: ColumnInput) -> (np.ndarray, Optional[np.ndarray]):
    """Split out a null mask and substitute typed placeholders."""
    dt = field.data_type
    arr = np.asarray(raw, dtype=object) if not isinstance(raw, np.ndarray) or raw.dtype == object else raw
    null_mask = None
    if arr.dtype == object:
        null_mask = np.array([v is None or (isinstance(v, float) and np.isnan(v)) for v in arr], dtype=bool)
        if null_mask.any():
            arr = arr.copy()
            arr[null_mask] = dt.null_placeholder
        else:
            null_mask = None
        if not dt.is_string_like:
            arr = arr.astype(dt.np_dtype)
    else:
        if np.issubdtype(arr.dtype, np.floating):
            nan = np.isnan(arr)
            if nan.any():
                null_mask = nan
                arr = np.where(nan, dt.np_dtype.type(dt.null_placeholder), arr)
        if not dt.is_string_like:
            arr = arr.astype(dt.np_dtype, copy=False)
    if dt.is_string_like and arr.dtype != object:
        arr = arr.astype(object)
    if null_mask is not None and not field.nullable:
        raise ValueError(f"nulls in non-nullable column {field.name}")
    return arr, null_mask


def narrow_ints(arr: np.ndarray, nmask: Optional[np.ndarray]) -> np.ndarray:
    """Store 64-bit integer columns as int32 when the value range fits.

    TPUs have no 64-bit ALU (emulated, ~50x slower) — narrowing at build time
    makes scans/compares native-speed and halves HBM traffic.  The logical
    type stays LONG; only storage narrows.  Columns with nulls keep their
    dtype (the null placeholder is int64-min)."""
    if (
        nmask is None
        and np.issubdtype(arr.dtype, np.integer)
        and arr.dtype.itemsize > 4
        and len(arr)
        and np.iinfo(np.int32).min <= arr.min()
        and arr.max() <= np.iinfo(np.int32).max
    ):
        return arr.astype(np.int32)
    return arr


def build_segment(
    schema: Schema,
    data: Dict[str, ColumnInput],
    segment_name: str,
    table_config: Optional[TableConfig] = None,
    output_dir: Optional[str] = None,
) -> ImmutableSegment:
    """Build an immutable segment from column-major data.

    If output_dir is given, also persists it (driver's handlePostCreation)."""
    cfg = table_config or TableConfig(name=schema.name)
    idx_cfg: IndexingConfig = cfg.indexing
    names = schema.column_names
    missing = [n for n in names if n not in data]
    if missing:
        raise ValueError(f"missing columns in input data: {missing}")
    lengths = {n: len(data[n]) for n in names}
    if len(set(lengths.values())) > 1:
        raise ValueError(f"ragged column lengths: {lengths}")
    num_docs = lengths[names[0]] if names else 0

    # Extract nulls + typed arrays first (record-transformer analog).
    # Multi-value fields keep their raw list-of-lists shape here; they build
    # through the dedicated MV path below (null -> empty array, like the
    # reference's default MV null handling).
    arrays: Dict[str, np.ndarray] = {}
    nulls: Dict[str, Optional[np.ndarray]] = {}
    for f in schema.fields:
        if not f.single_value:
            arrays[f.name] = np.asarray(
                [tuple(v) if v is not None else () for v in data[f.name]], dtype=object
            )
            nulls[f.name] = None
            continue
        arrays[f.name], nulls[f.name] = _extract_nulls(f, data[f.name])

    # Sort by the configured sorted column (Pinot keeps segments sorted when
    # declared; gives contiguous docId ranges for predicates on that column).
    sort_order = None  # new position -> input row (upsert validDocIds remap)
    if idx_cfg.sorted_column and idx_cfg.sorted_column in arrays and num_docs > 1:
        order = np.argsort(arrays[idx_cfg.sorted_column], kind="stable")
        if not np.array_equal(order, np.arange(num_docs)):
            sort_order = order
            for n in names:
                arrays[n] = np.asarray(arrays[n])[order]
                if nulls[n] is not None:
                    nulls[n] = nulls[n][order]

    columns: Dict[str, ColumnData] = {}
    indexes: Dict[str, Dict[str, Any]] = {}
    for f in schema.fields:
        arr, nmask = arrays[f.name], nulls[f.name]
        if not f.single_value:
            if f.name in idx_cfg.vector_index_columns:
                col, vidx = _build_vector_column(f, arr, num_docs)
                columns[f.name] = col
                indexes.setdefault("vector", {})[f.name] = vidx
                continue
            columns[f.name] = _build_mv_column(f, arr, num_docs)
            continue
        use_dict = _wants_dictionary(f, idx_cfg)
        if use_dict:
            dictionary, codes32 = Dictionary.build(f.data_type, arr)
            codes = codes32.astype(min_code_dtype(dictionary.cardinality))
            stats = collect_stats(f.name, f.data_type, arr, nmask, dictionary.cardinality, True)
            # bit-pack the forward index when the cardinality fits a 4/8/16
            # bit lane (segment/packing.py); codes stay materialized for
            # host-side consumers (index builds, sorted searchsorted, decode)
            bits = packing.lane_bits(dictionary.cardinality)
            columns[f.name] = ColumnData(
                f.name, f.data_type, dictionary, codes, None, nmask, stats,
                code_bits=bits if bits < 32 else None,
                packed=packing.pack_codes(codes, bits) if bits < 32 else None,
            )
            card = dictionary.cardinality
            if f.name in idx_cfg.inverted_index_columns:
                if card <= MAX_BITMAP_INDEX_CARDINALITY:
                    indexes.setdefault("inverted", {})[f.name] = InvertedIndex.build(codes32, card, num_docs)
                else:
                    # high cardinality: sparse compressed postings, O(docs)
                    # total storage (indexes/inverted.py CompressedInvertedIndex)
                    from pinot_tpu.indexes.inverted import CompressedInvertedIndex

                    indexes.setdefault("inverted", {})[f.name] = CompressedInvertedIndex.build(
                        codes32, card, num_docs
                    )
            if f.name in idx_cfg.range_index_columns and card <= MAX_BITMAP_INDEX_CARDINALITY:
                indexes.setdefault("range", {})[f.name] = RangeEncodedIndex.build(codes32, card, num_docs)
            if f.name in idx_cfg.json_index_columns:
                from pinot_tpu.indexes.jsonidx import JsonIndex

                indexes.setdefault("json", {})[f.name] = JsonIndex.build(dictionary.values)
            if f.name in idx_cfg.text_index_columns:
                from pinot_tpu.indexes.text import TextIndex

                indexes.setdefault("text", {})[f.name] = TextIndex.build(dictionary.values)
        else:
            if f.data_type.is_string_like:
                raise ValueError(f"string column {f.name} requires a dictionary")
            card = int(len(np.unique(arr)))
            stats = collect_stats(f.name, f.data_type, arr, nmask, card, False)
            columns[f.name] = ColumnData(f.name, f.data_type, None, None, narrow_ints(arr, nmask), nmask, stats)
        if f.name in idx_cfg.bloom_filter_columns:
            uniq = columns[f.name].dictionary.values if use_dict else np.unique(arr)
            indexes.setdefault("bloom", {})[f.name] = BloomFilter.build(list(uniq))

    # star-tree indexes: pre-aggregated prefix-level tensors (indexes/startree.py)
    for i, st_cfg in enumerate(idx_cfg.star_tree_index_configs):
        from pinot_tpu.indexes.startree import StarTreeIndex

        st = StarTreeIndex.build(
            columns,
            num_docs,
            st_cfg.get("dimensionsSplitOrder", []),
            st_cfg.get("functionColumnPairs", []),
            min_collapse=float(st_cfg.get("minCollapse", 1.1)),
        )
        if st is not None:
            indexes.setdefault("startree", {})[f"st{i}"] = st

    # partition metadata for partition-pinned routing
    if cfg.partition_column and cfg.partition_column in columns and cfg.num_partitions:
        col = columns[cfg.partition_column]
        vals = col.decoded()
        pids = np.unique([partition_of(v, cfg.num_partitions) for v in vals.tolist()])
        if len(pids) == 1:
            col.stats.partition_id = int(pids[0])
            col.stats.num_partitions = cfg.num_partitions

    time_range = None
    tc = cfg.segments.time_column
    if tc and tc in columns:
        s = columns[tc].stats
        time_range = (s.min_value, s.max_value)

    seg = ImmutableSegment(
        name=segment_name,
        table_name=cfg.name,
        schema=schema,
        columns=columns,
        num_docs=num_docs,
        indexes=indexes,
        creation_time_ms=int(time.time() * 1000),
        time_range=time_range,
    )
    seg.sort_order = sort_order
    if output_dir is not None:
        seg.save(output_dir)
    return seg


def _build_mv_column(f, lists: np.ndarray, num_docs: int) -> ColumnData:
    """Multi-value column: dictionary over the FLATTENED values + a padded
    [num_docs, max_len] code matrix with per-row lengths.

    Reference parity: FixedBitMVForwardIndexReader (pinot-segment-local/...
    readers/forward/FixedBitMVForwardIndexReader.java) stores var-length
    code runs; the TPU layout is fixed-width padded — a dense matrix the
    kernels scan with a length mask (static shapes, no row offsets).
    Padding cells hold code == cardinality (one past the dictionary), which
    every predicate table/range treats as no-match."""
    flat: list = []
    lengths = np.empty(num_docs, dtype=np.int32)
    for i, row in enumerate(lists):
        lengths[i] = len(row)
        flat.extend(row)
    flat_arr = np.asarray(flat, dtype=object if f.data_type.is_string_like else f.data_type.np_dtype)
    if flat_arr.dtype == object and not f.data_type.is_string_like:
        flat_arr = flat_arr.astype(f.data_type.np_dtype)
    dictionary, flat_codes = Dictionary.build(f.data_type, flat_arr)
    card = dictionary.cardinality
    max_len = max(1, int(lengths.max()) if num_docs else 1)
    code_dt = min_code_dtype(card + 1)  # +1: the padding code
    codes2d = np.full((num_docs, max_len), card, dtype=code_dt)
    pos = 0
    for i in range(num_docs):
        ln = lengths[i]
        codes2d[i, :ln] = flat_codes[pos : pos + ln]
        pos += ln
    stats = collect_stats(f.name, f.data_type, flat_arr, None, card, True)
    stats.num_docs = num_docs  # rows, not elements
    return ColumnData(f.name, f.data_type, dictionary, codes2d, None, None, stats, mv_lengths=lengths)


def _build_vector_column(f, lists: np.ndarray, num_docs: int):
    """Embedding column: raw padded [n, dim] float32 matrix (no dictionary)
    + a VectorIndex of the row-normalized matrix (indexes/vector.py)."""
    from pinot_tpu.indexes.vector import VectorIndex

    lengths = np.array([len(r) for r in lists], dtype=np.int32)
    max_len = max(1, int(lengths.max()) if num_docs else 1)
    mat = np.zeros((num_docs, max_len), dtype=np.float32)
    for i, row in enumerate(lists):
        mat[i, : len(row)] = np.asarray(row, dtype=np.float32)
    flat = mat[np.arange(max_len)[None, :] < lengths[:, None]]
    stats = collect_stats(f.name, f.data_type, flat.astype(np.float64), None, 0, False)
    stats.num_docs = num_docs
    col = ColumnData(f.name, f.data_type, None, None, mat, None, stats, mv_lengths=lengths)
    return col, VectorIndex.build(mat, lengths)


def _wants_dictionary(f, idx_cfg: IndexingConfig) -> bool:
    if f.data_type.is_string_like:
        return True
    if f.name in idx_cfg.no_dictionary_columns:
        return False
    return f.role in (FieldRole.DIMENSION, FieldRole.DATE_TIME)
