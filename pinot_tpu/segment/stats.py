"""Per-column statistics collected at segment build time.

Reference parity: pinot-segment-local stats collectors feeding ColumnMetadata
(SegmentColumnarIndexCreator writes min/max/cardinality/sorted into segment
metadata).  Used host-side for segment pruning before any kernel launch
(SegmentPrunerService analog, query/pruner.py).
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, Optional

import numpy as np

from pinot_tpu.spi.schema import DataType


@dataclass
class ColumnStats:
    name: str
    data_type: DataType
    num_docs: int
    cardinality: int
    min_value: Any = None
    max_value: Any = None
    is_sorted: bool = False
    has_nulls: bool = False
    has_dictionary: bool = True
    # partition info for partition-pinned routing (SURVEY.md 2.5)
    partition_id: Optional[int] = None
    num_partitions: Optional[int] = None

    def to_dict(self) -> Dict[str, Any]:
        def _py(v):
            if isinstance(v, np.generic):
                return v.item()
            if isinstance(v, bytes):
                return v.decode("latin-1")
            return v

        return {
            "name": self.name,
            "dataType": self.data_type.value,
            "numDocs": self.num_docs,
            "cardinality": self.cardinality,
            "min": _py(self.min_value),
            "max": _py(self.max_value),
            "sorted": self.is_sorted,
            "hasNulls": self.has_nulls,
            "hasDictionary": self.has_dictionary,
            "partitionId": self.partition_id,
            "numPartitions": self.num_partitions,
        }

    @staticmethod
    def from_dict(d: Dict[str, Any]) -> "ColumnStats":
        dt = DataType(d["dataType"])
        mn, mx = d.get("min"), d.get("max")
        if dt is DataType.BYTES:
            mn = mn.encode("latin-1") if isinstance(mn, str) else mn
            mx = mx.encode("latin-1") if isinstance(mx, str) else mx
        return ColumnStats(
            name=d["name"],
            data_type=dt,
            num_docs=d["numDocs"],
            cardinality=d["cardinality"],
            min_value=mn,
            max_value=mx,
            is_sorted=d.get("sorted", False),
            has_nulls=d.get("hasNulls", False),
            has_dictionary=d.get("hasDictionary", True),
            partition_id=d.get("partitionId"),
            num_partitions=d.get("numPartitions"),
        )


def collect_stats(
    name: str,
    data_type: DataType,
    values: np.ndarray,
    null_mask: Optional[np.ndarray],
    cardinality: int,
    has_dictionary: bool,
) -> ColumnStats:
    """Single-pass stats over the (null-substituted) column values."""
    n = len(values)
    if n == 0:
        return ColumnStats(name, data_type, 0, 0, has_dictionary=has_dictionary)
    if data_type.is_string_like:
        mn, mx = min(values), max(values)
        arr = np.asarray(values, dtype=object)
        is_sorted = bool(np.all(arr[:-1] <= arr[1:])) if n > 1 else True
    else:
        arr = np.asarray(values, dtype=data_type.np_dtype)
        mn, mx = arr.min(), arr.max()
        is_sorted = bool(np.all(arr[:-1] <= arr[1:]))
    return ColumnStats(
        name=name,
        data_type=data_type,
        num_docs=n,
        cardinality=cardinality,
        min_value=mn,
        max_value=mx,
        is_sorted=is_sorted,
        has_nulls=bool(null_mask is not None and null_mask.any()),
        has_dictionary=has_dictionary,
    )
