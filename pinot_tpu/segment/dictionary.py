"""Sorted per-column value dictionaries.

Reference parity: pinot-segment-local SegmentDictionaryCreator + the typed
readers (pinot-segment-spi Dictionary.java:38 — indexOf/insertionIndexOf/get*).
The dictionary is SORTED, which is the load-bearing trick the TPU build keeps:
range predicates on a dict-encoded column become closed-form dictId-range
compares on the code array (no value gather needed on device).

Design deltas vs the reference:
  * One implementation for all types over numpy (object array for strings).
  * encode() is vectorized (np.searchsorted) — the whole column at once.
  * Numeric dictionaries can be shipped to HBM (values array) so projection of
    a dict-encoded numeric column is a device-side gather; string dictionaries
    stay host-side and the device only ever sees int32 codes.
"""
from __future__ import annotations

from dataclasses import dataclass, field as dataclass_field
from typing import Optional, Sequence, Tuple

import numpy as np

from pinot_tpu.spi.schema import DataType

# Sentinel dictId for "value not in dictionary" (Dictionary.NULL_VALUE_INDEX).
NULL_DICT_ID = -1


def min_code_dtype(cardinality: int) -> np.dtype:
    """Smallest unsigned dtype that holds [0, cardinality) codes.

    This is the TPU answer to Pinot's fixed-bit packing
    (FixedBitSVForwardIndexReaderV2): byte-aligned widths mmap and DMA with
    zero unpack cost; sub-byte packing is a later Pallas optimization."""
    if cardinality <= 1 << 8:
        return np.dtype(np.uint8)
    if cardinality <= 1 << 16:
        return np.dtype(np.uint16)
    return np.dtype(np.uint32)


@dataclass
class Dictionary:
    """Immutable sorted dictionary for one column."""

    data_type: DataType
    values: np.ndarray  # sorted ascending; dtype = data_type.np_dtype (object for strings)

    @property
    def cardinality(self) -> int:
        return len(self.values)

    # cache slot, not data: excluded from __init__/__eq__/__repr__ so a
    # poisoned fingerprint cannot be injected via the constructor
    _fp_cache: Optional[str] = dataclass_field(default=None, init=False, compare=False, repr=False)

    def fingerprint(self) -> str:
        """Content hash of the value set — used to detect segments that share
        a key space (aligned dense group-by merges, reduce.py)."""
        if self._fp_cache is None:
            import hashlib

            h = hashlib.blake2b(digest_size=12)
            if self.data_type.is_string_like:
                for v in self.values:
                    b = v if isinstance(v, bytes) else str(v).encode("utf-8")
                    h.update(len(b).to_bytes(4, "little"))  # length-prefix: no delimiter collisions
                    h.update(b)
            else:
                h.update(np.ascontiguousarray(self.values).tobytes())
            object.__setattr__(self, "_fp_cache", h.hexdigest())
        return self._fp_cache

    @property
    def code_dtype(self) -> np.dtype:
        return min_code_dtype(self.cardinality)

    # -- build -----------------------------------------------------------
    @staticmethod
    def build(data_type: DataType, raw_values: np.ndarray) -> Tuple["Dictionary", np.ndarray]:
        """One pass: sorted unique values + codes for every row.

        Collapses Pinot's two-phase flow (stats collector -> dictionary
        creator -> per-row indexOf) into np.unique(return_inverse), which is
        exactly 'sort unique + searchsorted' fused."""
        if data_type.is_string_like:
            # np.unique on object arrays works for str; for bytes too.
            values, inverse = np.unique(np.asarray(raw_values, dtype=object), return_inverse=True)
        else:
            arr = np.asarray(raw_values, dtype=data_type.np_dtype)
            values, inverse = np.unique(arr, return_inverse=True)
        d = Dictionary(data_type=data_type, values=values)
        return d, inverse.astype(np.int32)

    # -- lookups ---------------------------------------------------------
    def index_of(self, value) -> int:
        """Exact-match dictId or NULL_DICT_ID (Dictionary.indexOf)."""
        i = int(np.searchsorted(self.values, self._coerce(value)))
        if i < len(self.values) and self.values[i] == self._coerce(value):
            return i
        return NULL_DICT_ID

    def insertion_index_of(self, value) -> int:
        """Bisect-left index; callers use it to turn range predicates into
        dictId ranges (Dictionary.insertionIndexOf semantics: -(pos)-1 when
        absent).  We return the plain insertion point plus a found flag via
        index_of; range translation lives in query/predicates.py."""
        return int(np.searchsorted(self.values, self._coerce(value)))

    def encode(self, raw_values: np.ndarray) -> np.ndarray:
        """Vectorized value->code; raises on unknown values."""
        if self.data_type.is_string_like:
            arr = np.asarray(raw_values, dtype=object)
        else:
            arr = np.asarray(raw_values, dtype=self.data_type.np_dtype)
        codes = np.searchsorted(self.values, arr)
        codes = np.clip(codes, 0, len(self.values) - 1)
        if not (self.values[codes] == arr).all():
            bad = arr[self.values[codes] != arr]
            raise ValueError(f"values not in dictionary: {bad[:5]!r}")
        return codes.astype(np.int32)

    def get_values(self, dict_ids: np.ndarray) -> np.ndarray:
        return self.values[np.asarray(dict_ids)]

    def _coerce(self, value):
        """Keep literals semantically intact: numpy compares/searchsorts
        cross-dtype correctly (2.5 lands between 2 and 3 in an int dict and
        equals nothing), whereas casting to the column dtype would truncate
        and match the wrong rows."""
        if isinstance(value, np.generic):
            return value.item()
        return value

    # -- device ----------------------------------------------------------
    def device_values(self) -> Optional[np.ndarray]:
        """Numeric dictionary values for HBM residency (None for strings).

        64-bit integer dictionaries narrow to int32 when the range fits —
        TPUs emulate 64-bit ALU ops (see segment/builder.py narrow_ints)."""
        if self.data_type.is_string_like:
            return None
        vals = np.asarray(self.values, dtype=self.data_type.np_dtype)
        if (
            np.issubdtype(vals.dtype, np.integer)
            and vals.dtype.itemsize > 4
            and len(vals)
            and np.iinfo(np.int32).min <= vals[0]
            and vals[-1] <= np.iinfo(np.int32).max
        ):
            return vals.astype(np.int32)
        return vals

    # -- serde (store.py writes these regions) ---------------------------
    def to_regions(self, prefix: str):
        """Yield (name, ndarray) regions. Strings become a utf-8 blob +
        int64 offsets — the V3-single-file analog of Pinot's var-length
        dictionary layout."""
        if self.data_type.is_string_like:
            if self.data_type is DataType.BYTES:
                encoded = [bytes(v) for v in self.values]
            else:
                encoded = [str(v).encode("utf-8") for v in self.values]
            blob = b"".join(encoded)
            offsets = np.zeros(len(encoded) + 1, dtype=np.int64)
            np.cumsum([len(e) for e in encoded], out=offsets[1:])
            yield f"{prefix}.dict.blob", np.frombuffer(blob, dtype=np.uint8)
            yield f"{prefix}.dict.offsets", offsets
        else:
            yield f"{prefix}.dict.values", np.asarray(self.values)

    @staticmethod
    def from_regions(data_type: DataType, regions, prefix: str) -> "Dictionary":
        if data_type.is_string_like:
            blob = regions[f"{prefix}.dict.blob"].tobytes()
            offsets = regions[f"{prefix}.dict.offsets"]
            if data_type is DataType.BYTES:
                vals = [blob[offsets[i]: offsets[i + 1]] for i in range(len(offsets) - 1)]
            else:
                vals = [blob[offsets[i]: offsets[i + 1]].decode("utf-8") for i in range(len(offsets) - 1)]
            values = np.asarray(vals, dtype=object)
        else:
            values = np.asarray(regions[f"{prefix}.dict.values"], dtype=data_type.np_dtype)
        return Dictionary(data_type=data_type, values=values)
