"""Tiered segment storage: HBM as a cost-aware cache over host RAM.

Reference parity: Pinot's tiered storage / off-heap memory manager — local
disk is a cache over the deep store and segments are mmap-loaded on demand
— composed with "Near Data Processing in Taurus Database" (PAPERS.md): only
bytes that survive host-side pruning ride the slow link.  The TPU mapping:

  deep store (r12)  ->  host RAM (mmap'd segments / stacked arrays)
                    ->  HBM, managed HERE as a byte-budgeted cache.

`ResidencyManager` owns the device-cache byte budget (an r11
`ResourceBudget` ledger, shared with query working-set reservations so
cache bytes and in-flight reservations can never jointly overcommit), a
cost-aware eviction policy fed by the r13 `PERF_LEDGER` (hot tables — high
bytes/s — survive; within a table, least-recently-used first), and the
single-worker *staging stream*: the one thread allowed to issue
segment-sized host->device copies (repo_lint W021 flags segment-shaped
`jax.device_put` anywhere else on the serving path).

Residency state machine, per cache GROUP (a whole `ImmutableSegment` per
device, or one doc-slice of a `StackedTable` per mesh):

    HOST_ONLY ──begin_stage──> STAGING ──finish_stage──> RESIDENT
        ^                         │abort_stage              │
        └──────(event set)────────┘          begin_grow────>│ (back to
        ^                                                   │  STAGING)
        └───────────── EVICTING <──────evict────────────────┘

HOST_ONLY is represented by absence.  Every transition out of STAGING /
EVICTING sets the entry's event, so concurrent queries park on the event
instead of double-copying, and a query racing an eviction re-stages the
whole group — it can never observe half of a group's flavors (the raw and
`#packed` entries of one segment always live and die together, satellite
fix r17).  A mid-stage crash unwinds through `abort_stage`, which uncharges
the pending bytes — the crash-harness tests assert no ledger leak.
"""
from __future__ import annotations

import time
from concurrent.futures import Future, ThreadPoolExecutor
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, Iterable, Optional, Tuple

from pinot_tpu.utils import threads
from pinot_tpu.utils.metrics import METRICS

HOST_ONLY = "host_only"
STAGING = "staging"
RESIDENT = "resident"
EVICTING = "evicting"

# Outcomes of begin_stage / begin_grow
OWN = "own"  # caller is the staging owner: charge, copy, publish
WAIT = "wait"  # another thread is staging/evicting: park on entry.event
HIT = "hit"  # group already resident
RETRY = "retry"  # state moved underneath the caller: re-plan from scratch


@dataclass
class _Entry:
    group: Tuple
    table: str
    evict_cb: Callable[[], None]
    state: str = STAGING
    nbytes: int = 0  # committed (RESIDENT) bytes
    pending: int = 0  # charged but not yet finish_stage'd bytes
    last_access: int = 0
    prefetched: bool = False
    event: Any = field(default_factory=threads.Event)


class ResidencyManager:
    """Byte-budgeted device cache of segment groups with cost-aware eviction
    and a single-worker async staging stream (the host->device copy engine
    that double-buffers the *next* macro-batch while the current one scans).

    Thread-safety: `_lock` guards the entry table and accounting; it is
    never held across a device copy (the owner stages with NO lock held —
    waiters park on per-entry events), and eviction callbacks run outside
    it too, so the manager lock never orders against a cache's own lock."""

    def __init__(
        self,
        budget,
        name: str = "residency",
        ledger=None,
        stall_timeout_s: float = 30.0,
    ):
        self.budget = budget  # cluster.admission.ResourceBudget
        self.name = name
        # r13 perf ledger supplying the eviction cost signal (bytes/s per
        # table); None falls back to pure LRU
        self._ledger = ledger
        self.stall_timeout_s = float(stall_timeout_s)
        self._lock = threads.Lock()
        self._entries: Dict[Tuple, _Entry] = {}
        self._clock = 0  # logical access clock (recency, not wall time)
        self._resident_bytes = 0
        self._stream: Optional[ThreadPoolExecutor] = None

    # -- staging stream -------------------------------------------------
    def submit(self, fn: Callable, *args, **kwargs) -> Future:
        """Enqueue work on the staging stream (ONE worker: copies are
        serialized against each other, overlapped with device compute)."""
        with self._lock:
            if self._stream is None:
                self._stream = ThreadPoolExecutor(
                    max_workers=1, thread_name_prefix=f"{self.name}-stage"
                )
            stream = self._stream
        return stream.submit(fn, *args, **kwargs)

    def shutdown(self) -> None:
        with self._lock:
            stream, self._stream = self._stream, None
        if stream is not None:
            stream.shutdown(wait=True)

    # -- state machine --------------------------------------------------
    def begin_stage(
        self,
        group: Tuple,
        table: str,
        evict_cb: Callable[[], None],
        prefetch: bool = False,
    ) -> Tuple[str, Optional[_Entry]]:
        """Enter the state machine for one cache group.  Returns (status,
        entry): OWN means the caller must charge/copy/publish then
        finish_stage (or abort_stage on failure); WAIT means park on
        entry.event and retry; HIT means the group is resident."""
        with self._lock:
            e = self._entries.get(group)
            if e is None:
                e = _Entry(group=group, table=table, evict_cb=evict_cb)
                e.prefetched = prefetch
                self._clock += 1
                e.last_access = self._clock
                self._entries[group] = e
                if prefetch:
                    METRICS.counter(f"{self.name}.prefetchIssued").inc()
                else:
                    METRICS.counter(f"{self.name}.misses").inc()
                return OWN, e
            if e.state == RESIDENT:
                self._touch_locked(e, prefetch)
                return HIT, e
            # STAGING or EVICTING: a demand arrival overlapping an in-flight
            # prefetch still counts as a prefetch hit (the copy was issued
            # ahead of need); the residual wait is the staging stall.
            if not prefetch and e.state == STAGING and e.prefetched:
                e.prefetched = False
                METRICS.counter(f"{self.name}.prefetchHits").inc()
            return WAIT, e

    def begin_grow(self, group: Tuple) -> Tuple[str, Optional[_Entry]]:
        """Claim a RESIDENT group for incremental staging (a query needing
        columns/flavors the resident group does not hold yet)."""
        with self._lock:
            e = self._entries.get(group)
            if e is None:
                return RETRY, None  # evicted underneath us: re-plan
            if e.state == RESIDENT:
                e.state = STAGING
                e.event.clear()
                return OWN, e
            return WAIT, e

    def charge(self, group: Tuple, nbytes: int, query_id: Optional[str] = None) -> None:
        """Owner-side budget charge for the bytes about to be copied.  Evicts
        cost-ranked victims (never the group being staged) until the charge
        fits; raises ReservationError when even a fully-drained cache could
        not hold it — the caller unwinds via abort_stage."""
        n = max(0, int(nbytes))
        if n == 0:
            return
        with self._lock:
            e = self._entries[group]
            e.pending += n
        while not self.budget.try_charge(n):
            victim = None
            with self._lock:
                victim = self._select_victim_locked(exclude=group)
                if victim is not None:
                    victim.state = EVICTING
                    victim.event.clear()
            if victim is None:
                with self._lock:
                    e.pending -= n
                from pinot_tpu.cluster.admission import ReservationError  # local import; avoids cycle

                METRICS.counter(f"{self.name}.stageRejected").inc()
                raise ReservationError(
                    f"staging {n / 1e6:.1f} MB into the {self.name} cache "
                    f"exceeds its {self.budget.budget_bytes / 1e6:.1f} MB budget "
                    "even after draining every evictable group",
                    query_id=query_id,
                )
            self._complete_eviction(victim)

    def finish_stage(self, group: Tuple) -> None:
        """Owner-side publish: pending bytes commit, waiters wake."""
        with self._lock:
            e = self._entries[group]
            e.nbytes += e.pending
            self._resident_bytes += e.pending
            e.pending = 0
            e.state = RESIDENT
            self._clock += 1
            e.last_access = self._clock
            self._publish_locked()
            e.event.set()

    def abort_stage(self, group: Tuple) -> None:
        """Owner-side unwind (copy failed, injected crash, ...): uncharge
        the pending bytes so a mid-stage kill leaves no ledger leak.  A
        failed GROW reverts to RESIDENT (the committed part is intact); a
        failed fresh stage removes the entry entirely."""
        pend = 0
        with self._lock:
            e = self._entries.get(group)
            if e is None:
                return
            pend, e.pending = e.pending, 0
            if e.nbytes > 0:
                e.state = RESIDENT
            else:
                del self._entries[group]
            e.event.set()
        if pend:
            self.budget.uncharge(pend)

    def wait(self, entry: _Entry, timeout_s: Optional[float] = None) -> bool:
        """Park until the entry's in-flight transition completes; the wall
        time spent here is the staging stall the bench sweep reports."""
        t0 = time.perf_counter()
        ok = entry.event.wait(timeout_s if timeout_s is not None else self.stall_timeout_s)
        METRICS.histogram(f"{self.name}.stagingStallMs").update(
            (time.perf_counter() - t0) * 1000.0
        )
        return ok

    def touch(self, group: Tuple) -> None:
        with self._lock:
            e = self._entries.get(group)
            if e is not None:
                self._touch_locked(e, prefetch=False)

    # -- eviction --------------------------------------------------------
    def evict(self, group: Tuple) -> bool:
        """Explicit eviction (segment drop, server crash, release_device):
        drops ALL device flavors of the group atomically via its callback."""
        with self._lock:
            e = self._entries.get(group)
            if e is None or e.state != RESIDENT:
                return False
            e.state = EVICTING
            e.event.clear()
        self._complete_eviction(e)
        return True

    def evict_matching(self, pred: Callable[[Tuple], bool]) -> int:
        """Evict every RESIDENT group whose key satisfies `pred` (all groups
        of one segment/table when it is dropped)."""
        n = 0
        while True:
            victim = None
            with self._lock:
                for e in self._entries.values():
                    if e.state == RESIDENT and pred(e.group):
                        e.state = EVICTING
                        e.event.clear()
                        victim = e
                        break
            if victim is None:
                return n
            self._complete_eviction(victim)
            n += 1

    def _complete_eviction(self, e: _Entry) -> None:
        # callback OUTSIDE the manager lock: it takes the owning cache's
        # _device_lock and clears every flavor of the group in one critical
        # section — a racing reader re-checks and re-stages, never mixing
        try:
            e.evict_cb()
        finally:
            self.budget.uncharge(e.nbytes)
            with self._lock:
                self._resident_bytes -= e.nbytes
                e.nbytes = 0
                self._entries.pop(e.group, None)
                METRICS.counter(f"{self.name}.evictions").inc()
                self._publish_locked()
                e.event.set()

    def _select_victim_locked(self, exclude: Tuple) -> Optional[_Entry]:
        """Cost-ranked victim: most over-share table first when the
        autopilot has published per-table residency splits (a table resident
        beyond its traffic-weighted fraction of the budget donates first),
        then coldest table (r13 ledger bytes/s — a hot table's groups are
        the expensive ones to refetch), then least recently used within a
        heat class.  With no splits set (autopilot off) this is exactly the
        pre-autopilot heat/LRU policy."""
        candidates = [
            e
            for e in self._entries.values()
            if e.state == RESIDENT and e.group != exclude and e.nbytes > 0
        ]
        if not candidates:
            return None
        heat = self._table_heat({e.table for e in candidates})
        over = self._table_overshare_locked({e.table for e in candidates})
        return min(
            candidates,
            key=lambda e: (-over.get(e.table, 0.0), heat.get(e.table, 0.0), e.last_access),
        )

    def _table_overshare_locked(self, tables: Iterable[str]) -> Dict[str, float]:
        """Bytes each table is resident BEYOND its autopilot split share of
        the budget (0 when under share or when no splits are published)."""
        from pinot_tpu.cluster import autopilot

        splits = autopilot.knobs().splits()
        if not splits:
            return {}
        resident: Dict[str, int] = {}
        for e in self._entries.values():
            if e.state == RESIDENT and e.nbytes > 0:
                resident[e.table] = resident.get(e.table, 0) + e.nbytes
        total_budget = float(self.budget.budget_bytes)
        out: Dict[str, float] = {}
        for t in tables:
            share = splits.get(t)
            if share is None:
                continue
            out[t] = max(0.0, resident.get(t, 0) - share * total_budget)
        return out

    def _table_heat(self, tables: Iterable[str]) -> Dict[str, float]:
        if self._ledger is None:
            return {}
        try:
            snap = self._ledger.snapshot()
        except Exception:  # noqa: BLE001 — eviction must not die on telemetry
            return {}
        out: Dict[str, float] = {}
        for t in tables:
            rec = snap.get("tables", {}).get(t)
            if not rec:
                continue
            bps = 0.0
            for shape in rec.get("shapes", {}).values():
                v = shape.get("bytesPerSec", {}).get("mean")
                if v:
                    bps = max(bps, float(v))
            out[t] = bps
        return out

    # -- internals -------------------------------------------------------
    def _touch_locked(self, e: _Entry, prefetch: bool) -> None:
        self._clock += 1
        e.last_access = self._clock
        if not prefetch:
            METRICS.counter(f"{self.name}.hits").inc()
            if e.prefetched:
                e.prefetched = False
                METRICS.counter(f"{self.name}.prefetchHits").inc()

    def _publish_locked(self) -> None:
        METRICS.gauge(f"{self.name}.residentBytes").set(float(self._resident_bytes))

    # -- observability ---------------------------------------------------
    @property
    def resident_bytes(self) -> int:
        with self._lock:
            return self._resident_bytes

    def state_of(self, group: Tuple) -> str:
        with self._lock:
            e = self._entries.get(group)
            return e.state if e is not None else HOST_ONLY

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            by_state: Dict[str, int] = {}
            for e in self._entries.values():
                by_state[e.state] = by_state.get(e.state, 0) + 1
            return {
                "groups": len(self._entries),
                "byState": by_state,
                "residentBytes": self._resident_bytes,
                "budgetBytes": self.budget.budget_bytes,
                "hits": METRICS.counter(f"{self.name}.hits").value,
                "misses": METRICS.counter(f"{self.name}.misses").value,
                "evictions": METRICS.counter(f"{self.name}.evictions").value,
                "prefetchIssued": METRICS.counter(f"{self.name}.prefetchIssued").value,
                "prefetchHits": METRICS.counter(f"{self.name}.prefetchHits").value,
            }


def default_residency(budget=None, name: str = "residency"):
    """Process-default residency manager factory: budget from
    PINOT_TPU_HBM_CACHE_BYTES (0 disables tiering — every to_device call
    behaves as the legacy pin-everything path), else the server HBM default;
    eviction heat from the process PERF_LEDGER."""
    import os

    from pinot_tpu.utils import perf

    if budget is None:
        from pinot_tpu.cluster.admission import ResourceBudget, default_server_hbm_budget

        nbytes = int(
            os.environ.get("PINOT_TPU_HBM_CACHE_BYTES", str(default_server_hbm_budget()))
        )
        if nbytes <= 0:
            return None
        budget = ResourceBudget(nbytes, gauge=f"{name}.reservedBytes")
    return ResidencyManager(budget, name=name, ledger=perf.PERF_LEDGER)


def row_residency(num_rows: int, row: int, total_bytes=None, name: str = "residency"):
    """Per-mesh-row residency manager: one replica row's even share of the
    HBM cache budget (parallel/engine.ReplicatedEngine).

    A replica axis multiplies QPS only if staging and eviction stay
    row-local: each row holds its own full data copy on its own device set,
    charged against its OWN budget/ledger, so one hot row's working set can
    never evict another row's resident slices.  total_bytes defaults to
    PINOT_TPU_HBM_CACHE_BYTES (the whole-mesh cache size); 0 disables
    tiering for every row, like default_residency."""
    import os

    from pinot_tpu.utils import perf

    if total_bytes is None:
        from pinot_tpu.cluster.admission import default_server_hbm_budget

        total_bytes = int(
            os.environ.get("PINOT_TPU_HBM_CACHE_BYTES", str(default_server_hbm_budget()))
        )
    share = int(total_bytes) // max(1, int(num_rows))
    if share <= 0:
        return None
    from pinot_tpu.cluster.admission import ResourceBudget

    row_name = f"{name}.row{row}"
    budget = ResourceBudget(share, gauge=f"{row_name}.reservedBytes")
    return ResidencyManager(budget, name=row_name, ledger=perf.PERF_LEDGER)
