"""Segment persistence: one metadata.json + one aligned binary file.

Reference parity: Pinot V3 single-file layout — all column indexes packed into
`columns.psf` with an `index_map` of (offset, size) entries
(pinot-segment-local SingleFileIndexDirectory.java:235, names in
V1Constants.java:26-27).  Re-design: the region table lives in metadata.json
with dtype+shape so every region loads as a zero-copy np.memmap (Pinot's
ReadMode.mmap), ready for jax.device_put straight into HBM.

Layout of columns.bin: regions back-to-back, each aligned to 64 bytes.
"""
from __future__ import annotations

import json
import os
from typing import Any, Dict, Iterable, List, Mapping, Tuple

import numpy as np

ALIGN = 64
DATA_FILE = "columns.bin"
META_FILE = "metadata.json"
FORMAT_VERSION = 1


def write_segment(path: str, metadata: Dict[str, Any], regions: Iterable[Tuple[str, np.ndarray]]) -> None:
    """Write metadata + binary regions atomically-ish (tmp file + rename)."""
    os.makedirs(path, exist_ok=True)
    region_table: List[Dict[str, Any]] = []
    tmp_data = os.path.join(path, DATA_FILE + ".tmp")
    offset = 0
    with open(tmp_data, "wb") as f:
        for name, arr in regions:
            arr = np.ascontiguousarray(arr)
            pad = (-offset) % ALIGN
            if pad:
                f.write(b"\x00" * pad)
                offset += pad
            raw = arr.tobytes()
            f.write(raw)
            region_table.append(
                {
                    "name": name,
                    "offset": offset,
                    "nbytes": len(raw),
                    "dtype": arr.dtype.str,
                    "shape": list(arr.shape),
                }
            )
            offset += len(raw)
    os.replace(tmp_data, os.path.join(path, DATA_FILE))

    meta = dict(metadata)
    meta["formatVersion"] = FORMAT_VERSION
    meta["regions"] = region_table
    tmp_meta = os.path.join(path, META_FILE + ".tmp")
    with open(tmp_meta, "w") as f:
        json.dump(meta, f, indent=1)
    os.replace(tmp_meta, os.path.join(path, META_FILE))


class RegionMap(Mapping[str, np.ndarray]):
    """Lazy mmap view over columns.bin keyed by region name."""

    def __init__(self, path: str, meta: Dict[str, Any]):
        self._data_path = os.path.join(path, DATA_FILE)
        self._table = {r["name"]: r for r in meta["regions"]}
        self._cache: Dict[str, np.ndarray] = {}

    def __getitem__(self, name: str) -> np.ndarray:
        if name not in self._cache:
            r = self._table[name]
            if r["nbytes"] == 0:
                self._cache[name] = np.empty(tuple(r["shape"]), dtype=np.dtype(r["dtype"]))
            else:
                self._cache[name] = np.memmap(
                    self._data_path,
                    mode="r",
                    dtype=np.dtype(r["dtype"]),
                    offset=r["offset"],
                    shape=tuple(r["shape"]),
                )
        return self._cache[name]

    def __contains__(self, name: object) -> bool:
        return name in self._table

    def __iter__(self):
        return iter(self._table)

    def __len__(self) -> int:
        return len(self._table)


def read_segment(path: str) -> Tuple[Dict[str, Any], RegionMap]:
    with open(os.path.join(path, META_FILE)) as f:
        meta = json.load(f)
    if meta.get("formatVersion") != FORMAT_VERSION:
        raise ValueError(f"unsupported segment format version {meta.get('formatVersion')}")
    return meta, RegionMap(path, meta)
