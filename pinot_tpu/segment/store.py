"""Segment persistence: one metadata.json + one aligned binary file.

Reference parity: Pinot V3 single-file layout — all column indexes packed into
`columns.psf` with an `index_map` of (offset, size) entries
(pinot-segment-local SingleFileIndexDirectory.java:235, names in
V1Constants.java:26-27).  Re-design: the region table lives in metadata.json
with dtype+shape so every region loads as a zero-copy np.memmap (Pinot's
ReadMode.mmap), ready for jax.device_put straight into HBM.

Layout of columns.bin: regions back-to-back, each aligned to 64 bytes.

Durability: both files commit via tmp-fsync-replace (data first, then the
metadata that references it — a crash between the two leaves the OLD
committed metadata pointing at the OLD data, or no segment at all, never a
torn one).  metadata.json carries the CRC32 of columns.bin (the reference's
segment CRC in ZK metadata / creation.meta), verified on deep-store
download and on load(verify=True) so a corrupt local copy is detected and
re-fetched instead of silently serving garbage.
"""
from __future__ import annotations

import json
import os
import zlib
from typing import Any, Dict, Iterable, List, Mapping, Tuple

import numpy as np

from pinot_tpu.spi.filesystem import durable_write_bytes, fsync_dir
from pinot_tpu.utils.crashpoints import crash_point

ALIGN = 64
DATA_FILE = "columns.bin"
META_FILE = "metadata.json"
FORMAT_VERSION = 1


class SegmentCorruptError(RuntimeError):
    """Segment data does not match its committed metadata (bad CRC or a
    missing/short data file) — the local copy must be discarded and
    re-fetched from the deep store."""


def write_segment(path: str, metadata: Dict[str, Any], regions: Iterable[Tuple[str, np.ndarray]]) -> None:
    """Write metadata + binary regions atomically (tmp + fsync + rename)."""
    os.makedirs(path, exist_ok=True)
    region_table: List[Dict[str, Any]] = []
    tmp_data = os.path.join(path, DATA_FILE + ".tmp")
    offset = 0
    crc = 0
    with open(tmp_data, "wb") as f:
        for name, arr in regions:
            arr = np.ascontiguousarray(arr)
            pad = (-offset) % ALIGN
            if pad:
                f.write(b"\x00" * pad)
                crc = zlib.crc32(b"\x00" * pad, crc)
                offset += pad
            raw = arr.tobytes()
            f.write(raw)
            crc = zlib.crc32(raw, crc)
            region_table.append(
                {
                    "name": name,
                    "offset": offset,
                    "nbytes": len(raw),
                    "dtype": arr.dtype.str,
                    "shape": list(arr.shape),
                }
            )
            offset += len(raw)
        crash_point("segment.write.after_data_write")
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp_data, os.path.join(path, DATA_FILE))
    crash_point("segment.write.after_data_replace")

    meta = dict(metadata)
    meta["formatVersion"] = FORMAT_VERSION
    meta["regions"] = region_table
    meta["dataBytes"] = offset
    meta["dataCrc32"] = crc
    durable_write_bytes(
        os.path.join(path, META_FILE),
        json.dumps(meta, indent=1).encode("utf-8"),
        crash_prefix="segment.write.meta",
    )
    fsync_dir(path)


def data_crc32(path: str, chunk_bytes: int = 1 << 22) -> int:
    """Streamed CRC32 of a segment's columns.bin."""
    crc = 0
    with open(os.path.join(path, DATA_FILE), "rb") as f:
        while True:
            chunk = f.read(chunk_bytes)
            if not chunk:
                break
            crc = zlib.crc32(chunk, crc)
    return crc


def verify_segment(path: str) -> Dict[str, Any]:
    """Check the segment's data file against its committed metadata (size +
    CRC32).  Returns the parsed metadata on success; raises
    SegmentCorruptError on any mismatch.  Pre-CRC segments (no dataCrc32
    field) verify by size alone."""
    meta_path = os.path.join(path, META_FILE)
    data_path = os.path.join(path, DATA_FILE)
    if not os.path.isfile(meta_path):
        raise SegmentCorruptError(f"segment {path}: missing {META_FILE}")
    try:
        with open(meta_path) as f:
            meta = json.load(f)
    except (json.JSONDecodeError, OSError) as e:
        raise SegmentCorruptError(f"segment {path}: unreadable {META_FILE}: {e}") from e
    expect_bytes = meta.get("dataBytes")
    if expect_bytes is None:
        regions = meta.get("regions", [])
        expect_bytes = max((r["offset"] + r["nbytes"] for r in regions), default=0)
    if not os.path.isfile(data_path):
        if expect_bytes:
            raise SegmentCorruptError(f"segment {path}: missing {DATA_FILE}")
        return meta
    size = os.path.getsize(data_path)
    if size < expect_bytes:
        raise SegmentCorruptError(
            f"segment {path}: {DATA_FILE} is {size} bytes, metadata commits {expect_bytes}"
        )
    expect_crc = meta.get("dataCrc32")
    if expect_crc is not None and data_crc32(path) != expect_crc:
        raise SegmentCorruptError(f"segment {path}: {DATA_FILE} CRC32 mismatch")
    return meta


class RegionMap(Mapping[str, np.ndarray]):
    """Lazy mmap view over columns.bin keyed by region name."""

    def __init__(self, path: str, meta: Dict[str, Any]):
        self._data_path = os.path.join(path, DATA_FILE)
        self._table = {r["name"]: r for r in meta["regions"]}
        self._cache: Dict[str, np.ndarray] = {}

    def __getitem__(self, name: str) -> np.ndarray:
        if name not in self._cache:
            r = self._table[name]
            if r["nbytes"] == 0:
                self._cache[name] = np.empty(tuple(r["shape"]), dtype=np.dtype(r["dtype"]))
            else:
                self._cache[name] = np.memmap(
                    self._data_path,
                    mode="r",
                    dtype=np.dtype(r["dtype"]),
                    offset=r["offset"],
                    shape=tuple(r["shape"]),
                )
        return self._cache[name]

    def __contains__(self, name: object) -> bool:
        return name in self._table

    def __iter__(self):
        return iter(self._table)

    def __len__(self) -> int:
        return len(self._table)


def read_segment(path: str, verify: bool = False) -> Tuple[Dict[str, Any], RegionMap]:
    if verify:
        meta = verify_segment(path)
    else:
        with open(os.path.join(path, META_FILE)) as f:
            meta = json.load(f)
    if meta.get("formatVersion") != FORMAT_VERSION:
        raise ValueError(f"unsupported segment format version {meta.get('formatVersion')}")
    return meta, RegionMap(path, meta)
