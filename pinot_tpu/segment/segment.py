"""Immutable segment: the unit of storage, distribution and query.

Reference parity: pinot-segment-spi IndexSegment/ImmutableSegment and
pinot-segment-local ImmutableSegmentImpl + ImmutableSegmentLoader.load
(ImmutableSegmentLoader.java:91) — a named, immutable, columnar slice of a
table with per-column metadata, dictionaries, forward storage and optional
extra indexes.

TPU re-design (SURVEY.md section 7 "Segment = pytree of device arrays"):
  * Host side: zero-copy mmaps over columns.bin (store.py).
  * Device side: `to_device()` pins a plain-dict pytree of jnp arrays in HBM —
    {col: {"codes": u8/u16/u32[n]} | {"values": dtype[n]}, plus "dict" for
    numeric dictionaries and "nulls" for null masks}.  Static facts
    (num_docs, cardinalities, stats) stay host-side for pruning and for
    building closed-form predicate constants, so jitted kernels see only
    dense arrays and static shapes.
"""
from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

import numpy as np

from pinot_tpu.segment import packing, store
from pinot_tpu.segment.dictionary import Dictionary, min_code_dtype
from pinot_tpu.segment.stats import ColumnStats
from pinot_tpu.spi.schema import DataType, Schema

# Bumped when build-time encoding changes shape (v2: bit-packed forward
# indexes).  Segments carry it in meta; absent/v1 segments have no
# `codeBits` column attribute and load through the raw path unchanged.
BUILDER_VERSION = 2


@dataclass
class ColumnData:
    """One column inside a segment (DataSource analog: forward index +
    dictionary + null vector handles)."""

    name: str
    data_type: DataType
    dictionary: Optional[Dictionary]  # None => raw storage
    codes: Optional[np.ndarray]  # uint8/16/32[num_docs] (SV) or [num_docs, max_len] (MV)
    values: Optional[np.ndarray]  # raw storage (numeric) when no dictionary
    nulls: Optional[np.ndarray]  # bool[num_docs] true=null, None if no nulls
    stats: ColumnStats
    # multi-value columns: per-row element counts; codes beyond a row's
    # length hold the padding code (== cardinality)
    mv_lengths: Optional[np.ndarray] = None
    # bit-packed forward index (segment/packing.py): `packed` holds codes in
    # `code_bits`-wide lanes inside uint32 words.  `codes` stays materialized
    # host-side (index builds, sorted searchsorted, decode); `packed` is what
    # save() persists and to_device(packed_codes=True) ships.  None on raw,
    # MV, and wide (>16-bit) columns.
    code_bits: Optional[int] = None
    packed: Optional[np.ndarray] = None

    @property
    def has_dictionary(self) -> bool:
        return self.dictionary is not None

    @property
    def is_multi_value(self) -> bool:
        return self.mv_lengths is not None

    @property
    def cardinality(self) -> int:
        return self.dictionary.cardinality if self.dictionary else self.stats.cardinality

    def value_at(self, doc: int):
        """Point read of one value (upsert merge reads) — O(1), no full
        column materialization."""
        if self.mv_lengths is not None:
            ln = int(self.mv_lengths[doc])
            if self.dictionary is not None:
                return tuple(self.dictionary.get_values(self.codes[doc, :ln]))
            return tuple(self.values[doc, :ln].tolist())
        if self.nulls is not None and self.nulls[doc]:
            return None
        if self.dictionary is not None:
            v = self.dictionary.get_values(np.asarray([self.codes[doc]]))[0]
        else:
            v = self.values[doc]
        return v.item() if isinstance(v, np.generic) else v

    def decoded(self) -> np.ndarray:
        """Materialize raw values host-side (tests/golden comparisons).
        MV columns decode to an object array of tuples."""
        if self.mv_lengths is not None:
            out = np.empty(len(self.mv_lengths), dtype=object)
            for i, ln in enumerate(self.mv_lengths):
                if self.dictionary is not None:
                    out[i] = tuple(self.dictionary.get_values(self.codes[i, :ln]))
                else:
                    out[i] = tuple(self.values[i, :ln].tolist())
            return out
        if self.dictionary is not None:
            return self.dictionary.get_values(self.codes)
        return self.values


class ImmutableSegment:
    """Loaded immutable segment with optional device residency."""

    def __init__(
        self,
        name: str,
        table_name: str,
        schema: Schema,
        columns: Dict[str, ColumnData],
        num_docs: int,
        indexes: Optional[Dict[str, Dict[str, Any]]] = None,
        creation_time_ms: int = 0,
        time_range: Optional[tuple] = None,
    ):
        self.name = name
        self.table_name = table_name
        self.schema = schema
        self.columns = columns
        self.num_docs = num_docs
        # indexes[kind][column] -> index object (indexes/ package), e.g.
        # indexes["inverted"]["color"] -> BitmapInvertedIndex
        self.indexes: Dict[str, Dict[str, Any]] = indexes or {}
        self.creation_time_ms = creation_time_ms
        self.time_range = time_range  # (min, max) of the table's time column
        # upsert hooks: validDocIds bitmask (bool[num_docs], False = replaced
        # by a newer row elsewhere) and the build-time sort permutation
        # (new position -> input row) used to remap it at seal time
        self.valid_docs: Optional[np.ndarray] = None
        self.sort_order: Optional[np.ndarray] = None
        self._device_cache: Dict[str, Any] = {}
        # guards _device_cache reads/publishes under tiered residency
        # (segment/residency.py); NEVER held across a device copy — owners
        # stage with no lock held, then publish in one critical section so a
        # query racing an eviction re-checks instead of mixing tiers
        self._device_lock = threading.Lock()
        # durable home of this segment on local disk (set by save/load):
        # the deep store uploads from here without a redundant re-serialize
        self.source_dir: Optional[str] = None

    # ------------------------------------------------------------------
    def column(self, name: str) -> ColumnData:
        try:
            return self.columns[name]
        except KeyError:
            raise KeyError(f"segment {self.name} has no column {name!r}") from None

    def ensure_columns(self, table_schema, names) -> None:
        """Schema evolution: synthesize virtual columns for fields the TABLE
        schema has but this (older) segment lacks.  Old rows read as SQL
        NULL (null mask all-set over the type placeholder) — a documented
        delta from Pinot's defaultColumnHandler, whose legacy semantics
        return the default VALUE; with this engine's SQL-standard null
        handling, placeholder values leaking into SUM/MIN would corrupt
        aggregates (review-caught)."""
        from pinot_tpu.segment.dictionary import Dictionary
        from pinot_tpu.segment.stats import collect_stats

        for name in names:
            if name in self.columns or name not in table_schema:
                continue
            f = table_schema.field(name)
            if not f.single_value:
                raise NotImplementedError(f"virtual default for MV column {name} is unsupported")
            default = f.data_type.null_placeholder
            n = self.num_docs
            nulls = np.ones(n, dtype=bool)
            if f.data_type.is_string_like:
                dictionary, _ = Dictionary.build(f.data_type, np.asarray([default], dtype=object))
                codes = np.zeros(n, dtype=np.uint8)
                stats = collect_stats(name, f.data_type, np.asarray([default], dtype=object), None, 1, True)
                stats.num_docs = n
                self.columns[name] = ColumnData(name, f.data_type, dictionary, codes, None, nulls, stats)
            else:
                arr = np.broadcast_to(f.data_type.np_dtype.type(default), (n,))
                stats = collect_stats(name, f.data_type, np.asarray([default]), None, 1, False)
                stats.num_docs = n
                self.columns[name] = ColumnData(name, f.data_type, None, None, arr, nulls, stats)

    @property
    def column_names(self) -> List[str]:
        return list(self.columns)

    # -- device residency ----------------------------------------------
    def device_group(self, device=None):
        """Residency cache-group key: ALL flavors (raw and #packed) of this
        segment on one device live and die as a unit."""
        return ("seg", id(self), device)

    @staticmethod
    def _entry_bytes(c: ColumnData, use_packed: bool) -> int:
        """Host-side estimate of the device bytes one cache entry pins."""
        n = 0
        if use_packed:
            n += c.packed.nbytes
        elif c.codes is not None:
            n += c.codes.nbytes
        if c.codes is not None and c.dictionary is not None:
            dvals = c.dictionary.device_values()
            if dvals is not None:
                n += dvals.nbytes
        for arr in (c.values, c.nulls, c.mv_lengths):
            if arr is not None:
                n += arr.nbytes
        return n

    def _plan_missing(self, device, cols, packed_codes):
        """(missing [(cname, key, use_packed)], bytes) the cache lacks."""
        need = []
        nbytes = 0
        with self._device_lock:
            cache = self._device_cache.get(device, {})
            for cname in cols:
                c = self.columns[cname]
                use_packed = bool(packed_codes and c.packed is not None)
                key = f"{cname}#packed" if use_packed else cname
                if key in cache:
                    continue
                need.append((cname, key, use_packed))
                nbytes += self._entry_bytes(c, use_packed)
        return need, nbytes

    def _stage_entry(self, c: ColumnData, use_packed: bool, device) -> Dict[str, Any]:
        """One column's host->device copy (NO locks held — this runs on the
        staging stream or a staging owner, never under _device_lock)."""
        import jax

        entry: Dict[str, Any] = {}
        if use_packed:
            entry["codes_packed"] = jax.device_put(np.asarray(c.packed), device)
        elif c.codes is not None:
            entry["codes"] = jax.device_put(np.asarray(c.codes), device)
        if c.codes is not None:
            dvals = c.dictionary.device_values() if c.dictionary else None
            if dvals is not None:
                entry["dict"] = jax.device_put(dvals, device)
        if c.values is not None:
            entry["values"] = jax.device_put(np.asarray(c.values), device)
        if c.nulls is not None:
            entry["nulls"] = jax.device_put(np.asarray(c.nulls), device)
        if c.mv_lengths is not None:
            entry["lengths"] = jax.device_put(np.asarray(c.mv_lengths), device)
        return entry

    def _assemble(self, device, cols, packed_codes) -> Optional[Dict[str, Any]]:
        """Read the pytree out of the cache in ONE critical section; None if
        any needed entry vanished (a racing eviction) — the caller re-stages
        the whole group, so it can never observe a half-evicted segment."""
        with self._device_lock:
            cache = self._device_cache.get(device, {})
            out: Dict[str, Any] = {}
            for cname in cols:
                c = self.columns[cname]
                use_packed = bool(packed_codes and c.packed is not None)
                key = f"{cname}#packed" if use_packed else cname
                if key not in cache:
                    return None
                out[cname] = cache[key]
            return out

    def evict_device(self, device=None) -> None:
        """Atomic flavor invalidation: the entire per-device cache region —
        raw, #packed, dict, null entries together — drops in one critical
        section (residency eviction callback; satellite fix r17)."""
        with self._device_lock:
            self._device_cache.pop(device, None)

    def to_device(
        self,
        device=None,
        columns: Optional[List[str]] = None,
        packed_codes: bool = False,
        residency=None,
        prefetch: bool = False,
        query_id: Optional[str] = None,
    ) -> Dict[str, Any]:
        """Pin column arrays into device memory; returns the segment pytree.

        The pytree is cached — segments are immutable so repeated queries hit
        HBM-resident arrays.  With `residency` (segment/residency.py) HBM is
        a byte-budgeted CACHE over the host arrays: staging charges the
        residency budget (evicting cost-ranked victims to make room), at most
        one thread copies while the rest park on the group's event, and a
        mid-stage failure unwinds the charge (crash-harness covered).
        `prefetch=True` marks the stage as issued ahead of need for the
        prefetch-hit accounting.  Without `residency` this is the legacy
        pin-everything path.

        packed_codes=True ships bit-packed columns as uint32 lane words under
        entry key "codes_packed" instead of widened "codes" — opt-in because
        only plan kernels that unpack at trace time (or route the words to
        the Pallas lane-unpack) can consume it; direct `cols[n]["codes"]`
        readers keep the default.  Packed entries cache under a distinct
        key so the two shapes never alias."""
        cols = columns or list(self.columns)
        if residency is None:
            # legacy pin-everything path: no budget, no eviction — but the
            # copy still happens with no lock held, and the publish races
            # resolve first-wins through setdefault
            out: Dict[str, Any] = {}
            for cname in cols:
                c = self.columns[cname]
                use_packed = bool(packed_codes and c.packed is not None)
                key = f"{cname}#packed" if use_packed else cname
                with self._device_lock:
                    entry = self._device_cache.setdefault(device, {}).get(key)
                if entry is None:
                    entry = self._stage_entry(c, use_packed, device)
                    with self._device_lock:
                        entry = self._device_cache.setdefault(device, {}).setdefault(key, entry)
                out[cname] = entry
            return out

        from pinot_tpu.segment import residency as res_mod
        from pinot_tpu.utils.crashpoints import crash_point

        group = self.device_group(device)
        while True:
            missing, _ = self._plan_missing(device, cols, packed_codes)
            st, entry = residency.begin_stage(
                group, self.table_name, lambda: self.evict_device(device), prefetch=prefetch
            )
            if st == res_mod.WAIT:
                residency.wait(entry)
                continue
            if st == res_mod.HIT:
                if not missing:
                    out = self._assemble(device, cols, packed_codes)
                    if out is not None:
                        return out
                    continue  # evicted between plan and read: re-stage
                # resident but lacking columns/flavors this query needs:
                # claim the group for incremental staging
                st2, entry2 = residency.begin_grow(group)
                if st2 == res_mod.WAIT:
                    residency.wait(entry2)
                    continue
                if st2 == res_mod.RETRY:
                    continue
            # OWN: charge, copy (no locks held), publish, commit
            try:
                missing, nbytes = self._plan_missing(device, cols, packed_codes)
                residency.charge(group, nbytes, query_id=query_id)
                crash_point("segment.stage.after_charge")
                staged = {
                    key: self._stage_entry(self.columns[cname], up, device)
                    for cname, key, up in missing
                }
                crash_point("segment.stage.after_copy")
                with self._device_lock:
                    self._device_cache.setdefault(device, {}).update(staged)
            except BaseException:
                residency.abort_stage(group)
                raise
            residency.finish_stage(group)
            out = self._assemble(device, cols, packed_codes)
            if out is not None:
                return out

    def release_device(self) -> None:
        with self._device_lock:
            self._device_cache.clear()

    # -- persistence ----------------------------------------------------
    def save(self, path: str) -> None:
        regions = []
        col_meta = []
        for c in self.columns.values():
            if c.dictionary is not None:
                regions.extend(c.dictionary.to_regions(c.name))
                # packed columns persist the lane words; codes are
                # rematerialized at load via packing.unpack_codes
                regions.append((f"{c.name}.fwd", c.packed if c.packed is not None else c.codes))
            else:
                regions.append((f"{c.name}.fwd", c.values))
            if c.nulls is not None:
                regions.append((f"{c.name}.nulls", np.packbits(c.nulls)))
            if c.mv_lengths is not None:
                regions.append((f"{c.name}.mvlen", c.mv_lengths))
            cm = {
                "stats": c.stats.to_dict(),
                "hasNulls": c.nulls is not None,
                "isMV": c.mv_lengths is not None,
            }
            if c.packed is not None:
                cm["codeBits"] = int(c.code_bits)
            col_meta.append(cm)
        for kind, by_col in self.indexes.items():
            for cname, idx in by_col.items():
                regions.extend(idx.to_regions(f"{cname}.{kind}"))
        meta = {
            "segmentName": self.name,
            "tableName": self.table_name,
            "numDocs": self.num_docs,
            "builderVersion": BUILDER_VERSION,
            "schema": self.schema.to_dict(),
            "columns": col_meta,
            "indexes": {kind: {c: idx.meta() for c, idx in by_col.items()} for kind, by_col in self.indexes.items()},
            "creationTimeMs": self.creation_time_ms,
            "timeRange": [v.item() if isinstance(v, np.generic) else v for v in self.time_range]
            if self.time_range
            else None,
        }
        store.write_segment(path, meta, regions)
        self.source_dir = path

    @staticmethod
    def load(path: str, verify: bool = False) -> "ImmutableSegment":
        """mmap-load (ImmutableSegmentLoader.load analog — ReadMode.mmap).

        verify=True checks columns.bin against the committed size + CRC32
        first (SegmentCorruptError on mismatch) — the deep-store download
        and server restart-recovery paths load verified."""
        from pinot_tpu.indexes import load_index  # local import; avoids cycle

        meta, regions = store.read_segment(path, verify=verify)
        schema = Schema.from_dict(meta["schema"])
        num_docs = meta["numDocs"]
        columns: Dict[str, ColumnData] = {}
        for cm in meta["columns"]:
            stats = ColumnStats.from_dict(cm["stats"])
            name = stats.name
            dt = stats.data_type
            nulls = None
            if cm.get("hasNulls"):
                nulls = np.unpackbits(np.asarray(regions[f"{name}.nulls"]), count=num_docs).astype(bool)
            if stats.has_dictionary:
                dictionary = Dictionary.from_regions(dt, regions, name)
                fwd = regions[f"{name}.fwd"]
                mv_lengths = regions[f"{name}.mvlen"] if cm.get("isMV") else None
                bits = cm.get("codeBits")  # absent on pre-v2 segments: raw path
                packed = None
                codes = fwd
                if bits and bits < 32:
                    packed = np.asarray(fwd)
                    codes = packing.unpack_codes(
                        packed, bits, num_docs, dtype=min_code_dtype(dictionary.cardinality)
                    )
                columns[name] = ColumnData(
                    name, dt, dictionary, codes, None, nulls, stats,
                    mv_lengths=mv_lengths, code_bits=bits, packed=packed,
                )
            else:
                mv_lengths = regions[f"{name}.mvlen"] if cm.get("isMV") else None
                columns[name] = ColumnData(
                    name, dt, None, None, regions[f"{name}.fwd"], nulls, stats, mv_lengths=mv_lengths
                )
        indexes: Dict[str, Dict[str, Any]] = {}
        for kind, by_col in meta.get("indexes", {}).items():
            for cname, idx_meta in by_col.items():
                idx = load_index(kind, idx_meta, regions, f"{cname}.{kind}")
                indexes.setdefault(kind, {})[cname] = idx
        # text indexes evaluate phrase queries over the ORIGINAL values —
        # rehydrate them from the column dictionary (not persisted twice)
        for cname, idx in indexes.get("text", {}).items():
            if cname in columns and columns[cname].dictionary is not None:
                idx.values = columns[cname].dictionary.values
        seg = ImmutableSegment(
            name=meta["segmentName"],
            table_name=meta["tableName"],
            schema=schema,
            columns=columns,
            num_docs=num_docs,
            indexes=indexes,
            creation_time_ms=meta.get("creationTimeMs", 0),
            time_range=tuple(meta["timeRange"]) if meta.get("timeRange") else None,
        )
        seg.source_dir = path
        return seg
