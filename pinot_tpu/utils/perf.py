"""Performance observatory: kernel cost accounting, roofline %, the
per-table/per-shape perf ledger, and the bench-history regression gate.

Reference parity: pinot-server's query-cost/latency instrumentation
(ServerQueryLogger + the per-table QueryPhase timers) has no analog for
*device* work — on TPU the interesting number is bytes streamed vs peak HBM
bandwidth (roofline %), not CPU time.  This module closes that gap:

- KernelCost: per-compiled-kernel flops / bytes-accessed / output-bytes plus
  lower+compile wall time, captured ONCE at plan-cache fill.  On TPU the
  numbers come from XLA's `lowered.cost_analysis()`; everywhere else (CPU
  tier-1, interpret-mode Pallas, backends that don't expose cost analysis)
  a guarded analytic fallback models bytes as packed storage widths per row
  and flops from the group-accumulate matmul shape.  PINOT_TPU_COST_SOURCE
  ∈ {auto, xla, analytic} overrides the choice.

- peak_hbm_bytes_per_sec(): device peak from `jax.devices()` metadata (a
  device-kind table; PINOT_TPU_PEAK_HBM_BPS overrides), feeding
  roofline_pct() = achieved bytes/s ÷ peak.

- PerfLedger: rolling windows of rows/s, bytes/s, roofline %, compile ms,
  plan-cache outcome and QPS keyed (table, shape digest) — the QPS/latency
  tracking groundwork ROADMAP item 1 asks for.  Exported as bounded-name
  gauges (`perf.{table}.*`) and the `GET /debug/perf` / `cli perf` views.

- Bench-history gate: bench.py appends one `bench_record()` per run to
  bench_history.jsonl; `check_regression()` compares the latest run against
  a pinned baseline with a noise-aware allowance derived from bench.py's
  run-variance spread, capped below 20% so a real one-fifth throughput loss
  can never hide inside the noise term.
"""
from __future__ import annotations

import collections
import json
import math
import os
import threading
import time
from dataclasses import dataclass, field
from functools import lru_cache
from typing import Any, Deque, Dict, List, Optional, Tuple

from pinot_tpu.utils.metrics import METRICS

# ---------------------------------------------------------------------------
# kernel cost accounting
# ---------------------------------------------------------------------------


@dataclass
class KernelCost:
    """Cost model for one compiled kernel, captured at plan-cache fill.

    `compile_ms` is filled in by the caller after timing the first dispatch
    (trace+compile happen inside the first jit call; XLA's AOT compile path
    would pay compilation twice and pin the executable to one device, so we
    never use it here).  `lower_ms` is the StableHLO lowering wall time when
    the XLA source ran, 0 for the analytic path.
    """

    flops: float = 0.0
    bytes_accessed: float = 0.0
    output_bytes: float = 0.0
    source: str = "analytic"  # "xla" | "analytic"
    lower_ms: float = 0.0
    compile_ms: float = 0.0

    def to_dict(self) -> Dict[str, Any]:
        return {
            "flops": self.flops,
            "bytesAccessed": self.bytes_accessed,
            "outputBytes": self.output_bytes,
            "source": self.source,
            "lowerMs": round(self.lower_ms, 3),
            "compileMs": round(self.compile_ms, 3),
        }


def _cost_source_mode() -> str:
    return os.environ.get("PINOT_TPU_COST_SOURCE", "auto").strip().lower()


def _finite(v: Any) -> Optional[float]:
    try:
        f = float(v)
    except (TypeError, ValueError):
        return None
    return f if math.isfinite(f) and f >= 0 else None


def capture_cost(fn, args: tuple, analytic: KernelCost, force: Optional[str] = None) -> KernelCost:
    """Capture the cost model for a jitted `fn` called with `args`.

    Mode "xla" lowers the function (without compiling — the first real
    dispatch compiles and is timed by the caller) and reads XLA's
    `cost_analysis()`; any failure — backend without cost analysis, lowering
    error, missing/non-finite keys — falls back to the provided analytic
    estimate.  Mode "auto" uses XLA only on TPU: on CPU the analytic model
    is free while an extra trace+lower costs milliseconds per cold plan.
    """
    mode = force or _cost_source_mode()
    if mode not in ("xla", "analytic"):
        import jax

        mode = "xla" if jax.default_backend() == "tpu" else "analytic"
    if mode != "xla":
        return analytic
    t0 = time.perf_counter()
    try:
        lowered = fn.lower(*args)
        costs = lowered.cost_analysis()
    except Exception:
        return analytic
    lower_ms = (time.perf_counter() - t0) * 1000.0
    if isinstance(costs, (list, tuple)):  # per-device list on some versions
        costs = costs[0] if costs else None
    if not isinstance(costs, dict):
        analytic.lower_ms = lower_ms
        return analytic
    flops = _finite(costs.get("flops"))
    bytes_accessed = _finite(costs.get("bytes accessed"))
    if bytes_accessed is None:
        # backend lowered fine but doesn't report byte traffic — the number
        # the roofline needs — so the whole estimate stays analytic
        analytic.lower_ms = lower_ms
        return analytic
    out_bytes = _finite(costs.get("bytes accessedout{}"))
    return KernelCost(
        flops=flops if flops is not None else analytic.flops,
        bytes_accessed=bytes_accessed,
        output_bytes=out_bytes if out_bytes is not None else analytic.output_bytes,
        source="xla",
        lower_ms=lower_ms,
    )


def analytic_bytes_per_row(columns, bitmap_params: int = 0) -> float:
    """Bytes the scan streams per row under the packed-storage model: each
    needed column at its stored width — bit-packed dict columns at
    `code_bits / 8` (the uint32 lane words are what actually stream; see
    segment/packing.py), unpacked dict codes at code dtype width, raw
    columns at value width — null bitmaps at 1 byte/row, plus one uint32
    per 32 rows per row-sharded index-bitmap parameter — the same model
    bench.py uses."""
    bpr = 0.0
    for c in columns:
        arr = c.codes if getattr(c, "codes", None) is not None else c.values
        if arr is not None:
            bits = getattr(c, "code_bits", None)
            if bits and getattr(c, "packed", None) is not None:
                bpr += bits / 8.0  # MV columns never pack, so no width factor
            else:
                bpr += arr.dtype.itemsize
        if getattr(c, "nulls", None) is not None:
            bpr += 1
    return bpr + bitmap_params * 4.0 / 32.0


def analytic_cost(
    num_rows: int,
    bytes_per_row: float,
    *,
    kind: str = "aggregation",
    num_groups: int = 0,
    num_entries: int = 1,
) -> KernelCost:
    """Analytic fallback cost for one kernel launch over `num_rows` rows.

    Flops follow the accumulate shape: group-bys one-hot-matmul every row
    into `num_groups` slots per agg table (ops.pallas_scan
    matmul_flops_per_row), plain aggregations do a couple of flops per row
    per entry, selections roughly one predicate op per row."""
    from pinot_tpu.ops.pallas_scan import matmul_flops_per_row

    num_entries = max(1, num_entries)
    if kind.startswith("groupby") and num_groups > 0:
        flops_per_row = matmul_flops_per_row(num_groups, num_entries)
        out_bytes = float(num_groups) * 8.0 * (num_entries + 1)  # partials + presence
    elif kind == "selection":
        flops_per_row = 1.0
        out_bytes = float(num_rows) * bytes_per_row  # gathered rows, pre-LIMIT
    else:
        flops_per_row = 2.0 * num_entries
        out_bytes = 8.0 * num_entries
    return KernelCost(
        flops=float(num_rows) * flops_per_row,
        bytes_accessed=float(num_rows) * bytes_per_row,
        output_bytes=out_bytes,
        source="analytic",
    )


# ---------------------------------------------------------------------------
# roofline: achieved vs peak HBM bytes/s
# ---------------------------------------------------------------------------

# Peak HBM bandwidth by jax device_kind (bytes/s).  Published chip specs;
# substring match so "TPU v5 lite" and "TPU v5e" both hit the v5e row.
_PEAK_HBM_BPS: Tuple[Tuple[str, float], ...] = (
    ("v6", 1.64e12),  # Trillium: 1,640 GB/s
    ("v5p", 2.765e12),
    ("v5", 8.19e11),  # v5e: 819 GB/s
    ("v4", 1.2e12),
    ("v3", 9.0e11),
    ("v2", 7.0e11),
)
# Host fallback: order-of-magnitude DDR bandwidth so CPU tier-1 rooflines
# are small-but-nonzero percentages rather than lies about TPU peaks.
_CPU_PEAK_HBM_BPS = 5.0e10


@lru_cache(maxsize=1)
def peak_hbm_bytes_per_sec() -> float:
    """Peak memory bandwidth of device 0 in bytes/s.  Env override
    PINOT_TPU_PEAK_HBM_BPS wins (tests flipping it must cache_clear())."""
    override = os.environ.get("PINOT_TPU_PEAK_HBM_BPS")
    if override:
        try:
            v = float(override)
            if v > 0:
                return v
        except ValueError:
            pass
    try:
        import jax

        kind = jax.devices()[0].device_kind.lower()
    except Exception:
        return _CPU_PEAK_HBM_BPS
    if "tpu" in kind:
        for marker, bps in _PEAK_HBM_BPS:
            if marker in kind:
                return bps
        return _PEAK_HBM_BPS[0][1]
    return _CPU_PEAK_HBM_BPS


def roofline_pct(bytes_accessed: float, seconds: float) -> Optional[float]:
    """Achieved HBM bandwidth as % of device peak; None when unmeasurable."""
    if bytes_accessed <= 0 or seconds <= 0:
        return None
    return 100.0 * (bytes_accessed / seconds) / peak_hbm_bytes_per_sec()


def combine_sources(a: Optional[str], b: Optional[str]) -> Optional[str]:
    """Merge two cost-source tags when stats accumulate across kernels."""
    if a is None or a == b:
        return b if a is None else a
    if b is None:
        return a
    return "mixed"


# ---------------------------------------------------------------------------
# per-table / per-shape perf ledger
# ---------------------------------------------------------------------------


@dataclass
class _LedgerEntry:
    window: int
    queries: int = 0
    cache_hits: int = 0
    cache_misses: int = 0
    compile_ms_total: float = 0.0
    rows_per_sec: Deque[float] = field(default_factory=collections.deque)
    bytes_per_sec: Deque[float] = field(default_factory=collections.deque)
    roofline: Deque[float] = field(default_factory=collections.deque)
    latency_ms: Deque[float] = field(default_factory=collections.deque)
    arrivals: Deque[float] = field(default_factory=collections.deque)

    def push(self, dq: Deque[float], v: float) -> None:
        dq.append(v)
        while len(dq) > self.window:
            dq.popleft()


def _win_stats(dq: Deque[float]) -> Dict[str, float]:
    if not dq:
        return {"last": 0.0, "mean": 0.0, "max": 0.0, "p99": 0.0}
    vals = list(dq)
    ordered = sorted(vals)
    p99 = ordered[min(len(ordered) - 1, int(round(0.99 * (len(ordered) - 1))))]
    return {
        "last": round(vals[-1], 3),
        "mean": round(sum(vals) / len(vals), 3),
        "max": round(max(vals), 3),
        # windowed tail: the autopilot's primary feedback signal
        "p99": round(p99, 3),
    }


def _window_qps(arrivals: Deque[float]) -> float:
    """Arrival rate over the rolling window: (n-1) queries per elapsed span.
    Span-based rather than per-second bucketing so short test bursts still
    read as a meaningful rate."""
    if len(arrivals) < 2:
        return 0.0
    span = arrivals[-1] - arrivals[0]
    return (len(arrivals) - 1) / span if span > 0 else 0.0


class PerfLedger:
    """Rolling perf windows keyed (table, shape digest).

    Gauges are per-table only (`perf.{table}.rowsPerSec` etc. — table names
    are a bounded set, same precedent as `server.segmentBytes.{table}`);
    shape digests stay inside the snapshot payload so metric-name
    cardinality never tracks query shapes."""

    def __init__(self, window: int = 128) -> None:
        self.window = window
        self._lock = threading.Lock()
        self._entries: Dict[Tuple[str, str], _LedgerEntry] = {}

    def record(
        self,
        table: str,
        shape_fp: str,
        *,
        rows: float,
        time_ms: float,
        kernel_bytes: float = 0.0,
        compile_ms: float = 0.0,
        cache_hit: Optional[bool] = None,
        engine: str = "sse",
    ) -> None:
        if not table:
            table = "_unknown"
        rows_ps = rows / (time_ms / 1000.0) if time_ms > 0 else 0.0
        bytes_ps = kernel_bytes / (time_ms / 1000.0) if time_ms > 0 else 0.0
        roof = roofline_pct(kernel_bytes, time_ms / 1000.0)
        now = time.monotonic()
        with self._lock:
            key = (table, shape_fp or "")
            e = self._entries.get(key)
            if e is None:
                e = self._entries[key] = _LedgerEntry(window=self.window)
            e.queries += 1
            if cache_hit is True:
                e.cache_hits += 1
            elif cache_hit is False:
                e.cache_misses += 1
            e.compile_ms_total += compile_ms
            e.push(e.rows_per_sec, rows_ps)
            e.push(e.bytes_per_sec, bytes_ps)
            e.push(e.latency_ms, time_ms)
            if roof is not None:
                e.push(e.roofline, roof)
            e.push(e.arrivals, now)
            table_arrivals = [
                t for (tb, _), en in self._entries.items() if tb == table for t in en.arrivals
            ]
        # gauge export outside the ledger lock (gauge ops take their own)
        table_arrivals.sort()
        qps_dq: Deque[float] = collections.deque(table_arrivals[-self.window :])
        g = METRICS.gauge
        g(f"perf.{table}.rowsPerSec").set(rows_ps)
        g(f"perf.{table}.bytesPerSec").set(bytes_ps)
        g(f"perf.{table}.qps").set(_window_qps(qps_dq))
        if roof is not None:
            g(f"perf.{table}.rooflinePct").set(roof)
        if compile_ms > 0:
            g(f"perf.{table}.lastCompileMs").set(compile_ms)

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            items = list(self._entries.items())
        tables: Dict[str, Any] = {}
        for (table, fp), e in items:
            t = tables.setdefault(table, {"queries": 0, "qps": 0.0, "shapes": {}})
            t["queries"] += e.queries
            hitseen = e.cache_hits + e.cache_misses
            t["shapes"][fp or "-"] = {
                "queries": e.queries,
                "qps": round(_window_qps(e.arrivals), 3),
                "rowsPerSec": _win_stats(e.rows_per_sec),
                "bytesPerSec": _win_stats(e.bytes_per_sec),
                "rooflinePct": _win_stats(e.roofline),
                "latencyMs": _win_stats(e.latency_ms),
                "compileMsTotal": round(e.compile_ms_total, 3),
                "planCacheHitRate": round(e.cache_hits / hitseen, 3) if hitseen else None,
            }
        for table, t in tables.items():
            arrivals = sorted(
                ts
                for (tb, _), e in items
                if tb == table
                for ts in e.arrivals
            )
            t["qps"] = round(_window_qps(collections.deque(arrivals[-self.window :])), 3)
        return {"window": self.window, "tables": tables}

    def reset(self) -> None:
        with self._lock:
            self._entries.clear()


PERF_LEDGER = PerfLedger()


# ---------------------------------------------------------------------------
# bench-history regression gate
# ---------------------------------------------------------------------------

# Higher-is-better throughput series the gate compares run-over-run.
GATE_METRICS: Tuple[str, ...] = (
    "kernel_rows_per_sec",
    "e2e_rows_per_sec",
    "warm_p50_rows_per_sec",
    "effective_bytes_per_sec",
    "batched_qps",
    # packed-forward-index sections (bench.py scan_bound / agg_bound): a
    # low-selectivity filter scan and a group-by-heavy aggregation, both
    # streaming bit-packed columns
    "scan_bound_rows_per_sec",
    "agg_bound_rows_per_sec",
    # tiered-storage working-set sweep (bench.py working_set_sweep): rows/s
    # with the working set at 1x and 4x the HBM cache budget, plus the
    # prefetch-hit rate of the staged copy stream on the 4x (capacity-
    # exceeding) leg — the regime the r11 ledger used to simply 503
    "ws_sweep_1x_rows_per_sec",
    "ws_sweep_4x_rows_per_sec",
    "ws_prefetch_hit_rate",
    # 2-D mesh scale-out (bench.py mesh_scaling): shard-axis capacity ratio
    # (full shard width vs one device) and replica-axis concurrent-QPS ratio
    # (ReplicatedEngine R=2 vs R=1).  In-image both hover near 1.0 (emulated
    # devices share the container's cores) — gated as regression canaries
    # for the hierarchical-combine and replica-routing paths, not as
    # scaling claims
    "mesh_shard_speedup",
    "mesh_replica_qps_scale",
)

# Lower-is-better latency series: the gate fails when these RISE past the
# allowance (drop is computed with the sign flipped).  hedged_p99_ms is the
# tail_latency bench's hedged p99 under one 10x-degraded replica — the
# tail-tolerance layer's whole point is keeping it near the fault-free p99.
# failover_blackout_ms is the HA drill's control-plane blackout in SIM time
# (lease expiry + standby replay-to-tip + handle adoption): the election
# protocol's cost, which a regression in lease/fence/promote code inflates.
# autopilot_admitted_p99_ms is the autopilot_overload bench's admitted-p99
# at 3x offered load under a seeded gray fault with the closed loop driving
# the knobs — the adaptive-serving layer's headline number.
GATE_METRICS_LOWER: Tuple[str, ...] = (
    "hedged_p99_ms",
    "failover_blackout_ms",
    "autopilot_admitted_p99_ms",
)

# Allowance bounds: at least 15% slack (CI-grade CPU runs are noisy even
# with bench.py's median-of-pairs machinery), never 20%+ — the acceptance
# bar is that a true ≥20% throughput regression always trips the gate.
_MIN_ALLOWED_DROP = 0.15
_MAX_ALLOWED_DROP = 0.19
_NOISE_MULT = 1.25


def bench_record(report: Dict[str, Any], *, bench: str = "ssb_groupby") -> Dict[str, Any]:
    """Distill one bench.py report into the flat history-line schema the
    gate compares.  Timestamps are stamped by the caller (bench.py)."""
    sweep = report.get("distinct_literal_sweep", {}) or {}
    roofline = report.get("roofline", {}) or {}
    qps = report.get("concurrent_qps", {}) or {}
    tail = report.get("tail_latency", {}) or {}
    scan_b = report.get("scan_bound", {}) or {}
    agg_b = report.get("agg_bound", {}) or {}
    ws = report.get("working_set_sweep", {}) or {}
    fo = report.get("failover", {}) or {}
    ms = report.get("mesh_scaling", {}) or {}
    ap = report.get("autopilot_overload", {}) or {}
    return {
        "schema": 1,
        "bench": bench,
        "backend": report.get("backend"),
        "rows": report.get("rows"),
        "device_kind": roofline.get("device_kind"),
        "metrics": {
            "kernel_rows_per_sec": report.get("value"),
            "e2e_rows_per_sec": report.get("value_e2e"),
            "warm_p50_rows_per_sec": sweep.get("warm_p50_rows_per_sec"),
            "effective_bytes_per_sec": report.get("effective_bytes_per_sec"),
            "cost_bytes_per_sec": roofline.get("cost_bytes_per_sec"),
            "roofline_pct": roofline.get("kernel_roofline_pct"),
            "plan_cache_hit_rate": (report.get("plan_cache", {}) or {}).get("hit_rate"),
            "batched_qps": (qps.get("batched", {}) or {}).get("qps"),
            "unbatched_qps": (qps.get("unbatched", {}) or {}).get("qps"),
            "batch_speedup": qps.get("batch_speedup"),
            "hedged_p99_ms": (tail.get("hedged", {}) or {}).get("p99_ms"),
            "unhedged_p99_ms": (tail.get("unhedged", {}) or {}).get("p99_ms"),
            "hedge_rate": tail.get("hedge_rate"),
            "scan_bound_rows_per_sec": scan_b.get("rows_per_sec"),
            "scan_bound_roofline_pct": scan_b.get("roofline_pct"),
            "agg_bound_rows_per_sec": agg_b.get("rows_per_sec"),
            "agg_bound_roofline_pct": agg_b.get("roofline_pct"),
            "ws_sweep_1x_rows_per_sec": (ws.get("legs", {}).get("1x", {}) or {}).get(
                "rows_per_sec"
            ),
            "ws_sweep_4x_rows_per_sec": (ws.get("legs", {}).get("4x", {}) or {}).get(
                "rows_per_sec"
            ),
            "ws_prefetch_hit_rate": (ws.get("legs", {}).get("4x", {}) or {}).get(
                "prefetch_hit_rate"
            ),
            "failover_blackout_ms": fo.get("blackout_ms"),
            "failover_replay_ms": fo.get("replay_to_tip_ms"),
            "failover_data_plane_success_rate": (fo.get("data_plane", {}) or {}).get(
                "success_rate"
            ),
            "mesh_shard_speedup": ms.get("mesh_shard_speedup"),
            "mesh_replica_qps_scale": ms.get("mesh_replica_qps_scale"),
            "mesh_2x4_rows_per_sec": ((ms.get("topologies", {}) or {}).get("2x4", {}) or {}).get(
                "rows_per_sec"
            ),
            "autopilot_admitted_p99_ms": (ap.get("autopilot", {}) or {}).get(
                "admitted_p99_ms"
            ),
            "autopilot_vs_best_static": ap.get("autopilot_vs_best_static"),
            "autopilot_knob_changes": (ap.get("autopilot", {}) or {}).get("knob_changes"),
        },
        "noise": {"run_variance": report.get("run_variance", 0.0)},
    }


def append_bench_history(path: str, record: Dict[str, Any]) -> None:
    with open(path, "a", encoding="utf-8") as f:
        f.write(json.dumps(record, sort_keys=True) + "\n")


def load_bench_history(path: str) -> List[Dict[str, Any]]:
    """All parseable history lines, oldest first; corrupt lines skipped (a
    torn append must not wedge the gate)."""
    out: List[Dict[str, Any]] = []
    if not os.path.exists(path):
        return out
    with open(path, "r", encoding="utf-8") as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                continue
            if isinstance(rec, dict):
                out.append(rec)
    return out


def regression_allowance(*records: Dict[str, Any]) -> float:
    """Noise-aware allowed fractional drop, from the worst run-variance
    spread among the compared records (bench.py's (max-min)/median over
    marginal-slope pairs), scaled and clamped to [15%, 19%]."""
    spread = 0.0
    for rec in records:
        rv = (rec.get("noise", {}) or {}).get("run_variance", 0.0)
        try:
            rv = float(rv)
        except (TypeError, ValueError):
            rv = 0.0
        if math.isfinite(rv) and rv > spread:
            spread = rv
    return min(_MAX_ALLOWED_DROP, max(_MIN_ALLOWED_DROP, _NOISE_MULT * spread))


def check_regression(
    latest: Dict[str, Any],
    baseline: Dict[str, Any],
    threshold: Optional[float] = None,
) -> Dict[str, Any]:
    """Compare the latest bench record against the pinned baseline.

    Returns {ok, allowed_drop, checks: [...], reasons: [...]}.  Fails when
    any gated throughput metric drops more than the allowance, when the two
    records ran different benches/backends (incomparable), or when no gated
    metric exists in both (a silent empty comparison must not pass)."""
    reasons: List[str] = []
    for key in ("bench", "backend", "rows"):
        a, b = latest.get(key), baseline.get(key)
        if a is not None and b is not None and a != b:
            reasons.append(f"incomparable: {key} changed {b!r} -> {a!r}")
    allowed = threshold if threshold is not None else regression_allowance(latest, baseline)
    lm = latest.get("metrics", {}) or {}
    bm = baseline.get("metrics", {}) or {}
    checks: List[Dict[str, Any]] = []
    for m in GATE_METRICS + GATE_METRICS_LOWER:
        lv, bv = _finite(lm.get(m)), _finite(bm.get(m))
        if lv is None or bv is None or bv == 0:
            continue
        # lower-is-better series invert the sign: a latency RISE is the
        # regression, so drop = (lv - bv) / bv
        drop = (lv - bv) / bv if m in GATE_METRICS_LOWER else (bv - lv) / bv
        ok = drop <= allowed
        checks.append(
            {
                "metric": m,
                "baseline": bv,
                "latest": lv,
                "drop_pct": round(drop * 100.0, 2),
                "ok": ok,
            }
        )
        if not ok:
            reasons.append(
                f"{m} regressed {drop * 100.0:.1f}% "
                f"({bv:g} -> {lv:g}; allowed {allowed * 100.0:.1f}%)"
            )
    if not checks:
        reasons.append("no gated metrics present in both records")
    return {
        "ok": not reasons,
        "allowed_drop": round(allowed, 4),
        "checks": checks,
        "reasons": reasons,
    }
