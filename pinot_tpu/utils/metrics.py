"""Process-wide metrics registry (ServerMetrics/BrokerMetrics analog,
pinot-common/.../metrics/ — meters, gauges and timers keyed by name).

Re-design: one lock-free-enough registry of counters/gauges/timers with a
snapshot() export instead of yammer/dropwizard plumbing; emitters call
METRICS.counter("queries").inc() on the hot path (dict lookups only).
"""
from __future__ import annotations

import threading
import time
from typing import Any, Dict, List


class Counter:
    __slots__ = ("value",)

    def __init__(self) -> None:
        self.value = 0

    def inc(self, n: int = 1) -> None:
        self.value += n


class Gauge:
    __slots__ = ("value",)

    def __init__(self) -> None:
        self.value = 0.0

    def set(self, v: float) -> None:
        self.value = v


class Timer:
    """Count + total + max milliseconds (the useful aggregate slice of a
    latency histogram without per-query allocation)."""

    __slots__ = ("count", "total_ms", "max_ms")

    def __init__(self) -> None:
        self.count = 0
        self.total_ms = 0.0
        self.max_ms = 0.0

    def update(self, ms: float) -> None:
        self.count += 1
        self.total_ms += ms
        if ms > self.max_ms:
            self.max_ms = ms

    @property
    def mean_ms(self) -> float:
        return self.total_ms / self.count if self.count else 0.0


class MetricsRegistry:
    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._counters: Dict[str, Counter] = {}
        self._gauges: Dict[str, Gauge] = {}
        self._timers: Dict[str, Timer] = {}

    def counter(self, name: str) -> Counter:
        c = self._counters.get(name)
        if c is None:
            with self._lock:
                c = self._counters.setdefault(name, Counter())
        return c

    def gauge(self, name: str) -> Gauge:
        g = self._gauges.get(name)
        if g is None:
            with self._lock:
                g = self._gauges.setdefault(name, Gauge())
        return g

    def timer(self, name: str) -> Timer:
        t = self._timers.get(name)
        if t is None:
            with self._lock:
                t = self._timers.setdefault(name, Timer())
        return t

    def snapshot(self) -> Dict[str, Any]:
        return {
            "counters": {k: c.value for k, c in self._counters.items()},
            "gauges": {k: g.value for k, g in self._gauges.items()},
            "timers": {
                k: {"count": t.count, "meanMs": t.mean_ms, "maxMs": t.max_ms}
                for k, t in self._timers.items()
            },
        }

    def reset(self) -> None:
        with self._lock:
            self._counters.clear()
            self._gauges.clear()
            self._timers.clear()


METRICS = MetricsRegistry()


class Span:
    """One trace span (RequestContext/tracing analog, SURVEY.md 5.1)."""

    __slots__ = ("name", "start", "duration_ms", "children")

    def __init__(self, name: str):
        self.name = name
        self.start = time.perf_counter()
        self.duration_ms = 0.0
        self.children: List["Span"] = []

    def close(self) -> None:
        self.duration_ms = (time.perf_counter() - self.start) * 1000

    def to_dict(self) -> Dict[str, Any]:
        d: Dict[str, Any] = {"name": self.name, "ms": round(self.duration_ms, 3)}
        if self.children:
            d["children"] = [c.to_dict() for c in self.children]
        return d


class Trace:
    """Span-tree builder: `with trace.span("plan"): ...`; no-ops when
    disabled so the hot path pays one attribute check."""

    def __init__(self, enabled: bool = False):
        self.enabled = enabled
        self.root = Span("query") if enabled else None
        self._stack = [self.root] if enabled else []

    class _Ctx:
        def __init__(self, trace: "Trace", name: str):
            self.trace = trace
            self.name = name
            self.sp = None

        def __enter__(self):
            if self.trace.enabled:
                self.sp = Span(self.name)
                self.trace._stack[-1].children.append(self.sp)
                self.trace._stack.append(self.sp)
            return self.sp

        def __exit__(self, *exc):
            if self.sp is not None:
                self.sp.close()
                self.trace._stack.pop()
            return False

    def span(self, name: str) -> "Trace._Ctx":
        return Trace._Ctx(self, name)

    def finish(self):
        if self.root is not None:
            self.root.close()
            return self.root.to_dict()
        return None
