"""Process-wide metrics registry (ServerMetrics/BrokerMetrics analog,
pinot-common/.../metrics/ — meters, gauges, timers and histograms keyed by
name).

Re-design: one registry of counters/gauges/timers/histograms with a
snapshot() export instead of yammer/dropwizard plumbing; emitters call
METRICS.counter("queries").inc() on the hot path (dict lookups only).

Thread-safety contract: REST handler threads and concurrent scatter calls
mutate the same metric objects, so every read-modify-write holds that
metric's own lock (a bare `+=` on an attribute is NOT atomic in CPython),
and snapshot() copies the name->metric maps under the registry lock before
reading each metric under its own — a snapshot taken mid-traffic is
internally consistent per metric and never races a concurrent register.

Exposure formats: snapshot() is the JSON surface (/metrics); to_prometheus()
renders the same registry as Prometheus text exposition 0.0.4 for
`GET /metrics?format=prometheus` (histograms as cumulative `_bucket{le=...}`
series the way promhttp would).
"""
from __future__ import annotations

import bisect
import re
import threading
import time
from typing import Any, Dict, List, Optional, Tuple


class Counter:
    __slots__ = ("value", "_lock")

    def __init__(self) -> None:
        self.value = 0
        self._lock = threading.Lock()

    def inc(self, n: int = 1) -> None:
        with self._lock:
            self.value += n


class Gauge:
    __slots__ = ("value", "_lock")

    def __init__(self) -> None:
        self.value = 0.0
        self._lock = threading.Lock()

    def set(self, v: float) -> None:
        # single attribute store: atomic under the GIL, no lock needed
        self.value = float(v)

    def add(self, delta: float) -> None:
        """Locked increment for gauges tracking a live count (in-flight
        scatters, pinned bytes) where += would lose concurrent updates."""
        with self._lock:
            self.value += float(delta)


class Timer:
    """Count + total + max milliseconds (the cheap aggregate slice when a
    full histogram is overkill — latency-critical paths use Histogram)."""

    __slots__ = ("count", "total_ms", "max_ms", "_lock")

    def __init__(self) -> None:
        self.count = 0
        self.total_ms = 0.0
        self.max_ms = 0.0
        self._lock = threading.Lock()

    def update(self, ms: float) -> None:
        with self._lock:
            self.count += 1
            self.total_ms += ms
            if ms > self.max_ms:
                self.max_ms = ms

    @property
    def mean_ms(self) -> float:
        with self._lock:
            return self.total_ms / self.count if self.count else 0.0

    def _snap(self) -> Dict[str, Any]:
        with self._lock:
            mean = self.total_ms / self.count if self.count else 0.0
            return {"count": self.count, "meanMs": mean, "maxMs": self.max_ms}


# log-spaced millisecond bucket upper bounds: 0.1ms .. ~52s, doubling —
# the same scale promhttp's ExponentialBuckets(0.1, 2, 20) would pick for a
# query-latency histogram (sub-ms kernel launches up to deadline-scale tails)
_HIST_BOUNDS_MS: Tuple[float, ...] = tuple(0.1 * (2.0 ** k) for k in range(20))


class Histogram:
    """Fixed log-spaced ms buckets + count/sum/max/min; p50/p95/p99 come from
    a cumulative bucket walk with linear interpolation inside the bucket (the
    HdrHistogram-lite answer — a few percent of bucket width, allocation-free
    on the update path)."""

    __slots__ = ("bounds", "counts", "count", "sum_ms", "max_ms", "min_ms", "_lock")

    def __init__(self, bounds: Tuple[float, ...] = _HIST_BOUNDS_MS) -> None:
        self.bounds = bounds
        self.counts = [0] * (len(bounds) + 1)  # last slot = +Inf overflow
        self.count = 0
        self.sum_ms = 0.0
        self.max_ms = 0.0
        self.min_ms = float("inf")
        self._lock = threading.Lock()

    def update(self, ms: float) -> None:
        i = bisect.bisect_left(self.bounds, ms)
        with self._lock:
            self.counts[i] += 1
            self.count += 1
            self.sum_ms += ms
            if ms > self.max_ms:
                self.max_ms = ms
            if ms < self.min_ms:
                self.min_ms = ms

    def _quantile_locked(self, q: float) -> float:
        """Caller holds self._lock."""
        if not self.count:
            return 0.0
        target = q * self.count
        cum = 0
        for i, c in enumerate(self.counts):
            if not c:
                continue
            prev_cum = cum
            cum += c
            if cum >= target:
                if i >= len(self.bounds):
                    return self.max_ms  # overflow bucket: best bound we have
                lo = self.bounds[i - 1] if i else 0.0
                hi = self.bounds[i]
                frac = (target - prev_cum) / c
                return min(lo + (hi - lo) * frac, self.max_ms)
        return self.max_ms

    def _snap(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "count": self.count,
                "meanMs": self.sum_ms / self.count if self.count else 0.0,
                "maxMs": self.max_ms,
                "minMs": self.min_ms if self.count else 0.0,
                "p50Ms": self._quantile_locked(0.50),
                "p95Ms": self._quantile_locked(0.95),
                "p99Ms": self._quantile_locked(0.99),
            }

    def buckets(self) -> List[Tuple[float, int]]:
        """Cumulative (upper_bound_ms, count<=bound) pairs, +Inf last —
        exactly the Prometheus histogram series shape."""
        with self._lock:
            out: List[Tuple[float, int]] = []
            cum = 0
            for b, c in zip(self.bounds, self.counts):
                cum += c
                out.append((b, cum))
            out.append((float("inf"), self.count))
            return out


class MetricsRegistry:
    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._counters: Dict[str, Counter] = {}
        self._gauges: Dict[str, Gauge] = {}
        self._timers: Dict[str, Timer] = {}
        self._histograms: Dict[str, Histogram] = {}

    def counter(self, name: str) -> Counter:
        c = self._counters.get(name)
        if c is None:
            with self._lock:
                c = self._counters.setdefault(name, Counter())
        return c

    def gauge(self, name: str) -> Gauge:
        g = self._gauges.get(name)
        if g is None:
            with self._lock:
                g = self._gauges.setdefault(name, Gauge())
        return g

    def timer(self, name: str) -> Timer:
        t = self._timers.get(name)
        if t is None:
            with self._lock:
                t = self._timers.setdefault(name, Timer())
        return t

    def histogram(self, name: str) -> Histogram:
        h = self._histograms.get(name)
        if h is None:
            with self._lock:
                h = self._histograms.setdefault(name, Histogram())
        return h

    def _copies(self):
        """Stable name->metric copies: concurrent registration must never
        blow up the snapshot iteration (dict-changed-size)."""
        with self._lock:
            return (
                dict(self._counters),
                dict(self._gauges),
                dict(self._timers),
                dict(self._histograms),
            )

    def snapshot(self) -> Dict[str, Any]:
        counters, gauges, timers, hists = self._copies()
        return {
            "counters": {k: c.value for k, c in counters.items()},
            "gauges": {k: g.value for k, g in gauges.items()},
            "timers": {k: t._snap() for k, t in timers.items()},
            "histograms": {k: h._snap() for k, h in hists.items()},
        }

    def to_prometheus(self, prefix: str = "pinot") -> str:
        """Prometheus text exposition 0.0.4 of the whole registry."""
        counters, gauges, timers, hists = self._copies()
        lines: List[str] = []
        for name, c in sorted(counters.items()):
            full = f"{prefix}_{_prom_name(name)}_total"
            lines.append(f"# TYPE {full} counter")
            lines.append(f"{full} {c.value}")
        for name, g in sorted(gauges.items()):
            full = f"{prefix}_{_prom_name(name)}"
            lines.append(f"# TYPE {full} gauge")
            lines.append(f"{full} {_prom_num(g.value)}")
        for name, t in sorted(timers.items()):
            full = f"{prefix}_{_prom_name(name)}_ms"
            s = t._snap()
            lines.append(f"# TYPE {full} summary")
            lines.append(f"{full}_sum {_prom_num(s['count'] * s['meanMs'])}")
            lines.append(f"{full}_count {s['count']}")
        for name, h in sorted(hists.items()):
            full = f"{prefix}_{_prom_name(name)}_ms"
            lines.append(f"# TYPE {full} histogram")
            for bound, cum in h.buckets():
                le = "+Inf" if bound == float("inf") else f"{bound:g}"
                lines.append(f'{full}_bucket{{le="{le}"}} {cum}')
            with h._lock:
                total, count = h.sum_ms, h.count
            lines.append(f"{full}_sum {_prom_num(total)}")
            lines.append(f"{full}_count {count}")
        return "\n".join(lines) + "\n"

    def reset(self) -> None:
        with self._lock:
            self._counters.clear()
            self._gauges.clear()
            self._timers.clear()
            self._histograms.clear()


def _prom_name(name: str) -> str:
    s = re.sub(r"[^a-zA-Z0-9_:]", "_", name)
    return s if re.match(r"[a-zA-Z_:]", s) else "_" + s


def _prom_num(v: float) -> str:
    return f"{v:g}"


def merge_registry_snapshots(registries: Dict[str, "MetricsRegistry"]) -> Dict[str, Any]:
    """Cluster-level merge of per-source registries, with per-TYPE semantics:

    - counters: SUM (monotone totals add across processes)
    - gauges: LAST (point-in-time levels; the lexicographically last source
      wins, deterministic for tests — a real scrape would use scrape time)
    - timers: count/total SUM, max MAX (the slowest anywhere is the
      cluster's max)
    - histograms: bucket-wise SUM (cumulative bucket counts add exactly)

    Source iteration is sorted by name so the merge is deterministic."""
    counters: Dict[str, int] = {}
    gauges: Dict[str, float] = {}
    timers: Dict[str, Dict[str, float]] = {}
    hist_counts: Dict[str, List[int]] = {}
    hist_meta: Dict[str, Dict[str, float]] = {}
    hist_bounds: Dict[str, Tuple[float, ...]] = {}
    for src in sorted(registries):
        cs, gs, ts, hs = registries[src]._copies()
        for name, c in cs.items():
            counters[name] = counters.get(name, 0) + c.value
        for name, g in gs.items():
            gauges[name] = g.value  # last-wins
        for name, t in ts.items():
            with t._lock:
                count, total, mx = t.count, t.total_ms, t.max_ms
            agg = timers.setdefault(name, {"count": 0, "totalMs": 0.0, "maxMs": 0.0})
            agg["count"] += count
            agg["totalMs"] += total
            agg["maxMs"] = max(agg["maxMs"], mx)
        for name, h in hs.items():
            with h._lock:
                counts, total, count, mx = list(h.counts), h.sum_ms, h.count, h.max_ms
            if name not in hist_counts:
                hist_counts[name] = [0] * len(counts)
                hist_bounds[name] = h.bounds
                hist_meta[name] = {"count": 0, "sumMs": 0.0, "maxMs": 0.0}
            if len(hist_counts[name]) == len(counts):
                hist_counts[name] = [a + b for a, b in zip(hist_counts[name], counts)]
            meta = hist_meta[name]
            meta["count"] += count
            meta["sumMs"] += total
            meta["maxMs"] = max(meta["maxMs"], mx)
    return {
        "counters": counters,
        "gauges": gauges,
        "timers": {
            k: {
                "count": v["count"],
                "meanMs": v["totalMs"] / v["count"] if v["count"] else 0.0,
                "maxMs": v["maxMs"],
            }
            for k, v in timers.items()
        },
        "histograms": {
            k: {"bounds": list(hist_bounds[k]), "counts": hist_counts[k], **hist_meta[k]}
            for k in hist_counts
        },
    }


def federate_prometheus(
    registries: Dict[str, "MetricsRegistry"],
    prefix: str = "pinot",
    label: str = "server",
) -> str:
    """Prometheus text exposition of a fleet of registries: every series
    appears once per source with a `{server="..."}` label, plus a merged
    `{prefix}_cluster_*` aggregate per series using the
    merge_registry_snapshots semantics (counters sum, gauges last, timers
    sum+max, histogram buckets sum).  Per-source histogram buckets are
    elided (series-count discipline) — the labeled `_sum`/`_count` pair plus
    the merged cluster buckets carry the distribution."""
    lines: List[str] = []
    for src in sorted(registries):
        counters, gauges, timers, hists = registries[src]._copies()
        tag = f'{{{label}="{src}"}}'
        for name, c in sorted(counters.items()):
            lines.append(f"{prefix}_{_prom_name(name)}_total{tag} {c.value}")
        for name, g in sorted(gauges.items()):
            lines.append(f"{prefix}_{_prom_name(name)}{tag} {_prom_num(g.value)}")
        for name, t in sorted(timers.items()):
            s = t._snap()
            full = f"{prefix}_{_prom_name(name)}_ms"
            lines.append(f"{full}_sum{tag} {_prom_num(s['count'] * s['meanMs'])}")
            lines.append(f"{full}_count{tag} {s['count']}")
            lines.append(f"{full}_max{tag} {_prom_num(s['maxMs'])}")
        for name, h in sorted(hists.items()):
            s = h._snap()
            full = f"{prefix}_{_prom_name(name)}_ms"
            lines.append(f"{full}_sum{tag} {_prom_num(s['count'] * s['meanMs'])}")
            lines.append(f"{full}_count{tag} {s['count']}")
    merged = merge_registry_snapshots(registries)
    cp = f"{prefix}_cluster"
    for name, v in sorted(merged["counters"].items()):
        full = f"{cp}_{_prom_name(name)}_total"
        lines.append(f"# TYPE {full} counter")
        lines.append(f"{full} {v}")
    for name, v in sorted(merged["gauges"].items()):
        full = f"{cp}_{_prom_name(name)}"
        lines.append(f"# TYPE {full} gauge")
        lines.append(f"{full} {_prom_num(v)}")
    for name, t in sorted(merged["timers"].items()):
        full = f"{cp}_{_prom_name(name)}_ms"
        lines.append(f"# TYPE {full} summary")
        lines.append(f"{full}_sum {_prom_num(t['count'] * t['meanMs'])}")
        lines.append(f"{full}_count {t['count']}")
        lines.append(f"{full}_max {_prom_num(t['maxMs'])}")
    for name, h in sorted(merged["histograms"].items()):
        full = f"{cp}_{_prom_name(name)}_ms"
        lines.append(f"# TYPE {full} histogram")
        cum = 0
        for bound, c in zip(h["bounds"], h["counts"]):
            cum += c
            lines.append(f'{full}_bucket{{le="{bound:g}"}} {cum}')
        lines.append(f'{full}_bucket{{le="+Inf"}} {h["count"]}')
        lines.append(f"{full}_sum {_prom_num(h['sumMs'])}")
        lines.append(f"{full}_count {h['count']}")
    return "\n".join(lines) + "\n"


METRICS = MetricsRegistry()


class Span:
    """One trace span (RequestContext/tracing analog, SURVEY.md 5.1).

    `attrs` carry bounded-cardinality annotations (segment counts, docs
    scanned, scan backend, retry round, breaker state, fault events) that
    ride the span instead of exploding into metric names.  `children` may
    hold Span objects or already-rendered span dicts — a server-built
    subtree grafts into the broker trace as a dict."""

    __slots__ = ("name", "start", "duration_ms", "children", "attrs")

    def __init__(self, name: str, attrs: Optional[Dict[str, Any]] = None):
        self.name = name
        self.start = time.perf_counter()
        self.duration_ms = 0.0
        self.children: List[Any] = []  # Span | dict
        self.attrs: Dict[str, Any] = dict(attrs) if attrs else {}

    def annotate(self, **kw: Any) -> None:
        self.attrs.update(kw)

    def close(self) -> None:
        self.duration_ms = (time.perf_counter() - self.start) * 1000

    def to_dict(self) -> Dict[str, Any]:
        d: Dict[str, Any] = {"name": self.name, "ms": round(self.duration_ms, 3)}
        if self.attrs:
            d["attrs"] = dict(self.attrs)
        if self.children:
            d["children"] = [c if isinstance(c, dict) else c.to_dict() for c in self.children]
        return d


class Trace:
    """Span-tree builder: `with trace.span("plan"): ...`; no-ops when
    disabled so the hot path pays one attribute check.

    Distributed propagation: the broker mints the query id on the root span
    (`query_id=`), each server builds its own Trace (root="server:<name>")
    and ships the finished dict back in ExecutionStats.trace; the broker
    grafts that subtree under its per-server span via `graft()` — one tree
    per query across the whole scatter."""

    def __init__(self, enabled: bool = False, root: str = "query", query_id: Optional[str] = None):
        self.enabled = enabled
        self.root = Span(root) if enabled else None
        if self.root is not None and query_id is not None:
            self.root.attrs["queryId"] = query_id
        self._stack = [self.root] if enabled else []

    class _Ctx:
        def __init__(self, trace: "Trace", name: str, attrs: Optional[Dict[str, Any]] = None):
            self.trace = trace
            self.name = name
            self.attrs = attrs
            self.sp = None

        def __enter__(self):
            if self.trace.enabled:
                self.sp = Span(self.name, self.attrs)
                self.trace._stack[-1].children.append(self.sp)
                self.trace._stack.append(self.sp)
            return self.sp

        def __exit__(self, *exc):
            if self.sp is not None:
                self.sp.close()
                self.trace._stack.pop()
            return False

    def span(self, name: str, **attrs: Any) -> "Trace._Ctx":
        return Trace._Ctx(self, name, attrs or None)

    def annotate(self, **kw: Any) -> None:
        """Attach attrs to the innermost open span (no-op when disabled)."""
        if self.enabled:
            self._stack[-1].annotate(**kw)

    def graft(self, subtree: Optional[Dict[str, Any]]) -> None:
        """Append an already-rendered span dict (a server's finished trace)
        as a child of the innermost open span."""
        if self.enabled and subtree:
            self._stack[-1].children.append(subtree)

    def finish(self):
        if self.root is not None:
            self.root.close()
            return self.root.to_dict()
        return None
