"""Stable, process-independent hashing.

Reference parity: Pinot partitions tables with pluggable partition functions
(Murmur/Modulo/HashCode, pinot-segment-spi partition functions) so that
build-time partition metadata matches broker-side routing across processes.
Python's builtin hash() is seed-randomized for strings — never use it for
anything persisted.
"""
from __future__ import annotations

import hashlib
import math
from typing import Any, Tuple

import numpy as np


def canonical_bytes(value: Any) -> bytes:
    """Canonical byte encoding: numpy scalars and Python literals of the same
    logical value must encode identically (np.int64(2) == 2 == 2.0)."""
    if isinstance(value, np.generic):
        value = value.item()
    if isinstance(value, bool):
        return b"b1" if value else b"b0"
    if isinstance(value, float):
        if math.isfinite(value) and value == int(value):
            value = int(value)
        else:
            return b"f" + repr(value).encode("ascii")
    if isinstance(value, int):
        return b"i" + str(value).encode("ascii")
    if isinstance(value, bytes):
        return b"y" + value
    return b"s" + str(value).encode("utf-8")


def hash2_64(value: Any) -> Tuple[int, int]:
    """Two independent 64-bit hashes from one blake2b digest (C-speed)."""
    d = hashlib.blake2b(canonical_bytes(value), digest_size=16).digest()
    return int.from_bytes(d[:8], "little"), int.from_bytes(d[8:], "little")


def murmur2(data: bytes, seed: int = 0x9747B28C) -> int:
    """Murmur2 32-bit — the Kafka default partitioner hash, which Pinot's
    Murmur partition function mirrors so stream partitions line up with
    segment partition metadata."""
    m = 0x5BD1E995
    mask = 0xFFFFFFFF
    h = (seed ^ len(data)) & mask
    n = len(data) & ~3
    for i in range(0, n, 4):
        k = int.from_bytes(data[i: i + 4], "little")
        k = (k * m) & mask
        k ^= k >> 24
        k = (k * m) & mask
        h = (h * m) & mask
        h ^= k
    rem = data[n:]
    if rem:
        h ^= int.from_bytes(rem.ljust(4, b"\x00")[: len(rem)], "little")
        h = (h * m) & mask
    h ^= h >> 13
    h = (h * m) & mask
    h ^= h >> 15
    return h


def partition_of(value: Any, num_partitions: int) -> int:
    """Stable partition id (Murmur partition function analog)."""
    return (murmur2(canonical_bytes(value)) & 0x7FFFFFFF) % num_partitions
