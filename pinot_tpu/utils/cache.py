"""Bounded caches for the serving path: entry- and bytes-bounded LRU + TTL.

Reference parity: Pinot's broker/server caches (query result cache,
segment-level plan reuse) are all bounded maps with eviction metrics —
never bare dicts.  Re-design: one thread-safe LRU primitive serving two
consumers:

  * plan caches (query/planner.py, parallel/engine.py, mse/engine.py):
    entry-bounded — a compiled plan's footprint lives in XLA, not here, so
    counting entries is the honest bound;
  * the broker result cache (cluster/broker.py): bytes-bounded with TTL +
    version-token invalidation — results are data, so bytes are the bound.

Metrics contract: a named cache exports `{name}.hits` / `{name}.misses` /
`{name}.evictions` counters and `{name}.cacheSize` / `{name}.cacheBytes`
gauges through the process METRICS registry (Prometheus exposition rides
the existing to_prometheus()).  Eviction order is strict LRU on get/put;
TTL expiry is checked lazily on get (monotonic clock — wall-clock steps
must never mass-expire a cache, same W005 contract as deadlines).
"""
from __future__ import annotations

import sys
import threading
import time
import weakref
from collections import OrderedDict
from typing import Any, Callable, Dict, Hashable, Optional, Tuple

from pinot_tpu.utils.metrics import METRICS

# named caches register here (weakly — short-lived test caches vanish with
# their last reference) so the perf observatory (/debug/perf, cli perf) can
# report plan/result-cache occupancy alongside the ledger
_NAMED_CACHES: "weakref.WeakValueDictionary[str, LruCache]" = weakref.WeakValueDictionary()


def named_cache_stats() -> Dict[str, Dict[str, Any]]:
    """entries/bytes per live named cache (compile.sse, compile.dist,
    compile.mse, broker.resultCache, ...) — the /debug/perf cache view."""
    return {name: cache.stats() for name, cache in sorted(_NAMED_CACHES.items())}


def estimate_size(obj: Any, _depth: int = 0) -> int:
    """Cheap recursive byte estimate for cache accounting (NOT exact):
    sys.getsizeof on the spine, one level of recursion into containers,
    sampled for long sequences so a million-row result costs O(1) to
    estimate.  Good to a small factor, which is all an eviction bound
    needs."""
    n = sys.getsizeof(obj, 64)
    if _depth >= 4:
        return n
    if isinstance(obj, dict):
        items = list(obj.items())
        if len(items) > 32:  # sample + extrapolate
            step = len(items) // 32
            sampled = items[::step]
            scale = len(items) / max(1, len(sampled))
            return n + int(scale * sum(
                estimate_size(k, _depth + 1) + estimate_size(v, _depth + 1) for k, v in sampled
            ))
        return n + sum(
            estimate_size(k, _depth + 1) + estimate_size(v, _depth + 1) for k, v in items
        )
    if isinstance(obj, (list, tuple, set, frozenset)):
        seq = list(obj)
        if len(seq) > 32:
            step = len(seq) // 32
            sampled = seq[::step]
            scale = len(seq) / max(1, len(sampled))
            return n + int(scale * sum(estimate_size(x, _depth + 1) for x in sampled))
        return n + sum(estimate_size(x, _depth + 1) for x in seq)
    nbytes = getattr(obj, "nbytes", None)  # numpy / jax arrays
    if isinstance(nbytes, int):
        return n + nbytes
    return n


class LruCache:
    """Thread-safe LRU bounded by entries and/or bytes, with optional TTL.

    `name` wires the hit/miss/eviction counters and size gauges into the
    METRICS registry; anonymous caches skip metrics entirely (zero
    registry churn from short-lived instances in tests)."""

    def __init__(
        self,
        max_entries: Optional[int] = None,
        max_bytes: Optional[int] = None,
        ttl_s: Optional[float] = None,
        name: Optional[str] = None,
        sizeof: Callable[[Any], int] = estimate_size,
        budget=None,
    ) -> None:
        if max_entries is None and max_bytes is None:
            raise ValueError("LruCache needs max_entries and/or max_bytes")
        self.max_entries = max_entries
        self.max_bytes = max_bytes
        self.ttl_s = ttl_s
        self.name = name
        self._sizeof = sizeof
        # optional shared byte ledger (cluster.admission.ResourceBudget):
        # retained bytes charge the SAME budget the admission controller
        # reserves query working sets from, so caches + in-flight queries
        # can never jointly overcommit host memory.  Lock order is always
        # cache lock -> budget lock (the budget never calls back into us).
        self.budget = budget
        self.clock = time.monotonic  # injectable for deterministic TTL tests
        self._lock = threading.Lock()
        # key -> (value, nbytes, inserted_at_monotonic)
        self._entries: "OrderedDict[Hashable, Tuple[Any, int, float]]" = OrderedDict()
        self._bytes = 0
        if name is not None:
            _NAMED_CACHES[name] = self  # latest same-named cache wins

    def _charge(self, nbytes: int) -> bool:
        """Charge the shared budget (True when admitted or no budget)."""
        if self.budget is None or nbytes <= 0:
            return True
        ok = self.budget.try_charge(nbytes)
        if not ok:
            self._count("budgetRejected")
        return ok

    def _uncharge(self, nbytes: int) -> None:
        if self.budget is not None and nbytes > 0:
            self.budget.uncharge(nbytes)

    # -- metrics -----------------------------------------------------------
    def _count(self, event: str, n: int = 1) -> None:
        if self.name is not None:
            METRICS.counter(f"{self.name}.{event}").inc(n)

    def _publish_size_locked(self) -> None:
        if self.name is not None:
            METRICS.gauge(f"{self.name}.cacheSize").set(len(self._entries))
            METRICS.gauge(f"{self.name}.cacheBytes").set(self._bytes)

    # -- core --------------------------------------------------------------
    def get(self, key: Hashable, default: Any = None) -> Any:
        now = self.clock()
        with self._lock:
            entry = self._entries.get(key)
            if entry is not None and self.ttl_s is not None and now - entry[2] > self.ttl_s:
                self._entries.pop(key)
                self._bytes -= entry[1]
                self._uncharge(entry[1])
                self._publish_size_locked()
                entry = None
            if entry is None:
                self._count("misses")
                return default
            self._entries.move_to_end(key)
            self._count("hits")
            return entry[0]

    def put(self, key: Hashable, value: Any, nbytes: Optional[int] = None) -> None:
        track = self.max_bytes is not None or self.budget is not None
        size = self._sizeof(value) if (nbytes is None and track) else (nbytes or 0)
        if self.max_bytes is not None and size > self.max_bytes:
            return  # an entry larger than the whole cache never admits
        evicted = 0
        with self._lock:
            old = self._entries.pop(key, None)
            if old is not None:
                self._bytes -= old[1]
                self._uncharge(old[1])
            # shared-budget admission: evict our own LRU tail to make room
            # before giving up — the cache yields to in-flight queries, the
            # budget never yields to the cache
            admitted = self._charge(size)
            while not admitted and self._entries:
                _k, (_v, sz, _t) = self._entries.popitem(last=False)
                self._bytes -= sz
                self._uncharge(sz)
                evicted += 1
                admitted = self._charge(size)
            if admitted:
                self._entries[key] = (value, size, self.clock())
                self._bytes += size
                while (self.max_entries is not None and len(self._entries) > self.max_entries) or (
                    self.max_bytes is not None and self._bytes > self.max_bytes
                ):
                    _k, (_v, sz, _t) = self._entries.popitem(last=False)
                    self._bytes -= sz
                    self._uncharge(sz)
                    evicted += 1
            self._publish_size_locked()
        if evicted:
            self._count("evictions", evicted)

    def invalidate(self, key: Hashable) -> bool:
        with self._lock:
            entry = self._entries.pop(key, None)
            if entry is not None:
                self._bytes -= entry[1]
                self._uncharge(entry[1])
                self._publish_size_locked()
            return entry is not None

    def invalidate_where(self, pred: Callable[[Hashable], bool]) -> int:
        """Drop every entry whose KEY matches `pred` (version-token
        invalidation: the broker drops a table's results on segment churn
        by matching the table component of the key)."""
        with self._lock:
            doomed = [k for k in self._entries if pred(k)]
            for k in doomed:
                _v, sz, _t = self._entries.pop(k)
                self._bytes -= sz
                self._uncharge(sz)
            self._publish_size_locked()
            return len(doomed)

    def clear(self) -> None:
        with self._lock:
            self._entries.clear()
            self._uncharge(self._bytes)
            self._bytes = 0
            self._publish_size_locked()

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    def __contains__(self, key: Hashable) -> bool:
        with self._lock:
            return key in self._entries

    @property
    def bytes(self) -> int:
        with self._lock:
            return self._bytes

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            return {"entries": len(self._entries), "bytes": self._bytes}
