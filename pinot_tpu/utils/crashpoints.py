"""Named kill-points: deterministic crash injection between commit steps.

Reference parity: Pinot proves its segment-completion and ideal-state commit
protocols with controller/server restart integration tests (e.g.
PinotLLCRealtimeSegmentManager's commit FSM tests kill the committer between
ZK writes).  Here every multi-step commit path (segment seal, checkpoint
write, journal append, snapshot compaction, deep-store upload, rebalance
move) calls `crash_point("<path>.<step>")` between its write/rename/swap
steps.  Production cost is one dict lookup against an empty registry; a test
arms a point via FaultPlan.kill_at (cluster/faults.py) and the Nth hit
raises InjectedCrash — the process-death stand-in.  The test then rebuilds
the component from disk and asserts the atomicity invariant (no lost rows,
no duplicates, identical ideal state) held.

Determinism contract: hits are counted per point name under a lock, so the
same plan against the same call sequence crashes at the same step.
"""
from __future__ import annotations

import threading
from typing import Dict, List, Optional, Tuple


class InjectedCrash(RuntimeError):
    """A kill-point fired: the component 'died' between two commit steps.

    Deliberately a RuntimeError: serving-path handlers treat it like any
    process fault (the broker's failover sees a dead server), while harness
    code catches it explicitly to simulate the restart."""

    def __init__(self, point: str, hit: int):
        super().__init__(f"injected crash at kill-point {point!r} (hit {hit})")
        self.point = point
        self.hit = hit


_lock = threading.Lock()
# point -> hit number (1-based) that fires; None entry means never armed
_armed: Dict[str, int] = {}
# point -> calls seen since arming (only counted while something is armed)
_hits: Dict[str, int] = {}
# every fired crash, for harness assertions: (point, hit)
fired: List[Tuple[str, int]] = []


def arm(point: str, hit: int = 1) -> None:
    """Arm `point` to raise InjectedCrash on its `hit`-th call (1-based)."""
    with _lock:
        _armed[point] = max(1, int(hit))
        _hits.setdefault(point, 0)


def disarm(point: str) -> None:
    with _lock:
        _armed.pop(point, None)
        _hits.pop(point, None)


def reset() -> None:
    """Clear every armed point, hit counter, and the fired ledger."""
    with _lock:
        _armed.clear()
        _hits.clear()
        del fired[:]


def armed() -> Dict[str, int]:
    with _lock:
        return dict(_armed)


def crash_point(point: str) -> None:
    """Commit paths call this between their write/rename/swap steps.

    No-op (one dict lookup) unless a harness armed the point; the armed hit
    raises InjectedCrash and DISARMS the point, so the post-restart re-run
    of the same path commits normally."""
    if not _armed:  # fast path: nothing armed anywhere (production)
        return
    with _lock:
        target: Optional[int] = _armed.get(point)
        if target is None:
            return
        n = _hits[point] = _hits.get(point, 0) + 1
        if n < target:
            return
        _armed.pop(point, None)
        _hits.pop(point, None)
        fired.append((point, n))
    raise InjectedCrash(point, n)
