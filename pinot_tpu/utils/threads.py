"""Threading-primitive injection seam for the serving-tier protocols.

The hand-rolled lock/condition-variable protocols (ResidencyManager,
AdmissionController/ResourceBudget, MicroBatcher, LeaseManager /
CoordinatorHandle, ServerHealth) construct their primitives through THIS
module instead of `threading` directly:

    from pinot_tpu.utils import threads
    ...
    self._lock = threads.Lock()
    self._cv = threads.Condition()

Under the default provider every call delegates 1:1 to the stdlib
(`threading.Lock`, `concurrent.futures.Future`, `time.monotonic`) — zero
behavior change, no monkeypatching, nothing to configure.  The model
checker (analysis/scheduler.py) installs a `DeterministicScheduler`
provider for the duration of one explored schedule, so every primitive
the protocol touches becomes a cooperative yield point and the
interleaving is chosen by a seeded, replayable scheduler instead of the
OS.

`checkpoint()` marks a "real work happens here" point (a device copy, an
fsync window): a no-op in production, a scheduling point under the
checker.  Protocol code may call it where a non-atomic window matters to
the protocol's correctness argument.

The provider is process-global on purpose: a schedule under exploration
owns the whole process (the checker runs protocols in isolation), and
production never changes it.  `use_provider` restores the previous
provider even when the schedule dies mid-flight.
"""
from __future__ import annotations

import threading as _threading
import time as _time
from concurrent.futures import Future as _Future
from contextlib import contextmanager
from typing import Any, Iterator


class RealProvider:
    """The production provider: stdlib primitives, verbatim."""

    name = "threading"

    Lock = staticmethod(_threading.Lock)
    RLock = staticmethod(_threading.RLock)
    Condition = staticmethod(_threading.Condition)
    Event = staticmethod(_threading.Event)
    Thread = staticmethod(_threading.Thread)
    Future = staticmethod(_Future)
    monotonic = staticmethod(_time.monotonic)

    @staticmethod
    def checkpoint() -> None:
        pass


_DEFAULT = RealProvider()
_current: Any = _DEFAULT


def provider() -> Any:
    return _current


def set_provider(p: Any) -> Any:
    """Install a provider; returns the one it replaced."""
    global _current
    prev = _current
    _current = p
    return prev


def reset_provider() -> None:
    global _current
    _current = _DEFAULT


@contextmanager
def use_provider(p: Any) -> Iterator[Any]:
    prev = set_provider(p)
    try:
        yield p
    finally:
        set_provider(prev)


# -- primitive constructors (dispatch at CALL time, not import time) -------

def Lock():
    return _current.Lock()


def RLock():
    return _current.RLock()


def Condition(lock=None):
    if lock is None:
        return _current.Condition()
    return _current.Condition(lock)


def Event():
    return _current.Event()


def Thread(*args, **kwargs):
    return _current.Thread(*args, **kwargs)


def Future():
    return _current.Future()


def monotonic() -> float:
    return _current.monotonic()


def checkpoint() -> None:
    _current.checkpoint()
