"""ctypes loader for the C++ runtime library (native/).

The library builds on demand via the checked-in Makefile (g++, no external
deps); every native entry point has a numpy fallback at its call site, so a
missing toolchain degrades performance, never correctness.
"""
from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional

_NATIVE_DIR = os.path.normpath(os.path.join(os.path.dirname(__file__), "..", "..", "native"))
_LIB_PATH = os.path.join(_NATIVE_DIR, "build", "libpinot_native.so")

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_tried = False


def _build() -> bool:
    try:
        proc = subprocess.run(
            ["make", "-C", _NATIVE_DIR],
            capture_output=True,
            text=True,
            timeout=120,
        )
        return proc.returncode == 0
    except (OSError, subprocess.TimeoutExpired):
        return False


def _bind(lib: ctypes.CDLL) -> ctypes.CDLL:
    i64 = ctypes.c_int64
    p8 = ctypes.POINTER(ctypes.c_uint8)
    p32 = ctypes.POINTER(ctypes.c_uint32)
    pc = ctypes.c_char_p
    lib.rb_max_compressed_size.restype = i64
    lib.rb_max_compressed_size.argtypes = [i64]
    lib.rb_compress.restype = i64
    lib.rb_compress.argtypes = [p32, i64, p8, i64]
    lib.rb_cardinality.restype = i64
    lib.rb_cardinality.argtypes = [p8, i64]
    lib.rb_decompress.restype = i64
    lib.rb_decompress.argtypes = [p8, i64, p32, i64]
    lib.csv_count_rows.restype = i64
    lib.csv_count_rows.argtypes = [pc, i64]
    lib.csv_parse.restype = i64
    lib.csv_parse.argtypes = [
        pc,
        i64,
        ctypes.c_char,
        i64,
        ctypes.POINTER(i64),
        ctypes.POINTER(i64),
        p8,
        i64,
    ]
    return lib


def get_lib() -> Optional[ctypes.CDLL]:
    """The loaded native library, building it on first use; None if the
    toolchain is unavailable."""
    global _lib, _tried
    if _lib is not None or _tried:
        return _lib
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        if not os.path.exists(_LIB_PATH):
            src_newer = False
        else:
            lib_mtime = os.path.getmtime(_LIB_PATH)
            src_newer = any(
                os.path.getmtime(os.path.join(_NATIVE_DIR, f)) > lib_mtime
                for f in ("bitmap.cc", "csv.cc")
                if os.path.exists(os.path.join(_NATIVE_DIR, f))
            )
        if (not os.path.exists(_LIB_PATH) or src_newer) and not _build():
            return None
        try:
            _lib = _bind(ctypes.CDLL(_LIB_PATH))
        except OSError:
            _lib = None
        return _lib


def available() -> bool:
    return get_lib() is not None
