"""Broker-side slow-query log: bounded ring buffer of recent queries.

Reference parity: Pinot's broker query log (BaseSingleStageBrokerRequestHandler
logs requestId/SQL/timing per request, rate-limited) + the druid-style
/debug surface.  Re-design: an in-memory deque the REST layer serves at
`GET /debug/queries` (newest first) and the CLI prints via `slow-queries`;
queries over `slow_ms` additionally keep their full span tree, so the tail
that matters arrives with its own flame graph attached.

Entries are plain dicts (JSON-ready); SQL text is stored verbatim but
NEVER used as a metric/span name (repo_lint W007 guards that class), and
the plan fingerprint is stored as a short digest — full fingerprints embed
literal values.
"""
from __future__ import annotations

import hashlib
import os
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional

from pinot_tpu.utils.metrics import METRICS


def _fp_digest(fingerprint: str) -> str:
    return hashlib.sha1(fingerprint.encode("utf-8", "replace")).hexdigest()[:12]


class SlowQueryLog:
    """Ring buffer of the last `capacity` queries; `snapshot()` is newest
    first.  `slow_ms` gates trace retention (and the slowQueries counter),
    not admission — every query lands in the ring so /debug/queries doubles
    as a recent-query log."""

    def __init__(self, capacity: Optional[int] = None, slow_ms: Optional[float] = None):
        if capacity is None:
            capacity = int(os.environ.get("PINOT_TPU_SLOW_LOG_CAPACITY", "128"))
        if slow_ms is None:
            slow_ms = float(os.environ.get("PINOT_TPU_SLOW_QUERY_MS", "250"))
        self.capacity = max(1, capacity)
        self.slow_ms = slow_ms
        self._entries: deque = deque(maxlen=self.capacity)
        self._lock = threading.Lock()

    def record(
        self,
        sql: str,
        fingerprint: str,
        result=None,
        query_id: Optional[str] = None,
        error: Optional[str] = None,
        shape_fingerprint: Optional[str] = None,
    ) -> Dict[str, Any]:
        """Log one finished (or failed) query; returns the entry dict."""
        stats = getattr(result, "stats", None)
        time_ms = float(stats.time_ms) if stats is not None else 0.0
        entry: Dict[str, Any] = {
            # epoch stamp for display only — never used in elapsed math (W005)
            "timestamp": time.time(),
            "queryId": query_id if query_id is not None else (stats.query_id if stats else None),
            "sql": sql,
            "planFingerprint": _fp_digest(fingerprint),
            # literal-canonical shape digest: every member of a parameterized
            # plan-cache family shares this value (query/shape.py)
            "shapeFingerprint": _fp_digest(shape_fingerprint)
            if shape_fingerprint is not None
            else None,
            # "hit" | "miss" when the broker result cache was consulted
            "resultCache": getattr(stats, "result_cache", None) if stats else None,
            "timeMs": round(time_ms, 3),
            "rows": len(result.rows) if result is not None else 0,
            "numDocsScanned": stats.num_docs_scanned if stats else 0,
            "numSegmentsProcessed": stats.num_segments_processed if stats else 0,
            "partialResult": bool(stats.partial_result) if stats else False,
            "numExceptions": len(stats.exceptions) if stats else 0,
        }
        # kernel cost accounting (utils/perf.py): bytes/flops the compiled
        # scans streamed, the compile cost THIS query paid, and the achieved
        # roofline % — slow queries annotated with whether the device or the
        # compile/dispatch path made them slow
        if stats is not None and getattr(stats, "kernel_bytes", 0):
            from pinot_tpu.utils.perf import roofline_pct

            entry["kernelBytes"] = round(stats.kernel_bytes, 1)
            entry["kernelFlops"] = round(stats.kernel_flops, 1)
            entry["costSource"] = stats.kernel_cost_source
            entry["compileMs"] = round(stats.compile_ms, 3)
            denom_s = (stats.device_ms or time_ms) / 1000.0
            roof = roofline_pct(stats.kernel_bytes, denom_s)
            if roof is not None:
                entry["rooflinePct"] = round(roof, 2)
            if time_ms > 0:
                entry["rowsPerSec"] = round(stats.num_docs_scanned / (time_ms / 1000.0), 1)
        if error is not None:
            entry["error"] = error
        # watchdog kill record: a killed-but-partial query carries its
        # QUERY_KILLED exception entry (query id, reason, server) — surface
        # it top-level so /debug/queries and the CLI show kills at a glance
        if stats is not None:
            for exc in stats.exceptions:
                if isinstance(exc, dict) and exc.get("errorCode") == "QUERY_KILLED":
                    entry["kill"] = exc
                    break
        # tail-tolerance decisions (r15): hedged scatter calls and brownout
        # transitions surface top-level, so /debug/queries and EXPLAIN
        # ANALYZE show WHY a tail query came back fast (or didn't)
        if stats is not None and getattr(stats, "hedged", 0):
            entry["hedge"] = {
                "hedged": stats.hedged,
                "winner": stats.hedge_winner,
                "cancelledMs": round(stats.hedge_cancelled_ms, 3),
            }
        if stats is not None and getattr(stats, "brownout_events", None):
            entry["brownout"] = list(stats.brownout_events)
        if time_ms >= self.slow_ms or error is not None or "kill" in entry:
            METRICS.counter("broker.slowQueries").inc()
            if stats is not None and stats.trace is not None:
                entry["trace"] = stats.trace
        with self._lock:
            self._entries.append(entry)
        return entry

    def snapshot(self, limit: Optional[int] = None) -> List[Dict[str, Any]]:
        with self._lock:
            out = list(self._entries)
        out.reverse()  # newest first
        return out[:limit] if limit else out

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)
