"""Compressed doc-id bitmaps (RoaringBitmap analog) over the native codec.

Used where dense [cardinality, words] bitmap tensors don't scale — the
CompressedInvertedIndex posting lists (indexes/inverted.py) whose total
storage is O(docs), not O(cardinality x docs).  The numpy fallback speaks
the same byte format as native/bitmap.cc (round-trip tested), so segments
compress/decompress identically with or without the toolchain.
"""
from __future__ import annotations

import ctypes
from typing import List

import numpy as np

from pinot_tpu.utils.native import get_lib

_CHUNK = 65536
_ARRAY_MAX = 4096
_BITMAP_BYTES = 8192


def compress(docs: np.ndarray) -> bytes:
    """Sorted distinct doc ids -> compressed container bytes."""
    docs = np.ascontiguousarray(docs, dtype=np.uint32)
    lib = get_lib()
    if lib is not None:
        cap = int(lib.rb_max_compressed_size(len(docs)))
        out = np.empty(cap, dtype=np.uint8)
        n = lib.rb_compress(
            docs.ctypes.data_as(ctypes.POINTER(ctypes.c_uint32)),
            len(docs),
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
            cap,
        )
        if n < 0:
            raise RuntimeError("rb_compress overflow")
        return bytes(out[:n])
    return _compress_py(docs)


def decompress_into_words(buf: bytes, words: np.ndarray) -> int:
    """OR the compressed bitmap into dense u32 words; returns cardinality."""
    lib = get_lib()
    if lib is not None:
        arr = np.frombuffer(buf, dtype=np.uint8)
        n = lib.rb_decompress(
            arr.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
            len(arr),
            words.ctypes.data_as(ctypes.POINTER(ctypes.c_uint32)),
            len(words),
        )
        if n < 0:
            raise ValueError("corrupt compressed bitmap")
        return int(n)
    return _decompress_py(buf, words)


def cardinality(buf: bytes) -> int:
    lib = get_lib()
    if lib is not None:
        arr = np.frombuffer(buf, dtype=np.uint8)
        return int(lib.rb_cardinality(arr.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)), len(arr)))
    return _cardinality_py(buf)


# ---------------------------------------------------------------------------
# numpy fallback, byte-compatible with native/bitmap.cc
# ---------------------------------------------------------------------------
def _compress_py(docs: np.ndarray) -> bytes:
    parts: List[bytes] = []
    keys = docs >> 16
    n_containers = 0
    i = 0
    n = len(docs)
    while i < n:
        key = int(keys[i])
        j = int(np.searchsorted(keys, key, side="right"))
        lows = (docs[i:j] & 0xFFFF).astype(np.uint16)
        count = j - i
        head = np.uint32(key).tobytes() + bytes([0 if count <= _ARRAY_MAX else 1]) + np.uint32(count).tobytes()
        if count <= _ARRAY_MAX:
            parts.append(head + lows.tobytes())
        else:
            bits = np.zeros(_BITMAP_BYTES, dtype=np.uint8)
            np.bitwise_or.at(bits, lows >> 3, (1 << (lows & 7)).astype(np.uint8))
            parts.append(head + bits.tobytes())
        n_containers += 1
        i = j
    return np.uint32(n_containers).tobytes() + b"".join(parts)


def _decompress_py(buf: bytes, words: np.ndarray) -> int:
    mv = memoryview(buf)
    nc = int(np.frombuffer(mv[:4], dtype=np.uint32)[0])
    pos = 4
    total = 0
    for _ in range(nc):
        key = int(np.frombuffer(mv[pos : pos + 4], dtype=np.uint32)[0])
        ctype = mv[pos + 4]
        count = int(np.frombuffer(mv[pos + 5 : pos + 9], dtype=np.uint32)[0])
        pos += 9
        base = key * _CHUNK
        total += count
        if ctype == 0:
            lows = np.frombuffer(mv[pos : pos + count * 2], dtype=np.uint16)
            pos += count * 2
            d = base + lows.astype(np.int64)
            np.bitwise_or.at(words, d >> 5, (np.uint32(1) << (d & 31).astype(np.uint32)))
        else:
            bits = np.frombuffer(mv[pos : pos + _BITMAP_BYTES], dtype=np.uint8)
            pos += _BITMAP_BYTES
            w0 = base >> 5
            src = bits.view(np.uint32)
            copy = max(0, min(_CHUNK // 32, len(words) - w0))
            words[w0 : w0 + copy] |= src[:copy]
            if src[copy:].any():
                raise ValueError("corrupt compressed bitmap: docs past buffer")
    return total


def _cardinality_py(buf: bytes) -> int:
    mv = memoryview(buf)
    nc = int(np.frombuffer(mv[:4], dtype=np.uint32)[0])
    pos = 4
    total = 0
    for _ in range(nc):
        ctype = mv[pos + 4]
        count = int(np.frombuffer(mv[pos + 5 : pos + 9], dtype=np.uint32)[0])
        pos += 9 + (count * 2 if ctype == 0 else _BITMAP_BYTES)
        total += count
    return total
