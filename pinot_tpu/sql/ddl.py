"""SQL DDL: CREATE/DROP TABLE, SHOW TABLES, SHOW CREATE TABLE.

Reference parity: the fork's pinot-sql-ddl module (pinot-sql-ddl/DESIGN.md —
DDL compiled to (Schema, TableConfig) with a round-trip fixed point).

Grammar:
  CREATE TABLE name (
      col TYPE [METRIC | DIMENSION | TIME] [MV] [NULLABLE],
      ...,
      PRIMARY KEY (col, ...)
  ) [WITH (key = 'value', ...)]
  DROP TABLE name
  SHOW TABLES
  SHOW CREATE TABLE name

WITH keys map onto TableConfig: invertedIndexColumns, rangeIndexColumns,
bloomFilterColumns, jsonIndexColumns, textIndexColumns, vectorIndexColumns,
sortedColumn, noDictionaryColumns, timeColumnName, retentionDays,
partitionColumn, numPartitions, streamType, upsertMode, comparisonColumn,
dedup (comma-separated lists where plural).
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Tuple

from pinot_tpu.spi.config import (
    DedupConfig,
    IndexingConfig,
    SegmentsConfig,
    StreamConfig,
    TableConfig,
    UpsertConfig,
)
from pinot_tpu.spi.schema import DataType, FieldRole, FieldSpec, Schema
from pinot_tpu.sql.parser import SqlParseError, _Parser


@dataclass
class DdlStatement:
    kind: str  # create_table | drop_table | show_tables | show_create_table
    table: Optional[str] = None
    schema: Optional[Schema] = None
    config: Optional[TableConfig] = None


_TYPES = {t.value: t for t in DataType}


def is_ddl(sql: str) -> bool:
    head = sql.lstrip().split(None, 1)
    return bool(head) and head[0].lower() in ("create", "drop", "show")


def parse_ddl(sql: str) -> DdlStatement:
    p = _DdlParser(sql)
    return p.parse_ddl()


class _DdlParser(_Parser):
    def parse_ddl(self) -> DdlStatement:
        if self._accept_word("create"):
            self._expect_word("table")
            return self._create_table()
        if self._accept_word("drop"):
            self._expect_word("table")
            return DdlStatement("drop_table", table=self._ident())
        if self._accept_word("show"):
            if self._accept_word("tables"):
                return DdlStatement("show_tables")
            self._expect_word("create")
            self._expect_word("table")
            return DdlStatement("show_create_table", table=self._ident())
        self.fail("expected CREATE / DROP / SHOW")

    # DDL words are plain identifiers to the base lexer
    def _accept_word(self, w: str) -> bool:
        t = self.cur
        if t.kind in ("ident", "kw") and str(t.value).lower() == w:
            self.advance()
            return True
        return False

    def _expect_word(self, w: str) -> None:
        if not self._accept_word(w):
            self.fail(f"expected {w.upper()}")

    def _ident(self) -> str:
        if self.cur.kind not in ("ident",):
            self.fail("expected identifier")
        return self.advance().value

    def _create_table(self) -> DdlStatement:
        name = self._ident()
        self.expect_op("(")
        fields: List[FieldSpec] = []
        pks: List[str] = []
        while True:
            if self._accept_word("primary"):
                self._expect_word("key")
                self.expect_op("(")
                pks.append(self._ident())
                while self.accept_op(","):
                    pks.append(self._ident())
                self.expect_op(")")
            else:
                fields.append(self._column_def())
            if not self.accept_op(","):
                break
        self.expect_op(")")
        props: Dict[str, str] = {}
        if self._accept_word("with"):
            self.expect_op("(")
            while True:
                key = str(self.advance().value)
                self.expect_op("=")
                props[key] = str(self.literal_value())
                if not self.accept_op(","):
                    break
            self.expect_op(")")
        self.accept_op(";")
        schema = Schema(name=name, fields=fields, primary_key_columns=pks)
        config = _config_from_props(name, props)
        return DdlStatement("create_table", table=name, schema=schema, config=config)

    def _column_def(self) -> FieldSpec:
        col = self._ident()
        tname = str(self.advance().value).upper()
        if tname not in _TYPES:
            self.fail(f"unknown type {tname} (have {sorted(_TYPES)})")
        dt = _TYPES[tname]
        role = FieldRole.DATE_TIME if dt is DataType.TIMESTAMP else FieldRole.DIMENSION
        single_value = True
        nullable = False
        while True:
            if self._accept_word("metric"):
                role = FieldRole.METRIC
            elif self._accept_word("dimension"):
                role = FieldRole.DIMENSION
            elif self._accept_word("time"):
                role = FieldRole.DATE_TIME
            elif self._accept_word("mv"):
                single_value = False
            elif self._accept_word("nullable"):
                nullable = True
            else:
                break
        return FieldSpec(col, dt, role=role, single_value=single_value, nullable=nullable)


def _split(v: str) -> List[str]:
    return [s.strip() for s in v.split(",") if s.strip()]


def _config_from_props(name: str, props: Dict[str, str]) -> TableConfig:
    idx = IndexingConfig(
        inverted_index_columns=_split(props.get("invertedIndexColumns", "")),
        range_index_columns=_split(props.get("rangeIndexColumns", "")),
        bloom_filter_columns=_split(props.get("bloomFilterColumns", "")),
        json_index_columns=_split(props.get("jsonIndexColumns", "")),
        text_index_columns=_split(props.get("textIndexColumns", "")),
        vector_index_columns=_split(props.get("vectorIndexColumns", "")),
        no_dictionary_columns=_split(props.get("noDictionaryColumns", "")),
        sorted_column=props.get("sortedColumn"),
    )
    seg = SegmentsConfig(
        time_column=props.get("timeColumnName"),
        retention_time_value=int(props["retentionDays"]) if "retentionDays" in props else None,
    )
    upsert = None
    if props.get("upsertMode", "").upper() in ("FULL", "PARTIAL"):
        upsert = UpsertConfig(mode=props["upsertMode"].upper(), comparison_column=props.get("comparisonColumn"))
    dedup = DedupConfig(enabled=True) if str(props.get("dedup", "")).lower() in ("true", "1") else None
    stream = None
    if "streamType" in props:
        stream = StreamConfig(
            stream_type=props["streamType"],
            topic=props.get("topic", ""),
            max_rows_per_segment=int(props.get("maxRowsPerSegment", 1 << 20)),
        )
    return TableConfig(
        name=name,
        indexing=idx,
        segments=seg,
        upsert=upsert,
        dedup=dedup,
        stream=stream,
        partition_column=props.get("partitionColumn"),
        num_partitions=int(props.get("numPartitions", 0)),
    )


def show_create_table(schema: Schema, config: TableConfig) -> str:
    """(Schema, TableConfig) -> CREATE TABLE text (the round-trip fixed
    point: parse_ddl(show_create_table(s, c)) == (s, c))."""
    cols = []
    for f in schema.fields:
        parts = [f.name, f.data_type.value]
        if f.role is FieldRole.METRIC:
            parts.append("METRIC")
        elif f.role is FieldRole.DATE_TIME and f.data_type is not DataType.TIMESTAMP:
            parts.append("TIME")
        if not f.single_value:
            parts.append("MV")
        if f.nullable:
            parts.append("NULLABLE")
        cols.append("  " + " ".join(parts))
    if schema.primary_key_columns:
        cols.append("  PRIMARY KEY (" + ", ".join(schema.primary_key_columns) + ")")
    props: List[Tuple[str, Any]] = []
    idx = config.indexing
    for key, val in (
        ("invertedIndexColumns", ",".join(idx.inverted_index_columns)),
        ("rangeIndexColumns", ",".join(idx.range_index_columns)),
        ("bloomFilterColumns", ",".join(idx.bloom_filter_columns)),
        ("jsonIndexColumns", ",".join(idx.json_index_columns)),
        ("textIndexColumns", ",".join(idx.text_index_columns)),
        ("vectorIndexColumns", ",".join(idx.vector_index_columns)),
        ("noDictionaryColumns", ",".join(idx.no_dictionary_columns)),
        ("sortedColumn", idx.sorted_column or ""),
        ("timeColumnName", config.segments.time_column or ""),
        (
            "retentionDays",
            str(config.segments.retention_time_value) if config.segments.retention_time_value else "",
        ),
        ("partitionColumn", config.partition_column or ""),
        ("numPartitions", str(config.num_partitions) if config.num_partitions else ""),
        ("upsertMode", config.upsert.mode if config.upsert else ""),
        ("comparisonColumn", config.upsert.comparison_column or "" if config.upsert else ""),
        ("dedup", "true" if config.dedup and config.dedup.enabled else ""),
        ("streamType", config.stream.stream_type if config.stream else ""),
    ):
        if val:
            props.append((key, val))
    out = f"CREATE TABLE {schema.name} (\n" + ",\n".join(cols) + "\n)"
    if props:
        out += " WITH (\n" + ",\n".join(f"  {k} = '{v}'" for k, v in props) + "\n)"
    return out
