"""SQL front door: text -> QueryContext IR.

Reference parity: CalciteSqlParser (pinot-common/.../sql/parsers/
CalciteSqlParser.java) compiling SQL text into the Thrift PinotQuery IR, plus
the `SET key=value;` query-option prelude (QueryOptionsUtils analog,
pinot-common/.../common/utils/config/QueryOptionsUtils.java).

Re-design: no Calcite/sqlglot dependency — a small hand-rolled lexer and
recursive-descent parser for the Pinot SQL surface (SELECT / WHERE boolean
algebra / GROUP BY / HAVING / ORDER BY / LIMIT-OFFSET / query options).
The grammar targets QueryContext directly; there is no intermediate AST to
keep the planner's input canonical (predicates normalised to EQ/IN/RANGE
exactly like Pinot's predicate contexts).
"""
from __future__ import annotations

import dataclasses
import re
from typing import Any, Dict, List, Optional, Tuple, Union

from pinot_tpu.query.functions import is_agg_function
from pinot_tpu.query.ir import (
    AggregationSpec,
    Expr,
    ExprKind,
    FilterNode,
    FilterOp,
    JoinClause,
    OrderByExpr,
    Predicate,
    PredicateType,
    QueryContext,
    Subquery,
    WindowSpec,
)


def _substitute_alias_expr(e: Expr, mapping: Dict[str, Expr]) -> Expr:
    """Replace bare-column references to select aliases with the aliased
    expression (Calcite resolves ORDER BY/HAVING aliases the same way).

    Does NOT descend into aggregation calls: columns inside SUM(v) resolve
    against the table even when an alias shadows the name (MySQL/Calcite
    resolution — otherwise `SELECT year AS v, SUM(v) ... HAVING SUM(v)>k`
    silently becomes SUM(year))."""
    if e.is_column and e.op in mapping:
        return mapping[e.op]
    if e.kind is ExprKind.CALL and not is_agg_function(e.op):
        new_args = tuple(_substitute_alias_expr(a, mapping) for a in e.args)
        if new_args != e.args:
            return Expr(ExprKind.CALL, op=e.op, value=e.value, args=new_args)
    return e


def _substitute_alias_filter(node: FilterNode, mapping: Dict[str, Expr]) -> FilterNode:
    if node.op is FilterOp.PRED:
        p = node.predicate
        new_lhs = _substitute_alias_expr(p.lhs, mapping)
        if new_lhs is not p.lhs:
            return FilterNode.pred(dataclasses.replace(p, lhs=new_lhs))
        return node
    return FilterNode(
        node.op,
        children=tuple(_substitute_alias_filter(c, mapping) for c in node.children),
        predicate=node.predicate,
    )


class SqlParseError(ValueError):
    pass


def _contains_agg(e: Expr) -> bool:
    if not isinstance(e, Expr) or e.kind is not ExprKind.CALL:
        return False
    if is_agg_function(e.op):
        return True
    return any(_contains_agg(a) for a in e.args)


def _filter_to_expr(node: FilterNode) -> Expr:
    """CASE condition -> boolean expression ops (__and/__or/__not/__eq/...)
    the transform layer evaluates on device."""
    if node.op is FilterOp.AND:
        return Expr.call("__and", *[_filter_to_expr(c) for c in node.children])
    if node.op is FilterOp.OR:
        return Expr.call("__or", *[_filter_to_expr(c) for c in node.children])
    if node.op is FilterOp.NOT:
        return Expr.call("__not", _filter_to_expr(node.children[0]))
    p = node.predicate
    if p.ptype is PredicateType.EQ:
        return Expr.call("__eq", p.lhs, Expr.lit(p.values[0]))
    if p.ptype is PredicateType.NEQ:
        return Expr.call("__not", Expr.call("__eq", p.lhs, Expr.lit(p.values[0])))
    if p.ptype is PredicateType.IN:
        return Expr.call("__in", p.lhs, *[Expr.lit(v) for v in p.values])
    if p.ptype is PredicateType.NOT_IN:
        return Expr.call("__not", Expr.call("__in", p.lhs, *[Expr.lit(v) for v in p.values]))
    if p.ptype is PredicateType.RANGE:
        parts = []
        if p.lower is not None:
            parts.append(Expr.call("__ge" if p.lower_inclusive else "__gt", p.lhs, Expr.lit(p.lower)))
        if p.upper is not None:
            parts.append(Expr.call("__le" if p.upper_inclusive else "__lt", p.lhs, Expr.lit(p.upper)))
        if len(parts) == 1:
            return parts[0]
        return Expr.call("__and", *parts)
    if p.ptype is PredicateType.IS_NULL:
        return Expr.call("__isnull", p.lhs)
    if p.ptype is PredicateType.IS_NOT_NULL:
        return Expr.call("__not", Expr.call("__isnull", p.lhs))
    raise SqlParseError(f"unsupported predicate {p.ptype.value} inside a CASE condition")


# ---------------------------------------------------------------------------
# Lexer
# ---------------------------------------------------------------------------
_TOKEN_RE = re.compile(
    r"""
    (?P<ws>\s+)
  | (?P<comment>--[^\n]*)
  | (?P<number>\d+\.\d*(?:[eE][+-]?\d+)?|\.\d+(?:[eE][+-]?\d+)?|\d+(?:[eE][+-]?\d+)?)
  | (?P<string>'(?:[^']|'')*')
  | (?P<qident>"(?:[^"]|"")*")
  | (?P<ident>[A-Za-z_][A-Za-z0-9_$]*)
  | (?P<op><>|!=|>=|<=|=|<|>|\+|-|\*|/|%|\(|\)|,|;|\.)
    """,
    re.VERBOSE,
)

KEYWORDS = {
    "select", "from", "where", "group", "by", "having", "order", "limit",
    "offset", "and", "or", "not", "in", "between", "like", "is", "null",
    "as", "asc", "desc", "nulls", "first", "last", "set", "distinct",
    "true", "false", "filter", "option",
    "join", "on", "inner", "left", "right", "full", "cross", "outer",
    "over", "partition", "union", "intersect", "except", "all",
    # NOTE: explain/plan/for are intentionally NOT keywords — they are
    # matched as words only in the EXPLAIN PLAN FOR prefix so columns named
    # `plan` keep working
}


class Token:
    __slots__ = ("kind", "value", "pos")

    def __init__(self, kind: str, value: Any, pos: int):
        self.kind = kind  # "number" | "string" | "ident" | "kw" | "op" | "eof"
        self.value = value
        self.pos = pos

    def __repr__(self):
        return f"Token({self.kind},{self.value!r})"


def tokenize(sql: str) -> List[Token]:
    out: List[Token] = []
    i = 0
    n = len(sql)
    while i < n:
        m = _TOKEN_RE.match(sql, i)
        if not m:
            raise SqlParseError(f"unexpected character {sql[i]!r} at position {i}")
        i = m.end()
        kind = m.lastgroup
        text = m.group()
        if kind in ("ws", "comment"):
            continue
        if kind == "number":
            if "." in text or "e" in text or "E" in text:
                out.append(Token("number", float(text), m.start()))
            else:
                out.append(Token("number", int(text), m.start()))
        elif kind == "string":
            out.append(Token("string", text[1:-1].replace("''", "'"), m.start()))
        elif kind == "qident":
            out.append(Token("ident", text[1:-1].replace('""', '"'), m.start()))
        elif kind == "ident":
            low = text.lower()
            if low in KEYWORDS:
                out.append(Token("kw", low, m.start()))
            else:
                out.append(Token("ident", text, m.start()))
        else:
            out.append(Token("op", text, m.start()))
    out.append(Token("eof", None, n))
    return out


# ---------------------------------------------------------------------------
# Parser
# ---------------------------------------------------------------------------
class _Parser:
    def __init__(self, sql: str):
        self.sql = sql
        self.toks = tokenize(sql)
        self.i = 0
        self._gapfill = None  # GapfillSpec captured by select_statement

    # -- token helpers ---------------------------------------------------
    @property
    def cur(self) -> Token:
        return self.toks[self.i]

    def advance(self) -> Token:
        t = self.toks[self.i]
        self.i += 1
        return t

    def at_kw(self, *kws: str) -> bool:
        return self.cur.kind == "kw" and self.cur.value in kws

    def at_op(self, *ops: str) -> bool:
        return self.cur.kind == "op" and self.cur.value in ops

    def accept_kw(self, *kws: str) -> bool:
        if self.at_kw(*kws):
            self.advance()
            return True
        return False

    def accept_op(self, *ops: str) -> bool:
        if self.at_op(*ops):
            self.advance()
            return True
        return False

    def expect_kw(self, kw: str) -> None:
        if not self.accept_kw(kw):
            self.fail(f"expected {kw.upper()}")

    def expect_op(self, op: str) -> None:
        if not self.accept_op(op):
            self.fail(f"expected {op!r}")

    def fail(self, msg: str):
        t = self.cur
        raise SqlParseError(f"{msg} at position {t.pos} (near {t.value!r}) in: {self.sql!r}")

    # -- entry -----------------------------------------------------------
    def parse(self) -> QueryContext:
        options = {}
        # EXPLAIN PLAN FOR SELECT ... (Pinot explain syntax) or
        # EXPLAIN ANALYZE SELECT ... (execute with tracing forced, join the
        # operator tree with measured ms/rows); matched as words, not
        # keywords, so `plan`/`for`/`analyze` stay valid identifiers
        if self.cur.kind == "ident" and str(self.cur.value).lower() == "explain":
            self.advance()
            if self.cur.kind in ("ident", "kw") and str(self.cur.value).lower() == "analyze":
                self.advance()
                options["__analyze__"] = True
                options["trace"] = True
            else:
                for w in ("plan", "for"):
                    if not (self.cur.kind in ("ident", "kw") and str(self.cur.value).lower() == w):
                        self.fail("expected PLAN FOR or ANALYZE after EXPLAIN")
                    self.advance()
                options["__explain__"] = True
        # Pinot option prelude: SET key = value; ... SELECT ...
        while self.at_kw("set"):
            self.advance()
            if self.cur.kind not in ("ident", "kw"):
                self.fail("expected option name after SET")
            name = self.advance().value
            self.expect_op("=")
            options[str(name)] = self.literal_value()
            self.expect_op(";")
        ctx = self.select_statement(options)
        # set operations: INTERSECT binds tighter than UNION/EXCEPT (SQL
        # standard); `a UNION b INTERSECT c` = a UNION (b INTERSECT c).
        # Tight ops fold into the PRECEDING term's own set_ops; loose ops
        # chain left-associatively at the top level.
        last_term = ctx
        while self.at_kw("union", "intersect", "except"):
            op = self.advance().value
            all_flag = self.accept_kw("all")
            if all_flag and op != "union":
                self.fail(f"{op.upper()} ALL is not supported")
            rhs = self.select_statement(dict(options))
            if op == "intersect" and last_term is not ctx:
                last_term.set_ops.append((op, all_flag, rhs))
            else:
                ctx.set_ops.append((op, all_flag, rhs))
                last_term = rhs
        self.accept_op(";")
        if self.cur.kind != "eof":
            self.fail("unexpected trailing input")
        return ctx

    def select_statement(self, options) -> QueryContext:
        self.expect_kw("select")
        distinct = self.accept_kw("distinct")
        select_list: List[Union[Expr, AggregationSpec]] = []
        aliases: List[Optional[str]] = []
        self._gapfill = None
        while True:
            item, alias = self.select_item()
            select_list.append(item)
            aliases.append(alias)
            if not self.accept_op(","):
                break
        # capture before FROM/WHERE: a subquery's select_statement resets
        # the parser-level slot
        gapfill = self._gapfill
        self._gapfill = None
        self.expect_kw("from")
        if self.cur.kind not in ("ident",):
            self.fail("expected table name")
        table = self.advance().value
        table_alias = self.table_alias()
        joins = self.join_clauses()

        where = None
        if self.accept_kw("where"):
            where = self.boolean_expr()
        group_by: List[Expr] = []
        if self.accept_kw("group"):
            self.expect_kw("by")
            while True:
                group_by.append(self.expr())
                if not self.accept_op(","):
                    break
        having = None
        if self.accept_kw("having"):
            having = self.boolean_expr()
        order_by: List[OrderByExpr] = []
        if self.accept_kw("order"):
            self.expect_kw("by")
            while True:
                # Plain expression parse: an aggregation call like SUM(v)
                # stays an Expr.call — reduce resolves its fingerprint against
                # the aggregation results (env.setdefault in _reduce_groupby).
                e = self.expr()
                asc = True
                if self.accept_kw("desc"):
                    asc = False
                else:
                    self.accept_kw("asc")
                nulls_last = True
                if self.accept_kw("nulls"):
                    if self.accept_kw("first"):
                        nulls_last = False
                    else:
                        self.expect_kw("last")
                order_by.append(OrderByExpr(e, ascending=asc, nulls_last=nulls_last))
                if not self.accept_op(","):
                    break
        limit = 10  # Pinot's default LIMIT 10
        offset = 0
        if self.at_kw("limit"):
            # semi-join subquery resolution distinguishes an explicit LIMIT
            # from the cosmetic default (engine.resolve_subqueries)
            options["__hasExplicitLimit__"] = True
        if self.accept_kw("limit"):
            limit = self.int_literal()
            if self.accept_op(","):
                # MySQL style LIMIT offset, count
                offset = limit
                limit = self.int_literal()
            elif self.accept_kw("offset"):
                offset = self.int_literal()
        # trailing OPTION(key=value, ...) — legacy Pinot option syntax
        if self.accept_kw("option"):
            self.expect_op("(")
            while True:
                if self.cur.kind not in ("ident", "kw"):
                    self.fail("expected option name")
                name = self.advance().value
                self.expect_op("=")
                options[str(name)] = self.literal_value()
                if not self.accept_op(","):
                    break
            self.expect_op(")")

        # Resolve select aliases referenced in ORDER BY / HAVING.  Plain
        # expressions substitute in-place (so `SELECT ts AS t ... ORDER BY t`
        # plans on the real column); aggregation aliases stay as bare columns
        # — reduce registers alias -> final array in its env, and the planner
        # skips them in _needed_columns.  Alias wins over a same-named
        # physical column only when the physical column doesn't exist
        # (checked planner-side; here substitution is unconditional for
        # expression aliases, matching MySQL/Calcite alias-first resolution).
        expr_aliases: Dict[str, Expr] = {}
        for item, alias in zip(select_list, aliases):
            if alias and isinstance(item, Expr) and not (item.is_column and item.op == alias):
                expr_aliases[alias] = item
        if expr_aliases:
            order_by = [
                OrderByExpr(_substitute_alias_expr(o.expr, expr_aliases), o.ascending, o.nulls_last)
                for o in order_by
            ]
            if having is not None:
                having = _substitute_alias_filter(having, expr_aliases)

        if distinct:
            # DISTINCT c1, c2 == GROUP BY c1, c2 selecting keys only (Pinot
            # executes DISTINCT via DistinctOperator; group-by is equivalent).
            if any(isinstance(s, AggregationSpec) for s in select_list):
                self.fail("SELECT DISTINCT with aggregations is not supported")
            group_by = [s for s in select_list if isinstance(s, Expr)]
            # DISTINCT defaults to LIMIT 10 like Pinot

        # Aggregations referenced by ORDER BY/HAVING/select EXPRESSIONS but
        # not selected directly are computed as hidden extras (Pinot permits
        # ORDER BY SUM(v) and post-aggregation arithmetic like
        # SELECT SUM(a)/COUNT(*)); reduce resolves their fingerprints and
        # evaluates the surrounding arithmetic host-side over final arrays.
        extra_aggs: List[AggregationSpec] = []
        if group_by or any(
            isinstance(s, Expr) and _contains_agg(s) for s in select_list
        ):
            selected_fps = {
                s.fingerprint() for s in select_list if isinstance(s, AggregationSpec)
            }

            def _maybe_extra(e: Expr) -> None:
                if (
                    isinstance(e, Expr)
                    and e.kind is ExprKind.CALL
                    and is_agg_function(e.op)
                ):
                    spec = self._call_to_agg(e)
                    if spec.fingerprint() not in selected_fps and not any(
                        spec.fingerprint() == x.fingerprint() for x in extra_aggs
                    ):
                        extra_aggs.append(spec)
                    return
                if isinstance(e, Expr):
                    for a in e.args:
                        _maybe_extra(a)

            for s in select_list:
                if isinstance(s, Expr) and s.kind is ExprKind.CALL:
                    _maybe_extra(s)
            for o in order_by:
                _maybe_extra(o.expr)
            if having is not None:
                for pred in having.predicates():
                    _maybe_extra(pred.lhs)

        # Single-table queries: resolve alias.column qualifiers here — the
        # SSE engines know nothing about aliases (only the MSE resolver
        # strips qualifiers, and it only runs for join queries).
        if not joins:
            from pinot_tpu.query.ir import map_expr_columns, map_filter_columns

            known = {table}
            if table_alias:
                known.add(table_alias)

            def strip_q(e: Expr) -> Expr:
                if "." in e.op:
                    q, c = e.op.split(".", 1)
                    if q not in known:
                        raise SqlParseError(
                            f"unknown table alias {q!r} in {e.op!r} "
                            f"(FROM {table}{' ' + table_alias if table_alias else ''})"
                        )
                    return Expr.col(c)
                return e

            def strip_agg(s: AggregationSpec) -> AggregationSpec:
                return dataclasses.replace(
                    s,
                    expr=map_expr_columns(s.expr, strip_q) if s.expr is not None else None,
                    filter=map_filter_columns(s.filter, strip_q),
                )

            def strip_item(s):
                if isinstance(s, AggregationSpec):
                    return strip_agg(s)
                if isinstance(s, WindowSpec):
                    return dataclasses.replace(
                        s,
                        expr=map_expr_columns(s.expr, strip_q) if s.expr is not None else None,
                        partition_by=tuple(map_expr_columns(p, strip_q) for p in s.partition_by),
                        order_by=tuple(
                            OrderByExpr(map_expr_columns(o.expr, strip_q), o.ascending, o.nulls_last)
                            for o in s.order_by
                        ),
                    )
                return map_expr_columns(s, strip_q)

            select_list = [strip_item(s) for s in select_list]
            group_by = [map_expr_columns(g, strip_q) for g in group_by]
            where = map_filter_columns(where, strip_q)
            having = map_filter_columns(having, strip_q)
            order_by = [
                OrderByExpr(map_expr_columns(o.expr, strip_q), o.ascending, o.nulls_last)
                for o in order_by
            ]
            extra_aggs = [strip_agg(s) for s in extra_aggs]
            if gapfill is not None:
                gapfill = dataclasses.replace(
                    gapfill,
                    time_expr=map_expr_columns(gapfill.time_expr, strip_q),
                    fills=tuple((map_expr_columns(t, strip_q), m) for t, m in gapfill.fills),
                    series=tuple(map_expr_columns(s, strip_q) for s in gapfill.series),
                )

        return QueryContext(
            table=table,
            select_list=select_list,
            select_aliases=aliases,
            table_alias=table_alias,
            joins=joins,
            filter=where,
            group_by=group_by,
            having=having,
            order_by=order_by,
            limit=limit,
            offset=offset,
            options=options,
            extra_aggregations=extra_aggs,
            gapfill=gapfill,
        )

    # -- FROM clause: aliases + joins -----------------------------------
    def table_alias(self) -> Optional[str]:
        if self.accept_kw("as"):
            if self.cur.kind != "ident":
                self.fail("expected table alias after AS")
            return self.advance().value
        if self.cur.kind == "ident":
            return self.advance().value
        return None

    def join_clauses(self) -> List[JoinClause]:
        joins: List[JoinClause] = []
        while self.at_kw("join", "inner", "left", "right", "full", "cross"):
            jt = "inner"
            if self.accept_kw("inner"):
                pass
            elif self.accept_kw("left"):
                self.accept_kw("outer")
                jt = "left"
            elif self.at_kw("right", "full", "cross"):
                self.fail(f"{self.cur.value.upper()} JOIN is not supported (INNER/LEFT only)")
            self.expect_kw("join")
            if self.cur.kind != "ident":
                self.fail("expected table name after JOIN")
            tbl = self.advance().value
            alias = self.table_alias()
            self.expect_kw("on")
            lhs = self.expr()
            self.expect_op("=")
            rhs = self.expr()
            if not (lhs.is_column and rhs.is_column):
                self.fail("JOIN ON requires column = column (equi-join keys)")
            joins.append(JoinClause(tbl, alias, jt, lhs, rhs))
        return joins

    # -- select items ----------------------------------------------------
    def select_item(self) -> Tuple[Union[Expr, AggregationSpec], Optional[str]]:
        item = self.expr_or_agg()
        alias = None
        if self.accept_kw("as"):
            if self.cur.kind not in ("ident", "string"):
                self.fail("expected alias after AS")
            alias = self.advance().value
        elif self.cur.kind == "ident":
            alias = self.advance().value
        return item, alias

    # Aggregation names the engine knows about but has not implemented yet —
    # parsed specially so the user sees "unsupported aggregation" instead of
    # a misleading selection-expression error.
    _KNOWN_UNIMPLEMENTED_AGGS = frozenset({"distinctcountrawhll", "distinctcountthetasketch"})

    _WINDOW_FNS = frozenset({
        "row_number", "rank", "dense_rank", "ntile",
        "lag", "lead", "first_value", "last_value",
        "sum", "count", "avg", "min", "max", "bool_and", "bool_or",
    })

    def _at_word(self, w: str) -> bool:
        return self.cur.kind in ("ident", "kw") and str(self.cur.value).lower() == w

    def _accept_word(self, w: str) -> bool:
        if self._at_word(w):
            self.advance()
            return True
        return False

    def _expect_word(self, w: str) -> None:
        if not self._accept_word(w):
            self.fail(f"expected {w.upper()} in window frame")

    def _frame_bound(self, is_lower: bool) -> Optional[float]:
        """One frame bound as a signed offset: None = UNBOUNDED, 0 = CURRENT
        ROW, -k = k PRECEDING, +k = k FOLLOWING (WindowFrame.java bounds)."""
        if self._accept_word("unbounded"):
            if is_lower:
                self._expect_word("preceding")
            else:
                self._expect_word("following")
            return None
        if self._accept_word("current"):
            self._expect_word("row")
            return 0
        if self.cur.kind != "number":
            self.fail("expected UNBOUNDED, CURRENT ROW or <n> PRECEDING/FOLLOWING")
        k = self.advance().value
        if self._accept_word("preceding"):
            return -k
        self._expect_word("following")
        return k

    def _window_frame(self) -> Tuple[str, Optional[float], Optional[float]]:
        """[ROWS|RANGE] [BETWEEN <bound> AND <bound> | <bound>]."""
        if self._accept_word("rows"):
            mode = "rows"
        elif self._accept_word("range"):
            mode = "range"
        else:
            return "range_all", None, None
        if self._accept_word("between"):
            lo = self._frame_bound(True)
            self._expect_word("and")
            hi = self._frame_bound(False)
            if lo is not None and hi is not None and lo > hi:
                self.fail("window frame start must not be after frame end")
        else:
            lo = self._frame_bound(True)
            hi = 0  # shorthand: <bound> == BETWEEN <bound> AND CURRENT ROW
            if lo is not None and lo > 0:
                self.fail("shorthand window frame bound must be UNBOUNDED/k PRECEDING or CURRENT ROW")
        if mode == "rows":
            for b in (lo, hi):
                if b is not None and float(b) != int(b):
                    self.fail("ROWS frame bounds must be integers")
            lo = None if lo is None else int(lo)
            hi = None if hi is None else int(hi)
        return mode, lo, hi

    def _gapfill_item(self, e: Expr) -> Expr:
        """Interpret a parsed GAPFILL(...) call: stash the GapfillSpec on the
        parser (select_statement collects it) and return the time expression
        as the select item (the bucket output column)."""
        from pinot_tpu.query.ir import GapfillSpec

        if len(e.args) < 4:
            self.fail("GAPFILL requires (time_expr, start, end, step, ...)")
        time_expr = e.args[0]

        def _int_lit(a: Expr, what: str) -> int:
            if not a.is_literal:
                self.fail(f"GAPFILL {what} must be a literal")
            try:
                return int(a.value)
            except (TypeError, ValueError):
                self.fail(f"GAPFILL {what} must be an integer (got {a.value!r})")

        start = _int_lit(e.args[1], "start")
        end = _int_lit(e.args[2], "end")
        step = _int_lit(e.args[3], "step")
        if step <= 0:
            self.fail("GAPFILL step must be positive")
        fills: List[tuple] = []
        series: List[Expr] = []
        for a in e.args[4:]:
            if not (isinstance(a, Expr) and a.kind.name == "CALL"):
                self.fail(f"unexpected GAPFILL argument {a}")
            if a.op == "fill":
                if len(a.args) != 2 or not a.args[1].is_literal:
                    self.fail("FILL requires (target, 'mode')")
                mode = str(a.args[1].value).upper()
                if mode not in ("FILL_PREVIOUS_VALUE", "FILL_DEFAULT_VALUE"):
                    self.fail(f"unknown FILL mode {mode!r}")
                fills.append((a.args[0], mode))
            elif a.op == "timeserieson":
                series.extend(a.args)
            else:
                self.fail(f"unexpected GAPFILL argument {a.op!r}")
        if self._gapfill is not None:
            self.fail("only one GAPFILL per query")
        self._gapfill = GapfillSpec(
            time_expr, start, end, step, tuple(fills), tuple(series)
        )
        return time_expr

    def expr_or_agg(self) -> Union[Expr, AggregationSpec]:
        """Expression that may be a top-level aggregation call."""
        e = self.expr()
        if isinstance(e, Expr) and e.kind.name == "CALL" and e.op in self._KNOWN_UNIMPLEMENTED_AGGS:
            self.fail(f"aggregation function {e.op!r} is not supported yet")
        if isinstance(e, Expr) and e.kind.name == "CALL" and e.op == "gapfill":
            return self._gapfill_item(e)
        # window function: fn(...) OVER (PARTITION BY ... ORDER BY ...)
        if isinstance(e, Expr) and e.kind.name == "CALL" and self.at_kw("over"):
            if e.op not in self._WINDOW_FNS:
                self.fail(f"{e.op!r} is not a supported window function")
            self.advance()
            self.expect_op("(")
            partition: List[Expr] = []
            worder: List[OrderByExpr] = []
            if self.accept_kw("partition"):
                self.expect_kw("by")
                while True:
                    partition.append(self.expr())
                    if not self.accept_op(","):
                        break
            if self.accept_kw("order"):
                self.expect_kw("by")
                while True:
                    oe = self.expr()
                    asc = True
                    if self.accept_kw("desc"):
                        asc = False
                    else:
                        self.accept_kw("asc")
                    worder.append(OrderByExpr(oe, ascending=asc))
                    if not self.accept_op(","):
                        break
            frame, frame_lo, frame_hi = self._window_frame()
            self.expect_op(")")
            arg = None
            literal_args: Tuple = ()
            if e.op == "ntile":
                # NTILE(n): the single argument is the bucket count literal
                if len(e.args) != 1 or not e.args[0].is_literal:
                    self.fail("NTILE requires one literal bucket count")
                if int(e.args[0].value) < 1:
                    self.fail("NTILE bucket count must be >= 1")
                literal_args = (int(e.args[0].value),)
            elif e.op in ("lag", "lead"):
                # LAG/LEAD(expr [, offset [, default]])
                if not e.args:
                    self.fail(f"{e.op.upper()} requires an argument")
                arg = e.args[0]
                extras = []
                for a in e.args[1:]:
                    if not a.is_literal:
                        self.fail(f"{e.op.upper()} offset/default must be literals")
                    extras.append(a.value)
                if extras:
                    extras[0] = int(extras[0])
                literal_args = tuple(extras)
            elif e.args and not (e.args[0].is_column and e.args[0].op == "*"):
                arg = e.args[0]
            return WindowSpec(
                e.op, arg, tuple(partition), tuple(worder),
                frame, frame_lo, frame_hi, literal_args,
            )
        if isinstance(e, Expr) and e.kind.name == "CALL" and is_agg_function(e.op):
            spec = self._call_to_agg(e)
            # FILTER (WHERE ...) clause — Pinot filtered aggregations
            if self.accept_kw("filter"):
                self.expect_op("(")
                self.expect_kw("where")
                f = self.boolean_expr()
                self.expect_op(")")
                spec = AggregationSpec(spec.function, spec.expr, filter=f, literal_args=spec.literal_args)
            return spec
        return e

    @staticmethod
    def _call_to_agg(e: Expr) -> AggregationSpec:
        args = list(e.args)
        if e.op == "count" and len(args) == 1 and args[0].is_column and args[0].op == "*":
            return AggregationSpec("count", None)
        if e.op.replace("_", "") in ("funnelcount", "funnelcompletecount", "funnelmaxstep"):
            # FUNNELCOUNT(STEPS(c1, c2, ...), CORRELATEBY(col)) -> the
            # correlate column is the (codes) input, the step conditions are
            # extra boolean expressions (FunnelCountAggregationFunction)
            steps = next((a for a in args if not a.is_literal and a.op == "steps"), None)
            corr = next(
                (a for a in args if not a.is_literal and a.op in ("correlateby", "correlatedby", "correlate_by")),
                None,
            )
            if steps is None or corr is None or not steps.args or len(corr.args) != 1:
                raise SqlParseError(
                    f"{e.op.upper()} needs STEPS(cond, ...) and CORRELATEBY(column) arguments"
                )
            # TIMESTAMPBY(col) [, window] selects the ORDERED funnel: steps
            # must occur in timestamp order per correlate key, optionally
            # all within `window` (same units as the timestamp column) of
            # the chain's first step.  The ts expr rides as the LAST extra
            # expr; the window literal flags ordered mode downstream.
            tsby = next(
                (a for a in args if not a.is_literal and a.op in ("timestampby", "timestamp_by")),
                None,
            )
            window = next((a.value for a in args if a.is_literal), None)
            extra = tuple(steps.args)
            lits = ()
            if tsby is not None:
                if len(tsby.args) != 1:
                    raise SqlParseError(f"{e.op.upper()} TIMESTAMPBY takes exactly one column")
                extra = extra + (tsby.args[0],)
                lits = (float(window) if window is not None else float("inf"),)
            elif window is not None:
                raise SqlParseError(
                    f"{e.op.upper()} window argument requires TIMESTAMPBY(column)"
                )
            return AggregationSpec(e.op, corr.args[0], extra_exprs=extra, literal_args=lits)
        expr = args[0] if args else None
        lits = tuple(a.value for a in args[1:] if a.is_literal)
        extra = tuple(a for a in args[1:] if not a.is_literal)
        return AggregationSpec(e.op, expr, literal_args=lits, extra_exprs=extra)

    def _case_expr(self) -> Expr:
        """CASE WHEN cond THEN expr ... [ELSE expr] END -> a `case` CALL
        whose args alternate (condition-as-expr, result): conditions convert
        through _filter_to_expr into boolean expression ops the transform
        layer evaluates on device (CaseTransformFunction analog)."""
        self.advance()  # CASE
        def word(w):
            t = self.cur
            if t.kind in ("ident", "kw") and str(t.value).lower() == w:
                self.advance()
                return True
            return False

        args: List[Expr] = []
        saw_when = False
        while word("when"):
            saw_when = True
            cond = self.boolean_expr()
            args.append(_filter_to_expr(cond))
            if not word("then"):
                self.fail("expected THEN in CASE")
            args.append(self.expr())
        if not saw_when:
            self.fail("expected WHEN in CASE")
        if word("else"):
            args.append(self.expr())
        else:
            args.append(Expr.lit(None))
        if not word("end"):
            self.fail("expected END closing CASE")
        return Expr.call("case", *args)

    # -- boolean (filter) grammar ---------------------------------------
    def boolean_expr(self) -> FilterNode:
        node = self.boolean_term()
        while self.accept_kw("or"):
            rhs = self.boolean_term()
            if node.op.name == "OR":
                node = FilterNode(node.op, children=node.children + (rhs,))
            else:
                node = FilterNode.or_(node, rhs)
        return node

    def boolean_term(self) -> FilterNode:
        node = self.boolean_factor()
        while self.accept_kw("and"):
            rhs = self.boolean_factor()
            if node.op.name == "AND":
                node = FilterNode(node.op, children=node.children + (rhs,))
            else:
                node = FilterNode.and_(node, rhs)
        return node

    def boolean_factor(self) -> FilterNode:
        if self.accept_kw("not"):
            return FilterNode.not_(self.boolean_factor())
        # parenthesized boolean vs parenthesized arithmetic: try boolean
        if self.at_op("("):
            save = self.i
            self.advance()
            try:
                inner = self.boolean_expr()
                self.expect_op(")")
                return inner
            except SqlParseError:
                self.i = save  # fall through to predicate over arithmetic expr
        return self.predicate()

    def predicate(self) -> FilterNode:
        lhs = self.expr()
        # special boolean-function predicates used bare: text_match(col,'x')
        if isinstance(lhs, Expr) and lhs.kind.name == "CALL" and lhs.op in (
            "text_match", "json_match", "regexp_like", "vector_similarity",
        ):
            return self._special_call_predicate(lhs)
        negate = self.accept_kw("not")
        if self.accept_kw("in"):
            self.expect_op("(")
            if self.at_kw("select"):
                # IN (SELECT ...) — semi-join marker resolved by the engine
                sub = self.select_statement({})
                self.expect_op(")")
                pt = PredicateType.NOT_IN if negate else PredicateType.IN
                return FilterNode.pred(Predicate(pt, lhs, values=(Subquery(sub),)))
            vals = [self.literal_value()]
            while self.accept_op(","):
                vals.append(self.literal_value())
            self.expect_op(")")
            pt = PredicateType.NOT_IN if negate else PredicateType.IN
            return FilterNode.pred(Predicate(pt, lhs, values=tuple(vals)))
        if self.accept_kw("between"):
            lo = self.add_expr()
            self.expect_kw("and")
            hi = self.add_expr()
            node = FilterNode.pred(
                Predicate(PredicateType.RANGE, lhs, lower=self._const(lo), upper=self._const(hi))
            )
            return FilterNode.not_(node) if negate else node
        if self.accept_kw("like"):
            pat = self.literal_value()
            node = FilterNode.pred(Predicate(PredicateType.LIKE, lhs, values=(pat,)))
            return FilterNode.not_(node) if negate else node
        if negate:
            self.fail("expected IN/BETWEEN/LIKE after NOT")
        if self.accept_kw("is"):
            neg = self.accept_kw("not")
            self.expect_kw("null")
            pt = PredicateType.IS_NOT_NULL if neg else PredicateType.IS_NULL
            return FilterNode.pred(Predicate(pt, lhs))
        for op, make in (
            ("=", lambda v: Predicate(PredicateType.EQ, lhs, values=(v,))),
            ("!=", lambda v: Predicate(PredicateType.NEQ, lhs, values=(v,))),
            ("<>", lambda v: Predicate(PredicateType.NEQ, lhs, values=(v,))),
            (">=", lambda v: Predicate(PredicateType.RANGE, lhs, lower=v)),
            (">", lambda v: Predicate(PredicateType.RANGE, lhs, lower=v, lower_inclusive=False)),
            ("<=", lambda v: Predicate(PredicateType.RANGE, lhs, upper=v)),
            ("<", lambda v: Predicate(PredicateType.RANGE, lhs, upper=v, upper_inclusive=False)),
        ):
            if self.accept_op(op):
                rhs = self.add_expr()
                return FilterNode.pred(make(self._const(rhs)))
        # bare boolean column: `WHERE flag` == flag = true
        if isinstance(lhs, Expr) and lhs.is_column:
            return FilterNode.pred(Predicate(PredicateType.EQ, lhs, values=(True,)))
        self.fail("expected comparison operator")

    def _special_call_predicate(self, call: Expr) -> FilterNode:
        args = call.args
        if len(args) < 2 or not args[0].is_column:
            self.fail(f"{call.op}(column, pattern...) expected")
        pt = {
            "text_match": PredicateType.TEXT_MATCH,
            "json_match": PredicateType.JSON_MATCH,
            "regexp_like": PredicateType.REGEXP_LIKE,
            "vector_similarity": PredicateType.VECTOR_SIMILARITY,
        }[call.op]
        vals = tuple(a.value if a.is_literal else a for a in args[1:])
        return FilterNode.pred(Predicate(pt, args[0], values=vals))

    @staticmethod
    def _const(e: Expr) -> Any:
        if not e.is_literal:
            raise SqlParseError(f"expected a literal comparison value, got expression {e}")
        return e.value

    # -- arithmetic expression grammar ----------------------------------
    def expr(self) -> Expr:
        return self.add_expr()

    def add_expr(self) -> Expr:
        e = self.mul_expr()
        while self.at_op("+", "-"):
            op = self.advance().value
            rhs = self.mul_expr()
            e = self._fold(Expr.call("plus" if op == "+" else "minus", e, rhs))
        return e

    def mul_expr(self) -> Expr:
        e = self.unary_expr()
        while self.at_op("*", "/", "%"):
            # `*` only means multiply if a term follows (disambiguate COUNT(*))
            op = self.advance().value
            rhs = self.unary_expr()
            name = {"*": "times", "/": "divide", "%": "mod"}[op]
            e = self._fold(Expr.call(name, e, rhs))
        return e

    def unary_expr(self) -> Expr:
        if self.accept_op("-"):
            e = self.unary_expr()
            if e.is_literal:
                return Expr.lit(-e.value)
            return Expr.call("neg", e)
        self.accept_op("+")
        return self.primary()

    @staticmethod
    def _fold(e: Expr) -> Expr:
        """Constant-fold literal arithmetic so `v > 10*2` stays a literal."""
        if e.kind.name == "CALL" and all(a.is_literal for a in e.args):
            import operator

            ops = {
                "plus": operator.add, "minus": operator.sub,
                "times": operator.mul, "mod": operator.mod,
                "divide": operator.truediv,
            }
            fn = ops.get(e.op)
            if fn is not None:
                try:
                    return Expr.lit(fn(*(a.value for a in e.args)))
                except Exception:
                    return e
        return e

    def primary(self) -> Expr:
        t = self.cur
        if t.kind == "number":
            self.advance()
            return Expr.lit(t.value)
        if t.kind == "string":
            self.advance()
            return Expr.lit(t.value)
        if t.kind == "kw" and t.value in ("true", "false"):
            self.advance()
            return Expr.lit(t.value == "true")
        if t.kind == "kw" and t.value == "null":
            self.advance()
            return Expr.lit(None)
        if self.accept_op("("):
            e = self.expr()
            self.expect_op(")")
            return e
        if self.accept_op("*"):
            return Expr.col("*")
        if t.kind == "ident" and str(t.value).lower() == "case":
            return self._case_expr()
        if t.kind == "ident" or (t.kind == "kw" and t.value in ("filter",)):
            name = self.advance().value
            if self.accept_op("("):
                # CAST(expr AS TYPE) special form
                if str(name).lower() == "cast":
                    e = self.expr()
                    self.expect_kw("as")
                    if self.cur.kind not in ("ident", "kw"):
                        self.fail("expected type name in CAST")
                    target = self.advance().value
                    self.expect_op(")")
                    return Expr.call("cast", e, Expr.lit(str(target).upper()))
                # function call
                args: List[Expr] = []
                if self.accept_op("*"):
                    args.append(Expr.col("*"))
                    self.expect_op(")")
                    return Expr.call(name, *args)
                # STEPS(cond, cond, ...) — the funnel family's step
                # conditions are BOOLEAN expressions; convert each through
                # the CASE condition machinery into boolean expression ops
                # (FunnelCountAggregationFunction STEPS syntax)
                if str(name).lower() == "steps":
                    conds: List[Expr] = []
                    if not self.at_op(")"):
                        conds.append(_filter_to_expr(self.boolean_expr()))
                        while self.accept_op(","):
                            conds.append(_filter_to_expr(self.boolean_expr()))
                    self.expect_op(")")
                    return Expr.call("steps", *conds)
                if not self.at_op(")"):
                    # DISTINCT inside agg: count(distinct x) -> distinctcount
                    if self.accept_kw("distinct"):
                        arg = self.expr()
                        self.expect_op(")")
                        if str(name).lower() == "count":
                            return Expr.call("distinctcount", arg)
                        # silently dropping DISTINCT would return wrong
                        # results (SUM(DISTINCT x) != SUM(x))
                        self.fail(f"{name}(DISTINCT ...) is not supported")
                    args.append(self.expr())
                    while self.accept_op(","):
                        args.append(self.expr())
                self.expect_op(")")
                return Expr.call(name, *args)
            # qualified reference: alias.column (resolved by the MSE planner)
            if self.accept_op("."):
                if self.cur.kind not in ("ident", "kw"):
                    self.fail("expected column name after '.'")
                return Expr.col(f"{name}.{self.advance().value}")
            return Expr.col(name)
        self.fail("expected expression")

    # -- literal helpers -------------------------------------------------
    def literal_value(self) -> Any:
        t = self.cur
        if t.kind in ("number", "string"):
            self.advance()
            return t.value
        if t.kind == "kw" and t.value in ("true", "false"):
            self.advance()
            return t.value == "true"
        if t.kind == "kw" and t.value == "null":
            self.advance()
            return None
        if self.accept_op("-"):
            v = self.literal_value()
            return -v
        if t.kind == "ident":
            # bare identifier option values, e.g. SET mode=fast;
            self.advance()
            return t.value
        self.fail("expected literal")

    def int_literal(self) -> int:
        t = self.cur
        if t.kind == "number" and isinstance(t.value, int):
            self.advance()
            return t.value
        self.fail("expected integer literal")


def parse_filter_expression(text: str) -> FilterNode:
    """Parse a standalone boolean expression (theta sub-filter strings,
    DISTINCTCOUNTTHETA(col, 'dim=''a''', ...))."""
    p = _Parser(text)
    node = p.boolean_expr()
    if p.cur.kind != "eof":
        p.fail("unexpected trailing input in filter expression")
    return node


def parse_query(sql: str) -> QueryContext:
    """Parse one SQL statement into a QueryContext (CalciteSqlParser analog)."""
    return _Parser(sql).parse()
