"""Per-segment plan maker + jit kernel compiler — the SSE hot path.

Reference parity: InstancePlanMakerImplV2.makeSegmentPlanNode
(pinot-core/.../core/plan/maker/InstancePlanMakerImplV2.java:347-362) picking
Aggregation/GroupBy/Selection plans per query shape, plus the operator chain
it builds (FilterPlanNode -> DocIdSet -> Projection -> Transform ->
Aggregation/GroupBy operators, SURVEY.md 3.1 hot loop).

Re-design (SURVEY.md section 7 "Query plan = traced function"): instead of an
interpreted operator tree pulling 10k-doc blocks, the whole
filter->project->aggregate chain for one query shape is traced into ONE
jax.jit kernel over whole columns; XLA fuses it. Compiled kernels are cached
by (query fingerprint, segment signature) — the plan-cache analog — so a
table of uniformly-shaped segments compiles once.

Group-by: dictId-packed keys (DictionaryBasedGroupKeyGenerator analog,
.../groupby/DictionaryBasedGroupKeyGenerator.java:68): the composite key is
codes raveled over dimension cardinalities; when the cardinality product fits
numGroupsLimit the result is a DENSE group table filled by segment_sum /
scatter-min-max (result-holder analog). Overflow falls back to a vectorized
host groupby (executor.py) — the IndexedTable-with-trim analog, to be
replaced by a Pallas hash table.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from pinot_tpu import ops
from pinot_tpu.query.filter import FilterCompiler
from pinot_tpu.query.functions import (
    FIELD_COMBINE,
    AggFunction,
    field_identity,
    for_spec,
    get_agg_function,
)
from pinot_tpu.query.ir import AggregationSpec, Expr, QueryContext
from pinot_tpu.query.transform import as_row_array, eval_expr
from pinot_tpu.segment.segment import ImmutableSegment
from pinot_tpu.spi.schema import DataType

MAX_DENSE_RAW_INT_RANGE = 1 << 20  # raw ints join the dense keyspace when (max-min+1) is small


@dataclass
class GroupDim:
    """How one group-by dimension maps into the dense key space.

    kinds:
      dict    - dictionary codes of a column
      rawint  - integer column values shifted by base
      expr    - integer-valued device expression shifted by base (range
                bounded statically by scalar.expr_int_range)
      derived - dict column remapped through a host-computed derived
                dictionary (string functions: code -> remap[code], decode via
                derived_values) — Pinot's expression group-by over strings
    """

    expr: Expr
    name: str
    kind: str  # "dict" | "rawint" | "expr" | "derived"
    cardinality: int
    dictionary: Optional[Any] = None  # Dictionary for kind=dict
    base: int = 0  # min value for kind=rawint/expr
    null_code: int = -1  # code representing SQL NULL (placeholder), -1 if none
    derived_values: Optional[np.ndarray] = None  # kind=derived decode table
    remap: Optional[np.ndarray] = None  # kind=derived code remap (int32)
    # multi-value dimension: rows EXPLODE — each element contributes a row
    # (Pinot's MV group-by semantics); kernels expand [n] -> [n, max_len]
    mv: bool = False

    def decode(self, codes: np.ndarray) -> np.ndarray:
        if self.kind == "dict":
            # null_code may be an extra slot past the dictionary (LEFT JOIN
            # no-match rows, mse/engine.py) — clip before the gather
            card = self.dictionary.cardinality
            vals = self.dictionary.get_values(np.minimum(np.asarray(codes), card - 1))
        elif self.kind == "derived":
            vals = self.derived_values[np.minimum(np.asarray(codes), len(self.derived_values) - 1)]
        else:
            vals = codes.astype(np.int64) + self.base
        if self.null_code >= 0:
            vals = np.asarray(vals, dtype=object)
            vals[np.asarray(codes) == self.null_code] = None
        return vals

    def device_code(self, cols, segment, dtype=None):
        """Traced per-row dimension code (the group-key contribution)."""
        from pinot_tpu.query.transform import eval_expr

        dtype = dtype or jnp.int32
        if self.kind == "dict":
            return cols[self.name]["codes"].astype(dtype)
        if self.kind == "rawint":
            v = cols[self.name]["values"]
            # subtract in storage dtype (np scalar: no x64 promotion)
            return (v - np.asarray(self.base, dtype=v.dtype)).astype(dtype)
        if self.kind == "derived":
            return jnp.asarray(self.remap)[cols[self.name]["codes"].astype(jnp.int32)].astype(dtype)
        v, _ = eval_expr(self.expr, segment, cols)
        return (v.astype(jnp.int64) - np.int64(self.base)).astype(dtype)


def group_strides(group_dims: List["GroupDim"]) -> List[int]:
    """Strides of the packed composite group key (most-significant-first, the
    layout _group_key produces).  Single source of truth for key packing —
    dense decode, sparse host groupby and reduce all unravel through here."""
    strides: List[int] = []
    acc = 1
    for gd in reversed(group_dims):
        strides.append(acc)
        acc *= gd.cardinality
    return list(reversed(strides))


def decode_packed_keys(group_dims: List["GroupDim"], packed: np.ndarray) -> List[np.ndarray]:
    """Packed composite keys -> per-dimension decoded value arrays."""
    packed = np.asarray(packed)
    return [
        gd.decode(((packed // stride) % gd.cardinality).astype(np.int64))
        for gd, stride in zip(group_dims, group_strides(group_dims))
    ]


@dataclass
class SegmentPlan:
    kind: str  # "aggregation" | "groupby_dense" | "groupby_sparse" | "selection"
    fn: Callable  # jitted kernel(cols, params)
    params: Dict[str, Any]
    needed_columns: List[str]
    aggs: List[AggFunction] = field(default_factory=list)
    group_dims: List[GroupDim] = field(default_factory=list)
    num_groups: int = 0
    select_columns: List[str] = field(default_factory=list)
    # selection output items in order (columns AND expressions)
    select_exprs: List[Expr] = field(default_factory=list)
    # (column, index kind) per index-accelerated filter predicate
    index_uses: List[Tuple[str, str]] = field(default_factory=list)
    # kernel cost model (utils/perf.KernelCost), captured lazily at the
    # FIRST launch of this plan and shared through the plan cache: hits
    # copy the cached cost instead of re-lowering (None until captured)
    cost: Optional[Any] = None
    # plan-cache key (shape fp, segment signature, backend) — the stable
    # identity the cross-query batcher keys its vmapped-fn LRU on, so
    # batching never compiles more than once per (shape, batch width)
    cache_key: Optional[Tuple] = None


# jit cache: (query SHAPE fingerprint, segment signature, backend) -> plan.
# Shape-keyed (query/shape.py): literals ride the params pytree, so distinct
# literals of one query shape share a single traced program.  Bounded LRU —
# an unbounded plan cache under shape churn (many distinct query shapes) is
# a slow memory leak; eviction only drops OUR reference, XLA's own
# executable cache keeps the compiled artifact reusable.
_PLAN_CACHE_ENTRIES = 512  # override: PINOT_TPU_PLAN_CACHE_ENTRIES


def _plan_cache_entries() -> int:
    import os

    return int(os.environ.get("PINOT_TPU_PLAN_CACHE_ENTRIES", _PLAN_CACHE_ENTRIES))


from pinot_tpu.utils.cache import LruCache  # noqa: E402  (after np/jax imports)

_PLAN_CACHE: LruCache = LruCache(max_entries=_plan_cache_entries(), name="compile.sse")


def plan_cache_clear() -> None:
    _PLAN_CACHE.clear()


def attach_plan_cache_budget(budget) -> None:
    """Charge the SSE plan cache's byte accounting to a shared host ledger
    (cluster.admission.ResourceBudget) — the broker attaches its admission
    budget here so cached plans + cached results + in-flight working sets
    all bound against ONE budget.  Clears the cache on first attach so every
    resident entry is charged exactly once; idempotent for the same ledger
    (repeat broker constructions must not cold the cache)."""
    if _PLAN_CACHE.budget is budget:
        return
    _PLAN_CACHE.clear()
    _PLAN_CACHE.budget = budget


def plan_cache_size() -> int:
    return len(_PLAN_CACHE)


def _sig_value(v):
    return v.item() if isinstance(v, np.generic) else v


def _segment_signature(
    segment: ImmutableSegment, needed: List[str], sketch_cols: frozenset = frozenset()
) -> Tuple:
    sig = [segment.num_docs, segment.valid_docs is not None]
    for name in sorted(needed):
        c = segment.column(name)
        # MV columns: the padded width is a static kernel shape, and the
        # vector predicate bakes the index dim — both join the key.
        mv_width = None
        if getattr(c, "mv_lengths", None) is not None:
            arr = c.codes if c.codes is not None else c.values
            mv_width = int(arr.shape[1]) if arr is not None and arr.ndim == 2 else None
        # Raw columns include min/max: the kernel bakes rawint group-dim
        # base/cardinality in statically, so they are part of the cache key.
        raw_range = None
        if not c.has_dictionary and c.data_type.is_numeric:
            raw_range = (
                (_sig_value(c.stats.min_value), _sig_value(c.stats.max_value)) if c.stats.num_docs else (0, 0)
            )
        # Sketch-bound columns bake DICTIONARY-DERIVED constants (HLL hash
        # tables, histogram edges) into the compiled kernel as closure
        # constants — the exact dictionary must be part of the cache key or
        # a same-shaped segment silently reuses another segment's tables.
        sketch_extra = None
        if name in sketch_cols:
            sketch_extra = (
                c.dictionary.fingerprint() if c.has_dictionary else None,
                _sig_value(c.stats.min_value),
                _sig_value(c.stats.max_value),
            )
        sig.append(
            (
                name,
                c.cardinality if c.has_dictionary else -1,
                str(c.codes.dtype if c.codes is not None else c.values.dtype),
                # packed lane width: packed and unpacked segments trace
                # different kernels (word inputs vs code inputs)
                getattr(c, "code_bits", None),
                c.nulls is not None,
                raw_range,
                sketch_extra,
                column_limb_sig(c),
                c.stats.is_sorted,
                mv_width,
                tuple(
                    sorted(
                        k
                        for k, by_col in getattr(segment, "indexes", {}).items()
                        if name in by_col
                    )
                ),
            )
        )
    return tuple(sig)


def sketch_bound_columns(ctx: QueryContext) -> frozenset:
    """Columns whose sketch bindings bake per-segment constants into kernels."""
    out = set()
    for spec in ctx.aggregations:
        if spec.expr is not None and spec.expr.is_column and for_spec(spec).needs_binding:
            out.add(spec.expr.op)
    return frozenset(out)


def const_bound_columns(ctx: QueryContext) -> frozenset:
    """Columns whose DICTIONARY VALUES are baked into compiled kernels as
    closure constants: any column under a dictionary-domain function call
    (derived arrays, transform.py) or an expression group-by (derived remap
    / expr ranges).  Their dictionary fingerprint must join the plan-cache
    signature or a same-shaped segment would reuse another segment's
    constants (same hazard as sketch bindings)."""
    from pinot_tpu.query import scalar

    out = set()

    def visit(e: Expr) -> None:
        if e is None:
            return
        if e.kind.name == "CALL":
            if e.op in scalar.DICT_FNS:
                out.update(e.columns())
            for a in e.args:
                visit(a)

    def visit_filter(node) -> None:
        if node is None:
            return
        if node.predicate is not None:
            visit(node.predicate.lhs)
        for ch in node.children:
            visit_filter(ch)

    for g in ctx.group_by:
        if not g.is_column:
            out.update(g.columns())  # expr dims bake ranges/remaps
    for spec in list(ctx.aggregations):
        if spec.expr is not None:
            visit(spec.expr)
        if spec.filter is not None:
            visit_filter(spec.filter)
    visit_filter(ctx.filter)
    return frozenset(out)


def guard_sparse_vector_fields(kind: str, aggs: List[AggFunction]) -> None:
    """Pre-trace check for the sparse group path.  Round 5: vector-field
    sketches (DISTINCTCOUNT/HLL/PERCENTILE/MODE/theta/...) now ride the
    sparse kernel through their own partial_grouped over slot ids
    (sparse_grouped_tables), matching the reference's high-cardinality
    group-by with any aggregation (DefaultGroupByExecutor.java:51 + object
    result holders).  Only genuinely un-groupable forms raise early with a
    pointed message instead of failing mid-trace."""
    if kind != "groupby_sparse":
        return
    from pinot_tpu.query.sketches import DistinctCountValueSetFunction

    for fn in aggs:
        base = getattr(fn, "base", fn)  # MV wrappers delegate
        if isinstance(base, DistinctCountValueSetFunction):
            raise NotImplementedError(
                "exact grouped DISTINCTCOUNT requires a shared dictionary across "
                "segments; these segments' dictionaries differ — use DISTINCTCOUNTHLL"
            )
        if getattr(fn, "subfilter_args", False):
            raise NotImplementedError(
                "theta sub-filter set expressions do not support GROUP BY"
            )


def _all_column_names(segment) -> List[str]:
    """All queryable columns, INCLUDING schema-evolution virtuals the
    segment's own (older) schema does not list."""
    cols = getattr(segment, "columns", None)
    if isinstance(cols, dict):
        return list(cols)
    return segment.schema.column_names


def _needed_columns(ctx: QueryContext, segment: ImmutableSegment) -> List[str]:
    cols: List[str] = []
    if ctx.filter:
        cols.extend(ctx.filter.columns())
    for g in ctx.group_by:
        cols.extend(g.columns())
    from pinot_tpu.query.ir import WindowSpec

    for s in list(ctx.select_list) + list(ctx.extra_aggregations):
        if isinstance(s, AggregationSpec):
            if s.expr is not None:
                cols.extend(s.expr.columns())
            for ex in s.extra_exprs:
                cols.extend(ex.columns())
            if s.filter:
                cols.extend(s.filter.columns())
            fn_ = for_spec(s)
            if getattr(fn_, "subfilter_args", False):
                for node in fn_.filter_nodes:
                    cols.extend(node.columns())
        elif isinstance(s, WindowSpec):
            if s.expr is not None:
                cols.extend(s.expr.columns())
            for p in s.partition_by:
                cols.extend(p.columns())
            for o in s.order_by:
                cols.extend(o.expr.columns())
        else:
            cols.extend(s.columns())
    # ORDER BY/HAVING references to AGGREGATION aliases are resolved by
    # reduce against final arrays, not segment columns — skip them unless a
    # physical column shadows the alias.
    agg_aliases = {
        a
        for s, a in zip(ctx.select_list, ctx.select_aliases)
        if a and isinstance(s, AggregationSpec)
    }
    physical = set(segment.schema.column_names)
    alias_only = agg_aliases - physical
    # "*" here can only come from count(*) inside an ORDER BY/HAVING call —
    # it needs no column loads (unlike SELECT *).
    for o in ctx.order_by:
        cols.extend(c for c in o.expr.columns() if c not in alias_only and c != "*")
    if ctx.having:
        cols.extend(c for c in ctx.having.columns() if c not in alias_only and c != "*")
    seen, out = set(), []
    for c in cols:
        if c == "*":
            for name in _all_column_names(segment):
                if name not in seen:
                    seen.add(name)
                    out.append(name)
            continue
        if c not in seen:
            seen.add(c)
            out.append(c)
    return out


def _non_filter_columns(ctx: QueryContext, segment) -> set:
    """Columns the kernel needs independent of WHERE / FILTER clauses."""
    import dataclasses as dc

    def strip(s):
        if isinstance(s, AggregationSpec) and s.filter is not None:
            return dc.replace(s, filter=None)
        return s

    ctx2 = dc.replace(
        ctx,
        filter=None,
        select_list=[strip(s) for s in ctx.select_list],
        extra_aggregations=[strip(s) for s in ctx.extra_aggregations],
    )
    return set(_needed_columns(ctx2, segment))


def _group_dim(expr: Expr, segment: ImmutableSegment, null_handling: bool) -> GroupDim:
    from pinot_tpu.query import scalar

    if expr.is_column:
        c = segment.column(expr.op)
        if getattr(c, "is_multi_value", False):
            if c.dictionary is None:
                raise NotImplementedError(f"GROUP BY on raw MV column {c.name} (vector columns are not groupable)")
            return GroupDim(
                expr, c.name, "dict", c.dictionary.cardinality, dictionary=c.dictionary, mv=True
            )
        null_code = -1
        if c.has_dictionary:
            if c.nulls is not None and null_handling:
                nc = c.dictionary.index_of(c.data_type.null_placeholder)
                if nc >= 0:
                    null_code = nc
            return GroupDim(expr, c.name, "dict", c.dictionary.cardinality, dictionary=c.dictionary, null_code=null_code)
        if c.data_type in (DataType.INT, DataType.LONG, DataType.TIMESTAMP, DataType.BOOLEAN):
            lo, hi = int(c.stats.min_value), int(c.stats.max_value)
            rng = hi - lo + 1
            return GroupDim(expr, c.name, "rawint", rng, base=lo)
        raise NotImplementedError(f"group-by on raw {c.data_type.value} column {c.name} is not groupable")
    # GROUP BY <expression> (ExpressionContext function analog):
    # string-valued dictionary function -> derived dictionary dimension
    if scalar.is_dict_fn_expr(expr) and scalar.string_result(expr):
        col = next(a for a in expr.args if not a.is_literal).op
        c = segment.column(col)
        if c.has_dictionary:
            derived = scalar.derived_for(expr, c.dictionary)
            uniq, remap = np.unique(derived, return_inverse=True)
            return GroupDim(
                expr,
                col,
                "derived",
                len(uniq),
                derived_values=uniq,
                remap=remap.astype(np.int32),
            )
    # integer-valued device expression -> statically bounded expr dimension
    # (GROUP BY DATETRUNC('day', ts) — the archetypal OLAP bucketing)
    rng = scalar.expr_int_range(expr, segment)
    if rng is not None:
        lo, hi = rng
        return GroupDim(expr, str(expr), "expr", hi - lo + 1, base=lo)
    raise NotImplementedError(
        f"group-by expression {expr} is not supported: its integer range cannot be "
        "bounded from column stats and it is not a dictionary string function"
    )


def column_binding(spec, segment, ctx: Optional[QueryContext] = None):
    """Per-column constants for sketch aggregations (query/sketches.py).

    Alignment resolution: engine-injected options carry the table-global
    value range ("__range__<col>") and dictionary-fingerprint consensus
    ("__dictfp__<col>", "MIXED" when segments disagree).  A dict column whose
    key space is NOT shared across segments must not merge code-indexed
    partials — numeric columns downgrade to a value-range ("rawint") binding,
    everything else to "raw" (hash-based sketches only)."""
    from pinot_tpu.query.sketches import ColumnBinding

    e = spec.expr
    if e is None or not e.is_column:
        raise NotImplementedError(f"{spec.function} requires a bare column argument")
    c = segment.column(e.op)
    mn, mx = c.stats.min_value, c.stats.max_value
    aligned = True
    if ctx is not None:
        rng = ctx.options.get(f"__range__{e.op}")
        if rng is not None:
            mn, mx = rng
        aligned = ctx.options.get(f"__dictfp__{e.op}", "") != "MIXED"
    dict_values = c.dictionary.values if c.has_dictionary else None
    if c.has_dictionary and aligned:
        return ColumnBinding(
            "dict", domain=c.dictionary.cardinality, dict_values=dict_values,
            min_value=mn, max_value=mx,
        )
    if c.data_type in (DataType.INT, DataType.LONG, DataType.TIMESTAMP, DataType.BOOLEAN) and mn is not None:
        rng_width = int(mx) - int(mn) + 1
        if rng_width <= MAX_DENSE_RAW_INT_RANGE:
            return ColumnBinding("rawint", domain=rng_width, base=int(mn), min_value=mn, max_value=mx)
    # dict_values still flow through: value-based host hashing (HLL) stays
    # correct across misaligned dictionaries
    return ColumnBinding("raw", dict_values=dict_values, min_value=mn, max_value=mx)


def bind_aggs(agg_specs, segment, ctx: QueryContext):
    """Specialize + column-bind the aggregation functions for one plan."""
    out = []
    for spec in agg_specs:
        fn = for_spec(spec)
        if fn.needs_binding:
            fn = fn.bind_column(column_binding(spec, segment, ctx))
        out.append(fn)
    return out


def mv_agg_input(spec, fn, segment, cols, mask):
    """(values, mask) for an MV aggregation: padded [rows, max_len] element
    matrix + combined row-filter x length mask."""
    if spec.expr is None or not spec.expr.is_column:
        raise ValueError(f"{spec.function} requires a multi-value column argument")
    c = segment.column(spec.expr.op)
    if not getattr(c, "is_multi_value", False):
        raise ValueError(f"{spec.function} requires a multi-value column; {spec.expr.op} is single-value")
    entry = cols[spec.expr.op]
    codes = entry["codes"].astype(jnp.int32)
    pad = jnp.arange(codes.shape[1], dtype=jnp.int32)[None, :] < entry["lengths"][:, None].astype(jnp.int32)
    m2 = mask[:, None] & pad
    if fn.needs_codes:
        return codes, m2
    if fn.base.name == "count":
        return m2, m2
    if c.data_type.is_string_like:
        raise ValueError(f"{spec.function} needs numeric elements; {spec.expr.op} is {c.data_type.value}")
    vals = entry["dict"][jnp.minimum(codes, np.int32(c.dictionary.cardinality - 1))]
    return vals, m2


def agg_input_codes(spec, fn, segment, cols, mask, null_handling: bool):
    """Kernel-side input for needs_codes aggregations, dispatched on the
    bound function's input_kind:
      codes         - dictionary codes (shared key space / per-segment hash
                      tables index by them)
      values_offset - decoded numeric values minus the binding's base (a
                      table-global int range, aligned by construction)
      values_hash   - raw numeric values, hashed on device (full bit
                      pattern; see sketches._device_hash_values)"""
    import jax.numpy as jnp

    from pinot_tpu.query.transform import column_values

    name = spec.expr.op
    c = segment.column(name)
    entry = cols[name]
    if c.nulls is not None and null_handling:
        mask = mask & ~entry["nulls"]
    kind = getattr(fn, "input_kind", "codes")
    if kind == "codes":
        if not c.has_dictionary:
            raise ValueError(f"{spec.function} bound to codes but column {name} has no dictionary")
        return entry["codes"].astype(jnp.int32), mask
    vals, _ = column_values(name, segment, cols)
    if kind == "values_offset":
        return (vals - np.asarray(fn.base, dtype=vals.dtype)).astype(jnp.int32), mask
    return vals, mask  # values_hash


def column_limb_sig(c) -> Optional[Tuple[int, bool]]:
    """Limb-decomposition plan implied by an int column's stats — part of the
    kernel cache key because grouped_partials bakes it into the trace."""
    if c.data_type in (DataType.INT, DataType.LONG, DataType.TIMESTAMP, DataType.BOOLEAN):
        s = c.stats
        if s.num_docs and s.min_value is not None:
            return ops.sum_limb_plan(s.min_value, s.max_value)
    return None


def agg_vranges(agg_specs, table_like) -> List[Optional[Tuple[int, int]]]:
    """Per-aggregation (min, max) column stats when the input is a bare int
    column — lets the fused scan drop statically-zero limbs."""
    out: List[Optional[Tuple[int, int]]] = []
    for spec in agg_specs:
        rng = None
        e = spec.expr
        if e is not None and e.is_column and e.op != "*":
            try:
                c = table_like.column(e.op)
            except KeyError:
                c = None
            if c is not None and c.data_type in (
                DataType.INT, DataType.LONG, DataType.TIMESTAMP, DataType.BOOLEAN
            ):
                s = c.stats
                if s.num_docs and s.min_value is not None:
                    rng = (int(s.min_value), int(s.max_value))
        out.append(rng)
    return out


def grouped_partials(aggs, inputs, tmask, key, num_groups: int, vranges,
                     backend=None, mask_words=None, key_packed=None):
    """Presence table + per-agg grouped partial dicts for the dense path.

    All additive fields (presence, counts, sums, sums of squares) across ALL
    aggregations share ONE fused one-hot-matmul scan
    (ops.fused_group_tables) — one (A, B) one-hot pair per chunk instead of
    one per table, the single biggest kernel-time win of round 2.  min/max
    fields scatter (no matmul semiring); sketch functions (field_kinds None)
    run their own partial_grouped.

    backend tags the plan-time scan backend (ops.scan_backend()) so eligible
    entry sets route to the Pallas fused kernel.  mask_words optionally
    carries the filter as PACKED uint32 bitmap words instead of folded into
    tmask/input masks — the Pallas scan unpacks them in-register.
    key_packed optionally carries the group-key column's bit-packed forward
    index as (words, code_bits) so the Pallas scan streams packed key bytes
    and lane-unpacks in-register; `key` must still be the (trace-level
    unpacked) codes for every non-Pallas consumer.  Scatter and sketch
    paths never see packed words, so they are defensively unpacked here
    whenever any aggregation needs a non-fusable field."""
    if mask_words is not None:
        fuse_ok = all(fn.field_kinds is not None for fn in aggs) and all(
            k in ("count", "sum", "sumsq")
            for fn in aggs
            for k in fn.field_kinds.values()
        )
        if not fuse_ok:
            row_mask = ops.unpack_bitmap_words(mask_words, tmask.shape[0])
            tmask = tmask & row_mask
            inputs = [(v, m & row_mask) for v, m in inputs]
            mask_words = None
    entries: List[Tuple] = []
    slot_of: Dict[Tuple, int] = {}

    def entry_slot(kind, values, mask, limb_plan=None) -> int:
        k = (kind, id(values) if values is not None else None, id(mask), limb_plan)
        idx = slot_of.get(k)
        if idx is None:
            idx = len(entries)
            entries.append((kind, values, mask, limb_plan))
            slot_of[k] = idx
        return idx

    presence_idx = entry_slot("count", None, tmask)
    requests: List[Tuple[str, Optional[Dict]]] = []
    for i, (fn, (vals, mask)) in enumerate(zip(aggs, inputs)):
        if fn.field_kinds is None:
            requests.append(("own", None))
            continue
        fmap: Dict[str, Tuple[str, Optional[int]]] = {}
        for field, kind in fn.field_kinds.items():
            if kind == "count":
                fmap[field] = ("fused", entry_slot("count", None, mask))
            elif kind == "sum":
                v = vals
                is_int = jnp.issubdtype(v.dtype, jnp.integer)
                rng = vranges[i] if i < len(vranges) else None
                if is_int and v.dtype.itemsize > 4 and rng is not None and (
                    -(1 << 31) <= rng[0] and rng[1] < (1 << 31)
                ):
                    v = v.astype(jnp.int32)  # stats prove int32 narrowing safe
                    is_int = True
                if is_int and v.dtype.itemsize <= 4:
                    lp = ops.sum_limb_plan(*rng) if rng is not None else (4, True)
                    fmap[field] = ("fused", entry_slot("int_sum", v, mask, lp))
                elif is_int:
                    # wide-range int64: signed-magnitude limb decomposition,
                    # bit-exact while sum(|v|) < 2^53 — the reference's
                    # double-accumulate contract (SumAggregationFunction)
                    nl = ops.sum_limb_plan64(*rng) if rng is not None else 8
                    fmap[field] = ("fused", entry_slot("int64_sum", v, mask, nl))
                else:
                    fmap[field] = ("fused", entry_slot("f32_sum", vals, mask))
            elif kind == "sumsq":
                fmap[field] = ("fused", entry_slot("f32_sumsq", vals, mask))
            else:
                fmap[field] = (kind, None)  # min/max: scatter below
        requests.append(("fields", fmap))

    tables = ops.fused_group_tables(
        entries, key, num_groups, backend=backend, mask_words=mask_words,
        codes_packed=key_packed,
    )

    def _as_table(idx):
        t = tables[idx]
        if entries[idx][0] == "count":
            return t.astype(jnp.int64)
        return t

    presence = _as_table(presence_idx)
    partials: List[Dict] = []
    for (tag, fmap), fn, (vals, mask) in zip(requests, aggs, inputs):
        if tag == "own":
            partials.append(fn.partial_grouped(vals, mask, key, num_groups))
            continue
        p: Dict[str, Any] = {}
        for field, (k2, idx) in fmap.items():
            if k2 == "fused":
                p[field] = _as_table(idx)
            elif k2 == "min":
                p[field] = ops.group_min(vals, mask, key, num_groups)
            else:
                p[field] = ops.group_max(vals, mask, key, num_groups)
        partials.append(p)
    return presence, partials


# sentinel packed key for rows filtered out / slots never written; all real
# packed keys are >= 0, so int64 max never collides
SPARSE_EMPTY_KEY = np.int64(np.iinfo(np.int64).max)


def order_by_agg_index(ctx: QueryContext) -> Optional[Tuple[int, bool]]:
    """Map the FIRST ORDER BY expression to an index into ctx.aggregations
    (by alias or by call shape).  The trim paths use it to rank groups by
    the ORDER BY comparator before dropping any — the TableResizer analog
    (pinot-core/.../core/data/table/TableResizer.java) replacing the
    round-4 lowest-packed-key trim that could drop the true top groups of
    a `GROUP BY hi_card ORDER BY SUM(x) DESC LIMIT k` query."""
    if not ctx.order_by:
        return None
    ob = ctx.order_by[0]
    e = ob.expr
    specs = list(ctx.aggregations)
    if e.is_column:
        # alias of a select aggregation
        for s, a in zip(ctx.select_list, ctx.select_aliases):
            if a == e.op and isinstance(s, AggregationSpec):
                fp = s.fingerprint()
                for i, sp in enumerate(specs):
                    if sp.fingerprint() == fp:
                        return i, ob.ascending
        return None
    if e.kind.name != "CALL":
        return None
    for i, sp in enumerate(specs):
        if sp.filter is not None or sp.extra_exprs or sp.literal_args:
            continue
        if e.op.lower() != sp.function.lower():
            continue
        if sp.expr is None:
            if not e.args or (len(e.args) == 1 and e.args[0].is_column and e.args[0].op == "*"):
                return i, ob.ascending
        elif len(e.args) == 1 and e.args[0].fingerprint() == sp.expr.fingerprint():
            return i, ob.ascending
    return None


def kernel_order_spec(ctx: QueryContext, aggs: List[AggFunction]) -> Optional[Tuple[int, str, bool]]:
    """(agg index, contribution mode, ascending) when the first ORDER BY key
    is an aggregate whose per-group order value the sparse kernel can derive
    in one pass: additive sum/count via a segment cumsum, min/max via a
    secondary sort key.  None falls back to the lowest-packed-key trim."""
    hit = order_by_agg_index(ctx)
    if hit is None:
        return None
    i, asc = hit
    fn = aggs[i]
    mode = {"sum": "sum", "count": "count", "min": "min", "max": "max"}.get(fn.name)
    if mode is None or getattr(fn, "mv_input", False) or getattr(fn, "needs_extra_exprs", False):
        return None
    return i, mode, asc


def packed_key64(cols, group_dims, segment) -> jnp.ndarray:
    """Ravel per-dim codes into one int64 key (device side).  The planner
    guards the key space to < 2^62 before choosing the sparse path."""
    key = None
    for gd in group_dims:
        code = gd.device_code(cols, segment, jnp.int64)
        key = code if key is None else key * np.int64(gd.cardinality) + code
    return key


def sparse_grouped_tables(aggs, inputs, tmask, key, num_slots: int, order_spec=None):
    """Device-side high-cardinality group-by: sort + segment-scatter into
    FIXED-size tables (the IndexedTable analog with numGroupsLimit trim
    built into the kernel).

    Replaces the round-1/2 host fallback that device_get the mask, codes and
    every agg input for ALL rows (tens of GB over PCIe at 1B rows).  Now the
    kernel returns [num_slots]-sized tables only:

      sort rows by packed key (filtered rows get SPARSE_EMPTY_KEY, sorting
      last) -> group starts where the sorted key changes -> running group
      index = cumsum(starts) -> rows beyond num_slots groups scatter into a
      dropped overflow slot.  Sorted keys make the trim deterministic (lowest
      keys win — the documented analog of Pinot's first-arrival trim).

    Accumulation dtypes mirror the host reduce contracts: counts int64,
    sums/sumsq float64 (exact for int sums < 2^53 — the reference likewise
    accumulates long sums in double), min/max float64.  This path is
    scatter/HBM-bound, not MXU-bound, so f64 costs little on TPU here.

    Returns (uniq_keys[num_slots] int64 with SPARSE_EMPTY_KEY padding,
             [{field: table[num_slots]}] per agg)."""
    from jax import lax

    n = tmask.shape[0]
    k64 = jnp.where(tmask, key, SPARSE_EMPTY_KEY)
    iota = jnp.arange(n, dtype=jnp.int32)
    if order_spec is not None and order_spec[1] in ("min", "max"):
        # min/max order value rides the row sort as a secondary key: after
        # sorting by (key, ±value) the group's extremum sits at its start row
        oi, omode, _ = order_spec
        ov_raw, om = inputs[oi]
        ovr = ov_raw.astype(jnp.float64)
        ovr = ovr if omode == "min" else -ovr
        ovr = jnp.where(om, ovr, jnp.inf)
        skey, sov, perm = lax.sort((k64, ovr, iota), num_keys=2)
    else:
        sov = None
        skey, perm = lax.sort((k64, iota), num_keys=1)
    smask = tmask[perm]
    prev = jnp.concatenate([jnp.full((1,), -1, skey.dtype), skey[:-1]])
    is_start = smask & (skey != prev)
    seg = jnp.cumsum(is_start.astype(jnp.int32)) - 1
    if order_spec is None:
        # slot num_slots = overflow/invalid bin, sliced off before returning;
        # first-num_slots-groups-by-packed-key trim (deterministic)
        slot = jnp.where(smask & (seg < num_slots), seg, num_slots)
    else:
        # ORDER BY-aware trim (TableResizer analog): compute each group's
        # order value in-row-space, rank groups by (order value, packed key)
        # on device, and give slots to the top num_slots groups only.
        oi, omode, asc = order_spec
        if sov is not None:
            empty = jnp.isinf(sov)  # no agg-mask rows in the group: NULL
            group_ov = sov  # valid at start rows: the group's min / -max
            group_ov = group_ov if asc else -group_ov
            # sov carries -v for max, so one more flip restores the sign
            if omode == "max":
                group_ov = -group_ov
            # NULL (empty) and NaN order values rank LAST in every direction
            # (matching the host-side _order_trim_select NaN handling); clamp
            # keeps them FINITE so the finite check below still marks the
            # group rankable instead of dropping it (review-caught).  An
            # all-NaN group's start-row sov is NaN (NaN sorts last), which
            # would otherwise survive clip as NaN and drop the group.
            group_ov = jnp.clip(
                jnp.where(empty | jnp.isnan(group_ov), jnp.inf, group_ov), -1e300, 1e300
            )
        else:
            ov_raw, om = inputs[oi]
            isn = None
            if omode == "count":
                c = om.astype(jnp.float64)
            else:
                v = ov_raw if getattr(ov_raw, "ndim", 0) else jnp.broadcast_to(ov_raw, (n,))
                cv = v.astype(jnp.float64)
                # NaN rows are excluded from the cumsum (one NaN would poison
                # the prefix sums of every later-keyed group) and tracked per
                # group instead; NaN-sum groups rank last like the host path
                isn = jnp.isnan(cv)
                c = jnp.where(om & ~isn, cv, 0.0)
            cp = c[perm]
            s0 = jnp.concatenate([jnp.zeros((1,), jnp.float64), jnp.cumsum(cp)])
            # smallest start index >= i, from the right; strict next start
            starts_at = jnp.where(is_start, iota, np.int32(n))
            nxt_ge = lax.cummin(starts_at[::-1])[::-1]
            nxt = jnp.concatenate([nxt_ge[1:], jnp.full((1,), n, jnp.int32)])
            total = s0[nxt] - s0[iota]  # valid at start rows
            group_ov = total if asc else -total
            if omode == "sum":
                # SUM over zero agg-mask rows is SQL NULL, not 0: count the
                # mask the same way and send empty groups to rank-last
                mp = om.astype(jnp.float64)[perm]
                m0 = jnp.concatenate([jnp.zeros((1,), jnp.float64), jnp.cumsum(mp)])
                np_ = (isn & om).astype(jnp.float64)[perm]
                n0 = jnp.concatenate([jnp.zeros((1,), jnp.float64), jnp.cumsum(np_)])
                # rank-last when the group saw a NaN value, when the prefix
                # sums overflowed to inf (inf - inf = NaN), or when no
                # agg-mask rows contributed (SQL NULL)
                bad = ((n0[nxt] - n0[iota]) > 0) | jnp.isnan(group_ov)
                group_ov = jnp.clip(
                    jnp.where(bad | ((m0[nxt] - m0[iota]) <= 0), jnp.inf, group_ov),
                    -1e300, 1e300,
                )
        ovkey = jnp.where(is_start, group_ov, jnp.inf)
        sovk, sskey, sseg = lax.sort((ovkey, skey, seg), num_keys=2)
        rank = jnp.minimum(iota, np.int32(num_slots))
        ranks = (
            jnp.full((n + 1,), num_slots, dtype=jnp.int32)
            .at[jnp.where(jnp.isfinite(sovk), sseg, np.int32(n))]
            .set(rank, mode="drop")
        )
        gslot = ranks[jnp.minimum(seg, np.int32(n))]
        slot = jnp.where(smask & (gslot < num_slots), gslot, num_slots)
    uniq = (
        jnp.full((num_slots + 1,), SPARSE_EMPTY_KEY, dtype=jnp.int64)
        .at[jnp.where(is_start, slot, num_slots)]
        .set(skey)
    )
    partials = []
    for fn, (vals, mask) in zip(aggs, inputs):
        m = mask[perm]

        def _perm(x):
            x = x if getattr(x, "ndim", 0) else jnp.broadcast_to(x, (n,))
            return x[perm]

        if fn.field_kinds is None:
            # sketch / own-scatter family (HLL registers, presence bitmaps,
            # histograms, KMV, (t, v) pairs, MV wrappers): the slot array IS
            # a dense group-key space of num_slots+1 ids, so the function's
            # own partial_grouped scatters per-slot vector fields directly;
            # the overflow slot is sliced off like the scalar tables.
            v = tuple(_perm(x) for x in vals) if isinstance(vals, tuple) else _perm(vals)
            own = fn.partial_grouped(v, m, slot, num_slots + 1)
            partials.append({f: t[:num_slots] for f, t in own.items()})
            continue
        v = _perm(vals)
        p: Dict[str, Any] = {}
        for fname in fn.fields:
            comb = FIELD_COMBINE[fname]
            if comb == "add":
                if fname == "count":
                    acc = jnp.zeros((num_slots + 1,), jnp.int64).at[slot].add(m.astype(jnp.int64))
                else:
                    w = v.astype(jnp.float64)
                    if fname == "sumsq":
                        w = w * w
                    acc = jnp.zeros((num_slots + 1,), jnp.float64).at[slot].add(jnp.where(m, w, 0.0))
            else:
                ident = field_identity(fname)
                masked = jnp.where(m, v.astype(jnp.float64), ident)
                base = jnp.full((num_slots + 1,), ident, jnp.float64)
                acc = base.at[slot].min(masked) if comb == "min" else base.at[slot].max(masked)
            p[fname] = acc[:num_slots]
        partials.append(p)
    return uniq[:num_slots], partials


def plan_segment(ctx: QueryContext, segment: ImmutableSegment) -> SegmentPlan:
    from pinot_tpu.analysis.compile_audit import SSE_AUDIT
    from pinot_tpu.analysis.plan_check import check_plan_cached

    from pinot_tpu.query.shape import column_info_from, params_structure

    # static IR validation before anything traces: malformed plans raise
    # structured PlanCheckError here instead of a tracer error inside jit
    check_plan_cached(ctx)
    needed = _needed_columns(ctx, segment)
    key = (
        ctx.shape_fingerprint(column_info_from(segment)),
        _segment_signature(segment, needed, sketch_bound_columns(ctx) | const_bound_columns(ctx)),
        ops.scan_backend(),  # pallas/xla plans trace different kernels
    )
    cached = _PLAN_CACHE.get(key)
    if cached is not None:
        # params are per-query/per-segment (literals, dictionary lookups):
        # rebuild them, reuse the compiled fn.  The structure check is the
        # safety net under the shape audit — a mismatch would silently
        # retrace, so it counts (and compiles) as a miss instead.
        plan = _build_plan(ctx, segment, needed, compiled_fn=cached.fn)
        if params_structure(plan.params) == params_structure(cached.params):
            # cost model rides the cache entry: captured once at the first
            # launch of the cached plan, never re-lowered on hits
            plan.cost = cached.cost
            plan.cache_key = key
            SSE_AUDIT.record_hit(key[0])
            return plan
    SSE_AUDIT.record_compile(key[0])
    plan = _build_plan(ctx, segment, needed, compiled_fn=None)
    plan.cache_key = key
    _PLAN_CACHE.put(key, plan)
    return plan


def _build_plan(
    ctx: QueryContext,
    segment: ImmutableSegment,
    needed: List[str],
    compiled_fn: Optional[Callable],
) -> SegmentPlan:
    null_handling = ctx.null_handling
    fc = FilterCompiler(segment, null_handling)
    filter_fn = fc.compile(ctx.filter)

    # Upsert validDocIds: rows replaced by a newer row elsewhere are ANDed
    # out of EVERY filter (the reference's validDocIds bitmap in
    # FilterPlanNode).  The mask ships as a param so invalidations between
    # queries apply without recompiling; presence is part of the plan-cache
    # signature (_segment_signature) since the kernel must consume it.
    if segment.valid_docs is not None:
        fc.params["__valid__"] = np.asarray(segment.valid_docs, dtype=bool)
        base_filter_fn = filter_fn

        def filter_fn(cols, params):
            t, nl = base_filter_fn(cols, params)
            v = params["__valid__"]
            return t & v, (nl & v if nl is not None else None)

    agg_specs = list(ctx.aggregations)
    aggs = bind_aggs(agg_specs, segment, ctx)

    # per-aggregation FILTER(WHERE ...) clauses
    agg_filter_fns: List[Optional[Callable]] = []
    for spec in agg_specs:
        agg_filter_fns.append(fc.compile(spec.filter) if spec.filter is not None else None)

    # theta sub-filter strings ('dim=''a''' literals) compile through the
    # same FilterCompiler; the kernel feeds one mask per sub-filter
    agg_subfilter_fns: List[Optional[List[Callable]]] = []
    for fn_ in aggs:
        if getattr(fn_, "subfilter_args", False):
            agg_subfilter_fns.append([fc.compile(node) for node in fn_.filter_nodes])
        else:
            agg_subfilter_fns.append(None)

    # Columns touched ONLY by index-resolved predicates never ship to device
    # (the index row already answered them) — the byte-savings half of the
    # BitmapBasedFilterOperator redesign.
    keep = _non_filter_columns(ctx, segment) | fc.used_columns
    needed = [c for c in needed if c in keep]

    # Bit-packed forward indexes (segment/packing.py): columns the executor
    # may ship as uint32 lane words ("codes_packed" entries).  The kernel
    # overlays a trace-time vectorized-shift unpack so every existing
    # reader sees "codes" unchanged; XLA dedups the single unpack across
    # readers and DCEs it when the Pallas path consumes the words directly.
    packed_meta: Dict[str, int] = {}
    for name in needed:
        c = segment.column(name)
        bits = getattr(c, "code_bits", None)
        if bits and getattr(c, "packed", None) is not None:
            packed_meta[name] = int(bits)
    num_docs = segment.num_docs

    def _overlay_unpacked(cols):
        from pinot_tpu.segment import packing

        out = dict(cols)
        for name, bits in packed_meta.items():
            e = out.get(name)
            if e is not None and "codes_packed" in e and "codes" not in e:
                e = dict(e)
                e["codes"] = packing.unpack_codes_jnp(e["codes_packed"], bits, num_docs)
                out[name] = e
        return out

    if ctx.is_aggregate and not ctx.group_by:
        kind = "aggregation"
        group_dims: List[GroupDim] = []
        num_groups = 0
    elif ctx.group_by:
        group_dims = [_group_dim(g, segment, null_handling) for g in ctx.group_by]
        num_groups = 1
        for gd in group_dims:
            num_groups *= max(1, gd.cardinality)
        kind = "groupby_dense" if num_groups <= ctx.max_dense_groups else "groupby_sparse"
    else:
        kind = "selection"
        group_dims = []
        num_groups = 0

    guard_sparse_vector_fields(kind, aggs)

    def _agg_inputs(cols, params, base_mask):
        """Per-aggregation (values, mask) with null + FILTER handling."""
        out = []
        for spec, fn, ffn, sfns in zip(agg_specs, aggs, agg_filter_fns, agg_subfilter_fns):
            mask = base_mask
            if ffn is not None:
                ft, _ = ffn(cols, params)
                mask = mask & ft
            if getattr(fn, "mv_input", False):
                out.append(mv_agg_input(spec, fn, segment, cols, mask))
                continue
            if spec.expr is None:
                vals = mask  # COUNT(*): values unused
            elif fn.needs_codes:
                vals, mask = agg_input_codes(spec, fn, segment, cols, mask, null_handling)
            elif fn.name == "count" and spec.expr.is_column:
                # COUNT(col) needs only the null mask — works on strings too.
                vals = mask
                c = segment.column(spec.expr.op)
                if c.nulls is not None and null_handling:
                    mask = mask & ~cols[spec.expr.op]["nulls"]
            else:
                vals, nulls = eval_expr(spec.expr, segment, cols)
                vals = as_row_array(vals, mask.shape)
                if nulls is not None and null_handling:
                    mask = mask & ~nulls
            if fn.needs_extra_exprs:
                extras = []
                for ex in spec.extra_exprs:
                    ev, en = eval_expr(ex, segment, cols)
                    extras.append(as_row_array(ev, mask.shape))
                    if en is not None and null_handling:
                        mask = mask & ~en
                vals = (vals, *extras)
            if sfns:
                vals = (vals, *[mask & sf(cols, params)[0] for sf in sfns])
            out.append((vals, mask))
        return out

    def _group_key(cols, params):
        if len(group_dims) == 1 and group_dims[0].kind == "dict":
            # storage-dtype passthrough: the group kernels cast per chunk
            return cols[group_dims[0].name]["codes"]
        key = None
        for gd in group_dims:
            code = gd.device_code(cols, segment, jnp.int32)
            key = code if key is None else key * np.int32(gd.cardinality) + code
        return key

    def _key_packed(cols):
        """(words, code_bits) when the single dict group key shipped packed
        AND the Pallas backend can lane-unpack it in-register; else None."""
        if scan_be not in ("pallas", "interpret") or len(group_dims) != 1:
            return None
        gd = group_dims[0]
        if gd.kind != "dict" or gd.mv:
            return None
        bits = packed_meta.get(gd.name)
        if not bits or num_docs % (32 // bits):
            return None
        e = cols.get(gd.name)
        if e is None or "codes_packed" not in e:
            return None
        return (e["codes_packed"], bits)

    if kind == "aggregation":

        def kernel(cols, params):
            tmask, _ = filter_fn(cols, params)
            return [fn.partial(vals, mask) for fn, (vals, mask) in zip(aggs, _agg_inputs(cols, params, tmask))]

    mv_dims = [i for i, gd in enumerate(group_dims) if gd.mv]
    if len(mv_dims) > 1:
        raise NotImplementedError("at most one multi-value GROUP BY dimension (explode) per query")
    if mv_dims and any(
        getattr(fn_, "mv_input", False) or getattr(fn_, "needs_extra_exprs", False) for fn_ in aggs
    ):
        raise NotImplementedError(
            "MV/tuple-input aggregations (SUMMV..., FIRST/LASTWITHTIME) cannot combine "
            "with an MV GROUP BY dimension"
        )
    mv_i = mv_dims[0] if mv_dims else None

    def _mv_explode(cols, params, tmask, key_dtype):
        """MV group-by explode: [n] -> flattened [n*max_len] key/mask/inputs
        (each element of the MV dimension contributes one logical row —
        Pinot's MV group-by semantics)."""
        gd_mv = group_dims[mv_i]
        entry = cols[gd_mv.name]
        codes2 = entry["codes"].astype(jnp.int32)
        pad = jnp.arange(codes2.shape[1], dtype=jnp.int32)[None, :] < entry["lengths"][:, None].astype(jnp.int32)
        t2 = tmask[:, None] & pad
        shape2 = t2.shape
        key = None
        for i2, gd in enumerate(group_dims):
            if i2 == mv_i:
                code = jnp.minimum(codes2, np.asarray(gd.cardinality - 1, dtype=key_dtype)).astype(key_dtype)
            else:
                code = jnp.broadcast_to(
                    gd.device_code(cols, segment, key_dtype)[:, None], shape2
                )
            key = code if key is None else key * np.asarray(gd.cardinality, dtype=key_dtype) + code
        inputs = _agg_inputs(cols, params, tmask)
        flat_inputs = [
            (
                jnp.broadcast_to(jnp.broadcast_to(v, tmask.shape)[:, None], shape2).reshape(-1),
                (m[:, None] & t2).reshape(-1),
            )
            for v, m in inputs
        ]
        return key.reshape(-1), t2.reshape(-1), flat_inputs

    scan_be = ops.scan_backend()  # plan-time backend decision (cache-keyed)

    if kind == "groupby_dense" and mv_i is not None:
        vranges = agg_vranges(agg_specs, segment)

        def kernel(cols, params):
            tmask, _ = filter_fn(cols, params)
            key, t_f, inputs = _mv_explode(cols, params, tmask, jnp.int32)
            return grouped_partials(aggs, inputs, t_f, key, num_groups, vranges,
                                    backend=scan_be)

    elif kind == "groupby_dense":
        vranges = agg_vranges(agg_specs, segment)

        def kernel(cols, params):
            tmask, _ = filter_fn(cols, params)
            key = _group_key(cols, params)
            inputs = _agg_inputs(cols, params, tmask)
            return grouped_partials(aggs, inputs, tmask, key, num_groups, vranges,
                                    backend=scan_be, key_packed=_key_packed(cols))

    elif kind == "groupby_sparse":
        # Device-side sort+scatter into fixed [numGroupsLimit] tables — no
        # row-length arrays ever leave the device (sparse_grouped_tables).
        if num_groups >= (1 << 62):
            raise NotImplementedError("composite group key exceeds 62 bits")
        num_slots = min(ctx.num_groups_limit, num_groups)
        order_spec = kernel_order_spec(ctx, aggs)

        if mv_i is not None:

            def kernel(cols, params):
                tmask, _ = filter_fn(cols, params)
                key, t_f, inputs = _mv_explode(cols, params, tmask, jnp.int64)
                return sparse_grouped_tables(aggs, inputs, t_f, key, num_slots, order_spec)

        else:

            def kernel(cols, params):
                tmask, _ = filter_fn(cols, params)
                key = packed_key64(cols, group_dims, segment)
                inputs = _agg_inputs(cols, params, tmask)
                return sparse_grouped_tables(aggs, inputs, tmask, key, num_slots, order_spec)

    elif kind == "selection":

        def kernel(cols, params):
            tmask, _ = filter_fn(cols, params)
            return tmask

    if packed_meta:
        base_kernel = kernel

        def kernel(cols, params):
            return base_kernel(_overlay_unpacked(cols), params)

    fn = compiled_fn if compiled_fn is not None else jax.jit(kernel)

    select_columns = []
    select_exprs: List[Any] = []
    if kind == "selection":
        from pinot_tpu.query.ir import WindowSpec

        for s in ctx.select_list:
            if isinstance(s, WindowSpec):
                select_exprs.append(s)  # computed at reduce over merged rows
                continue
            if not isinstance(s, Expr):
                raise NotImplementedError(f"unsupported selection item {s}")
            if s.is_column and s.op == "*":
                select_exprs.extend(Expr.col(n) for n in _all_column_names(segment))
            else:
                select_exprs.append(s)
        select_columns = [e.op for e in select_exprs if isinstance(e, Expr) and e.is_column]
    elif ctx.windows:
        raise NotImplementedError("window functions apply to selection queries only")

    return SegmentPlan(
        kind=kind,
        fn=fn,
        params=fc.params,
        needed_columns=needed,
        aggs=aggs,
        group_dims=group_dims,
        num_groups=num_groups,
        select_columns=select_columns,
        select_exprs=select_exprs,
        index_uses=list(fc.index_uses),
    )
