"""Shape fingerprint: compile-cache keys by query SHAPE, not literal values.

Reference parity: Pinot caches per-segment plans by query structure and
feeds literals through predicate evaluators at run time; DrJAX (PAPERS.md)
makes the same split — control structure static, data dynamic.  Here the
jitted kernels already take predicate state (dict-code bounds, lookup
tables, bitmap words, value arrays) through the params pytree, so two
queries that differ only in literals trace byte-identical programs.  What
baked literals into the compile caches was the KEY: `Predicate.fingerprint`
embeds `values`/`lower`/`upper`, so `WHERE user_id = 12345` vs `= 12346`
was a full re-trace + XLA recompile.

`shape_fingerprint(ctx, column_info)` canonicalizes every literal that
provably cannot change the traced program into a typed slot (`?`), keyed by
an explicit per-predicate audit:

  PARAMETERIZABLE (slot in the key, literal rides params):
    * dict-encoded EQ/RANGE on a sorted column, a range-indexed column, or
      a plain scan column — lo/hi codes or doc ranges are int32 params;
    * dict-encoded NEQ/IN/NOT_IN/REGEXP/LIKE without an inverted index —
      the bool lookup table is cardinality-shaped, value-independent;
    * derived-string predicates (fn(dictcol) = 'x') — same table shape;
    * raw-column EQ/NEQ/RANGE with numeric literals — the literal becomes
      a scalar param (query/filter.py eval_cmp);
    * raw-column IN/NOT_IN over numeric literals — the value array pads to
      a bucketed size class (4/16/64/...) with identity fill, so distinct
      list lengths within a bucket share one compile.

  SHAPE-AFFECTING (literal stays in the key):
    * any predicate resolvable through an INVERTED index: the positive-row
      / negated-row / scan choice (`_INV_MAX_ROWS` thresholds in
      query/filter.py) depends on the literal and bakes `negate`;
    * TEXT_MATCH / JSON_MATCH / VECTOR_SIMILARITY (top-k `k` is traced);
    * values containing Subquery markers or non-scalar objects;
    * unknown columns (no metadata — conservative default).

LIMIT/OFFSET and HAVING literals canonicalize unconditionally: both are
applied host-side in reduce from the live ctx, never traced.  The audit is
deliberately conservative — a predicate only canonicalizes when every
structure decision the compiler can make for it is literal-independent —
and the engines re-verify by comparing the rebuilt params structure against
the cached plan before reusing a compiled fn (repo_lint W008 guards the
regression where raw fingerprints creep back into plan-cache keys).
"""
from __future__ import annotations

import hashlib
from typing import Any, Callable, List, NamedTuple, Optional, Tuple

from pinot_tpu.query.ir import (
    FilterNode,
    FilterOp,
    Predicate,
    PredicateType,
    QueryContext,
    Subquery,
)

# column metadata the audit needs; `None` from a provider means "unknown"
class ColumnShape(NamedTuple):
    has_dictionary: bool
    is_sorted: bool
    has_inverted: bool
    has_range_index: bool


# provider: column name -> ColumnShape | None
ColumnInfo = Callable[[str], Optional[ColumnShape]]

# IN-list size classes: distinct list lengths within one bucket share a
# compile; the compiler pads the value array to the bucket with identity
# fill (repeating a member never changes isin semantics)
_IN_BUCKETS = (4, 16, 64, 256, 1024, 4096)


def bucket_size(n: int) -> int:
    for b in _IN_BUCKETS:
        if n <= b:
            return b
    return n  # beyond the largest class: exact size keys itself


def shape_digest(fingerprint: str) -> str:
    """Short stable digest for spans / slow-log entries (full fingerprints
    can embed literal values; the digest never does more than identify)."""
    return hashlib.sha1(fingerprint.encode("utf-8", "replace")).hexdigest()[:12]


def column_info_from(table_like: Any) -> ColumnInfo:
    """Best-effort provider over a segment / StackedTable / shard view:
    anything with `.column(name)` and an `.indexes` dict.  Unknown columns
    (or any introspection failure) return None -> the audit bakes."""

    def info(name: str) -> Optional[ColumnShape]:
        try:
            col = table_like.column(name)
        except Exception:
            return None
        if col is None:
            return None
        idx = getattr(table_like, "indexes", None) or {}
        stats = getattr(col, "stats", None)
        return ColumnShape(
            has_dictionary=bool(getattr(col, "has_dictionary", False)),
            is_sorted=bool(getattr(stats, "is_sorted", False))
            and getattr(col, "codes", None) is not None,
            has_inverted=name in (idx.get("inverted") or {}),
            has_range_index=name in (idx.get("range") or {}),
        )

    return info


def _type_class(v: Any) -> Optional[str]:
    """Literal type class — part of the slot (a float param and an int
    param trace different dtypes).  None = not a parameterizable scalar."""
    if v is None:
        return "n"
    if isinstance(v, bool):
        return "b"
    if isinstance(v, int):
        return "i"
    if isinstance(v, float):
        return "f"
    if isinstance(v, str):
        return "s"
    return None


def _scalar_classes(values: Tuple[Any, ...]) -> Optional[List[str]]:
    out: List[str] = []
    for v in values:
        if isinstance(v, Subquery):
            return None
        c = _type_class(v)
        if c is None:
            return None
        out.append(c)
    return out


_NUMERIC = ("b", "i", "f")

# predicates routed through _compile_dict_predicate's bool-table path
_TABLE_PREDS = (
    PredicateType.NEQ,
    PredicateType.IN,
    PredicateType.NOT_IN,
    PredicateType.REGEXP_LIKE,
    PredicateType.LIKE,
)


def audit_predicate(p: Predicate, info: Optional[ColumnInfo]) -> Tuple[bool, str]:
    """(parameterizable, reason) for ONE predicate — the explicit
    shape-affecting audit.  `reason` names the deciding rule so EXPLAIN /
    tests can assert on WHY a literal stayed in the key."""
    pt = p.ptype
    if pt in (PredicateType.IS_NULL, PredicateType.IS_NOT_NULL):
        return False, "no-literals"
    if pt in (
        PredicateType.TEXT_MATCH,
        PredicateType.JSON_MATCH,
        PredicateType.VECTOR_SIMILARITY,
    ):
        return False, "traced-structure"
    classes = _scalar_classes(p.values)
    if classes is None:
        return False, "non-scalar-values"
    bound_classes = _scalar_classes(tuple(v for v in (p.lower, p.upper) if v is not None))
    if bound_classes is None:
        return False, "non-scalar-bounds"

    if p.lhs.is_column:
        cs = info(p.lhs.op) if info is not None else None
        if cs is None:
            return False, "unknown-column"
        if cs.has_dictionary:
            if pt in (PredicateType.EQ, PredicateType.RANGE):
                if cs.is_sorted or cs.has_range_index or not cs.has_inverted:
                    return True, "dict-code-range"
                return False, "inverted-index-threshold"
            if pt in _TABLE_PREDS:
                if cs.has_inverted:
                    return False, "inverted-index-threshold"
                return True, "dict-table"
            return False, "unsupported-ptype"
        # raw column: literals become device params — numeric only
        if pt in (PredicateType.EQ, PredicateType.NEQ, PredicateType.RANGE):
            if all(c in _NUMERIC for c in classes + bound_classes):
                return True, "raw-cmp-param"
            return False, "non-numeric-raw"
        if pt in (PredicateType.IN, PredicateType.NOT_IN):
            if classes and all(c in _NUMERIC for c in classes):
                return True, "raw-in-bucketed"
            return False, "non-numeric-raw"
        return False, "unsupported-ptype"

    # CALL lhs: routes to the derived-string table (dict inner column) or
    # the raw value path — both literal-independent in structure, but only
    # numeric literals are provably safe on the raw side, and the derived
    # path handles strings host-side.  EQ/NEQ/RANGE/IN/NOT_IN only; the
    # regex forms raise on the raw path, so their routing IS the structure.
    if pt in (PredicateType.EQ, PredicateType.NEQ, PredicateType.RANGE):
        if all(c in _NUMERIC for c in classes + bound_classes):
            return True, "call-cmp-param"
        return False, "non-numeric-call"
    if pt in (PredicateType.IN, PredicateType.NOT_IN):
        if classes and all(c in _NUMERIC for c in classes):
            return True, "call-in-bucketed"
        return False, "non-numeric-call"
    return False, "unsupported-ptype"


def audit_filter(
    node: Optional[FilterNode], info: Optional[ColumnInfo]
) -> List[Tuple[Predicate, bool, str]]:
    """Full per-predicate audit of a filter tree (test / EXPLAIN surface)."""
    if node is None:
        return []
    return [(p, *audit_predicate(p, info)) for p in node.predicates()]


def _slot(p: Predicate) -> str:
    """Canonical literal-free form of a parameterizable predicate: type
    classes + bucket size + bound presence/inclusivity — everything that
    still selects a distinct traced program, nothing that doesn't."""
    classes = _scalar_classes(p.values) or []
    if p.ptype in (PredicateType.IN, PredicateType.NOT_IN):
        tclass = classes[0] if classes else "?"
        return f"?set[{tclass}x{bucket_size(len(p.values))}]"
    if p.ptype is PredicateType.RANGE:
        lo = "" if p.lower is None else (_type_class(p.lower) or "?")
        hi = "" if p.upper is None else (_type_class(p.upper) or "?")
        li = "[" if p.lower_inclusive else "("
        ui = "]" if p.upper_inclusive else ")"
        return f"?{li}{lo},{hi}{ui}"
    return f"?{','.join(classes)}"


def predicate_shape_fp(p: Predicate, info: Optional[ColumnInfo]) -> str:
    ok, _reason = audit_predicate(p, info)
    if not ok:
        return p.fingerprint()
    return f"{p.ptype.value}:{p.lhs.fingerprint()}:{_slot(p)}"


def _filter_shape_fp(node: Optional[FilterNode], info: Optional[ColumnInfo]) -> str:
    if node is None:
        return ""
    if node.op is FilterOp.PRED:
        return predicate_shape_fp(node.predicate, info)
    return f"{node.op.value}({';'.join(_filter_shape_fp(c, info) for c in node.children)})"


def _host_info(_name: str) -> ColumnShape:
    """Permissive provider for host-evaluated trees (HAVING runs in reduce
    from the live ctx; nothing it holds is ever traced)."""
    return ColumnShape(True, False, False, False)


def _canon_option(v: Any) -> Any:
    """Option values canonicalized for the shape key: ndarray payloads
    (sketch-binding __dictvals__) reduce to shape+dtype — the companion
    __dictfp__ already identifies the content."""
    shape = getattr(v, "shape", None)
    dtype = getattr(v, "dtype", None)
    if shape is not None and dtype is not None:
        return f"ndarray{tuple(shape)}:{dtype}"
    return v


def params_structure(params: Any) -> Tuple:
    """Structural signature of a params pytree: sorted (key, dtype, shape)
    per leaf, nested dicts recursed.  Two param dicts with equal structure
    replay one traced program; the engines compare a shape-cache hit's
    rebuilt params against the cached plan's before reusing its compiled
    fn — the safety net under the audit."""
    import numpy as np

    out: List[Tuple] = []
    for k in sorted(params):
        v = params[k]
        if isinstance(v, dict):
            out.append((k, params_structure(v)))
        else:
            arr = np.asarray(v)
            out.append((k, str(arr.dtype), tuple(arr.shape)))
    return tuple(out)


def shape_fingerprint(ctx: QueryContext, column_info: Optional[ColumnInfo] = None) -> str:
    """Literal-canonicalized twin of QueryContext.fingerprint().  Queries
    with equal shape fingerprints (against equal segment signatures and
    backend) trace the same program; literals ride the params pytree.  The
    `trace` option is excluded (spans are host-side), and LIMIT/OFFSET
    canonicalize to slots (applied host-side in reduce)."""
    opts = sorted(
        (k, _canon_option(v)) for k, v in ctx.options.items() if k != "trace"
    )
    parts = [
        "shape1",  # versioned prefix: never collides with full fingerprints
        ctx.table,
        "|".join(j.fingerprint() for j in ctx.joins),
        "|".join(s.fingerprint() for s in ctx.select_list),
        _filter_shape_fp(ctx.filter, column_info),
        "|".join(g.fingerprint() for g in ctx.group_by),
        _filter_shape_fp(ctx.having, _host_info),
        "|".join(f"{o.expr.fingerprint()}:{o.ascending}" for o in ctx.order_by),
        "|".join(a.fingerprint() for a in ctx.extra_aggregations),
        "?limit" if ctx.limit is not None else "",
        "?offset",
        str(opts),
        "|".join(f"{op}:{al}:{c.fingerprint()}" for op, al, c in ctx.set_ops),
    ]
    return "\x1f".join(parts)
