"""Query IR: expressions, predicates, filter tree, query context.

Reference parity: the Thrift query IR PinotQuery/Expression
(pinot-common/src/thrift/query.thrift:21,57) and pinot-core's QueryContext
(pinot-core/.../core/query/request/context/QueryContext.java) — the engine's
internal representation that the SQL parser produces and the planner consumes.

Re-design: one small immutable tree; hashable/fingerprintable so compiled
kernels can be cached by (query shape, segment layout) — the TPU analog of
Pinot's plan cache by query shape (SURVEY.md section 7 design stance).
"""
from __future__ import annotations

import enum
from dataclasses import dataclass, field as dc_field
from typing import Any, Dict, List, Optional, Sequence, Tuple, Union


# ---------------------------------------------------------------------------
# Expressions
# ---------------------------------------------------------------------------
class ExprKind(enum.Enum):
    COLUMN = "COLUMN"
    LITERAL = "LITERAL"
    CALL = "CALL"


@dataclass(frozen=True)
class Expr:
    """Expression node (query.thrift Expression analog).

    kind COLUMN: name in `op`.
    kind LITERAL: python value in `value`.
    kind CALL: function name in `op`, children in `args` (arithmetic,
    transform functions, and aggregation calls share this node type, exactly
    like Pinot's FunctionContext)."""

    kind: ExprKind
    op: str = ""
    value: Any = None
    args: Tuple["Expr", ...] = ()

    # -- constructors ----------------------------------------------------
    @staticmethod
    def col(name: str) -> "Expr":
        return Expr(ExprKind.COLUMN, op=name)

    @staticmethod
    def lit(value: Any) -> "Expr":
        return Expr(ExprKind.LITERAL, value=value)

    @staticmethod
    def call(op: str, *args: "Expr") -> "Expr":
        return Expr(ExprKind.CALL, op=op.lower(), args=tuple(args))

    # -- helpers ---------------------------------------------------------
    @property
    def is_column(self) -> bool:
        return self.kind is ExprKind.COLUMN

    @property
    def is_literal(self) -> bool:
        return self.kind is ExprKind.LITERAL

    def columns(self) -> List[str]:
        if self.kind is ExprKind.COLUMN:
            return [self.op]
        out: List[str] = []
        for a in self.args:
            out.extend(a.columns())
        return out

    def fingerprint(self) -> str:
        if self.kind is ExprKind.COLUMN:
            return f"c:{self.op}"
        if self.kind is ExprKind.LITERAL:
            return f"l:{self.value!r}"
        return f"f:{self.op}({','.join(a.fingerprint() for a in self.args)})"

    def __str__(self) -> str:
        if self.kind is ExprKind.COLUMN:
            return self.op
        if self.kind is ExprKind.LITERAL:
            return repr(self.value)
        return f"{self.op}({', '.join(str(a) for a in self.args)})"


def map_expr_columns(e: "Expr", fn) -> "Expr":
    """Rewrite COLUMN leaves via fn(Expr) -> Expr (identity-preserving)."""
    if e.kind is ExprKind.COLUMN:
        return fn(e)
    if e.kind is ExprKind.CALL:
        new_args = tuple(map_expr_columns(a, fn) for a in e.args)
        if new_args != e.args:
            return Expr(ExprKind.CALL, op=e.op, value=e.value, args=new_args)
    return e


def map_filter_columns(node: Optional["FilterNode"], fn) -> Optional["FilterNode"]:
    import dataclasses as _dc

    if node is None:
        return None
    if node.op is FilterOp.PRED:
        p = node.predicate
        new_lhs = map_expr_columns(p.lhs, fn)
        if new_lhs is not p.lhs:
            return FilterNode.pred(_dc.replace(p, lhs=new_lhs))
        return node
    return FilterNode(
        node.op,
        children=tuple(map_filter_columns(c, fn) for c in node.children),
        predicate=node.predicate,
    )


# ---------------------------------------------------------------------------
# Predicates & filter tree
# ---------------------------------------------------------------------------
class PredicateType(enum.Enum):
    EQ = "EQ"
    NEQ = "NEQ"
    IN = "IN"
    NOT_IN = "NOT_IN"
    RANGE = "RANGE"  # lo/hi with inclusivity flags; half-open forms of >,>=,<,<=,BETWEEN
    REGEXP_LIKE = "REGEXP_LIKE"
    LIKE = "LIKE"
    IS_NULL = "IS_NULL"
    IS_NOT_NULL = "IS_NOT_NULL"
    TEXT_MATCH = "TEXT_MATCH"
    JSON_MATCH = "JSON_MATCH"
    VECTOR_SIMILARITY = "VECTOR_SIMILARITY"


@dataclass(frozen=True)
class Predicate:
    """Leaf predicate over one expression (pinot-core predicate analog:
    .../core/query/request/context/predicate/)."""

    ptype: PredicateType
    lhs: Expr
    # EQ/NEQ: values[0]; IN/NOT_IN: values tuple; REGEXP/LIKE/TEXT/JSON: pattern.
    values: Tuple[Any, ...] = ()
    # RANGE bounds: None = unbounded.
    lower: Any = None
    upper: Any = None
    lower_inclusive: bool = True
    upper_inclusive: bool = True

    def fingerprint(self) -> str:
        return (
            f"{self.ptype.value}:{self.lhs.fingerprint()}:{self.values!r}:"
            f"{self.lower!r}:{self.upper!r}:{self.lower_inclusive}:{self.upper_inclusive}"
        )

    def __str__(self) -> str:
        if self.ptype is PredicateType.RANGE:
            lo = f"{self.lower!r} {'<=' if self.lower_inclusive else '<'} " if self.lower is not None else ""
            hi = f" {'<=' if self.upper_inclusive else '<'} {self.upper!r}" if self.upper is not None else ""
            return f"{lo}{self.lhs}{hi}"
        return f"{self.lhs} {self.ptype.value} {self.values!r}"


class FilterOp(enum.Enum):
    AND = "AND"
    OR = "OR"
    NOT = "NOT"
    PRED = "PRED"


@dataclass(frozen=True)
class FilterNode:
    """Boolean filter tree (FilterContext analog)."""

    op: FilterOp
    children: Tuple["FilterNode", ...] = ()
    predicate: Optional[Predicate] = None

    @staticmethod
    def pred(p: Predicate) -> "FilterNode":
        return FilterNode(FilterOp.PRED, predicate=p)

    @staticmethod
    def and_(*children: "FilterNode") -> "FilterNode":
        return FilterNode(FilterOp.AND, children=tuple(children))

    @staticmethod
    def or_(*children: "FilterNode") -> "FilterNode":
        return FilterNode(FilterOp.OR, children=tuple(children))

    @staticmethod
    def not_(child: "FilterNode") -> "FilterNode":
        return FilterNode(FilterOp.NOT, children=(child,))

    def fingerprint(self) -> str:
        if self.op is FilterOp.PRED:
            return self.predicate.fingerprint()
        return f"{self.op.value}({';'.join(c.fingerprint() for c in self.children)})"

    def predicates(self) -> List[Predicate]:
        if self.op is FilterOp.PRED:
            return [self.predicate]
        out: List[Predicate] = []
        for c in self.children:
            out.extend(c.predicates())
        return out

    def columns(self) -> List[str]:
        out: List[str] = []
        for p in self.predicates():
            out.extend(p.lhs.columns())
        return out


# ---------------------------------------------------------------------------
# Aggregations & query context
# ---------------------------------------------------------------------------
@dataclass(frozen=True)
class AggregationSpec:
    """One aggregation call, optionally filtered (FILTER(WHERE ...) clause —
    Pinot's filtered aggregations, AggregationPlanNode filtered variants)."""

    function: str  # lowercase: count/sum/min/max/avg/distinctcount/...
    expr: Optional[Expr]  # None for COUNT(*)
    filter: Optional[FilterNode] = None
    # extra literal args, e.g. percentile rank, HLL log2m
    literal_args: Tuple[Any, ...] = ()
    # extra EXPRESSION args beyond the first (LASTWITHTIME's time column)
    extra_exprs: Tuple[Expr, ...] = ()

    def fingerprint(self) -> str:
        e = self.expr.fingerprint() if self.expr else "*"
        f = self.filter.fingerprint() if self.filter else ""
        x = "|".join(a.fingerprint() for a in self.extra_exprs)
        return f"{self.function}({e};{x})[{f}]{self.literal_args!r}"

    def __str__(self) -> str:
        return f"{self.function}({self.expr if self.expr else '*'})"


@dataclass(frozen=True)
class OrderByExpr:
    expr: Expr
    ascending: bool = True
    nulls_last: bool = True


@dataclass(frozen=True)
class WindowSpec:
    """One window-function select item — fn(...) OVER (PARTITION BY ...
    ORDER BY ... [ROWS|RANGE frame]) (reference: WindowAggregateOperator,
    pinot-query-runtime/.../runtime/operator/WindowAggregateOperator.java,
    value functions under .../operator/window/value/, frames per
    WindowFrame.java).

    Functions: row_number/rank/dense_rank/ntile (ranking), lag/lead/
    first_value/last_value (value), sum/count/avg/min/max/bool_and/bool_or
    (aggregate).  literal_args carries NTILE's bucket count and LAG/LEAD's
    (offset, default)."""

    function: str
    expr: Optional[Expr]
    partition_by: Tuple[Expr, ...] = ()
    order_by: Tuple[OrderByExpr, ...] = ()
    # "range_all" = no frame clause (standard default: whole partition, or
    # RANGE UNBOUNDED PRECEDING..CURRENT ROW when ORDER BY is present);
    # "rows"/"range" = explicit frame with signed bounds; "rows_cumulative"
    # = legacy alias for rows(None, 0)
    frame: str = "range_all"
    # signed bound offsets: None = UNBOUNDED, 0 = CURRENT ROW, -k = k
    # PRECEDING, +k = k FOLLOWING (ROWS: row counts; RANGE: order-key deltas)
    frame_lo: Optional[float] = None
    frame_hi: Optional[float] = None
    literal_args: Tuple = ()

    def fingerprint(self) -> str:
        e = self.expr.fingerprint() if self.expr else "*"
        p = "|".join(x.fingerprint() for x in self.partition_by)
        o = "|".join(f"{x.expr.fingerprint()}:{x.ascending}" for x in self.order_by)
        f = f"{self.frame}:{self.frame_lo}:{self.frame_hi}"
        la = ",".join(repr(a) for a in self.literal_args)
        return f"win:{self.function}({e};{la})p[{p}]o[{o}]f[{f}]"

    def __str__(self) -> str:
        return f"{self.function}() OVER (...)"


@dataclass(frozen=True)
class GapfillSpec:
    """GAPFILL(time_expr, start, end, step [, FILL(target, 'mode')...
    [, TIMESERIESON(key...)]]) — post-reduce time-bucket gap filling
    (reference: pinot-core/.../core/query/reduce/GapfillProcessor.java,
    SumAvgGapfillProcessor.java, GapfillUtils fill modes).

    Buckets [start, end) stepping by step are emitted for every observed
    series (the TIMESERIESON key combination); missing cells fill per mode:
    FILL_PREVIOUS_VALUE carries the series' last seen value, default NULL."""

    time_expr: Expr
    start: int
    end: int
    step: int
    fills: Tuple[Tuple[Expr, str], ...] = ()  # (target, FILL_* mode)
    series: Tuple[Expr, ...] = ()


@dataclass(frozen=True)
class Subquery:
    """IN (SELECT ...) marker carried inside Predicate.values until the
    engine resolves it (semi-join rewrite, reference: Calcite semi-join /
    IN-subquery planning in QueryEnvironment)."""

    ctx: "QueryContext"

    def __repr__(self) -> str:
        return f"Subquery({self.ctx.table})"


@dataclass(frozen=True)
class JoinClause:
    """One JOIN ... ON a = b clause (MSE JoinNode analog — the logical join
    of pinot-query-planner's LogicalJoin; only equi-joins, like the
    reference's HashJoinOperator key requirement,
    pinot-query-runtime/.../runtime/operator/HashJoinOperator.java)."""

    table: str
    alias: Optional[str]
    join_type: str  # "inner" | "left"
    left_key: Expr
    right_key: Expr

    def fingerprint(self) -> str:
        return (
            f"join:{self.join_type}:{self.table}:{self.alias or ''}:"
            f"{self.left_key.fingerprint()}={self.right_key.fingerprint()}"
        )


@dataclass
class QueryContext:
    """Everything the engine needs for one query (QueryContext.java analog).

    select_list entries are Expr (projection / group column refs) or
    AggregationSpec.  For group-by queries, Pinot requires select expressions
    to be group keys or aggregations — same constraint here."""

    table: str
    select_list: List[Union[Expr, AggregationSpec]]
    select_aliases: List[Optional[str]] = dc_field(default_factory=list)
    table_alias: Optional[str] = None
    joins: List[JoinClause] = dc_field(default_factory=list)
    filter: Optional[FilterNode] = None
    group_by: List[Expr] = dc_field(default_factory=list)
    having: Optional[FilterNode] = None
    order_by: List[OrderByExpr] = dc_field(default_factory=list)
    limit: int = 10
    offset: int = 0
    # SQL `SET key=value` per-query options (QueryOptionsUtils analog):
    # numGroupsLimit, enableNullHandling, timeoutMs, maxExecutionThreads...
    options: Dict[str, Any] = dc_field(default_factory=dict)
    # aggregations referenced ONLY by ORDER BY/HAVING (not selected) — Pinot
    # allows `GROUP BY d ORDER BY SUM(v)` without selecting SUM(v); these are
    # computed alongside select aggregations but excluded from output rows.
    extra_aggregations: List[AggregationSpec] = dc_field(default_factory=list)
    # set operations chained onto this query: (op, all_flag, rhs ctx) with
    # op in {"union", "intersect", "except"} (MSE SetOperator analog)
    set_ops: List[tuple] = dc_field(default_factory=list)
    # time-bucket gap filling applied post-reduce (GapfillProcessor analog)
    gapfill: Optional[GapfillSpec] = None

    @property
    def aggregations(self) -> List[AggregationSpec]:
        return [s for s in self.select_list if isinstance(s, AggregationSpec)] + list(
            self.extra_aggregations
        )

    @property
    def windows(self) -> List["WindowSpec"]:
        return [s for s in self.select_list if isinstance(s, WindowSpec)]

    @property
    def is_aggregate(self) -> bool:
        return bool(self.aggregations) or bool(self.group_by)

    @property
    def null_handling(self) -> bool:
        # SQL-standard null semantics by default (delta from Pinot, whose
        # legacy default treats stored placeholder values as values; Pinot's
        # modern enableNullHandling=true matches our default).
        return bool(self.options.get("enableNullHandling", True))

    @property
    def num_groups_limit(self) -> int:
        # InstancePlanMakerImplV2 numGroupsLimit analog (safety valve on the
        # number of groups TRACKED; results may be incomplete beyond it).
        return int(self.options.get("numGroupsLimit", 100_000))

    @property
    def max_dense_groups(self) -> int:
        # Key-space bound for the dense group-table kernel; above it the
        # sparse path runs.  Memory knob, distinct from numGroupsLimit.
        return int(self.options.get("maxDenseGroups", 1 << 20))

    def column_names_out(self) -> List[str]:
        out = []
        for i, s in enumerate(self.select_list):
            alias = self.select_aliases[i] if i < len(self.select_aliases) else None
            out.append(alias if alias else str(s))
        return out

    def shape_fingerprint(self, column_info=None) -> str:
        """Literal-canonicalized fingerprint for compile caches: queries
        that differ only in parameterizable predicate literals share one
        key (query/shape.py holds the per-predicate audit).  `column_info`
        is a per-table metadata provider (shape.column_info_from); without
        it every filter literal conservatively stays in the key."""
        from pinot_tpu.query.shape import shape_fingerprint

        return shape_fingerprint(self, column_info)

    def fingerprint(self) -> str:
        parts = [
            self.table,
            "|".join(j.fingerprint() for j in self.joins),
            "|".join(s.fingerprint() for s in self.select_list),
            self.filter.fingerprint() if self.filter else "",
            "|".join(g.fingerprint() for g in self.group_by),
            self.having.fingerprint() if self.having else "",
            "|".join(f"{o.expr.fingerprint()}:{o.ascending}" for o in self.order_by),
            "|".join(a.fingerprint() for a in self.extra_aggregations),
            str(self.limit),
            str(self.offset),
            str(sorted(self.options.items())),
            "|".join(f"{op}:{al}:{c.fingerprint()}" for op, al, c in self.set_ops),
        ]
        return "\x1f".join(parts)
