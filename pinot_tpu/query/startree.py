"""Star-tree query routing + execution over collapsed level tables.

Reference parity: Pinot injects the star-tree when a group-by's filter and
group columns fall inside the tree's dimension split order and every
aggregation has a matching function-column pair
(AggregationPlanNode.buildAggregationInfoWithStarTree,
pinot-core/.../core/plan/AggregationPlanNode.java:109;
StarTreeFilterOperator traversal, .../core/startree/operator/
StarTreeFilterOperator.java:90,218; StarTreeAggregationExecutor /
StarTreeGroupByExecutor, .../core/startree/executor/).

Re-design (see indexes/startree.py): tree traversal becomes level selection —
pick the smallest prefix level covering the query's dimension set, compile the
ordinary FilterCompiler against the level facade (parent dictionaries, so the
result merges with raw-scan segments in one key space), and combine the
pre-aggregated partial FIELDS per group.  Rows scanned = collapsed level rows,
the docs-scanned win the reference gets from skipping to aggregated docs.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import numpy as np

from pinot_tpu.indexes.startree import scatter_combine
from pinot_tpu.query import planner
from pinot_tpu.query.filter import FilterCompiler
from pinot_tpu.query.functions import for_spec
from pinot_tpu.query.ir import QueryContext
from pinot_tpu.query.result import (
    AggSegmentResult,
    ExecutionStats,
    GroupBySegmentResult,
)

_IDENT = {"count": 0, "sum": 0, "sumsq": 0.0, "min": np.inf, "max": -np.inf}


def pick_tree(ctx: QueryContext, segment) -> Optional[Tuple[object, int]]:
    """(StarTreeIndex, level k) when a tree of this segment can answer ctx."""
    trees = getattr(segment, "indexes", {}).get("startree", {})
    if not trees or ctx.joins or not ctx.is_aggregate:
        return None
    for g in ctx.group_by:
        if not g.is_column or g.op == "*":
            return None
    group_cols = {g.op for g in ctx.group_by}
    filter_cols = set(ctx.filter.columns()) if ctx.filter else set()
    agg_filter_cols = set()
    for spec in ctx.aggregations:
        if spec.expr is not None and not spec.expr.is_column:
            return None
        if spec.filter is not None:
            agg_filter_cols |= set(spec.filter.columns())
    dims_used = group_cols | filter_cols | agg_filter_cols
    if "*" in dims_used:
        return None

    best: Optional[Tuple[object, int]] = None
    for st in trees.values():
        k = st.level_for(dims_used)
        if k is None:
            continue
        ok = True
        for spec in ctx.aggregations:
            col = spec.expr.op if spec.expr is not None else "*"
            if col != "*" and segment.column(col).nulls is not None:
                ok = False  # star count fields assume null-free metrics
                break
            if not st.has_fields(spec.function, col):
                ok = False
                break
        if not ok:
            continue
        if best is None or st.levels[k].num_rows < best[0].levels[best[1]].num_rows:
            best = (st, k)
    return best


def execute_star(ctx: QueryContext, segment, st, k):
    """Run ctx against star level k; returns (SegmentResult, ExecutionStats).

    Returns None when a runtime limit (composite key overflow) forces the
    regular scan path after all."""
    lvl = st.levels[k]
    view = lvl.facade(segment)
    stats = ExecutionStats(
        num_segments_queried=1,
        num_segments_processed=1,
        num_docs_scanned=lvl.num_rows,
        total_docs=segment.num_docs,
    )

    fc = FilterCompiler(view, null_handling=False)
    filter_fn = fc.compile(ctx.filter)
    agg_specs = list(ctx.aggregations)
    agg_filter_fns = [
        fc.compile(s.filter) if s.filter is not None else None for s in agg_specs
    ]

    # level tables are collapsed-small: evaluate the compiled mask closures
    # eagerly (jnp ops accept numpy inputs) and finish host-side
    cols: Dict[str, Dict[str, np.ndarray]] = {}
    for name, c in view.columns.items():
        entry: Dict[str, np.ndarray] = {}
        if c.codes is not None:
            entry["codes"] = c.codes
            dv = c.dictionary.device_values() if c.dictionary else None
            if dv is not None:
                entry["dict"] = dv
        if c.values is not None:
            entry["values"] = c.values
        cols[name] = entry
    tmask = np.asarray(filter_fn(cols, fc.params)[0])
    agg_masks = [
        tmask if fn is None else (tmask & np.asarray(fn(cols, fc.params)[0]))
        for fn in agg_filter_fns
    ]

    counts = lvl.fields[("*", "count")]
    aggs = [for_spec(s) for s in agg_specs]
    stats.add_index_uses(fc.index_uses)
    stats.add_index_uses([("/".join(st.split_order[:k]) or "*", "startree")])

    def field_source(spec, kind) -> np.ndarray:
        if kind == "count":
            return counts
        return lvl.fields[(spec.expr.op, kind)]

    if not ctx.group_by:
        partials: List[Dict[str, np.ndarray]] = []
        for spec, fn, m in zip(agg_specs, aggs, agg_masks):
            p: Dict[str, np.ndarray] = {}
            for fname, kind in fn.field_kinds.items():
                src = field_source(spec, kind)
                sel = src[m]
                if kind in ("count", "sum", "sumsq"):
                    p[fname] = sel.sum() if len(sel) else np.asarray(_IDENT[kind], src.dtype)
                elif kind == "min":
                    p[fname] = sel.min() if len(sel) else np.asarray(np.inf)
                else:
                    p[fname] = sel.max() if len(sel) else np.asarray(-np.inf)
            partials.append(p)
        return AggSegmentResult(partials=partials), stats

    # group-by: pack level dim codes into composite keys (same packing as the
    # raw-scan paths so decoded keys land in the same space)
    group_dims = [planner._group_dim(g, view, False) for g in ctx.group_by]
    packed = np.zeros(lvl.num_rows, dtype=np.int64)
    scale = 1
    for gd in reversed(group_dims):
        if scale > (1 << 62) // max(1, gd.cardinality):
            return None  # >63-bit composite key: let the scan path handle it
        c = view.column(gd.name)
        code = (
            c.codes.astype(np.int64)
            if gd.kind == "dict"
            else c.values.astype(np.int64) - gd.base
        )
        packed += code * scale
        scale *= gd.cardinality

    sel = np.nonzero(tmask)[0]
    uniq, inverse_sel = np.unique(packed[sel], return_inverse=True)
    if len(uniq) > ctx.num_groups_limit:
        keep = inverse_sel < ctx.num_groups_limit
        sel = sel[keep]
        inverse_sel = inverse_sel[keep]
        uniq = uniq[: ctx.num_groups_limit]
    n_groups = len(uniq)
    keys = planner.decode_packed_keys(group_dims, uniq)

    partials = []
    for spec, fn, m in zip(agg_specs, aggs, agg_masks):
        msel = m[sel]
        p: Dict[str, np.ndarray] = {}
        for fname, kind in fn.field_kinds.items():
            src = field_source(spec, kind)[sel]
            p[fname] = scatter_combine(kind, inverse_sel[msel], src[msel], n_groups)
        partials.append(p)
    stats.num_groups = n_groups
    return GroupBySegmentResult(keys=keys, partials=partials, dense=None), stats


def try_startree(ctx: QueryContext, segment):
    """Entry point for executor: result when a star-tree served the query."""
    opt = ctx.options.get("useStarTree", True)
    if (not opt) or (isinstance(opt, str) and opt.lower() in ("false", "0")):
        return None
    # upsert segments: pre-aggregated levels can't honor per-row validDocIds
    # (the reference likewise excludes star-trees from upsert tables)
    if getattr(segment, "valid_docs", None) is not None:
        return None
    pick = pick_tree(ctx, segment)
    if pick is None:
        return None
    return execute_star(ctx, segment, pick[0], pick[1])
