"""EXPLAIN ANALYZE: join the static operator tree with measured execution.

Reference parity: Pinot 1.1's `EXPLAIN ANALYZE` (multi-stage) returns the
operator tree annotated with actual stats instead of the planned shape.
Re-design: the query executes normally with tracing forced; the static
EXPLAIN rows (engine._explain) join against the finished span tree by
stage, and the full span tree is appended below the operator rows so
per-server / per-launch timing is visible in the same table.

Stage attribution is approximate by construction — the engine pipelines
launches, so "AGGREGATE time" is the sum of its launch/dispatch spans, not
an exclusive wall-clock slice.  The TRACE rows underneath are the ground
truth; the operator-row ms are the navigation aid.
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

from pinot_tpu.query.result import ResultTable

ANALYZE_COLUMNS = [
    "Operator",
    "Operator_Id",
    "Parent_Id",
    "Actual_Ms",
    "Rows",
    # kernel cost accounting (utils/perf.py): cost-model bytes/flops the
    # stage's compiled kernels streamed, and achieved-vs-peak HBM roofline %
    "Bytes",
    "Flops",
    "Roofline_Pct",
]

# span names carrying per-kernel cost attrs (SSE/server `launch:*` spans,
# the dist engine's `launches` section) and the fence spans carrying the
# measured roofline — the two sets never double-count inside one trace
_SCAN_COST_SPANS = ("launch", "launches")
_ROOFLINE_SPANS = ("device_wait", "launches")

# operator-name prefix -> trace span names whose ms sum to that stage
# (a span matches a candidate by exact name or "<candidate>:" prefix)
_STAGE_SPANS: Tuple[Tuple[str, Tuple[str, ...]], ...] = (
    ("BROKER_REDUCE", ("reduce",)),
    ("COMBINE", ("collect", "device_wait", "sparse_merge", "scatter", "realtime")),
    ("AGGREGATE", ("launch", "dispatch", "run", "launches")),
    ("GROUP_BY", ("launch", "dispatch", "run", "launches")),
    ("SELECT", ("launch", "dispatch", "run", "launches")),
    ("PROJECT", ()),
    ("FILTER", ()),
)


def _span_ms_index(trace: Optional[Dict[str, Any]]) -> Dict[str, float]:
    """Total ms per span name over the whole tree (grafted subtrees
    included); names like 'launch:seg_3' also accumulate under 'launch'."""
    out: Dict[str, float] = {}

    def walk(node: Optional[Dict[str, Any]]) -> None:
        if not node:
            return
        name = node.get("name", "")
        ms = float(node.get("ms", 0.0))
        out[name] = out.get(name, 0.0) + ms
        base = name.split(":", 1)[0]
        if base != name:
            out[base] = out.get(base, 0.0) + ms
        for c in node.get("children", ()):
            walk(c)

    walk(trace)
    return out


def _stage_ms(op_name: str, index: Dict[str, float]) -> Optional[float]:
    for prefix, candidates in _STAGE_SPANS:
        if not op_name.startswith(prefix):
            continue
        vals = [index[c] for c in candidates if c in index]
        return round(sum(vals), 3) if vals else None
    return None


def _stage_rows(op_name: str, executed: ResultTable) -> Optional[int]:
    s = executed.stats
    if op_name.startswith("BROKER_REDUCE") or op_name.startswith("SELECT"):
        return len(executed.rows)
    if op_name.startswith(("COMBINE", "AGGREGATE", "GROUP_BY")):
        return s.num_groups if s.num_groups else len(executed.rows)
    if op_name.startswith(("PROJECT", "FILTER")):
        return s.num_docs_scanned
    return None


def _attr_summary(attrs: Dict[str, Any]) -> str:
    parts = [f"{k}={v}" for k, v in attrs.items() if not isinstance(v, (dict, list))]
    return ", ".join(parts)


def _span_cost_index(
    trace: Optional[Dict[str, Any]],
) -> Tuple[Dict[str, float], Dict[str, float], Dict[str, float]]:
    """Per-span-base-name sums of the kernelBytes/kernelFlops attrs and the
    max rooflinePct seen — the cost twin of _span_ms_index."""
    bytes_by: Dict[str, float] = {}
    flops_by: Dict[str, float] = {}
    roof_by: Dict[str, float] = {}

    def walk(node: Optional[Dict[str, Any]]) -> None:
        if not node:
            return
        attrs = node.get("attrs", {})
        base = node.get("name", "").split(":", 1)[0]
        for key, acc in (("kernelBytes", bytes_by), ("kernelFlops", flops_by)):
            v = attrs.get(key)
            if isinstance(v, (int, float)):
                acc[base] = acc.get(base, 0.0) + float(v)
        roof = attrs.get("rooflinePct")
        if isinstance(roof, (int, float)):
            roof_by[base] = max(roof_by.get(base, 0.0), float(roof))
        for c in node.get("children", ()):
            walk(c)

    walk(trace)
    return bytes_by, flops_by, roof_by


def _stage_cost(
    op_name: str,
    executed: ResultTable,
    bytes_by: Dict[str, float],
    flops_by: Dict[str, float],
    roof_by: Dict[str, float],
) -> Tuple[Optional[float], Optional[float], Optional[float]]:
    """(Bytes, Flops, Roofline_Pct) for one operator row: the scan stage
    carries its launch-span cost sums + the fence-measured roofline; the
    root BROKER_REDUCE row carries the query totals from ExecutionStats."""
    s = executed.stats
    if op_name.startswith("BROKER_REDUCE"):
        roof = None
        if s.kernel_bytes and s.device_ms:
            from pinot_tpu.utils.perf import roofline_pct

            r = roofline_pct(s.kernel_bytes, s.device_ms / 1000.0)
            roof = round(r, 2) if r is not None else None
        return (s.kernel_bytes or None, s.kernel_flops or None, roof)
    if op_name.startswith(("AGGREGATE", "GROUP_BY", "SELECT", "COMBINE")):
        b = sum(bytes_by.get(c, 0.0) for c in _SCAN_COST_SPANS)
        f = sum(flops_by.get(c, 0.0) for c in _SCAN_COST_SPANS)
        roofs = [roof_by[c] for c in _ROOFLINE_SPANS if c in roof_by]
        if op_name.startswith("COMBINE"):
            # the combine row owns the fence: show where the device time
            # went (roofline) without re-counting the scan's bytes
            return (None, None, round(max(roofs), 2) if roofs else None)
        return (b or None, f or None, round(max(roofs), 2) if roofs else None)
    return (None, None, None)


def analyze_result(static: ResultTable, executed: ResultTable) -> ResultTable:
    """Static EXPLAIN rows + Actual_Ms/Rows + per-operator kernel cost
    (Bytes/Flops/Roofline_Pct), followed by the measured span tree as
    TRACE(...) rows parented under the operator root."""
    index = _span_ms_index(executed.stats.trace)
    cost_idx = _span_cost_index(executed.stats.trace)
    rows: List[tuple] = []
    for op_name, oid, parent in static.rows:
        b, f, r = _stage_cost(op_name, executed, *cost_idx)
        rows.append(
            (op_name, oid, parent, _stage_ms(op_name, index), _stage_rows(op_name, executed), b, f, r)
        )
    next_id = max((r[1] for r in static.rows), default=0) + 1

    def add_span(node: Dict[str, Any], parent_id: int) -> None:
        nonlocal next_id
        oid = next_id
        next_id += 1
        attrs = node.get("attrs", {})
        label = f"TRACE({node.get('name', '?')})"
        summary = _attr_summary(attrs)
        if summary:
            label += f" [{summary}]"
        docs = attrs.get("docs", attrs.get("docsScanned"))
        rows.append(
            (
                label,
                oid,
                parent_id,
                round(float(node.get("ms", 0.0)), 3),
                docs,
                attrs.get("kernelBytes"),
                attrs.get("kernelFlops"),
                attrs.get("rooflinePct"),
            )
        )
        for c in node.get("children", ()):
            add_span(c, oid)

    if executed.stats.trace:
        add_span(executed.stats.trace, 0)
    return ResultTable(columns=list(ANALYZE_COLUMNS), rows=rows, stats=executed.stats)
