"""Broker reduce: merge per-segment results, HAVING/ORDER BY/LIMIT, format.

Reference parity: BrokerReduceService.reduceOnDataTable
(pinot-core/.../query/reduce/BrokerReduceService.java:65) and its per-shape
reducers (GroupByDataTableReducer, AggregationDataTableReducer,
SelectionDataTableReducer) + PostAggregationHandler/HAVING handling.

Re-design: partials arrive as numpy arrays, not serialized DataTables.  The
group-by merge has two paths:
  * ALIGNED DENSE: when every segment produced a dense group table over the
    SAME key space (shared dictionary fingerprints — always true for stacked/
    aligned tables, M2), merging is pure elementwise array combination; this
    is the shape that becomes a psum over ICI in the distributed engine.
  * GENERIC: decoded-key hash merge (GroupByDataTableReducer's IndexedTable
    analog) for heterogeneous segments.
"""
from __future__ import annotations

import functools
import math
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from pinot_tpu.query.functions import FIELD_COMBINE, combine_field, field_identity, for_spec
from pinot_tpu.query.ir import (
    AggregationSpec,
    Expr,
    FilterNode,
    FilterOp,
    OrderByExpr,
    PredicateType,
    QueryContext,
)
from pinot_tpu.query.result import (
    AggSegmentResult,
    ExecutionStats,
    GroupBySegmentResult,
    ResultTable,
    SelectionSegmentResult,
)


def reduce_results(ctx: QueryContext, results: List[Any], stats: ExecutionStats) -> ResultTable:
    if ctx.is_aggregate and not ctx.group_by:
        return _reduce_aggregation(ctx, results, stats)
    if ctx.group_by:
        return _reduce_groupby(ctx, results, stats)
    return _reduce_selection(ctx, results, stats)


# ---------------------------------------------------------------------------
# Aggregation-only
# ---------------------------------------------------------------------------
def _reduce_aggregation(ctx: QueryContext, results: List[AggSegmentResult], stats: ExecutionStats) -> ResultTable:
    aggs = [for_spec(a).bind_reduce(ctx, a) for a in ctx.aggregations]
    merged: Optional[List[Dict[str, np.ndarray]]] = None
    for r in results:
        if merged is None:
            merged = [dict(p) for p in r.partials]
        else:
            merged = [fn.merge(m, p) for fn, m, p in zip(aggs, merged, r.partials)]
    # finals for every aggregation (selected + hidden extras), then resolve
    # select items — post-aggregation arithmetic evaluates over the env
    specs = list(ctx.aggregations)
    env: Dict[str, Any] = {}
    for i, (spec, fn) in enumerate(zip(specs, aggs)):
        if merged is None:
            val = 0 if fn.name == "count" else None  # all segments pruned
        else:
            val = fn.final(merged[i])
            if not isinstance(val, (list, tuple)):
                val = _scalar(val)
        cell = np.empty(1, dtype=object)  # explicit: np.asarray would
        cell[0] = np.nan if val is None else val  # 2D-ify a list value
        _register_agg_env(env, spec, cell)
    row = []
    for s in ctx.select_list:
        if isinstance(s, AggregationSpec):
            v = env[s.fingerprint()][0]
        else:
            v = _eval_env_expr(s, env, 1)[0]
        row.append(_scalar(v) if not isinstance(v, (str, bytes, list, tuple, type(None))) else v)
    return ResultTable(columns=ctx.column_names_out(), rows=[tuple(row)], stats=stats)


def _scalar(v):
    v = np.asarray(v)
    x = v.item() if v.ndim == 0 else v
    if isinstance(x, float) and (math.isnan(x) or math.isinf(x)):
        return None
    return x


def _register_agg_env(env: Dict[str, Any], spec: AggregationSpec, finals) -> None:
    """Register one aggregation's final array under every fingerprint form
    HAVING/ORDER BY/post-aggregation may reference it by: the spec itself,
    the plain call `sum(v)` (literal args re-attached), and explicit
    `count(*)`.  Shared by the scalar and group-by reducers."""
    env[spec.fingerprint()] = finals
    if spec.filter is None:
        args = list(spec.expr and [spec.expr] or []) + [Expr.lit(a) for a in spec.literal_args]
        env.setdefault(Expr.call(spec.function, *args).fingerprint(), finals)
        if spec.expr is None and not spec.literal_args:
            env.setdefault(Expr.call(spec.function, Expr.col("*")).fingerprint(), finals)


# ---------------------------------------------------------------------------
# Group-by
# ---------------------------------------------------------------------------
def _reduce_groupby(ctx: QueryContext, results: List[GroupBySegmentResult], stats: ExecutionStats) -> ResultTable:
    aggs = [for_spec(a).bind_reduce(ctx, a) for a in ctx.aggregations]
    results = [r for r in results if r is not None]
    if not results:
        return ResultTable(columns=ctx.column_names_out(), rows=[], stats=stats)

    # -- aligned dense fast path ---------------------------------------
    key_spaces = {r.dense.key_space for r in results if r.dense is not None}
    if len(results) > 1 and len(key_spaces) == 1 and all(r.dense is not None for r in results):
        d0 = results[0].dense
        presence = np.zeros_like(d0.presence)
        merged_partials = [
            {f: np.full_like(arr, _ident_like(f, arr)) for f, arr in p.items()}
            if not fn.pairwise_merge
            else None
            for fn, p in zip(aggs, d0.partials)
        ]
        for r in results:
            presence = presence + r.dense.presence
            for ai, (fn, p) in enumerate(zip(aggs, r.dense.partials)):
                if fn.pairwise_merge:
                    # coupled fields (LASTWITHTIME's (t, v)): elementwise
                    # fn.merge over the whole dense table, not per-field
                    cur = merged_partials[ai]
                    merged_partials[ai] = p if cur is None else fn.merge(cur, p)
                    continue
                mp = merged_partials[ai]
                for f in mp:
                    mp[f] = combine_field(f, mp[f], np.asarray(p[f]))
        present = np.nonzero(presence > 0)[0]
        keys = _decode_dense_keys(d0.group_dims, present)
        partials = [{f: arr[present] for f, arr in p.items()} for p in merged_partials]
    elif len(results) == 1:
        keys, partials = results[0].keys, results[0].partials
    else:
        keys, partials = _hash_merge(results, aggs)

    stats.num_groups = len(keys[0]) if keys else 0
    finals = [np.atleast_1d(np.asarray(fn.final(p))) for fn, p in zip(aggs, partials)]

    # fingerprint -> column array, for select/having/order resolution
    env: Dict[str, np.ndarray] = {}
    for g, k in zip(ctx.group_by, keys):
        env[g.fingerprint()] = k
    for spec, f in zip(ctx.aggregations, finals):
        _register_agg_env(env, spec, f)
    # select aliases: ORDER BY/HAVING may reference any select item by alias
    # (covers filtered/literal-arg aggregations the call forms above can't)
    for s, alias in zip(ctx.select_list, ctx.select_aliases):
        if alias:
            fp = s.fingerprint()
            if fp in env:
                env.setdefault(Expr.col(alias).fingerprint(), env[fp])

    # HAVING
    n = len(keys[0]) if keys else 0
    if ctx.having is not None and n:
        mask = _eval_host_filter(ctx.having, env, n)
        keys = [k[mask] for k in keys]
        finals = [f[mask] for f in finals]
        env = {k: v[mask] for k, v in env.items()}
        n = int(mask.sum())

    # output columns in select order (post-aggregation arithmetic resolves
    # against the env of final arrays)
    out_cols: List[np.ndarray] = []
    for s in ctx.select_list:
        out_cols.append(_eval_env_expr(s, env, n) if isinstance(s, Expr) else env[s.fingerprint()])

    rows = _rows_from_columns(out_cols, n)
    if ctx.gapfill is not None:
        rows = _apply_gapfill(ctx, rows)
        if ctx.order_by:
            rows = _order_rows_by_select(ctx, rows)
        rows = rows[ctx.offset: ctx.offset + ctx.limit]
    else:
        rows = _order_and_trim(ctx, rows, [s.fingerprint() for s in ctx.select_list], env, n)
    return ResultTable(columns=ctx.column_names_out(), rows=rows, stats=stats)


def _gapfill_select_pos(ctx, e) -> int:
    """Resolve a GAPFILL argument expression to its select-list position
    (by fingerprint, then by alias name)."""
    fps = [s.fingerprint() for s in ctx.select_list]
    fp = e.fingerprint()
    if fp in fps:
        return fps.index(fp)
    if e.is_column and e.op in ctx.select_aliases:
        return ctx.select_aliases.index(e.op)
    # plain-call form of a selected aggregation: FILL(SUM(v), ...)
    for i, s in enumerate(ctx.select_list):
        if isinstance(s, AggregationSpec) and s.filter is None:
            args = ([s.expr] if s.expr is not None else []) + [Expr.lit(a) for a in s.literal_args]
            if Expr.call(s.function, *args).fingerprint() == fp:
                return i
            if s.expr is None and not s.literal_args and (
                Expr.call(s.function, Expr.col("*")).fingerprint() == fp
            ):
                return i
    raise ValueError(f"GAPFILL references {e}, which is not in the select list")


def _apply_gapfill(ctx, rows: List[tuple]) -> List[tuple]:
    """Time-bucket gap filling over reduced group-by rows — the
    GapfillProcessor contract (pinot-core/.../core/query/reduce/
    GapfillProcessor.java): emit every bucket in [start, end) stepping by
    step for every observed TIMESERIESON key combination; missing cells
    fill per FILL mode (FILL_PREVIOUS_VALUE carries the series' last seen
    value; default NULL).  Buckets outside the range are dropped."""
    gf = ctx.gapfill
    tpos = _gapfill_select_pos(ctx, gf.time_expr)
    spos = [_gapfill_select_pos(ctx, s) for s in gf.series]
    fill_modes = {_gapfill_select_pos(ctx, t): mode for t, mode in gf.fills}
    ncol = len(ctx.select_list)
    cell: Dict[tuple, tuple] = {}
    series_seen: List[tuple] = []
    sset = set()
    for r in rows:
        b = r[tpos]
        if b is None:
            continue
        b = int(b)
        sk = tuple(r[i] for i in spos)
        if sk not in sset:
            sset.add(sk)
            series_seen.append(sk)
        if gf.start <= b < gf.end and (b - gf.start) % gf.step == 0:
            cell[(b, sk)] = r
    if not series_seen:
        series_seen = [()] if not spos else []
    # FILL_DEFAULT_VALUE fills the column's TYPE default (0 for numeric, ""
    # for strings — GapfillUtils.getDefaultValue), inferred from observed
    # values; columns without a FILL spec stay NULL
    defaults: Dict[int, Any] = {}
    for i, mode in fill_modes.items():
        if mode != "FILL_DEFAULT_VALUE":
            continue
        defaults[i] = 0
        for r in rows:
            if r[i] is not None:
                defaults[i] = "" if isinstance(r[i], str) else 0
                break
    prev: Dict[tuple, Dict[int, Any]] = {sk: {} for sk in series_seen}
    out: List[tuple] = []
    for b in range(gf.start, gf.end, gf.step):
        for sk in series_seen:
            r = cell.get((b, sk))
            if r is not None:
                out.append(tuple(b if i == tpos else v for i, v in enumerate(r)))
                for i in range(ncol):
                    prev[sk][i] = r[i]
            else:
                vals = []
                for i in range(ncol):
                    if i == tpos:
                        vals.append(b)
                    elif i in spos:
                        vals.append(sk[spos.index(i)])
                    elif fill_modes.get(i) == "FILL_PREVIOUS_VALUE":
                        vals.append(prev[sk].get(i))
                    elif i in defaults:
                        vals.append(defaults[i])
                    else:
                        vals.append(None)
                out.append(tuple(vals))
    return out


def _order_rows_by_select(ctx, rows: List[tuple]) -> List[tuple]:
    """ORDER BY over already-materialized rows (post-gapfill): each order
    expression must resolve to a select-list position."""
    ord_vals = []
    for ob in ctx.order_by:
        p = _gapfill_select_pos(ctx, ob.expr)
        ord_vals.append(np.asarray([r[p] for r in rows], dtype=object))
    order = _sorted_order(ctx.order_by, ord_vals, len(rows))
    return [rows[i] for i in order]


def _ident_like(field: str, arr: np.ndarray):
    if field == "count":
        return 0
    ident = field_identity(field)
    if np.issubdtype(np.asarray(arr).dtype, np.integer):
        # +-inf identities don't exist for int fields (presence bitmaps, HLL
        # registers, histograms); use the dtype extremes / zero instead
        info = np.iinfo(np.asarray(arr).dtype)
        return {0.0: 0, float("inf"): info.max, float("-inf"): 0}[ident]
    return ident


def _decode_dense_keys(group_dims, present: np.ndarray) -> List[np.ndarray]:
    from pinot_tpu.query.planner import decode_packed_keys

    return decode_packed_keys(group_dims, present)


def _hash_merge(results: List[GroupBySegmentResult], aggs) -> Tuple[List[np.ndarray], List[Dict[str, np.ndarray]]]:
    """Generic keyed merge (IndexedTable upsert analog).

    Fast path: key tuples encode to dense int codes (np.unique per dim) and
    every partial field combines with ONE ufunc scatter (the FIELD_COMBINE
    name contract the dense/psum merges already rely on) — no per-row Python
    upsert.  First-seen key order is preserved.  Pairwise-merge aggregations
    (coupled fields) and incomparable mixed-type keys fall back to the loop."""
    if all(
        not fn.pairwise_merge and all(f in FIELD_COMBINE for f in results[0].partials[ai])
        for ai, fn in enumerate(aggs)
    ):
        merged = _hash_merge_vectorized(results, aggs)
        if merged is not None:
            return merged
    table: Dict[tuple, List[Dict[str, Any]]] = {}
    for r in results:
        n = len(r.keys[0]) if r.keys else 0
        for i in range(n):
            key = tuple(k[i] for k in r.keys)
            partial = [{f: arr[i] for f, arr in p.items()} for p in r.partials]
            cur = table.get(key)
            if cur is None:
                table[key] = partial
            else:
                table[key] = [fn.merge(a, b) for fn, a, b in zip(aggs, cur, partial)]
    keys_out: List[np.ndarray] = []
    ndims = len(results[0].keys)
    all_keys = list(table.keys())
    for d in range(ndims):
        keys_out.append(np.asarray([k[d] for k in all_keys], dtype=object))
    partials_out: List[Dict[str, np.ndarray]] = []
    for ai, fn in enumerate(aggs):
        fields = results[0].partials[ai].keys()
        partials_out.append({f: np.asarray([table[k][ai][f] for k in all_keys]) for f in fields})
    return keys_out, partials_out


def _scatter_init(shape, dtype, op: str):
    """Identity-filled accumulator for one ufunc-scatter combine; every group
    has at least one row, so the identity never reaches the output."""
    if op == "add":
        return np.zeros(shape, dtype=dtype)
    if np.issubdtype(dtype, np.floating):
        fill = np.inf if op == "min" else -np.inf
    elif dtype == np.bool_:
        fill = op == "min"
    else:
        info = np.iinfo(dtype)
        fill = info.max if op == "min" else info.min
    return np.full(shape, fill, dtype=dtype)


def _hash_merge_vectorized(results: List[GroupBySegmentResult], aggs):
    """Returns (keys, partials) in first-seen key order, or None when the
    keys defy np.unique coding (caller falls back to the upsert loop)."""
    ndims = len(results[0].keys)
    total = sum(len(r.keys[0]) if r.keys else 0 for r in results)
    if total == 0 or ndims == 0:
        return None
    cat_keys = [
        np.concatenate([np.asarray(r.keys[d], dtype=object) for r in results])
        for d in range(ndims)
    ]
    cards, invs = [], []
    for d in range(ndims):
        try:
            uniq, inv = np.unique(cat_keys[d], return_inverse=True)
        except TypeError:
            return None
        cards.append(max(1, len(uniq)))
        invs.append(inv.reshape(-1))
    space = 1
    for c in cards:
        space *= c
    if space >= (1 << 62):  # packed composite code must fit int64
        return None
    codes = np.zeros(total, dtype=np.int64)
    for card, inv in zip(cards, invs):
        codes = codes * np.int64(card) + inv.astype(np.int64)
    uniq_codes, first_pos, inv = np.unique(codes, return_index=True, return_inverse=True)
    order = np.argsort(first_pos, kind="stable")  # sorted-unique -> first-seen
    rank = np.empty(len(uniq_codes), dtype=np.int64)
    rank[order] = np.arange(len(uniq_codes))
    g = rank[inv.reshape(-1)]  # row -> output slot
    k = len(uniq_codes)
    keys_out = [cat_keys[d][first_pos[order]] for d in range(ndims)]
    partials_out: List[Dict[str, np.ndarray]] = []
    for ai in range(len(aggs)):
        out: Dict[str, np.ndarray] = {}
        for f in results[0].partials[ai]:
            arr = np.concatenate(
                [np.atleast_1d(np.asarray(r.partials[ai][f])) for r in results]
            )
            if arr.dtype == object:
                return None  # non-numeric partials: upsert loop path
            op = FIELD_COMBINE[f]
            acc = _scatter_init((k,) + arr.shape[1:], arr.dtype, op)
            if op == "add":
                np.add.at(acc, g, arr)
            elif op == "min":
                np.minimum.at(acc, g, arr)
            else:
                np.maximum.at(acc, g, arr)
            out[f] = acc
        partials_out.append(out)
    return keys_out, partials_out


# ---------------------------------------------------------------------------
# Selection
# ---------------------------------------------------------------------------
def _reduce_selection(ctx: QueryContext, results: List[SelectionSegmentResult], stats: ExecutionStats) -> ResultTable:
    results = [r for r in results if r is not None]
    out_names = ctx.column_names_out()
    if not results:
        return ResultTable(columns=out_names, rows=[], stats=stats)
    cols = results[0].columns
    if "*" in out_names:
        # SELECT *: label with the actual gathered columns so dataSchema
        # matches the row arity (window inputs/order keys are internal)
        out_names = [c for c in cols if not (c.startswith("__ord") or c.startswith("__wx_"))]
    arrays = {
        c: np.concatenate([np.asarray(r.arrays[c], dtype=object) for r in results])
        if len(results) > 1
        else np.asarray(results[0].arrays[c], dtype=object)
        for c in cols
    }
    n = len(next(iter(arrays.values()))) if arrays else 0
    # window functions: computed HERE, over the globally merged row set
    # (WindowAggregateOperator analog; whole-partition frames)
    if ctx.windows:
        from pinot_tpu.query.ir import WindowSpec

        for i, s in enumerate(ctx.select_list):
            if isinstance(s, WindowSpec):
                arrays[f"__win{i}"] = _compute_window(s, arrays, n)
    select_cols = [c for c in cols if not (c.startswith("__ord") or c.startswith("__wx_"))]
    rows = _rows_from_columns([arrays[c] for c in select_cols], n)
    if ctx.order_by:
        ord_vals = [arrays[f"__ord{i}"] for i in range(len(ctx.order_by))]
        order = _sorted_order(ctx.order_by, ord_vals, n)
        rows = [rows[i] for i in order]
    rows = rows[ctx.offset: ctx.offset + ctx.limit]
    return ResultTable(columns=out_names, rows=rows, stats=stats)


def _win_lex_key(vals, asc: bool) -> Tuple[np.ndarray, bool]:
    """(sortable float key, is_numeric) for one OVER(ORDER BY) expression:
    numeric values rank numerically, genuine strings by sorted-unique codes.
    Descending flips sign, so 'preceding' is always toward SMALLER keys —
    which makes signed RANGE offsets direction-agnostic.  RANGE offset
    frames are only legal over a numeric key (the caller checks the flag)."""
    a = np.asarray(vals)
    if a.dtype == object:
        try:
            a = a.astype(np.float64)
        except (ValueError, TypeError):
            pass
    if np.issubdtype(a.dtype, np.number):
        a = a.astype(np.float64)
        return (a if asc else -a), True
    _, inv = np.unique(a.astype(str), return_inverse=True)
    inv = inv.astype(np.float64)
    return (inv if asc else -inv), False


_WIN_AGG_FNS = ("sum", "avg", "count", "min", "max", "bool_and", "bool_or")


def _compute_window(spec, arrays: Dict[str, np.ndarray], n: int) -> np.ndarray:
    """One window function over the merged result rows.

    Reference parity: WindowAggregateOperator + the window/value family
    (pinot-query-runtime/.../runtime/operator/window/value/
    LagValueWindowFunction.java, LeadValueWindowFunction.java,
    FirstValueWindowFunction.java, LastValueWindowFunction.java,
    range/NtileWindowFunction.java) with ROWS/RANGE frames per
    WindowFrame.java.

    Partition ids hash the partition-key tuples; within each partition rows
    order by the OVER(ORDER BY ...) keys (stable).  Every frame shape
    reduces to per-row inclusive-exclusive bounds [ws, we) in sorted space;
    sums/counts then resolve via prefix sums, min/max via prefix/suffix
    accumulation (unbounded edge) or per-row slices (bounded frames)."""
    pid = np.zeros(n, dtype=np.int64)
    if spec.partition_by:
        pkeys = [np.asarray(arrays[f"__wx_{p.fingerprint()}"]) for p in spec.partition_by]
        seen: Dict[tuple, int] = {}
        for i in range(n):
            key = tuple(k[i] for k in pkeys)
            pid[i] = seen.setdefault(key, len(seen))
    fn = spec.function
    keyed = [_win_lex_key(arrays[f"__wx_{o.expr.fingerprint()}"], o.ascending) for o in spec.order_by]
    lex = [k for k, _ in keyed]
    lex_numeric = [num for _, num in keyed]
    order = np.lexsort(tuple(reversed([pid] + lex)))
    spid = pid[order]
    idx = np.arange(n)
    starts = np.ones(n, dtype=bool)
    if n > 1:
        starts[1:] = spid[1:] != spid[:-1]
    # partition bounds per sorted row: [start_idx, end_idx)
    ps = idx[starts]
    pe = np.append(ps[1:], n)
    pnum = np.cumsum(starts) - 1
    start_idx = ps[pnum] if n else idx
    end_idx = pe[pnum] if n else idx
    pos0 = idx - start_idx
    plen = end_idx - start_idx
    # peer groups: rows with equal ORDER BY keys (frame CURRENT ROW in RANGE
    # mode, and rank/dense_rank steps)
    peer_flags = starts.copy()
    if lex and n > 1:
        diff = np.zeros(n - 1, dtype=bool)
        for k in lex:
            a = np.asarray(k)[order]
            diff |= ~((a[1:] == a[:-1]) | (np.isnan(a[1:]) & np.isnan(a[:-1])))
        peer_flags[1:] |= diff
    pps = idx[peer_flags]
    ppe = np.append(pps[1:], n)
    ppnum = np.cumsum(peer_flags) - 1
    peer_start = pps[ppnum] if n else idx
    peer_end = ppe[ppnum] if n else idx

    def unsort(sorted_vals, dtype):
        out = np.empty(n, dtype=dtype)
        out[order] = sorted_vals
        return out

    # -- ranking functions (frames do not apply) ------------------------
    if fn in ("row_number", "rank", "dense_rank", "ntile"):
        if fn == "row_number":
            r = pos0 + 1
        elif fn == "rank":
            r = peer_start - start_idx + 1
        elif fn == "dense_rank":
            dc = np.cumsum(peer_flags)
            r = dc - (dc[start_idx] - 1)
        else:  # NTILE(t): first (plen % t) buckets get one extra row
            t = int(spec.literal_args[0])
            q, rem = plen // t, plen % t
            cut = rem * (q + 1)
            r = np.where(
                pos0 < cut,
                pos0 // np.maximum(q + 1, 1),
                rem + (pos0 - cut) // np.maximum(q, 1),
            ) + 1
        return unsort(r.astype(np.int64), np.int64)

    sval = None
    if spec.expr is not None:
        sval = np.asarray(arrays[f"__wx_{spec.expr.fingerprint()}"], dtype=object)[order]

    # -- offset value functions (frames do not apply) -------------------
    if fn in ("lag", "lead"):
        off = int(spec.literal_args[0]) if spec.literal_args else 1
        default = spec.literal_args[1] if len(spec.literal_args) > 1 else None
        src = idx - off if fn == "lag" else idx + off
        valid = (src >= start_idx) & (src < end_idx)
        srcc = np.clip(src, 0, max(n - 1, 0))
        return unsort(np.where(valid, sval[srcc], default), object)

    # -- frame resolution: [ws, we) per sorted row ----------------------
    mode, lo, hi = spec.frame, spec.frame_lo, spec.frame_hi
    if mode == "rows_cumulative":
        mode, lo, hi = "rows", None, 0
    elif mode == "range_all":
        if spec.order_by:
            # SQL default frame with ORDER BY: RANGE UNBOUNDED PRECEDING ..
            # CURRENT ROW (cumulative by peer groups)
            mode, lo, hi = "range", None, 0
        else:
            mode, lo, hi = "rows", None, None  # whole partition
    if mode == "rows":
        ws = start_idx if lo is None else np.maximum(start_idx, idx + int(lo))
        we = end_idx if hi is None else np.minimum(end_idx, idx + int(hi) + 1)
    else:  # range
        if not lex:
            ws, we = start_idx, end_idx
        elif lo in (None, 0) and hi in (None, 0):
            ws = start_idx if lo is None else peer_start
            we = end_idx if hi is None else peer_end
        else:
            if len(lex) != 1:
                raise ValueError("RANGE frame with offsets requires exactly one ORDER BY key")
            if not lex_numeric[0]:
                raise ValueError("RANGE frame with offsets requires a NUMERIC ORDER BY key")
            sk = np.asarray(lex[0], np.float64)[order]
            ws = np.empty(n, dtype=np.int64)
            we = np.empty(n, dtype=np.int64)
            for s, e in zip(ps, pe):  # per partition: vectorized searchsorted
                seg = sk[s:e]
                if lo is None:
                    ws[s:e] = s
                elif lo == 0:
                    ws[s:e] = peer_start[s:e]
                else:
                    ws[s:e] = s + np.searchsorted(seg, seg + float(lo), side="left")
                if hi is None:
                    we[s:e] = e
                elif hi == 0:
                    we[s:e] = peer_end[s:e]
                else:
                    we[s:e] = s + np.searchsorted(seg, seg + float(hi), side="right")
    wsc = np.minimum(ws, we)  # empty frames collapse to zero-width slices

    if fn == "count" and spec.expr is None:  # COUNT(*): frame row count
        return unsort(np.maximum(we - ws, 0).astype(np.int64), np.int64)
    if sval is None:
        raise ValueError(f"window {fn} needs an argument")

    if fn in ("first_value", "last_value"):
        valid = we > ws
        pos = np.clip(np.where(fn == "first_value", wsc, we - 1), 0, max(n - 1, 0))
        return unsort(np.where(valid, sval[pos], None), object)

    # -- numeric frame aggregates ---------------------------------------
    v = np.array([np.nan if x is None else float(x) for x in sval], dtype=np.float64)
    if fn in ("bool_and", "bool_or"):
        v = np.where(np.isnan(v), np.nan, (v != 0).astype(np.float64))
    notnan = ~np.isnan(v)
    cn = np.concatenate([[0], np.cumsum(notnan.astype(np.int64))])
    m = cn[we] - cn[wsc]  # non-null rows in frame
    if fn == "count":
        return unsort(m.astype(np.int64), np.int64)
    if fn in ("sum", "avg"):
        cs = np.concatenate([[0.0], np.cumsum(np.where(notnan, v, 0.0))])
        tot = cs[we] - cs[wsc]
        out_sorted = np.where(m > 0, tot, np.nan)
        if fn == "avg":
            out_sorted = out_sorted / np.maximum(m, 1)
        return unsort(out_sorted, np.float64)
    # min/max family: prefix/suffix accumulation when one edge is the
    # partition bound, per-row slices for doubly-bounded frames
    is_min = fn in ("min", "bool_and")
    acc_op = np.fmin if is_min else np.fmax  # fmin/fmax ignore NaN
    lo_unbounded = bool(np.all(wsc == start_idx))
    hi_unbounded = bool(np.all(we == end_idx))
    out_sorted = np.full(n, np.nan)
    if lo_unbounded:
        pref = np.empty(n, dtype=np.float64)
        for i in range(n):
            pref[i] = v[i] if starts[i] else acc_op(pref[i - 1], v[i])
        sel = we > wsc
        out_sorted[sel] = pref[we[sel] - 1]
    elif hi_unbounded:
        suf = np.empty(n, dtype=np.float64)
        for i in range(n - 1, -1, -1):
            last = (i == n - 1) or starts[i + 1]
            suf[i] = v[i] if last else acc_op(suf[i + 1], v[i])
        sel = we > wsc
        out_sorted[sel] = suf[wsc[sel]]
    else:
        for i in range(n):
            if we[i] > wsc[i] and m[i] > 0:
                seg = v[wsc[i]: we[i]]
                out_sorted[i] = np.nanmin(seg) if is_min else np.nanmax(seg)
    out_sorted = np.where(m > 0, out_sorted, np.nan)
    return unsort(out_sorted, np.float64)


# ---------------------------------------------------------------------------
# Shared helpers
# ---------------------------------------------------------------------------
def _rows_from_columns(cols: Sequence[np.ndarray], n: int) -> List[tuple]:
    rows = []
    for i in range(n):
        rows.append(
            tuple(
                _scalar(c[i]) if not isinstance(c[i], (str, bytes, list, tuple, type(None))) else c[i]
                for c in cols
            )
        )
    return rows


def _order_codes(order_by: List[OrderByExpr], ord_vals: List[np.ndarray], n: int):
    """Vectorized rank keys for _sorted_order's lexsort fast path: each
    column codes to float ranks via np.unique over the RAW objects (python
    `<` ordering, so strings and numbers alike match the comparator), nulls
    to +-inf per nulls placement.  Returns None when a column defies
    total-order coding (mixed incomparable types, NaN) — the caller falls
    back to the Python comparator."""
    keys = []
    for ob, vals in zip(reversed(order_by), reversed(ord_vals)):
        a = np.asarray(vals, dtype=object)
        isnull = np.fromiter((v is None for v in a), dtype=bool, count=len(a))
        body = a[~isnull]
        k = np.empty(n, dtype=np.float64)
        if body.size:
            if any(isinstance(v, (float, np.floating)) and math.isnan(v) for v in body):
                return None
            try:
                _, inv = np.unique(body, return_inverse=True)
            except TypeError:
                return None
            num = inv.reshape(-1).astype(np.float64)
            k[~isnull] = num if ob.ascending else -num
        k[isnull] = np.inf if ob.nulls_last else -np.inf
        keys.append(k)
    return keys


def _sorted_order(order_by: List[OrderByExpr], ord_vals: List[np.ndarray], n: int) -> List[int]:
    """Stable index sort honoring asc/desc + nulls placement, robust to
    mixed/None/object values (python comparison semantics)."""
    if n > 1:
        keys = _order_codes(order_by, ord_vals, n)
        if keys is not None:
            # np.lexsort is stable, so equal-ranked rows keep their original
            # order — the same i - j tiebreak the comparator applies
            return list(np.lexsort(tuple(keys)))

    def cmp(i: int, j: int) -> int:
        for ob, vals in zip(order_by, ord_vals):
            a, b = vals[i], vals[j]
            if a is None or b is None:
                if a is None and b is None:
                    continue
                null_first = not ob.nulls_last
                if a is None:
                    return -1 if null_first else 1
                return 1 if null_first else -1
            if a == b:
                continue
            less = a < b
            if ob.ascending:
                return -1 if less else 1
            return 1 if less else -1
        return i - j  # stable tiebreak

    return sorted(range(n), key=functools.cmp_to_key(cmp))


def _order_and_trim(
    ctx: QueryContext,
    rows: List[tuple],
    select_fps: List[str],
    env: Dict[str, np.ndarray],
    n: int,
) -> List[tuple]:
    if ctx.order_by:
        ord_vals = []
        for ob in ctx.order_by:
            try:
                vals = _eval_env_expr(ob.expr, env, n)
            except ValueError:
                raise ValueError(
                    f"ORDER BY {ob.expr} must be a select/group/aggregation expression"
                ) from None
            ord_vals.append(np.asarray([_scalar(v) if not isinstance(v, (str, bytes, type(None))) else v for v in vals], dtype=object))
        order = _sorted_order(ctx.order_by, ord_vals, n)
        rows = [rows[i] for i in order]
    return rows[ctx.offset: ctx.offset + ctx.limit]


_ENV_BINOPS = {
    "plus": np.add,
    "add": np.add,
    "minus": np.subtract,
    "sub": np.subtract,
    "times": np.multiply,
    "mult": np.multiply,
    "mod": np.mod,
    "pow": np.power,
}
_ENV_UNARY = {
    "abs": np.abs,
    "neg": np.negative,
    "sqrt": np.sqrt,
    "ln": np.log,
    "log": np.log,
    "log2": np.log2,
    "log10": np.log10,
    "exp": np.exp,
    "floor": np.floor,
    "ceil": np.ceil,
    "ceiling": np.ceil,
    "round": np.round,
}


def _eval_env_expr(e, env: Dict[str, np.ndarray], n: int) -> np.ndarray:
    """POST-AGGREGATION expression evaluation over final arrays — the
    reference's post-aggregation gap-filling (PostAggregationFunction):
    SELECT SUM(a)/COUNT(*), HAVING SUM(v)*2 > x, ORDER BY SUM(a)-SUM(b).
    Resolution: fingerprint in env (group keys, aggregation finals, aliases)
    else arithmetic over recursively evaluated args."""
    fp = e.fingerprint()
    if fp in env:
        return np.asarray(env[fp])
    if e.is_literal:
        return np.full(n, e.value)
    if e.kind is not None and e.kind.name == "CALL":
        op = e.op
        if op in _ENV_BINOPS and len(e.args) == 2:
            a = np.asarray(_eval_env_expr(e.args[0], env, n), dtype=np.float64)
            b = np.asarray(_eval_env_expr(e.args[1], env, n), dtype=np.float64)
            return _ENV_BINOPS[op](a, b)
        if op in ("divide", "div") and len(e.args) == 2:
            a = np.asarray(_eval_env_expr(e.args[0], env, n), dtype=np.float64)
            b = np.asarray(_eval_env_expr(e.args[1], env, n), dtype=np.float64)
            with np.errstate(divide="ignore", invalid="ignore"):
                return a / b
        if op in _ENV_UNARY and len(e.args) == 1:
            return _ENV_UNARY[op](np.asarray(_eval_env_expr(e.args[0], env, n), dtype=np.float64))
    raise ValueError(f"select item {e} is neither a group key nor an aggregation")


def _eval_host_filter(node: FilterNode, env: Dict[str, np.ndarray], n: int) -> np.ndarray:
    """HAVING evaluation over final (already-aggregated) columns."""
    if node.op is FilterOp.AND:
        m = np.ones(n, dtype=bool)
        for c in node.children:
            m &= _eval_host_filter(c, env, n)
        return m
    if node.op is FilterOp.OR:
        m = np.zeros(n, dtype=bool)
        for c in node.children:
            m |= _eval_host_filter(c, env, n)
        return m
    if node.op is FilterOp.NOT:
        return ~_eval_host_filter(node.children[0], env, n)
    p = node.predicate
    try:
        vals = _eval_env_expr(p.lhs, env, n)
    except ValueError:
        raise ValueError(f"HAVING references {p.lhs}, which is not in the select/group list") from None

    def isnull(v) -> bool:
        # NULL aggregates arrive as np.nan here (converted to None only at
        # _scalar); SQL 3VL: any comparison with NULL excludes the group.
        return v is None or (isinstance(v, (float, np.floating)) and math.isnan(v))

    if p.ptype is PredicateType.EQ:
        return np.asarray([not isnull(v) and v == p.values[0] for v in vals], dtype=bool)
    if p.ptype is PredicateType.NEQ:
        return np.asarray([not isnull(v) and v != p.values[0] for v in vals], dtype=bool)
    if p.ptype in (PredicateType.IN, PredicateType.NOT_IN):
        s = set(p.values)
        if p.ptype is PredicateType.IN:
            return np.asarray([not isnull(v) and v in s for v in vals], dtype=bool)
        return np.asarray([not isnull(v) and v not in s for v in vals], dtype=bool)
    if p.ptype is PredicateType.RANGE:
        m = np.ones(n, dtype=bool)
        for i, v in enumerate(vals):
            if isnull(v):
                m[i] = False
                continue
            if p.lower is not None and not (v >= p.lower if p.lower_inclusive else v > p.lower):
                m[i] = False
            if p.upper is not None and not (v <= p.upper if p.upper_inclusive else v < p.upper):
                m[i] = False
        return m
    raise ValueError(f"HAVING predicate {p.ptype} unsupported")
