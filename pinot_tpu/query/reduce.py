"""Broker reduce: merge per-segment results, HAVING/ORDER BY/LIMIT, format.

Reference parity: BrokerReduceService.reduceOnDataTable
(pinot-core/.../query/reduce/BrokerReduceService.java:65) and its per-shape
reducers (GroupByDataTableReducer, AggregationDataTableReducer,
SelectionDataTableReducer) + PostAggregationHandler/HAVING handling.

Re-design: partials arrive as numpy arrays, not serialized DataTables.  The
group-by merge has two paths:
  * ALIGNED DENSE: when every segment produced a dense group table over the
    SAME key space (shared dictionary fingerprints — always true for stacked/
    aligned tables, M2), merging is pure elementwise array combination; this
    is the shape that becomes a psum over ICI in the distributed engine.
  * GENERIC: decoded-key hash merge (GroupByDataTableReducer's IndexedTable
    analog) for heterogeneous segments.
"""
from __future__ import annotations

import functools
import math
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from pinot_tpu.query.functions import combine_field, field_identity, for_spec
from pinot_tpu.query.ir import (
    AggregationSpec,
    Expr,
    FilterNode,
    FilterOp,
    OrderByExpr,
    PredicateType,
    QueryContext,
)
from pinot_tpu.query.result import (
    AggSegmentResult,
    ExecutionStats,
    GroupBySegmentResult,
    ResultTable,
    SelectionSegmentResult,
)


def reduce_results(ctx: QueryContext, results: List[Any], stats: ExecutionStats) -> ResultTable:
    if ctx.is_aggregate and not ctx.group_by:
        return _reduce_aggregation(ctx, results, stats)
    if ctx.group_by:
        return _reduce_groupby(ctx, results, stats)
    return _reduce_selection(ctx, results, stats)


# ---------------------------------------------------------------------------
# Aggregation-only
# ---------------------------------------------------------------------------
def _reduce_aggregation(ctx: QueryContext, results: List[AggSegmentResult], stats: ExecutionStats) -> ResultTable:
    aggs = [for_spec(a) for a in ctx.aggregations]
    merged: Optional[List[Dict[str, np.ndarray]]] = None
    for r in results:
        if merged is None:
            merged = [dict(p) for p in r.partials]
        else:
            merged = [fn.merge(m, p) for fn, m, p in zip(aggs, merged, r.partials)]
    # finals for every aggregation (selected + hidden extras), then resolve
    # select items — post-aggregation arithmetic evaluates over the env
    specs = list(ctx.aggregations)
    env: Dict[str, Any] = {}
    for i, (spec, fn) in enumerate(zip(specs, aggs)):
        if merged is None:
            val = 0 if fn.name == "count" else None  # all segments pruned
        else:
            val = fn.final(merged[i])
            if not isinstance(val, (list, tuple)):
                val = _scalar(val)
        cell = np.empty(1, dtype=object)  # explicit: np.asarray would
        cell[0] = np.nan if val is None else val  # 2D-ify a list value
        _register_agg_env(env, spec, cell)
    row = []
    for s in ctx.select_list:
        if isinstance(s, AggregationSpec):
            v = env[s.fingerprint()][0]
        else:
            v = _eval_env_expr(s, env, 1)[0]
        row.append(_scalar(v) if not isinstance(v, (str, bytes, list, tuple, type(None))) else v)
    return ResultTable(columns=ctx.column_names_out(), rows=[tuple(row)], stats=stats)


def _scalar(v):
    v = np.asarray(v)
    x = v.item() if v.ndim == 0 else v
    if isinstance(x, float) and (math.isnan(x) or math.isinf(x)):
        return None
    return x


def _register_agg_env(env: Dict[str, Any], spec: AggregationSpec, finals) -> None:
    """Register one aggregation's final array under every fingerprint form
    HAVING/ORDER BY/post-aggregation may reference it by: the spec itself,
    the plain call `sum(v)` (literal args re-attached), and explicit
    `count(*)`.  Shared by the scalar and group-by reducers."""
    env[spec.fingerprint()] = finals
    if spec.filter is None:
        args = list(spec.expr and [spec.expr] or []) + [Expr.lit(a) for a in spec.literal_args]
        env.setdefault(Expr.call(spec.function, *args).fingerprint(), finals)
        if spec.expr is None and not spec.literal_args:
            env.setdefault(Expr.call(spec.function, Expr.col("*")).fingerprint(), finals)


# ---------------------------------------------------------------------------
# Group-by
# ---------------------------------------------------------------------------
def _reduce_groupby(ctx: QueryContext, results: List[GroupBySegmentResult], stats: ExecutionStats) -> ResultTable:
    aggs = [for_spec(a) for a in ctx.aggregations]
    results = [r for r in results if r is not None]
    if not results:
        return ResultTable(columns=ctx.column_names_out(), rows=[], stats=stats)

    # -- aligned dense fast path ---------------------------------------
    key_spaces = {r.dense.key_space for r in results if r.dense is not None}
    if len(results) > 1 and len(key_spaces) == 1 and all(r.dense is not None for r in results):
        d0 = results[0].dense
        presence = np.zeros_like(d0.presence)
        merged_partials = [
            {f: np.full_like(arr, _ident_like(f, arr)) for f, arr in p.items()}
            if not fn.pairwise_merge
            else None
            for fn, p in zip(aggs, d0.partials)
        ]
        for r in results:
            presence = presence + r.dense.presence
            for ai, (fn, p) in enumerate(zip(aggs, r.dense.partials)):
                if fn.pairwise_merge:
                    # coupled fields (LASTWITHTIME's (t, v)): elementwise
                    # fn.merge over the whole dense table, not per-field
                    cur = merged_partials[ai]
                    merged_partials[ai] = p if cur is None else fn.merge(cur, p)
                    continue
                mp = merged_partials[ai]
                for f in mp:
                    mp[f] = combine_field(f, mp[f], np.asarray(p[f]))
        present = np.nonzero(presence > 0)[0]
        keys = _decode_dense_keys(d0.group_dims, present)
        partials = [{f: arr[present] for f, arr in p.items()} for p in merged_partials]
    elif len(results) == 1:
        keys, partials = results[0].keys, results[0].partials
    else:
        keys, partials = _hash_merge(results, aggs)

    stats.num_groups = len(keys[0]) if keys else 0
    finals = [np.atleast_1d(np.asarray(fn.final(p))) for fn, p in zip(aggs, partials)]

    # fingerprint -> column array, for select/having/order resolution
    env: Dict[str, np.ndarray] = {}
    for g, k in zip(ctx.group_by, keys):
        env[g.fingerprint()] = k
    for spec, f in zip(ctx.aggregations, finals):
        _register_agg_env(env, spec, f)
    # select aliases: ORDER BY/HAVING may reference any select item by alias
    # (covers filtered/literal-arg aggregations the call forms above can't)
    for s, alias in zip(ctx.select_list, ctx.select_aliases):
        if alias:
            fp = s.fingerprint()
            if fp in env:
                env.setdefault(Expr.col(alias).fingerprint(), env[fp])

    # HAVING
    n = len(keys[0]) if keys else 0
    if ctx.having is not None and n:
        mask = _eval_host_filter(ctx.having, env, n)
        keys = [k[mask] for k in keys]
        finals = [f[mask] for f in finals]
        env = {k: v[mask] for k, v in env.items()}
        n = int(mask.sum())

    # output columns in select order (post-aggregation arithmetic resolves
    # against the env of final arrays)
    out_cols: List[np.ndarray] = []
    for s in ctx.select_list:
        out_cols.append(_eval_env_expr(s, env, n) if isinstance(s, Expr) else env[s.fingerprint()])

    rows = _rows_from_columns(out_cols, n)
    rows = _order_and_trim(ctx, rows, [s.fingerprint() for s in ctx.select_list], env, n)
    return ResultTable(columns=ctx.column_names_out(), rows=rows, stats=stats)


def _ident_like(field: str, arr: np.ndarray):
    if field == "count":
        return 0
    ident = field_identity(field)
    if np.issubdtype(np.asarray(arr).dtype, np.integer):
        # +-inf identities don't exist for int fields (presence bitmaps, HLL
        # registers, histograms); use the dtype extremes / zero instead
        info = np.iinfo(np.asarray(arr).dtype)
        return {0.0: 0, float("inf"): info.max, float("-inf"): 0}[ident]
    return ident


def _decode_dense_keys(group_dims, present: np.ndarray) -> List[np.ndarray]:
    from pinot_tpu.query.planner import decode_packed_keys

    return decode_packed_keys(group_dims, present)


def _hash_merge(results: List[GroupBySegmentResult], aggs) -> Tuple[List[np.ndarray], List[Dict[str, np.ndarray]]]:
    """Generic keyed merge (IndexedTable upsert analog)."""
    table: Dict[tuple, List[Dict[str, Any]]] = {}
    for r in results:
        n = len(r.keys[0]) if r.keys else 0
        for i in range(n):
            key = tuple(k[i] for k in r.keys)
            partial = [{f: arr[i] for f, arr in p.items()} for p in r.partials]
            cur = table.get(key)
            if cur is None:
                table[key] = partial
            else:
                table[key] = [fn.merge(a, b) for fn, a, b in zip(aggs, cur, partial)]
    keys_out: List[np.ndarray] = []
    ndims = len(results[0].keys)
    all_keys = list(table.keys())
    for d in range(ndims):
        keys_out.append(np.asarray([k[d] for k in all_keys], dtype=object))
    partials_out: List[Dict[str, np.ndarray]] = []
    for ai, fn in enumerate(aggs):
        fields = results[0].partials[ai].keys()
        partials_out.append({f: np.asarray([table[k][ai][f] for k in all_keys]) for f in fields})
    return keys_out, partials_out


# ---------------------------------------------------------------------------
# Selection
# ---------------------------------------------------------------------------
def _reduce_selection(ctx: QueryContext, results: List[SelectionSegmentResult], stats: ExecutionStats) -> ResultTable:
    results = [r for r in results if r is not None]
    out_names = ctx.column_names_out()
    if not results:
        return ResultTable(columns=out_names, rows=[], stats=stats)
    cols = results[0].columns
    if "*" in out_names:
        # SELECT *: label with the actual gathered columns so dataSchema
        # matches the row arity (window inputs/order keys are internal)
        out_names = [c for c in cols if not (c.startswith("__ord") or c.startswith("__wx_"))]
    arrays = {
        c: np.concatenate([np.asarray(r.arrays[c], dtype=object) for r in results])
        if len(results) > 1
        else np.asarray(results[0].arrays[c], dtype=object)
        for c in cols
    }
    n = len(next(iter(arrays.values()))) if arrays else 0
    # window functions: computed HERE, over the globally merged row set
    # (WindowAggregateOperator analog; whole-partition frames)
    if ctx.windows:
        from pinot_tpu.query.ir import WindowSpec

        for i, s in enumerate(ctx.select_list):
            if isinstance(s, WindowSpec):
                arrays[f"__win{i}"] = _compute_window(s, arrays, n)
    select_cols = [c for c in cols if not (c.startswith("__ord") or c.startswith("__wx_"))]
    rows = _rows_from_columns([arrays[c] for c in select_cols], n)
    if ctx.order_by:
        ord_vals = [arrays[f"__ord{i}"] for i in range(len(ctx.order_by))]
        order = _sorted_order(ctx.order_by, ord_vals, n)
        rows = [rows[i] for i in order]
    rows = rows[ctx.offset: ctx.offset + ctx.limit]
    return ResultTable(columns=out_names, rows=rows, stats=stats)


def _compute_window(spec, arrays: Dict[str, np.ndarray], n: int) -> np.ndarray:
    """One window function over the merged result rows.

    Partition ids by hashing the partition-key tuples; within each
    partition, rows order by the OVER(ORDER BY ...) keys (stable).  Frames
    are the whole partition (ir.WindowSpec contract)."""
    pid = np.zeros(n, dtype=np.int64)
    if spec.partition_by:
        pkeys = [np.asarray(arrays[f"__wx_{p.fingerprint()}"]) for p in spec.partition_by]
        seen: Dict[tuple, int] = {}
        for i in range(n):
            key = tuple(k[i] for k in pkeys)
            pid[i] = seen.setdefault(key, len(seen))
    okeys = [(np.asarray(arrays[f"__wx_{o.expr.fingerprint()}"]), o.ascending) for o in spec.order_by]
    arg = np.asarray(arrays[f"__wx_{spec.expr.fingerprint()}"], dtype=np.float64) if spec.expr is not None else None

    fn = spec.function
    out = np.zeros(n, dtype=np.float64)
    if fn in ("row_number", "rank", "dense_rank"):
        # global stable sort by (pid, order keys) then rank within partition
        lex: List[np.ndarray] = [pid]
        for vals, asc in okeys:
            # merged selection arrays are object-dtype; numeric values must
            # rank numerically, genuine strings by sorted-unique codes
            a = np.asarray(vals)
            if a.dtype == object:
                try:
                    a = a.astype(np.float64)
                except (ValueError, TypeError):
                    pass
            if np.issubdtype(a.dtype, np.number):
                a = a.astype(np.float64)
                lex.append(a if asc else -a)
            else:
                u, inv = np.unique(a.astype(str), return_inverse=True)
                lex.append(inv if asc else -inv)
        order = np.lexsort(tuple(reversed(lex)))
        prev_pid = None
        pos = rank = dense = 0
        prev_key = None
        for idx in order:
            key = tuple(np.asarray(l)[idx] for l in lex[1:])
            if pid[idx] != prev_pid:
                prev_pid = pid[idx]
                pos = rank = dense = 1
                prev_key = key
            else:
                pos += 1
                if key != prev_key:
                    rank = pos
                    dense += 1
                    prev_key = key
            out[idx] = pos if fn == "row_number" else (rank if fn == "rank" else dense)
        return out.astype(np.int64)
    if spec.frame == "rows_cumulative":
        return _running_window(fn, pid, okeys, arg, n)
    # whole-partition aggregates
    nparts = int(pid.max()) + 1 if n else 0
    if fn == "count":
        cnt = np.bincount(pid, minlength=nparts)
        return cnt[pid].astype(np.int64)
    if arg is None:
        raise ValueError(f"window {fn} needs an argument")
    if fn in ("sum", "avg"):
        s = np.bincount(pid, weights=arg, minlength=nparts)
        if fn == "sum":
            return s[pid]
        cnt = np.bincount(pid, minlength=nparts)
        return (s / cnt)[pid]
    ident = np.inf if fn == "min" else -np.inf
    acc = np.full(nparts, ident)
    (np.minimum if fn == "min" else np.maximum).at(acc, pid, arg)
    return acc[pid]


def _running_window(fn: str, pid: np.ndarray, okeys, arg, n: int) -> np.ndarray:
    """ROWS BETWEEN UNBOUNDED PRECEDING AND CURRENT ROW: sort within
    partitions by the OVER(ORDER BY) keys and accumulate (running
    aggregate).  Vectorized via segment-reset cumulative sums."""
    lex: List[np.ndarray] = [pid]
    for vals, asc in okeys:
        a = np.asarray(vals)
        if a.dtype == object:
            try:
                a = a.astype(np.float64)
            except (ValueError, TypeError):
                pass
        if np.issubdtype(a.dtype, np.number):
            lex.append(a.astype(np.float64) if asc else -a.astype(np.float64))
        else:
            _, inv = np.unique(a.astype(str), return_inverse=True)
            lex.append(inv if asc else -inv)
    order = np.lexsort(tuple(reversed(lex)))
    spid = pid[order]
    starts = np.ones(n, dtype=bool)
    starts[1:] = spid[1:] != spid[:-1]
    start_idx = np.maximum.accumulate(np.where(starts, np.arange(n), 0))
    out_sorted = np.empty(n, dtype=np.float64)
    if fn == "count":
        out_sorted = (np.arange(n) - start_idx + 1).astype(np.float64)
    else:
        if arg is None:
            raise ValueError(f"window {fn} needs an argument")
        v = np.asarray(arg, dtype=np.float64)[order]
        if fn in ("sum", "avg"):
            c = np.cumsum(v)
            base = np.where(start_idx > 0, c[start_idx - 1], 0.0)
            run = c - base
            if fn == "sum":
                out_sorted = run
            else:
                out_sorted = run / (np.arange(n) - start_idx + 1)
        else:  # running min/max: loop with partition resets
            best = 0.0
            for i in range(n):
                best = v[i] if starts[i] else (min(best, v[i]) if fn == "min" else max(best, v[i]))
                out_sorted[i] = best
    out = np.empty(n, dtype=np.float64)
    out[order] = out_sorted
    return out


# ---------------------------------------------------------------------------
# Shared helpers
# ---------------------------------------------------------------------------
def _rows_from_columns(cols: Sequence[np.ndarray], n: int) -> List[tuple]:
    rows = []
    for i in range(n):
        rows.append(
            tuple(
                _scalar(c[i]) if not isinstance(c[i], (str, bytes, list, tuple, type(None))) else c[i]
                for c in cols
            )
        )
    return rows


def _sorted_order(order_by: List[OrderByExpr], ord_vals: List[np.ndarray], n: int) -> List[int]:
    """Stable index sort honoring asc/desc + nulls placement, robust to
    mixed/None/object values (python comparison semantics)."""

    def cmp(i: int, j: int) -> int:
        for ob, vals in zip(order_by, ord_vals):
            a, b = vals[i], vals[j]
            if a is None or b is None:
                if a is None and b is None:
                    continue
                null_first = not ob.nulls_last
                if a is None:
                    return -1 if null_first else 1
                return 1 if null_first else -1
            if a == b:
                continue
            less = a < b
            if ob.ascending:
                return -1 if less else 1
            return 1 if less else -1
        return i - j  # stable tiebreak

    return sorted(range(n), key=functools.cmp_to_key(cmp))


def _order_and_trim(
    ctx: QueryContext,
    rows: List[tuple],
    select_fps: List[str],
    env: Dict[str, np.ndarray],
    n: int,
) -> List[tuple]:
    if ctx.order_by:
        ord_vals = []
        for ob in ctx.order_by:
            try:
                vals = _eval_env_expr(ob.expr, env, n)
            except ValueError:
                raise ValueError(
                    f"ORDER BY {ob.expr} must be a select/group/aggregation expression"
                ) from None
            ord_vals.append(np.asarray([_scalar(v) if not isinstance(v, (str, bytes, type(None))) else v for v in vals], dtype=object))
        order = _sorted_order(ctx.order_by, ord_vals, n)
        rows = [rows[i] for i in order]
    return rows[ctx.offset: ctx.offset + ctx.limit]


_ENV_BINOPS = {
    "plus": np.add,
    "add": np.add,
    "minus": np.subtract,
    "sub": np.subtract,
    "times": np.multiply,
    "mult": np.multiply,
    "mod": np.mod,
    "pow": np.power,
}
_ENV_UNARY = {
    "abs": np.abs,
    "neg": np.negative,
    "sqrt": np.sqrt,
    "ln": np.log,
    "log": np.log,
    "log2": np.log2,
    "log10": np.log10,
    "exp": np.exp,
    "floor": np.floor,
    "ceil": np.ceil,
    "ceiling": np.ceil,
    "round": np.round,
}


def _eval_env_expr(e, env: Dict[str, np.ndarray], n: int) -> np.ndarray:
    """POST-AGGREGATION expression evaluation over final arrays — the
    reference's post-aggregation gap-filling (PostAggregationFunction):
    SELECT SUM(a)/COUNT(*), HAVING SUM(v)*2 > x, ORDER BY SUM(a)-SUM(b).
    Resolution: fingerprint in env (group keys, aggregation finals, aliases)
    else arithmetic over recursively evaluated args."""
    fp = e.fingerprint()
    if fp in env:
        return np.asarray(env[fp])
    if e.is_literal:
        return np.full(n, e.value)
    if e.kind is not None and e.kind.name == "CALL":
        op = e.op
        if op in _ENV_BINOPS and len(e.args) == 2:
            a = np.asarray(_eval_env_expr(e.args[0], env, n), dtype=np.float64)
            b = np.asarray(_eval_env_expr(e.args[1], env, n), dtype=np.float64)
            return _ENV_BINOPS[op](a, b)
        if op in ("divide", "div") and len(e.args) == 2:
            a = np.asarray(_eval_env_expr(e.args[0], env, n), dtype=np.float64)
            b = np.asarray(_eval_env_expr(e.args[1], env, n), dtype=np.float64)
            with np.errstate(divide="ignore", invalid="ignore"):
                return a / b
        if op in _ENV_UNARY and len(e.args) == 1:
            return _ENV_UNARY[op](np.asarray(_eval_env_expr(e.args[0], env, n), dtype=np.float64))
    raise ValueError(f"select item {e} is neither a group key nor an aggregation")


def _eval_host_filter(node: FilterNode, env: Dict[str, np.ndarray], n: int) -> np.ndarray:
    """HAVING evaluation over final (already-aggregated) columns."""
    if node.op is FilterOp.AND:
        m = np.ones(n, dtype=bool)
        for c in node.children:
            m &= _eval_host_filter(c, env, n)
        return m
    if node.op is FilterOp.OR:
        m = np.zeros(n, dtype=bool)
        for c in node.children:
            m |= _eval_host_filter(c, env, n)
        return m
    if node.op is FilterOp.NOT:
        return ~_eval_host_filter(node.children[0], env, n)
    p = node.predicate
    try:
        vals = _eval_env_expr(p.lhs, env, n)
    except ValueError:
        raise ValueError(f"HAVING references {p.lhs}, which is not in the select/group list") from None

    def isnull(v) -> bool:
        # NULL aggregates arrive as np.nan here (converted to None only at
        # _scalar); SQL 3VL: any comparison with NULL excludes the group.
        return v is None or (isinstance(v, (float, np.floating)) and math.isnan(v))

    if p.ptype is PredicateType.EQ:
        return np.asarray([not isnull(v) and v == p.values[0] for v in vals], dtype=bool)
    if p.ptype is PredicateType.NEQ:
        return np.asarray([not isnull(v) and v != p.values[0] for v in vals], dtype=bool)
    if p.ptype in (PredicateType.IN, PredicateType.NOT_IN):
        s = set(p.values)
        if p.ptype is PredicateType.IN:
            return np.asarray([not isnull(v) and v in s for v in vals], dtype=bool)
        return np.asarray([not isnull(v) and v not in s for v in vals], dtype=bool)
    if p.ptype is PredicateType.RANGE:
        m = np.ones(n, dtype=bool)
        for i, v in enumerate(vals):
            if isnull(v):
                m[i] = False
                continue
            if p.lower is not None and not (v >= p.lower if p.lower_inclusive else v > p.lower):
                m[i] = False
            if p.upper is not None and not (v <= p.upper if p.upper_inclusive else v < p.upper):
                m[i] = False
        return m
    raise ValueError(f"HAVING predicate {p.ptype} unsupported")
