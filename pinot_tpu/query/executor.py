"""Segment execution: prune -> plan -> kernel launch -> segment result.

Reference parity: ServerQueryExecutorV1Impl.executeInternal
(pinot-core/.../query/executor/ServerQueryExecutorV1Impl.java:161,316) —
acquire segments, server-side pruning (SegmentPrunerService, value/bloom
pruners), per-segment plan execution — and the per-segment hot loop of
SURVEY.md 3.1.

Re-design: "execution" is one jitted kernel call per segment (planner.py);
this module owns the host-side halves: pruning from metadata before any
launch, and the post-kernel decode (dense group table -> present keys, the
sparse-groupby host fallback, selection row gather)."""
from __future__ import annotations

import os
import threading
import time
from typing import Dict, List, Optional, Tuple

import numpy as np

from pinot_tpu.query import planner
from pinot_tpu.utils import perf
from pinot_tpu.query.functions import combine_field
from pinot_tpu.query.ir import Expr, FilterNode, FilterOp, PredicateType, QueryContext
from pinot_tpu.query.transform import eval_expr_host
from pinot_tpu.query.result import (
    AggSegmentResult,
    DenseGroupData,
    ExecutionStats,
    GroupBySegmentResult,
    SelectionSegmentResult,
)
from pinot_tpu.segment.segment import ImmutableSegment


# ---------------------------------------------------------------------------
# Pruning (SegmentPrunerService analog — entirely host-side, metadata only)
# ---------------------------------------------------------------------------
def _top_level_predicates(node: Optional[FilterNode]):
    if node is None:
        return []
    if node.op is FilterOp.PRED:
        return [node.predicate]
    if node.op is FilterOp.AND:
        out = []
        for c in node.children:
            out.extend(_top_level_predicates(c))
        return out
    return []


def prune_segment(ctx: QueryContext, segment: ImmutableSegment) -> bool:
    """True if the segment provably matches no rows (value/bloom pruner)."""
    for p in _top_level_predicates(ctx.filter):
        if not p.lhs.is_column or p.lhs.op == "*" or p.lhs.op not in segment.columns:
            continue
        c = segment.column(p.lhs.op)
        s = c.stats
        if s.num_docs == 0:
            return True
        if p.ptype is PredicateType.EQ:
            v = p.values[0]
            if c.has_dictionary:
                if c.dictionary.index_of(v) < 0:
                    return True
            elif s.min_value is not None and not c.data_type.is_string_like:
                try:
                    if v < s.min_value or v > s.max_value:
                        return True
                except TypeError:
                    pass
            bloom = segment.indexes.get("bloom", {}).get(p.lhs.op)
            if bloom is not None and not bloom.might_contain(v):
                return True
        elif p.ptype is PredicateType.IN:
            if c.has_dictionary and all(c.dictionary.index_of(v) < 0 for v in p.values):
                return True
        elif p.ptype is PredicateType.RANGE and s.min_value is not None:
            try:
                if p.lower is not None and (
                    s.max_value < p.lower or (s.max_value == p.lower and not p.lower_inclusive)
                ):
                    return True
                if p.upper is not None and (
                    s.min_value > p.upper or (s.min_value == p.upper and not p.upper_inclusive)
                ):
                    return True
            except TypeError:
                pass
    return False


# ---------------------------------------------------------------------------
# Execution
# ---------------------------------------------------------------------------
def launch_segment(
    ctx: QueryContext, segment: ImmutableSegment, device=None, residency=None
):
    """Phase 1 of pipelined execution: plan, ship inputs, and DISPATCH the
    segment kernel (jax dispatch is asynchronous — the call returns as soon
    as the work is enqueued).  Returns an opaque pending state for
    collect_segment.

    This is the pipeline-parallelism axis (SURVEY.md §2.5): while segment
    k's kernel runs on device, the host plans/ships segment k+1 and later
    drains results — the streaming overlap the reference gets from mailbox
    block streaming."""
    import jax

    from pinot_tpu.query.startree import try_startree

    star = try_startree(ctx, segment)
    if star is not None:
        return ("done", star)

    stats = ExecutionStats(
        num_segments_queried=1,
        num_segments_processed=1,
        num_docs_scanned=segment.num_docs,
        total_docs=segment.num_docs,
    )
    plan = planner.plan_segment(ctx, segment)
    stats.filter_index_uses = tuple(plan.index_uses)
    cols = segment.to_device(
        device=device, columns=plan.needed_columns, packed_codes=True,
        residency=residency,
    )
    params = {k: jax.device_put(v, device) for k, v in plan.params.items()}
    first_launch = plan.cost is None
    if first_launch:
        # cost model captured ONCE per cached plan (hits copy it forward in
        # plan_segment); racing first launches both capture — idempotent
        plan.cost = perf.capture_cost(
            plan.fn,
            (cols, params),
            perf.analytic_cost(
                segment.num_docs,
                perf.analytic_bytes_per_row(
                    segment.column(n) for n in plan.needed_columns
                ),
                kind=plan.kind,
                num_groups=plan.num_groups,
                num_entries=len(plan.aggs),
            ),
        )
    t0 = time.perf_counter()
    out = plan.fn(cols, params)  # async dispatch; device_get happens at collect
    if first_launch:
        # first jit dispatch pays trace+compile before enqueueing — its wall
        # time IS the compile cost (AOT compile would pay it a second time)
        plan.cost.compile_ms = (time.perf_counter() - t0) * 1000.0
        stats.compile_ms = plan.cost.compile_ms + plan.cost.lower_ms
    stats.kernel_bytes = plan.cost.bytes_accessed
    stats.kernel_flops = plan.cost.flops
    stats.kernel_cost_source = plan.cost.source
    return ("pending", ctx, segment, plan, out, stats)


def pending_outputs(states) -> list:
    """Device output pytrees of the not-yet-collected launch states — the
    tracing layer fences on ALL of these with ONE jax.block_until_ready to
    split device compute time from host dispatch (never per-launch: a
    per-launch fence in the loop would serialize the pipeline, lint W002)."""
    return [st[4] for st in states if st[0] in ("pending", "pending_batch")]


def collect_segment(state):
    """Phase 2: block on the kernel's outputs and finish host-side."""
    import jax

    if state[0] == "done":
        return state[1]
    _, ctx, segment, plan, out, stats = state
    host = jax.device_get(out)
    return _decode_host(ctx, segment, plan, host, stats)


def _decode_host(ctx, segment, plan, host, stats):
    """Host-side decode of one query's (already fetched) kernel outputs —
    shared by the unbatched collect and the per-member unstack of a
    cross-query batched launch."""
    if plan.kind == "aggregation":
        partials = [fn.host_partial(p) for fn, p in zip(plan.aggs, host)]
        return AggSegmentResult(partials=partials), stats

    if plan.kind == "groupby_dense":
        presence, partials = host
        dense = DenseGroupData(
            presence=presence,
            partials=partials,
            key_space=_key_space_id(plan),
            group_dims=plan.group_dims,
        )
        keys, sliced = _dense_to_present(
            plan, presence, partials, ctx.num_groups_limit,
            order_trim=planner.order_by_agg_index(ctx),
        )
        stats.num_groups = len(keys[0]) if keys else 0
        return GroupBySegmentResult(keys=keys, partials=sliced, dense=dense), stats

    if plan.kind == "groupby_sparse":
        uniq, partials = host
        res = sparse_tables_to_result(
            plan.group_dims, plan.aggs, uniq, partials, ctx.num_groups_limit,
            order_trim=planner.order_by_agg_index(ctx),
        )
        stats.num_groups = len(res.keys[0]) if res.keys else 0
        return res, stats

    # selection
    tmask = np.asarray(host)
    return _gather_selection(ctx, plan, segment, tmask), stats


# ---------------------------------------------------------------------------
# cross-query vmap batching (the concurrent serving tier's kernel layer)
# ---------------------------------------------------------------------------


class BatchShapeError(RuntimeError):
    """Batch members do not share one compiled plan — callers must fall
    back to per-member execution (never a user-visible failure)."""


class BatchAudit:
    """Counts vmapped-plan compiles vs. cache hits, mirroring SSE_AUDIT for
    the base plans: the ≤2-compiles-per-shape guarantee is 1 base compile
    (SSE_AUDIT) + 1 batched compile (here)."""

    def __init__(self):
        self._lock = threading.Lock()
        self.compiles = 0
        self.hits = 0

    def record_compile(self):
        with self._lock:
            self.compiles += 1

    def record_hit(self):
        with self._lock:
            self.hits += 1

    def reset(self):
        with self._lock:
            self.compiles = 0
            self.hits = 0

    def snapshot(self) -> Dict[str, int]:
        with self._lock:
            return {"compiles": self.compiles, "hits": self.hits}


BATCH_AUDIT = BatchAudit()


def batch_width() -> int:
    """Fixed lane count of a batched launch (PINOT_TPU_BATCH_MAX).  Partial
    batches pad to this width by repeating the last member's params, so
    every batched launch of a given plan shares ONE compiled vmap kernel."""
    return max(2, int(os.environ.get("PINOT_TPU_BATCH_MAX", "8")))


def _batch_fn_cache():
    global _BATCH_FN_CACHE
    if _BATCH_FN_CACHE is None:
        from pinot_tpu.utils.cache import LruCache

        _BATCH_FN_CACHE = LruCache(
            max_entries=int(os.environ.get("PINOT_TPU_BATCH_PLAN_ENTRIES", "64")),
            name="compile.batch",
        )
    return _BATCH_FN_CACHE


_BATCH_FN_CACHE = None


def launch_segment_batch(
    ctxs: List[QueryContext], segment: ImmutableSegment, device=None, residency=None
):
    """Dispatch N same-shape queries over one segment as a SINGLE vmapped
    kernel launch: member literal-parameter pytrees stack along a leading
    `query` axis (r9 made literals device args, so stacking needs no
    retrace), segment columns are shared (in_axes None), and the vmapped
    jitted fn lives in a bounded LRU keyed on the plan-cache key + lane
    width so batching never causes recompile churn.

    Per-member ExecutionStats divide the physical launch's cost — docs
    scanned, kernel bytes/flops — across the N live members (padding lanes
    attributed to nobody), so summing member stats reproduces ONE unbatched
    run of the same query, not N copies.  compile_ms lands on member 0.

    Raises BatchShapeError when members don't resolve to one compiled plan
    (callers fall back to per-member launches).  Star-tree shortcuts are
    intentionally not taken here — members were vetted as batchable by the
    broker before coalescing."""
    import jax

    n = len(ctxs)
    if n < 1:
        raise ValueError("launch_segment_batch needs at least one member")
    plans = [planner.plan_segment(ctx, segment) for ctx in ctxs]
    base = plans[0]
    for p in plans[1:]:
        if p.fn is not base.fn or p.kind != base.kind:
            raise BatchShapeError(
                "batch members resolved to different compiled plans"
            )
    width = batch_width()
    if n > width:
        raise BatchShapeError(f"batch of {n} exceeds lane width {width}")

    shared_keys = frozenset(k for k in base.params if k == "__valid__")
    params_list = [p.params for p in plans]
    if n < width:
        params_list = params_list + [plans[-1].params] * (width - n)
    cols = segment.to_device(
        device=device, columns=base.needed_columns, packed_codes=True,
        residency=residency,
    )
    stacked = {}
    for k, v0 in base.params.items():
        if k in shared_keys:
            stacked[k] = jax.device_put(v0, device)
        else:
            stacked[k] = jax.device_put(
                jax.tree_util.tree_map(
                    lambda *xs: np.stack([np.asarray(x) for x in xs]),
                    *(pl[k] for pl in params_list),
                ),
                device,
            )

    key = (base.cache_key or id(base.fn), width, shared_keys)
    cache = _batch_fn_cache()
    fnb = cache.get(key)
    first_batched = fnb is None
    if first_batched:
        axes = {k: (None if k in shared_keys else 0) for k in base.params}
        fnb = jax.jit(jax.vmap(base.fn, in_axes=(None, axes)))
        cache.put(key, fnb)
        BATCH_AUDIT.record_compile()
    else:
        BATCH_AUDIT.record_hit()

    if base.cost is None:
        # same single-lane cost model as launch_segment, so per-member
        # shares divide the identical numbers an unbatched run reports
        single = {k: jax.device_put(v, device) for k, v in base.params.items()}
        base.cost = perf.capture_cost(
            base.fn,
            (cols, single),
            perf.analytic_cost(
                segment.num_docs,
                perf.analytic_bytes_per_row(
                    segment.column(nm) for nm in base.needed_columns
                ),
                kind=base.kind,
                num_groups=base.num_groups,
                num_entries=len(base.aggs),
            ),
        )
    t0 = time.perf_counter()
    out = fnb(cols, stacked)  # async dispatch; one device_get at collect
    # deliberately times the dispatch: the first vmapped call pays
    # trace+compile inline, and THAT is the cost being recorded
    compile_ms = (time.perf_counter() - t0) * 1000.0 if first_batched else 0.0  # pinot-lint: disable=W017

    docs = segment.num_docs
    share, rem = divmod(docs, n)
    stats_list = []
    for i in range(n):
        st = ExecutionStats(
            num_segments_queried=1,
            num_segments_processed=1,
            num_docs_scanned=share + (1 if i < rem else 0),
            total_docs=docs,
        )
        st.filter_index_uses = tuple(plans[i].index_uses)
        st.kernel_bytes = base.cost.bytes_accessed / n
        st.kernel_flops = base.cost.flops / n
        st.kernel_cost_source = base.cost.source
        stats_list.append(st)
    if first_batched:
        stats_list[0].compile_ms = compile_ms + base.cost.lower_ms
    return ("pending_batch", ctxs, segment, plans, out, stats_list)


def collect_segment_batch(state):
    """Phase 2 of a batched launch: ONE device_get fence for all members,
    then per-member unstack (leading `query` axis) and host decode via the
    same path the unbatched collect uses — batched results are bit-exact
    vs. sequential execution."""
    import jax

    _, ctxs, segment, plans, out, stats_list = state
    host = jax.device_get(out)
    results = []
    for i, (ctx, plan, st) in enumerate(zip(ctxs, plans, stats_list)):
        member = jax.tree_util.tree_map(lambda a: a[i], host)
        results.append(_decode_host(ctx, segment, plan, member, st))
    return results


def execute_segment(ctx: QueryContext, segment: ImmutableSegment, device=None):
    """Run one query on one segment; returns (SegmentResult, ExecutionStats)."""
    return collect_segment(launch_segment(ctx, segment, device=device))


def _key_space_id(plan) -> Tuple:
    parts = []
    for gd in plan.group_dims:
        if gd.kind == "dict":
            parts.append(("dict", gd.name, gd.dictionary.fingerprint(), gd.null_code))
        else:
            parts.append(("rawint", gd.name, gd.base, gd.cardinality))
    return tuple(parts)


def _order_trim_select(aggs, partials_for, candidates_key, order_trim, limit):
    """Indices (into the candidate set) surviving an ORDER BY-aware trim:
    rank by the order aggregation's FINAL value (NaN last), tie-break by
    packed key — the TableResizer comparator analog.  Returns None when the
    order value is not rankable (object finals), signalling the caller to
    fall back to the deterministic lowest-key trim."""
    idx, asc = order_trim
    try:
        vals = np.asarray(aggs[idx].final(partials_for(idx)))
    except Exception:
        return None
    if vals.dtype == object or not np.issubdtype(vals.dtype, np.number):
        return None
    k = vals.astype(np.float64)
    if not asc:
        k = -k
    k = np.where(np.isnan(k), np.inf, k)
    sel = np.lexsort((candidates_key, k))[:limit]
    sel.sort()
    return sel


def _dense_to_present(
    plan, presence: np.ndarray, partials, num_groups_limit: Optional[int] = None,
    order_trim: Optional[Tuple[int, bool]] = None,
) -> Tuple[List[np.ndarray], List[Dict]]:
    """Dense table -> (decoded keys, partials) for present groups only.

    num_groups_limit caps TRACKED groups (the numGroupsLimit safety valve,
    InstancePlanMakerImplV2.java:100-120).  With an ORDER BY over an
    aggregate, the trim ranks groups by the comparator (TableResizer.java
    analog); otherwise lowest packed keys win (deterministic)."""
    present = np.nonzero(presence > 0)[0]
    if num_groups_limit is not None and len(present) > num_groups_limit:
        sel = None
        if order_trim is not None:
            sel = _order_trim_select(
                plan.aggs,
                lambda i: {f: np.asarray(a)[present] for f, a in partials[i].items()},
                present,
                order_trim,
                num_groups_limit,
            )
        present = present[sel] if sel is not None else present[:num_groups_limit]
    keys = planner.decode_packed_keys(plan.group_dims, present)
    sliced = [{f: np.asarray(arr)[present] for f, arr in p.items()} for p in partials]
    return keys, sliced


def sparse_tables_to_result(
    group_dims, aggs, uniq, partials, num_groups_limit: int,
    order_trim: Optional[Tuple[int, bool]] = None,
    assume_unique: bool = False,
) -> GroupBySegmentResult:
    """Decode fixed-size sparse group tables (planner.sparse_grouped_tables)
    into a GroupBySegmentResult, merging slots that share a key.

    Handles both the single-kernel shape ([K] tables, keys already unique)
    and the multi-device shape ([ndev*K] concatenated per-device tables,
    where the same key may appear on several devices — the IndexedTable
    merge the reference runs in CombineOperator).  Only table-sized arrays
    are touched; nothing here is row-length.

    assume_unique: the caller already merged duplicate keys (the device-side
    ops.merge_sparse_tables path) — keys are unique, ascending, and any
    order-aware trim has been applied; this just drops empty padding slots
    and decodes, no unique/fold pass."""
    uniq = np.asarray(uniq).reshape(-1)
    present = uniq != planner.SPARSE_EMPTY_KEY
    if assume_unique:
        u = uniq[present]
        if len(u) > num_groups_limit:  # defensive; device merge already trims
            present = present & (np.cumsum(present) <= num_groups_limit)
            u = u[:num_groups_limit]
        out = [
            {f: np.asarray(arr)[present] for f, arr in p.items()} for p in partials
        ]
        keys = planner.decode_packed_keys(group_dims, u)
        return GroupBySegmentResult(keys=keys, partials=out, dense=None)
    keys_flat = uniq[present]
    u, inverse = np.unique(keys_flat, return_inverse=True)
    if len(u) > num_groups_limit and order_trim is None:
        # numGroupsLimit safety valve (InstancePlanMakerImplV2.java:100-120):
        # lowest packed keys win — deterministic, documented trim.  With an
        # ORDER BY comparator the trim instead happens AFTER the fold below,
        # over fully merged per-group partials (TableResizer analog).
        keep = inverse < num_groups_limit
        u = u[:num_groups_limit]
        inverse = inverse[keep]
    else:
        keep = None
    n_groups = len(u)

    # Padded per-group row matrix: mat[g] lists the slot rows carrying key g
    # (-1 padding).  Duplicate keys only arise on the multi-device shape, so
    # the fold depth is <= ndev; one vectorized combine per fold level merges
    # every group at once — scalar fields, vector fields (present/hll/hist
    # [slots, W]) and pairwise-coupled partials (KMV, (t, v)) all ride it.
    counts = np.bincount(inverse, minlength=n_groups) if len(inverse) else np.zeros(n_groups, np.int64)
    maxc = int(counts.max(initial=1))
    order = np.argsort(inverse, kind="stable")
    starts = np.zeros(n_groups, dtype=np.int64)
    np.cumsum(counts[:-1], out=starts[1:] if n_groups > 1 else starts[:0])
    mat = np.full((n_groups, maxc), -1, dtype=np.int64)
    if len(order):
        col = np.arange(len(order)) - starts[inverse[order]]
        mat[inverse[order], col] = order

    first = np.maximum(mat[:, 0], 0)
    out: List[Dict[str, np.ndarray]] = []
    for fn, p in zip(aggs, partials):
        rows: Dict[str, np.ndarray] = {}
        for fname, arr in p.items():
            a = np.asarray(arr)
            a = a[present] if keep is None else a[present][keep]
            rows[fname] = a
        acc = {f: a[first] for f, a in rows.items()}
        for j in range(1, maxc):
            validj = mat[:, j] >= 0
            if not validj.any():
                break
            idx = np.maximum(mat[:, j], 0)
            other = {f: a[idx] for f, a in rows.items()}
            if getattr(fn, "pairwise_merge", False):
                merged = fn.merge(acc, other)
            else:
                merged = {f: combine_field(f, acc[f], other[f]) for f in acc}
            for f in acc:
                v = validj.reshape((-1,) + (1,) * (acc[f].ndim - 1))
                acc[f] = np.where(v, merged[f], acc[f])
        out.append(acc)

    if order_trim is not None and n_groups > num_groups_limit:
        sel = _order_trim_select(aggs, lambda i: out[i], u, order_trim, num_groups_limit)
        if sel is None:
            sel = np.arange(num_groups_limit)  # u is sorted: lowest keys
        u = u[sel]
        out = [{f: a[sel] for f, a in p.items()} for p in out]
    keys = planner.decode_packed_keys(group_dims, u)
    return GroupBySegmentResult(keys=keys, partials=out, dense=None)


def _gather_selection(ctx: QueryContext, plan, segment: ImmutableSegment, tmask: np.ndarray) -> SelectionSegmentResult:
    """Host-side row gather for selection queries, with per-segment trim
    (SelectionOnly / SelectionOrderBy operator analog)."""
    from pinot_tpu.query.ir import WindowSpec

    docids = np.nonzero(tmask)[0]
    # window functions rank/aggregate over ALL matched rows, and UNNEST
    # drops empty-MV rows AFTER gathering — per-segment trim would change
    # results for both, so it is disabled (bounded by a valve)
    has_unnest = any(
        isinstance(s, Expr) and s.kind.name == "CALL" and s.op == "unnest" for s in ctx.select_list
    )
    if ctx.windows or has_unnest:
        cap = int(ctx.options.get("maxWindowRows", 1_000_000))
        if len(docids) > cap:
            raise ValueError(f"window/unnest query matched {len(docids)} rows > maxWindowRows={cap}")
        want = len(docids)
    else:
        want = ctx.offset + ctx.limit
    if ctx.order_by:
        if len(docids) > want:
            # Per-segment trim: WITHIN one segment dict codes are sort ranks
            # (sorted dictionary), so a numeric lexsort on codes/values is a
            # correct local top-k regardless of type.  Expression keys
            # evaluate host-side over the matched rows (O(matched)).
            # lexsort's primary key is the LAST array; push
            # (value, null_rank) per order-by expr in reverse significance.
            lex_keys: List[np.ndarray] = []
            for ob in reversed(ctx.order_by):
                if ob.expr.is_column:
                    value_key, null_rank = _local_order_key(
                        segment, ob.expr.op, docids, ob.ascending, ob.nulls_last
                    )
                else:
                    value_key, null_rank = _expr_order_key(
                        segment, ob.expr, docids, ob.ascending, ob.nulls_last
                    )
                lex_keys.append(value_key)
                if null_rank is not None:
                    lex_keys.append(null_rank)
            order = np.lexsort(tuple(lex_keys))[:want]
            docids = docids[order]
    else:
        docids = docids[:want]
    arrays: Dict[str, np.ndarray] = {}

    def _decoded(name: str) -> np.ndarray:
        c = segment.column(name)
        vals = c.decoded()[docids]
        if c.nulls is not None and ctx.null_handling:
            vals = np.asarray(vals, dtype=object)
            vals[c.nulls[docids]] = None
        return vals

    def _value_array(e) -> np.ndarray:
        return _decoded(e.op) if e.is_column else eval_expr_host(e, segment, docids)

    out_keys: List[str] = []
    items = plan.select_exprs or [planner.Expr.col(n) for n in plan.select_columns]
    # window keys are indexed by position in ctx.select_list (what reduce
    # enumerates), NOT the *-expanded items index
    win_positions = iter(i for i, s in enumerate(ctx.select_list) if isinstance(s, WindowSpec))
    for i, e in enumerate(items):
        if isinstance(e, WindowSpec):
            # placeholder output slot (reduce overwrites after the global
            # merge) + the window's input arrays keyed by expr fingerprint
            key = f"__win{next(win_positions)}"
            out_keys.append(key)
            arrays[key] = np.zeros(len(docids))
            for ie in list(e.partition_by) + [o.expr for o in e.order_by] + ([e.expr] if e.expr else []):
                wkey = f"__wx_{ie.fingerprint()}"
                if wkey not in arrays:
                    arrays[wkey] = _value_array(ie)
            continue
        if e.is_column:
            out_keys.append(e.op)
            arrays[e.op] = _decoded(e.op)
            continue
        if e.kind.name == "CALL" and e.op == "unnest":
            key = f"__sel{i}"
            out_keys.append(key)
            arrays[key] = np.zeros(len(docids), dtype=object)  # filled by the explode below
            continue
        # expression select item: host evaluation over the gathered rows only
        # (O(limit), TransformOperator-on-selection analog)
        key = f"__sel{i}"
        out_keys.append(key)
        vals = eval_expr_host(e, segment, docids)
        nmask = None
        if ctx.null_handling:
            for cname in e.columns():
                cn = segment.column(cname).nulls
                if cn is not None:
                    m = cn[docids]
                    nmask = m if nmask is None else (nmask | m)
        if nmask is not None and nmask.any():
            vals = np.asarray(vals, dtype=object)
            vals[nmask] = None
        arrays[key] = vals
    # Cross-segment merge needs real VALUES for order columns (codes are
    # segment-local); reduce.py re-sorts the concatenated trimmed rows.
    for i, ob in enumerate(ctx.order_by):
        arrays[f"__ord{i}"] = _value_array(ob.expr)
    cols = out_keys + [f"__ord{i}" for i in range(len(ctx.order_by))]
    cols += sorted(k for k in arrays if k.startswith("__wx_"))

    # UNNEST(mvcol): explode each gathered row once per element (the MSE
    # UnnestOperator analog on the selection path; zero-length rows drop)
    unnest_keys = [
        (k, e)
        for k, e in zip(out_keys, items)
        if isinstance(e, planner.Expr) and e.kind.name == "CALL" and e.op == "unnest"
    ]
    if unnest_keys:
        if len(unnest_keys) > 1:
            raise NotImplementedError("one UNNEST per query")
        ukey, uexpr = unnest_keys[0]
        if not (len(uexpr.args) == 1 and uexpr.args[0].is_column):
            raise NotImplementedError("UNNEST takes a bare multi-value column")
        c = segment.column(uexpr.args[0].op)
        if c.mv_lengths is None:
            raise ValueError(f"UNNEST requires a multi-value column ({uexpr.args[0].op})")
        reps = c.mv_lengths[docids].astype(np.int64)
        idx = np.repeat(np.arange(len(docids)), reps)
        elems = np.concatenate(
            [list(t) for t in c.decoded()[docids] if len(t)] or [np.array([], dtype=object)]
        )
        new_arrays: Dict[str, np.ndarray] = {}
        for k in cols:
            if k == ukey:
                new_arrays[k] = np.asarray(elems, dtype=object)
            else:
                new_arrays[k] = np.asarray(arrays[k], dtype=object)[idx]
        arrays = new_arrays
    return SelectionSegmentResult(columns=cols, arrays=arrays)


def order_key_arrays(
    codes: Optional[np.ndarray],
    values: Optional[np.ndarray],
    nulls: Optional[np.ndarray],
    docids: np.ndarray,
    ascending: bool,
    nulls_last: bool,
):
    """(value_key, null_rank) lexsort keys for ORDER BY, keeping integer
    dtypes intact (no float64 cast: LONG values above 2^53 must not collide).
    Shared by the per-segment selection trim and the distributed gather
    (codes are sort ranks within their dictionary's key space)."""
    if codes is not None:
        key = np.asarray(codes)[docids].astype(np.int64)
    else:
        key = np.asarray(values)[docids]
    if not ascending:
        key = -key.astype(np.int64) if np.issubdtype(key.dtype, np.integer) else -key.astype(np.float64)
    null_rank = None
    if nulls is not None:
        nullm = np.asarray(nulls)[docids]
        null_rank = np.where(nullm, np.int8(1 if nulls_last else -1), np.int8(0))
        key = np.where(nullm, key.dtype.type(0), key)
    return key, null_rank


def _expr_order_key(
    segment: ImmutableSegment, expr, docids: np.ndarray, ascending: bool, nulls_last: bool
):
    """(lexsort key, null_rank) for an ORDER BY expression: host evaluation
    over matched rows; a row is NULL when any input column is null there
    (SQL null propagation), ranked by NULLS FIRST/LAST — not by whatever
    placeholder value the expression computed (review-caught)."""
    vals = eval_expr_host(expr, segment, docids)
    nullm = None
    for cname in expr.columns():
        cn = segment.column(cname).nulls
        if cn is not None:
            m = cn[docids]
            nullm = m if nullm is None else (nullm | m)
    a = np.asarray(vals)
    if a.dtype == object:
        none_m = np.array([v is None for v in a], dtype=bool)
        if none_m.any():
            nullm = none_m if nullm is None else (nullm | none_m)
            a = a.copy()
            a[none_m] = 0
        try:
            a = a.astype(np.float64)
        except (ValueError, TypeError):
            pass
    if np.issubdtype(a.dtype, np.number):
        key = a.astype(np.float64)
        key = key if ascending else -key
    else:
        _, inv = np.unique(a.astype(str), return_inverse=True)
        key = inv if ascending else -inv
    null_rank = None
    if nullm is not None and nullm.any():
        null_rank = np.where(nullm, np.int8(1 if nulls_last else -1), np.int8(0))
        key = np.where(nullm, 0, key)
    return key, null_rank


def _local_order_key(segment: ImmutableSegment, col: str, docids: np.ndarray, ascending: bool, nulls_last: bool):
    c = segment.column(col)
    return order_key_arrays(c.codes, c.values, c.nulls, docids, ascending, nulls_last)
