"""Filter compilation: predicate tree -> device mask computation.

Reference parity: pinot-core's filter operators + predicate evaluators
(BaseFilterOperator subclasses, .../operator/filter/; dictionary-based
evaluators in .../operator/filter/predicate/).  The key Pinot trick is kept
and tensorized:

  * Dictionary-based evaluation: predicates on dict-encoded columns are
    resolved AGAINST THE SORTED DICTIONARY host-side, then evaluated on the
    code array on device as either
      - a closed-form code-range compare (EQ/RANGE -> lo <= code < hi), or
      - a boolean lookup table over the dictionary space, gathered by code
        (IN/NOT_IN/REGEXP/LIKE -> table[codes]); O(rows) regardless of the
        predicate's value-set size, and it makes regex a device-side tensor
        op because the regex only ever ran over the dictionary.
  * Raw columns use direct vectorized value compares (ScanBasedFilterOperator
    analog — except a TPU scan IS the vector unit's native mode).
  * AND/OR/NOT are mask algebra with SQL three-valued-logic null tracking:
    each node yields (true_mask, null_mask); rows are selected iff truly true.

Per-segment dictionaries mean per-segment constants: the jitted kernel takes
them via a params pytree so equal-shaped segments share one compiled kernel.
"""
from __future__ import annotations

import re
from typing import Callable, Dict, List, Optional, Tuple

import jax.numpy as jnp
import numpy as np

from pinot_tpu.query.ir import FilterNode, FilterOp, Predicate, PredicateType
from pinot_tpu.query.transform import eval_expr, _or_masks
from pinot_tpu.segment.segment import ImmutableSegment

# (true_mask, null_mask|None)
MaskPair = Tuple[jnp.ndarray, Optional[jnp.ndarray]]
Params = Dict[str, np.ndarray]


def _match_values(p, values: np.ndarray) -> np.ndarray:
    """Evaluate a predicate over a (derived) value array -> bool table.
    Used by derived-string predicates, where codes are NOT sort ranks of the
    derived values, so everything is a table lookup (no code ranges)."""
    pt = p.ptype
    if pt is PredicateType.EQ:
        return np.array([v == p.values[0] for v in values], dtype=bool)
    if pt is PredicateType.NEQ:
        return np.array([v != p.values[0] for v in values], dtype=bool)
    if pt in (PredicateType.IN, PredicateType.NOT_IN):
        s = set(p.values)
        t = np.array([v in s for v in values], dtype=bool)
        return ~t if pt is PredicateType.NOT_IN else t
    if pt is PredicateType.RANGE:
        t = np.ones(len(values), dtype=bool)
        if p.lower is not None:
            t &= np.array(
                [(v >= p.lower if p.lower_inclusive else v > p.lower) for v in values], dtype=bool
            )
        if p.upper is not None:
            t &= np.array(
                [(v <= p.upper if p.upper_inclusive else v < p.upper) for v in values], dtype=bool
            )
        return t
    if pt in (PredicateType.REGEXP_LIKE, PredicateType.LIKE):
        pat = p.values[0]
        rx = re.compile(pat if pt is PredicateType.REGEXP_LIKE else like_to_regex(pat))
        return np.array([rx.search(str(v)) is not None for v in values], dtype=bool)
    raise ValueError(f"predicate {pt} not supported on derived string values")


def like_to_regex(pattern: str) -> str:
    """SQL LIKE -> anchored regex (Pinot LikeToRegexpLikePatternConverter)."""
    out = []
    for ch in pattern:
        if ch == "%":
            out.append(".*")
        elif ch == "_":
            out.append(".")
        else:
            out.append(re.escape(ch))
    return "^" + "".join(out) + "$"


# IN/NOT_IN/regex tables resolve through the inverted index only up to this
# many bitmap-row ORs (past it a code scan reads less)
_INV_MAX_ROWS = 256


class FilterCompiler:
    """Compiles one filter tree against one segment.

    Produces (a) a params dict of per-segment device constants and (b) an
    eval closure usable inside jit.  Param keys follow traversal order, so
    segments with the same query shape produce structurally identical params
    pytrees -> one jit cache entry per (query, segment-signature).

    Index acceleration (round 2 — BitmapBasedFilterOperator /
    SortedIndexBasedFilterOperator analogs,
    pinot-core/.../operator/filter/BitmapBasedFilterOperator.java:29):
      * sorted column + code-range predicate -> contiguous doc range, two
        int params, ZERO row reads on device;
      * range index + code-range predicate -> prefix[hi] & ~prefix[lo]
        resolved host-side from the mmap'd index (n/8 bytes), shipped as a
        packed-words param and bit-unpacked on device;
      * inverted index + small dictId set -> OR of bitmap rows, same.
    The device never rescans the code array for such predicates, and if a
    column is touched ONLY by index-resolved predicates its codes are never
    shipped to HBM at all (planner prunes via `used_columns`).
    `index_uses` records (column, kind) per accelerated predicate for
    ExecutionStats."""

    def __init__(self, segment: ImmutableSegment, null_handling: bool = True):
        self.segment = segment
        self.null_handling = null_handling
        self.params: Params = {}
        self._counter = 0
        # columns whose device entries the compiled closures will read
        self.used_columns = set()
        # (column, "sorted"|"range"|"inverted") per index-accelerated predicate
        self.index_uses: List[Tuple[str, str]] = []
        # Sharded compilation target (_ShardView): (axis_name, ndev,
        # local_rows) — bitmap params split on the leading device axis and
        # doc ranges compare against GLOBAL flat doc ids (parallel/engine.py)
        self.shard_info: Optional[Tuple[str, int, int]] = getattr(segment, "shard_info", None)
        # Macro-batch launches (parallel/engine.py): per-device global doc
        # ids come from a params-dependent closure (the batch offset is a
        # param), and bitmap words are stored FULL as [ndev, L, D//32] so
        # the engine can slice the doc axis per launch.
        self.docs_fn = getattr(segment, "docs_fn", None)
        self.bitmap_layout: Optional[Tuple[int, int, int]] = getattr(segment, "bitmap_layout", None)
        # param keys whose leading axis is the device axis (in_spec P(axis))
        self.row_sharded_params: set = set()
        # bitmap param keys that are PLAIN (not negated, no null guard) —
        # candidates for staying packed through a fused Pallas scan
        self._plain_bitmaps: set = set()
        # set when the ROOT filter is exactly one plain bitmap predicate:
        # the engine can then skip the unpack entirely and hand the packed
        # words to the fused scan (pallas_scan word-slicing)
        self.sole_bitmap_param: Optional[str] = None
        self._root_compiled = False

    def _key(self, suffix: str) -> str:
        k = f"f{self._counter}.{suffix}"
        self._counter += 1
        return k

    def _col_index(self, kind: str, name: str):
        idx = getattr(self.segment, "indexes", None)
        if not idx:
            return None
        return idx.get(kind, {}).get(name)

    def _cache_index(self, kind: str, name: str, idx) -> None:
        """Cache a lazily-built (text/json) index on the segment so repeated
        queries pay the cardinality-sized build once."""
        store = getattr(self.segment, "indexes", None)
        if isinstance(store, dict):
            store.setdefault(kind, {})[name] = idx

    # ------------------------------------------------------------------
    def compile(self, node: Optional[FilterNode]) -> Callable[[Dict, Dict], MaskPair]:
        is_root = not self._root_compiled
        self._root_compiled = True
        if node is None:
            n = self.segment.num_docs

            def match_all(cols, params):
                return jnp.ones((n,), dtype=bool), None

            return match_all
        before_keys = set(self.params)
        fn = self._compile_node(node)
        if is_root and node.op is FilterOp.PRED:
            new_keys = set(self.params) - before_keys
            if len(new_keys) == 1 and next(iter(new_keys)) in self._plain_bitmaps:
                self.sole_bitmap_param = next(iter(new_keys))
        return fn

    def _compile_node(self, node: FilterNode) -> Callable[[Dict, Dict], MaskPair]:
        if node.op is FilterOp.PRED:
            return self._compile_predicate(node.predicate)
        children = [self._compile_node(c) for c in node.children]
        if node.op is FilterOp.AND:

            def eval_and(cols, params):
                t, nl = children[0](cols, params)
                for c in children[1:]:
                    t2, n2 = c(cols, params)
                    # null = at least one null, no false (3VL)
                    if nl is None and n2 is None:
                        t = t & t2
                        continue
                    f1 = ~t & (jnp.zeros_like(t) if nl is None else ~nl)
                    f2 = ~t2 & (jnp.zeros_like(t2) if n2 is None else ~n2)
                    nl = (_or_masks(nl, n2)) & ~f1 & ~f2
                    t = t & t2
                return t, nl

            return eval_and
        if node.op is FilterOp.OR:

            def eval_or(cols, params):
                t, nl = children[0](cols, params)
                for c in children[1:]:
                    t2, n2 = c(cols, params)
                    t = t | t2
                    nl = _or_masks(nl, n2)
                if nl is not None:
                    nl = nl & ~t
                return t, nl

            return eval_or
        if node.op is FilterOp.NOT:

            def eval_not(cols, params):
                t, nl = children[0](cols, params)
                if nl is None:
                    return ~t, None
                return ~t & ~nl, nl

            return eval_not
        raise ValueError(f"unknown filter op {node.op}")

    # ------------------------------------------------------------------
    def _compile_predicate(self, p: Predicate) -> Callable[[Dict, Dict], MaskPair]:
        seg = self.segment
        # IS_NULL / IS_NOT_NULL act on the column's null vector directly.
        if p.ptype in (PredicateType.IS_NULL, PredicateType.IS_NOT_NULL):
            if not p.lhs.is_column:
                raise ValueError("IS [NOT] NULL requires a bare column")
            col = seg.column(p.lhs.op)
            want_null = p.ptype is PredicateType.IS_NULL
            has_nulls = col.nulls is not None and self.null_handling
            if has_nulls:
                self.used_columns.add(p.lhs.op)
            n = seg.num_docs

            def eval_null(cols, params, _want=want_null, _has=has_nulls, _name=p.lhs.op):
                if not _has:
                    return (jnp.zeros((n,), bool) if _want else jnp.ones((n,), bool)), None
                nulls = cols[_name]["nulls"]
                return (nulls if _want else ~nulls), None

            return eval_null

        if p.ptype is PredicateType.VECTOR_SIMILARITY:
            return self._compile_vector_predicate(p)
        if p.lhs.is_column and seg.column(p.lhs.op).has_dictionary:
            return self._compile_dict_predicate(p)
        from pinot_tpu.query import scalar

        if (
            scalar.is_dict_fn_expr(p.lhs)
            and scalar.string_result(p.lhs)
        ):
            return self._compile_derived_string_predicate(p)
        return self._compile_value_predicate(p)

    def _compile_vector_predicate(self, p: Predicate) -> Callable[[Dict, Dict], MaskPair]:
        """VECTOR_SIMILARITY(col, queryVec, topK): one MXU matvec over the
        HBM-resident embedding matrix + lax.top_k — exact cosine top-k (the
        reference's HNSW is approximate; brute-force is the TPU-idiomatic
        trade, indexes/vector.py).  Ties at the kth score may admit extras."""
        import jax

        from pinot_tpu.indexes.vector import parse_query_vector

        if not p.lhs.is_column:
            raise ValueError("VECTOR_SIMILARITY requires a bare vector column")
        name = p.lhs.op
        vidx = self._col_index("vector", name)
        if vidx is None:
            raise ValueError(
                f"VECTOR_SIMILARITY requires a vector index on {name} (tableIndexConfig.vectorIndexColumns)"
            )
        q = vidx.normalize_query(parse_query_vector(p.values[0]))
        k = int(p.values[1]) if len(p.values) > 1 else 10
        key = self._key("qvec")
        self.params[key] = q
        self.used_columns.add(name)
        self.index_uses.append((name, "vector"))
        dim = vidx.dim

        def eval_vec(cols, params, _key=key, _name=name, _k=k, _dim=dim):
            m = cols[_name]["values"][:, :_dim].astype(jnp.float32)
            norms = jnp.sqrt(jnp.sum(m * m, axis=1))
            scores = (m @ params[_key]) / jnp.where(norms == 0, 1.0, norms)
            scores = jnp.where(norms == 0, -jnp.inf, scores)
            kk = min(_k, scores.shape[0])
            thresh = jax.lax.top_k(scores, kk)[0][-1]
            return scores >= thresh, None

        return eval_vec

    def _compile_derived_string_predicate(self, p: Predicate) -> Callable[[Dict, Dict], MaskPair]:
        """Predicate over a string function of a dict column — e.g.
        WHERE UPPER(city) = 'SF'.  The function evaluates over the
        DICTIONARY'S VALUES (cardinality work, host-side), the predicate over
        the derived values yields a code table, and the device work is the
        same table[codes] lookup as any dictionary predicate."""
        from pinot_tpu.query import scalar

        name = next(a for a in p.lhs.args if not a.is_literal).op
        col = self.segment.column(name)
        if not col.has_dictionary:
            raise ValueError(f"{p.lhs.op} predicate requires dictionary column, {name} is raw")
        derived = scalar.derived_for(p.lhs, col.dictionary)
        table = _match_values(p, derived)
        has_nulls = col.nulls is not None and self.null_handling
        key = self._key("dtable")
        self.params[key] = table
        self.used_columns.add(name)

        def eval_table(cols, params, _key=key, _name=name, _has=has_nulls):
            codes = cols[_name]["codes"].astype(jnp.int32)
            t = params[_key][codes]
            nulls = cols[_name].get("nulls") if _has else None
            if nulls is not None:
                t = t & ~nulls
            return t, nulls

        return eval_table

    # -- dictionary-based ------------------------------------------------
    def _compile_dict_predicate(self, p: Predicate) -> Callable[[Dict, Dict], MaskPair]:
        name = p.lhs.op
        col = self.segment.column(name)
        d = col.dictionary
        card = d.cardinality
        values = d.values
        pt = p.ptype
        # Multi-value columns: predicates match a row when ANY element
        # matches (the reference's per-value MV predicate semantics).  The
        # padded code matrix evaluates elementwise, then any(axis=1); the
        # padding code (== cardinality) must stay no-match, so code tables
        # get an explicit False pad slot — including after NEQ/NOT_IN
        # negation — and code ranges can never reach it (hi <= card).
        is_mv = getattr(col, "is_multi_value", False)

        lo_code = hi_code = None
        table: Optional[np.ndarray] = None

        if pt is PredicateType.EQ:
            i = d.index_of(p.values[0])
            lo_code, hi_code = (i, i + 1) if i >= 0 else (0, 0)
        elif pt is PredicateType.NEQ:
            i = d.index_of(p.values[0])
            table = np.ones(card, dtype=bool)
            if i >= 0:
                table[i] = False
        elif pt is PredicateType.RANGE:
            lo_code = 0
            hi_code = card
            # raw literals into searchsorted: numpy's cross-dtype compare keeps
            # 2.5 between 2 and 3 on an INT dictionary (no truncation).
            if p.lower is not None:
                lo_code = int(np.searchsorted(values, p.lower, side="left" if p.lower_inclusive else "right"))
            if p.upper is not None:
                hi_code = int(np.searchsorted(values, p.upper, side="right" if p.upper_inclusive else "left"))
        elif pt in (PredicateType.IN, PredicateType.NOT_IN):
            table = np.zeros(card, dtype=bool)
            for v in p.values:
                i = d.index_of(v)
                if i >= 0:
                    table[i] = True
            if pt is PredicateType.NOT_IN:
                table = ~table
        elif pt in (PredicateType.REGEXP_LIKE, PredicateType.LIKE):
            pat = p.values[0]
            rx = re.compile(pat if pt is PredicateType.REGEXP_LIKE else like_to_regex(pat))
            # regex over the dictionary, not the rows — card evaluations total.
            table = np.fromiter((rx.search(str(v)) is not None for v in values), dtype=bool, count=card)
        elif pt is PredicateType.TEXT_MATCH:
            from pinot_tpu.indexes.text import TextIndex

            idx = self._col_index("text", name)
            if idx is None:
                idx = TextIndex.build(values)  # lazy: cardinality work, cached below
                self._cache_index("text", name, idx)
            else:
                self.index_uses.append((name, "text"))
            table = idx.match(str(p.values[0]))
        elif pt is PredicateType.JSON_MATCH:
            from pinot_tpu.indexes.jsonidx import JsonIndex

            idx = self._col_index("json", name)
            if idx is None:
                idx = JsonIndex.build(values)
                self._cache_index("json", name, idx)
            else:
                self.index_uses.append((name, "json"))
            table = idx.match(str(p.values[0]))
        else:
            raise ValueError(f"predicate {pt} not supported on dictionary column {name}")

        has_nulls = col.nulls is not None and self.null_handling

        # -- index-accelerated paths (no code scan) ----------------------
        if not is_mv:
            accel = self._try_index_paths(name, col, lo_code, hi_code, table, has_nulls)
            if accel is not None:
                return accel

        if table is not None:
            if is_mv:
                table = np.append(table, False)  # padding code slot
            key = self._key("table")
            self.params[key] = table
            self.used_columns.add(name)

            def eval_table(cols, params, _key=key, _name=name, _has=has_nulls):
                codes = cols[_name]["codes"].astype(jnp.int32)
                t = params[_key][codes]
                if t.ndim == 2:
                    t = jnp.any(t, axis=1)
                nulls = cols[_name].get("nulls") if _has else None
                if nulls is not None:
                    t = t & ~nulls
                return t, nulls

            return eval_table

        lo_key = self._key("lo")
        hi_key = self._key("hi")
        self.params[lo_key] = np.int32(lo_code)
        self.params[hi_key] = np.int32(hi_code)
        self.used_columns.add(name)

        def eval_range(cols, params, _lo=lo_key, _hi=hi_key, _name=name, _has=has_nulls):
            codes = cols[_name]["codes"].astype(jnp.int32)
            t = (codes >= params[_lo]) & (codes < params[_hi])
            if t.ndim == 2:
                t = jnp.any(t, axis=1)
            nulls = cols[_name].get("nulls") if _has else None
            if nulls is not None:
                t = t & ~nulls
            return t, nulls

        return eval_range

    # -- index-accelerated predicate compilation -------------------------
    def _null_guard(self, name: str, has_nulls: bool):
        if has_nulls:
            self.used_columns.add(name)

    def _emit_doc_range(self, name: str, d0: int, d1: int, has_nulls: bool):
        n = self.segment.num_docs
        lo_key = self._key("d0")
        hi_key = self._key("d1")
        self.params[lo_key] = np.int32(d0)
        self.params[hi_key] = np.int32(d1)
        self._null_guard(name, has_nulls)
        self.index_uses.append((name, "sorted"))
        shard_info = self.shard_info
        docs_fn = self.docs_fn

        def eval_docrange(cols, params, _lo=lo_key, _hi=hi_key, _name=name, _has=has_nulls):
            if docs_fn is not None:
                docs = docs_fn(params)
            elif shard_info is not None:
                axis, _, local_rows = shard_info
                from jax import lax

                base = lax.axis_index(axis).astype(jnp.int32) * jnp.int32(local_rows)
                docs = base + jnp.arange(local_rows, dtype=jnp.int32)
            else:
                docs = jnp.arange(n, dtype=jnp.int32)
            t = (docs >= params[_lo]) & (docs < params[_hi])
            nulls = cols[_name].get("nulls") if _has else None
            if nulls is not None:
                t = t & ~nulls
            return t, nulls

        return eval_docrange

    def _emit_bitmap(self, name: str, words: np.ndarray, kind: str, has_nulls: bool, negate: bool):
        n = self.segment.num_docs
        key = self._key("bits")
        words = np.ascontiguousarray(words, dtype=np.uint32)
        if self.bitmap_layout is not None:
            # macro-batch engine: store FULL words as [ndev, L, D//32]; the
            # engine slices the doc axis per launch to [ndev, L*Db//32]
            # (parallel/engine.py _batch_params)
            assert words.size == int(np.prod(self.bitmap_layout)), (words.size, self.bitmap_layout)
            words = words.reshape(self.bitmap_layout)
            self.row_sharded_params.add(key)
        elif self.shard_info is not None:
            # split words on the device axis: each device ships + unpacks
            # ONLY its slice (local_rows is 32-aligned by construction)
            _, ndev, local_rows = self.shard_info
            assert local_rows % 32 == 0 and words.size == ndev * (local_rows // 32), (
                words.size, ndev, local_rows,
            )
            words = words.reshape(ndev, local_rows // 32)
            self.row_sharded_params.add(key)
        self.params[key] = words
        if not negate and not has_nulls:
            self._plain_bitmaps.add(key)
        self._null_guard(name, has_nulls)
        self.index_uses.append((name, kind))

        def eval_bitmap(cols, params, _key=key, _name=name, _has=has_nulls, _neg=negate):
            w = params[_key].reshape(-1)
            bits = ((w[:, None] >> jnp.arange(32, dtype=jnp.uint32)) & jnp.uint32(1)) != 0
            t = bits.reshape(-1)[:n]
            if _neg:
                t = ~t
            nulls = cols[_name].get("nulls") if _has else None
            if nulls is not None:
                t = t & ~nulls
            return t, nulls

        return eval_bitmap

    def _try_index_paths(self, name, col, lo_code, hi_code, table, has_nulls):
        """Sorted doc-range > range-index > inverted-index, else None (scan)."""
        if lo_code is not None:  # code-range predicate (EQ / RANGE)
            if col.stats.is_sorted and col.codes is not None:
                codes_arr = np.asarray(col.codes)
                if codes_arr.ndim == 2:
                    # stacked [S, D]: flat row-major order IS the build input
                    # order (padding all at the tail) — slice it off so
                    # searchsorted sees the sorted run; doc ranges are in
                    # GLOBAL flat coordinates (see _emit_doc_range)
                    total = getattr(self.segment, "total_docs", None)
                    if total is None:
                        return self._try_bitmap_range(name, col, lo_code, hi_code, has_nulls)
                    codes_arr = codes_arr.reshape(-1)[:total]
                d0 = int(np.searchsorted(codes_arr, lo_code, side="left"))
                d1 = int(np.searchsorted(codes_arr, hi_code, side="left")) if hi_code > lo_code else d0
                return self._emit_doc_range(name, d0, d1, has_nulls)
            return self._try_bitmap_range(name, col, lo_code, hi_code, has_nulls)
        # table predicate (IN / NOT_IN / NEQ / regex / LIKE)
        inv = self._col_index("inverted", name)
        if inv is None:
            return None
        pos = np.nonzero(table)[0]
        neg_ids = np.nonzero(~table)[0]
        if len(pos) <= _INV_MAX_ROWS:
            words = inv.doc_bitmap(pos) if len(pos) else np.zeros(inv.num_words, np.uint32)
            return self._emit_bitmap(name, words, "inverted", has_nulls, False)
        if len(neg_ids) <= _INV_MAX_ROWS:
            words = inv.doc_bitmap(neg_ids) if len(neg_ids) else np.zeros(inv.num_words, np.uint32)
            return self._emit_bitmap(name, words, "inverted", has_nulls, True)
        return None

    def _try_bitmap_range(self, name, col, lo_code, hi_code, has_nulls):
        """Range-index / inverted-index resolution for a code-range predicate."""
        rng_idx = self._col_index("range", name)
        if rng_idx is not None:
            return self._emit_bitmap(
                name, rng_idx.range_bitmap(lo_code, hi_code), "range", has_nulls, False
            )
        inv = self._col_index("inverted", name)
        if inv is not None and (hi_code - lo_code) <= _INV_MAX_ROWS:
            ids = np.arange(lo_code, hi_code, dtype=np.int64)
            words = inv.doc_bitmap(ids) if len(ids) else np.zeros(inv.num_words, np.uint32)
            return self._emit_bitmap(name, words, "inverted", has_nulls, False)
        return None

    # -- raw-value -------------------------------------------------------
    def _compile_value_predicate(self, p: Predicate) -> Callable[[Dict, Dict], MaskPair]:
        seg = self.segment
        pt = p.ptype
        if pt in (PredicateType.REGEXP_LIKE, PredicateType.LIKE, PredicateType.TEXT_MATCH, PredicateType.JSON_MATCH):
            raise ValueError(f"{pt.value} requires a dictionary-encoded column (lhs={p.lhs})")
        null_handling = self.null_handling
        self.used_columns.update(c for c in p.lhs.columns() if c != "*")

        if pt in (PredicateType.IN, PredicateType.NOT_IN):
            from pinot_tpu.query.shape import bucket_size

            key = self._key("set")
            vals_arr = np.asarray(sorted(p.values))
            # numeric lists: normalize dtype (a value-dependent downcast
            # would make the traced program depend on the literals) and pad
            # to the bucketed size class with identity fill — repeating a
            # member never changes isin semantics, and distinct list
            # lengths within one bucket share a single compile
            # (shape-fingerprint contract, query/shape.py).
            if np.issubdtype(vals_arr.dtype, np.integer):
                vals_arr = vals_arr.astype(np.int64)
            elif np.issubdtype(vals_arr.dtype, np.floating):
                vals_arr = vals_arr.astype(np.float64)
            if vals_arr.dtype.kind in "iuf" and len(vals_arr):
                b = bucket_size(len(vals_arr))
                if b > len(vals_arr):
                    fill = np.full(b - len(vals_arr), vals_arr[0], vals_arr.dtype)
                    vals_arr = np.concatenate([vals_arr, fill])
            self.params[key] = vals_arr

            def eval_in(cols, params, _key=key, _neg=(pt is PredicateType.NOT_IN)):
                vals, nulls = eval_expr(p.lhs, seg, cols)
                t = jnp.isin(vals, params[_key])
                if _neg:
                    t = ~t
                if nulls is not None and null_handling:
                    t = t & ~nulls
                    return t, nulls
                return t, None

            return eval_in

        # raw EQ/NEQ/RANGE: numeric literals ship as scalar params, so
        # distinct literals replay one traced program (the shape-
        # fingerprint contract).  Which bounds exist and their inclusivity
        # stay trace-time structure — exactly what query/shape.py keeps in
        # the slot.  Non-numeric literals remain trace-time constants (the
        # audit keeps those predicates literal-keyed).
        def _num_param(suffix: str, v):
            if not isinstance(v, (bool, int, float)):
                return None
            key = self._key(suffix)
            if isinstance(v, bool):
                self.params[key] = np.bool_(v)
            elif isinstance(v, int):
                self.params[key] = np.int64(v)
            else:
                self.params[key] = np.float64(v)
            return key

        eq_key = lo_key = hi_key = None
        if pt in (PredicateType.EQ, PredicateType.NEQ):
            eq_key = _num_param("cmp", p.values[0])
        elif pt is PredicateType.RANGE:
            if p.lower is not None:
                lo_key = _num_param("lo", p.lower)
            if p.upper is not None:
                hi_key = _num_param("hi", p.upper)

        def eval_cmp(cols, params):
            vals, nulls = eval_expr(p.lhs, seg, cols)
            if pt is PredicateType.EQ:
                t = vals == (params[eq_key] if eq_key is not None else p.values[0])
            elif pt is PredicateType.NEQ:
                t = vals != (params[eq_key] if eq_key is not None else p.values[0])
            elif pt is PredicateType.RANGE:
                t = jnp.ones_like(vals, dtype=bool)
                if p.lower is not None:
                    lo = params[lo_key] if lo_key is not None else p.lower
                    t = t & (vals >= lo if p.lower_inclusive else vals > lo)
                if p.upper is not None:
                    hi = params[hi_key] if hi_key is not None else p.upper
                    t = t & (vals <= hi if p.upper_inclusive else vals < hi)
            else:
                raise ValueError(f"predicate {pt} unsupported on raw values")
            if nulls is not None and null_handling:
                t = t & ~nulls
                return t, nulls
            return t, None

        return eval_cmp
