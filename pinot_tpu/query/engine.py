"""In-process query engine: table registry + execute = broker+server in one.

Reference parity: this is the BaseQueriesTest topology (SURVEY.md 4.2) as a
production object — real planner + executor + reduce, no cluster required.
The cluster layer (cluster/) wraps the same engine behind broker/server
roles; the distributed combine (parallel/) slots in between execute and
reduce.
"""
from __future__ import annotations

import itertools
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from pinot_tpu.query import executor, reduce as reduce_mod
from pinot_tpu.query.ir import Expr, QueryContext
from pinot_tpu.query.result import ExecutionStats, ResultTable
from pinot_tpu.segment.segment import ImmutableSegment
from pinot_tpu.spi.config import TableConfig
from pinot_tpu.spi.schema import Schema
from pinot_tpu.utils import perf


@dataclass
class TableState:
    schema: Schema
    config: TableConfig
    segments: List[ImmutableSegment] = field(default_factory=list)
    # realtime tables: RealtimeTableDataManager owning sealed + consuming
    # segments (realtime/manager.py); None for offline tables
    realtime: Optional[object] = None

    def query_segments(self) -> List[ImmutableSegment]:
        """The segment list a query against this table scans: offline
        segments plus the realtime view (sealed + consuming snapshots)."""
        segs = list(self.segments)
        if self.realtime is not None:
            segs.extend(self.realtime.query_segments())
        return segs


class QueryEngine:
    def __init__(self, memory_budget_bytes: int = 8 << 30, secondary_slots: int = 2) -> None:
        from pinot_tpu.query.safety import MemoryAccountant, WorkloadScheduler
        from pinot_tpu.utils.slowlog import SlowQueryLog

        self.tables: Dict[str, TableState] = {}
        self.accountant = MemoryAccountant(memory_budget_bytes)
        self.scheduler = WorkloadScheduler(secondary_slots)
        self._qid_seq = itertools.count(1)
        self.slow_queries = SlowQueryLog()

    # -- table registry (controller-lite) -------------------------------
    def register_table(self, schema: Schema, config: Optional[TableConfig] = None) -> None:
        cfg = config or TableConfig(name=schema.name)
        self.tables[cfg.name] = TableState(schema=schema, config=cfg)

    def add_segment(self, table: str, segment: ImmutableSegment) -> None:
        self.tables[table].segments.append(segment)

    def table(self, name: str) -> TableState:
        if name not in self.tables:
            raise KeyError(f"table {name!r} not registered (have {list(self.tables)})")
        return self.tables[name]

    # -- execution -------------------------------------------------------
    def execute(self, ctx: QueryContext, device=None) -> ResultTable:
        from pinot_tpu.spi.env import apply_env_defaults

        apply_env_defaults(ctx.options)
        if ctx.options.get("__explain__"):
            # explain never executes anything — not subqueries, not set-op
            # components (review-caught: per-component explains would union)
            return self._explain(ctx, self.table(ctx.table).query_segments())
        if ctx.options.get("__analyze__"):
            return self._explain_analyze(ctx, device=device)
        resolve_subqueries(ctx, lambda c: self.execute(c, device=device))
        if ctx.set_ops:
            return apply_set_ops(ctx, lambda c: self.execute(c, device=device))
        if ctx.joins:
            raise NotImplementedError(
                "JOIN queries require the distributed engine "
                "(parallel.DistributedEngine routes them to mse.MultiStageEngine); "
                "the single-node QueryEngine serves single-table queries only"
            )
        from pinot_tpu.query.safety import Deadline, estimate_segment_bytes
        from pinot_tpu.utils.metrics import METRICS, Trace

        t0 = time.perf_counter()
        deadline = Deadline.from_ctx(ctx)
        req_id = f"engine_{next(self._qid_seq)}"
        trace = Trace(bool(ctx.options.get("trace", False)), query_id=req_id)
        METRICS.counter("queries").inc()
        state = self.table(ctx.table)
        # schema-aware static validation before any per-segment planning:
        # malformed plans fail here with a structured PlanCheckError
        from pinot_tpu.analysis.plan_check import check_plan

        check_plan(ctx, state.schema)
        segments = state.query_segments()
        self._inject_global_ranges(ctx, state, segments)
        # admission: charge the estimated device bytes up front (safety.py),
        # counting only the columns the query actually ships
        from pinot_tpu.query.planner import _needed_columns

        est = sum(
            estimate_segment_bytes(ctx, seg, _needed_columns(ctx, seg)) for seg in segments
        )
        # workload tier gate first (BinaryWorkloadScheduler): secondary
        # queries wait for a slot before charging memory
        release_slot = self.scheduler.acquire(ctx, deadline)
        try:
            qid = self.accountant.acquire(est)
        except BaseException:
            release_slot()
            raise
        stats = ExecutionStats()
        results = []
        try:
            # pipelined execution: dispatch every segment kernel (async),
            # then drain — device compute for segment k overlaps planning/
            # shipping of k+1 and the collect of earlier segments
            from pinot_tpu.query.planner import _needed_columns

            pending = []
            for seg in segments:
                deadline.check(f"query on {ctx.table}")
                stats.num_segments_queried += 1
                stats.total_docs += seg.num_docs
                # schema evolution: older segments synthesize virtual
                # default columns for schema-added fields; SELECT * covers
                # the FULL table schema (review-caught: per-segment schemas
                # would drop/crash on added columns)
                needed = _needed_columns(ctx, seg)
                if any(isinstance(s, Expr) and s.is_column and s.op == "*" for s in ctx.select_list):
                    needed = list(dict.fromkeys(list(needed) + state.schema.column_names))
                seg.ensure_columns(state.schema, needed)
                if executor.prune_segment(ctx, seg):
                    stats.num_segments_pruned += 1
                    continue
                with trace.span(f"launch:{seg.name}") as lsp:
                    st = executor.launch_segment(ctx, seg, device=device)
                    pending.append(st)
                if lsp is not None and st[0] == "pending":
                    # per-operator cost model on the launch span: EXPLAIN
                    # ANALYZE and the trace view read these attributes
                    lst = st[5]
                    lsp.annotate(
                        kernelBytes=lst.kernel_bytes,
                        kernelFlops=lst.kernel_flops,
                        costSource=lst.kernel_cost_source,
                    )
            if trace.enabled:
                # device/host time split: ONE fence over every pending output
                # (trace-only — the untraced path lets collect's device_get
                # fence so deadline checks stay responsive between collects)
                import jax

                pend_bytes = sum(
                    st[5].kernel_bytes for st in pending if st[0] == "pending"
                )
                tw = time.perf_counter()
                with trace.span("device_wait", launches=len(pending)) as wsp:
                    jax.block_until_ready(executor.pending_outputs(pending))
                wait_s = time.perf_counter() - tw
                stats.device_ms = wait_s * 1000.0
                if wsp is not None:
                    roof = perf.roofline_pct(pend_bytes, wait_s)
                    wsp.annotate(
                        kernelBytes=pend_bytes,
                        **({"rooflinePct": round(roof, 2)} if roof is not None else {}),
                    )
            for st in pending:
                deadline.check(f"query on {ctx.table}")
                with trace.span("collect"):
                    res, seg_stats = executor.collect_segment(st)
                stats.num_segments_processed += 1
                stats.num_docs_scanned += seg_stats.num_docs_scanned
                stats.add_index_uses(seg_stats.filter_index_uses)
                stats.add_kernel_cost(seg_stats)
                results.append(res)
            deadline.check(f"query on {ctx.table}")
            with trace.span("reduce"):
                out = reduce_mod.reduce_results(ctx, results, stats)
        except Exception:
            METRICS.counter("queryExceptions").inc()
            raise
        finally:
            self.accountant.release(qid)
            release_slot()
        out.stats.time_ms = (time.perf_counter() - t0) * 1000
        out.stats.query_id = req_id
        out.stats.trace = trace.finish()
        METRICS.histogram("queryLatency").update(out.stats.time_ms)
        METRICS.counter("docsScanned").inc(stats.num_docs_scanned)
        from pinot_tpu.query.shape import shape_digest

        perf.PERF_LEDGER.record(
            ctx.table,
            shape_digest(ctx.shape_fingerprint()),
            rows=out.stats.num_docs_scanned,
            time_ms=out.stats.time_ms,
            kernel_bytes=out.stats.kernel_bytes,
            compile_ms=out.stats.compile_ms,
            cache_hit=out.stats.compile_ms == 0.0,
            engine="sse",
        )
        return out

    def _explain_analyze(self, ctx: QueryContext, device=None) -> ResultTable:
        """EXPLAIN ANALYZE: run the query with tracing forced, then join the
        static operator tree with the measured span tree (query.analyze)."""
        from pinot_tpu.query.analyze import analyze_result

        ctx.options.pop("__analyze__", None)
        ctx.options["trace"] = True
        for _op, _all, rhs in ctx.set_ops:
            rhs.options.pop("__analyze__", None)
            rhs.options["trace"] = True
        executed = self.execute(ctx, device=device)
        return analyze_result(
            self._explain(ctx, self.table(ctx.table).query_segments()), executed
        )

    def _explain(self, ctx: QueryContext, segments) -> ResultTable:
        """EXPLAIN PLAN FOR: per-shape operator tree rows (Pinot's explain
        table: Operator / Operator_Id / Parent_Id)."""
        from pinot_tpu.query import planner as planner_mod

        rows = [("BROKER_REDUCE(" + ("sort/limit" if ctx.order_by else "limit") + ")", 1, 0)]
        if not segments:
            return ResultTable(columns=["Operator", "Operator_Id", "Parent_Id"], rows=rows, stats=ExecutionStats())
        plan = planner_mod.plan_segment(ctx, segments[0])
        oid = 2
        rows.append((f"COMBINE_{plan.kind.upper()}", oid, 1))
        parent = oid
        oid += 1
        if plan.kind == "aggregation":
            rows.append((f"AGGREGATE({', '.join(str(a) for a in ctx.aggregations)})", oid, parent))
        elif plan.kind.startswith("groupby"):
            rows.append(
                (
                    f"GROUP_BY(keys: {', '.join(str(g) for g in ctx.group_by)}; "
                    f"{'dense' if plan.kind == 'groupby_dense' else 'sparse'} table {plan.num_groups})",
                    oid,
                    parent,
                )
            )
        else:
            rows.append((f"SELECT(columns: {', '.join(plan.select_columns)})", oid, parent))
        parent = oid
        oid += 1
        rows.append((f"PROJECT({', '.join(plan.needed_columns)})", oid, parent))
        parent = oid
        oid += 1
        if plan.index_uses:
            uses = ", ".join(f"{c}:{k}" for c, k in plan.index_uses)
            rows.append((f"FILTER_INDEX({uses})", oid, parent))
        elif ctx.filter is not None:
            rows.append((f"FILTER_SCAN({ctx.filter.fingerprint()[:80]})", oid, parent))
        else:
            rows.append(("FILTER_MATCH_ALL", oid, parent))
        return ResultTable(columns=["Operator", "Operator_Id", "Parent_Id"], rows=rows, stats=ExecutionStats())

    def attach_realtime(self, table: str, manager) -> None:
        """Bind a RealtimeTableDataManager to a registered table."""
        self.table(table).realtime = manager

    @staticmethod
    def _inject_global_ranges(ctx: QueryContext, state: TableState, segments=None) -> None:
        """Table-global facts per sketch-aggregated column, injected as ctx
        options so every segment binds identically:
          __range__<col>  - global [min, max]: histogram bin edges must be
                            the same everywhere for partials to add
          __dictfp__<col> - dictionary-fingerprint consensus; "MIXED" tells
                            column_binding the code space is NOT shared, so
                            code-indexed partials must not merge"""
        from pinot_tpu.query.functions import for_spec

        if segments is None:
            segments = state.query_segments()
        for spec in ctx.aggregations:
            if spec.expr is None or not spec.expr.is_column:
                continue
            if not for_spec(spec).needs_binding:
                continue
            col = spec.expr.op
            rkey, fkey = f"__range__{col}", f"__dictfp__{col}"
            if rkey in ctx.options and fkey in ctx.options:
                continue
            mins, maxs = [], []
            fps = set()
            dict_values = None
            for seg in segments:
                if col not in seg.columns:
                    continue
                c = seg.column(col)
                fps.add(c.dictionary.fingerprint() if c.has_dictionary else None)
                if c.has_dictionary and dict_values is None:
                    dict_values = c.dictionary.values
                if c.stats.min_value is not None and not c.data_type.is_string_like:
                    mins.append(c.stats.min_value)
                    maxs.append(c.stats.max_value)
            if mins:
                ctx.options.setdefault(rkey, (min(mins), max(maxs)))
            if fps:
                only = next(iter(fps)) if len(fps) == 1 else None
                ctx.options.setdefault(fkey, "MIXED" if len(fps) > 1 else (only or ""))
                if len(fps) == 1 and dict_values is not None:
                    # shared key space: reduce-time decode (bind_reduce) may
                    # need the dictionary values themselves
                    ctx.options.setdefault(f"__dictvals__{col}", dict_values)

    def query(self, sql: str, device=None) -> ResultTable:
        """SQL front door (CalciteSqlParser analog lives in sql/); finished
        requests land in the slow-query ring (utils/slowlog.py)."""
        from pinot_tpu.sql.parser import parse_query

        ctx = parse_query(sql)
        if ctx.options.get("__explain__"):
            return self.execute(ctx, device=device)  # plan-only: not served
        fp = ctx.fingerprint()
        try:
            out = self.execute(ctx, device=device)
        except Exception as e:
            self.slow_queries.record(sql, fp, None, error=f"{type(e).__name__}: {e}")
            raise
        self.slow_queries.record(sql, fp, out)
        return out

    def sql(self, statement: str, device=None) -> ResultTable:
        """DDL + DML front door (the pinot-sql-ddl controller resource)."""
        from pinot_tpu.sql.ddl import is_ddl, parse_ddl, show_create_table

        if not is_ddl(statement):
            return self.query(statement, device=device)
        stmt = parse_ddl(statement)
        if stmt.kind == "create_table":
            self.register_table(stmt.schema, stmt.config)
            return ResultTable(columns=["status"], rows=[(f"created {stmt.table}",)], stats=ExecutionStats())
        if stmt.kind == "drop_table":
            if stmt.table not in self.tables:
                raise KeyError(f"table {stmt.table!r} not found")
            del self.tables[stmt.table]
            return ResultTable(columns=["status"], rows=[(f"dropped {stmt.table}",)], stats=ExecutionStats())
        if stmt.kind == "show_tables":
            return ResultTable(
                columns=["tableName"], rows=[(n,) for n in sorted(self.tables)], stats=ExecutionStats()
            )
        state = self.table(stmt.table)
        return ResultTable(
            columns=["createTable"],
            rows=[(show_create_table(state.schema, state.config),)],
            stats=ExecutionStats(),
        )


# ---------------------------------------------------------------------------
# Engine-agnostic rewrites (shared by QueryEngine / Broker / Distributed)
# ---------------------------------------------------------------------------
def resolve_subqueries(ctx: QueryContext, exec_fn) -> None:
    """IN (SELECT ...) semi-joins: run the subquery, substitute its first
    output column as the IN value set (the reference's IdSet/semi-join
    rewrite in the Calcite planner).  An unspecified subquery LIMIT bumps to
    the semi-join valve instead of Pinot's cosmetic default 10."""
    from pinot_tpu.query.ir import FilterNode, FilterOp, Predicate, Subquery

    def rewrite(node):
        if node is None:
            return None
        if node.op is FilterOp.PRED:
            p = node.predicate
            if p is not None and p.values and isinstance(p.values[0], Subquery):
                sub = p.values[0].ctx
                if not sub.options.get("__hasExplicitLimit__", False):
                    sub.limit = int(ctx.options.get("inSubqueryLimit", 1_000_000))
                res = exec_fn(sub)
                vals = tuple(sorted({r[0] for r in res.rows if r[0] is not None}))
                return FilterNode.pred(
                    Predicate(p.ptype, p.lhs, values=vals)
                    if vals
                    else Predicate(p.ptype, p.lhs, values=("\x00__nomatch__",))
                )
            return node
        children = tuple(rewrite(c) for c in node.children)
        return FilterNode(node.op, children=children, predicate=node.predicate)

    ctx.filter = rewrite(ctx.filter)
    if ctx.having is not None:
        ctx.having = rewrite(ctx.having)


def apply_set_ops(ctx: QueryContext, exec_fn) -> ResultTable:
    """UNION [ALL] / INTERSECT / EXCEPT over component results (the MSE
    SetOperator analog, executed at the broker-reduce level)."""
    ops = ctx.set_ops
    ctx.set_ops = []
    try:
        base = exec_fn(ctx)
        rows = list(base.rows)
        for op, all_flag, rhs_ctx in ops:
            rhs = exec_fn(rhs_ctx)
            if rhs.columns and base.columns and len(rhs.columns) != len(base.columns):
                raise ValueError(
                    f"set operation arity mismatch: {len(base.columns)} vs {len(rhs.columns)} columns"
                )
            if op == "union" and all_flag:
                rows = rows + list(rhs.rows)
            elif op == "union":
                seen = set()
                out = []
                for r in rows + list(rhs.rows):
                    if r not in seen:
                        seen.add(r)
                        out.append(r)
                rows = out
            elif op == "intersect":
                rset = set(rhs.rows)
                seen = set()
                rows = [r for r in rows if r in rset and not (r in seen or seen.add(r))]
            else:  # except
                rset = set(rhs.rows)
                seen = set()
                rows = [r for r in rows if r not in rset and not (r in seen or seen.add(r))]
        return ResultTable(columns=base.columns, rows=rows, stats=base.stats)
    finally:
        ctx.set_ops = ops
