"""Aggregation breadth: log-bucket percentile sketch, theta distinct count,
MODE, FIRST/LAST_WITH_TIME.

Reference parity: pinot-core/.../query/aggregation/function/
PercentileKLLAggregationFunction, DistinctCountThetaSketchAggregationFunction,
ModeAggregationFunction, FirstWithTimeAggregationFunction /
LastWithTimeAggregationFunction.

Re-designs (TPU-first):
  * PERCENTILEKLL -> a DDSketch-style LOG-BUCKETED histogram: bucket =
    floor(log_gamma(|v|)) with mirrored negative buckets and a zero bucket.
    Fixed-size additive tensor partial (dense-mergeable, psum-able — which
    the reference's KLL bytes are not), guaranteed RELATIVE value error
    alpha on any skewed/unbounded range — exactly where the equi-width
    histogram of query/sketches.py fails.  (Error contract differs from
    KLL's rank-error; documented.)
  * DISTINCTCOUNTTHETA -> KMV/theta: the K smallest distinct 63-bit row
    hashes, computed on device with the same sort + cumsum-compaction trick
    as the sparse group-by; fixed [K] partial, pairwise host merge.
  * MODE -> value-offset histogram (like exact DISTINCTCOUNT's bounded-range
    form) + argmax at final; additive fields make it fully generic.
  * FIRST/LAST_WITH_TIME -> per-segment argmin/argmax over the time column
    (a second expression argument — AggregationSpec.extra_exprs), scatter
    min/max per group; partials carry (t, v) and merge pairwise by time.
"""
from __future__ import annotations

import math
from typing import Any, Dict, Optional, Tuple

import numpy as np

from pinot_tpu import ops
from pinot_tpu.query.functions import AggFunction, register
from pinot_tpu.query.sketches import ColumnBinding, _check_cell_budget

_I64_MAX = np.int64(np.iinfo(np.int64).max)


# ---------------------------------------------------------------------------
# PERCENTILEKLL: log-bucketed (DDSketch-style) quantile histogram
# ---------------------------------------------------------------------------
class PercentileLogSketchFunction(AggFunction):
    name = "percentilekll"
    vector_fields = True
    fields = ("hist",)

    # magnitude contract: values with |v| in [MIN_MAG, MAX_MAG] keep the
    # relative-error bound; smaller collapse into the zero bucket, larger
    # clamp into the top bucket.
    MIN_MAG = 1e-9
    MAX_MAG = 1e12

    def __init__(self, rank: float = 50.0, alpha: float = 0.01):
        self.rank = float(rank)
        self.alpha = float(alpha)
        self.gamma = (1.0 + alpha) / (1.0 - alpha)
        self.lg = math.log(self.gamma)
        # buckets per sign covering [MIN_MAG, MAX_MAG]
        self.bins = int(math.ceil(math.log(self.MAX_MAG / self.MIN_MAG) / self.lg)) + 1
        self.min_idx = int(math.floor(math.log(self.MIN_MAG) / self.lg))
        self.width = 2 * self.bins + 1  # neg | zero | pos

    def with_args(self, literal_args):
        rank = float(literal_args[0]) if literal_args else 50.0
        # 2nd literal: Pinot's kllSize K; mapped to alpha = 2/K (K=200 -> 1%)
        alpha = 2.0 / float(literal_args[1]) if len(literal_args) > 1 else 0.01
        return PercentileLogSketchFunction(rank, alpha)

    def _bucket(self, values):
        import jax.numpy as jnp

        v = values.astype(jnp.float64)
        av = jnp.abs(v)
        safe = jnp.maximum(av, self.MIN_MAG)
        idx = jnp.clip(
            (jnp.log(safe) / self.lg).astype(jnp.int32) - np.int32(self.min_idx),
            0,
            self.bins - 1,
        )
        center = np.int32(self.bins)
        b = jnp.where(av < self.MIN_MAG, center, jnp.where(v > 0, center + 1 + idx, center - 1 - idx))
        return b

    def partial(self, values, mask):
        b = self._bucket(values)
        return {"hist": ops.group_count(mask, b, self.width)}

    def partial_grouped(self, values, mask, keys, num_groups):
        _check_cell_budget(self.name, num_groups, self.width)
        b = self._bucket(values)
        flat = keys * np.int32(self.width) + b
        return {"hist": ops.group_count(mask, flat, num_groups * self.width).reshape(num_groups, self.width)}

    def merge(self, a, b):
        return {"hist": np.asarray(a["hist"]) + np.asarray(b["hist"])}

    def _bucket_value(self, g: int) -> float:
        """Representative value of global bucket g (midpoint in log space)."""
        center = self.bins
        if g == center:
            return 0.0
        i = abs(g - center) - 1
        mag = math.exp((i + self.min_idx) * self.lg) * (2.0 * self.gamma / (self.gamma + 1.0))
        return mag if g > center else -mag

    def final(self, p):
        hist = np.atleast_2d(np.asarray(p["hist"], dtype=np.float64))
        n_groups = hist.shape[0]
        out = np.full(n_groups, np.nan)
        for g in range(n_groups):
            total = hist[g].sum()
            if total == 0:
                continue
            target = self.rank / 100.0 * total
            cum = np.cumsum(hist[g])
            idx = min(int(np.searchsorted(cum, target, side="left")), self.width - 1)
            out[g] = self._bucket_value(idx)
        return out[0] if np.asarray(p["hist"]).ndim == 1 else out


# ---------------------------------------------------------------------------
# DISTINCTCOUNTTHETA: KMV sketch (K smallest distinct hashes)
# ---------------------------------------------------------------------------
class DistinctCountThetaFunction(AggFunction):
    """KMV theta sketch, optionally with SUB-FILTER set expressions
    (reference: DistinctCountThetaSketchAggregationFunction's
    'filter1', ..., 'SET_INTERSECT($1, $2)' literal arguments): each filter
    string compiles through the ordinary FilterCompiler, the kernel builds
    one KMV row per filter, and the final step evaluates the set expression
    over (hash set, theta) pairs host-side."""

    name = "distinctcounttheta"
    needs_codes = True
    needs_binding = True
    vector_fields = True
    pairwise_merge = True
    input_kind = "values_hash"
    fields = ("kmv",)

    K = 4096

    def __init__(self, filter_exprs: Tuple[str, ...] = (), post_expr: Optional[str] = None):
        self.filter_exprs = tuple(filter_exprs)
        self.post_expr = post_expr
        # parsed once here; planner column-collection and compilation reuse
        # these nodes instead of re-parsing the strings per segment plan
        if filter_exprs:
            from pinot_tpu.sql.parser import parse_filter_expression

            self.filter_nodes = tuple(parse_filter_expression(s) for s in self.filter_exprs)
        else:
            self.filter_nodes = ()

    @property
    def subfilter_args(self) -> bool:
        return bool(self.filter_exprs)

    _SET_EXPR_RX = None  # compiled lazily below

    @classmethod
    def _is_set_expr(cls, s: str) -> bool:
        import re as _re

        if cls._SET_EXPR_RX is None:
            cls._SET_EXPR_RX = _re.compile(
                r"^\s*(?:\$\d+|(?:SET_UNION|SET_INTERSECT|SET_DIFF)\s*\()", _re.IGNORECASE
            )
        return bool(cls._SET_EXPR_RX.match(s))

    def with_args(self, literal_args):
        if not literal_args:
            return self
        lits = [str(a) for a in literal_args]
        # the set expression is recognized by SHAPE ($i / SET_* call), not by
        # containing '$' (review-caught: a filter like dim='a$b' was eaten)
        if self._is_set_expr(lits[-1]):
            filters, post = tuple(lits[:-1]), lits[-1]
            if not filters:
                raise ValueError("theta set expression given without any sub-filters")
        else:
            filters, post = tuple(lits), None
        if filters and post is None:
            if len(filters) > 1:
                raise ValueError(
                    "multiple theta sub-filters need a set expression (e.g. 'SET_INTERSECT($1, $2)')"
                )
            post = "$1"  # single filter: the sketch of the filtered rows
        return DistinctCountThetaFunction(filters, post)

    def bind_column(self, info: ColumnBinding) -> "DistinctCountThetaFunction":
        return self  # hash-based: no per-column constants

    def partial(self, values, mask):
        import jax.numpy as jnp

        if self.filter_exprs:
            # values = (raw values, subfilter_mask_1, ..., subfilter_mask_F)
            v, *fmasks = values
            rows = [self._one_sketch(v, mask & fm) for fm in fmasks]
            return {"kmv": jnp.stack(rows, axis=0)}  # [F, K]
        return {"kmv": self._one_sketch(values, mask)}

    def _one_sketch(self, values, mask):
        import jax.numpy as jnp
        from jax import lax

        from pinot_tpu.query.sketches import _device_hash62

        # clean 62-bit hash in [0, 2^62): two independently seeded streams
        # (positive int64, so int64 sort order == unsigned order)
        h = _device_hash62(values)
        h = jnp.where(mask, h, _I64_MAX)
        s = lax.sort(h)
        prev = jnp.concatenate([jnp.full((1,), -1, s.dtype), s[:-1]])
        is_new = (s != prev) & (s != _I64_MAX)
        idx = jnp.cumsum(is_new.astype(jnp.int32)) - 1
        # ALWAYS full width: a short sketch would cap the whole query's
        # accuracy at merge time (review-caught); segments with fewer rows
        # than K pad with the sentinel and stay exact
        k = self.K
        slot = jnp.where(is_new & (idx < k), idx, k)
        return jnp.full((k + 1,), _I64_MAX, dtype=jnp.int64).at[slot].set(s)[:k]

    GROUPED_K = 256  # per-group sketch width (cell budget bounds it further)

    def partial_grouped(self, values, mask, keys, num_groups):
        """Per-group K smallest DISTINCT hashes via one double-keyed sort:
        rows sort by (group, hash); the distinct-rank within each group
        comes from cumulative counts with per-group resets, and ranks < K
        scatter into the [G, K] table (the same static-shape compaction
        trick as the sparse group-by)."""
        import jax.numpy as jnp
        from jax import lax

        from pinot_tpu.query.sketches import _device_hash62

        if self.filter_exprs:
            raise NotImplementedError("theta sub-filter set expressions do not support GROUP BY")
        kk = max(16, min(self.GROUPED_K, 2_000_000 // max(1, num_groups)))
        _check_cell_budget(self.name, num_groups, kk)
        h = _device_hash62(values)
        gk = jnp.where(mask, keys.astype(jnp.int32), np.int32(num_groups))
        h = jnp.where(mask, h, _I64_MAX)
        s_k, s_h = lax.sort((gk, h), num_keys=2)
        prev_k = jnp.concatenate([jnp.full((1,), -1, s_k.dtype), s_k[:-1]])
        prev_h = jnp.concatenate([jnp.full((1,), -1, s_h.dtype), s_h[:-1]])
        grp_start = s_k != prev_k
        new = (grp_start | (s_h != prev_h)) & (s_k < num_groups) & (s_h != _I64_MAX)
        c = jnp.cumsum(new.astype(jnp.int32))
        base = lax.cummax(jnp.where(grp_start, c - new.astype(jnp.int32), 0))
        rank = c - 1 - base  # 0-indexed distinct rank within the group
        cells = num_groups * kk
        slot = jnp.where(new & (rank < kk), s_k * np.int32(kk) + rank, np.int32(cells))
        kmv = (
            jnp.full((cells + 1,), _I64_MAX, dtype=jnp.int64)
            .at[slot]
            .set(s_h)[:cells]
            .reshape(num_groups, kk)
        )
        return {"kmv": kmv}

    def merge(self, a, b):
        """Merge KMV rows along the last axis: concat, sort, mask duplicate
        neighbors to MAX, re-sort, keep the K smallest (shape-generic:
        scalar [K] and grouped [G, K])."""
        x = np.concatenate([np.asarray(a["kmv"]), np.asarray(b["kmv"])], axis=-1)
        x = np.sort(x, axis=-1)
        dup = np.zeros_like(x, dtype=bool)
        dup[..., 1:] = x[..., 1:] == x[..., :-1]
        x = np.where(dup, _I64_MAX, x)
        x = np.sort(x, axis=-1)
        k = min(np.asarray(a["kmv"]).shape[-1], np.asarray(b["kmv"]).shape[-1])
        return {"kmv": x[..., :k]}

    def final(self, p):
        kmv = np.asarray(p["kmv"])
        if self.post_expr is not None and kmv.ndim == 2:
            # kmv rows are per-subfilter sketches; evaluate the set expression
            sets = [self._as_set(kmv[i]) for i in range(kmv.shape[0])]
            hashes, theta = _eval_theta_set_expr(self.post_expr, sets)
            return len(hashes) / theta if theta > 0 else 0.0
        k = kmv.shape[-1]
        valid = kmv != _I64_MAX
        n_v = valid.sum(axis=-1)
        kth = kmv[..., -1].astype(np.float64)
        with np.errstate(divide="ignore", invalid="ignore"):
            theta = kth / float(1 << 62)
            est = np.where(theta > 0, (n_v - 1) / theta, n_v)
        out = np.where(n_v < k, n_v, est)
        return out if kmv.ndim > 1 else out.item()

    @staticmethod
    def _as_set(row: np.ndarray):
        """KMV row -> (hashes STRICTLY below theta, theta in (0, 1]).
        Saturated sketches drop the theta-defining Kth hash so estimates
        match the plain path's (K-1)/theta (review-caught bias)."""
        valid = row[row != _I64_MAX]
        if len(valid) < len(row):
            return valid, 1.0  # unsaturated: the COMPLETE distinct hash set
        return valid[:-1], float(valid[-1]) / float(1 << 62)

    def final_dtype(self):
        return np.dtype(np.int64)


def _eval_theta_set_expr(expr: str, sets):
    """Evaluate SET_UNION/SET_INTERSECT/SET_DIFF over $i sketch refs.

    Each operand is (sorted distinct hashes, theta).  Standard theta-sketch
    set algebra: results truncate at theta = min of operand thetas; the
    estimate is |hashes below theta| / theta."""
    import re as _re

    s = expr.strip()
    m = _re.fullmatch(r"\$(\d+)", s)
    if m:
        i = int(m.group(1)) - 1
        if not 0 <= i < len(sets):
            raise ValueError(f"theta set expression references ${i + 1}; only {len(sets)} filters")
        return sets[i]
    m = _re.fullmatch(r"(SET_UNION|SET_INTERSECT|SET_DIFF)\s*\((.*)\)", s, _re.IGNORECASE | _re.DOTALL)
    if not m:
        raise ValueError(f"unsupported theta set expression {expr!r}")
    op = m.group(1).upper()
    # split args at top-level commas
    args, depth, start = [], 0, 0
    body = m.group(2)
    for j, ch in enumerate(body):
        if ch == "(":
            depth += 1
        elif ch == ")":
            depth -= 1
        elif ch == "," and depth == 0:
            args.append(body[start:j])
            start = j + 1
    args.append(body[start:])
    operands = [_eval_theta_set_expr(a, sets) for a in args]
    theta = min(t for _, t in operands)
    cut = int(theta * float(1 << 62))
    # hashes STRICTLY below theta participate (theta-sketch convention);
    # theta == 1.0 means every operand is a complete set — keep everything
    trimmed = [h[h < cut] if theta < 1.0 else h for h, _ in operands]
    if op == "SET_UNION":
        out = np.unique(np.concatenate(trimmed))
    elif op == "SET_INTERSECT":
        out = trimmed[0]
        for h in trimmed[1:]:
            out = out[np.isin(out, h)]
    else:  # SET_DIFF(a, b)
        if len(trimmed) != 2:
            raise ValueError("SET_DIFF takes exactly two operands")
        out = trimmed[0][~np.isin(trimmed[0], trimmed[1])]
    return out, theta


# ---------------------------------------------------------------------------
# MODE: value-offset histogram + argmax
# ---------------------------------------------------------------------------
class ModeFunction(AggFunction):
    """Most frequent value over a bounded int range; ties break to the
    SMALLEST value (Pinot's default MIN reducer)."""

    name = "mode"
    needs_codes = True
    needs_binding = True
    vector_fields = True
    input_kind = "values_offset"
    fields = ("hist", "lo")

    def __init__(self, domain: int = 0, base: int = 0):
        self.domain = domain
        self.base = base

    def bind_column(self, info: ColumnBinding) -> "ModeFunction":
        if info.kind == "rawint" or (
            info.min_value is not None
            and isinstance(info.min_value, (int, np.integer))
            and isinstance(info.max_value, (int, np.integer))
        ):
            base = int(info.min_value)
            domain = int(info.max_value) - base + 1
            return ModeFunction(domain=domain, base=base)
        raise NotImplementedError(
            "MODE requires a bounded integer value range (int/long column with stats)"
        )

    def partial(self, codes, mask):
        import jax.numpy as jnp

        _check_cell_budget(self.name, 1, self.domain)
        hist = ops.group_count(mask, codes, self.domain)
        return {"hist": hist, "lo": jnp.asarray(float(self.base))}

    def partial_grouped(self, codes, mask, keys, num_groups):
        import jax.numpy as jnp

        _check_cell_budget(self.name, num_groups, self.domain)
        flat = keys * np.int32(self.domain) + codes
        hist = ops.group_count(mask, flat, num_groups * self.domain).reshape(num_groups, self.domain)
        return {"hist": hist, "lo": jnp.full((num_groups,), float(self.base))}

    def merge(self, a, b):
        return {"hist": np.asarray(a["hist"]) + np.asarray(b["hist"]), "lo": np.minimum(a["lo"], b["lo"])}

    def final(self, p):
        hist = np.atleast_2d(np.asarray(p["hist"]))
        lo = np.atleast_1d(np.asarray(p["lo"], dtype=np.float64))
        # np.argmax takes the FIRST max — the lowest offset = smallest value
        best = np.argmax(hist, axis=1).astype(np.float64)
        out = np.where(hist.sum(axis=1) > 0, lo + best, np.nan)
        return out[0] if np.asarray(p["hist"]).ndim == 1 else out


# ---------------------------------------------------------------------------
# FIRST/LAST_WITH_TIME(value, timeCol, 'dataType')
# ---------------------------------------------------------------------------
class LastWithTimeFunction(AggFunction):
    """Value at the max (LAST) / min (FIRST) time.  values arrives as the
    tuple (v, t) via AggregationSpec.extra_exprs; ties on t take the max v
    (deterministic).  Partials merge pairwise by time comparison."""

    name = "lastwithtime"
    needs_extra_exprs = True
    vector_fields = True  # keep off the generic sparse/psum field paths
    pairwise_merge = True
    fields = ("t", "v")
    pick_last = True

    def _prep(self, values, mask):
        import jax.numpy as jnp

        v, t = values[0], values[1]
        sign = 1.0 if self.pick_last else -1.0
        # maximize sign*t; track v among time-ties via a second scatter
        tt = jnp.where(mask, t.astype(jnp.float64) * sign, -jnp.inf)
        return v.astype(jnp.float64), tt, sign

    def partial(self, values, mask):
        import jax.numpy as jnp

        v, tt, sign = self._prep(values, mask)
        tmax = jnp.max(tt)
        best = mask & (tt == tmax)
        vbest = jnp.max(jnp.where(best, v, -jnp.inf))
        return {"t": tmax * sign, "v": vbest}

    def partial_grouped(self, values, mask, keys, num_groups):
        import jax.numpy as jnp

        v, tt, sign = self._prep(values, mask)
        k = keys.astype(jnp.int32)
        tmax = jnp.full((num_groups,), -jnp.inf).at[k].max(jnp.where(mask, tt, -jnp.inf), mode="drop")
        best = mask & (tt == tmax[k])
        vbest = jnp.full((num_groups,), -jnp.inf).at[k].max(jnp.where(best, v, -jnp.inf), mode="drop")
        return {"t": tmax * sign, "v": vbest}

    def merge(self, a, b):
        sign = 1.0 if self.pick_last else -1.0
        at, bt = np.asarray(a["t"], np.float64) * sign, np.asarray(b["t"], np.float64) * sign
        av, bv = np.asarray(a["v"], np.float64), np.asarray(b["v"], np.float64)
        take_b = (bt > at) | ((bt == at) & (bv > av))
        return {"t": np.where(take_b, b["t"], a["t"]), "v": np.where(take_b, bv, av)}

    def final(self, p):
        v = np.asarray(p["v"], dtype=np.float64)
        t = np.asarray(p["t"], dtype=np.float64)
        return np.where(np.isfinite(t), v, np.nan)


class FirstWithTimeFunction(LastWithTimeFunction):
    name = "firstwithtime"
    pick_last = False


class FrequentLongsFunction(ModeFunction):
    """Top-k most frequent values over a bounded int range (reference:
    FrequentLongsSketchAggregationFunction — theirs is an approximate
    Frequent-Items sketch; ours is exact over the value-offset histogram).
    Returns a list of values, most frequent first (ties: smaller value)."""

    name = "frequentlongs"

    def __init__(self, domain: int = 0, base: int = 0, k: int = 10):
        super().__init__(domain=domain, base=base)
        self.k = k

    def with_args(self, literal_args):
        k = int(literal_args[0]) if literal_args else 10
        return FrequentLongsFunction(k=k)

    def bind_column(self, info: ColumnBinding):
        bound = ModeFunction.bind_column(self, info)
        return FrequentLongsFunction(domain=bound.domain, base=bound.base, k=self.k)

    def final(self, p):
        hist = np.atleast_2d(np.asarray(p["hist"]))
        lo = np.atleast_1d(np.asarray(p["lo"], dtype=np.int64))
        out = np.empty(hist.shape[0], dtype=object)
        for g in range(hist.shape[0]):
            nz = np.nonzero(hist[g])[0]
            # most frequent first; ties break to the smaller value (stable
            # sort over -count keeps ascending offset order within ties)
            top = nz[np.argsort(-hist[g][nz], kind="stable")][: self.k]
            out[g] = [int(lo[g] + o) for o in top]
        return out[0] if np.asarray(p["hist"]).ndim == 1 else out

    def final_dtype(self):
        return np.dtype(object)


# ---------------------------------------------------------------------------
# DISTINCTSUM / DISTINCTAVG: sum/avg over the DISTINCT values
# ---------------------------------------------------------------------------
class DistinctSumFunction(ModeFunction):
    """Sum of distinct values over a bounded int range (reference:
    DistinctSumAggregationFunction).  Rides MODE's value-offset histogram:
    distinct-sum = sum over present offsets of (lo + offset)."""

    name = "distinctsum"

    def bind_column(self, info: ColumnBinding):
        bound = super().bind_column(info)
        return DistinctSumFunction(domain=bound.domain, base=bound.base)

    def final(self, p):
        hist = np.atleast_2d(np.asarray(p["hist"]))
        lo = np.atleast_1d(np.asarray(p["lo"], dtype=np.float64))
        offsets = np.arange(hist.shape[1], dtype=np.float64)
        present = hist > 0
        out = (present * (lo[:, None] + offsets[None, :])).sum(axis=1)
        return out[0] if np.asarray(p["hist"]).ndim == 1 else out


class DistinctAvgFunction(DistinctSumFunction):
    name = "distinctavg"

    def bind_column(self, info: ColumnBinding):
        bound = ModeFunction.bind_column(self, info)
        return DistinctAvgFunction(domain=bound.domain, base=bound.base)

    def final(self, p):
        hist = np.atleast_2d(np.asarray(p["hist"]))
        s = np.atleast_1d(DistinctSumFunction.final(self, p))
        n = (hist > 0).sum(axis=1)
        with np.errstate(divide="ignore", invalid="ignore"):
            out = np.where(n > 0, s / n, np.nan)
        return out[0] if np.asarray(p["hist"]).ndim == 1 else out


# ---------------------------------------------------------------------------
# Multi-value aggregations: COUNTMV/SUMMV/MINMV/MAXMV/AVGMV/DISTINCTCOUNTMV
# ---------------------------------------------------------------------------
class MVAggFunction(AggFunction):
    """Wraps an SV aggregation to run over every ELEMENT of an MV column
    (reference: SumMVAggregationFunction et al).  The planner hands the
    padded [rows, max_len] value/code matrix with a combined row+length
    mask; partials flatten and delegate — grouped keys broadcast across the
    element axis, so one row's elements all land in its group."""

    mv_input = True
    field_kinds = None
    vector_fields = True  # 2D inputs can't ride the sparse sort kernel

    def __init__(self, base: AggFunction):
        self.base = base
        self.name = base.name + "mv"
        self.fields = base.fields
        self.needs_codes = base.needs_codes
        self.needs_binding = base.needs_binding
        self.pairwise_merge = base.pairwise_merge

    def with_args(self, literal_args):
        return MVAggFunction(self.base.with_args(literal_args))

    def bind_column(self, info):
        return MVAggFunction(self.base.bind_column(info))

    def partial(self, values, mask):
        return self.base.partial(values.reshape(-1), mask.reshape(-1))

    def partial_grouped(self, values, mask, keys, num_groups):
        import jax.numpy as jnp

        n, m = mask.shape
        k2 = jnp.broadcast_to(keys[:, None], (n, m)).reshape(-1)
        return self.base.partial_grouped(values.reshape(-1), mask.reshape(-1), k2, num_groups)

    def host_partial(self, p):
        return self.base.host_partial(p)

    def merge(self, a, b):
        return self.base.merge(a, b)

    def final(self, p):
        return self.base.final(p)

    def final_dtype(self):
        return self.base.final_dtype()


_EXTRA = (
    PercentileLogSketchFunction,
    DistinctCountThetaFunction,
    ModeFunction,
    FrequentLongsFunction,
    DistinctSumFunction,
    DistinctAvgFunction,
    LastWithTimeFunction,
    FirstWithTimeFunction,
)
for _cls in _EXTRA:
    register(_cls())

from pinot_tpu.query.functions import get_agg_function as _get  # noqa: E402

for _base_name in ("count", "sum", "min", "max", "avg", "distinctcount"):
    register(MVAggFunction(_get(_base_name)))

# aliases matching the reference's surface
from pinot_tpu.query.functions import _REGISTRY  # noqa: E402

_REGISTRY["distinctcountrawtheta"] = _REGISTRY["distinctcounttheta"]
_REGISTRY["distinctcountbitmap"] = _REGISTRY["distinctcount"]
