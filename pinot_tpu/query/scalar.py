"""Scalar function library: datetime device kernels + dictionary-domain
string functions + interval analysis.

Reference parity: pinot-core's transform function classes
(.../operator/transform/function/ — DateTruncTransformFunction,
DateTimeConversionTransformFunction, scalar string/math functions registered
through FunctionRegistry, pinot-common/.../function/FunctionRegistry.java:73,
and the annotated scalar functions in pinot-common/.../function/scalar/).

Re-design, two executions domains:

* DEVICE_FNS — numeric/datetime functions traced into the segment kernel as
  jnp integer arithmetic.  Calendar math uses Howard Hinnant's civil-date
  algorithms (public domain, branchless integer ops) so YEAR/DATETRUNC/etc.
  compile to a handful of fused integer ops on the MXU-adjacent VPU — no
  per-row host calls, no timezone library (UTC only, documented delta).

* DICT_FNS — string functions evaluated host-side over a DICTIONARY'S
  VALUES (cardinality-sized, not row-sized), producing a derived per-code
  array the kernel gathers: f(values)[codes].  This turns Pinot's per-row
  string transforms into O(cardinality) host work + one device gather —
  the TPU-idiomatic split (strings never materialize on device).
"""
from __future__ import annotations

import functools
import math
import re
from typing import Any, Callable, Dict, Optional, Tuple

import jax.numpy as jnp
import numpy as np

MS_SECOND = 1000
MS_MINUTE = 60 * MS_SECOND
MS_HOUR = 60 * MS_MINUTE
MS_DAY = 24 * MS_HOUR
MS_WEEK = 7 * MS_DAY

TIME_UNIT_MS = {
    "MILLISECONDS": 1,
    "SECONDS": MS_SECOND,
    "MINUTES": MS_MINUTE,
    "HOURS": MS_HOUR,
    "DAYS": MS_DAY,
}


# ---------------------------------------------------------------------------
# Civil-date math (Hinnant algorithms; exact integer ops, vectorized).
# jnp/np integer // is floor division, so no truncation-era fixups needed.
# ---------------------------------------------------------------------------
def civil_from_days(days):
    """Epoch days -> (year, month 1-12, day 1-31)."""
    z = days.astype(jnp.int64) + 719468
    era = z // 146097
    doe = z - era * 146097
    yoe = (doe - doe // 1460 + doe // 36524 - doe // 146096) // 365
    y = yoe + era * 400
    doy = doe - (365 * yoe + yoe // 4 - yoe // 100)
    mp = (5 * doy + 2) // 153
    d = doy - (153 * mp + 2) // 5 + 1
    m = mp + 3 - 12 * (mp // 10)
    return y + (m <= 2), m, d


def days_from_civil(y, m, d):
    """(year, month, day) -> epoch days."""
    y = y - (m <= 2)
    era = y // 400
    yoe = y - era * 400
    doy = (153 * (m + jnp.where(m > 2, -3, 9)) + 2) // 5 + d - 1
    doe = yoe * 365 + yoe // 4 - yoe // 100 + doy
    return era * 146097 + doe - 719468


def _epoch_days(ms):
    return ms.astype(jnp.int64) // MS_DAY


def _day_of_week_iso(days):
    """ISO day-of-week 1=Monday..7=Sunday (epoch day 0 was a Thursday)."""
    return (days + 3) % 7 + 1


def _doy(ms):
    y, m, d = civil_from_days(_epoch_days(ms))
    return _epoch_days(ms) - days_from_civil(y, jnp.ones_like(m), jnp.ones_like(d)) + 1


def _week_of_year(ms):
    """ISO-8601 week number: the week containing this date's Thursday."""
    days = _epoch_days(ms)
    thursday = days - ((days + 3) % 7) + 3
    y, _, _ = civil_from_days(thursday)
    jan1 = days_from_civil(y, jnp.full_like(y, 1), jnp.full_like(y, 1))
    return (thursday - jan1) // 7 + 1


def date_trunc(unit: str, ms):
    """DATETRUNC(unit, epoch_millis) -> epoch millis at bucket start."""
    unit = unit.lower()
    ms = ms.astype(jnp.int64)
    if unit == "millisecond":
        return ms
    if unit == "second":
        return (ms // MS_SECOND) * MS_SECOND
    if unit == "minute":
        return (ms // MS_MINUTE) * MS_MINUTE
    if unit == "hour":
        return (ms // MS_HOUR) * MS_HOUR
    if unit == "day":
        return (ms // MS_DAY) * MS_DAY
    if unit == "week":  # ISO week: truncate to Monday
        days = _epoch_days(ms)
        return (days - (days + 3) % 7) * MS_DAY
    y, m, _ = civil_from_days(_epoch_days(ms))
    one = jnp.ones_like(m)
    if unit == "month":
        return days_from_civil(y, m, one) * MS_DAY
    if unit == "quarter":
        qm = ((m - 1) // 3) * 3 + 1
        return days_from_civil(y, qm, one) * MS_DAY
    if unit == "year":
        return days_from_civil(y, one, one) * MS_DAY
    raise ValueError(f"DATETRUNC: unknown unit {unit!r}")


def _extract(part: str, ms):
    ms = ms.astype(jnp.int64)
    part = part.lower()
    if part == "millisecond":
        return ms % MS_SECOND
    if part == "second":
        return (ms // MS_SECOND) % 60
    if part == "minute":
        return (ms // MS_MINUTE) % 60
    if part == "hour":
        return (ms // MS_HOUR) % 24
    days = _epoch_days(ms)
    if part in ("dayofweek", "dow"):
        return _day_of_week_iso(days) % 7 + 1  # SQL: 1=Sunday..7=Saturday
    if part in ("dayofyear", "doy"):
        return _doy(ms)
    if part == "week":
        return _week_of_year(ms)
    y, m, d = civil_from_days(days)
    if part == "year":
        return y
    if part == "quarter":
        return (m - 1) // 3 + 1
    if part == "month":
        return m
    if part in ("day", "dayofmonth"):
        return d
    raise ValueError(f"unknown datetime part {part!r}")


def time_convert(ms, from_unit: str, to_unit: str):
    """TIMECONVERT(col, fromUnit, toUnit) — epoch unit rescale."""
    f = TIME_UNIT_MS[from_unit.upper()]
    t = TIME_UNIT_MS[to_unit.upper()]
    return (ms.astype(jnp.int64) * f) // t


def _parse_dt_format(fmt: str) -> Tuple[int, str]:
    """Pinot datetime format '1:MILLISECONDS:EPOCH' / 'EPOCH|SECONDS|1'
    -> (unit-size-in-ms, 'EPOCH').  SIMPLE_DATE_FORMAT is host/dictionary
    territory and rejected here."""
    parts = fmt.split("|") if "|" in fmt else fmt.split(":")
    if "|" in fmt:
        kind = parts[0].upper()
        unit = parts[1].upper() if len(parts) > 1 else "MILLISECONDS"
        size = int(parts[2]) if len(parts) > 2 and parts[2] else 1
    else:
        size = int(parts[0])
        unit = parts[1].upper()
        kind = parts[2].upper() if len(parts) > 2 else "EPOCH"
    if kind != "EPOCH":
        raise ValueError(f"SIMPLE_DATE_FORMAT not supported on device: {fmt!r}")
    return size * TIME_UNIT_MS[unit], kind


def datetime_convert(col, in_fmt: str, out_fmt: str, granularity: str):
    """DATETIMECONVERT(col, inFmt, outFmt, granularity) for EPOCH formats:
    rescale + bucket (DateTimeConversionTransformFunction)."""
    in_ms, _ = _parse_dt_format(in_fmt)
    out_ms, _ = _parse_dt_format(out_fmt)
    g = granularity.split(":")
    gran_ms = int(g[0]) * TIME_UNIT_MS[g[1].upper()]
    ms = col.astype(jnp.int64) * in_ms
    bucketed = (ms // gran_ms) * gran_ms
    return bucketed // out_ms


# ---------------------------------------------------------------------------
# DEVICE_FNS registry: name -> fn(traced_value, *literal_args)
# ---------------------------------------------------------------------------
def _rounder(v, *args):
    if not args:
        return jnp.round(v)
    # ROUND(x, d): d decimal places
    scale = 10.0 ** int(args[0])
    return jnp.round(v * scale) / scale


def _truncator(v, *args):
    scale = 10.0 ** (int(args[0]) if args else 0)
    return jnp.trunc(v * scale) / scale


DEVICE_FNS: Dict[str, Callable] = {
    "datetrunc": lambda v, unit, *rest: _date_trunc_args(str(unit), v, rest),
    "year": lambda v, *a: _extract("year", _dt_ms(v, a)),
    "quarter": lambda v, *a: _extract("quarter", _dt_ms(v, a)),
    "month": lambda v, *a: _extract("month", _dt_ms(v, a)),
    "week": lambda v, *a: _extract("week", _dt_ms(v, a)),
    "weekofyear": lambda v, *a: _extract("week", _dt_ms(v, a)),
    "day": lambda v, *a: _extract("day", _dt_ms(v, a)),
    "dayofmonth": lambda v, *a: _extract("day", _dt_ms(v, a)),
    "dayofweek": lambda v, *a: _extract("dayofweek", _dt_ms(v, a)),
    "dayofyear": lambda v, *a: _extract("dayofyear", _dt_ms(v, a)),
    "hour": lambda v, *a: _extract("hour", _dt_ms(v, a)),
    "minute": lambda v, *a: _extract("minute", _dt_ms(v, a)),
    "second": lambda v, *a: _extract("second", _dt_ms(v, a)),
    "millisecond": lambda v, *a: _extract("millisecond", _dt_ms(v, a)),
    "timeconvert": lambda v, fu, tu: time_convert(v, str(fu), str(tu)),
    "datetimeconvert": lambda v, i, o, g: datetime_convert(v, str(i), str(o), str(g)),
    "round": _rounder,
    "truncate": _truncator,
    "sin": jnp.sin,
    "cos": jnp.cos,
    "tan": jnp.tan,
    "asin": jnp.arcsin,
    "acos": jnp.arccos,
    "atan": jnp.arctan,
    "sinh": jnp.sinh,
    "cosh": jnp.cosh,
    "tanh": jnp.tanh,
    "degrees": jnp.degrees,
    "radians": jnp.radians,
}


# ---------------------------------------------------------------------------
# Geo functions (device): haversine distance + quantized grid cells.
# Reference: Pinot's ST_DISTANCE + H3 index (BaseH3IndexCreator, h3 JNI).
# Delta: no H3 library in-image — GEOGRID is a lat/lng quantization with the
# same analytical role (cell bucketing for GROUP BY / coarse containment);
# distances are exact haversine on the VPU, vectorized over all rows.
# ---------------------------------------------------------------------------
_EARTH_RADIUS_M = 6371008.8


def st_distance(lat1, lng1, lat2, lng2):
    """Great-circle distance in meters (haversine), any mix of traced
    arrays and scalars."""
    to_rad = math.pi / 180.0
    p1 = _asf64(lat1) * to_rad
    p2 = _asf64(lat2) * to_rad
    dphi = (_asf64(lat2) - _asf64(lat1)) * to_rad
    dlmb = (_asf64(lng2) - _asf64(lng1)) * to_rad
    a = jnp.sin(dphi / 2) ** 2 + jnp.cos(p1) * jnp.cos(p2) * jnp.sin(dlmb / 2) ** 2
    return 2.0 * _EARTH_RADIUS_M * jnp.arcsin(jnp.sqrt(jnp.clip(a, 0.0, 1.0)))


def geogrid(lat, lng, precision):
    """Quantized geo cell id: a 2^p x 2^p lat/lng grid (H3-cell analog for
    bucketing; cell = row * 2^p + col, groupable via expr_int_range)."""
    n = 1 << int(precision)
    cx = jnp.clip(((_asf64(lng) + 180.0) / 360.0 * n).astype(jnp.int64), 0, n - 1)
    cy = jnp.clip(((_asf64(lat) + 90.0) / 180.0 * n).astype(jnp.int64), 0, n - 1)
    return cy * np.int64(n) + cx


def _asf64(v):
    return v.astype(jnp.float64) if hasattr(v, "astype") else jnp.float64(v)


# multi-argument device functions: fn(*evaluated_args) — args arrive in SQL
# order, literals as python scalars, columns/exprs as traced arrays
DEVICE_MULTI_FNS: Dict[str, Callable] = {
    "st_distance": st_distance,
    "stdistance": st_distance,
    "geogrid": geogrid,
    "atan2": lambda y, x: jnp.arctan2(_asf64(y), _asf64(x)),
    "power": lambda a, b: jnp.power(_asf64(a), _asf64(b)),
}


def _in_ms(v, unit_args) -> jnp.ndarray:
    """Optional trailing inputTimeUnit literal rescales the epoch to millis
    (DATETRUNC('day', ts, 'SECONDS') — Pinot's extended form)."""
    v = v if hasattr(v, "astype") else jnp.asarray(v)
    if unit_args:
        v = v.astype(jnp.int64) * TIME_UNIT_MS[str(unit_args[0]).upper()]
    return v


# ---------------------------------------------------------------------------
# Timezones (DateTimeFunctions.java tz-suffixed variants — VERDICT r4
# missing #7).  No per-row host calls: each zone compiles ONCE into a
# (transition instants, offset) table via stdlib zoneinfo probing, and the
# device resolves per-row offsets with a searchsorted over the baked
# constants (~couple hundred entries for 1970-2080) — DST arithmetic as two
# vector ops instead of a Joda chronology.
# ---------------------------------------------------------------------------
_TZ_YEARS = (1970, 2080)


@functools.lru_cache(maxsize=None)
def _tz_table(tz_name: str):
    """(transition_ms int64[n], offset_ms int64[n]): offset_ms[i] is the
    zone's UTC offset from transition_ms[i] (until the next entry).  Built
    by ~monthly probing with bisection to 1 ms precision (zoneinfo exposes
    no transition list; real transitions are >1 month apart) — the old
    1-minute tolerance misplaced instants within a minute of a DST shift
    (ADVICE r5)."""
    import datetime as _dt

    try:
        from zoneinfo import ZoneInfo

        tz = ZoneInfo(tz_name)
    except Exception as e:  # unknown zone: match Pinot's error surface
        raise ValueError(f"unknown time zone {tz_name!r}") from e

    def off(ms_v: int) -> int:
        # fromtimestamp(tz=tz) localizes the INSTANT; utcoffset() then reads
        # the zone's offset at it (ZoneInfo.utcoffset(naive_utc) would treat
        # the UTC wall reading as local time — hours off near transitions)
        return int(_dt.datetime.fromtimestamp(ms_v / 1000, tz=tz).utcoffset().total_seconds() * 1000)

    y0, y1 = _TZ_YEARS
    start = int(_dt.datetime(y0, 1, 1, tzinfo=_dt.timezone.utc).timestamp() * 1000)
    end = int(_dt.datetime(y1, 1, 1, tzinfo=_dt.timezone.utc).timestamp() * 1000)
    step = 28 * MS_DAY
    trans = [np.iinfo(np.int64).min]
    offs = [off(start)]
    t = start
    while t < end:
        nt = min(t + step, end)
        o = off(nt)
        if o != offs[-1]:
            lo, hi = t, nt
            while hi - lo > 1:
                mid = (lo + hi) // 2
                if off(mid) == offs[-1]:
                    lo = mid
                else:
                    hi = mid
            trans.append(hi)
            offs.append(o)
        t = nt
    return np.asarray(trans, np.int64), np.asarray(offs, np.int64)


def _tz_offset_ms(ms, tz_name: str):
    trans, offs = _tz_table(tz_name)
    idx = jnp.clip(
        jnp.searchsorted(jnp.asarray(trans), ms, side="right") - 1, 0, len(offs) - 1
    )
    return jnp.asarray(offs)[idx]


def _split_dt_args(args):
    """Pinot's (col[, inputTimeUnit][, tzId][, outputTimeUnit]) literal tail
    -> (unit list in order, tz or None).  Literals naming a TimeUnit are
    units (first = input, second = output — the 5-arg dateTrunc form);
    anything else is the zone id."""
    unit_args, tz = [], None
    for a in args:
        s = str(a)
        if s.upper() in TIME_UNIT_MS:
            unit_args.append(s)
        else:
            tz = s
    if tz is not None and tz.upper() in ("UTC", "GMT", "Z"):
        tz = None
    return unit_args, tz


def _dt_ms(v, args):
    """Input millis shifted into the arg-designated zone's local time."""
    unit_args, tz = _split_dt_args(args)
    ms = _in_ms(v, unit_args[:1]).astype(jnp.int64)
    if tz is not None:
        ms = ms + _tz_offset_ms(ms, tz)
    return ms


def _date_trunc_args(unit: str, v, rest):
    """DATETRUNC(unit, col[, inputTimeUnit][, tz][, outputTimeUnit]):
    truncate in local wall time; result in outputTimeUnit (default millis,
    the reference's 5-arg form).  The instant's own offset maps the bucket
    start back — exact except for buckets that straddle a DST shift (the
    reference's chronology handles those; documented delta)."""
    unit_args, tz = _split_dt_args(rest)
    ms = _in_ms(v, unit_args[:1]).astype(jnp.int64)
    if tz is None:
        out = date_trunc(unit, ms)
    else:
        o = _tz_offset_ms(ms, tz)
        out = date_trunc(unit, ms + o) - o
    if len(unit_args) > 1:
        out = out // TIME_UNIT_MS[str(unit_args[1]).upper()]
    return out


# ---------------------------------------------------------------------------
# DICT_FNS: host string functions over dictionary values.
# fn(np object array of values, *literal args) -> derived np array
# (object array for string results, numeric array for numeric results).
# ---------------------------------------------------------------------------
def _sv(fn):
    """Lift a python str->Any function to an object-array map."""

    def apply(values: np.ndarray, *args):
        return np.array([fn(v, *args) for v in values], dtype=object)

    return apply


def _sv_num(fn, dtype=np.int64):
    def apply(values: np.ndarray, *args):
        return np.array([fn(v, *args) for v in values], dtype=dtype)

    return apply


def _substr(v: str, start, length=None):
    # Pinot SUBSTR is 0-based; length -1 / omitted = to end
    s = int(start)
    if length is None or int(length) < 0:
        return v[s:]
    return v[s : s + int(length)]


DICT_FNS: Dict[str, Callable] = {
    "upper": _sv(lambda v: v.upper()),
    "lower": _sv(lambda v: v.lower()),
    "trim": _sv(lambda v: v.strip()),
    "ltrim": _sv(lambda v: v.lstrip()),
    "rtrim": _sv(lambda v: v.rstrip()),
    "reverse": _sv(lambda v: v[::-1]),
    "substr": _sv(_substr),
    "substring": _sv(_substr),
    "concat": _sv(lambda v, *args: v + "".join(str(a) for a in args)),
    "replace": _sv(lambda v, find, repl: v.replace(str(find), str(repl))),
    "lpad": _sv(lambda v, size, pad: v.rjust(int(size), str(pad))),
    "rpad": _sv(lambda v, size, pad: v.ljust(int(size), str(pad))),
    # numeric results: gathered on device as derived[codes]
    "length": _sv_num(len),
    "strpos": _sv_num(lambda v, find, *inst: v.find(str(find))),
    "startswith": _sv_num(lambda v, p: int(v.startswith(str(p))), np.uint8),
    "endswith": _sv_num(lambda v, p: int(v.endswith(str(p))), np.uint8),
    "containsstr": _sv_num(lambda v, p: int(str(p) in v), np.uint8),
}


# -- string/url/hash breadth (StringFunctions.java, UrlFunctions.java,
# HashFunctions.java; regexpExtract/regexpReplace from RegexpFunctions) ----
def _split_part(v: str, delim, a, *b):
    """splitPart(input, delim, index) or the reference's 4-arg
    (input, delim, limit, index) form — limit bounds the SPLIT COUNT
    (StringFunctions.splitPart), not a default value."""
    if b:
        limit, i = int(a), int(b[0])
        parts = str(v).split(str(delim), max(0, limit - 1))
    else:
        i = int(a)
        parts = str(v).split(str(delim))
    if 0 <= i < len(parts):
        return parts[i]
    return "null"  # Pinot's miss marker


def _regexp_extract(v: str, pattern, *args):
    group = int(args[0]) if args else 0
    default = str(args[1]) if len(args) > 1 else ""
    m = re.search(str(pattern), str(v))
    if m is None:
        return default
    try:
        return m.group(group) or default
    except IndexError:
        return default


def _regexp_replace(v: str, pattern, repl, *args):
    """regexpReplace(value, regex, replace[, matchStartPos[, occurrence
    [, flags]]]) — occurrence k >= 0 replaces only the k-th match (0-based),
    -1 (default) replaces all; flags: 'i' case-insensitive
    (RegexpReplaceTransformFunction signature)."""
    s = str(v)
    start = int(args[0]) if args else 0
    occurrence = int(args[1]) if len(args) > 1 else -1
    fl = re.IGNORECASE if len(args) > 2 and "i" in str(args[2]).lower() else 0
    head, tail = s[:start], s[start:]
    if occurrence < 0:
        return head + re.sub(str(pattern), str(repl), tail, flags=fl)
    rx = re.compile(str(pattern), fl)
    k = -1
    out = []
    pos = 0
    for m in rx.finditer(tail):
        k += 1
        if k == occurrence:
            out.append(tail[pos : m.start()])
            out.append(m.expand(str(repl)))
            pos = m.end()
            break
    out.append(tail[pos:])
    return head + "".join(out)


def _hash_fn(algo):
    import hashlib

    def apply(v):
        h = hashlib.new(algo)
        h.update(v.encode() if isinstance(v, str) else bytes(v))
        return h.hexdigest()

    return apply


def _url_encode(v: str) -> str:
    from urllib.parse import quote_plus

    return quote_plus(str(v))


def _url_decode(v: str) -> str:
    from urllib.parse import unquote_plus

    return unquote_plus(str(v))


def _b64(v: str) -> str:
    import base64

    return base64.b64encode(v.encode() if isinstance(v, str) else bytes(v)).decode()


def _b64d(v: str) -> str:
    import base64

    return base64.b64decode(str(v)).decode()


DICT_FNS.update(
    {
        "splitpart": _sv(_split_part),
        "split_part": _sv(_split_part),
        "repeat": _sv(lambda v, n, *sep: (str(sep[0]) if sep else "").join([v] * int(n))),
        "regexpextract": _sv(_regexp_extract),
        "regexp_extract": _sv(_regexp_extract),
        "regexpreplace": _sv(_regexp_replace),
        "regexp_replace": _sv(_regexp_replace),
        "urlencode": _sv(_url_encode),
        "urldecode": _sv(_url_decode),
        "encodeurl": _sv(_url_encode),
        "decodeurl": _sv(_url_decode),
        "md5": _sv(_hash_fn("md5")),
        "sha": _sv(_hash_fn("sha1")),
        "sha256": _sv(_hash_fn("sha256")),
        "sha512": _sv(_hash_fn("sha512")),
        "tobase64": _sv(_b64),
        "frombase64": _sv(_b64d),
        "codepoint": _sv_num(lambda v: ord(str(v)[0]) if str(v) else 0),
        "chr": _sv(lambda v: chr(int(v))),
    }
)

def _json_extract(values: np.ndarray, path, rtype, default=None) -> np.ndarray:
    """JSON_EXTRACT_SCALAR(col, '$.path', 'type'[, default]) over dictionary
    values (JsonExtractScalarTransformFunction analog, evaluated per
    dictionary entry).  Path: $.a.b.c and [i] array access."""
    import json as _json

    rtype = str(rtype).upper()
    steps = []
    for part in str(path).lstrip("$").strip(".").split("."):
        if not part:
            continue
        base, _, rest = part.partition("[")
        if base:
            steps.append(("key", base))
        while rest:
            idx, _, rest = rest.partition("]")
            steps.append(("idx", int(idx)))
            rest = rest.lstrip("[")
    nulls = {"INT": -(2**31), "LONG": -(2**63), "FLOAT": float("-inf"), "DOUBLE": float("-inf"), "STRING": "null"}
    missing = default if default is not None else nulls.get(rtype, "null")

    def one(v):
        try:
            node = _json.loads(v)
        except (TypeError, ValueError):
            return missing
        for kind, s in steps:
            try:
                node = node[s]
            except (KeyError, IndexError, TypeError):
                return missing
        if isinstance(node, (dict, list)):
            return _json.dumps(node) if rtype == "STRING" else missing
        return node

    out = [one(v) for v in values]
    if rtype in ("INT", "LONG"):
        return np.array([int(x) if not isinstance(x, str) else int(float(x)) for x in out], dtype=np.int64)
    if rtype in ("FLOAT", "DOUBLE"):
        return np.array([float(x) for x in out], dtype=np.float64)
    return np.array([str(x) for x in out], dtype=object)


DICT_FNS["json_extract_scalar"] = _json_extract


def _java_fmt_to_strptime(fmt: str) -> str:
    """Joda/SimpleDateFormat pattern -> strptime (the subset Pinot docs use:
    yyyy MM dd HH mm ss SSS, plus 'quoted' literal sections like 'T')."""
    import re as _re

    out = fmt
    # SSS first: translating ss earlier would leave %S adjacent to SSS and
    # corrupt the pattern (ssSSS -> %SSSS mis-splits)
    for a, b in (
        ("SSS", "%f"),  # strptime %f = microseconds; see callers
        ("yyyy", "%Y"),
        ("MM", "%m"),
        ("dd", "%d"),
        ("HH", "%H"),
        ("mm", "%M"),
        ("ss", "%S"),
    ):
        out = out.replace(a, b)
    # SimpleDateFormat quotes literal text: yyyy-MM-dd'T'HH:mm:ss
    return _re.sub(r"'([^']*)'", r"\1", out)


def _from_datetime(values: np.ndarray, fmt: str, tz_name: Optional[str] = None) -> np.ndarray:
    """FROMDATETIME(strCol, 'yyyy-MM-dd ...'[, tzId]) -> epoch millis; the
    string is interpreted as wall time in tzId (default UTC).  Runs over the
    DICTIONARY (cardinality work) like all string functions."""
    import datetime as _dt

    tzinfo = _dt.timezone.utc
    if tz_name is not None and str(tz_name).upper() not in ("UTC", "GMT", "Z"):
        from zoneinfo import ZoneInfo

        tzinfo = ZoneInfo(str(tz_name))
    py_fmt = _java_fmt_to_strptime(str(fmt))
    has_millis = "%f" in py_fmt
    out = np.empty(len(values), dtype=np.int64)
    for i, v in enumerate(values):
        s = str(v)
        if has_millis:
            # SSS is milliseconds; pad to microseconds for %f
            base, _, frac = s.rpartition(".")
            if base and len(frac) == 3:
                s = f"{base}.{frac}000"
        try:
            d = _dt.datetime.strptime(s, py_fmt).replace(tzinfo=tzinfo)
            out[i] = int(d.timestamp() * 1000)
        except ValueError:
            out[i] = np.iinfo(np.int64).min  # unparseable -> placeholder
    return out


DICT_FNS["fromdatetime"] = _from_datetime


def to_datetime(ms, fmt: str, tz_name: Optional[str] = None):
    """TODATETIME(epochMillis, fmt[, tzId]) -> formatted string
    (host/selection path; strings never materialize on device)."""
    import datetime as _dt

    tzinfo = _dt.timezone.utc
    if tz_name is not None and str(tz_name).upper() not in ("UTC", "GMT", "Z"):
        from zoneinfo import ZoneInfo

        tzinfo = ZoneInfo(str(tz_name))
    py_fmt = _java_fmt_to_strptime(str(fmt))
    out = np.empty(len(ms), dtype=object)
    for i, v in enumerate(np.asarray(ms)):
        d = _dt.datetime.fromtimestamp(int(v) / 1000, tz=tzinfo)
        # SSS = milliseconds: substitute into the FORMAT (a post-hoc string
        # replace corrupted outputs whose digits matched — review-caught)
        fmt_i = py_fmt.replace("%f", f"{d.microsecond // 1000:03d}")
        out[i] = d.strftime(fmt_i)
    return out

STRING_RESULT_DICT_FNS = frozenset(
    {
        "upper", "lower", "trim", "ltrim", "rtrim", "reverse", "substr", "substring",
        "concat", "replace", "lpad", "rpad",
        "splitpart", "split_part", "repeat", "regexpextract", "regexp_extract",
        "regexpreplace", "regexp_replace", "urlencode", "urldecode", "encodeurl",
        "decodeurl", "md5", "sha", "sha256", "sha512", "tobase64", "frombase64", "chr",
    }
)


# user-registered string-result dict functions (register_dict_function)
_EXTRA_STRING_RESULT: set = set()


def string_result(expr) -> bool:
    """Does this dictionary-function expression produce STRING values?
    (Routes between the derived-string host paths and numeric device
    gathers; JSON_EXTRACT_SCALAR's result type is its literal argument.)"""
    if expr.op == "json_extract_scalar":
        lits = [a.value for a in expr.args if a.is_literal]
        return len(lits) >= 2 and str(lits[1]).upper() == "STRING"
    return expr.op in STRING_RESULT_DICT_FNS or expr.op in _EXTRA_STRING_RESULT


# ---------------------------------------------------------------------------
# Registration surface (FunctionRegistry analog,
# pinot-common/.../function/FunctionRegistry.java:73 — user scalar UDFs)
# ---------------------------------------------------------------------------
def register_device_function(name: str, fn) -> None:
    """Register a traced numeric function: fn(jnp_values, *literal_args) ->
    jnp array.  Usable anywhere expressions evaluate (filters, aggregation
    inputs, selection, GROUP BY via interval analysis if bounded)."""
    DEVICE_FNS[name.lower()] = fn


def register_dict_function(name: str, fn, string_result_fn: bool = False) -> None:
    """Register a dictionary-domain function: fn(np values array,
    *literal_args) -> derived np array (object for strings, typed for
    numerics); the engine gathers derived[codes] on device."""
    DICT_FNS[name.lower()] = fn
    if string_result_fn:
        _EXTRA_STRING_RESULT.add(name.lower())


def list_functions() -> dict:
    """Registered function names by execution domain (plus aggregations)."""
    from pinot_tpu.query.functions import _REGISTRY

    return {
        "device": sorted(DEVICE_FNS),
        "dictionary": sorted(DICT_FNS),
        "aggregation": sorted(_REGISTRY),
    }


def is_dict_fn_expr(expr) -> bool:
    """CALL of a dictionary-domain function over exactly one column (plus
    literals) — the shape rewritable as derived[codes]."""
    from pinot_tpu.query.ir import ExprKind

    if expr.kind is not ExprKind.CALL or expr.op not in DICT_FNS:
        return False
    col_args = [a for a in expr.args if not a.is_literal]
    return len(col_args) == 1 and col_args[0].is_column


def eval_dict_fn(expr, values: np.ndarray) -> np.ndarray:
    """Apply a dict-domain function to a dictionary's values array."""
    lits = [a.value for a in expr.args if a.is_literal]
    return DICT_FNS[expr.op](values, *lits)


# derived arrays keyed by (expr fingerprint, dictionary fingerprint) — the
# planner's interval bound and the execution gathers would otherwise run
# the same O(cardinality) pass (per-entry strptime for FROMDATETIME) two or
# three times per plan (review-caught)
_DERIVED_CACHE: Dict[Any, np.ndarray] = {}
_DERIVED_CACHE_MAX = 256


def derived_for(expr, dictionary) -> np.ndarray:
    key = (expr.fingerprint(), dictionary.fingerprint())
    hit = _DERIVED_CACHE.get(key)
    if hit is not None:
        return hit
    out = eval_dict_fn(expr, dictionary.values)
    if len(_DERIVED_CACHE) >= _DERIVED_CACHE_MAX:
        _DERIVED_CACHE.pop(next(iter(_DERIVED_CACHE)))
    _DERIVED_CACHE[key] = out
    return out


# ---------------------------------------------------------------------------
# Interval analysis: bound an integer expression's value range from column
# stats, to size expression group-by dimensions statically.
# ---------------------------------------------------------------------------
def expr_int_range(expr, segment) -> Optional[Tuple[int, int]]:
    """(lo, hi) bound of an integer-valued expression, or None if unbounded /
    non-integer.  Conservative: propagates column min/max through monotone
    integer ops; anything else returns None."""
    from pinot_tpu.query.ir import ExprKind

    if expr.kind is ExprKind.LITERAL:
        if isinstance(expr.value, (int, np.integer)) and not isinstance(expr.value, bool):
            v = int(expr.value)
            return (v, v)
        return None
    if expr.kind is ExprKind.COLUMN:
        c = segment.column(expr.op)
        if c.data_type.is_string_like or c.stats.min_value is None:
            return None
        mn, mx = c.stats.min_value, c.stats.max_value
        if isinstance(mn, (int, np.integer)) and isinstance(mx, (int, np.integer)):
            return (int(mn), int(mx))
        return None
    op = expr.op
    args = [expr_int_range(a, segment) for a in expr.args if not a.is_literal]
    lits = [a.value for a in expr.args if a.is_literal]
    if op == "datetrunc" and len(args) == 1 and args[0] is not None and lits:
        lo, hi = args[0]
        unit = str(lits[0])
        unit_args, tz = _split_dt_args(lits[1:])
        in_ms = TIME_UNIT_MS[str(unit_args[0]).upper()] if unit_args else 1
        # the 5-arg outputTimeUnit division MUST mirror _date_trunc_args —
        # a millis-ranged GroupDim against seconds-valued rows decodes
        # garbage group keys (review-caught)
        out_div = TIME_UNIT_MS[str(unit_args[1]).upper()] if len(unit_args) > 1 else 1
        f = lambda x: int(date_trunc(unit, jnp.asarray([x * in_ms], dtype=jnp.int64))[0])
        if tz is not None:
            # local truncation near a bucket boundary can land one WHOLE
            # bucket below the UTC truncation (an instant just past the UTC
            # year start is still in the previous local year) — widen the
            # lower bound by the unit's span, the upper by the max zone
            # shift (over-approximation is safe for range sizing;
            # review-caught: ±1 day only covers sub-day units)
            span = {
                "year": 366 * MS_DAY,
                "quarter": 92 * MS_DAY,
                "month": 31 * MS_DAY,
                "week": 7 * MS_DAY,
            }.get(unit.lower(), MS_DAY)
            # symmetric: zones AHEAD of UTC can truncate one whole bucket
            # ABOVE the UTC truncation too (review-caught: Pacific/Auckland
            # year boundary)
            return ((f(lo) - span) // out_div, (f(hi) + span) // out_div)
        return (f(lo) // out_div, f(hi) // out_div)
    if op in ("year", "quarter", "month", "week", "weekofyear", "day", "dayofmonth", "hour", "minute", "second") and len(args) == 1 and args[0] is not None:
        lo, hi = args[0]
        unit_args, tz = _split_dt_args(lits)
        in_ms = TIME_UNIT_MS[str(unit_args[0]).upper()] if unit_args else 1
        # YEAR is monotone in the epoch; cyclic parts use the full part range
        if op == "year":
            pad = MS_DAY if tz is not None else 0  # zone shift < a day
            glo = int(_extract("year", jnp.asarray([lo * in_ms - pad], dtype=jnp.int64))[0])
            ghi = int(_extract("year", jnp.asarray([hi * in_ms + pad], dtype=jnp.int64))[0])
            return (glo, ghi)
        return {
            "quarter": (1, 4),
            "month": (1, 12),
            "week": (1, 53),
            "weekofyear": (1, 53),
            "day": (1, 31),
            "dayofmonth": (1, 31),
            "hour": (0, 23),
            "minute": (0, 59),
            "second": (0, 59),
        }[op]
    if op in ("dayofweek",):
        return (1, 7)
    if op in ("dayofyear",):
        return (1, 366)
    if op in ("timeconvert", "datetimeconvert") and len(args) == 1 and args[0] is not None:
        lo, hi = args[0]
        f = DEVICE_FNS[op]
        glo = int(f(jnp.asarray([lo], dtype=jnp.int64), *lits)[0])
        ghi = int(f(jnp.asarray([hi], dtype=jnp.int64), *lits)[0])
        return (min(glo, ghi), max(glo, ghi))
    if op in ("arraylength", "cardinality") and len(expr.args) == 1 and expr.args[0].is_column:
        c = segment.column(expr.args[0].op)
        ml = getattr(c, "mv_lengths", None)
        if ml is not None and len(ml):
            return (0, int(ml.max()))
        return None
    if op == "geogrid":
        lits2 = [a.value for a in expr.args if a.is_literal]
        if lits2:
            n = 1 << int(lits2[-1])
            return (0, n * n - 1)
        return None
    # numeric dictionary-domain functions (LENGTH, STRPOS, FROMDATETIME...):
    # bound by evaluating the derived array over the dictionary itself
    if is_dict_fn_expr(expr) and not string_result(expr):
        col = next(a for a in expr.args if not a.is_literal).op
        c = segment.column(col)
        if c.has_dictionary and c.dictionary.cardinality:
            derived = derived_for(expr, c.dictionary)
            a = np.asarray(derived)
            if np.issubdtype(a.dtype, np.integer):
                # FROMDATETIME marks unparseable values with int64-min —
                # keeping it in the bound explodes the key space to 2^63
                # (review-caught); such rows fall outside the dense table
                # and silently drop from expression group-bys (documented)
                ok = a != np.iinfo(np.int64).min
                if not ok.any():
                    return None
                return (int(a[ok].min()), int(a[ok].max()))
        return None
    if op in ("plus", "add", "minus", "sub", "times", "mult") and len(expr.args) == 2:
        ra = expr_int_range(expr.args[0], segment)
        rb = expr_int_range(expr.args[1], segment)
        if ra is None or rb is None:
            return None
        combos = [
            a_ * b_ if op in ("times", "mult") else (a_ + b_ if op in ("plus", "add") else a_ - b_)
            for a_ in ra
            for b_ in rb
        ]
        return (min(combos), max(combos))
    if op == "abs" and len(expr.args) == 1:
        r = expr_int_range(expr.args[0], segment)
        if r is None:
            return None
        lo, hi = r
        return (0 if lo <= 0 <= hi else min(abs(lo), abs(hi)), max(abs(lo), abs(hi)))
    if op == "mod" and len(expr.args) == 2 and expr.args[1].is_literal:
        m = expr.args[1].value
        if isinstance(m, (int, np.integer)) and m > 0:
            return (0, int(m) - 1)
        return None
    if op == "length" or (op in DICT_FNS and op not in STRING_RESULT_DICT_FNS):
        # numeric dict functions: bound by evaluating over the dictionary
        return None  # planner handles via derived arrays instead
    return None
