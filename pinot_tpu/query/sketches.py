"""Sketch & distinct aggregations: DISTINCTCOUNT, DISTINCTCOUNTHLL, PERCENTILE.

Reference parity: pinot-core's sketch family —
DistinctCountAggregationFunction (exact, value sets),
DistinctCountHLLAggregationFunction (HyperLogLog registers),
PercentileEst/TDigest/KLL (quantile sketches)
(pinot-core/.../query/aggregation/function/, SURVEY.md 2.2 "Aggregation
functions": 106 classes, DISTINCTCOUNT(HLL/...)/PERCENTILE(Est/TDigest/KLL)).

TPU re-design — all three become FIXED-SIZE TENSOR partials whose combine is
elementwise, so they ride the same dense-group-table + psum machinery as SUM:

  * DISTINCTCOUNT (exact): a presence table over the column's code domain
    (dictionary ids, or range-offset raw ints).  partial field "present"
    [.., domain] int32 0/1, combine = max (set union).  final = row-sum.
    Pinot keeps hash sets per group; a bounded-domain bitmap is the exact
    tensor equivalent (same idea as its RoaringBitmap-based
    DistinctCountBitmapAggregationFunction).
  * DISTINCTCOUNTHLL: classic HLL registers [.., m] uint8? kept int32 for
    psum/pmax friendliness; combine = max (HLL union is register-wise max —
    exactly FIELD_COMBINE's "max").  Hashes are precomputed host-side over
    the DICTIONARY (card hashes total, not n) — the same dictionary trick the
    filter layer uses — or computed on device with a murmur-style finalizer
    for raw int columns.
  * PERCENTILE (and the Est/TDigest/KLL names): an equi-width histogram
    sketch over [lo, hi] taken from column stats; partial "hist" [.., B]
    additive + "lo"/"hi" scalar fields (min/max combine) to keep merges
    self-describing.  final interpolates within the hit bin.  Accuracy is
    (hi-lo)/B — with B=2048 that is tighter than Pinot's default TDigest
    compression for most distributions, and the partial is mergeable across
    segments by plain addition (a psum over ICI).

Binding: these functions need per-column constants (domain width, hash
tables, bin ranges).  `get_agg_function` returns unbound singletons whose
merge/final are shape-agnostic (reduce side); the planner calls
`with_args(literal_args)` then `bind_column(info)` to get the kernel-side
instance (see planner._bind_aggs).
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Optional, Tuple

import numpy as np

from pinot_tpu import ops
from pinot_tpu.query.functions import AggFunction, register

# Grouped sketch tables (presence bitmaps, HLL registers, histograms) are
# capped at this many cells (groups x per-group width) — the
# numGroupsLimit-style memory valve.  Also guarantees the flattened
# keys*width+offset index stays far below int32 overflow (silent
# FILL_OR_DROP row loss otherwise).
MAX_PRESENCE_CELLS = 1 << 26

# Pinot's DistinctCountHLL default is log2m=8 for the plain HLL type
# (CommonConstants.Helix.DEFAULT_HYPERLOGLOG_LOG2M); we default to 12 —
# ~0.8% standard error vs ~6.5% — because the register table is a cheap
# device tensor here.  Documented accuracy delta; pass an explicit log2m
# literal arg for parity.
_DEFAULT_LOG2M = 12
_DEFAULT_PERCENTILE_BINS = 2048


def _check_cell_budget(fn_name: str, num_groups: int, width: int) -> None:
    cells = num_groups * width
    if cells > MAX_PRESENCE_CELLS:
        raise NotImplementedError(
            f"{fn_name} grouped table {num_groups}x{width} = {cells} cells exceeds "
            f"{MAX_PRESENCE_CELLS}; lower group-key cardinality, numGroupsLimit, "
            "or the sketch width (log2m / bins)"
        )


@dataclass(frozen=True)
class ColumnBinding:
    """What the planner knows about the aggregated column at plan time.

    kind is already alignment-resolved by planner.column_binding:
      "dict"   - dictionary codes are a SHARED key space across all segments
                 of the query (single segment, stacked table, or verified
                 equal fingerprints) — code-indexed partials merge directly.
      "rawint" - bounded int value range (table-global); partials index by
                 (value - base), aligned by construction.
      "raw"    - unbounded/float values; only hash-based sketches apply.
    """

    kind: str  # "dict" | "rawint" | "raw"
    domain: int = 0  # dictionary cardinality / int range width
    base: int = 0  # min value for rawint code normalization
    # host-side dictionary values (numeric np array or object array) for
    # hash precomputation; None for raw columns
    dict_values: Optional[np.ndarray] = None
    # column stats for histogram ranges
    min_value: Any = None
    max_value: Any = None


# ---------------------------------------------------------------------------
# Exact DISTINCTCOUNT
# ---------------------------------------------------------------------------
class DistinctCountFunction(AggFunction):
    """Exact distinct count over a bounded code domain.

    needs_codes: the planner feeds dictionary codes (or range-offset ints)
    instead of values — the domain is what presence is tracked over."""

    name = "distinctcount"
    needs_codes = True
    needs_binding = True
    vector_fields = True
    fields = ("present",)

    # how the planner feeds rows: "codes" (shared-key-space dictionary) or
    # "values_offset" (decoded value - base over a table-global int range)
    input_kind = "codes"

    def __init__(self, domain: int = 0, base: int = 0, input_kind: str = "codes"):
        self.domain = domain
        self.base = base
        self.input_kind = input_kind

    def bind_column(self, info: ColumnBinding) -> "AggFunction":
        if info.kind == "dict":
            # codes only merge across segments when the key space is shared —
            # planner.column_binding already downgraded kind otherwise
            return DistinctCountFunction(domain=info.domain, input_kind="codes")
        if info.kind == "rawint":
            return DistinctCountFunction(domain=info.domain, base=info.base, input_kind="values_offset")
        if info.dict_values is not None:
            # misaligned per-segment dictionaries: exact count still works by
            # unioning DECODED value sets at reduce (the reference's
            # DistinctCountAggregationFunction value-set semantics); device
            # work stays a local presence bitmap, host decodes present codes
            return DistinctCountValueSetFunction(info.dict_values)
        raise NotImplementedError(
            "exact DISTINCTCOUNT needs a dictionary or a bounded int range; "
            "this column has neither (unbounded/float raw values) — use "
            "DISTINCTCOUNTHLL"
        )

    # codes arrive as the "values" argument
    def partial(self, codes, mask):
        import jax.numpy as jnp

        present = ops.group_count(mask, codes, self.domain) > 0
        return {"present": present.astype(jnp.int32)}

    def partial_grouped(self, codes, mask, keys, num_groups):
        import jax.numpy as jnp

        _check_cell_budget(self.name, num_groups, self.domain)
        cells = num_groups * self.domain
        flat = keys * np.int32(self.domain) + codes
        present = ops.group_count(mask, flat, cells) > 0
        return {"present": present.astype(jnp.int32).reshape(num_groups, self.domain)}

    def merge(self, a, b):
        # the unbound registry singleton merges BOTH partial forms: presence
        # bitmaps (aligned code spaces) and host value sets (fallback below)
        if "valueset" in a:
            return {"valueset": a["valueset"] | b["valueset"]}
        return {"present": np.maximum(a["present"], b["present"])}

    def final(self, p):
        if "valueset" in p:
            return len(p["valueset"])
        return np.asarray(p["present"]).sum(axis=-1)

    def final_dtype(self):
        return np.dtype(np.int64)


class DistinctCountValueSetFunction(AggFunction):
    """Exact distinct count across segments with DIFFERENT dictionaries.

    Device partial: presence bitmap over the segment's LOCAL dictionary.
    host_partial decodes present codes into a frozenset; reduce unions sets
    (reference DistinctCountAggregationFunction's value-set merge).  Grouped
    form is unsupported (per-group sets defeat the tensor contract) — use
    DISTINCTCOUNTHLL for grouped heterogeneous-dictionary counts."""

    name = "distinctcount"
    needs_codes = True
    needs_binding = True
    vector_fields = True
    fields = ("present",)
    input_kind = "codes"

    def __init__(self, dict_values):
        self._values = np.asarray(dict_values, dtype=object)
        self.domain = len(self._values)

    def partial(self, codes, mask):
        import jax.numpy as jnp

        present = ops.group_count(mask, codes, self.domain) > 0
        return {"present": present.astype(jnp.int32)}

    def partial_grouped(self, codes, mask, keys, num_groups):
        raise NotImplementedError(
            "exact grouped DISTINCTCOUNT requires a shared dictionary across "
            "segments; these segments' dictionaries differ — use DISTINCTCOUNTHLL"
        )

    def host_partial(self, p):
        present = np.asarray(p["present"]) > 0
        return {"valueset": frozenset(self._values[present].tolist())}

    def merge(self, a, b):
        return {"valueset": a["valueset"] | b["valueset"]}

    def final(self, p):
        return len(p["valueset"])

    def final_dtype(self):
        return np.dtype(np.int64)


# ---------------------------------------------------------------------------
# DISTINCTCOUNTHLL
# ---------------------------------------------------------------------------
def _splitmix64_np(x: np.ndarray) -> np.ndarray:
    """Vectorized splitmix64 over uint64 (host numpy — no per-value Python)."""
    with np.errstate(over="ignore"):
        z = (x + np.uint64(0x9E3779B97F4A7C15)).astype(np.uint64)
        z = (z ^ (z >> np.uint64(30))) * np.uint64(0xBF58476D1CE4E5B9)
        z = (z ^ (z >> np.uint64(27))) * np.uint64(0x94D049BB133111EB)
        return z ^ (z >> np.uint64(31))


def _hll_host_tables(values: np.ndarray, log2m: int) -> Tuple[np.ndarray, np.ndarray]:
    """Per-dictionary-id (bucket, rho) from a 64-bit host hash.

    card hashes total — the dictionary trick: device rows only gather.
    Numeric dictionaries hash fully vectorized; strings/bytes loop (their
    bytes must be digested individually)."""
    m = 1 << log2m
    nbits = 64 - log2m
    if values.dtype != object:
        # bitcast numerics to uint64 (pad narrower types) + splitmix64
        arr = np.asarray(values)
        if arr.dtype.itemsize == 8:
            u = arr.view(np.uint64)
        else:
            u = arr.astype(np.int64).view(np.uint64) if np.issubdtype(arr.dtype, np.integer) else arr.astype(np.float64).view(np.uint64)
        h = _splitmix64_np(u.astype(np.uint64))
        buckets = (h & np.uint64(m - 1)).astype(np.int32)
        w = (h >> np.uint64(log2m)).astype(np.uint64)
        # rho = nbits - floor(log2(w)) for w>0 else nbits+1, vectorized via
        # float64 exponent (w < 2^52 after the shift, exact)
        lg = np.zeros(len(w), dtype=np.int32)
        nz = w > 0
        lg[nz] = np.floor(np.log2(w[nz].astype(np.float64))).astype(np.int32)
        rhos = np.where(nz, nbits - lg, nbits + 1).astype(np.int32)
        return buckets, rhos
    import hashlib

    buckets = np.empty(len(values), dtype=np.int32)
    rhos = np.empty(len(values), dtype=np.int32)
    for i, v in enumerate(values):
        b = v if isinstance(v, bytes) else str(v).encode("utf-8")
        h = int.from_bytes(hashlib.blake2b(b, digest_size=8).digest(), "little")
        buckets[i] = h & (m - 1)
        w = h >> log2m
        rhos[i] = (nbits - w.bit_length()) + 1 if w else nbits + 1
    return buckets, rhos


def _device_hash32(x):
    """murmur3 finalizer on uint32 lanes (device-side, 32-bit ops only)."""
    import jax.numpy as jnp

    h = x.astype(jnp.uint32)
    h = h ^ (h >> np.uint32(16))
    h = h * np.uint32(0x85EBCA6B)
    h = h ^ (h >> np.uint32(13))
    h = h * np.uint32(0xC2B2AE35)
    h = h ^ (h >> np.uint32(16))
    return h


def _device_hash_values(v, seed=np.uint32(0)):
    """Hash arbitrary-width numeric values with 32-bit ops only.

    8-byte types split into two 32-bit words so (nearly) the full bit
    pattern participates — a plain int32 cast truncates and collides values
    2^32 apart (review-caught).  TPU's X64 rewriter cannot lower 64-bit
    bitcast-convert, so the split is arithmetic: LONGs shift/mask; DOUBLEs
    take the float32 head + float32 residual (~48 mantissa bits; doubles
    closer than that collide, which is within HLL's approximation budget).

    `seed` XORs into the input lanes before finalizing, yielding an
    INDEPENDENT hash stream per seed — the 62-bit sketch hashes combine two
    differently-seeded streams of the full value instead of deriving the low
    word from the high one (ADVICE r5: hash32(h1^c) carries only h1's 32
    bits of entropy)."""
    import jax.numpy as jnp
    from jax import lax

    seed = np.uint32(seed)
    if v.dtype.itemsize == 8:
        if jnp.issubdtype(v.dtype, jnp.floating):
            head = v.astype(jnp.float32)
            resid = (v - head.astype(jnp.float64)).astype(jnp.float32)
            w0 = lax.bitcast_convert_type(head, jnp.uint32)
            w1 = lax.bitcast_convert_type(resid, jnp.uint32)
        else:
            w0 = (v & np.int64(0xFFFFFFFF)).astype(jnp.uint32)
            w1 = ((v >> np.int64(32)) & np.int64(0xFFFFFFFF)).astype(jnp.uint32)
        return _device_hash32((w0 ^ seed) ^ _device_hash32(w1 ^ seed))
    if jnp.issubdtype(v.dtype, jnp.floating):
        return _device_hash32(lax.bitcast_convert_type(v.astype(jnp.float32), jnp.uint32) ^ seed)
    return _device_hash32(v.astype(jnp.int32).astype(jnp.uint32) ^ seed)


# second-stream seed for the 62-bit KMV hashes (any odd constant works; this
# is the golden-ratio word the old derived construction reused as an XOR)
_H2_SEED = np.uint32(0x9E3779B9)


def _device_hash62(values):
    """Positive-int64 62-bit hash: two independently seeded 32-bit streams,
    h1 -> bits 31..61, h2 -> bits 0..30 (int64 sort order == unsigned order).
    Shared by the theta/tuple KMV sketches."""
    import jax.numpy as jnp

    h1 = _device_hash_values(values)
    h2 = _device_hash_values(values, seed=_H2_SEED)
    return ((h1 & np.uint32(0x7FFFFFFF)).astype(jnp.int64) << np.int64(31)) | (
        h2 >> np.uint32(1)
    ).astype(jnp.int64)


class DistinctCountHLLFunction(AggFunction):
    """HyperLogLog distinct count: registers [.., m], combine = max."""

    name = "distinctcounthll"
    needs_codes = True
    needs_binding = True
    vector_fields = True
    fields = ("hll",)

    input_kind = "codes"

    def __init__(self, log2m: int = _DEFAULT_LOG2M, bucket_table=None, rho_table=None, device_hash=False):
        self.log2m = int(log2m)
        self.m = 1 << self.log2m
        self.bucket_table = bucket_table  # np.int32[card] for dict columns
        self.rho_table = rho_table
        self.device_hash = device_hash  # raw path: hash values on device
        self.input_kind = "values_hash" if device_hash else "codes"

    def with_args(self, literal_args):
        if literal_args:
            return DistinctCountHLLFunction(log2m=int(literal_args[0]))
        return self

    def bind_column(self, info: ColumnBinding) -> "DistinctCountHLLFunction":
        if info.dict_values is not None:
            # value-based host hash: registers align across segments even
            # when dictionaries differ (HLL union is value-level), so this
            # applies to "raw"-kind bindings of misaligned dict columns too
            b, r = _hll_host_tables(info.dict_values, self.log2m)
            return DistinctCountHLLFunction(self.log2m, bucket_table=b, rho_table=r)
        return DistinctCountHLLFunction(self.log2m, device_hash=True)

    def _bucket_rho(self, values_or_codes):
        import jax.numpy as jnp

        if self.device_hash:
            h = _device_hash_values(values_or_codes)
            bucket = (h & np.uint32(self.m - 1)).astype(jnp.int32)
            w = (h >> np.uint32(self.log2m)).astype(jnp.int32)
            nbits = 32 - self.log2m
            # floor(log2(w)) via f32 exponent — w < 2^21 is exact in f32
            lg = jnp.floor(jnp.log2(jnp.maximum(w, 1).astype(jnp.float32))).astype(jnp.int32)
            rho = jnp.where(w > 0, nbits - lg, nbits + 1)
            return bucket, rho
        bucket = jnp.asarray(self.bucket_table)[values_or_codes]
        rho = jnp.asarray(self.rho_table)[values_or_codes]
        return bucket, rho

    def partial(self, codes, mask):
        import jax.numpy as jnp

        bucket, rho = self._bucket_rho(codes)
        regs = ops.group_max(rho, mask, bucket, self.m)
        # group_max yields -inf for empty buckets; registers are >= 0
        return {"hll": jnp.maximum(regs, 0.0).astype(jnp.int32)}

    def partial_grouped(self, codes, mask, keys, num_groups):
        import jax.numpy as jnp

        _check_cell_budget(self.name, num_groups, self.m)
        bucket, rho = self._bucket_rho(codes)
        flat = keys * np.int32(self.m) + bucket
        regs = ops.group_max(rho, mask, flat, num_groups * self.m)
        return {"hll": jnp.maximum(regs, 0.0).astype(jnp.int32).reshape(num_groups, self.m)}

    def merge(self, a, b):
        return {"hll": np.maximum(a["hll"], b["hll"])}

    def final(self, p):
        regs = np.asarray(p["hll"], dtype=np.float64)
        m = regs.shape[-1]
        alpha = 0.7213 / (1 + 1.079 / m)
        est = alpha * m * m / np.sum(np.exp2(-regs), axis=-1)
        zeros = np.sum(regs == 0, axis=-1)
        # small-range correction (linear counting)
        with np.errstate(divide="ignore"):
            lc = m * np.log(np.where(zeros > 0, m / np.maximum(zeros, 1), 1.0))
        est = np.where((est <= 2.5 * m) & (zeros > 0), lc, est)
        return np.rint(est).astype(np.int64)

    def final_dtype(self):
        return np.dtype(np.int64)


# ---------------------------------------------------------------------------
# PERCENTILE (histogram sketch)
# ---------------------------------------------------------------------------
class PercentileFunction(AggFunction):
    """Equi-width histogram percentile: partial = ("hist" add, "lo" min,
    "hi" max).  The engine injects a table-global [lo, hi] via bind_column so
    all segments share bin edges (mergeable by addition)."""

    name = "percentile"
    needs_binding = True
    vector_fields = True
    fields = ("hist", "lo", "hi")

    def __init__(self, rank: float = 50.0, lo: float = 0.0, hi: float = 1.0, bins: int = _DEFAULT_PERCENTILE_BINS):
        self.rank = float(rank)
        self.lo = float(lo)
        self.hi = float(hi)
        self.bins = int(bins)

    def with_args(self, literal_args):
        if literal_args:
            return PercentileFunction(rank=float(literal_args[0]), lo=self.lo, hi=self.hi, bins=self.bins)
        return self

    def bind_column(self, info: ColumnBinding) -> "PercentileFunction":
        lo = float(info.min_value) if info.min_value is not None else 0.0
        hi = float(info.max_value) if info.max_value is not None else 1.0
        if hi <= lo:
            hi = lo + 1.0
        return PercentileFunction(self.rank, lo, hi, self.bins)

    def _bin(self, values):
        import jax.numpy as jnp

        v = values.astype(jnp.float32)
        scale = np.float32(self.bins / (self.hi - self.lo))
        b = jnp.floor((v - np.float32(self.lo)) * scale).astype(jnp.int32)
        return jnp.clip(b, 0, self.bins - 1)

    def _range_fields(self, template):
        import jax.numpy as jnp

        lo = jnp.full(template, self.lo, dtype=jnp.float32)
        hi = jnp.full(template, self.hi, dtype=jnp.float32)
        return lo, hi

    def partial(self, values, mask):
        b = self._bin(values)
        hist = ops.group_count(mask, b, self.bins)
        lo, hi = self._range_fields(())
        return {"hist": hist, "lo": lo, "hi": hi}

    def partial_grouped(self, values, mask, keys, num_groups):
        _check_cell_budget(self.name, num_groups, self.bins)
        b = self._bin(values)
        flat = keys * np.int32(self.bins) + b
        hist = ops.group_count(mask, flat, num_groups * self.bins).reshape(num_groups, self.bins)
        lo, hi = self._range_fields((num_groups,))
        return {"hist": hist, "lo": lo, "hi": hi}

    def merge(self, a, b):
        # bin edges are injected table-globally (engine _inject_sketch_info);
        # summing histograms with mismatched edges would silently skew the
        # percentile, so mismatch is an error, not a merge
        if not (np.allclose(a["lo"], b["lo"]) and np.allclose(a["hi"], b["hi"])):
            raise ValueError(
                "percentile histograms have mismatched bin edges "
                f"([{a['lo']}, {a['hi']}] vs [{b['lo']}, {b['hi']}]) — partials "
                "were built without a shared table-global range"
            )
        return {
            "hist": a["hist"] + b["hist"],
            "lo": np.minimum(a["lo"], b["lo"]),
            "hi": np.maximum(a["hi"], b["hi"]),
        }

    def final(self, p):
        hist = np.atleast_2d(np.asarray(p["hist"], dtype=np.float64))
        lo = np.atleast_1d(np.asarray(p["lo"], dtype=np.float64))
        hi = np.atleast_1d(np.asarray(p["hi"], dtype=np.float64))
        n_groups, bins = hist.shape
        out = np.full(n_groups, np.nan)
        width = (hi - lo) / bins
        for g in range(n_groups):
            total = hist[g].sum()
            if total == 0:
                continue
            target = self.rank / 100.0 * total
            cum = np.cumsum(hist[g])
            idx = int(np.searchsorted(cum, target, side="left"))
            idx = min(idx, bins - 1)
            prev = cum[idx - 1] if idx > 0 else 0.0
            in_bin = hist[g][idx]
            frac = (target - prev) / in_bin if in_bin > 0 else 0.0
            out[g] = lo[g] + width[g] * (idx + frac)
        scalar = np.asarray(p["hist"]).ndim == 1
        return out[0] if scalar else out


# The Est/TDigest names resolve to the same mergeable histogram sketch;
# accuracy contract is (hi-lo)/bins instead of the reference's per-sketch
# bounds (documented delta — the partials remain mergeable across segments
# and psum-combinable across chips, which the reference's sketches are not).
# PERCENTILEKLL lives in aggs_extra.py as a log-bucketed sketch with a
# relative-error bound on unbounded/skewed ranges.
class PercentileEstFunction(PercentileFunction):
    name = "percentileest"


class PercentileTDigestFunction(PercentileFunction):
    name = "percentiletdigest"


for _cls in (
    DistinctCountFunction,
    DistinctCountHLLFunction,
    PercentileFunction,
    PercentileEstFunction,
    PercentileTDigestFunction,
):
    register(_cls())

# Pinot alias: exact distinct count over partitioned segments
from pinot_tpu.query.functions import _REGISTRY  # noqa: E402

_REGISTRY["segmentpartitioneddistinctcount"] = _REGISTRY["distinctcount"]
_REGISTRY["distinctcountbitmap"] = _REGISTRY["distinctcount"]
