"""Aggregation long tail: HISTOGRAM, covariance family, EXPR_MIN/EXPR_MAX
(argmin/argmax), FREQUENTSTRINGS, and the integer tuple sketch family.

Reference parity (VERDICT r4 #8 / missing #3):
  * HISTOGRAM -> pinot-core/.../function/HistogramAggregationFunction.java:
    `HISTOGRAM(col, lower, upper, numBins)` equal-width bins, or
    `HISTOGRAM(col, '0,1,10,100')` explicit edges (the reference's
    ARRAY[0,1,10,100] spelled as a literal string — this parser has no
    ARRAY constructor).  Bins are [e_i, e_{i+1}) with the last bin closed;
    out-of-range values are dropped.  Device form: bucket ids via a
    broadcast edge compare, then the shared group_count scatter — one
    additive [bins] tensor partial, psum-able.
  * COVAR_POP/COVAR_SAMP/CORR -> CovarianceAggregationFunction.java's
    CovarianceTuple (sumX, sumY, sumXY, count) re-designed as additive
    field dicts so the in-graph psum combine and the sparse slot kernel
    both apply.  CORR adds sumsqx/sumsqy (PearsonCorrelation tuple).
  * EXPR_MIN/EXPR_MAX -> ParentExprMinMaxAggregationFunction.java:
    `EXPR_MIN(projection, measure)` returns the projection value at the
    extremal measure.  One measuring + one numeric projection column here
    (the reference supports lists); ties on the measure break to the
    LARGEST projection value (deterministic; the reference returns an
    arbitrary tied row).  Partials carry the coupled (m, v) pair and merge
    pairwise, like FIRST/LAST_WITH_TIME.
  * FREQUENTSTRINGS -> FrequentStringsSketchAggregationFunction.java:
    exact top-k over dictionary codes (FREQUENTLONGS' histogram on the
    shared code space) decoded through the dictionary at final — exact
    where the reference's ItemsSketch is approximate.
  * DISTINCTCOUNTTUPLESKETCH / SUMVALUESINTEGERSUMTUPLESKETCH /
    AVGVALUEINTEGERSUMTUPLESKETCH -> IntegerTupleSketchAggregationFunction
    .java + SumValues/AvgValue siblings: a KMV sketch that carries an
    int64 summary per retained hash, summing summaries of duplicate keys
    (the datasketches Tuple "integer sum" mode).  Device form: one sort by
    (group, hash) + run-boundary flags gives distinct ranks AND per-key
    payload segment sums; the K smallest distinct hashes and their sums
    scatter into fixed [K] tables.  Merge is pairwise (hash-aligned
    payload add), estimates scale by 1/theta exactly like the reference.
"""
from __future__ import annotations

import math
from typing import Optional, Tuple

import numpy as np

from pinot_tpu import ops
from pinot_tpu.query.functions import AggFunction, register
from pinot_tpu.query.sketches import ColumnBinding, _check_cell_budget
from pinot_tpu.query.aggs_extra import FrequentLongsFunction

_I64_MAX = np.int64(np.iinfo(np.int64).max)


# ---------------------------------------------------------------------------
# HISTOGRAM
# ---------------------------------------------------------------------------
class HistogramFunction(AggFunction):
    name = "histogram"
    vector_fields = True
    fields = ("hist",)

    def __init__(self, edges: Optional[np.ndarray] = None, equal_width: bool = False):
        self.edges = None if edges is None else np.asarray(edges, dtype=np.float64)
        self.equal_width = equal_width

    def with_args(self, literal_args):
        if len(literal_args) == 1:
            s = str(literal_args[0]).strip()
            if s.upper().startswith("ARRAY"):
                s = s[5:].strip()
            edges = np.asarray([float(x) for x in s.strip("[]() ").split(",")], np.float64)
            eq = False
        elif len(literal_args) == 3:
            lo, hi, bins = (float(literal_args[0]), float(literal_args[1]), int(literal_args[2]))
            if bins <= 0 or hi <= lo:
                raise ValueError(f"HISTOGRAM needs upper > lower and numBins > 0, got {literal_args}")
            edges = np.linspace(lo, hi, bins + 1)
            eq = True
        else:
            raise ValueError(
                "HISTOGRAM takes (col, lower, upper, numBins) or (col, '<edge,edge,...>'), "
                f"got {len(literal_args) + 1} arguments"
            )
        if len(edges) < 2 or np.any(np.diff(edges) <= 0):
            raise ValueError(f"HISTOGRAM bin edges must be strictly increasing, got {edges}")
        return HistogramFunction(edges, eq)

    @property
    def width(self) -> int:
        return len(self.edges) - 1

    def _bucket(self, values):
        """(bucket ids, in-range mask).  Bins are [e_i, e_{i+1}), last bin
        closed at the top (HistogramAggregationFunction semantics).  The
        compare runs f64 on CPU ('wide' policy) and f32 on TPU — edge-exact
        for int edges below 2^24 there; beyond that edge placement has f32
        precision (documented TPU trade)."""
        import jax.numpy as jnp

        dt = jnp.float64 if ops.accum_policy() == "wide" else jnp.float32
        v = values.astype(dt)
        e = jnp.asarray(self.edges, dt)
        inb = (v >= e[0]) & (v <= e[-1])
        # searchsorted over interior edges: O(n log bins), no [n, bins]
        # broadcast intermediate; top edge folds into the last bin
        b = jnp.searchsorted(e[1:-1], v, side="right").astype(jnp.int32)
        return b, inb

    def partial(self, values, mask):
        b, inb = self._bucket(values)
        return {"hist": ops.group_count(mask & inb, b, self.width)}

    def partial_grouped(self, values, mask, keys, num_groups):
        _check_cell_budget(self.name, num_groups, self.width)
        b, inb = self._bucket(values)
        flat = keys.astype(np.int32) * np.int32(self.width) + b
        return {
            "hist": ops.group_count(mask & inb, flat, num_groups * self.width).reshape(
                num_groups, self.width
            )
        }

    def merge(self, a, b):
        return {"hist": np.asarray(a["hist"]) + np.asarray(b["hist"])}

    def final(self, p):
        hist = np.asarray(p["hist"], dtype=np.float64)
        one = hist.ndim == 1
        hist = np.atleast_2d(hist)
        out = np.empty(hist.shape[0], dtype=object)
        for g in range(hist.shape[0]):
            out[g] = [float(c) for c in hist[g]]
        return out[0] if one else out

    def final_dtype(self):
        return np.dtype(object)


# ---------------------------------------------------------------------------
# COVAR_POP / COVAR_SAMP / CORR
# ---------------------------------------------------------------------------
class CovarianceFunction(AggFunction):
    """COVAR_POP(x, y): E[XY] - E[X]E[Y] over matching rows.  The partial is
    the CovarianceTuple as an additive field dict; products accumulate f64
    on CPU and f32 on TPU (documented float contract, like f32_sum)."""

    name = "covar_pop"
    needs_extra_exprs = True
    fields = ("count", "sumx", "sumy", "sumxy")
    sample = False

    def _floats(self, values):
        import jax.numpy as jnp

        dt = jnp.float64 if ops.accum_policy() == "wide" else jnp.float32
        x, y = values[0], values[1]
        return x.astype(dt), y.astype(dt)

    def partial(self, values, mask):
        x, y = self._floats(values)
        return {
            "count": ops.masked_count(mask),
            "sumx": ops.masked_sum(x, mask),
            "sumy": ops.masked_sum(y, mask),
            "sumxy": ops.masked_sum(x * y, mask),
        }

    def partial_grouped(self, values, mask, keys, num_groups):
        x, y = self._floats(values)
        return {
            "count": ops.group_count(mask, keys, num_groups),
            "sumx": ops.group_sum(x, mask, keys, num_groups),
            "sumy": ops.group_sum(y, mask, keys, num_groups),
            "sumxy": ops.group_sum(x * y, mask, keys, num_groups),
        }

    def merge(self, a, b):
        return {k: np.asarray(a[k]) + np.asarray(b[k]) for k in self.fields}

    def final(self, p):
        n = np.asarray(p["count"], dtype=np.float64)
        sx = np.asarray(p["sumx"], dtype=np.float64)
        sy = np.asarray(p["sumy"], dtype=np.float64)
        sxy = np.asarray(p["sumxy"], dtype=np.float64)
        with np.errstate(divide="ignore", invalid="ignore"):
            cov = sxy / n - (sx / n) * (sy / n)
            if self.sample:
                return np.where(n > 1, cov * n / (n - 1), np.nan)
            return np.where(n > 0, cov, np.nan)


class CovarianceSampFunction(CovarianceFunction):
    name = "covar_samp"
    sample = True


class CorrelationFunction(CovarianceFunction):
    """CORR(x, y): Pearson correlation (reference CovarianceAggregationFunction
    sibling tuple with sum-of-squares fields)."""

    name = "corr"
    fields = ("count", "sumx", "sumy", "sumxy", "sumsqx", "sumsqy")
    sample = False

    def partial(self, values, mask):
        x, y = self._floats(values)
        p = CovarianceFunction.partial(self, values, mask)
        p["sumsqx"] = ops.masked_sum(x * x, mask)
        p["sumsqy"] = ops.masked_sum(y * y, mask)
        return p

    def partial_grouped(self, values, mask, keys, num_groups):
        x, y = self._floats(values)
        p = CovarianceFunction.partial_grouped(self, values, mask, keys, num_groups)
        p["sumsqx"] = ops.group_sum(x * x, mask, keys, num_groups)
        p["sumsqy"] = ops.group_sum(y * y, mask, keys, num_groups)
        return p

    def final(self, p):
        n = np.asarray(p["count"], dtype=np.float64)
        sx = np.asarray(p["sumx"], dtype=np.float64)
        sy = np.asarray(p["sumy"], dtype=np.float64)
        sxy = np.asarray(p["sumxy"], dtype=np.float64)
        ssx = np.asarray(p["sumsqx"], dtype=np.float64)
        ssy = np.asarray(p["sumsqy"], dtype=np.float64)
        with np.errstate(divide="ignore", invalid="ignore"):
            covn = sxy - sx * sy / n
            varxn = ssx - sx * sx / n
            varyn = ssy - sy * sy / n
            return np.where(
                (n > 0) & (varxn > 0) & (varyn > 0), covn / np.sqrt(varxn * varyn), np.nan
            )


# ---------------------------------------------------------------------------
# EXPR_MIN / EXPR_MAX (argmin / argmax)
# ---------------------------------------------------------------------------
class ExprMaxFunction(AggFunction):
    """EXPR_MAX(projection, measure): projection value at the max measure.
    values arrives as (projection, measure) via extra_exprs.  Numeric
    projections only (the reference also projects strings); measure ties
    take the max projection value."""

    name = "exprmax"
    needs_extra_exprs = True
    vector_fields = True  # coupled fields: keep off generic field combines
    pairwise_merge = True
    fields = ("m", "v")
    pick_max = True

    def _prep(self, values, mask):
        import jax.numpy as jnp

        v, m = values[0], values[1]
        sign = 1.0 if self.pick_max else -1.0
        mm = jnp.where(mask, m.astype(jnp.float64) * sign, -jnp.inf)
        return v.astype(jnp.float64), mm, sign

    def partial(self, values, mask):
        import jax.numpy as jnp

        v, mm, sign = self._prep(values, mask)
        mbest = jnp.max(mm)
        best = mask & (mm == mbest)
        return {"m": mbest * sign, "v": jnp.max(jnp.where(best, v, -jnp.inf))}

    def partial_grouped(self, values, mask, keys, num_groups):
        import jax.numpy as jnp

        v, mm, sign = self._prep(values, mask)
        k = keys.astype(jnp.int32)
        mbest = jnp.full((num_groups,), -jnp.inf).at[k].max(jnp.where(mask, mm, -jnp.inf), mode="drop")
        best = mask & (mm == mbest[k])
        vbest = jnp.full((num_groups,), -jnp.inf).at[k].max(jnp.where(best, v, -jnp.inf), mode="drop")
        return {"m": mbest * sign, "v": vbest}

    def merge(self, a, b):
        sign = 1.0 if self.pick_max else -1.0
        am, bm = np.asarray(a["m"], np.float64) * sign, np.asarray(b["m"], np.float64) * sign
        av, bv = np.asarray(a["v"], np.float64), np.asarray(b["v"], np.float64)
        take_b = (bm > am) | ((bm == am) & (bv > av))
        return {"m": np.where(take_b, b["m"], a["m"]), "v": np.where(take_b, bv, av)}

    def final(self, p):
        m = np.asarray(p["m"], dtype=np.float64)
        return np.where(np.isfinite(m), np.asarray(p["v"], np.float64), np.nan)


class ExprMinFunction(ExprMaxFunction):
    name = "exprmin"
    pick_max = False


# ---------------------------------------------------------------------------
# FREQUENTSTRINGS: exact top-k over dictionary codes
# ---------------------------------------------------------------------------
class FrequentStringsFunction(FrequentLongsFunction):
    name = "frequentstrings"
    input_kind = "codes"

    def __init__(self, domain: int = 0, k: int = 10, dict_values: Optional[np.ndarray] = None):
        # base 0: codes ARE the offsets on the shared dictionary key space
        FrequentLongsFunction.__init__(self, domain=domain, base=0, k=k)
        self.dict_values = dict_values

    def with_args(self, literal_args):
        k = int(literal_args[0]) if literal_args else 10
        return FrequentStringsFunction(k=k)

    def bind_column(self, info: ColumnBinding):
        if info.kind != "dict" or info.dict_values is None:
            raise NotImplementedError(
                "FREQUENTSTRINGS requires a dictionary-encoded column with a "
                "shared key space across segments"
            )
        return FrequentStringsFunction(domain=info.domain, k=self.k, dict_values=info.dict_values)

    def bind_reduce(self, ctx, spec):
        """final() decodes codes through the dictionary, which the reduce-side
        registry singleton lacks — the engines inject it as a ctx option
        (__dictvals__<col>, set alongside __dictfp__)."""
        dv = ctx.options.get(f"__dictvals__{spec.expr.op}") if spec.expr is not None else None
        if dv is None:
            raise NotImplementedError(
                "FREQUENTSTRINGS reduce needs engine-injected dictionary values "
                "(__dictvals__ option missing)"
            )
        return FrequentStringsFunction(k=self.k, dict_values=dv)

    def final(self, p):
        hist = np.atleast_2d(np.asarray(p["hist"]))
        out = np.empty(hist.shape[0], dtype=object)
        for g in range(hist.shape[0]):
            nz = np.nonzero(hist[g])[0]
            top = nz[np.argsort(-hist[g][nz], kind="stable")][: self.k]
            out[g] = [str(self.dict_values[c]) for c in top]
        return out[0] if np.asarray(p["hist"]).ndim == 1 else out


# ---------------------------------------------------------------------------
# Integer tuple sketch: KMV + int64 summary per retained hash
# ---------------------------------------------------------------------------
class IntegerTupleSketchFunction(AggFunction):
    """DISTINCTCOUNTTUPLESKETCH(key, value): KMV over key hashes where each
    retained hash carries the SUM of its rows' int values (datasketches
    integer-sum Tuple mode).  final() dispatches on `estimate`:
      distinct -> (K-1)/theta distinct-key estimate
      sum      -> sum(retained summaries)/theta (SumValuesIntegerSumTuple)
      avg      -> mean retained summary (AvgValueIntegerSumTuple)."""

    name = "distinctcounttuplesketch"
    needs_codes = True
    needs_binding = True
    needs_extra_exprs = True
    vector_fields = True
    pairwise_merge = True
    input_kind = "values_hash"
    fields = ("kmv", "pay")
    estimate = "distinct"

    K = 4096
    GROUPED_K = 256

    def bind_column(self, info: ColumnBinding):
        return self  # hash-based

    def _hash(self, values):
        from pinot_tpu.query.sketches import _device_hash62

        return _device_hash62(values)

    def partial(self, values, mask):
        return {k: t[0] for k, t in self.partial_grouped(values, mask, None, 1).items()}

    def partial_grouped(self, values, mask, keys, num_groups):
        """One sort by (group, hash) yields distinct ranks AND per-key
        payload segment sums (prefix-sum difference at run boundaries)."""
        import jax.numpy as jnp
        from jax import lax

        v, pay = values[0], values[1]
        if num_groups == 1:
            kk = self.K
            gk = jnp.where(mask, np.int32(0), np.int32(1))
        else:
            kk = max(16, min(self.GROUPED_K, 2_000_000 // max(1, num_groups)))
            gk = jnp.where(mask, keys.astype(jnp.int32), np.int32(num_groups))
        _check_cell_budget(self.name, num_groups, kk)
        n = mask.shape[0]
        h = jnp.where(mask, self._hash(v), _I64_MAX)
        payf = jnp.where(mask, pay.astype(jnp.float64), 0.0)
        iota = jnp.arange(n, dtype=jnp.int32)
        s_k, s_h, perm = lax.sort((gk, h, iota), num_keys=2)
        s_pay = payf[perm]
        prev_k = jnp.concatenate([jnp.full((1,), -1, s_k.dtype), s_k[:-1]])
        prev_h = jnp.concatenate([jnp.full((1,), -1, s_h.dtype), s_h[:-1]])
        grp_start = s_k != prev_k
        new = (grp_start | (s_h != prev_h)) & (s_k < num_groups) & (s_h != _I64_MAX)
        c = jnp.cumsum(new.astype(jnp.int32))
        base = lax.cummax(jnp.where(grp_start, c - new.astype(jnp.int32), 0))
        rank = c - 1 - base
        # per-key payload sum: prefix sums differenced between run starts
        p0 = jnp.concatenate([jnp.zeros((1,), jnp.float64), jnp.cumsum(s_pay)])
        starts_at = jnp.where(new, iota, np.int32(n))
        nxt_ge = lax.cummin(starts_at[::-1])[::-1]
        nxt_start = jnp.concatenate([nxt_ge[1:], jnp.full((1,), n, jnp.int32)])
        # at a run start i: sum of s_pay[i : next run start); elsewhere unused
        run_end = jnp.where(
            nxt_start >= n, np.int32(n), nxt_start
        )
        run_sum = p0[run_end] - p0[iota]
        cells = num_groups * kk
        slot = jnp.where(new & (rank < kk), s_k * np.int32(kk) + rank, np.int32(cells))
        kmv = (
            jnp.full((cells + 1,), _I64_MAX, dtype=jnp.int64)
            .at[slot]
            .set(s_h)[:cells]
            .reshape(num_groups, kk)
        )
        pays = (
            jnp.zeros((cells + 1,), jnp.float64)
            .at[slot]
            .set(run_sum)[:cells]
            .reshape(num_groups, kk)
        )
        return {"kmv": kmv, "pay": pays}

    def merge(self, a, b):
        """Hash-aligned pairwise merge: concat along the K axis, sort by
        hash, fold duplicate neighbors' payloads left, keep the K smallest."""
        ak, bk = np.asarray(a["kmv"]), np.asarray(b["kmv"])
        ap, bp = np.asarray(a["pay"], np.float64), np.asarray(b["pay"], np.float64)
        x = np.concatenate([ak, bk], axis=-1)
        p = np.concatenate([ap, bp], axis=-1)
        order = np.argsort(x, axis=-1, kind="stable")
        x = np.take_along_axis(x, order, -1)
        p = np.take_along_axis(p, order, -1)
        dup = np.zeros_like(x, dtype=bool)
        dup[..., 1:] = x[..., 1:] == x[..., :-1]
        # fold payload of duplicates into the first of each equal run
        # (runs have length <= 2: each side holds distinct hashes)
        carry = np.where(dup, p, 0.0)
        p = p + np.roll(carry, -1, axis=-1)
        p = np.where(dup, 0.0, p)
        x = np.where(dup, _I64_MAX, x)
        order = np.argsort(x, axis=-1, kind="stable")
        x = np.take_along_axis(x, order, -1)
        p = np.take_along_axis(p, order, -1)
        k = min(ak.shape[-1], bk.shape[-1])
        return {"kmv": x[..., :k], "pay": p[..., :k]}

    def final(self, p):
        kmv = np.asarray(p["kmv"])
        pay = np.asarray(p["pay"], dtype=np.float64)
        one = kmv.ndim == 1
        kmv = np.atleast_2d(kmv)
        pay = np.atleast_2d(pay)
        k = kmv.shape[-1]
        valid = kmv != _I64_MAX
        n_v = valid.sum(axis=-1)
        kth = kmv[..., -1].astype(np.float64)
        with np.errstate(divide="ignore", invalid="ignore"):
            theta = np.where(n_v < k, 1.0, kth / float(1 << 62))
            if self.estimate == "distinct":
                out = np.where(n_v < k, n_v, (n_v - 1) / theta)
            elif self.estimate == "sum":
                psum = np.where(valid, pay, 0.0)
                # saturated: drop the theta-defining Kth entry like the
                # distinct estimator, scale by 1/theta
                psum = np.where(
                    (n_v < k)[..., None], psum, np.where(
                        np.arange(k)[None, :] < k - 1, psum, 0.0
                    ),
                )
                out = psum.sum(axis=-1) / theta
            else:  # avg summary value among retained keys
                cnt = np.where(n_v < k, n_v, n_v - 1)
                psum = np.where(valid, pay, 0.0).sum(axis=-1)
                psum = np.where(n_v < k, psum, psum - np.where(valid[..., -1], pay[..., -1], 0.0))
                out = np.where(cnt > 0, psum / np.maximum(cnt, 1), np.nan)
        return out[0] if one else out

    def final_dtype(self):
        return np.dtype(np.int64) if self.estimate == "distinct" else np.dtype(np.float64)


# ---------------------------------------------------------------------------
# Funnel family: per-step correlate-key presence bitmaps
# ---------------------------------------------------------------------------
def _ordered_funnel_reach(codes, steps, ts, mask, cells, window):
    """Deepest ORDERED funnel step per correlate key: [cells] int32.

    Device kernel: stable-sort rows by (key, ts), then one lax.scan over the
    sorted rows carrying per-step chain-START timestamps.  DP invariant:
    carry[s] is the LATEST start time of any chain that has reached step
    s+1 — a later start never has less window slack, so keeping the max is
    exact (equals the brute-force over all chains).  An event extends step
    s from the PRE-update carry[s-1], so one row never serves two
    consecutive steps (strict event ordering).  Per-row reach scatter-maxes
    into a [cells+1] table; masked rows ride the sentinel slot and drop.

    The scan is sequential over rows — correctness-first; the unordered
    set-intersection path (no TIMESTAMPBY) remains the fast default.
    """
    import jax.numpy as jnp
    from jax import lax

    S = len(steps)
    key = jnp.where(mask, codes.astype(jnp.int32), jnp.int32(cells))
    # x64 is enabled package-wide: float64 carries epoch-ms exactly (< 2^53)
    tsv = ts.astype(jnp.float64)
    sorted_ops = lax.sort(
        (key, tsv) + tuple(s.astype(bool) for s in steps), num_keys=2
    )
    key_s, ts_s = sorted_ops[0], sorted_ops[1]
    smat_s = jnp.stack(sorted_ops[2:], axis=1)  # [N, S]
    NEG = jnp.float64(-(2.0 ** 62))
    win = jnp.float64(window)

    def body(carry, x):
        prev, pkey = carry
        k, t, srow = x
        prev = jnp.where(k != pkey, NEG, prev)  # new key: reset the chains
        started = jnp.where(srow[0], t, prev[0])
        if S > 1:
            ext = srow[1:] & (prev[:-1] > NEG) & (t - prev[:-1] <= win)
            rest = jnp.where(ext, jnp.maximum(prev[1:], prev[:-1]), prev[1:])
            new = jnp.concatenate([started[None], rest])
        else:
            new = started[None]
        reach = (new > NEG).sum().astype(jnp.int32)
        return (new, k), reach

    init = (jnp.full((S,), NEG, jnp.float64), jnp.int32(-1))
    _, reach = lax.scan(body, init, (key_s, ts_s, smat_s))
    tbl = jnp.zeros((cells + 1,), jnp.int32).at[key_s].max(reach)
    return tbl[:cells]


class FunnelCountFunction(AggFunction):
    """FUNNELCOUNT(STEPS(cond1, ..., condS), CORRELATEBY(col)) — per step s,
    how many correlate keys matched ALL of steps 1..s (set-intersection
    funnel, the reference's bitmap strategy:
    pinot-core/.../query/aggregation/function/funnel/
    FunnelCountAggregationFunction.java).

    TPU form: per-step presence bitmaps over the correlate key domain
    (scatter-or via group_count>0) — an additive [S, domain] int32 tensor
    partial that merges by max and psums across shards; the prefix-AND and
    counting happen at final over the table-sized array.  Keys need a
    shared dictionary or bounded int range (like exact DISTINCTCOUNT).

    ORDERED mode (TIMESTAMPBY(col) [, window] — ADVICE r5): the
    set-intersection form inflates because it ignores event order; with a
    timestamp the per-segment partial becomes deepest-REACHED-step per key
    (_ordered_funnel_reach: sorted scan, window measured from the chain's
    first step).  present[s] = reach > s is prefix-monotone, so the same
    max-merge and cumprod final apply unchanged.  Caveat: reach merges
    across segments by MAX — a chain whose steps span two segments of one
    key is undercounted (never inflated); co-partition events by correlate
    key for exact multi-segment results."""

    name = "funnelcount"
    needs_codes = True
    needs_binding = True
    needs_extra_exprs = True
    vector_fields = True
    fields = ("present",)
    mode = "counts"  # counts | complete | maxstep
    input_kind = "codes"

    def __init__(
        self,
        domain: int = 0,
        base: int = 0,
        input_kind: str = "codes",
        ordered: bool = False,
        window: float = float("inf"),
    ):
        self.domain = domain
        self.base = base
        self.input_kind = input_kind
        self.ordered = ordered
        self.window = window

    def _rebind(self, **kw):
        cur = dict(
            domain=self.domain, base=self.base, input_kind=self.input_kind,
            ordered=self.ordered, window=self.window,
        )
        cur.update(kw)
        return type(self)(**cur)

    def with_args(self, literal_args):
        if not literal_args:
            return self
        # parser emits literal_args=(window,) iff TIMESTAMPBY is present
        return self._rebind(ordered=True, window=float(literal_args[0]))

    def bind_column(self, info: ColumnBinding):
        if info.kind == "dict":
            return self._rebind(domain=info.domain, input_kind="codes")
        if info.kind == "rawint":
            return self._rebind(domain=info.domain, base=info.base, input_kind="values_offset")
        raise NotImplementedError(
            f"{self.name.upper()} needs a dictionary or bounded-int CORRELATEBY column"
        )

    def partial(self, values, mask):
        import jax.numpy as jnp

        if self.ordered:
            codes, *rest = values
            steps, ts = rest[:-1], rest[-1]
            _check_cell_budget(self.name, len(steps), self.domain)
            tbl = _ordered_funnel_reach(codes, steps, ts, mask, self.domain, self.window)
            rows = [(tbl > s).astype(jnp.int32) for s in range(len(steps))]
            return {"present": jnp.stack(rows, axis=0)}  # [S, domain]
        codes, *steps = values
        _check_cell_budget(self.name, len(steps), self.domain)
        rows = [
            (ops.group_count(mask & s.astype(bool), codes, self.domain) > 0).astype(jnp.int32)
            for s in steps
        ]
        return {"present": jnp.stack(rows, axis=0)}  # [S, domain]

    def partial_grouped(self, values, mask, keys, num_groups):
        import jax.numpy as jnp

        if self.ordered:
            codes, *rest = values
            steps, ts = rest[:-1], rest[-1]
            _check_cell_budget(self.name, num_groups * len(steps), self.domain)
            flat = keys.astype(jnp.int32) * np.int32(self.domain) + codes
            cells = num_groups * self.domain
            tbl = _ordered_funnel_reach(flat, steps, ts, mask, cells, self.window)
            tbl = tbl.reshape(num_groups, self.domain)
            rows = [(tbl > s).astype(jnp.int32) for s in range(len(steps))]
            return {"present": jnp.stack(rows, axis=1)}  # [G, S, domain]
        codes, *steps = values
        _check_cell_budget(self.name, num_groups * len(steps), self.domain)
        flat = keys.astype(jnp.int32) * np.int32(self.domain) + codes
        cells = num_groups * self.domain
        rows = [
            (ops.group_count(mask & s.astype(bool), flat, cells) > 0)
            .astype(jnp.int32)
            .reshape(num_groups, self.domain)
            for s in steps
        ]
        return {"present": jnp.stack(rows, axis=1)}  # [G, S, domain]

    def merge(self, a, b):
        return {"present": np.maximum(np.asarray(a["present"]), np.asarray(b["present"]))}

    def final(self, p):
        pres = np.asarray(p["present"])
        one = pres.ndim == 2
        if one:
            pres = pres[None]  # [1, S, domain]
        prefix = np.cumprod(pres > 0, axis=1)  # AND over steps 1..s
        if self.mode == "counts":
            counts = prefix.sum(axis=2)  # [G, S]
            out = np.empty(counts.shape[0], dtype=object)
            for g in range(counts.shape[0]):
                out[g] = [int(c) for c in counts[g]]
        elif self.mode == "complete":
            out = prefix[:, -1, :].sum(axis=1).astype(np.int64)
        else:  # maxstep: deepest step any correlate key completed
            per_key = prefix.sum(axis=1)  # [G, domain] leading-True runs
            out = per_key.max(axis=1).astype(np.int64)
        return out[0] if one else out

    def final_dtype(self):
        return np.dtype(object) if self.mode == "counts" else np.dtype(np.int64)


class FunnelCompleteCountFunction(FunnelCountFunction):
    name = "funnelcompletecount"
    mode = "complete"


class FunnelMaxStepFunction(FunnelCountFunction):
    name = "funnelmaxstep"
    mode = "maxstep"


class SumValuesTupleSketchFunction(IntegerTupleSketchFunction):
    name = "sumvaluesintegersumtuplesketch"
    estimate = "sum"


class AvgValueTupleSketchFunction(IntegerTupleSketchFunction):
    name = "avgvalueintegersumtuplesketch"
    estimate = "avg"


for _cls in (
    HistogramFunction,
    CovarianceFunction,
    CovarianceSampFunction,
    CorrelationFunction,
    ExprMaxFunction,
    ExprMinFunction,
    FrequentStringsFunction,
    IntegerTupleSketchFunction,
    SumValuesTupleSketchFunction,
    AvgValueTupleSketchFunction,
    FunnelCountFunction,
    FunnelCompleteCountFunction,
    FunnelMaxStepFunction,
):
    register(_cls())

from pinot_tpu.query.functions import _REGISTRY  # noqa: E402

# reference exposes both spellings
for _alias, _target in (
    ("expr_max", "exprmax"),
    ("expr_min", "exprmin"),
    ("argmax", "exprmax"),
    ("argmin", "exprmin"),
    ("arg_max", "exprmax"),
    ("arg_min", "exprmin"),
    ("covarpop", "covar_pop"),
    ("covarsamp", "covar_samp"),
    ("funnel_count", "funnelcount"),
    ("funnel_complete_count", "funnelcompletecount"),
    ("funnel_max_step", "funnelmaxstep"),
):
    _REGISTRY[_alias] = _REGISTRY[_target]
