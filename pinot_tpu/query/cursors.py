"""Result cursors: server-side response store with paged fetch.

Reference parity: pinot-spi ResponseStore + broker cursor endpoints
(pinot-broker/.../broker/cursors/, CursorIntegrationTest) — a query run
with cursors enabled keeps its full result server-side; clients page
through it by cursor id.
"""
from __future__ import annotations

import threading
import time
import uuid
from typing import Dict, Optional

from pinot_tpu.query.result import ResultTable


class ResponseStore:
    def __init__(self, ttl_seconds: float = 300.0, max_entries: int = 128):
        self.ttl = ttl_seconds
        self.max_entries = max_entries
        self._lock = threading.Lock()
        self._store: Dict[str, tuple] = {}  # id -> (ResultTable, page_size, created)

    def register(self, result: ResultTable, page_size: int = 1000) -> str:
        cid = uuid.uuid4().hex[:16]
        with self._lock:
            self._evict_locked()
            # monotonic: TTL age math must not jump with wall-clock steps
            self._store[cid] = (result, max(1, page_size), time.monotonic())
        return cid

    def fetch(self, cursor_id: str, page: int) -> Dict:
        with self._lock:
            self._evict_locked()  # TTL applies on read too, not just register
            entry = self._store.get(cursor_id)
        if entry is None:
            raise KeyError(f"cursor {cursor_id!r} not found (expired or never created)")
        result, page_size, _ = entry
        n = len(result.rows)
        start = page * page_size
        rows = result.rows[start : start + page_size]
        return {
            "cursorId": cursor_id,
            "page": page,
            "pageSize": page_size,
            "totalRows": n,
            "numPages": (n + page_size - 1) // page_size,
            "columns": result.columns,
            "rows": [list(r) for r in rows],
        }

    def delete(self, cursor_id: str) -> bool:
        with self._lock:
            return self._store.pop(cursor_id, None) is not None

    def _evict_locked(self) -> None:
        now = time.monotonic()
        dead = [cid for cid, (_, _, t) in self._store.items() if now - t > self.ttl]
        for cid in dead:
            del self._store[cid]
        while len(self._store) >= self.max_entries:
            oldest = min(self._store, key=lambda c: self._store[c][2])
            del self._store[oldest]
