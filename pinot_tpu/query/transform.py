"""Expression evaluation on device columns.

Reference parity: pinot-core's 76 vectorized transform-function classes +
TransformOperator (.../operator/transform/).  Re-design: expressions are
evaluated by tracing — each Expr node becomes jnp ops inside the segment
kernel closure, and XLA fuses the whole expression into the surrounding
filter/aggregate kernel (no per-block operator objects, no intermediate
buffers unless XLA wants them).

Null propagation is SQL-style: a row's expression value is null if any input
column value is null (tracked as a parallel bool mask; None when statically
known null-free).
"""
from __future__ import annotations

from typing import Callable, Dict, Optional, Tuple

import jax.numpy as jnp

import numpy as np

from pinot_tpu.query import scalar
from pinot_tpu.query.ir import Expr, ExprKind
from pinot_tpu.segment.segment import ImmutableSegment

# value, null-mask (None = no nulls possible)
EvalResult = Tuple[jnp.ndarray, Optional[jnp.ndarray]]


def _or_masks(a, b):
    if a is None:
        return b
    if b is None:
        return a
    return a | b


_BINARY = {
    "plus": jnp.add,
    "add": jnp.add,
    "minus": jnp.subtract,
    "sub": jnp.subtract,
    "times": jnp.multiply,
    "mult": jnp.multiply,
    "mod": jnp.mod,
    "pow": jnp.power,
}

_UNARY = {
    "abs": jnp.abs,
    "neg": jnp.negative,
    "floor": jnp.floor,
    "ceiling": jnp.ceil,
    "ceil": jnp.ceil,
    "exp": jnp.exp,
    "ln": jnp.log,
    "log": jnp.log,  # Pinot's LOG is natural log
    "log2": jnp.log2,
    "log10": jnp.log10,
    "sqrt": jnp.sqrt,
    "sign": jnp.sign,
}


def column_values(name: str, segment: ImmutableSegment, cols: Dict) -> EvalResult:
    """Numeric values of a column from the device pytree (dictionary gather
    for dict-encoded numerics — the ProjectionOperator/DataFetcher analog)."""
    c = segment.column(name)
    entry = cols[name]
    if c.data_type.is_string_like:
        raise ValueError(
            f"column {name!r} is {c.data_type.value}; string values never materialize on device "
            "(use it in predicates/group-by, which operate on dict codes)"
        )
    if "values" in entry:
        vals = entry["values"]
    else:
        vals = entry["dict"][entry["codes"].astype(jnp.int32)]
    nulls = entry.get("nulls")
    return vals, nulls


def eval_expr(expr: Expr, segment: ImmutableSegment, cols: Dict) -> EvalResult:
    """Trace an expression into jnp ops over the segment's device columns."""
    if expr.kind is ExprKind.COLUMN:
        return column_values(expr.op, segment, cols)
    if expr.kind is ExprKind.LITERAL:
        # Python scalars stay weak-typed: arithmetic keeps the column's dtype
        # (jnp.asarray would mint an int64/f64 under x64 and force emulated
        # 64-bit elementwise ops on TPU).
        return expr.value, None
    op = expr.op
    if op in _BINARY and len(expr.args) == 2:
        (a, na) = eval_expr(expr.args[0], segment, cols)
        (b, nb) = eval_expr(expr.args[1], segment, cols)
        return _BINARY[op](a, b), _or_masks(na, nb)
    if op in ("divide", "div"):
        (a, na) = eval_expr(expr.args[0], segment, cols)
        (b, nb) = eval_expr(expr.args[1], segment, cols)
        # SQL divide: always double (Pinot DivisionTransformFunction)
        return astype(a, jnp.float64) / astype(b, jnp.float64), _or_masks(na, nb)
    if op in _UNARY and len(expr.args) == 1:
        (a, na) = eval_expr(expr.args[0], segment, cols)
        return _UNARY[op](a), na
    if op == "cast" and len(expr.args) == 2 and expr.args[1].is_literal:
        (a, na) = eval_expr(expr.args[0], segment, cols)
        target = str(expr.args[1].value).upper()
        dt = {"INT": jnp.int32, "LONG": jnp.int64, "FLOAT": jnp.float32, "DOUBLE": jnp.float64}.get(target)
        if dt is None:
            raise ValueError(f"unsupported CAST target {target}")
        return astype(a, dt), na
    if op in ("arraylength", "cardinality") and len(expr.args) == 1 and expr.args[0].is_column:
        entry = cols[expr.args[0].op]
        if "lengths" not in entry:
            raise ValueError(f"{op} requires a multi-value column ({expr.args[0].op} is single-value)")
        return entry["lengths"].astype(jnp.int32), None
    if op == "case":
        return _eval_case(expr, segment, cols)
    if op in ("__and", "__or", "__not", "__eq", "__in", "__ge", "__gt", "__le", "__lt", "__isnull"):
        return _eval_bool(expr, segment, cols), None
    if op in ("least", "greatest") and expr.args:
        vals, nulls = zip(*(eval_expr(a, segment, cols) for a in expr.args))
        acc, nl = vals[0], nulls[0]
        for v, n in zip(vals[1:], nulls[1:]):
            acc = jnp.minimum(acc, v) if op == "least" else jnp.maximum(acc, v)
            nl = _or_masks(nl, n)
        return acc, nl
    if op in scalar.DEVICE_MULTI_FNS:
        # positional: every arg evaluates (literals stay scalars)
        vals, nulls = [], None
        for a in expr.args:
            if a.is_literal:
                vals.append(a.value)
            else:
                v, nv = eval_expr(a, segment, cols)
                vals.append(v)
                nulls = _or_masks(nulls, nv)
        return scalar.DEVICE_MULTI_FNS[op](*vals), nulls
    if op in scalar.DEVICE_FNS:
        # one traced operand + literal parameters, in SQL order
        # (DATETRUNC('day', ts) / ROUND(x, 2) / TIMECONVERT(t, 'SECONDS', 'DAYS'))
        traced = [a for a in expr.args if not a.is_literal]
        lits = [a.value for a in expr.args if a.is_literal]
        if len(traced) != 1:
            raise ValueError(f"{op} expects exactly one column/expression argument, got {expr}")
        v, nv = eval_expr(traced[0], segment, cols)
        return scalar.DEVICE_FNS[op](v if hasattr(v, "astype") else jnp.asarray(v), *lits), nv
    if scalar.is_dict_fn_expr(expr):
        # dictionary-domain function: host-evaluate over the dictionary's
        # VALUES (cardinality-sized) and gather derived[codes] on device.
        col = next(a for a in expr.args if not a.is_literal).op
        c = segment.column(col)
        if not c.has_dictionary:
            raise ValueError(f"{op} requires a dictionary-encoded column ({col} is raw)")
        if scalar.string_result(expr):
            raise ValueError(
                f"string-valued {op}(...) never materializes on device; use it in "
                "predicates, GROUP BY, or the select list (host paths)"
            )
        derived = scalar.derived_for(expr, c.dictionary)
        entry = cols[col]
        vals = jnp.asarray(derived)[entry["codes"].astype(jnp.int32)]
        return vals, entry.get("nulls")
    raise ValueError(f"unsupported transform function {op!r} in {expr}")


def _eval_bool_host(expr: Expr, segment: ImmutableSegment, docids: np.ndarray) -> np.ndarray:
    """Host (numpy) twin of _eval_bool for selection-path CASE."""
    op = expr.op
    if op == "__and":
        out = None
        for a in expr.args:
            b = _eval_bool_host(a, segment, docids)
            out = b if out is None else out & b
        return out
    if op == "__or":
        out = None
        for a in expr.args:
            b = _eval_bool_host(a, segment, docids)
            out = b if out is None else out | b
        return out
    if op == "__not":
        return ~_eval_bool_host(expr.args[0], segment, docids)
    lhs = expr.args[0]
    lits = [a.value for a in expr.args[1:]]
    if op == "__isnull":
        if lhs.is_column and segment.column(lhs.op).nulls is not None:
            return segment.column(lhs.op).nulls[docids]
        return np.zeros(len(docids), dtype=bool)
    v = eval_expr_host(lhs, segment, docids)
    if op == "__eq":
        return np.asarray([x == lits[0] for x in v], dtype=bool)
    if op == "__in":
        s = set(lits)
        return np.asarray([x in s for x in v], dtype=bool)
    v = np.asarray(v, dtype=np.float64)
    if op == "__ge":
        return v >= lits[0]
    if op == "__gt":
        return v > lits[0]
    if op == "__le":
        return v <= lits[0]
    return v < lits[0]


def _eval_bool(expr: Expr, segment: ImmutableSegment, cols: Dict):
    """CASE condition ops -> traced bool row mask (CaseTransformFunction's
    WHEN evaluation).  String equality/IN resolve against the dictionary
    (code compares); numerics compare values directly."""
    op = expr.op
    if op == "__and":
        out = None
        for a in expr.args:
            b = _eval_bool(a, segment, cols)
            out = b if out is None else out & b
        return out
    if op == "__or":
        out = None
        for a in expr.args:
            b = _eval_bool(a, segment, cols)
            out = b if out is None else out | b
        return out
    if op == "__not":
        return ~_eval_bool(expr.args[0], segment, cols)
    lhs = expr.args[0]
    lits = [a.value for a in expr.args[1:]]
    if op == "__isnull":
        entry = cols.get(lhs.op, {}) if lhs.is_column else {}
        if "nulls" in entry:
            return entry["nulls"]
        n = segment.num_docs
        return jnp.zeros((n,), dtype=bool)
    # string column comparisons resolve to dictionary codes
    if lhs.is_column and segment.column(lhs.op).data_type.is_string_like:
        c = segment.column(lhs.op)
        codes = cols[lhs.op]["codes"].astype(jnp.int32)
        ids = [c.dictionary.index_of(v) for v in lits]
        if op == "__eq":
            return codes == np.int32(ids[0])
        if op == "__in":
            valid = np.asarray([i for i in ids if i >= 0], dtype=np.int32)
            return jnp.isin(codes, valid) if len(valid) else jnp.zeros(codes.shape, bool)
        raise ValueError(f"CASE condition {op} not supported on string column {lhs.op}")
    v, _ = eval_expr(lhs, segment, cols)
    if op == "__eq":
        return v == lits[0]
    if op == "__in":
        return jnp.isin(v, jnp.asarray(lits))
    if op == "__ge":
        return v >= lits[0]
    if op == "__gt":
        return v > lits[0]
    if op == "__le":
        return v <= lits[0]
    return v < lits[0]


def _eval_case(expr: Expr, segment: ImmutableSegment, cols: Dict) -> EvalResult:
    """CASE WHEN ... THEN ... ELSE ... END: reverse-fold of jnp.where.
    An omitted ELSE yields SQL NULL via the null mask."""
    args = list(expr.args)
    else_e = args[-1]
    else_null = else_e.is_literal and else_e.value is None
    if else_null:
        out, en = jnp.float64(0.0), None  # implicit ELSE NULL
    else:
        out, en = eval_expr(else_e, segment, cols)
    evaluated = [
        (_eval_bool(c, segment, cols), *eval_expr(t, segment, cols))
        for c, t in zip(args[:-1:2], args[1::2])
    ]
    # reverse-fold values AND null masks together: a row's nullness is the
    # CHOSEN branch's nullness, not the OR of all branches (review-caught)
    if else_null or en is not None or any(tn is not None for _, _, tn in evaluated):
        nulls = en if en is not None else jnp.full((segment.num_docs,), else_null, dtype=bool)
    else:
        nulls = None
    for cond, tv, tn in reversed(evaluated):
        out = jnp.where(cond, tv, out)
        if nulls is not None:
            branch_null = tn if tn is not None else False
            nulls = jnp.where(cond, branch_null, nulls)
    return out, nulls


def eval_expr_host(expr: Expr, segment: ImmutableSegment, docids: np.ndarray) -> np.ndarray:
    """Host-side expression evaluation over a SELECTED row subset (selection
    queries gather at most offset+limit rows, so O(rows-out) host work).
    Shares DEVICE_FNS via eager jnp; string-valued dictionary functions
    evaluate over the dictionary and gather by code."""
    if expr.kind is ExprKind.COLUMN:
        return segment.column(expr.op).decoded()[docids]
    if expr.kind is ExprKind.LITERAL:
        return np.full(len(docids), expr.value)
    if expr.op in ("arraylength", "cardinality") and len(expr.args) == 1 and expr.args[0].is_column:
        c = segment.column(expr.args[0].op)
        if c.mv_lengths is None:
            raise ValueError(f"{expr.op} requires a multi-value column")
        return c.mv_lengths[docids].astype(np.int64)
    if expr.op == "case":
        args = list(expr.args)
        else_e = args[-1]
        pairs = list(zip(args[:-1:2], args[1::2]))
        if else_e.is_literal and else_e.value is None:
            out = np.full(len(docids), None, dtype=object)
        else:
            out = np.asarray(eval_expr_host(else_e, segment, docids), dtype=object)
        for cond_e, then_e in reversed(pairs):
            cond = _eval_bool_host(cond_e, segment, docids)
            tv = np.asarray(eval_expr_host(then_e, segment, docids), dtype=object)
            out = np.where(cond, tv, out)
        return out
    if scalar.is_dict_fn_expr(expr):
        col = next(a for a in expr.args if not a.is_literal).op
        c = segment.column(col)
        if c.has_dictionary:
            derived = scalar.derived_for(expr, c.dictionary)
            return derived[np.asarray(c.codes, dtype=np.int64)[docids]]
    op = expr.op
    if op in _BINARY and len(expr.args) == 2:
        a = eval_expr_host(expr.args[0], segment, docids)
        b = eval_expr_host(expr.args[1], segment, docids)
        return np.asarray(_BINARY[op](jnp.asarray(a), jnp.asarray(b)))
    if op in ("divide", "div"):
        a = eval_expr_host(expr.args[0], segment, docids).astype(np.float64)
        b = eval_expr_host(expr.args[1], segment, docids).astype(np.float64)
        return a / b
    if op in _UNARY and len(expr.args) == 1:
        return np.asarray(_UNARY[op](jnp.asarray(eval_expr_host(expr.args[0], segment, docids))))
    if op in scalar.DEVICE_MULTI_FNS:
        vals = [
            a.value if a.is_literal else jnp.asarray(eval_expr_host(a, segment, docids).astype(np.float64))
            for a in expr.args
        ]
        return np.asarray(scalar.DEVICE_MULTI_FNS[op](*vals))
    if op in scalar.DEVICE_FNS:
        traced = [a for a in expr.args if not a.is_literal]
        lits = [a.value for a in expr.args if a.is_literal]
        if len(traced) == 1:
            v = eval_expr_host(traced[0], segment, docids)
            return np.asarray(scalar.DEVICE_FNS[op](jnp.asarray(v), *lits))
    if op == "todatetime" and len(expr.args) in (2, 3) and expr.args[1].is_literal:
        v = eval_expr_host(expr.args[0], segment, docids)
        tz = expr.args[2].value if len(expr.args) == 3 and expr.args[2].is_literal else None
        return scalar.to_datetime(v, expr.args[1].value, tz)
    if op == "cast" and len(expr.args) == 2 and expr.args[1].is_literal:
        v = eval_expr_host(expr.args[0], segment, docids)
        target = str(expr.args[1].value).upper()
        npdt = {"INT": np.int32, "LONG": np.int64, "FLOAT": np.float32, "DOUBLE": np.float64, "STRING": None}.get(
            target, np.float64
        )
        return v.astype(str) if npdt is None else v.astype(npdt)
    raise ValueError(f"unsupported selection expression {op!r} in {expr}")


def astype(vals, dt):
    """dtype cast that also accepts the weak-typed python scalars LITERAL
    nodes produce (the single normalization point for literal operands)."""
    if hasattr(vals, "astype"):
        return vals.astype(dt)
    return jnp.asarray(vals, dtype=dt)


def as_row_array(vals, shape):
    """Broadcast a weak-typed literal to a row-shaped array; pass arrays
    through (shared by planner/engine aggregation-input plumbing)."""
    if hasattr(vals, "astype"):
        return vals
    return jnp.full(shape, float(vals), dtype=jnp.float64)
