"""Result containers flowing segment -> combine -> broker reduce.

Reference parity: per-segment result blocks + the DataTable payload
(IntermediateResultsBlock / DataTableImplV4, SURVEY.md 2.2).  Re-design:
results stay columnar numpy end-to-end; "serialization" only exists at the
client boundary (JSON), since combine happens via collectives/arrays, not
sockets.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

import numpy as np


@dataclass
class ExecutionStats:
    """Per-query execution statistics (ExecutionStatistics /
    BrokerResponse stats analog)."""

    num_segments_queried: int = 0
    num_segments_pruned: int = 0
    num_segments_processed: int = 0
    num_docs_scanned: int = 0
    total_docs: int = 0
    num_groups: int = 0
    time_ms: float = 0.0
    # scatter-gather fault surface (BrokerResponse partialResult /
    # processingExceptions / numServersQueried|Responded analog): a query
    # that lost segments but was allowed to degrade carries
    # partial_result=True plus one exception entry per absorbed failure
    num_servers_queried: int = 0
    num_servers_responded: int = 0
    partial_result: bool = False
    exceptions: List[Dict[str, Any]] = field(default_factory=list)
    # (column, "sorted"|"range"|"inverted") per index-accelerated predicate —
    # proof that the filter read bitmap/doc-range rows instead of scanning
    # codes (BitmapBasedFilterOperator analog; see query/filter.py)
    filter_index_uses: Tuple = ()
    # span tree dict when the query ran with trace=true (utils/metrics.Trace)
    trace: Optional[dict] = None
    # broker/engine-minted request id (RequestContext requestId analog)
    query_id: Optional[str] = None
    # kernel cost accounting (utils/perf.KernelCost, summed over every
    # kernel launch this query dispatched): cost-model bytes/flops the
    # compiled scans streamed, the lower+compile wall time paid by THIS
    # query (0 on plan-cache hits), and where the model came from
    # ("xla" | "analytic" | "mixed" across kernels)
    kernel_bytes: float = 0.0
    kernel_flops: float = 0.0
    kernel_cost_source: Optional[str] = None
    compile_ms: float = 0.0
    # fence-bounded device-compute wall time (the device_wait span), when
    # the execution path measured one — the roofline denominator
    device_ms: float = 0.0
    # tail-tolerance surface (hedged scatter + brownout router, r15): how
    # many scatter calls hedged a backup, which server won the last hedged
    # call, how long the cancelled loser ran (best-effort: the loser thread
    # stamps it when its cooperative kill lands), and any brownout
    # transitions ("enter:server" / "exit:server") observed this query
    hedged: int = 0
    hedge_winner: Optional[str] = None
    hedge_cancelled_ms: float = 0.0
    brownout_events: List[str] = field(default_factory=list)

    def merge(self, other: "ExecutionStats") -> None:
        self.num_segments_queried += other.num_segments_queried
        self.num_segments_pruned += other.num_segments_pruned
        self.num_segments_processed += other.num_segments_processed
        self.num_docs_scanned += other.num_docs_scanned
        self.total_docs += other.total_docs
        self.num_groups = max(self.num_groups, other.num_groups)
        self.num_servers_queried += other.num_servers_queried
        self.num_servers_responded += other.num_servers_responded
        self.partial_result = self.partial_result or other.partial_result
        self.exceptions.extend(other.exceptions)
        self.add_index_uses(other.filter_index_uses)
        self.query_id = self.query_id or other.query_id
        self.hedged += other.hedged
        self.hedge_winner = other.hedge_winner or self.hedge_winner
        self.hedge_cancelled_ms += other.hedge_cancelled_ms
        self.brownout_events.extend(other.brownout_events)
        self.add_kernel_cost(other)

    def add_kernel_cost(self, other: "ExecutionStats") -> None:
        """Accumulate just the kernel-cost slice of `other` (used by the
        broker's scatter path, which merges the rest field-by-field)."""
        from pinot_tpu.utils.perf import combine_sources

        self.kernel_bytes += other.kernel_bytes
        self.kernel_flops += other.kernel_flops
        self.compile_ms += other.compile_ms
        self.device_ms += other.device_ms
        self.kernel_cost_source = combine_sources(
            self.kernel_cost_source, other.kernel_cost_source
        )

    def add_index_uses(self, uses: Tuple) -> None:
        """Order-preserving dedup-union into filter_index_uses."""
        if uses:
            self.filter_index_uses = tuple(
                dict.fromkeys(self.filter_index_uses + tuple(uses))
            )


@dataclass
class AggSegmentResult:
    """Scalar aggregation partials: one Partial (dict of np scalars) per agg."""

    partials: List[Dict[str, np.ndarray]]


@dataclass
class GroupBySegmentResult:
    """Columnar group-by partials.

    keys: one np array per group dimension (decoded values; dtype=object when
    the dimension can hold None).  partials[i][field] is aligned with keys.
    dense_meta carries (num_groups, dim cardinalities, decode tables id) when
    the result came off the dense kernel with its FULL key space intact —
    enabling the aligned array merge fast path in reduce.py."""

    keys: List[np.ndarray]
    partials: List[Dict[str, np.ndarray]]
    dense: Optional["DenseGroupData"] = None


@dataclass
class DenseGroupData:
    """Full dense group table straight from the device kernel (before
    presence filtering) — kept when segments share a key space so the combine
    is pure array addition (the psum-shaped path)."""

    presence: np.ndarray  # int32[num_groups]
    partials: List[Dict[str, np.ndarray]]  # field arrays [num_groups]
    key_space: Tuple  # hashable id of the decode tables (see reduce.py)
    group_dims: List[Any] = field(default_factory=list)  # planner.GroupDim (decode)


@dataclass
class SelectionSegmentResult:
    columns: List[str]  # gathered columns (select + order-by needs)
    arrays: Dict[str, np.ndarray]


SegmentResult = Any  # union of the three above


@dataclass
class ResultTable:
    """Final client-facing result (BrokerResponse resultTable analog)."""

    columns: List[str]
    rows: List[tuple]
    stats: ExecutionStats = field(default_factory=ExecutionStats)

    def to_dict(self) -> Dict[str, Any]:
        def _py(v):
            if isinstance(v, np.generic):
                return v.item()
            if isinstance(v, bytes):
                return v.decode("latin-1")
            return v

        return {
            "resultTable": {
                "dataSchema": {"columnNames": self.columns},
                "rows": [[_py(v) for v in r] for r in self.rows],
            },
            "numSegmentsQueried": self.stats.num_segments_queried,
            "numSegmentsPruned": self.stats.num_segments_pruned,
            "numSegmentsProcessed": self.stats.num_segments_processed,
            "numDocsScanned": self.stats.num_docs_scanned,
            "totalDocs": self.stats.total_docs,
            "timeUsedMs": self.stats.time_ms,
            "numServersQueried": self.stats.num_servers_queried,
            "numServersResponded": self.stats.num_servers_responded,
            "partialResult": self.stats.partial_result,
            "exceptions": list(self.stats.exceptions),
        }
