"""Aggregation function registry.

Reference parity: pinot-core AggregationFunction contract
(.../query/aggregation/function/AggregationFunction.java:44 — aggregate /
aggregateGroupBySV / merge / extractFinalResult) and
AggregationFunctionFactory.

Re-design: the per-row `aggregate` loop becomes two vectorized device forms —
`partial(values, mask)` (scalar partial over a whole segment) and
`partial_grouped(values, mask, keys, num_groups)` (dense group table via
segment_sum/scatter-min — the DefaultGroupByExecutor + result-holder analog).
Partials are dicts of arrays so merge is shape-generic: AVG carries
(sum, count), MIN carries (min, seen), etc.  All numeric aggregation is
float64, matching Pinot's double accumulators (SumAggregationFunction et al).
"""
from __future__ import annotations

from typing import Any, Dict, Optional

import numpy as np

from pinot_tpu import ops

Partial = Dict[str, Any]

_POS_INF = float("inf")
_NEG_INF = float("-inf")

# CONTRACT: partial field NAMES imply their combine semantics.  Generic code
# (host sparse groupby, aligned dense merges, psum combines) dispatches on the
# field name instead of calling per-function merge() pairwise.
#   sum/count/sumsq -> additive      min -> minimum      max -> maximum
FIELD_COMBINE = {
    "sum": "add",
    "count": "add",
    "sumsq": "add",
    "min": "min",
    "max": "max",
    # sketch fields (query/sketches.py): presence bitmaps and HLL registers
    # union via max; histograms add; bin-range bookkeeping via min/max
    "present": "max",
    "hll": "max",
    "hist": "add",
    "lo": "min",
    "hi": "max",
    # covariance tuple fields (query/aggs_stats.py) — all additive
    "sumx": "add",
    "sumy": "add",
    "sumxy": "add",
    "sumsqx": "add",
    "sumsqy": "add",
}


def field_identity(field_name: str) -> float:
    op = FIELD_COMBINE[field_name]
    return 0.0 if op == "add" else (_POS_INF if op == "min" else _NEG_INF)


def combine_field(field_name: str, a, b):
    op = FIELD_COMBINE[field_name]
    if op == "add":
        return a + b
    if op == "min":
        return np.minimum(a, b)
    return np.maximum(a, b)


class AggFunction:
    """Base: one aggregation function's device/host contract."""

    name: str = ""
    needs_expr: bool = True
    # static partial field names (keys of partial()/partial_grouped() output);
    # host paths read this instead of probing with a dummy device call
    fields: tuple = ()
    # planner feeds dictionary codes / range-offset ints instead of values
    needs_codes: bool = False
    # planner must call bind_column() with per-column constants before use
    needs_binding: bool = False
    # partial fields are per-group VECTORS (presence/registers/histograms);
    # such aggs cannot ride the scalar-field sparse group-by kernel
    vector_fields: bool = False
    # partials merge ONLY via pairwise fn.merge (fields are coupled, e.g.
    # LASTWITHTIME's (t, v) or theta's kmv set) — the field-name elementwise
    # combines and in-graph psum paths must not touch them
    pairwise_merge: bool = False
    # spec.extra_exprs evaluate alongside expr; partial() receives the tuple
    # (values, extra0, ...) instead of a single array
    needs_extra_exprs: bool = False
    # field -> entry kind ("count"|"sum"|"sumsq"|"min"|"max") for the fused
    # dense group-by scan (ops.fused_group_tables); None = the function's own
    # partial_grouped runs instead (sketch family)
    field_kinds = None

    # -- binding (sketch functions override; see query/sketches.py) ------
    def with_args(self, literal_args) -> "AggFunction":
        """Specialize with SQL literal arguments (percentile rank, log2m)."""
        return self

    def bind_column(self, info) -> "AggFunction":
        """Bind per-column constants (domain, hash tables, bin ranges)."""
        return self

    def bind_reduce(self, ctx, spec) -> "AggFunction":
        """Bind REDUCE-time constants from engine-injected ctx options (e.g.
        FREQUENTSTRINGS' dictionary values for final-step decode).  Called on
        the registry singleton at broker reduce, where plan-side bind_column
        results are not available."""
        return self

    # -- device: per-segment partials -----------------------------------
    def partial(self, values, mask) -> Partial:
        raise NotImplementedError

    def partial_grouped(self, values, mask, keys, num_groups: int) -> Partial:
        raise NotImplementedError

    # -- host: post-device_get conversion hook ---------------------------
    def host_partial(self, p: Partial) -> Partial:
        """Convert a device partial to its host merge form (identity for
        tensor partials; value-set sketches decode here)."""
        return p

    # -- host or device: combine ----------------------------------------
    def merge(self, a: Partial, b: Partial) -> Partial:
        raise NotImplementedError

    def final(self, p: Partial):
        raise NotImplementedError

    def final_dtype(self) -> np.dtype:
        return np.dtype(np.float64)


class CountFunction(AggFunction):
    name = "count"
    needs_expr = False  # COUNT(*) — COUNT(col) counts non-null via mask
    fields = ("count",)
    field_kinds = {"count": "count"}

    def partial(self, values, mask):
        return {"count": ops.masked_count(mask)}

    def partial_grouped(self, values, mask, keys, num_groups):
        return {"count": ops.group_count(mask, keys, num_groups)}

    def merge(self, a, b):
        return {"count": a["count"] + b["count"]}

    def final(self, p):
        return p["count"]

    def final_dtype(self):
        return np.dtype(np.int64)


class SumFunction(AggFunction):
    """Carries (sum, count) so SUM over zero matching rows is SQL NULL."""

    name = "sum"
    fields = ("sum", "count")
    field_kinds = {"sum": "sum", "count": "count"}

    def partial(self, values, mask):
        return {"sum": ops.masked_sum(values, mask), "count": ops.masked_count(mask)}

    def partial_grouped(self, values, mask, keys, num_groups):
        return {
            "sum": ops.group_sum(values, mask, keys, num_groups),
            "count": ops.group_count(mask, keys, num_groups),
        }

    def merge(self, a, b):
        return {"sum": a["sum"] + b["sum"], "count": a["count"] + b["count"]}

    def final(self, p):
        return np.where(np.asarray(p["count"]) > 0, np.asarray(p["sum"], dtype=np.float64), np.nan)


class MinFunction(AggFunction):
    name = "min"
    fields = ("min", "count")
    field_kinds = {"min": "min", "count": "count"}

    def partial(self, values, mask):
        return {"min": ops.masked_min(values, mask), "count": ops.masked_count(mask)}

    def partial_grouped(self, values, mask, keys, num_groups):
        return {
            "min": ops.group_min(values, mask, keys, num_groups),
            "count": ops.group_count(mask, keys, num_groups),
        }

    def merge(self, a, b):
        return {"min": np.minimum(a["min"], b["min"]), "count": a["count"] + b["count"]}

    def final(self, p):
        return np.where(np.asarray(p["count"]) > 0, np.asarray(p["min"], dtype=np.float64), np.nan)


class MaxFunction(AggFunction):
    name = "max"
    fields = ("max", "count")
    field_kinds = {"max": "max", "count": "count"}

    def partial(self, values, mask):
        return {"max": ops.masked_max(values, mask), "count": ops.masked_count(mask)}

    def partial_grouped(self, values, mask, keys, num_groups):
        return {
            "max": ops.group_max(values, mask, keys, num_groups),
            "count": ops.group_count(mask, keys, num_groups),
        }

    def merge(self, a, b):
        return {"max": np.maximum(a["max"], b["max"]), "count": a["count"] + b["count"]}

    def final(self, p):
        return np.where(np.asarray(p["count"]) > 0, np.asarray(p["max"], dtype=np.float64), np.nan)


class AvgFunction(AggFunction):
    """Carries (sum, count) — Pinot's AvgPair intermediate result."""

    name = "avg"
    fields = ("sum", "count")
    field_kinds = {"sum": "sum", "count": "count"}

    def partial(self, values, mask):
        return {"sum": ops.masked_sum(values, mask), "count": ops.masked_count(mask)}

    def partial_grouped(self, values, mask, keys, num_groups):
        return {
            "sum": ops.group_sum(values, mask, keys, num_groups),
            "count": ops.group_count(mask, keys, num_groups),
        }

    def merge(self, a, b):
        return {"sum": a["sum"] + b["sum"], "count": a["count"] + b["count"]}

    def final(self, p):
        cnt = np.asarray(p["count"], dtype=np.float64)
        with np.errstate(divide="ignore", invalid="ignore"):
            return np.where(cnt > 0, np.asarray(p["sum"]) / cnt, np.nan)


class MinMaxRangeFunction(AggFunction):
    """MINMAXRANGE = max - min (Pinot MinMaxRangeAggregationFunction)."""

    name = "minmaxrange"
    fields = ("min", "max", "count")
    field_kinds = {"min": "min", "max": "max", "count": "count"}

    def partial(self, values, mask):
        return {
            "min": ops.masked_min(values, mask),
            "max": ops.masked_max(values, mask),
            "count": ops.masked_count(mask),
        }

    def partial_grouped(self, values, mask, keys, num_groups):
        return {
            "min": ops.group_min(values, mask, keys, num_groups),
            "max": ops.group_max(values, mask, keys, num_groups),
            "count": ops.group_count(mask, keys, num_groups),
        }

    def merge(self, a, b):
        return {
            "min": np.minimum(a["min"], b["min"]),
            "max": np.maximum(a["max"], b["max"]),
            "count": a["count"] + b["count"],
        }

    def final(self, p):
        rng = np.asarray(p["max"], dtype=np.float64) - np.asarray(p["min"], dtype=np.float64)
        return np.where(np.asarray(p["count"]) > 0, rng, np.nan)


class SumOfSquaresFunction(AggFunction):
    """Building block for VARIANCE/STDDEV (Pinot VarianceAggregationFunction
    carries count/sum/sumOfSquares the same way)."""

    name = "_sumsq"
    fields = ("count", "sum", "sumsq")
    field_kinds = {"count": "count", "sum": "sum", "sumsq": "sumsq"}

    def partial(self, values, mask):
        return {
            "count": ops.masked_count(mask),
            "sum": ops.masked_sum(values, mask),
            "sumsq": ops.masked_sum_sq(values, mask),
        }

    def partial_grouped(self, values, mask, keys, num_groups):
        return {
            "count": ops.group_count(mask, keys, num_groups),
            "sum": ops.group_sum(values, mask, keys, num_groups),
            "sumsq": ops.group_sum_sq(values, mask, keys, num_groups),
        }

    def merge(self, a, b):
        return {k: a[k] + b[k] for k in ("count", "sum", "sumsq")}


class VarianceFunction(SumOfSquaresFunction):
    name = "variance"  # population variance (VAR_POP)

    def final(self, p):
        cnt = np.asarray(p["count"], dtype=np.float64)
        s = np.asarray(p["sum"], dtype=np.float64)
        ss = np.asarray(p["sumsq"], dtype=np.float64)
        with np.errstate(divide="ignore", invalid="ignore"):
            mean = s / cnt
            return np.where(cnt > 0, ss / cnt - mean * mean, np.nan)


class VarianceSampFunction(SumOfSquaresFunction):
    name = "varsamp"

    def final(self, p):
        cnt = np.asarray(p["count"], dtype=np.float64)
        s = np.asarray(p["sum"], dtype=np.float64)
        ss = np.asarray(p["sumsq"], dtype=np.float64)
        with np.errstate(divide="ignore", invalid="ignore"):
            mean = s / cnt
            return np.where(cnt > 1, (ss - cnt * mean * mean) / (cnt - 1), np.nan)


class StdDevFunction(VarianceFunction):
    name = "stddev"

    def final(self, p):
        return np.sqrt(super().final(p))


class StdDevSampFunction(VarianceSampFunction):
    name = "stddevsamp"

    def final(self, p):
        return np.sqrt(super().final(p))


_REGISTRY: Dict[str, AggFunction] = {}


def register(fn: AggFunction) -> None:
    _REGISTRY[fn.name] = fn


for _cls in (
    CountFunction,
    SumFunction,
    MinFunction,
    MaxFunction,
    AvgFunction,
    MinMaxRangeFunction,
    VarianceFunction,
    VarianceSampFunction,
    StdDevFunction,
    StdDevSampFunction,
):
    register(_cls())

# aliases (Pinot exposes several)
_REGISTRY["var_pop"] = _REGISTRY["variance"]
_REGISTRY["var_samp"] = _REGISTRY["varsamp"]
_REGISTRY["stddev_pop"] = _REGISTRY["stddev"]
_REGISTRY["stddev_samp"] = _REGISTRY["stddevsamp"]


def is_agg_function(name: str) -> bool:
    return name.lower() in _REGISTRY


def get_agg_function(name: str) -> AggFunction:
    fn = _REGISTRY.get(name.lower())
    if fn is None:
        raise ValueError(f"unknown aggregation function {name!r} (have {sorted(_REGISTRY)})")
    return fn


def for_spec(spec) -> AggFunction:
    """Registry lookup + literal-arg specialization for one AggregationSpec.
    (Column binding is planner-side; merge/final never need it.)"""
    return get_agg_function(spec.function).with_args(spec.literal_args)


# Register the sketch family (import at bottom: sketches subclasses AggFunction)
from pinot_tpu.query import sketches  # noqa: E402,F401

# Extended aggregations (KLL log-sketch, theta, MODE, FIRST/LAST_WITH_TIME);
# must import AFTER sketches: percentilekll overrides the histogram stand-in
from pinot_tpu.query import aggs_extra  # noqa: E402,F401

# Statistics long tail (HISTOGRAM, covariance family, EXPR_MIN/MAX,
# FREQUENTSTRINGS, integer tuple sketches) — after aggs_extra (subclasses)
from pinot_tpu.query import aggs_stats  # noqa: E402,F401
