"""Query safety rails: deadlines, admission control, memory accounting.

Reference parity (SURVEY.md 5.2): Pinot's query-killing memory accountant
(PerQueryCPUMemAccountantFactory / ResourceManager heap protection), query
timeouts (ServerQueryExecutorV1Impl timeout checks between operator calls),
and scheduler admission (ResourceManager semaphores).

Re-design: the unit of work between checks is one SEGMENT KERNEL (the jitted
call), so the deadline is tested between segment launches — the same
granularity the reference gets between operator `nextBlock` calls.  Memory
admission is an up-front estimate of device bytes the plan will touch
(columns shipped + group tables), charged against a process-wide budget
while the query runs — an estimate-ahead variant of the reference's
sampling accountant (no mid-flight kill needed: XLA allocations are
per-kernel and bounded by the estimate).
"""
from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional

from pinot_tpu.query.ir import QueryContext


class QueryTimeoutError(RuntimeError):
    pass


class AdmissionError(RuntimeError):
    pass


class Deadline:
    __slots__ = ("expires_at", "timeout_ms")

    def __init__(self, timeout_ms: Optional[float]):
        self.timeout_ms = timeout_ms
        # `timeout_ms == 0` is an ALREADY-EXPIRED deadline, not "no deadline"
        # (a truthiness check here used to silently disable it)
        self.expires_at = (
            time.perf_counter() + timeout_ms / 1000 if timeout_ms is not None else None
        )

    @staticmethod
    def from_ctx(ctx: QueryContext) -> "Deadline":
        t = ctx.options.get("timeoutMs")
        return Deadline(float(t) if t is not None else None)

    def check(self, what: str = "query") -> None:
        if self.expired():
            raise QueryTimeoutError(f"{what} exceeded timeoutMs={self.timeout_ms:g}")

    def expired(self) -> bool:
        return self.expires_at is not None and time.perf_counter() >= self.expires_at

    def remaining_ms(self) -> Optional[float]:
        """Budget left, in ms; None = unbounded."""
        if self.expires_at is None:
            return None
        return max(0.0, (self.expires_at - time.perf_counter()) * 1000)

    def bounded(self, timeout_ms: Optional[float]) -> "Deadline":
        """A child deadline capped at min(this deadline, timeout_ms) — the
        per-server budget the broker hands each scatter call."""
        rem = self.remaining_ms()
        if timeout_ms is None:
            return self if rem is None else Deadline(rem)
        return Deadline(min(rem, float(timeout_ms)) if rem is not None else float(timeout_ms))


def estimate_segment_bytes(ctx: QueryContext, segment, needed_columns: Optional[List[str]] = None) -> int:
    """Device bytes one segment's kernel will touch: shipped column arrays
    plus the group-table output (the two allocations that scale)."""
    total = 0
    names = needed_columns if needed_columns is not None else segment.column_names
    for name in names:
        if name not in segment.columns:
            continue
        c = segment.columns[name]
        arr = c.codes if c.codes is not None else c.values
        if arr is not None:
            total += arr.nbytes
        if c.nulls is not None:
            total += c.nulls.nbytes // 8
    if ctx.group_by:
        total += int(ctx.num_groups_limit) * 16 * max(1, len(ctx.aggregations))
    return total


class WorkloadScheduler:
    """Two-tier workload isolation (BinaryWorkloadScheduler analog,
    pinot-core/.../core/query/scheduler/BinaryWorkloadScheduler.java).

    PRIMARY (interactive) queries are never queued.  SECONDARY queries —
    marked with the `isSecondaryWorkload` query option, the reference's
    contract for misbehaving/batch traffic — compete for a small semaphore
    and wait at most their remaining deadline (default 1s) for a slot, so
    a batch scan burst cannot starve interactive latency."""

    def __init__(self, secondary_slots: int = 2):
        self.secondary_slots = secondary_slots
        self._sem = threading.BoundedSemaphore(secondary_slots)

    @staticmethod
    def is_secondary(ctx: QueryContext) -> bool:
        v = ctx.options.get("isSecondaryWorkload")
        return str(v).lower() in ("1", "true", "yes") if v is not None else False

    def acquire(self, ctx: QueryContext, deadline: Optional["Deadline"] = None):
        """Returns a release callable (no-op for primary workloads)."""
        if not self.is_secondary(ctx):
            return lambda: None
        wait_s = 1.0
        if deadline is not None and deadline.expires_at is not None:
            wait_s = max(0.0, deadline.expires_at - time.perf_counter())
        if not self._sem.acquire(timeout=wait_s):
            raise AdmissionError(
                f"secondary workload queue full ({self.secondary_slots} slots); "
                "retry later or run without isSecondaryWorkload"
            )
        return self._sem.release


class MemoryAccountant:
    """Process-wide device-memory admission (budget in bytes).

    acquire() admits a query's estimate or raises AdmissionError — queries
    never start work they can't finish (the reference instead kills the
    largest query under heap pressure; with static shapes we can refuse
    up front)."""

    def __init__(self, budget_bytes: int = 8 << 30):
        self.budget = budget_bytes
        self.in_use = 0
        self._lock = threading.Lock()
        self._by_query: Dict[int, int] = {}
        self._next_id = 0

    def acquire(self, nbytes: int, what: str = "query") -> int:
        with self._lock:
            if self.in_use + nbytes > self.budget:
                raise AdmissionError(
                    f"{what} needs ~{nbytes / 1e6:.1f} MB device memory; "
                    f"{(self.budget - self.in_use) / 1e6:.1f} MB of {self.budget / 1e6:.1f} MB available "
                    "(raise the accountant budget or lower numGroupsLimit/query width)"
                )
            self._next_id += 1
            qid = self._next_id
            self._by_query[qid] = nbytes
            self.in_use += nbytes
            return qid

    def release(self, qid: int) -> None:
        with self._lock:
            n = self._by_query.pop(qid, 0)
            self.in_use -= n
