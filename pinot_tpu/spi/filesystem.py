"""PinotFS: deep-store filesystem abstraction + durable-write discipline.

Reference parity: pinot-spi/.../spi/filesystem/PinotFS.java and the
pinot-file-system plugins (local/S3/GCS/ADLS/HDFS).  Local is first-party;
cloud schemes register via register_fs (out-of-image here: zero egress),
so an s3:// URI fails with a pointed message instead of a stack trace.

This module also owns the repo's single durable-write idiom (tmp write ->
flush -> fsync -> os.replace -> directory fsync), used by the coordinator
journal, realtime checkpoints, and segment metadata so a crash at ANY point
leaves either the old committed state or the new one — never a torn file.
repo_lint W016 flags durability-path writes that bypass these helpers.
"""
from __future__ import annotations

import json
import os
import shutil
from typing import Any, Callable, Dict, List
from urllib.parse import urlparse

from pinot_tpu.utils.crashpoints import crash_point


def fsync_dir(path: str) -> None:
    """fsync the directory entry so a rename survives power loss (best
    effort: some platforms/filesystems refuse O_RDONLY dir fds)."""
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


def durable_write_bytes(path: str, data: bytes, crash_prefix: str = "durable_write") -> None:
    """Atomically replace `path` with `data`: tmp + fsync + os.replace.

    `crash_prefix` names the kill-points a FaultPlan can arm between the
    steps ({prefix}.after_write before the fsync+rename commit,
    {prefix}.after_replace before the directory fsync)."""
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        f.write(data)
        crash_point(f"{crash_prefix}.after_write")
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    crash_point(f"{crash_prefix}.after_replace")
    fsync_dir(os.path.dirname(path) or ".")


def durable_write_json(path: str, obj: Any, crash_prefix: str = "durable_write", **dump_kw) -> None:
    durable_write_bytes(
        path, json.dumps(obj, **dump_kw).encode("utf-8"), crash_prefix=crash_prefix
    )


def sweep_tmp(dir_path: str) -> List[str]:
    """Remove stale `*.tmp` files a crash left behind (a tmp file is by
    definition uncommitted — deleting it is always safe).  Returns what was
    swept, for logs/metrics."""
    swept: List[str] = []
    if not os.path.isdir(dir_path):
        return swept
    for name in sorted(os.listdir(dir_path)):
        if name.endswith(".tmp"):
            p = os.path.join(dir_path, name)
            if os.path.isfile(p):
                try:
                    os.remove(p)
                    swept.append(p)
                except OSError:
                    pass
    return swept


class PinotFS:
    """Filesystem contract (mkdir/delete/move/copy/exists/length/listFiles/
    copyToLocal/copyFromLocal), operating on scheme-less paths."""

    def mkdir(self, path: str) -> None:
        raise NotImplementedError

    def delete(self, path: str, force: bool = False) -> bool:
        raise NotImplementedError

    def move(self, src: str, dst: str) -> bool:
        raise NotImplementedError

    def copy(self, src: str, dst: str) -> bool:
        raise NotImplementedError

    def exists(self, path: str) -> bool:
        raise NotImplementedError

    def length(self, path: str) -> int:
        raise NotImplementedError

    def list_files(self, path: str, recursive: bool = False) -> List[str]:
        raise NotImplementedError

    def copy_to_local(self, src: str, dst: str) -> None:
        self.copy(src, dst)

    def copy_from_local(self, src: str, dst: str) -> None:
        self.copy(src, dst)


class LocalPinotFS(PinotFS):
    def mkdir(self, path: str) -> None:
        os.makedirs(path, exist_ok=True)

    def delete(self, path: str, force: bool = False) -> bool:
        if os.path.isdir(path):
            if os.listdir(path) and not force:
                return False
            shutil.rmtree(path)
            return True
        if os.path.exists(path):
            os.remove(path)
            return True
        return False

    def move(self, src: str, dst: str) -> bool:
        os.makedirs(os.path.dirname(dst) or ".", exist_ok=True)
        shutil.move(src, dst)
        return True

    def copy(self, src: str, dst: str) -> bool:
        os.makedirs(os.path.dirname(dst) or ".", exist_ok=True)
        if os.path.isdir(src):
            shutil.copytree(src, dst, dirs_exist_ok=True)
        else:
            shutil.copy2(src, dst)
        return True

    def exists(self, path: str) -> bool:
        return os.path.exists(path)

    def length(self, path: str) -> int:
        return os.path.getsize(path)

    def list_files(self, path: str, recursive: bool = False) -> List[str]:
        if not recursive:
            return sorted(os.path.join(path, f) for f in os.listdir(path))
        out = []
        for root, _, files in os.walk(path):
            out.extend(os.path.join(root, f) for f in files)
        return sorted(out)


_FS_REGISTRY: Dict[str, Callable[[], PinotFS]] = {
    "": lambda: LocalPinotFS(),
    "file": lambda: LocalPinotFS(),
}


def register_fs(scheme: str, factory: Callable[[], PinotFS]) -> None:
    _FS_REGISTRY[scheme] = factory


def fs_for_uri(uri: str) -> PinotFS:
    scheme = urlparse(uri).scheme
    factory = _FS_REGISTRY.get(scheme)
    if factory is None:
        raise ValueError(
            f"no PinotFS registered for scheme {scheme!r} (register via "
            "pinot_tpu.spi.filesystem.register_fs; cloud plugins are not bundled)"
        )
    return factory()


def strip_scheme(uri: str) -> str:
    p = urlparse(uri)
    return p.path if p.scheme else uri
