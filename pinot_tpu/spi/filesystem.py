"""PinotFS: deep-store filesystem abstraction + durable-write discipline.

Reference parity: pinot-spi/.../spi/filesystem/PinotFS.java and the
pinot-file-system plugins (local/S3/GCS/ADLS/HDFS).  Local is first-party;
cloud schemes register via register_fs (out-of-image here: zero egress),
so an s3:// URI fails with a pointed message instead of a stack trace.

This module also owns the repo's single durable-write idiom (tmp write ->
flush -> fsync -> os.replace -> directory fsync), used by the coordinator
journal, realtime checkpoints, and segment metadata so a crash at ANY point
leaves either the old committed state or the new one — never a torn file.
repo_lint W016 flags durability-path writes that bypass these helpers.
"""
from __future__ import annotations

import json
import os
import shutil
from typing import Any, Callable, Dict, List, Optional, Tuple
from urllib.parse import urlparse

from pinot_tpu.utils.crashpoints import crash_point


def fsync_dir(path: str) -> None:
    """fsync the directory entry so a rename survives power loss (best
    effort: some platforms/filesystems refuse O_RDONLY dir fds)."""
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


def durable_write_bytes(path: str, data: bytes, crash_prefix: str = "durable_write") -> None:
    """Atomically replace `path` with `data`: tmp + fsync + os.replace.

    `crash_prefix` names the kill-points a FaultPlan can arm between the
    steps ({prefix}.after_write before the fsync+rename commit,
    {prefix}.after_replace before the directory fsync)."""
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        f.write(data)
        crash_point(f"{crash_prefix}.after_write")
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    crash_point(f"{crash_prefix}.after_replace")
    fsync_dir(os.path.dirname(path) or ".")


def durable_write_json(path: str, obj: Any, crash_prefix: str = "durable_write", **dump_kw) -> None:
    durable_write_bytes(
        path, json.dumps(obj, **dump_kw).encode("utf-8"), crash_prefix=crash_prefix
    )


def sweep_tmp(dir_path: str) -> List[str]:
    """Remove stale `*.tmp` files a crash left behind (a tmp file is by
    definition uncommitted — deleting it is always safe).  Returns what was
    swept, for logs/metrics."""
    swept: List[str] = []
    if not os.path.isdir(dir_path):
        return swept
    for name in sorted(os.listdir(dir_path)):
        if name.endswith(".tmp"):
            p = os.path.join(dir_path, name)
            if os.path.isfile(p):
                try:
                    os.remove(p)
                    swept.append(p)
                except OSError:
                    pass
    return swept


class TailFollower:
    """Incremental line-tail over an append-only file: byte-offset memo +
    torn-tail park.

    The shared core of two long-running consume loops — FileStream.fetch
    (realtime/stream.py: JSONL ingest tail) and the standby coordinator's
    journal follower (cluster/election.py) — that previously each carried
    their own copy of the same discipline:

      * a byte-offset memo maps "line index N" to its byte position, so a
        steady-state tail seeks straight to where it left off instead of
        re-reading the whole file every poll (O(total) per batch makes
        long-running tails quadratic);
      * a final line with no trailing newline is a TORN TAIL — a writer
        crashed (or is) mid-append.  It is never surfaced: the memo parks
        BEFORE the partial bytes so the next poll re-reads the completed
        line once the writer finishes (or a recovery truncates it);
      * a file that shrank below the memo (truncated / rewritten — e.g. a
        journal compaction) is reported as `truncated=True` so the caller
        can resynchronize from its snapshot; the scan restarts from 0.

    State is (line, pos) only; the file is opened per read() call, so the
    follower never holds a descriptor across polls (the writer may rename
    the file underneath — the next read simply reopens)."""

    def __init__(self, path: str):
        self.path = path
        self._line = 0  # line index the memo points at
        self._pos = 0  # byte offset where that line starts

    @property
    def position(self) -> Tuple[int, int]:
        """(line index, byte offset) of the next unread line."""
        return self._line, self._pos

    def reset(self) -> None:
        self._line = 0
        self._pos = 0

    def read(
        self,
        start_line: Optional[int] = None,
        max_lines: Optional[int] = None,
        count_line: Optional[Callable[[str], bool]] = None,
    ) -> Tuple[List[Tuple[int, str]], int, bool, bool]:
        """Read complete lines from `start_line` (default: the memo).

        Returns (lines, next_line, eof, truncated) where `lines` is a list
        of (1-based end line index, decoded text without the newline) —
        blank lines are included (they consume a line index), `next_line`
        is the index after the last consumed line, `eof` is True when the
        scan reached the (possibly torn) end of file, and `truncated`
        flags a file that shrank below the memo since the last read.

        `max_lines` bounds how many lines COUNT — by default every line;
        `count_line(text) -> bool` lets a caller bound only meaningful
        lines (FileStream bounds messages, not blanks)."""
        start = self._line if start_line is None else start_line
        if not os.path.exists(self.path):
            return [], start, True, False
        out: List[Tuple[int, str]] = []
        counted = 0
        truncated = False
        with open(self.path, "rb") as f:
            if start == self._line and self._pos > 0:
                # the memo only short-circuits an append-only file: if it
                # shrank (truncate/rewrite/compaction), reset the memo and
                # report — surfacing lines here would let the old line
                # index skip past the rewritten file's fresh content.  The
                # caller resynchronizes (snapshot re-read) and reads again
                # from the top.
                if os.fstat(f.fileno()).st_size >= self._pos:
                    f.seek(self._pos)
                    i = self._line
                else:
                    self._line, self._pos = 0, 0
                    return [], 0, False, True
            else:
                i = 0
            if i == 0 and start != 0:
                # skip to start the slow way (cold start / replay / rescan
                # of a rewritten file)
                while i < start:
                    if not f.readline():
                        break
                    i += 1
            next_line = i
            for raw in iter(f.readline, b""):
                if not raw.endswith(b"\n"):
                    # torn tail: park the memo BEFORE the partial bytes so
                    # the next read re-reads the completed line
                    self._line, self._pos = i, f.tell() - len(raw)
                    return out, next_line, True, truncated
                text = raw[:-1].decode("utf-8")
                if count_line is None or count_line(text):
                    if max_lines is not None and counted >= max_lines:
                        self._line, self._pos = i, f.tell() - len(raw)
                        return out, next_line, False, truncated
                    counted += 1
                i += 1
                next_line = i
                out.append((i, text))
            self._line, self._pos = i, f.tell()
        return out, next_line, True, truncated

    def torn_tail_offset(self) -> Optional[int]:
        """Byte offset of a torn (newline-less) final line, or None when the
        file ends cleanly — the truncation point a recovery path may cut
        back to (the torn bytes never committed: their fsync didn't
        return)."""
        if not os.path.exists(self.path):
            return None
        with open(self.path, "rb") as f:
            pos = 0
            for raw in iter(f.readline, b""):
                if not raw.endswith(b"\n"):
                    return pos
                pos = f.tell()
        return None


class PinotFS:
    """Filesystem contract (mkdir/delete/move/copy/exists/length/listFiles/
    copyToLocal/copyFromLocal), operating on scheme-less paths."""

    def mkdir(self, path: str) -> None:
        raise NotImplementedError

    def delete(self, path: str, force: bool = False) -> bool:
        raise NotImplementedError

    def move(self, src: str, dst: str) -> bool:
        raise NotImplementedError

    def copy(self, src: str, dst: str) -> bool:
        raise NotImplementedError

    def exists(self, path: str) -> bool:
        raise NotImplementedError

    def length(self, path: str) -> int:
        raise NotImplementedError

    def list_files(self, path: str, recursive: bool = False) -> List[str]:
        raise NotImplementedError

    def copy_to_local(self, src: str, dst: str) -> None:
        self.copy(src, dst)

    def copy_from_local(self, src: str, dst: str) -> None:
        self.copy(src, dst)


class LocalPinotFS(PinotFS):
    def mkdir(self, path: str) -> None:
        os.makedirs(path, exist_ok=True)

    def delete(self, path: str, force: bool = False) -> bool:
        if os.path.isdir(path):
            if os.listdir(path) and not force:
                return False
            shutil.rmtree(path)
            return True
        if os.path.exists(path):
            os.remove(path)
            return True
        return False

    def move(self, src: str, dst: str) -> bool:
        os.makedirs(os.path.dirname(dst) or ".", exist_ok=True)
        shutil.move(src, dst)
        return True

    def copy(self, src: str, dst: str) -> bool:
        os.makedirs(os.path.dirname(dst) or ".", exist_ok=True)
        if os.path.isdir(src):
            shutil.copytree(src, dst, dirs_exist_ok=True)
        else:
            shutil.copy2(src, dst)
        return True

    def exists(self, path: str) -> bool:
        return os.path.exists(path)

    def length(self, path: str) -> int:
        return os.path.getsize(path)

    def list_files(self, path: str, recursive: bool = False) -> List[str]:
        if not recursive:
            return sorted(os.path.join(path, f) for f in os.listdir(path))
        out = []
        for root, _, files in os.walk(path):
            out.extend(os.path.join(root, f) for f in files)
        return sorted(out)


_FS_REGISTRY: Dict[str, Callable[[], PinotFS]] = {
    "": lambda: LocalPinotFS(),
    "file": lambda: LocalPinotFS(),
}


def register_fs(scheme: str, factory: Callable[[], PinotFS]) -> None:
    _FS_REGISTRY[scheme] = factory


def fs_for_uri(uri: str) -> PinotFS:
    scheme = urlparse(uri).scheme
    factory = _FS_REGISTRY.get(scheme)
    if factory is None:
        raise ValueError(
            f"no PinotFS registered for scheme {scheme!r} (register via "
            "pinot_tpu.spi.filesystem.register_fs; cloud plugins are not bundled)"
        )
    return factory()


def strip_scheme(uri: str) -> str:
    p = urlparse(uri)
    return p.path if p.scheme else uri
