"""Environment-layered configuration (SURVEY.md §5.6).

Reference parity: Pinot's config layering (properties files overridden by
env/system properties — PinotConfiguration's precedence chain).  Here the
layers, weakest first, are:

  1. engine defaults (QueryContext option defaults)
  2. process environment: PINOT_TPU_OPT_<optionName>=<value>
  3. per-query `OPTION(...)` / `SET k = v;` in the SQL text

so e.g. `PINOT_TPU_OPT_numGroupsLimit=50000` caps every query in the
process unless the query sets its own value.  Values parse as JSON when
possible (numbers/bools), else stay strings.
"""
from __future__ import annotations

import json
import os
from typing import Any, Dict

_PREFIX = "PINOT_TPU_OPT_"


def env_options(environ: Dict[str, str] = None) -> Dict[str, Any]:
    env = os.environ if environ is None else environ
    out: Dict[str, Any] = {}
    for k, v in env.items():
        if not k.startswith(_PREFIX):
            continue
        name = k[len(_PREFIX) :]
        try:
            out[name] = json.loads(v)
        except (json.JSONDecodeError, ValueError):
            out[name] = v
    return out


def apply_env_defaults(options: Dict[str, Any], environ: Dict[str, str] = None) -> None:
    """Overlay env-provided option defaults UNDER the query's own options."""
    for k, v in env_options(environ).items():
        options.setdefault(k, v)
