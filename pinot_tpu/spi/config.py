"""Table configuration — per-table knobs (TableConfig analog).

Reference parity: pinot-spi/.../spi/config/table/TableConfig.java:45 (table
name/type, indexing config, segment config, routing, upsert, stream configs).
JSON shape kept close to Pinot's tableConfig JSON for migration.
"""
from __future__ import annotations

import enum
import json
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional


class TableType(enum.Enum):
    OFFLINE = "OFFLINE"
    REALTIME = "REALTIME"


@dataclass
class IndexingConfig:
    """Per-table index declarations (IndexingConfig analog).

    Column lists select which index each column gets; the segment builder
    (segment/builder.py) materializes them, the planner (query/planner.py)
    exploits them — mirroring StandardIndexes (pinot-segment-spi
    StandardIndexes.java:73-157)."""

    inverted_index_columns: List[str] = field(default_factory=list)
    range_index_columns: List[str] = field(default_factory=list)
    sorted_column: Optional[str] = None
    bloom_filter_columns: List[str] = field(default_factory=list)
    json_index_columns: List[str] = field(default_factory=list)
    text_index_columns: List[str] = field(default_factory=list)
    vector_index_columns: List[str] = field(default_factory=list)
    # Columns stored raw (no dictionary); metrics default to raw anyway.
    no_dictionary_columns: List[str] = field(default_factory=list)
    # Star-tree index configs (list of dicts: dimensionsSplitOrder,
    # functionColumnPairs, maxLeafRecords) — see indexes/startree.py.
    star_tree_index_configs: List[Dict[str, Any]] = field(default_factory=list)

    def to_dict(self) -> Dict[str, Any]:
        return {
            "invertedIndexColumns": self.inverted_index_columns,
            "rangeIndexColumns": self.range_index_columns,
            "sortedColumn": self.sorted_column,
            "bloomFilterColumns": self.bloom_filter_columns,
            "jsonIndexColumns": self.json_index_columns,
            "textIndexColumns": self.text_index_columns,
            "vectorIndexColumns": self.vector_index_columns,
            "noDictionaryColumns": self.no_dictionary_columns,
            "starTreeIndexConfigs": self.star_tree_index_configs,
        }

    @staticmethod
    def from_dict(d: Dict[str, Any]) -> "IndexingConfig":
        return IndexingConfig(
            inverted_index_columns=d.get("invertedIndexColumns", []),
            range_index_columns=d.get("rangeIndexColumns", []),
            sorted_column=d.get("sortedColumn"),
            bloom_filter_columns=d.get("bloomFilterColumns", []),
            json_index_columns=d.get("jsonIndexColumns", []),
            text_index_columns=d.get("textIndexColumns", []),
            vector_index_columns=d.get("vectorIndexColumns", []),
            no_dictionary_columns=d.get("noDictionaryColumns", []),
            star_tree_index_configs=d.get("starTreeIndexConfigs", []),
        )


@dataclass
class SegmentsConfig:
    """Segment lifecycle config (SegmentsValidationAndRetentionConfig analog):
    time column for retention/time-pruning, retention, replication, and the
    target rows per segment used by builders and realtime sealing."""

    time_column: Optional[str] = None
    retention_time_value: Optional[int] = None
    retention_time_unit: str = "DAYS"
    replication: int = 1
    target_rows_per_segment: int = 1 << 20

    def to_dict(self) -> Dict[str, Any]:
        return {
            "timeColumnName": self.time_column,
            "retentionTimeValue": self.retention_time_value,
            "retentionTimeUnit": self.retention_time_unit,
            "replication": self.replication,
            "targetRowsPerSegment": self.target_rows_per_segment,
        }

    @staticmethod
    def from_dict(d: Dict[str, Any]) -> "SegmentsConfig":
        return SegmentsConfig(
            time_column=d.get("timeColumnName"),
            retention_time_value=d.get("retentionTimeValue"),
            retention_time_unit=d.get("retentionTimeUnit", "DAYS"),
            replication=int(d.get("replication", 1)),
            target_rows_per_segment=int(d.get("targetRowsPerSegment", 1 << 20)),
        )


@dataclass
class UpsertConfig:
    """Upsert mode (pinot-spi UpsertConfig analog): FULL replaces whole rows by
    primary key, PARTIAL merges per-column strategies; comparison column picks
    the winner (latest)."""

    mode: str = "NONE"  # NONE | FULL | PARTIAL
    comparison_column: Optional[str] = None
    partial_upsert_strategies: Dict[str, str] = field(default_factory=dict)
    # metadataTTL (ConcurrentMapPartitionUpsertMetadataManager.java:49):
    # primary keys whose comparison value falls more than this many
    # comparison-units behind the largest seen stop being tracked; 0 = off
    metadata_ttl: float = 0.0
    # deleteRecordColumn: rows with a truthy value here are consistent
    # DELETES — the PK's rows disappear from queries, and the tombstone
    # rejects older out-of-order arrivals until TTL expiry
    delete_record_column: Optional[str] = None

    def to_dict(self) -> Dict[str, Any]:
        return {
            "mode": self.mode,
            "comparisonColumn": self.comparison_column,
            "partialUpsertStrategies": self.partial_upsert_strategies,
            "metadataTTL": self.metadata_ttl,
            "deleteRecordColumn": self.delete_record_column,
        }

    @staticmethod
    def from_dict(d: Dict[str, Any]) -> "UpsertConfig":
        return UpsertConfig(
            mode=d.get("mode", "NONE"),
            comparison_column=d.get("comparisonColumn"),
            partial_upsert_strategies=d.get("partialUpsertStrategies", {}),
            metadata_ttl=float(d.get("metadataTTL", 0.0) or 0.0),
            delete_record_column=d.get("deleteRecordColumn"),
        )


@dataclass
class DedupConfig:
    """Exact-duplicate dropping by primary key at ingest time (pinot-spi
    DedupConfig analog): the FIRST row per PK wins; later rows are dropped
    before indexing."""

    enabled: bool = True

    def to_dict(self) -> Dict[str, Any]:
        return {"dedupEnabled": self.enabled}

    @staticmethod
    def from_dict(d: Dict[str, Any]) -> "DedupConfig":
        return DedupConfig(enabled=bool(d.get("dedupEnabled", True)))


@dataclass
class StreamConfig:
    """Realtime stream binding (pinot-spi stream SPI analog): consumer factory
    name + free-form properties (topic, decoder, end-criteria)."""

    stream_type: str = "memory"  # memory | kafka | file
    topic: str = ""
    decoder: str = "json"
    properties: Dict[str, Any] = field(default_factory=dict)
    # Segment end-criteria (RealtimeSegmentDataManager end-of-segment checks)
    max_rows_per_segment: int = 1 << 20
    max_segment_seconds: int = 6 * 3600

    def to_dict(self) -> Dict[str, Any]:
        return {
            "streamType": self.stream_type,
            "topic": self.topic,
            "decoder": self.decoder,
            "properties": self.properties,
            "maxRowsPerSegment": self.max_rows_per_segment,
            "maxSegmentSeconds": self.max_segment_seconds,
        }

    @staticmethod
    def from_dict(d: Dict[str, Any]) -> "StreamConfig":
        return StreamConfig(
            stream_type=d.get("streamType", "memory"),
            topic=d.get("topic", ""),
            decoder=d.get("decoder", "json"),
            properties=d.get("properties", {}),
            max_rows_per_segment=int(d.get("maxRowsPerSegment", 1 << 20)),
            max_segment_seconds=int(d.get("maxSegmentSeconds", 6 * 3600)),
        )


@dataclass
class TableConfig:
    name: str
    table_type: TableType = TableType.OFFLINE
    indexing: IndexingConfig = field(default_factory=IndexingConfig)
    segments: SegmentsConfig = field(default_factory=SegmentsConfig)
    upsert: Optional[UpsertConfig] = None
    dedup: Optional[DedupConfig] = None
    stream: Optional[StreamConfig] = None
    # Partitioning for partition-pinned parallelism (SURVEY.md 2.5):
    # column name -> number of partitions.
    partition_column: Optional[str] = None
    num_partitions: int = 0
    tenant: str = "default"
    # Per-table query rate limit (QuotaConfig.maxQueriesPerSecond,
    # enforced at the broker: HelixExternalViewBasedQueryQuotaManager
    # analog); 0 = unlimited
    max_queries_per_second: float = 0.0

    @property
    def table_name_with_type(self) -> str:
        return f"{self.name}_{self.table_type.value}"

    def to_dict(self) -> Dict[str, Any]:
        d: Dict[str, Any] = {
            "tableName": self.name,
            "tableType": self.table_type.value,
            "tableIndexConfig": self.indexing.to_dict(),
            "segmentsConfig": self.segments.to_dict(),
            "tenant": self.tenant,
        }
        if self.upsert:
            d["upsertConfig"] = self.upsert.to_dict()
        if self.dedup:
            d["dedupConfig"] = self.dedup.to_dict()
        if self.stream:
            d["streamConfigs"] = self.stream.to_dict()
        if self.partition_column:
            d["partitionColumn"] = self.partition_column
            d["numPartitions"] = self.num_partitions
        if self.max_queries_per_second:
            d["quota"] = {"maxQueriesPerSecond": self.max_queries_per_second}
        return d

    @staticmethod
    def from_dict(d: Dict[str, Any]) -> "TableConfig":
        return TableConfig(
            name=d["tableName"],
            table_type=TableType(d.get("tableType", "OFFLINE")),
            indexing=IndexingConfig.from_dict(d.get("tableIndexConfig", {})),
            segments=SegmentsConfig.from_dict(d.get("segmentsConfig", {})),
            upsert=UpsertConfig.from_dict(d["upsertConfig"]) if d.get("upsertConfig") else None,
            dedup=DedupConfig.from_dict(d["dedupConfig"]) if d.get("dedupConfig") else None,
            stream=StreamConfig.from_dict(d["streamConfigs"]) if d.get("streamConfigs") else None,
            partition_column=d.get("partitionColumn"),
            num_partitions=int(d.get("numPartitions", 0)),
            tenant=d.get("tenant", "default"),
            max_queries_per_second=float(
                (d.get("quota") or {}).get("maxQueriesPerSecond", 0.0) or 0.0
            ),
        )

    def to_json(self) -> str:
        return json.dumps(self.to_dict(), indent=2)

    @staticmethod
    def from_json(s: str) -> "TableConfig":
        return TableConfig.from_dict(json.loads(s))
