"""Schema / field specs — the L0 data-model contract.

Reference parity: pinot-spi/.../spi/data/Schema.java:69 and FieldSpec.java (the
DIMENSION/METRIC/DATE_TIME field roles, data types, single/multi-value flags,
nullability and default null values).  Re-designed: types map directly onto
numpy/JAX dtypes so a schema doubles as the dtype spec of the device pytree.
"""
from __future__ import annotations

import enum
import json
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

import numpy as np


class DataType(enum.Enum):
    """Column storage types (FieldSpec.DataType analog).

    Device representation notes:
      INT/LONG      -> int32/int64 arrays (or dict codes if dict-encoded)
      FLOAT/DOUBLE  -> float32/float64
      BOOLEAN       -> uint8 (0/1)
      TIMESTAMP     -> int64 epoch millis
      STRING/BYTES  -> always dictionary-encoded; device sees int codes only,
                       the value dictionary stays host-side (SURVEY.md section 7
                       "Strings/bytes on device").
      JSON          -> stored as STRING; JSON index provides JSON_MATCH.
    """

    INT = "INT"
    LONG = "LONG"
    FLOAT = "FLOAT"
    DOUBLE = "DOUBLE"
    BOOLEAN = "BOOLEAN"
    TIMESTAMP = "TIMESTAMP"
    STRING = "STRING"
    BYTES = "BYTES"
    JSON = "JSON"

    @property
    def is_numeric(self) -> bool:
        return self in _NUMERIC

    @property
    def is_string_like(self) -> bool:
        return self in (DataType.STRING, DataType.BYTES, DataType.JSON)

    @property
    def np_dtype(self) -> np.dtype:
        """Numpy dtype of raw (non-dict) storage for this type."""
        return _NP_DTYPES[self]

    @property
    def null_placeholder(self) -> Any:
        """Default value substituted for nulls in the forward index
        (Pinot's FieldSpec default-null-value semantics)."""
        return _NULL_PLACEHOLDER[self]


_NUMERIC = frozenset(
    {DataType.INT, DataType.LONG, DataType.FLOAT, DataType.DOUBLE, DataType.TIMESTAMP, DataType.BOOLEAN}
)

_NP_DTYPES = {
    DataType.INT: np.dtype(np.int32),
    DataType.LONG: np.dtype(np.int64),
    DataType.FLOAT: np.dtype(np.float32),
    DataType.DOUBLE: np.dtype(np.float64),
    DataType.BOOLEAN: np.dtype(np.uint8),
    DataType.TIMESTAMP: np.dtype(np.int64),
    DataType.STRING: np.dtype(object),
    DataType.BYTES: np.dtype(object),
    DataType.JSON: np.dtype(object),
}

_NULL_PLACEHOLDER = {
    DataType.INT: np.int32(np.iinfo(np.int32).min),
    DataType.LONG: np.int64(np.iinfo(np.int64).min),
    DataType.FLOAT: np.float32("-inf"),
    DataType.DOUBLE: np.float64("-inf"),
    DataType.BOOLEAN: np.uint8(0),
    DataType.TIMESTAMP: np.int64(0),
    DataType.STRING: "null",
    DataType.BYTES: b"",
    DataType.JSON: "null",
}


class FieldRole(enum.Enum):
    """Field category (FieldSpec.FieldType analog): dimensions are
    dictionary-encoded by default and filterable/groupable; metrics default to
    raw storage and are aggregated; DATE_TIME carries time semantics used for
    retention and time pruning."""

    DIMENSION = "DIMENSION"
    METRIC = "METRIC"
    DATE_TIME = "DATE_TIME"


@dataclass
class FieldSpec:
    """One column's declaration (pinot-spi FieldSpec analog)."""

    name: str
    data_type: DataType
    role: FieldRole = FieldRole.DIMENSION
    single_value: bool = True
    nullable: bool = False
    # DATE_TIME only: format/granularity strings, kept for config parity.
    datetime_format: Optional[str] = None
    datetime_granularity: Optional[str] = None

    def to_dict(self) -> Dict[str, Any]:
        d: Dict[str, Any] = {
            "name": self.name,
            "dataType": self.data_type.value,
            "role": self.role.value,
            "singleValue": self.single_value,
            "nullable": self.nullable,
        }
        if self.datetime_format:
            d["format"] = self.datetime_format
        if self.datetime_granularity:
            d["granularity"] = self.datetime_granularity
        return d

    @staticmethod
    def from_dict(d: Dict[str, Any]) -> "FieldSpec":
        return FieldSpec(
            name=d["name"],
            data_type=DataType(d["dataType"]),
            role=FieldRole(d.get("role", "DIMENSION")),
            single_value=d.get("singleValue", True),
            nullable=d.get("nullable", False),
            datetime_format=d.get("format"),
            datetime_granularity=d.get("granularity"),
        )


@dataclass
class Schema:
    """Table schema: ordered field specs + helpers (Schema.java analog).

    JSON shape intentionally close to Pinot's schema JSON
    (dimensionFieldSpecs/metricFieldSpecs/dateTimeFieldSpecs) so users of the
    reference can migrate configs mechanically."""

    name: str
    fields: List[FieldSpec] = field(default_factory=list)
    primary_key_columns: List[str] = field(default_factory=list)

    def __post_init__(self) -> None:
        self._by_name: Dict[str, FieldSpec] = {f.name: f for f in self.fields}
        if len(self._by_name) != len(self.fields):
            raise ValueError(f"duplicate column names in schema {self.name}")

    # -- lookups ---------------------------------------------------------
    def field(self, name: str) -> FieldSpec:
        try:
            return self._by_name[name]
        except KeyError:
            raise KeyError(f"column '{name}' not in schema '{self.name}' (has {list(self._by_name)})") from None

    def __contains__(self, name: str) -> bool:
        return name in self._by_name

    @property
    def column_names(self) -> List[str]:
        return [f.name for f in self.fields]

    @property
    def dimension_columns(self) -> List[str]:
        return [f.name for f in self.fields if f.role is FieldRole.DIMENSION]

    @property
    def metric_columns(self) -> List[str]:
        return [f.name for f in self.fields if f.role is FieldRole.METRIC]

    @property
    def datetime_columns(self) -> List[str]:
        return [f.name for f in self.fields if f.role is FieldRole.DATE_TIME]

    # -- serde -----------------------------------------------------------
    def to_dict(self) -> Dict[str, Any]:
        d: Dict[str, Any] = {
            "schemaName": self.name,
            "dimensionFieldSpecs": [f.to_dict() for f in self.fields if f.role is FieldRole.DIMENSION],
            "metricFieldSpecs": [f.to_dict() for f in self.fields if f.role is FieldRole.METRIC],
            "dateTimeFieldSpecs": [f.to_dict() for f in self.fields if f.role is FieldRole.DATE_TIME],
        }
        if self.primary_key_columns:
            d["primaryKeyColumns"] = list(self.primary_key_columns)
        d["fieldOrder"] = self.column_names
        return d

    @staticmethod
    def from_dict(d: Dict[str, Any]) -> "Schema":
        fields: List[FieldSpec] = []
        for key, role in (
            ("dimensionFieldSpecs", FieldRole.DIMENSION),
            ("metricFieldSpecs", FieldRole.METRIC),
            ("dateTimeFieldSpecs", FieldRole.DATE_TIME),
        ):
            for fd in d.get(key, []):
                fd = dict(fd)
                fd.setdefault("role", role.value)
                fields.append(FieldSpec.from_dict(fd))
        order = d.get("fieldOrder")
        if order:
            pos = {n: i for i, n in enumerate(order)}
            fields.sort(key=lambda f: pos.get(f.name, len(pos)))
        return Schema(
            name=d["schemaName"],
            fields=fields,
            primary_key_columns=list(d.get("primaryKeyColumns", [])),
        )

    def to_json(self) -> str:
        return json.dumps(self.to_dict(), indent=2)

    @staticmethod
    def from_json(s: str) -> "Schema":
        return Schema.from_dict(json.loads(s))
