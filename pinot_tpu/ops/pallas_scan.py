"""Pallas fused filter→group-by scan: one pass over HBM per macro-batch.

The XLA path (ops/segmented.py) streams each macro-batch several times —
bitmap words unpack into a row-length bool mask, limbs stack into an [n, L]
matrix (or re-slice per chunk), and the one-hot matmuls read it all back.
Measured ceiling ~11 Grows/s with ~27 GB/s of HBM touched per effective
pass (VERDICT r5: 2.09e9 rows/s end-to-end on config 2, ~3% of a v5e's
~819 GB/s).  This module fuses the whole row pipeline into ONE Pallas grid
over row tiles, so each input byte is read exactly once:

  tile load:   dict codes in STORAGE dtype (int8 stays int8 in HBM),
               range-index prefix-bitmap WORDS ([T/32] uint32, unpacked
               in-register), optional predicate codes
  tile math:   dictionary-code range predicate, 8-bit-limb extraction
               (two's-complement int32 / signed-magnitude int64 halves),
               two-level one-hot (A, B) pair shared by every limb, one
               [Hp, W] MXU matmul per limb column
  tile store:  int32 accumulation into a VMEM-resident [L, Hp, W] block,
               revisited across the tiles of one "super-segment"

Exactness contract (matches segmented.fused_group_tables bit-for-bit on
integer kinds): every limb is < 256 so each per-tile f32 dot accumulates
< 255 * _TILE < 2^24 (exact); tiles add into int32 where one super-segment
covers <= 2^23 rows so |sum| <= 255 * 2^23 < 2^31 (exact); the per-super
int32 tables recombine OUTSIDE the kernel in f64 with the limb scales —
TPU Pallas has no f64, and the recombine is table-sized anyway.  Float
kinds (f32_sum/f32_sumsq) are NOT eligible: f32 accumulation over 2^23-row
supers would lose vs the XLA path's per-chunk f64 combine, so the plan-time
dispatch keeps floats on the XLA path (pallas_supported).

Backend selection is a PLAN-TIME decision (scan_backend): "pallas" on TPU,
"xla" elsewhere, overridable with PINOT_TPU_SCAN_BACKEND=pallas|xla|
interpret — "interpret" runs this same kernel through the Pallas
interpreter so tier-1 exercises it under JAX_PLATFORMS=cpu.

Also here: merge_sparse_tables, the device-side cross-launch merge for the
sparse group-by path (fixed-slot tables merged in-graph; see the function
docstring) — jnp-only, so it runs on every backend.
"""
from __future__ import annotations

import functools
import os
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from pinot_tpu.ops import segmented as _seg

try:  # pallas ships with jax on this image; gate defensively anyway
    from jax.experimental import pallas as pl

    _HAS_PALLAS = True
except Exception:  # pragma: no cover - environment without pallas
    pl = None
    _HAS_PALLAS = False

# Rows per grid step.  Multiple of 32 so bitmap word tiles slice cleanly;
# 4096 keeps the worst-case VMEM working set (A [T, 128] f32 + B [T, 64]
# + one limb temp) a few MB under the 16MB budget.
_TILE = 4096
# Grid steps per int32 accumulator "super-segment": 2048 * 4096 = 2^23
# rows, so a per-limb super sum is <= 255 * 2^23 < 2^31 - 1 (int32 exact).
_SUPER_TILES = 2048

_W = _seg._W  # two-level decomposition lane width (code = hi * 64 + lo)

# Pallas-eligible fused entry kinds: exact integer accumulation only (see
# module docstring for why floats stay on the XLA path).
PALLAS_KINDS = ("count", "int_sum", "int64_sum")

# same sentinel as query/planner.SPARSE_EMPTY_KEY (ops cannot import the
# query layer); all real packed keys are >= 0 so int64 max never collides
SPARSE_EMPTY_KEY = np.int64(np.iinfo(np.int64).max)


@functools.lru_cache(maxsize=None)
def scan_backend() -> str:
    """Plan-time scan-backend selector, part of every plan-cache key.

    "pallas" on a real TPU backend, "xla" everywhere else.  Env override
    PINOT_TPU_SCAN_BACKEND in {pallas, xla, interpret}: "interpret" routes
    plans through this kernel under the Pallas interpreter (CPU tests, the
    bench smoke gate).  lru_cached like accum_policy — tests that flip the
    env var must scan_backend.cache_clear()."""
    forced = os.environ.get("PINOT_TPU_SCAN_BACKEND", "").strip().lower()
    if forced in ("pallas", "xla", "interpret"):
        if forced in ("pallas", "interpret") and not _HAS_PALLAS:
            return "xla"
        return forced
    return "pallas" if (_HAS_PALLAS and jax.default_backend() == "tpu") else "xla"


def matmul_flops_per_row(num_groups: int, num_entries: int) -> float:
    """Analytic flop estimate for the group-accumulate path, per scanned
    row: the fused kernel (Pallas and the dense XLA fallback alike)
    accumulates each row into the group table via a one-hot matmul, i.e. a
    multiply+add against every group slot for every agg entry — 2·G·E
    flops/row.  Predicate masks and bitmap ANDs are O(1)/row noise next to
    the G-wide accumulate, so they are deliberately not modeled.  Used by
    utils.perf.analytic_cost when XLA cost_analysis is unavailable."""
    return 2.0 * float(max(1, num_groups)) * float(max(1, num_entries))


def pallas_supported(entries, num_groups: int) -> bool:
    """Can fused_group_tables_pallas compute these entries exactly?

    Integer-exact kinds only, group table narrow enough for the one-hot
    matmul (the same _MATMUL_MAX_GROUPS ceiling as the XLA matmul path)."""
    if not _HAS_PALLAS or num_groups < 1 or num_groups > _seg._MATMUL_MAX_GROUPS:
        return False
    for kind, values, _mask, _lp in entries:
        if kind not in PALLAS_KINDS:
            return False
        if kind == "int_sum" and not (
            jnp.issubdtype(values.dtype, jnp.integer) and values.dtype.itemsize <= 4
        ):
            return False
        if kind == "int64_sum" and values.dtype != jnp.int64:
            return False
    return True


def _row_iota(shape_len: int):
    # TPU Mosaic rejects 1D iota; build [n] from a 2D one
    return lax.broadcasted_iota(jnp.int32, (shape_len, 1), 0).reshape(shape_len)


def _lane_unpack(w, bits: int, rows: int):
    """In-register lane unpack: [rows * bits // 32] uint32 words -> [rows]
    uint32 lanes, lane l of word i covering row i * (32 // bits) + l.

    The shared primitive behind BOTH packed operand kinds: range-index
    bitmap words are the bits=1 case (one bool per lane), bit-packed
    forward indexes (segment/packing.py) the bits=4/8/16 case.  Pure
    shift/mask on the VPU — the packed word tile is the only HBM read and
    the widened lanes never leave registers/VMEM."""
    f = 32 // bits
    shifts = lax.broadcasted_iota(jnp.uint32, (rows // f, f), 1) * jnp.uint32(bits)
    return ((w[:, None] >> shifts) & jnp.uint32((1 << bits) - 1)).reshape(rows)


def fused_group_tables_pallas(
    entries,
    codes,
    num_groups: int,
    *,
    mask_words=None,
    code_pred: Optional[Tuple[Any, int, int]] = None,
    codes_packed: Optional[Tuple[Any, int]] = None,
    interpret: bool = False,
):
    """Pallas twin of segmented.fused_group_tables for integer kinds.

    entries: list of (kind, values, mask, limb_plan) with kind in
    PALLAS_KINDS.  mask_words: optional packed uint32 filter bitmap
    ([n // 32], bit r of word w covers row 32*w + r — the range-index
    word-slice layout of query/filter.eval_bitmap) ANDed into every entry
    mask IN-REGISTER, so the row-length bool mask never exists in HBM.
    code_pred: optional (codes_array, lo, hi) dictionary-code range
    predicate, likewise fused.  codes_packed: optional (words, code_bits)
    bit-packed forward index of the key column (segment/packing.py lanes);
    the kernel streams the uint32 word tiles — a 32/code_bits-factor
    super-tile of rows per word tile — and lane-unpacks in-register, so
    the key's HBM traffic is its PACKED byte count.  Returns
    f64[num_groups] tables in entry order, bit-identical to the XLA path
    (both are exact integer sums).

    Rows are padded to a _TILE multiple when needed (padding carries
    mask=False, so padded rows contribute exactly nothing); 32-aligned
    macro-batch widths make the engine's hot path pad-free."""
    n = int(codes.shape[0])
    if mask_words is not None and n % 32:
        raise ValueError("mask_words requires a 32-aligned row count")
    if not pallas_supported(entries, num_groups):
        raise ValueError("entries not eligible for the Pallas fused scan")

    T = _TILE
    n_tiles = max(1, -(-n // T))
    n_super = -(-n_tiles // _SUPER_TILES)
    H = -(-num_groups // _W)
    Hp = -(-H // 8) * 8  # pad the sublane dim for TPU tiling

    key_bits = None
    if codes_packed is not None:
        kw, key_bits = codes_packed
        key_bits = int(key_bits)
        key_factor = 32 // key_bits
        if n % key_factor or int(kw.shape[0]) != n // key_factor:
            raise ValueError("codes_packed rows must be lane-aligned with codes")
        inputs: List[Any] = [kw]
        in_specs: List[Any] = [pl.BlockSpec((T // key_factor,), lambda i: (i,))]
    else:
        inputs = [codes]
        in_specs = [pl.BlockSpec((T,), lambda i: (i,))]
    ix_of: Dict[int, int] = {}

    def _operand(arr) -> int:
        k = id(arr)
        if k not in ix_of:
            inputs.append(arr)
            in_specs.append(pl.BlockSpec((T,), lambda i: (i,)))
            ix_of[k] = len(inputs) - 1
        return ix_of[k]

    words_ix = None
    if mask_words is not None:
        inputs.append(mask_words)
        in_specs.append(pl.BlockSpec((T // 32,), lambda i: (i,)))
        words_ix = len(inputs) - 1
    pred_plan = None
    if code_pred is not None:
        pc, plo, phi = code_pred
        pred_plan = (_operand(pc), int(plo), int(phi))

    halves_of: Dict[int, Tuple[Any, Any]] = {}

    def _halves(arr):
        """uint32 (lo, hi) halves of an int64 column, split OUTSIDE the
        kernel — TPU Pallas has no 64-bit row ops; the bitcast is a cheap
        elementwise pass and the kernel reads the halves once."""
        k = id(arr)
        if k not in halves_of:
            h = lax.bitcast_convert_type(arr, jnp.uint32)
            lo_ix = _seg._i64_low_half_index()
            halves_of[k] = (h[..., lo_ix], h[..., 1 - lo_ix])
        return halves_of[k]

    plans: List[Tuple] = []  # (kind, mask_ix, value_ixs, limb_plan, col0)
    scales_per_entry: List[List[float]] = []
    col = 0
    for kind, values, mask, limb_plan in entries:
        m_ix = _operand(mask)
        if kind == "count":
            plans.append(("count", m_ix, (), None, col))
            scales = [1.0]
            col += 1
        elif kind == "int_sum":
            n_limbs, signed = limb_plan if limb_plan is not None else (4, True)
            plans.append(("int_sum", m_ix, (_operand(values),), (n_limbs, signed), col))
            scales = [float(1 << (8 * i)) for i in range(n_limbs)]
            if signed:
                scales.append(-float(1 << (8 * n_limbs)))
            col += n_limbs + (1 if signed else 0)
        else:  # int64_sum: signed-magnitude limbs (see segmented._int64_signed_limbs)
            nl = limb_plan if limb_plan is not None else 8
            lo_arr, hi_arr = _halves(values)
            plans.append(("int64_sum", m_ix, (_operand(lo_arr), _operand(hi_arr)), nl, col))
            scales = [float(1 << (8 * i)) for i in range(nl)]
            col += nl
        scales_per_entry.append(scales)
    L = col

    if n % T:
        pad = n_tiles * T - n
        padded = []
        for ix, a in enumerate(inputs):
            # packed operands pad by lanes-per-word: bitmap words carry 32
            # rows each, key words 32 // key_bits
            if ix == words_ix:
                w = pad // 32
            elif ix == 0 and key_bits is not None:
                w = pad * key_bits // 32
            else:
                w = pad
            padded.append(jnp.pad(a, (0, w)))
        inputs = padded

    def scan_kernel(*refs):
        out_ref = refs[-1]
        i = pl.program_id(0)

        @pl.when(i % _SUPER_TILES == 0)
        def _init():
            out_ref[...] = jnp.zeros_like(out_ref)

        if key_bits is not None:
            # super-tile key read: T rows arrive as T * key_bits / 32 words
            ki = _lane_unpack(refs[0][...], key_bits, T).astype(jnp.int32)
        else:
            ki = refs[0][...].astype(jnp.int32)
        base = None
        if words_ix is not None:
            base = _lane_unpack(refs[words_ix][...], 1, T) != jnp.uint32(0)
        if pred_plan is not None:
            p_ix, plo, phi = pred_plan
            pc = refs[p_ix][...].astype(jnp.int32)
            pm = (pc >= plo) & (pc < phi)
            base = pm if base is None else base & pm

        # one (A, B) one-hot pair shared by EVERY limb matmul of the tile —
        # the same sharing that makes the fused XLA scan 3x faster than
        # per-table scans, now also sharing the single HBM read
        A = (lax.broadcasted_iota(jnp.int32, (T, Hp), 1) == (ki // _W)[:, None]).astype(
            jnp.float32
        )
        B = (lax.broadcasted_iota(jnp.int32, (T, _W), 1) == (ki % _W)[:, None]).astype(
            jnp.float32
        )

        def accum(col_ix, wcol):
            s = lax.dot_general(
                A * wcol[:, None], B, (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            out_ref[0, col_ix] = out_ref[0, col_ix] + s.astype(jnp.int32)

        for kind, m_ix, v_ixs, lp, col0 in plans:
            m = refs[m_ix][...]
            if base is not None:
                m = m & base
            mf = m.astype(jnp.float32)
            if kind == "count":
                accum(col0, mf)
            elif kind == "int_sum":
                n_limbs, signed = lp
                vm = jnp.where(m, refs[v_ixs[0]][...].astype(jnp.int32), 0)
                u = vm.astype(jnp.uint32)
                for k in range(n_limbs):
                    accum(col0 + k, ((u >> jnp.uint32(8 * k)) & jnp.uint32(0xFF)).astype(jnp.float32))
                if signed:
                    accum(col0 + n_limbs, (vm < 0).astype(jnp.float32))
            else:  # int64_sum
                lo_h = refs[v_ixs[0]][...]
                hi_h = refs[v_ixs[1]][...]
                neg = hi_h >= jnp.uint32(1 << 31)
                alo = jnp.where(neg, ~lo_h + jnp.uint32(1), lo_h)
                ahi = jnp.where(neg, ~hi_h + (lo_h == jnp.uint32(0)).astype(jnp.uint32), hi_h)
                sgn = jnp.where(neg, -1, 1).astype(jnp.float32) * mf
                for k in range(lp):
                    h = alo if k < 4 else ahi
                    limb = ((h >> jnp.uint32(8 * (k % 4))) & jnp.uint32(0xFF)).astype(jnp.float32)
                    accum(col0 + k, limb * sgn)

    out = pl.pallas_call(
        scan_kernel,
        grid=(n_tiles,),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, L, Hp, _W), lambda i: (i // _SUPER_TILES, 0, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((n_super, L, Hp, _W), jnp.int32),
        interpret=bool(interpret),
    )(*inputs)

    # cross-super recombine in f64 (table-sized): every per-super value is
    # an exact integer < 2^31, every partial sum stays < 2^53 under the
    # same contract as the XLA path's per-chunk f64 combine
    flat = out.astype(jnp.float64).sum(axis=0).reshape(L, Hp * _W)[:, :num_groups]
    tables = []
    for (kind, _m, _v, _lp, col0), scales in zip(plans, scales_per_entry):
        t = flat[col0] if scales[0] == 1.0 else flat[col0] * scales[0]
        for j, s in enumerate(scales[1:], start=1):
            t = t + flat[col0 + j] * s
        tables.append(t)
    return tables


# ---------------------------------------------------------------------------
# Device-side sparse group-by cross-launch merge
# ---------------------------------------------------------------------------
def merge_sparse_tables(
    uniq,
    partials: Sequence[Dict[str, Any]],
    num_slots: int,
    field_ops: Sequence[Dict[str, str]],
    order_spec: Optional[Tuple[int, str, bool]] = None,
):
    """Merge stacked fixed-slot sparse group tables ON DEVICE (VERDICT
    weak #5): replaces the host numpy fold of sparse_tables_to_result for
    the macro-batched path, so cross-launch combining is part of the graph
    and only FINAL [num_slots] tables ever cross PCIe.

    uniq: [M] int64 packed keys (SPARSE_EMPTY_KEY padding), the
    concatenation of every launch's per-device [K] key tables (M = B*ndev*K).
    partials: per-agg {field: [M]} stacked the same way.  field_ops: per-agg
    {field: "add"|"min"|"max"} (functions.FIELD_COMBINE, passed in because
    ops cannot import the query layer).  order_spec: (agg index, order
    FIELD name, ascending) when an ORDER BY-aware trim applies — the
    device analog of executor._order_trim_select: rank by the merged order
    value (empty/NaN groups last), tie-break by packed key, keep the top
    num_slots, and emit survivors in ascending key order so downstream
    decode matches the host merge byte-for-byte.

    The merge is sort-based over the SAME fixed-slot contract as the
    per-launch kernel (sort keys -> segment starts -> running group id ->
    scatter-combine), not a literal probed hash table: table-sized lax.sort
    is TPU-native and exact, where open-addressing probe loops serialize.
    Everything here is [M]-sized (never row-length)."""
    M = int(uniq.shape[0])
    uniq = uniq.astype(jnp.int64).reshape(-1)
    iota = jnp.arange(M, dtype=jnp.int32)
    skey, perm = lax.sort((uniq, iota), num_keys=1)
    valid = skey != SPARSE_EMPTY_KEY
    prev = jnp.concatenate([jnp.full((1,), np.int64(-1), skey.dtype), skey[:-1]])
    is_start = valid & (skey != prev)
    seg_id = jnp.cumsum(is_start.astype(jnp.int32)) - 1
    # empty slots fold into an overflow slot M (sliced off): add-fields
    # carry 0 there, min/max carry their identity, so it absorbs harmlessly
    slot = jnp.where(valid, seg_id, np.int32(M))

    merged: List[Dict[str, Any]] = []
    for fops, p in zip(field_ops, partials):
        q: Dict[str, Any] = {}
        for fname, comb in fops.items():
            x = p[fname].reshape(-1)[perm]
            if comb == "add":
                q[fname] = jnp.zeros((M + 1,), x.dtype).at[slot].add(x)
            elif comb == "min":
                base = jnp.full((M + 1,), jnp.asarray(np.inf, x.dtype))
                q[fname] = base.at[slot].min(x)
            else:
                base = jnp.full((M + 1,), jnp.asarray(-np.inf, x.dtype))
                q[fname] = base.at[slot].max(x)
        merged.append(q)

    gslot = jnp.where(is_start, seg_id, np.int32(M))
    gkey = (
        jnp.full((M + 1,), SPARSE_EMPTY_KEY, jnp.int64)
        .at[gslot]
        .set(jnp.where(is_start, skey, SPARSE_EMPTY_KEY))
    )
    phantom = gkey == SPARSE_EMPTY_KEY  # slots past the last real group
    if order_spec is None:
        # lowest packed keys win — the deterministic numGroupsLimit trim
        ovk = jnp.where(phantom, jnp.inf, 0.0)
    else:
        oi, field, asc = order_spec
        ov = merged[oi][field].astype(jnp.float64)
        cnt = merged[oi].get("count")
        if cnt is not None:
            # SUM/MIN/MAX over zero agg-mask rows is SQL NULL: rank last,
            # mirroring AggFunction.final's count>0 guard on the host
            ov = jnp.where(cnt.astype(jnp.float64) > 0, ov, jnp.nan)
        ovk = ov if asc else -ov
        ovk = jnp.where(jnp.isnan(ovk) | phantom, jnp.inf, ovk)
    slots = jnp.arange(M + 1, dtype=jnp.int32)
    _, _, ranked = lax.sort((ovk, gkey, slots), num_keys=2)
    selmask = jnp.zeros((M + 1,), bool).at[ranked[:num_slots]].set(True)
    outkey = jnp.where(selmask & ~phantom, gkey, SPARSE_EMPTY_KEY)
    okey, operm = lax.sort((outkey, slots), num_keys=1)
    out = [{f: t[operm][:num_slots] for f, t in q.items()} for q in merged]
    return okey[:num_slots], out
