"""Device kernels / numeric primitives (the XLA/Pallas op layer).

The slot where the reference's JNI-native dependencies live (SURVEY.md 2.4):
here they are TPU kernels — segmented reductions, bitmap algebra, sketch
updates — shared by the SSE planner, the distributed engine and the MSE.
"""
from pinot_tpu.ops.segmented import (  # noqa: F401
    accum_policy,
    fused_group_tables,
    sum_limb_plan,
    sum_limb_plan64,
    group_count,
    group_max,
    group_min,
    group_sum,
    group_sum_sq,
    masked_count,
    masked_max,
    masked_min,
    masked_sum,
    masked_sum_sq,
    unpack_bitmap_words,
)
from pinot_tpu.ops.pallas_scan import (  # noqa: F401
    fused_group_tables_pallas,
    merge_sparse_tables,
    pallas_supported,
    scan_backend,
)
