"""Segmented (grouped) and masked reductions — the aggregation hot path.

Reference parity: the per-row accumulate loops of DefaultGroupByExecutor +
the typed result holders (pinot-core/.../query/aggregation/groupby/
DefaultGroupByExecutor.java:192, result holders in the same package).  Pinot
accumulates into on-heap double[]/long[] arrays indexed by group id; the TPU
form maps the same computation onto the MXU.

TPU-native design (measured on v5e; numbers for 16M rows x 2406 groups):
  * TPUs have no 64-bit ALU: under jax_enable_x64, f64/i64 arithmetic is
    software-emulated (~50x slower on big arrays) and jax.ops.segment_sum
    promotes its scatter indices to int64 (1.75s vs 110us for a raw
    int32-index lax.scatter_add).  Nothing here ever touches 64-bit types on
    the row axis.
  * XLA lowers scatter to a serialized loop on TPU: even an f32 scatter-add
    group-by runs at ~0.15 Grows/s.  The MXU answer is the TWO-LEVEL ONE-HOT
    MATMUL: split code = hi*64 + lo, build two narrow one-hot matrices
    (n x H and n x 64 — n*(H+64) VPU compares instead of n*G), then
    (A * v)^T @ B accumulates the whole [H, 64] group table as one matmul.
    ~11 Grows/s in f32.
  * Exact integer sums at MXU speed: decompose values into 8-bit limbs —
    every limb (< 256) is exact in bfloat16, every per-chunk dot accumulates
    < 2^24 in the MXU's f32 accumulator, so each limb matmul is EXACT.  The
    per-chunk [limb, H, 64] tables are recombined in (emulated) f64, which is
    cheap at table size.  Negative int32 values ride a fifth limb: the
    two's-complement reinterpretation plus a -2^32 * count(v<0) correction.
    3.7-2.7 Grows/s, error == 0.  (Pinot's double accumulators round above
    2^53; this path doesn't round at all for int32 inputs.)
  * Float sums use the single-f32 matmul (~1e-5 worst-case relative error;
    float-float "double-single" limbs are a planned upgrade).
  * Group tables wider than _MATMUL_MAX_GROUPS fall back to the f32 scatter
    (correct, slower); min/max always use scatter (no matmul semiring).
  * On CPU (tests, golden comparisons) the "wide" policy scatters directly
    in f64/i64 — bit-exact vs sqlite — still with int32 indices.

All functions take a boolean mask (filter + null handling folded in by the
caller) and return f64 (i64 for counts) outputs; outputs are group-table
sized, so the final widening costs nothing.
"""
from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

# Rows per chunk for the matmul path: limb sums stay < 2^24 (255 * 65536),
# i.e. exact in the MXU's f32 accumulator.
_CHUNK = 1 << 16
# Lane width of the two-level decomposition (code = hi * _W + lo).
_W = 64
# Above this group count the one-hot matrices stop paying for themselves.
_MATMUL_MAX_GROUPS = 8192

_POS_INF32 = np.float32(np.inf)
_NEG_INF32 = np.float32(-np.inf)


@functools.lru_cache(maxsize=None)
def accum_policy() -> str:
    """"wide" (native 64-bit, CPU) or "chunked32" (32-bit kernels + small
    f64 combines, TPU and any backend without 64-bit ALUs)."""
    return "wide" if jax.default_backend() == "cpu" else "chunked32"


@functools.lru_cache(maxsize=None)
def _i64_low_half_index() -> int:
    """Which minor index of bitcast_convert_type(i64 -> u32) holds the LOW
    32 bits (XLA leaves the order to the backend; probe once per process)."""
    with jax.ensure_compile_time_eval():  # callable from inside a jit trace
        halves = np.asarray(
            jax.lax.bitcast_convert_type(jnp.asarray([1], jnp.int64), jnp.uint32)
        )
    return 0 if halves[0, 0] == 1 else 1


def _i32(codes):
    return codes.astype(jnp.int32)


def unpack_bitmap_words(words, n: int):
    """[n // 32] uint32 packed filter words -> [n] bool row mask (bit r of
    word w covers row 32 * w + r — the query/filter.eval_bitmap layout).
    The XLA-path materialization of the mask the Pallas scan keeps packed."""
    bits = ((words[:, None] >> jnp.arange(32, dtype=jnp.uint32)) & jnp.uint32(1)) != 0
    return bits.reshape(-1)[:n]


# ---------------------------------------------------------------------------
# Scatter primitives (explicit int32 indices)
# ---------------------------------------------------------------------------
def _scatter_add(target, idx_i32, updates):
    dnums = lax.ScatterDimensionNumbers(
        update_window_dims=(), inserted_window_dims=(0,), scatter_dims_to_operand_dims=(0,)
    )
    return lax.scatter_add(
        target, idx_i32[:, None], updates, dnums,
        indices_are_sorted=False, unique_indices=False,
        mode=lax.GatherScatterMode.FILL_OR_DROP,
    )


def _scatter_extreme(target, idx_i32, updates, *, is_min: bool):
    dnums = lax.ScatterDimensionNumbers(
        update_window_dims=(), inserted_window_dims=(0,), scatter_dims_to_operand_dims=(0,)
    )
    op = lax.scatter_min if is_min else lax.scatter_max
    return op(
        target, idx_i32[:, None], updates, dnums,
        indices_are_sorted=False, unique_indices=False,
        mode=lax.GatherScatterMode.FILL_OR_DROP,
    )


# ---------------------------------------------------------------------------
# Two-level one-hot matmul core (chunked32 group path)
# ---------------------------------------------------------------------------
def _pad_to_chunks(*arrays):
    """Pad row arrays to a multiple of _CHUNK (padding rows carry mask=False
    via the first array being the already-masked values/False mask)."""
    n = arrays[0].shape[0]
    rem = n % _CHUNK
    if rem == 0:
        return arrays
    pad = _CHUNK - rem
    return tuple(
        jnp.pad(a, ((0, pad),) + ((0, 0),) * (a.ndim - 1)) for a in arrays
    )


def _matmul_group_table(weighted_limbs, scales, codes, num_groups: int):
    """Core: sum of scales[l] * sum_rows(limb_l[row] * onehot(code)) tables.

    weighted_limbs: [n, L] bf16 (each limb value exact in bf16, masked rows 0)
    scales: f64[L] recombination factors
    Returns f64[num_groups]."""
    H = -(-num_groups // _W)
    n = weighted_limbs.shape[0]
    weighted_limbs, codes = _pad_to_chunks(weighted_limbs, _i32(codes))
    L = weighted_limbs.shape[1] if weighted_limbs.ndim == 2 else 1
    v_r = weighted_limbs.reshape(-1, _CHUNK, L)
    k_r = codes.reshape(-1, _CHUNK)
    scales = jnp.asarray(scales, jnp.float64)

    def body(acc, xs):
        li, ki = xs
        hi = ki // np.int32(_W)
        lo = ki % np.int32(_W)
        A = jax.nn.one_hot(hi, H, dtype=jnp.bfloat16)  # [C, H]
        B = jax.nn.one_hot(lo, _W, dtype=jnp.bfloat16)  # [C, W]
        S = jnp.einsum("cl,ch,cw->lhw", li, A, B, preferred_element_type=jnp.float32)
        tot = (S.astype(jnp.float64) * scales[:, None, None]).sum(0)
        return acc + tot, None

    acc, _ = lax.scan(body, jnp.zeros((H, _W), jnp.float64), (v_r, k_r))
    return acc.reshape(-1)[:num_groups]


def _matmul_group_sum_f32(values_f32, codes, num_groups: int):
    """Float path: single f32 matmul per chunk (~1e-5 relative error)."""
    H = -(-num_groups // _W)
    values_f32, codes = _pad_to_chunks(values_f32, _i32(codes))
    v_r = values_f32.reshape(-1, _CHUNK)
    k_r = codes.reshape(-1, _CHUNK)

    def body(acc, xs):
        vi, ki = xs
        hi = ki // np.int32(_W)
        lo = ki % np.int32(_W)
        A = jax.nn.one_hot(hi, H, dtype=jnp.float32)
        B = jax.nn.one_hot(lo, _W, dtype=jnp.float32)
        S = jnp.einsum("ch,cw->hw", A * vi[:, None], B, preferred_element_type=jnp.float32)
        return acc + S.astype(jnp.float64), None

    acc, _ = lax.scan(body, jnp.zeros((H, _W), jnp.float64), (v_r, k_r))
    return acc.reshape(-1)[:num_groups]


# ---------------------------------------------------------------------------
# Fused multi-aggregate group tables (the dense group-by hot path)
# ---------------------------------------------------------------------------
# A group-by query computes MANY additive group tables over the SAME key
# column: the presence table, each SUM's value limbs and null-aware count,
# AVG's pair, VARIANCE's triple...  Computing each through its own
# _matmul_group_table scan rebuilds the one-hot matrices per table — measured
# 3x slower end-to-end than one fused scan sharing one (A, B) pair per chunk
# (v5e, 134M rows x 2406 groups: 213ms separate vs ~60ms fused).  Layout
# note: W=64 with the lhw einsum is the measured sweet spot; W=128/256 and
# int8 MXU variants all regress (see round-2 bench notes).

def sum_limb_plan(vmin, vmax) -> Tuple[int, bool]:
    """(n_limbs, signed) for the exact two's-complement 8-bit limb
    decomposition of ints known to lie in [vmin, vmax].  Column stats shrink
    the default int32 plan (4 limbs + sign) down to as little as one limb —
    each dropped limb removes a whole matmul from every chunk."""
    if vmin is None or vmax is None:
        return 4, True
    vmin, vmax = int(vmin), int(vmax)
    if vmin < -(1 << 31) or vmax > (1 << 31) - 1:
        return 4, True  # caller guarantees int32 storage; defensive
    for k in (1, 2, 3, 4):
        if vmin >= 0 and vmax < (1 << (8 * k)):
            return k, False
        if -(1 << (8 * k - 1)) <= vmin and vmax < (1 << (8 * k - 1)):
            return k, True
    return 4, vmin < 0


def sum_limb_plan64(vmin, vmax) -> int:
    """Limb count for the SIGNED-MAGNITUDE 8-bit decomposition of int64
    values in [vmin, vmax] (the "int64_sum" fused kind).  Unlike the int32
    two's-complement plan there is no sign-correction limb — the sign rides
    each limb — so the count is just ceil(bits(max |v|) / 8)."""
    if vmin is None or vmax is None:
        return 8
    m = max(abs(int(vmin)), abs(int(vmax)))
    for k in range(1, 8):
        if m < (1 << (8 * k)):
            return k
    return 8


def _int64_signed_limbs(values, mask, n_limbs: int, dt):
    """Signed-magnitude 8-bit limb columns + scales for int64 values.

    Two's-complement limbs would recombine through a -2^64 * negcount
    correction whose f64 cancellation is catastrophic (a column of -1s
    yields n*(2^64 - 1) - n*2^64, which rounds to 0 long before 2^53);
    sign-magnitude limbs keep every recombine partial sum bounded by
    sum(|v| mod 2^(8k)) <= sum(|v|), so the ascending-scale f64 recombine
    is BIT-exact while sum(|v|) < 2^53 — the reference's double-accumulate
    contract (SumAggregationFunction.java).  Every row-axis op is 32-bit:
    the i64 column is bitcast to uint32 halves, |v| is computed with a
    one-bit carry (~v + 1 carries iff lo == 0), and each limb (<= 255,
    exact in bf16) is signed by the row's sign."""
    vm = jnp.where(mask, values, jnp.int64(0))
    halves = lax.bitcast_convert_type(vm, jnp.uint32)  # [n, 2]
    lo_ix = _i64_low_half_index()
    lo = halves[..., lo_ix]
    hi = halves[..., 1 - lo_ix]
    neg = hi >= np.uint32(1 << 31)
    alo = jnp.where(neg, ~lo + np.uint32(1), lo)
    ahi = jnp.where(neg, ~hi + (lo == np.uint32(0)).astype(jnp.uint32), hi)
    sgn = jnp.where(neg, np.int32(-1), np.int32(1))
    cols, scales = [], []
    for k in range(n_limbs):
        h = alo if k < 4 else ahi
        limb = ((h >> np.uint32(8 * (k % 4))) & np.uint32(0xFF)).astype(jnp.int32)
        cols.append((limb * sgn).astype(dt))
        scales.append(float(1 << (8 * k)))
    return cols, scales


# entry kinds understood by fused_group_tables
FUSED_KINDS = ("count", "int_sum", "int64_sum", "f32_sum", "f32_sumsq")


def _entry_fallback(kind, values, mask, codes, num_groups):
    if kind == "count":
        return group_count(mask, codes, num_groups)
    if kind in ("int_sum", "int64_sum", "f32_sum"):
        return group_sum(values, mask, codes, num_groups)
    return group_sum_sq(values, mask, codes, num_groups)


def _fused_wide_tables(entries, codes, num_groups: int):
    """Wide (native-f64) policy: ONE windowed scatter-add for ALL entries.

    Every FUSED_KIND is additive, so the whole group-by reduces to scattering
    [n, E] f64 update rows into a [num_groups, E] table — one serialized
    scatter loop instead of E of them.  Measured on the CPU bench (4M rows,
    2406 groups, 2 entries): 2 per-entry scatters = 375ms, one windowed
    scatter = 297ms/entry-pair — the difference between 11.7M and 14M rows/s.
    Counts ride as mask-valued f64 columns (exact integers below 2^53, the
    fused-table contract callers already cast from)."""
    codes = _i32(codes)
    cols = []
    for kind, values, mask, _ in entries:
        if kind == "count":
            cols.append(mask.astype(jnp.float64))
        elif kind == "f32_sumsq":
            v = values.astype(jnp.float64)
            cols.append(jnp.where(mask, v * v, 0.0))
        else:
            cols.append(jnp.where(mask, values.astype(jnp.float64), 0.0))
    upd = jnp.stack(cols, axis=1)  # [n, E]
    dnums = lax.ScatterDimensionNumbers(
        update_window_dims=(1,), inserted_window_dims=(0,), scatter_dims_to_operand_dims=(0,)
    )
    table = lax.scatter_add(
        jnp.zeros((num_groups, len(entries)), jnp.float64), codes[:, None], upd, dnums,
        indices_are_sorted=False, unique_indices=False,
        mode=lax.GatherScatterMode.FILL_OR_DROP,
    )
    return [table[:, e] for e in range(len(entries))]


# row-length limb stacks past this size extract in-chunk instead of
# materializing [n, L] in HBM (see fused_group_tables)
_FUSED_STACK_BYTES = 1 << 31


def _entry_width(kind, limb_plan) -> int:
    """Limb-column count _entry_limbs will produce for this entry."""
    if kind == "count":
        return 1
    if kind == "int_sum":
        n_limbs, signed = limb_plan if limb_plan is not None else (4, True)
        return n_limbs + (1 if signed else 0)
    if kind == "int64_sum":
        return limb_plan if limb_plan is not None else 8
    return 1


def _fused_scan_inchunk(entries, codes, num_groups, dt, H):
    """fused_group_tables' loop with PER-CHUNK limb extraction and
    dynamic_slice reads straight out of the ORIGINAL flat arrays.

    No [n, L] limb stack, no pad copy, no scan-operand reshape copies —
    every one of those materialized gigabytes of HLO temps at 1B rows
    (three HBM-OOM post-mortems of the 1B bench).  The tail chunk slices
    from n - CHUNK with already-covered head rows masked off, so unaligned
    row counts need no padding."""
    n = codes.shape[0]
    operands = []
    for kind, values, mask, limb_plan in entries:
        v = values if values is not None else mask
        operands.append((v, mask))
    slices = []
    L = 0
    for kind, _, _, limb_plan in entries:
        w = _entry_width(kind, limb_plan)
        slices.append((L, None))  # scales captured at trace time below
        L += w

    num_chunks = max(1, -(-n // _CHUNK))
    scale_box = []
    iota = jnp.arange(_CHUNK, dtype=jnp.int32)

    def body(i, acc):
        start = jnp.minimum(i * _CHUNK, np.int32(max(0, n - _CHUNK)))
        # rows already covered by the previous chunk (tail overlap) drop out
        fresh = (start + iota) >= i * _CHUNK
        ki = _i32(lax.dynamic_slice_in_dim(codes, start, _CHUNK))
        cols = []
        for ei, (kind, _, _, limb_plan) in enumerate(entries):
            v, m = operands[ei]
            vi = lax.dynamic_slice_in_dim(v, start, _CHUNK)
            mi = lax.dynamic_slice_in_dim(m, start, _CHUNK) & fresh
            ecols, scales = _entry_limbs(kind, vi, mi, limb_plan, dt)
            if len(scale_box) == ei:  # python-level capture at trace time
                scale_box.append(scales)
            cols.extend(ecols)
        li = jnp.stack(cols, axis=1)
        hi = ki // np.int32(_W)
        lo = ki % np.int32(_W)
        A = jax.nn.one_hot(hi, H, dtype=dt)
        B = jax.nn.one_hot(lo, _W, dtype=dt)
        S = jnp.einsum("cl,ch,cw->lhw", li, A, B, preferred_element_type=jnp.float32)
        return acc + S.astype(jnp.float64)

    if n < _CHUNK:
        # single undersized chunk: fall back to padded one-shot
        ops_p = _pad_to_chunks(*[a for pair in operands for a in pair], codes)
        *ent_ops, codes_p = ops_p
        cols = []
        for ei, (kind, _, _, limb_plan) in enumerate(entries):
            ecols, scales = _entry_limbs(kind, ent_ops[2 * ei], ent_ops[2 * ei + 1], limb_plan, dt)
            if len(scale_box) == ei:
                scale_box.append(scales)
            cols.extend(ecols)
        li = jnp.stack(cols, axis=1)
        ki = _i32(codes_p)
        A = jax.nn.one_hot(ki // np.int32(_W), H, dtype=dt)
        B = jax.nn.one_hot(ki % np.int32(_W), _W, dtype=dt)
        acc = jnp.einsum("cl,ch,cw->lhw", li, A, B, preferred_element_type=jnp.float32).astype(jnp.float64)
    else:
        acc = lax.fori_loop(0, num_chunks, body, jnp.zeros((L, H, _W), jnp.float64))
    flat = acc.reshape(L, H * _W)[:, :num_groups]
    slices = [(start, scale_box[ei]) for ei, (start, _) in enumerate(slices)]
    return flat, slices


def _entry_limbs(kind, values, mask, limb_plan, dt):
    """-> (list of [n] limb columns in dtype dt, list of f64 scales)."""
    if kind == "count":
        return [mask.astype(dt)], [1.0]
    if kind == "int_sum":
        n_limbs, signed = limb_plan if limb_plan is not None else (4, True)
        vm = jnp.where(mask, values, np.int32(0)).astype(jnp.int32)
        u = vm.astype(jnp.uint32)
        cols = [((u >> np.uint32(8 * i)) & np.uint32(0xFF)).astype(dt) for i in range(n_limbs)]
        scales = [float(1 << (8 * i)) for i in range(n_limbs)]
        if signed:
            cols.append((vm < 0).astype(dt))
            scales.append(-float(1 << (8 * n_limbs)))
        return cols, scales
    if kind == "int64_sum":
        return _int64_signed_limbs(values, mask, limb_plan if limb_plan is not None else 8, dt)
    if kind == "f32_sum":
        return [jnp.where(mask, values.astype(jnp.float32), np.float32(0.0))], [1.0]
    v = values.astype(jnp.float32)
    return [jnp.where(mask, v * v, np.float32(0.0))], [1.0]


def fused_group_tables(
    entries, codes, num_groups: int, backend=None, mask_words=None, codes_packed=None
):
    """Compute many additive group tables in ONE chunked one-hot-matmul scan.

    entries: list of (kind, values, mask, limb_plan); kind in FUSED_KINDS,
    limb_plan = sum_limb_plan(...) for int_sum (None -> full int32 plan).
    Returns a list of f64[num_groups] tables in entry order ("count" entries
    are exact integer-valued f64; callers cast).

    backend: plan-time scan-backend tag ("pallas" | "interpret" | "xla" |
    None).  "pallas"/"interpret" dispatch Pallas-eligible entry sets (exact
    integer kinds, narrow-enough table — pallas_scan.pallas_supported) to
    the fused single-HBM-pass kernel; everything else stays here.
    mask_words: optional packed uint32 filter bitmap ([n // 32], the
    range-index word-slice layout) ANDed into every entry mask — the Pallas
    kernel unpacks it in-register; the XLA path unpacks it once up front.
    codes_packed: optional (words, code_bits) — the bit-packed forward index
    of the SAME key column (segment/packing.py lanes).  The Pallas kernel
    reads the words and lane-unpacks in-register; non-Pallas paths keep
    using `codes` (the caller's trace-level unpack, which XLA dedups/DCEs),
    so `codes` must always be provided.

    Exactness: int_sum limbs (< 256) and count flags are exact in bf16; each
    per-chunk MXU dot accumulates < 2^24 in f32 (exact); cross-chunk
    accumulation is f64.  f32_sum/f32_sumsq share the scan by promoting the
    one-hot matrices to f32 (int limbs stay exact there too)."""
    if backend in ("pallas", "interpret"):
        from pinot_tpu.ops import pallas_scan  # lazy: keeps import DAG flat

        if pallas_scan.pallas_supported(entries, num_groups):
            return pallas_scan.fused_group_tables_pallas(
                entries, codes, num_groups,
                mask_words=mask_words,
                codes_packed=codes_packed,
                interpret=(backend == "interpret"),
            )
    if mask_words is not None:
        # declined the Pallas path (wide table, float kinds, CPU policy):
        # fall back to one explicit unpack shared by every entry
        row_mask = unpack_bitmap_words(mask_words, codes.shape[0])
        entries = [(k, v, m & row_mask, lp) for k, v, m, lp in entries]
    if accum_policy() == "wide":
        return _fused_wide_tables(entries, codes, num_groups)
    if num_groups > _MATMUL_MAX_GROUPS:
        return [_entry_fallback(k, v, m, codes, num_groups) for k, v, m, _ in entries]

    use_f32 = any(k in ("f32_sum", "f32_sumsq") for k, _, _, _ in entries)
    dt = jnp.float32 if use_f32 else jnp.bfloat16
    H = -(-num_groups // _W)

    # Estimate the [n, L] stacked-limb footprint; past the budget, limbs
    # extract INSIDE the scan body from the raw (values, mask) chunks —
    # VMEM-resident, ~25% slower per chunk but it removes the multi-GB HBM
    # intermediate that OOMed the 1B-row bench.  Dead-bytes rule: even under
    # the budget, when the widened stack would out-weigh the RAW inputs
    # (e.g. an int8 dict column fanning out to L bf16 limb columns) the
    # in-chunk form wins — it streams the narrow storage bytes instead of
    # writing back a wider copy of them.
    n_rows = codes.shape[0]
    L = sum(_entry_width(kind, limb_plan) for kind, _, _, limb_plan in entries)
    stack_bytes = n_rows * L * jnp.dtype(dt).itemsize
    # dead-byte rule: a bit-packed key streams code_bits/8 bytes per row —
    # the trace-level unpacked view never touches HBM at full width
    key_bytes = codes_packed[1] / 8.0 if codes_packed is not None else codes.dtype.itemsize
    raw_ids = {id(codes): key_bytes}
    for _, values, mask, _ in entries:
        if values is not None:
            raw_ids[id(values)] = values.dtype.itemsize
        raw_ids[id(mask)] = mask.dtype.itemsize
    raw_bytes = n_rows * sum(raw_ids.values())
    if stack_bytes > _FUSED_STACK_BYTES or (
        stack_bytes > raw_bytes and n_rows >= 4 * _CHUNK
    ):
        flat, slices = _fused_scan_inchunk(entries, codes, num_groups, dt, H)
    else:
        cols = []
        slices = []  # per entry: (start, scales)
        for kind, values, mask, limb_plan in entries:
            ecols, scales = _entry_limbs(kind, values, mask, limb_plan, dt)
            slices.append((len(cols), scales))
            cols.extend(ecols)

        stacked = jnp.stack(cols, axis=1)  # [n, L]
        # codes keep their storage dtype; the body casts one chunk at a time
        # (a full-array i32 cast is a multi-GB HBM temp at 1B rows)
        stacked, codes = _pad_to_chunks(stacked, codes)
        v_r = stacked.reshape(-1, _CHUNK, L)
        k_r = codes.reshape(-1, _CHUNK)

        def body(acc, xs):
            li, ki = xs
            ki = _i32(ki)
            hi = ki // np.int32(_W)
            lo = ki % np.int32(_W)
            A = jax.nn.one_hot(hi, H, dtype=dt)  # [C, H]
            B = jax.nn.one_hot(lo, _W, dtype=dt)  # [C, W]
            S = jnp.einsum("cl,ch,cw->lhw", li, A, B, preferred_element_type=jnp.float32)
            return acc + S.astype(jnp.float64), None

        acc, _ = lax.scan(body, jnp.zeros((L, H, _W), jnp.float64), (v_r, k_r))
        flat = acc.reshape(L, H * _W)[:, :num_groups]

    out = []
    for start, scales in slices:
        t = flat[start] * scales[0] if scales[0] != 1.0 else flat[start]
        for j, s in enumerate(scales[1:], start=1):
            t = t + flat[start + j] * s
        out.append(t)
    return out


# ---------------------------------------------------------------------------
# Grouped reductions
# ---------------------------------------------------------------------------
def group_sum(values, mask, codes, num_groups: int):
    """f64[num_groups] sum of values where mask, by group code."""
    codes = _i32(codes)
    is_int = jnp.issubdtype(values.dtype, jnp.integer)
    if accum_policy() == "wide":
        v = jnp.where(mask, values.astype(jnp.float64), 0.0)
        return _scatter_add(jnp.zeros((num_groups,), jnp.float64), codes, v)
    if num_groups > _MATMUL_MAX_GROUPS:
        if is_int and values.dtype.itemsize > 4:
            # exact-below-2^53 f64 scatter (matches the sparse path and the
            # reference's double accumulate); this path is scatter-bound
            # already, so the emulated-f64 adds cost little extra
            v = jnp.where(mask, values.astype(jnp.float64), 0.0)
            return _scatter_add(jnp.zeros((num_groups,), jnp.float64), codes, v)
        return _scatter_group_sum_f32(values, mask, codes, num_groups)
    if is_int and values.dtype.itemsize <= 4:
        # exact limb path (int32 and narrower)
        vm = jnp.where(mask, values, np.int32(0)).astype(jnp.int32)
        u = vm.astype(jnp.uint32)
        limbs = [((u >> np.uint32(8 * i)) & np.uint32(0xFF)).astype(jnp.bfloat16) for i in range(4)]
        limbs.append((vm < 0).astype(jnp.bfloat16))  # two's-complement correction
        stacked = jnp.stack(limbs, axis=1)
        scales = [float(1 << (8 * i)) for i in range(4)] + [-float(1 << 32)]
        return _matmul_group_table(stacked, scales, codes, num_groups)
    if is_int:
        # exact signed-magnitude limb path for int64 (see _int64_signed_limbs)
        cols, scales = _int64_signed_limbs(values, mask, 8, jnp.bfloat16)
        return _matmul_group_table(jnp.stack(cols, axis=1), scales, codes, num_groups)
    v = jnp.where(mask, values.astype(jnp.float32), np.float32(0.0))
    return _matmul_group_sum_f32(v, codes, num_groups)


def _scatter_group_sum_f32(values, mask, codes, num_groups: int):
    """Fallback for wide group tables: chunked f32 scatter + f64 combine."""
    n = values.shape[0]
    k = -(-n // _CHUNK)
    v = jnp.where(mask, values.astype(jnp.float32), np.float32(0.0))
    chunk_ids = lax.iota(jnp.int32, n) // np.int32(_CHUNK)
    idx = chunk_ids * np.int32(num_groups) + codes
    table = _scatter_add(jnp.zeros((k * num_groups,), jnp.float32), idx, v)
    return table.reshape(k, num_groups).astype(jnp.float64).sum(axis=0)


def group_sum_sq(values, mask, codes, num_groups: int):
    if accum_policy() == "wide":
        v = values.astype(jnp.float64)
        return group_sum(v * v, mask, codes, num_groups)
    v = values.astype(jnp.float32)
    return group_sum(v * v, mask, codes, num_groups)


def group_count(mask, codes, num_groups: int):
    """i64[num_groups] count of mask-true rows by group code."""
    codes = _i32(codes)
    if accum_policy() == "wide":
        return _scatter_add(jnp.zeros((num_groups,), jnp.int64), codes, mask.astype(jnp.int64))
    if num_groups > _MATMUL_MAX_GROUPS:
        n = mask.shape[0]
        k = -(-n // _CHUNK)
        chunk_ids = lax.iota(jnp.int32, n) // np.int32(_CHUNK)
        idx = chunk_ids * np.int32(num_groups) + codes
        table = _scatter_add(jnp.zeros((k * num_groups,), jnp.int32), idx, mask.astype(jnp.int32))
        return table.reshape(k, num_groups).astype(jnp.int64).sum(axis=0)
    # single-limb matmul: per-chunk counts <= _CHUNK, exact in f32
    stacked = mask.astype(jnp.bfloat16)[:, None]
    return _matmul_group_table(stacked, [1.0], codes, num_groups).astype(jnp.int64)


def group_min(values, mask, codes, num_groups: int):
    """f64[num_groups]; +inf where a group matched no rows.

    chunked32 note: f32 scatter (values round to f32; exact below 2^24).
    Scatter is the slow path on TPU — acceptable because min/max group-bys
    are rare vs sum/count; a Pallas tiled kernel is the planned upgrade."""
    codes = _i32(codes)
    if accum_policy() == "wide":
        v = jnp.where(mask, values.astype(jnp.float64), jnp.float64(np.inf))
        return _scatter_extreme(jnp.full((num_groups,), np.float64(np.inf)), codes, v, is_min=True)
    v = jnp.where(mask, values.astype(jnp.float32), _POS_INF32)
    out = _scatter_extreme(jnp.full((num_groups,), _POS_INF32), codes, v, is_min=True)
    return out.astype(jnp.float64)


def group_max(values, mask, codes, num_groups: int):
    codes = _i32(codes)
    if accum_policy() == "wide":
        v = jnp.where(mask, values.astype(jnp.float64), jnp.float64(-np.inf))
        return _scatter_extreme(jnp.full((num_groups,), np.float64(-np.inf)), codes, v, is_min=False)
    v = jnp.where(mask, values.astype(jnp.float32), _NEG_INF32)
    out = _scatter_extreme(jnp.full((num_groups,), _NEG_INF32), codes, v, is_min=False)
    return out.astype(jnp.float64)


# ---------------------------------------------------------------------------
# Masked scalar reductions (aggregation without group-by)
# ---------------------------------------------------------------------------
def masked_count(mask):
    """i64 scalar count (reduce in i32, widen the scalar)."""
    if accum_policy() == "wide":
        return jnp.sum(mask, dtype=jnp.int64)
    return jnp.sum(mask, dtype=jnp.int32).astype(jnp.int64)


def masked_sum(values, mask):
    """f64 scalar masked sum.

    chunked32: integer inputs (int32 and narrower) ride the exact limb path
    as a 1-group group_sum — bit-exact like the grouped path, matching
    Pinot's double accumulator below 2^53.  Floats use XLA's f32 tree
    reduction with an f64 chunk combine (~2^-24 relative error per chunk)."""
    if accum_policy() == "wide":
        return jnp.sum(jnp.where(mask, values.astype(jnp.float64), 0.0))
    if jnp.issubdtype(values.dtype, jnp.integer):
        # direct chunked limb reduction (no one-hot needed without groups):
        # per-chunk per-limb f32 sums, |sum| <= 255 * _CHUNK < 2^24, exact.
        if values.dtype.itemsize <= 4:
            vm = jnp.where(mask, values, np.int32(0)).astype(jnp.int32)
            u = vm.astype(jnp.uint32)
            limbs = [((u >> np.uint32(8 * i)) & np.uint32(0xFF)).astype(jnp.float32) for i in range(4)]
            limbs.append((vm < 0).astype(jnp.float32))  # two's-complement correction
            scales = [float(1 << (8 * i)) for i in range(4)] + [-float(1 << 32)]
        else:
            # int64: signed-magnitude limbs (exact while sum(|v|) < 2^53)
            limbs, scales = _int64_signed_limbs(values, mask, 8, jnp.float32)
        stacked = jnp.stack(limbs, axis=1)
        (stacked,) = _pad_to_chunks(stacked)
        chunk_sums = stacked.reshape(-1, _CHUNK, len(limbs)).sum(axis=1)
        return (chunk_sums.astype(jnp.float64) * jnp.asarray(scales, jnp.float64)).sum()
    v = jnp.where(mask, values.astype(jnp.float32), np.float32(0.0))
    # two-stage: f32 chunk sums (vectorized reduce), f64 combine of the
    # small vector — bounds error without the scatter.
    (v,) = _pad_to_chunks(v)
    return v.reshape(-1, _CHUNK).sum(axis=1).astype(jnp.float64).sum()


def masked_sum_sq(values, mask):
    if accum_policy() == "wide":
        v = values.astype(jnp.float64)
        return masked_sum(v * v, mask)
    v = values.astype(jnp.float32)
    return masked_sum(v * v, mask)


def masked_min(values, mask):
    """f64 scalar; +inf when nothing matched."""
    if accum_policy() == "wide":
        return jnp.min(jnp.where(mask, values.astype(jnp.float64), jnp.float64(np.inf)))
    return jnp.min(jnp.where(mask, values.astype(jnp.float32), _POS_INF32)).astype(jnp.float64)


def masked_max(values, mask):
    if accum_policy() == "wide":
        return jnp.max(jnp.where(mask, values.astype(jnp.float64), jnp.float64(-np.inf)))
    return jnp.max(jnp.where(mask, values.astype(jnp.float32), _NEG_INF32)).astype(jnp.float64)
