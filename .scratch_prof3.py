import time
import numpy as np
import jax, jax.numpy as jnp
from jax import lax

N = 1 << 27
rng = np.random.default_rng(0)
v = rng.integers(100, 1_000_000, N).astype(np.int32)
d_v = jax.device_put(v)
print("devices:", jax.devices(), "committed:", d_v.committed, d_v.sharding)

@jax.jit
def sum1(x):
    return x.astype(jnp.float32).sum()

@jax.jit
def sum10(x):
    def body(i, acc):
        return acc + (x + i).astype(jnp.float32).sum()
    return lax.fori_loop(0, 10, body, jnp.float32(0))

def bench(fn, *args, reps=5):
    out = fn(*args); jax.device_get(out)
    ts = []
    for _ in range(reps):
        t0 = time.perf_counter(); out = fn(*args); jax.device_get(out); ts.append(time.perf_counter()-t0)
    return float(np.median(ts))

t1 = bench(sum1, d_v)
t10 = bench(sum10, d_v)
print(f"sum x1: {t1*1000:.1f}ms -> {4*N/t1/1e9:.1f} GB/s")
print(f"sum x10 in-graph: {t10*1000:.1f}ms -> per-pass {4*N*10/t10/1e9:.1f} GB/s")
