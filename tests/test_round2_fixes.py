"""Round-2 regression tests for the round-1 advisor findings (ADVICE.md):
ORDER BY on aggregations through SQL, alias resolution in ORDER BY/HAVING,
grouped-sketch cell-budget valves, exact integer scalar SUM under the TPU
accumulation policy, and exact DISTINCTCOUNT across misaligned dictionaries."""
import numpy as np
import pytest

from pinot_tpu import ops
from pinot_tpu.ops import segmented
from pinot_tpu.query.engine import QueryEngine
from pinot_tpu.segment.builder import build_segment
from pinot_tpu.spi.config import TableConfig
from pinot_tpu.spi.schema import DataType, FieldRole, FieldSpec, Schema

from golden import assert_same_rows, sqlite_from_data

N = 3000
CITIES = ["sf", "nyc", "chi", "la", "sea"]


@pytest.fixture(scope="module")
def env():
    rng = np.random.default_rng(11)
    schema = Schema(
        "t",
        [
            FieldSpec("city", DataType.STRING),
            FieldSpec("year", DataType.INT),
            FieldSpec("v", DataType.LONG, role=FieldRole.METRIC),
        ],
    )
    engine = QueryEngine()
    engine.register_table(schema, TableConfig("t"))
    all_data = {k: [] for k in ("city", "year", "v")}
    for seed in (1, 2):
        data = {
            "city": rng.choice(CITIES, N).astype(object),
            "year": rng.integers(2000, 2010, N).astype(np.int32),
            "v": rng.integers(0, 1000, N),
        }
        engine.add_segment("t", build_segment(schema, data, f"s{seed}"))
        for k in all_data:
            all_data[k].append(data[k])
    merged = {k: np.concatenate(v) for k, v in all_data.items()}
    return engine, sqlite_from_data("t", merged)


ORDER_BY_AGG_QUERIES = [
    # the canonical top-N-by-metric query (ADVICE finding 1)
    "SELECT city, SUM(v) FROM t GROUP BY city ORDER BY SUM(v) DESC LIMIT 3",
    "SELECT city, COUNT(*) FROM t GROUP BY city ORDER BY COUNT(*) DESC, city LIMIT 5",
    "SELECT year, AVG(v) FROM t GROUP BY year ORDER BY AVG(v) LIMIT 4",
    # select-alias references (ADVICE finding 2)
    "SELECT city, SUM(v) AS s FROM t GROUP BY city ORDER BY s DESC LIMIT 3",
    "SELECT city, SUM(v) AS s FROM t GROUP BY city HAVING s > 100 ORDER BY city LIMIT 20",
    "SELECT year AS y, COUNT(*) FROM t GROUP BY year ORDER BY y LIMIT 20",
    "SELECT city AS c FROM t WHERE v < 5 ORDER BY c LIMIT 10",
    # aggregation not in the select list
    "SELECT city FROM t GROUP BY city ORDER BY SUM(v) DESC LIMIT 3",
]


@pytest.mark.parametrize("sql", ORDER_BY_AGG_QUERIES)
def test_order_by_aggregation_and_aliases(env, sql):
    engine, conn = env
    got = engine.query(sql)
    exp = conn.execute(sql).fetchall()
    assert_same_rows(got.rows, exp, ordered=True)


def test_alias_shadowing_physical_column(env):
    """An alias shadowing a physical column must NOT rewrite columns inside
    aggregation calls (review-caught): SUM(v) stays SUM(v) even when the
    select list says `year AS v`."""
    engine, _ = env
    shadowed = engine.query(
        "SELECT year AS v, SUM(v) AS s FROM t GROUP BY year "
        "HAVING SUM(v) > 100000 ORDER BY SUM(v) DESC LIMIT 30"
    )
    plain = engine.query(
        "SELECT year, SUM(v) AS s FROM t GROUP BY year "
        "HAVING SUM(v) > 100000 ORDER BY SUM(v) DESC LIMIT 30"
    )
    assert shadowed.rows == plain.rows


def test_grouped_hll_cell_valve():
    """num_groups * m beyond the cell budget must raise, not silently drop
    rows via int32 wraparound (ADVICE finding 3)."""
    rng = np.random.default_rng(3)
    schema = Schema(
        "w",
        [
            # METRIC role -> raw (undictionaried) int: the group dim spans
            # the full 40k value range, not the observed cardinality
            FieldSpec("k", DataType.INT, role=FieldRole.METRIC),
            FieldSpec("u", DataType.LONG, role=FieldRole.METRIC),
        ],
    )
    n = 2000
    data = {
        "k": np.concatenate([[0, 39_999], rng.integers(0, 40_000, n - 2)]).astype(np.int32),
        "u": rng.integers(0, 1 << 40, n),
    }
    engine = QueryEngine()
    engine.register_table(schema, TableConfig("w"))
    engine.add_segment("w", build_segment(schema, data, "w0"))
    with pytest.raises(NotImplementedError, match="cells"):
        # 40_000 groups x 4096 registers = 163M cells > 2^26
        engine.query("SELECT k, DISTINCTCOUNTHLL(u) FROM w GROUP BY k LIMIT 5")


def test_exact_int_scalar_sum_chunked32(monkeypatch):
    """Scalar SUM over int32 under the TPU policy must be bit-exact via the
    limb path (ADVICE finding 4)."""
    monkeypatch.setattr(segmented, "accum_policy", lambda: "chunked32")
    rng = np.random.default_rng(5)
    vals = rng.integers(-(2**31) + 1, 2**31 - 1, 200_000, dtype=np.int64).astype(np.int32)
    mask = rng.random(200_000) < 0.7
    got = int(np.asarray(ops.masked_sum(vals, mask)))
    exp = int(vals[mask].astype(object).sum())
    assert got == exp


def test_fused_grouped_partials_chunked32(monkeypatch):
    """The fused one-hot-matmul scan (TPU policy) must agree with the exact
    wide policy for every additive/min/max field combination."""
    from pinot_tpu.query import planner

    rng = np.random.default_rng(9)
    n = 50_000
    g = 300
    codes = rng.integers(0, g, n).astype(np.int32)
    ints = rng.integers(-50_000, 50_000, n).astype(np.int32)
    floats = rng.normal(0, 10, n).astype(np.float64)
    mask = rng.random(n) < 0.8

    from pinot_tpu.query.functions import get_agg_function

    aggs = [get_agg_function(nm) for nm in ("count", "sum", "avg", "min", "variance")]
    inputs = [(mask, mask), (ints, mask), (floats, mask), (ints, mask), (floats, mask)]
    vranges = [None, (-50_000, 50_000), None, None, None]

    def run():
        import jax

        pres, parts = planner.grouped_partials(
            aggs, [(jax.numpy.asarray(v), jax.numpy.asarray(m)) for v, m in inputs],
            jax.numpy.asarray(mask), jax.numpy.asarray(codes), g, vranges,
        )
        return np.asarray(pres), [{f: np.asarray(a) for f, a in p.items()} for p in parts]

    monkeypatch.setattr(segmented, "accum_policy", lambda: "wide")
    pres_w, parts_w = run()
    monkeypatch.setattr(segmented, "accum_policy", lambda: "chunked32")
    pres_c, parts_c = run()

    assert np.array_equal(pres_w, pres_c)
    for pw, pc in zip(parts_w, parts_c):
        for f in pw:
            if f in ("count",):
                assert np.array_equal(pw[f], pc[f]), f
            else:
                # float fields ride f32 accumulation (documented policy);
                # cancellation near zero needs an absolute term
                np.testing.assert_allclose(pc[f], pw[f], rtol=1e-4, atol=1e-2, err_msg=f)
    # integer sums are bit-exact through the limb path
    np.testing.assert_array_equal(parts_c[1]["sum"], parts_w[1]["sum"])


def test_distinctcount_misaligned_dictionaries():
    """Exact DISTINCTCOUNT across segments with different string dictionaries
    unions decoded value sets instead of erroring (ADVICE finding 5)."""
    schema = Schema("d", [FieldSpec("name", DataType.STRING)])
    engine = QueryEngine()
    engine.register_table(schema, TableConfig("d"))
    a = {"name": np.asarray(["a", "b", "c", "a"], dtype=object)}
    b = {"name": np.asarray(["c", "d", "e", "f", "d"], dtype=object)}
    engine.add_segment("d", build_segment(schema, a, "d0"))
    engine.add_segment("d", build_segment(schema, b, "d1"))
    got = engine.query("SELECT DISTINCTCOUNT(name) FROM d")
    assert got.rows[0][0] == 6  # a b c d e f
