"""Pallas fused filter→group-by scan (single-chip throughput push).

Exactness contract: the Pallas kernel (run here in interpret mode — tier-1
is JAX_PLATFORMS=cpu) must match the XLA segmented path bit-for-bit for
every integer kind it claims (count / int_sum / int64_sum), including the
in-register word-mask and dict-code-predicate fusion and the row-padding
tail.  The engine-level tests prove plan-time routing: the same query
returns identical rows under backend=xla and backend=interpret, the
word-fused dense kernel really rides the range-index bitmap, the sparse
cross-launch merge happens ON DEVICE (trace spans), and the
double-buffered launch pipeline is deterministic across depths."""
import json

import numpy as np
import pytest

import jax.numpy as jnp

from pinot_tpu import ops
from pinot_tpu.ops import pallas_scan, segmented
from pinot_tpu.parallel.engine import DistributedEngine
from pinot_tpu.parallel.stacked import StackedTable
from pinot_tpu.spi.config import IndexingConfig, TableConfig
from pinot_tpu.spi.schema import DataType, FieldRole, FieldSpec, Schema
from pinot_tpu.sql.parser import parse_query


pytestmark = pytest.mark.skipif(
    not pallas_scan._HAS_PALLAS, reason="jax.experimental.pallas unavailable"
)


def _reference(entries, codes, num_groups):
    return [
        np.asarray(segmented._entry_fallback(k, v, m, codes, num_groups), np.float64)
        for k, v, m, _ in entries
    ]


def _entries(rng, n):
    """One entry per supported kind, with signs and widths that exercise
    every limb column (int8 negative, int32 full range, int64 past int32).
    int64 magnitudes stay under 2^39 so worst-case group sums remain inside
    the f64 integer-exact window — the same output contract as the XLA
    path, whose tables are also f64."""
    m = lambda: rng.random(n) < 0.8
    return [
        ("count", jnp.zeros((n,), jnp.int32), jnp.asarray(m()), None),
        ("int_sum", jnp.asarray(rng.integers(-120, 120, n).astype(np.int8)), jnp.asarray(m()), (1, True)),
        ("int_sum", jnp.asarray(rng.integers(-(2**31), 2**31 - 1, n).astype(np.int32)), jnp.asarray(m()), (4, True)),
        ("int64_sum", jnp.asarray(rng.integers(-(2**39), 2**39, n).astype(np.int64)), jnp.asarray(m()), None),
    ]


@pytest.mark.parametrize("n", [32, 4096, 4096 * 2 + 32, 1000])  # 1000: pad tail
@pytest.mark.parametrize("num_groups", [1, 7, 300])
def test_exactness_vs_xla(rng, n, num_groups):
    entries = _entries(rng, n)
    codes = jnp.asarray(rng.integers(0, num_groups, n).astype(np.int32))
    got = pallas_scan.fused_group_tables_pallas(
        entries, codes, num_groups, interpret=True
    )
    ref = _reference(entries, codes, num_groups)
    for g, r in zip(got, ref):
        np.testing.assert_array_equal(np.asarray(g), r)


def test_word_mask_and_code_pred_fusion(rng):
    """Packed bitmap words + dict-code range predicate, fused in-register,
    must equal the same filter applied as an unpacked row mask."""
    n = 4096 * 3 + 32
    entries = _entries(rng, n)
    codes = jnp.asarray(rng.integers(0, 50, n).astype(np.int32))
    bits = rng.random(n) < 0.5
    words = jnp.asarray(
        np.packbits(bits.reshape(-1, 32), axis=1, bitorder="little")
        .view(np.uint32)
        .reshape(-1)
    )
    lo, hi = 10, 40
    got = pallas_scan.fused_group_tables_pallas(
        entries, codes, 50, mask_words=words, code_pred=(codes, lo, hi), interpret=True
    )
    unpacked = np.asarray(segmented.unpack_bitmap_words(words, n))
    pred = (np.asarray(codes) >= lo) & (np.asarray(codes) < hi)
    ref_entries = [
        (k, v, jnp.asarray(np.asarray(m) & unpacked & pred), lp)
        for k, v, m, lp in entries
    ]
    ref = _reference(ref_entries, codes, 50)
    for g, r in zip(got, ref):
        np.testing.assert_array_equal(np.asarray(g), r)


def test_word_mask_requires_alignment(rng):
    n = 40  # not a multiple of 32
    entries = [("count", jnp.zeros((n,), jnp.int32), jnp.ones((n,), bool), None)]
    with pytest.raises(ValueError):
        pallas_scan.fused_group_tables_pallas(
            entries,
            jnp.zeros((n,), jnp.int32),
            4,
            mask_words=jnp.zeros((2,), jnp.uint32),
            interpret=True,
        )


def test_pallas_supported_gates():
    n = 64
    count = ("count", jnp.zeros((n,), jnp.int32), jnp.ones((n,), bool), None)
    fsum = ("f32_sum", jnp.zeros((n,), jnp.float32), jnp.ones((n,), bool), None)
    assert pallas_scan.pallas_supported([count], 16)
    assert not pallas_scan.pallas_supported([count, fsum], 16)  # float kind
    assert not pallas_scan.pallas_supported([count], 0)
    assert not pallas_scan.pallas_supported([count], segmented._MATMUL_MAX_GROUPS + 1)
    wide = ("int_sum", jnp.zeros((n,), jnp.int64), jnp.ones((n,), bool), None)
    assert not pallas_scan.pallas_supported([wide], 16)  # int_sum must be <=4 bytes


def test_scan_backend_env(monkeypatch):
    monkeypatch.setenv("PINOT_TPU_SCAN_BACKEND", "interpret")
    ops.scan_backend.cache_clear()
    assert ops.scan_backend() == "interpret"
    monkeypatch.setenv("PINOT_TPU_SCAN_BACKEND", "pallas")
    ops.scan_backend.cache_clear()
    assert ops.scan_backend() == "pallas"
    monkeypatch.delenv("PINOT_TPU_SCAN_BACKEND")
    ops.scan_backend.cache_clear()
    assert ops.scan_backend() == "xla"  # CPU default: Pallas only on TPU
    ops.scan_backend.cache_clear()


def test_merge_sparse_tables_folds_duplicates():
    E = int(pallas_scan.SPARSE_EMPTY_KEY)
    uniq = jnp.asarray(np.array([5, 2, E, 2, 9, E, 5, 1], np.int64))
    s = jnp.asarray(np.array([10, 1, 0, 2, 7, 0, 5, 3], np.float64))
    c = jnp.asarray(np.array([2, 1, 0, 1, 1, 0, 1, 1], np.float64))
    keys, tables = pallas_scan.merge_sparse_tables(
        uniq, [{"sum": s, "count": c}], 8, [{"sum": "add", "count": "add"}]
    )
    keys, t = np.asarray(keys), {f: np.asarray(v) for f, v in tables[0].items()}
    present = keys != E
    assert list(keys[present]) == [1, 2, 5, 9]
    np.testing.assert_array_equal(t["sum"][present], [3, 3, 15, 7])
    np.testing.assert_array_equal(t["count"][present], [1, 2, 3, 1])


def test_merge_sparse_tables_min_max_identities():
    """Empty slots must not poison MIN/MAX (identity padding on device)."""
    E = int(pallas_scan.SPARSE_EMPTY_KEY)
    uniq = jnp.asarray(np.array([3, E, 3, 7], np.int64))
    mn = jnp.asarray(np.array([4.0, 0.0, -2.0, 9.0]))
    mx = jnp.asarray(np.array([4.0, 0.0, -2.0, 9.0]))
    c = jnp.asarray(np.array([1.0, 0.0, 1.0, 1.0]))
    keys, tables = pallas_scan.merge_sparse_tables(
        uniq, [{"min": mn, "max": mx, "count": c}], 4,
        [{"min": "min", "max": "max", "count": "add"}],
    )
    keys, t = np.asarray(keys), {f: np.asarray(v) for f, v in tables[0].items()}
    present = keys != E
    assert list(keys[present]) == [3, 7]
    np.testing.assert_array_equal(t["min"][present], [-2.0, 9.0])
    np.testing.assert_array_equal(t["max"][present], [4.0, 9.0])


def test_merge_sparse_tables_order_trim():
    """ORDER BY sum DESC LIMIT 2 keeps the top-2 groups, emitted in
    ascending key order (executor decode contract)."""
    E = int(pallas_scan.SPARSE_EMPTY_KEY)
    uniq = jnp.asarray(np.array([5, 2, E, 2, 9, E, 5, 1], np.int64))
    s = jnp.asarray(np.array([10, 1, 0, 2, 7, 0, 5, 3], np.float64))
    c = jnp.asarray(np.array([2, 1, 0, 1, 1, 0, 1, 1], np.float64))
    keys, tables = pallas_scan.merge_sparse_tables(
        uniq, [{"sum": s, "count": c}], 2,
        [{"sum": "add", "count": "add"}], order_spec=(0, "sum", False),
    )
    keys, t = np.asarray(keys), {f: np.asarray(v) for f, v in tables[0].items()}
    assert list(keys) == [5, 9]  # sums 15 and 7: the DESC top-2, key-ascending
    np.testing.assert_array_equal(t["sum"], [15, 7])


# ---------------------------------------------------------------------------
# engine-level routing
# ---------------------------------------------------------------------------

N = 1245 * 8


def _bench_shaped_table(eng, *, seed=3):
    """Mirror bench.py's lineorder: dict-encoded filter column with a range
    index, so the whole WHERE compiles to one plain bitmap and the dense
    kernel takes the word-fused path."""
    rng = np.random.default_rng(seed)
    schema = Schema(
        "lineorder",
        [
            FieldSpec("lo_orderdate", DataType.INT),
            FieldSpec("lo_quantity", DataType.INT),
            FieldSpec("g", DataType.STRING),
            FieldSpec("lo_revenue", DataType.LONG, role=FieldRole.METRIC),
        ],
    )
    data = {
        "lo_orderdate": (19920101 + rng.integers(0, 37, N)).astype(np.int32),
        "lo_quantity": rng.integers(1, 51, N).astype(np.int32),
        "g": np.asarray([f"g{i}" for i in rng.integers(0, 7, N)]),
        "lo_revenue": rng.integers(-(10**9), 10**9, N).astype(np.int64),
    }
    cfg = TableConfig(
        "lineorder", indexing=IndexingConfig(range_index_columns=["lo_quantity"])
    )
    eng.register_table(
        "lineorder",
        StackedTable.build(schema, data, eng.num_devices, table_config=cfg),
    )
    return data


DENSE_Q = (
    "SELECT lo_orderdate, SUM(lo_revenue), COUNT(*) FROM lineorder "
    "WHERE lo_quantity < 25 GROUP BY lo_orderdate LIMIT 2500"
)


def _with_backend(monkeypatch, backend, **eng_kwargs):
    monkeypatch.setenv("PINOT_TPU_SCAN_BACKEND", backend)
    ops.scan_backend.cache_clear()
    eng = DistributedEngine(**eng_kwargs)
    _bench_shaped_table(eng)
    return eng


@pytest.fixture(autouse=True)
def _reset_backend_cache():
    yield
    ops.scan_backend.cache_clear()


def test_engine_word_fused_dense_routing(monkeypatch):
    """The bench query rides the range-index bitmap on both backends and
    returns identical rows, exact vs a pure-numpy reference."""
    rows = {}
    for be in ("xla", "interpret"):
        eng = _with_backend(monkeypatch, be)
        ctx = parse_query(DENSE_Q)
        plan = eng._plan(ctx, eng.tables["lineorder"])
        assert plan.row_sharded_params, "filter must ship bitmap words"
        r = eng.execute(ctx)
        assert ("lo_quantity", "range") in list(r.stats.filter_index_uses)
        rows[be] = r.rows
    assert rows["xla"] == rows["interpret"]

    data = _bench_shaped_table(DistributedEngine())  # same seed: same rows
    mask = data["lo_quantity"] < 25
    ref = {}
    for d, rv in zip(data["lo_orderdate"][mask], data["lo_revenue"][mask]):
        s, c = ref.get(int(d), (0, 0))
        ref[int(d)] = (s + int(rv), c + 1)
    got = {int(r[0]): (int(r[1]), int(r[2])) for r in rows["interpret"]}
    assert got == ref


def test_engine_backend_in_plan_cache_key(monkeypatch):
    """Switching backend must not reuse a plan traced for the other one."""
    eng = _with_backend(monkeypatch, "xla")
    ctx = parse_query(DENSE_Q)
    p_xla = eng._plan(ctx, eng.tables["lineorder"])
    monkeypatch.setenv("PINOT_TPU_SCAN_BACKEND", "interpret")
    ops.scan_backend.cache_clear()
    p_int = eng._plan(ctx, eng.tables["lineorder"])
    assert p_xla is not p_int


# maxDenseGroups=2 forces the sparse (fixed-slot hash table) plan at low
# cardinality, same idiom as test_sparse_groupby.py
SPARSE_Q = (
    "SET maxDenseGroups = 2; SELECT g, SUM(lo_revenue), COUNT(*) FROM lineorder "
    "GROUP BY g ORDER BY g LIMIT 10"
)
SPARSE_ORDER_Q = (
    "SET maxDenseGroups = 2; SELECT g, SUM(lo_revenue) FROM lineorder GROUP BY g "
    "ORDER BY SUM(lo_revenue) DESC LIMIT 3"
)


@pytest.mark.parametrize("query", [SPARSE_Q, SPARSE_ORDER_Q])
def test_sparse_merge_on_device_across_batches(query):
    """Macro-batched sparse group-by combines partial tables in-graph: the
    trace shows a device merge span and NO host merge, and rows match the
    single-launch engine exactly (including the ORDER BY ... LIMIT trim)."""
    base = DistributedEngine()
    _bench_shaped_table(base)
    eng = DistributedEngine(launch_bytes=4096)  # force several launches
    _bench_shaped_table(eng)

    ctx = parse_query(query)
    plan = eng._plan(ctx, eng.tables["lineorder"])
    assert plan.kind == "groupby_sparse"
    assert plan.sparse_merge_fn is not None
    assert len(plan.batch_offsets) >= 2, "budget must force macro-batching"

    ctx.options["trace"] = True
    r = eng.execute(ctx)
    spans = json.dumps(r.stats.trace)
    assert "sparse_merge:device" in spans
    assert "sparse_merge:host" not in spans
    assert r.rows == base.query(query).rows


def test_pipeline_depth_determinism(monkeypatch):
    """Double-buffered launches (depth>1) must be byte-identical to the
    sequential depth-1 schedule for every query kind."""
    rows = {}
    for depth in (1, 3):
        monkeypatch.setenv("PINOT_TPU_PIPELINE_DEPTH", str(depth))
        eng = DistributedEngine(launch_bytes=4096)
        assert eng.pipeline_depth == depth
        _bench_shaped_table(eng)
        rows[depth] = [eng.query(q).rows for q in (DENSE_Q, SPARSE_Q, SPARSE_ORDER_Q)]
    assert rows[1] == rows[3]
