"""Resource-lifecycle passes (pinot_tpu.analysis.lifecycle).

W023 (paired-resource escape analysis) and W024 (condition-variable
discipline), each over minimal seeded-bug fixtures plus clean-negative
counterparts — the test style the race-detector suite established: a
rule earns its place by firing on the bug and staying quiet on the
idiomatic fix AND on every sanctioned ownership-transfer shape."""
import textwrap

from pinot_tpu.analysis.engine import Project, run_passes
from pinot_tpu.analysis.lifecycle import ConditionDisciplinePass, LifecyclePass


def _findings(src, pass_cls=LifecyclePass, **extra):
    files = {"pkg/m.py": textwrap.dedent(src)}
    for name, body in extra.items():
        files[f"pkg/{name}.py"] = textwrap.dedent(body)
    proj = Project.from_sources(files)
    return run_passes(proj, [pass_cls()])


def _rules(src, **kw):
    return [f.rule for f in _findings(src, **kw)]


class TestW023PairedResources:
    def test_flags_reservation_never_released(self):
        src = """
        def admit(budget, n):
            ticket = budget.reserve(n)
            do_work(n)
        """
        found = _findings(src)
        assert [f.rule for f in found] == ["W023"]
        assert "never repays" in found[0].message
        assert found[0].symbol == "admit"

    def test_flags_straight_line_release(self):
        src = """
        def admit(budget, n):
            ticket = budget.reserve(n)
            risky(n)
            budget.release(ticket)
        """
        found = _findings(src)
        assert [f.rule for f in found] == ["W023"]
        assert "straight-line" in found[0].message
        assert "finally" in found[0].hint

    def test_quiet_when_released_in_finally(self):
        src = """
        def admit(budget, n):
            ticket = budget.reserve(n)
            try:
                risky(n)
            finally:
                budget.release(ticket)
        """
        assert _rules(src) == []

    def test_quiet_when_unwound_in_except_handler(self):
        src = """
        def admit(budget, n):
            ticket = budget.reserve(n)
            try:
                risky(n)
            except Exception:
                budget.release(ticket)
                raise
            budget.release(ticket)
        """
        assert _rules(src) == []

    def test_quiet_when_handle_is_returned(self):
        src = """
        def admit(budget, n):
            return budget.reserve(n)
        """
        assert _rules(src) == []

    def test_quiet_when_handle_passes_to_a_new_owner(self):
        src = """
        def admit(self, budget, qid):
            ticket = budget.reserve(1)
            return Grant(self, qid, ticket)
        """
        assert _rules(src) == []

    def test_quiet_when_handle_stored_on_self(self):
        src = """
        class Holder:
            def open(self, budget):
                self.ticket = budget.reserve(1)
        """
        assert _rules(src) == []

    def test_quiet_when_finally_closes_interprocedurally(self):
        src = """
        class Hedger:
            def go(self, hc):
                hc.try_fire(1)
                try:
                    work()
                finally:
                    self._cleanup(hc)

            def _cleanup(self, hc):
                hc.unfire()
        """
        assert _rules(src) == []

    def test_quiet_inside_the_ledger_implementation_itself(self):
        # reserve_or_wait retrying reserve / release notifying is the
        # protocol's implementation, not a leaky client
        src = """
        class Budget:
            def reserve(self, n):
                self._in_use += n
                return 1

            def reserve_or_wait(self, n):
                while True:
                    t = self.reserve(n)
                    if t:
                        return t

            def release(self, t):
                self._in_use -= 1
        """
        assert _rules(src) == []

    def test_receiver_hint_scopes_generic_verbs(self):
        # `register` only binds watchdog-ish receivers; a cursor registry
        # with no deregister is not a lifecycle bug
        src = """
        def track(cursors, qid):
            cursors.register(qid)
        """
        assert _rules(src) == []
        src = """
        def track(self, qid):
            self.watchdog.register(qid)
        """
        assert _rules(src) == ["W023"]


class TestW024ConditionDiscipline:
    def test_flags_wait_outside_while(self):
        src = """
        import threading

        class Q:
            def __init__(self):
                self._cv = threading.Condition()
                self._items = []

            def get(self):
                with self._cv:
                    if not self._items:
                        self._cv.wait(timeout=1.0)
                    return self._items.pop()
        """
        found = _findings(src, pass_cls=ConditionDisciplinePass)
        assert [f.rule for f in found] == ["W024"]
        assert "while" in found[0].message
        assert found[0].symbol == "Q.get"

    def test_quiet_wait_inside_while(self):
        src = """
        import threading

        class Q:
            def __init__(self):
                self._cv = threading.Condition()
                self._items = []

            def get(self):
                with self._cv:
                    while not self._items:
                        self._cv.wait(timeout=1.0)
                    return self._items.pop()
        """
        assert _rules(src, pass_cls=ConditionDisciplinePass) == []

    def test_flags_notify_without_lock(self):
        src = """
        import threading

        class Q:
            def __init__(self):
                self._cv = threading.Condition()
                self._items = []

            def put(self, v):
                self._items.append(v)
                self._cv.notify_all()
        """
        found = _findings(src, pass_cls=ConditionDisciplinePass)
        assert [f.rule for f in found] == ["W024"]
        assert "lost wakeup" in found[0].message

    def test_quiet_notify_under_the_lock(self):
        src = """
        import threading

        class Q:
            def __init__(self):
                self._cv = threading.Condition()
                self._items = []

            def put(self, v):
                with self._cv:
                    self._items.append(v)
                    self._cv.notify_all()
        """
        assert _rules(src, pass_cls=ConditionDisciplinePass) == []

    def test_covers_the_injected_provider_ctor(self):
        # the seam (utils/threads.py) is what production classes use now
        src = """
        from pinot_tpu.utils import threads

        class Q:
            def __init__(self):
                self._cv = threads.Condition()
                self._n = 0

            def bump(self):
                self._n += 1
                self._cv.notify()
        """
        assert _rules(src, pass_cls=ConditionDisciplinePass) == ["W024"]
