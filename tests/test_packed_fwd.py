"""Bit-packed forward indexes (segment/packing.py): lane-width selection,
pack/unpack round-trips (numpy and trace-level), segment build→save→load
parity across lane widths and boundary cardinalities, device shipping of
packed words, the stacked-table twin, and the pre-packing backward-compat
path."""
import dataclasses

import numpy as np
import pytest

from pinot_tpu.segment import packing
from pinot_tpu.segment.builder import build_segment
from pinot_tpu.segment.segment import BUILDER_VERSION, ImmutableSegment
from pinot_tpu.spi.schema import DataType, FieldRole, FieldSpec, Schema


def _dict_schema(nullable=False):
    return Schema(
        "t",
        [
            FieldSpec("k", DataType.STRING, nullable=nullable),
            FieldSpec("v", DataType.LONG, role=FieldRole.METRIC),
        ],
    )


def _dict_data(n, card, seed=0, null_rate=0.0):
    assert n >= card
    rng = np.random.default_rng(seed)
    # every dictionary id appears at least once: boundary-cardinality tests
    # need the EXACT cardinality, not a random subset
    ids = np.concatenate([np.arange(card), rng.integers(0, card, n - card)])
    rng.shuffle(ids)
    vals = np.array([f"k{i:06d}" for i in ids], dtype=object)
    if null_rate:
        vals[rng.random(n) < null_rate] = None
    return {"k": vals, "v": rng.integers(0, 1000, n)}


class TestLaneSelection:
    @pytest.mark.parametrize(
        "card,bits",
        [(1, 4), (16, 4), (17, 8), (256, 8), (257, 16), (65536, 16), (65537, 32)],
    )
    def test_boundary_cardinalities(self, card, bits):
        assert packing.lane_bits(card) == bits


class TestPackRoundTrip:
    @pytest.mark.parametrize("bits", [4, 8, 16])
    @pytest.mark.parametrize("n", [1, 7, 32, 1000])  # incl. tail-word cases
    def test_numpy_round_trip(self, bits, n):
        rng = np.random.default_rng(bits * 1000 + n)
        codes = rng.integers(0, 1 << bits, n).astype(np.uint32)
        words = packing.pack_codes(codes, bits)
        assert words.dtype == np.uint32
        assert words.shape[0] == -(-n // (32 // bits))
        np.testing.assert_array_equal(packing.unpack_codes(words, bits, n), codes)

    @pytest.mark.parametrize("bits", [4, 8, 16])
    def test_jnp_unpack_matches_numpy(self, bits):
        import jax.numpy as jnp

        rng = np.random.default_rng(bits)
        n = 999
        codes = rng.integers(0, 1 << bits, n).astype(np.uint32)
        words = packing.pack_codes(codes, bits)
        got = np.asarray(packing.unpack_codes_jnp(jnp.asarray(words), bits, n))
        np.testing.assert_array_equal(got, codes.astype(np.int32))

    def test_jnp_unpack_last_axis_2d(self):
        """Stacked [S, W] layouts unpack along the last axis."""
        import jax.numpy as jnp

        rng = np.random.default_rng(3)
        codes = rng.integers(0, 16, 128).astype(np.uint32)
        words = packing.pack_codes(codes, 4).reshape(2, 8)
        got = np.asarray(packing.unpack_codes_jnp(jnp.asarray(words), 4, 64))
        np.testing.assert_array_equal(got, codes.reshape(2, 64))

    def test_rejects_unsupported_width(self):
        with pytest.raises(ValueError):
            packing.pack_codes(np.zeros(4, np.uint32), 5)
        with pytest.raises(ValueError):
            packing.unpack_codes(np.zeros(1, np.uint32), 3, 4)


class TestSegmentRoundTrip:
    @pytest.mark.parametrize(
        "card,bits",
        [(3, 4), (16, 4), (17, 8), (256, 8), (257, 16), (40000, 16)],
    )
    def test_build_save_load_parity(self, tmp_path, card, bits):
        n = max(card * 2, 500)
        schema, data = _dict_schema(), _dict_data(n, card, seed=card)
        seg = build_segment(schema, data, "s0", output_dir=str(tmp_path / "s0"))
        c = seg.column("k")
        assert c.code_bits == (bits if bits < 32 else None)
        assert c.packed is not None and c.packed.dtype == np.uint32
        loaded = ImmutableSegment.load(str(tmp_path / "s0"), verify=True)
        lc = loaded.column("k")
        assert lc.code_bits == c.code_bits
        np.testing.assert_array_equal(lc.codes, c.codes)
        np.testing.assert_array_equal(lc.packed, c.packed)
        np.testing.assert_array_equal(lc.decoded(), seg.column("k").decoded())

    def test_wide_dictionary_stays_unpacked(self, tmp_path):
        n, card = 140_000, 70_000  # needs >16 bits -> raw storage
        schema, data = _dict_schema(), _dict_data(n, card, seed=9)
        seg = build_segment(schema, data, "s0", output_dir=str(tmp_path / "s0"))
        c = seg.column("k")
        assert c.code_bits is None and c.packed is None
        loaded = ImmutableSegment.load(str(tmp_path / "s0"), verify=True)
        assert loaded.column("k").code_bits is None
        np.testing.assert_array_equal(loaded.column("k").codes, c.codes)

    def test_nullable_dict_column_round_trip(self, tmp_path):
        schema = _dict_schema(nullable=True)
        data = _dict_data(800, 20, seed=5, null_rate=0.15)
        seg = build_segment(schema, data, "s0", output_dir=str(tmp_path / "s0"))
        c = seg.column("k")
        assert c.code_bits == 8 and c.nulls is not None and c.nulls.sum() > 0
        loaded = ImmutableSegment.load(str(tmp_path / "s0"), verify=True)
        lc = loaded.column("k")
        np.testing.assert_array_equal(lc.nulls, c.nulls)
        np.testing.assert_array_equal(lc.codes, c.codes)
        np.testing.assert_array_equal(lc.packed, c.packed)

    def test_builder_version_stamped(self, tmp_path):
        from pinot_tpu.segment import store

        schema, data = _dict_schema(), _dict_data(200, 10)
        build_segment(schema, data, "s0", output_dir=str(tmp_path / "s0"))
        meta, _ = store.read_segment(str(tmp_path / "s0"))
        assert meta["builderVersion"] == BUILDER_VERSION == 2

    def test_pre_packing_segment_loads_via_raw_path(self, tmp_path):
        """A segment written before packing (no codeBits in column meta)
        must load and decode unchanged through the raw forward index."""
        schema, data = _dict_schema(), _dict_data(300, 10, seed=7)
        seg = build_segment(schema, data, "s0")
        # simulate the v1 builder: strip packing before save -> the .fwd
        # region holds raw codes and col meta carries no codeBits
        seg.columns["k"] = dataclasses.replace(
            seg.columns["k"], code_bits=None, packed=None
        )
        seg.save(str(tmp_path / "s0"))
        from pinot_tpu.segment import store

        meta, _ = store.read_segment(str(tmp_path / "s0"))
        km = meta["columns"][list(seg.columns).index("k")]  # positional meta
        assert "codeBits" not in km
        loaded = ImmutableSegment.load(str(tmp_path / "s0"), verify=True)
        lc = loaded.column("k")
        assert lc.code_bits is None and lc.packed is None
        np.testing.assert_array_equal(lc.decoded(), seg.column("k").decoded())


class TestDeviceShipping:
    def test_to_device_packed_opt_in(self):
        import jax

        schema, data = _dict_schema(), _dict_data(400, 10)
        seg = build_segment(schema, data, "s0")
        plain = seg.to_device(columns=["k"])
        assert "codes" in plain["k"] and "codes_packed" not in plain["k"]
        packed = seg.to_device(columns=["k"], packed_codes=True)
        assert "codes_packed" in packed["k"] and "codes" not in packed["k"]
        w = np.asarray(jax.device_get(packed["k"]["codes_packed"]))
        np.testing.assert_array_equal(
            packing.unpack_codes(w, seg.column("k").code_bits, seg.num_docs),
            np.asarray(seg.column("k").codes, dtype=np.uint32),
        )

    def test_plain_and_packed_entries_cached_separately(self):
        schema, data = _dict_schema(), _dict_data(100, 10)
        seg = build_segment(schema, data, "s0")
        a = seg.to_device(columns=["k"])["k"]
        b = seg.to_device(columns=["k"], packed_codes=True)["k"]
        assert a is not b
        assert seg.to_device(columns=["k"])["k"] is a  # cache hit per flavor
        assert seg.to_device(columns=["k"], packed_codes=True)["k"] is b


class TestStackedPacking:
    def _stacked(self, n=2000, card=10, shards=8):
        from pinot_tpu.parallel.stacked import StackedTable

        schema, data = _dict_schema(), _dict_data(n, card, seed=1)
        return StackedTable.build(schema, data, shards)

    def test_build_packs_per_shard(self):
        st = self._stacked()
        c = st.columns["k"]
        assert c.code_bits == 4
        S, D = c.codes.shape
        assert c.packed.shape == (S, D * 4 // 32)
        for s in range(S):
            np.testing.assert_array_equal(
                packing.unpack_codes(c.packed[s], 4, D),
                c.codes[s].astype(np.uint32),
            )

    def test_signature_keys_on_code_bits(self):
        st = self._stacked()
        sig_packed = st.signature()
        st.columns["k"] = dataclasses.replace(
            st.columns["k"], code_bits=None, packed=None
        )
        assert st.signature() != sig_packed

    def test_to_device_packed_with_doc_slice(self):
        import jax

        st = self._stacked()
        D = st.docs_per_shard
        lo, hi = 32, D  # 32-aligned slice, as _batching produces
        cols, _ = st.to_device(
            columns=["k"], doc_slice=(lo, hi), packed_codes=True, with_valid=False
        )
        w = np.asarray(jax.device_get(cols["k"]["codes_packed"]))
        assert w.shape == (st.num_shards, (hi - lo) * 4 // 32)
        for s in range(st.num_shards):
            np.testing.assert_array_equal(
                packing.unpack_codes(w[s], 4, hi - lo),
                st.columns["k"].codes[s, lo:hi].astype(np.uint32),
            )
