"""Real-TPU correctness assertions (round-2 verdict weak #8: exact-limb and
bitmap claims were never asserted on the actual accelerator).

The session conftest pins tests to the virtual CPU mesh, so these run the
kernels in a SUBPROCESS that inherits the ambient JAX platform (the axon
TPU relay when present) and skip when no accelerator is reachable.  One
subprocess runs all assertions to pay the compile latency once.
"""
import json
import os
import subprocess
import sys

import pytest

_PROBE = """
import jax, json
devs = jax.devices()
print(json.dumps({"platform": devs[0].platform, "n": len(devs)}))
"""

_ASSERTIONS = r"""
import json
import numpy as np
import jax
import jax.numpy as jnp
import pinot_tpu  # enables x64
from pinot_tpu import ops
from pinot_tpu.query import planner
from pinot_tpu.query.functions import get_agg_function

rng = np.random.default_rng(7)
n, G = 200_000, 64
out = {}

# 1. chunked32 exact-limb grouped int SUM: bit-exact vs numpy int64
codes = jnp.asarray(rng.integers(0, G, n).astype(np.int32))
vals_np = rng.integers(-1_000_000, 1_000_000, n).astype(np.int32)
vals = jnp.asarray(vals_np)
mask = jnp.asarray(rng.random(n) < 0.7)
got = np.asarray(jax.device_get(jax.jit(lambda v, m, c: ops.group_sum(v, m, c, G))(vals, mask, codes)))
exp = np.zeros(G, dtype=np.int64)
np.add.at(exp, np.asarray(codes), np.where(np.asarray(mask), vals_np.astype(np.int64), 0))
assert np.array_equal(got.astype(np.int64), exp), "grouped int SUM not exact on this platform"
out["group_sum_exact"] = True

# 2. masked_sum exact-limb scalar path
got_s = float(jax.device_get(jax.jit(ops.masked_sum)(vals, mask)))
exp_s = float(np.where(np.asarray(mask), vals_np.astype(np.int64), 0).sum())
assert got_s == exp_s, (got_s, exp_s)
out["masked_sum_exact"] = True

# 3. bitmap word unpack: device bit math == numpy unpackbits
words_np = rng.integers(0, 2**32, 2048, dtype=np.uint64).astype(np.uint32)
def unpack(words, n_docs):
    docs = jnp.arange(n_docs, dtype=jnp.int32)
    w = words[docs >> 5]
    return ((w >> (docs & 31).astype(jnp.uint32)) & jnp.uint32(1)).astype(bool)
got_b = np.asarray(jax.device_get(jax.jit(unpack, static_argnums=1)(jnp.asarray(words_np), 2048*32)))
exp_b = np.unpackbits(words_np.view(np.uint8), bitorder="little").astype(bool)
assert np.array_equal(got_b, exp_b), "bitmap unpack mismatch"
out["bitmap_unpack_exact"] = True

# 4. wide-range int64 grouped SUM: signed-magnitude limb path bit-exact
# (|v| < 2^35 exceeds int32 but keeps sum(|v|) < 2^53 over 200k rows)
vals64_np = rng.integers(-(1 << 35), 1 << 35, n, dtype=np.int64)
vals64 = jnp.asarray(vals64_np)
got64 = np.asarray(jax.device_get(jax.jit(lambda v, m, c: ops.group_sum(v, m, c, G))(vals64, mask, codes)))
exp64 = np.zeros(G, dtype=np.int64)
np.add.at(exp64, np.asarray(codes), np.where(np.asarray(mask), vals64_np, 0))
assert np.array_equal(got64.astype(np.int64), exp64), "wide int64 grouped SUM not exact"
out["group_sum64_exact"] = True

# 4b. same through the fused scan, and the scalar masked_sum
[t64] = jax.device_get(jax.jit(
    lambda v, m, c: ops.fused_group_tables([("int64_sum", v, m, 8)], c, G)
)(vals64, mask, codes))
assert np.array_equal(np.asarray(t64).astype(np.int64), exp64), "fused int64 SUM not exact"
got64_s = float(jax.device_get(jax.jit(ops.masked_sum)(vals64, mask)))
assert got64_s == float(np.where(np.asarray(mask), vals64_np, 0).sum()), "masked int64 SUM not exact"
out["fused_sum64_exact"] = True

# 4c. the two's-complement catastrophe guard: a column of -1s
neg1 = jnp.full((n,), -1, jnp.int64)
gneg = np.asarray(jax.device_get(jax.jit(lambda v, c: ops.group_sum(v, jnp.ones((n,), bool), c, G))(neg1, codes)))
expneg = np.zeros(G, dtype=np.int64)
np.add.at(expneg, np.asarray(codes), -1)
assert np.array_equal(gneg.astype(np.int64), expneg), "all -1 int64 SUM not exact"
out["sum64_neg_exact"] = True

# 5. sparse group-by sort kernel: tables match a host groupby
key_np = rng.integers(0, 5000, n).astype(np.int64)
sum_fn = get_agg_function("sum")
def sparse(vals, mask, key):
    return planner.sparse_grouped_tables([sum_fn], [(vals, mask)], mask, key, 6000)
uniq, partials = jax.device_get(jax.jit(sparse)(vals.astype(jnp.float64), mask, jnp.asarray(key_np)))
uniq = np.asarray(uniq); present = uniq != planner.SPARSE_EMPTY_KEY
hsum = {}
for k, v, m in zip(key_np, vals_np, np.asarray(mask)):
    if m: hsum[k] = hsum.get(k, 0.0) + float(v)
got_map = {int(k): float(s) for k, s in zip(uniq[present], np.asarray(partials[0]["sum"])[present])}
assert got_map == hsum, "sparse group tables mismatch"
out["sparse_groupby_exact"] = True

# 6. distributed engine on the ambient device: range-index WORD SLICING
# through forced MACRO-BATCHED launches (round 5) — end-to-end vs numpy
from pinot_tpu.parallel.engine import DistributedEngine
from pinot_tpu.parallel.stacked import StackedTable
from pinot_tpu.spi.config import IndexingConfig, TableConfig
from pinot_tpu.spi.schema import DataType, FieldRole, FieldSpec, Schema
from pinot_tpu.sql.parser import parse_query

n2 = 1 << 16
schema = Schema("t", [
    FieldSpec("g", DataType.INT),
    FieldSpec("q", DataType.INT),
    FieldSpec("v", DataType.LONG, role=FieldRole.METRIC),
])
data = {
    "g": rng.integers(0, 50, n2).astype(np.int32),
    "q": rng.integers(0, 100, n2).astype(np.int32),
    "v": rng.integers(-10**9, 10**9, n2).astype(np.int64),
}
cfg = TableConfig("t", indexing=IndexingConfig(range_index_columns=["q"]))
eng = DistributedEngine(launch_bytes=n2 * 3)  # forces several launches
st = StackedTable.build(schema, dict(data), eng.num_devices, table_config=cfg)
eng.register_table("t", st)
ctx = parse_query("SELECT g, SUM(v), COUNT(*) FROM t WHERE q < 37 GROUP BY g ORDER BY g LIMIT 64")
plan = eng._plan(ctx, st)
assert len(plan.batch_offsets) >= 2, plan.batch_offsets
r = eng.execute(ctx)
assert ("q", "range") in r.stats.filter_index_uses
fm = data["q"] < 37
esum, ecnt = {}, {}
for g, v, mm in zip(data["g"], data["v"], fm):
    if mm:
        esum[g] = esum.get(g, 0) + int(v)
        ecnt[g] = ecnt.get(g, 0) + 1
got_rows = {int(a): (int(b), int(c)) for a, b, c in r.rows}
assert got_rows == {int(k): (esum[k], ecnt[k]) for k in esum}, "batched range group-by mismatch"
out["range_index_macro_batched_exact"] = True

# 7. sketches on the device: exact presence DISTINCTCOUNT + HLL tolerance
rdc = eng.query("SELECT DISTINCTCOUNT(g) FROM t")
assert int(rdc.rows[0][0]) == len(np.unique(data["g"])), "DISTINCTCOUNT mismatch"
true_v = len(np.unique(data["v"]))
rhll = eng.query("SELECT DISTINCTCOUNTHLL(v) FROM t")
assert abs(int(rhll.rows[0][0]) - true_v) / true_v < 0.1, "HLL estimate off"
out["sketches_on_device"] = True

# 8. MV explode GROUP BY on the device (single-node engine kernels)
from pinot_tpu.query.engine import QueryEngine
from pinot_tpu.segment.builder import build_segment

mv_schema = Schema("m", [
    FieldSpec("tags", DataType.STRING, single_value=False),
    FieldSpec("x", DataType.INT, role=FieldRole.METRIC),
])
tags_pool = np.asarray(["a", "b", "c", "d"])
mv_rows = np.empty(5000, dtype=object)
for i in range(5000):
    mv_rows[i] = list(rng.choice(tags_pool, int(rng.integers(0, 4))))
xs = rng.integers(0, 100, 5000).astype(np.int32)
qe = QueryEngine()
qe.register_table(mv_schema)
qe.add_segment("m", build_segment(mv_schema, {"tags": mv_rows, "x": xs}, "s0"))
rmv = qe.query("SELECT tags, COUNT(*), SUM(x) FROM m GROUP BY tags ORDER BY tags LIMIT 10")
emv = {}
for row_tags, x in zip(mv_rows, xs):
    for t in row_tags:
        c0, s0 = emv.get(t, (0, 0))
        emv[t] = (c0 + 1, s0 + int(x))
assert {a: (int(b), int(c)) for a, b, c in rmv.rows} == emv, "MV explode mismatch"
out["mv_explode_exact"] = True

print(json.dumps(out))
"""


def _run(code: str, timeout: int = 300):
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)  # inherit the ambient accelerator
    return subprocess.run(
        [sys.executable, "-c", code],
        capture_output=True,
        text=True,
        timeout=timeout,
        env=env,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    )


@pytest.fixture(scope="module")
def accelerator():
    try:
        probe = _run(_PROBE, timeout=120)
    except subprocess.TimeoutExpired:
        pytest.skip("platform probe timed out")
    if probe.returncode != 0:
        pytest.skip(f"no ambient JAX platform: {probe.stderr[-200:]}")
    info = json.loads(probe.stdout.strip().splitlines()[-1])
    if info["platform"] in ("cpu",):
        pytest.skip("no accelerator attached (ambient platform is cpu)")
    return info


def test_kernel_exactness_on_accelerator(accelerator):
    """chunked32 limb sums, bitmap unpack, the sparse sort kernel, macro-
    batched range-index queries, device sketches, and MV explode are
    correct ON THE REAL ACCELERATOR, not just the CPU mesh."""
    res = _run(_ASSERTIONS, timeout=1100)
    assert res.returncode == 0, f"TPU assertions failed:\n{res.stderr[-2000:]}"
    out = json.loads(res.stdout.strip().splitlines()[-1])
    assert out == {
        "group_sum_exact": True,
        "masked_sum_exact": True,
        "bitmap_unpack_exact": True,
        "group_sum64_exact": True,
        "fused_sum64_exact": True,
        "sum64_neg_exact": True,
        "sparse_groupby_exact": True,
        "range_index_macro_batched_exact": True,
        "sketches_on_device": True,
        "mv_explode_exact": True,
    }
