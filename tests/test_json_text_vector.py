"""JSON, text, and vector index tests.

Reference model: JsonMatchFilterOperator + JSON index flattening,
Lucene TEXT_MATCH, VectorSimilarityFilterOperator (HNSW -> exact brute-force
matmul here).
"""
import json

import numpy as np
import pytest

from pinot_tpu.indexes.jsonidx import JsonIndex, flatten_json
from pinot_tpu.indexes.text import TextIndex
from pinot_tpu.query.engine import QueryEngine
from pinot_tpu.segment.builder import build_segment
from pinot_tpu.segment.segment import ImmutableSegment
from pinot_tpu.spi.config import IndexingConfig, TableConfig
from pinot_tpu.spi.schema import DataType, FieldRole, FieldSpec, Schema

N = 3000


def _schema():
    return Schema(
        "docs",
        [
            FieldSpec("meta", DataType.JSON),
            FieldSpec("body", DataType.STRING),
            FieldSpec("embedding", DataType.FLOAT, single_value=False),
            FieldSpec("v", DataType.LONG, role=FieldRole.METRIC),
        ],
    )


def _config():
    return TableConfig(
        name="docs",
        indexing=IndexingConfig(
            json_index_columns=["meta"],
            text_index_columns=["body"],
            vector_index_columns=["embedding"],
        ),
    )


WORDS = ["quick", "brown", "fox", "lazy", "dog", "jumps", "search", "engine", "analytics"]


@pytest.fixture(scope="module")
def data():
    rng = np.random.default_rng(47)
    metas, bodies, embs = [], [], []
    for i in range(N):
        metas.append(
            json.dumps(
                {
                    "user": {"id": int(rng.integers(0, 50)), "tier": ["free", "pro", "ent"][int(rng.integers(0, 3))]},
                    "events": [{"kind": "click"}] * int(rng.integers(0, 3)),
                    "score": float(np.round(rng.random() * 10, 2)),
                }
            )
        )
        bodies.append(" ".join(rng.choice(WORDS, size=6)))
        embs.append(list(rng.normal(size=8).astype(float)))
    return {
        "meta": metas,
        "body": bodies,
        "embedding": embs,
        "v": rng.integers(0, 100, N),
    }


@pytest.fixture(scope="module")
def eng(data, tmp_path_factory):
    e = QueryEngine()
    e.register_table(_schema(), _config())
    seg = build_segment(_schema(), data, "s0", table_config=_config())
    path = str(tmp_path_factory.mktemp("jtv") / "s0")
    seg.save(path)  # indexes must survive persistence
    e.add_segment("docs", ImmutableSegment.load(path))
    return e


def _metas(data):
    return [json.loads(m) for m in data["meta"]]


class TestJsonIndex:
    def test_flatten(self):
        f = flatten_json({"a": {"b": 1}, "c": [{"d": "x"}, {"d": "y"}], "e": 2.5})
        assert f["$.a.b"] == [1]
        assert f["$.c[*].d"] == ["x", "y"]
        assert f["$.e"] == [2.5]

    def test_json_match_eq(self, eng, data):
        res = eng.query("SELECT COUNT(*) FROM docs WHERE JSON_MATCH(meta, '\"$.user.tier\" = ''pro''')")
        expected = sum(1 for m in _metas(data) if m["user"]["tier"] == "pro")
        assert res.rows[0][0] == expected
        assert ("meta", "json") in res.stats.filter_index_uses

    def test_json_match_numeric_range_and_and(self, eng, data):
        res = eng.query(
            "SELECT COUNT(*) FROM docs WHERE JSON_MATCH(meta, '\"$.score\" > 5 AND \"$.user.tier\" != ''free''')"
        )
        expected = sum(1 for m in _metas(data) if m["score"] > 5 and m["user"]["tier"] != "free")
        assert res.rows[0][0] == expected

    def test_json_match_exists(self, eng, data):
        res = eng.query("SELECT COUNT(*) FROM docs WHERE JSON_MATCH(meta, '\"$.events[*].kind\" IS NOT NULL')")
        expected = sum(1 for m in _metas(data) if m["events"])
        assert res.rows[0][0] == expected

    def test_json_extract_scalar_filter_and_groupby(self, eng, data):
        res = eng.query("SELECT COUNT(*) FROM docs WHERE JSON_EXTRACT_SCALAR(meta, '$.user.id', 'LONG') < 10")
        expected = sum(1 for m in _metas(data) if m["user"]["id"] < 10)
        assert res.rows[0][0] == expected
        res2 = eng.query(
            "SELECT JSON_EXTRACT_SCALAR(meta, '$.user.tier', 'STRING'), COUNT(*) FROM docs "
            "GROUP BY JSON_EXTRACT_SCALAR(meta, '$.user.tier', 'STRING') ORDER BY JSON_EXTRACT_SCALAR(meta, '$.user.tier', 'STRING')"
        )
        from collections import Counter

        expected2 = Counter(m["user"]["tier"] for m in _metas(data))
        assert {r[0]: r[1] for r in res2.rows} == dict(expected2)


class TestTextIndex:
    def test_term_and(self, eng, data):
        res = eng.query("SELECT COUNT(*) FROM docs WHERE TEXT_MATCH(body, 'quick fox')")
        expected = sum(1 for b in data["body"] if "quick" in b.split() and "fox" in b.split())
        assert res.rows[0][0] == expected
        assert ("body", "text") in res.stats.filter_index_uses

    def test_or_and_not(self, eng, data):
        res = eng.query("SELECT COUNT(*) FROM docs WHERE TEXT_MATCH(body, 'search engine OR analytics NOT lazy')")
        def match(b):
            toks = set(b.split())
            return ("search" in toks and "engine" in toks) or ("analytics" in toks and "lazy" not in toks)

        assert res.rows[0][0] == sum(1 for b in data["body"] if match(b))

    def test_phrase(self, eng, data):
        res = eng.query('SELECT COUNT(*) FROM docs WHERE TEXT_MATCH(body, \'"quick brown"\')')
        expected = sum(1 for b in data["body"] if "quick brown" in b)
        assert res.rows[0][0] == expected

    def test_prefix_wildcard(self, eng, data):
        res = eng.query("SELECT COUNT(*) FROM docs WHERE TEXT_MATCH(body, 'jump*')")
        expected = sum(1 for b in data["body"] if any(t.startswith("jump") for t in b.split()))
        assert res.rows[0][0] == expected

    def test_regex_term(self, eng, data):
        """/regex/ terms match over the token dictionary (FST-regex analog)."""
        got = eng.query("SELECT COUNT(*) FROM docs WHERE TEXT_MATCH(body, '/qu.ck/')").rows[0][0]
        want = sum(1 for b in data["body"] if "quick" in b.split())
        assert int(got) == want
        got2 = eng.query(
            "SELECT COUNT(*) FROM docs WHERE TEXT_MATCH(body, '/(fox|dog)/')"
        ).rows[0][0]
        want2 = sum(1 for b in data["body"] if {"fox", "dog"} & set(b.split()))
        assert int(got2) == want2

    def test_mid_token_wildcard(self, eng, data):
        got = eng.query("SELECT COUNT(*) FROM docs WHERE TEXT_MATCH(body, 'an*tics')").rows[0][0]
        want = sum(1 for b in data["body"] if "analytics" in b.split())
        assert int(got) == want
        got2 = eng.query("SELECT COUNT(*) FROM docs WHERE TEXT_MATCH(body, 'f?x')").rows[0][0]
        want2 = sum(1 for b in data["body"] if "fox" in b.split())
        assert int(got2) == want2

    def test_fuzzy_term(self, eng, data):
        # 'quickk'~1 matches 'quick' (one deletion)
        got = eng.query("SELECT COUNT(*) FROM docs WHERE TEXT_MATCH(body, 'quickk~1')").rows[0][0]
        want = sum(1 for b in data["body"] if "quick" in b.split())
        assert int(got) == want
        # default ~ distance is 2: 'analytcs' (1 deletion) matches analytics
        got2 = eng.query("SELECT COUNT(*) FROM docs WHERE TEXT_MATCH(body, 'analytcs~')").rows[0][0]
        want2 = sum(1 for b in data["body"] if "analytics" in b.split())
        assert int(got2) == want2
        # distance 1 does NOT match a 2-edit-away token ('serch' vs 'search'
        # is 1 deletion; use 'sarch'~0 -> no match)
        got3 = eng.query("SELECT COUNT(*) FROM docs WHERE TEXT_MATCH(body, 'sarch~0')").rows[0][0]
        assert int(got3) == 0

    def test_edit_distance_helper(self):
        from pinot_tpu.indexes.text import _edit_within

        assert _edit_within("kitten", "sitting", 3)
        assert not _edit_within("kitten", "sitting", 2)
        assert _edit_within("abc", "abc", 0)
        assert not _edit_within("abc", "abd", 0)
        assert _edit_within("abc", "abd", 1)
        assert not _edit_within("a", "abcd", 2)

    def test_lazy_text_index_without_config(self, data):
        """TEXT_MATCH works without a configured index (lazy dictionary
        tokenization), it just isn't counted as an index use."""
        e = QueryEngine()
        e.register_table(_schema())
        cfg = TableConfig(name="docs", indexing=IndexingConfig(vector_index_columns=["embedding"]))
        e.add_segment("docs", build_segment(_schema(), data, "s0", table_config=cfg))
        res = e.query("SELECT COUNT(*) FROM docs WHERE TEXT_MATCH(body, 'dog')")
        expected = sum(1 for b in data["body"] if "dog" in b.split())
        assert res.rows[0][0] == expected


class TestVectorIndex:
    def test_top_k_exact(self, eng, data):
        q = np.asarray(data["embedding"][17], dtype=np.float32)
        qs = json.dumps([float(x) for x in q])
        res = eng.query(f"SELECT v FROM docs WHERE VECTOR_SIMILARITY(embedding, '{qs}', 5) LIMIT 100")
        # golden: exact cosine top-5
        m = np.asarray(data["embedding"], dtype=np.float32)
        mn = m / np.linalg.norm(m, axis=1, keepdims=True)
        scores = mn @ (q / np.linalg.norm(q))
        top5 = set(np.argsort(-scores)[:5].tolist())
        got_vs = sorted(r[0] for r in res.rows)
        expected_vs = sorted(int(data["v"][i]) for i in top5)
        assert got_vs == expected_vs
        assert ("embedding", "vector") in res.stats.filter_index_uses

    def test_vector_with_metadata_filter(self, eng, data):
        q = json.dumps([1.0] * 8)
        res = eng.query(f"SELECT COUNT(*) FROM docs WHERE VECTOR_SIMILARITY(embedding, '{q}', 50) AND v > 50")
        assert 0 < res.rows[0][0] <= 50
