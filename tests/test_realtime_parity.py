"""Realtime parity (VERDICT r4 #10): MV columns in consuming segments,
snapshot-time index builds, upsert metadataTTL, consistent deletes,
APPEND/UNION partial strategies.

Reference model: MutableSegmentImpl.java:638 (every mutable index type),
ConcurrentMapPartitionUpsertMetadataManager.java:49 (metadataTTL, deletes),
PartialUpsertHandler APPEND/UNION.
"""
import numpy as np
import pytest

from pinot_tpu.query.engine import QueryEngine
from pinot_tpu.realtime import InMemoryStream, RealtimeTableDataManager
from pinot_tpu.spi.config import (
    IndexingConfig,
    SegmentsConfig,
    StreamConfig,
    TableConfig,
    UpsertConfig,
)
from pinot_tpu.spi.schema import DataType, FieldRole, FieldSpec, Schema


def _mgr(schema, cfg, path, n_part=1):
    stream = InMemoryStream(n_part)
    return RealtimeTableDataManager(schema, cfg, str(path), stream=stream), stream


def _engine(schema, cfg, mgr):
    eng = QueryEngine()
    eng.register_table(schema, cfg)
    eng.attach_realtime(schema.name, mgr)
    return eng


class TestRealtimeMV:
    def _schema(self):
        return Schema(
            "events",
            [
                FieldSpec("eid", DataType.INT),
                FieldSpec("tags", DataType.STRING, single_value=False),
                FieldSpec("vals", DataType.INT, single_value=False),
                FieldSpec("ts", DataType.TIMESTAMP, role=FieldRole.DATE_TIME),
            ],
        )

    def test_mv_ingest_and_query(self, tmp_path):
        schema = self._schema()
        cfg = TableConfig(
            "events",
            segments=SegmentsConfig(time_column="ts"),
            stream=StreamConfig(stream_type="memory", max_rows_per_segment=10),
        )
        mgr, stream = _mgr(schema, cfg, tmp_path / "t")
        eng = _engine(schema, cfg, mgr)
        rows = [
            {
                "eid": i,
                "tags": ["red", "blue"] if i % 2 == 0 else ["green"],
                "vals": [i, i * 10],
                "ts": 1_700_000_000_000 + i,
            }
            for i in range(25)
        ]
        stream.publish_many(rows, partition=0)
        mgr.consume_all()
        # spans 2 sealed + 1 consuming segment
        r = eng.query("SELECT COUNT(*) FROM events WHERE tags = 'red'")
        assert int(r.rows[0][0]) == 13  # even eids
        r2 = eng.query("SELECT SUMMV(vals) FROM events WHERE eid < 3")
        assert float(r2.rows[0][0]) == sum(i + i * 10 for i in range(3))
        # empty-MV row: missing tags ingests as empty, matches nothing
        stream.publish({"eid": 99, "tags": None, "vals": [1], "ts": 1_700_000_100_000}, partition=0)
        mgr.consume_all()
        r3 = eng.query("SELECT COUNT(*) FROM events WHERE tags = 'red'")
        assert int(r3.rows[0][0]) == 13

    def test_mv_value_at_point_read(self, tmp_path):
        schema = self._schema()
        cfg = TableConfig(
            "events",
            segments=SegmentsConfig(time_column="ts"),
            stream=StreamConfig(stream_type="memory", max_rows_per_segment=100),
        )
        mgr, stream = _mgr(schema, cfg, tmp_path / "t")
        stream.publish({"eid": 1, "tags": ["a", "b"], "vals": [7], "ts": 1}, partition=0)
        mgr.consume_all()
        m = next(iter(mgr.managers.values())).mutable
        assert m.value_at("tags", 0) == ("a", "b")
        assert m.value_at("vals", 0) == (7,)


class TestSnapshotIndexes:
    def test_consuming_snapshot_builds_configured_indexes(self, tmp_path):
        schema = Schema(
            "logs",
            [
                FieldSpec("level", DataType.STRING),
                FieldSpec("msg", DataType.STRING),
                FieldSpec("ts", DataType.TIMESTAMP, role=FieldRole.DATE_TIME),
            ],
        )
        cfg = TableConfig(
            "logs",
            indexing=IndexingConfig(
                inverted_index_columns=["level"], text_index_columns=["msg"]
            ),
            segments=SegmentsConfig(time_column="ts"),
            stream=StreamConfig(stream_type="memory", max_rows_per_segment=1000),
        )
        mgr, stream = _mgr(schema, cfg, tmp_path / "t")
        eng = _engine(schema, cfg, mgr)
        rows = [
            {"level": ["info", "warn", "error"][i % 3], "msg": f"request {i} failed fast" if i % 3 == 2 else f"request {i} ok", "ts": i}
            for i in range(60)
        ]
        stream.publish_many(rows, partition=0)
        mgr.consume_all()
        r = eng.query("SELECT COUNT(*) FROM logs WHERE level = 'error'")
        assert int(r.rows[0][0]) == 20
        # the CONSUMING snapshot's inverted index answered the filter
        assert ("level", "inverted") in r.stats.filter_index_uses
        r2 = eng.query("SELECT COUNT(*) FROM logs WHERE TEXT_MATCH(msg, 'failed')")
        assert int(r2.rows[0][0]) == 20
        assert ("msg", "text") in r2.stats.filter_index_uses


def _upsert_schema():
    return Schema(
        "orders",
        [
            FieldSpec("oid", DataType.STRING),
            FieldSpec("amount", DataType.DOUBLE, role=FieldRole.METRIC),
            FieldSpec("deleted", DataType.BOOLEAN),
            FieldSpec("ts", DataType.TIMESTAMP, role=FieldRole.DATE_TIME),
        ],
        primary_key_columns=["oid"],
    )


class TestUpsertTTLAndDelete:
    def _cfg(self, **up):
        return TableConfig(
            "orders",
            segments=SegmentsConfig(time_column="ts"),
            stream=StreamConfig(stream_type="memory", max_rows_per_segment=1000),
            upsert=UpsertConfig(mode="FULL", comparison_column="ts", **up),
        )

    def test_consistent_delete_hides_rows(self, tmp_path):
        cfg = self._cfg(delete_record_column="deleted")
        mgr, stream = _mgr(_upsert_schema(), cfg, tmp_path / "t")
        eng = _engine(_upsert_schema(), cfg, mgr)
        stream.publish({"oid": "a", "amount": 10.0, "deleted": False, "ts": 1}, partition=0)
        stream.publish({"oid": "b", "amount": 20.0, "deleted": False, "ts": 2}, partition=0)
        stream.publish({"oid": "a", "amount": 0.0, "deleted": True, "ts": 3}, partition=0)
        mgr.consume_all()
        r = eng.query("SELECT COUNT(*), SUM(amount) FROM orders")
        assert int(r.rows[0][0]) == 1 and float(r.rows[0][1]) == 20.0
        # older out-of-order arrival cannot resurrect the deleted key
        stream.publish({"oid": "a", "amount": 99.0, "deleted": False, "ts": 2}, partition=0)
        mgr.consume_all()
        r2 = eng.query("SELECT COUNT(*) FROM orders")
        assert int(r2.rows[0][0]) == 1
        # NEWER arrival revives the key
        stream.publish({"oid": "a", "amount": 55.0, "deleted": False, "ts": 9}, partition=0)
        mgr.consume_all()
        r3 = eng.query("SELECT COUNT(*), SUM(amount) FROM orders")
        assert int(r3.rows[0][0]) == 2 and float(r3.rows[0][1]) == 75.0

    def test_metadata_ttl_expires_tracking(self, tmp_path):
        cfg = self._cfg(metadata_ttl=100.0)
        mgr, stream = _mgr(_upsert_schema(), cfg, tmp_path / "t")
        um = mgr.upsert
        stream.publish({"oid": "old", "amount": 1.0, "deleted": False, "ts": 10}, partition=0)
        stream.publish({"oid": "new", "amount": 2.0, "deleted": False, "ts": 500}, partition=0)
        mgr.consume_all()
        assert ("old",) in um.pk_map
        um.expire_ttl_keys()
        # ts=10 trails the 500 watermark by more than metadataTTL=100
        assert ("old",) not in um.pk_map
        assert ("new",) in um.pk_map
        # the expired key's ROW stays visible (tracking ends, data stays)
        eng = _engine(_upsert_schema(), cfg, mgr)
        assert int(eng.query("SELECT COUNT(*) FROM orders").rows[0][0]) == 2


class TestPartialMVStrategies:
    def test_append_and_union(self, tmp_path):
        schema = Schema(
            "carts",
            [
                FieldSpec("cid", DataType.STRING),
                FieldSpec("items", DataType.STRING, single_value=False),
                FieldSpec("seen", DataType.STRING, single_value=False),
                FieldSpec("ts", DataType.TIMESTAMP, role=FieldRole.DATE_TIME),
            ],
            primary_key_columns=["cid"],
        )
        cfg = TableConfig(
            "carts",
            segments=SegmentsConfig(time_column="ts"),
            stream=StreamConfig(stream_type="memory", max_rows_per_segment=1000),
            upsert=UpsertConfig(
                mode="PARTIAL",
                comparison_column="ts",
                partial_upsert_strategies={"items": "APPEND", "seen": "UNION"},
            ),
        )
        mgr, stream = _mgr(schema, cfg, tmp_path / "t")
        stream.publish({"cid": "c1", "items": ["x"], "seen": ["x"], "ts": 1}, partition=0)
        stream.publish({"cid": "c1", "items": ["y"], "seen": ["x", "z"], "ts": 2}, partition=0)
        mgr.consume_all()
        m = next(iter(mgr.managers.values())).mutable
        # winning row is doc 1 (merged)
        assert m.value_at("items", 1) == ("x", "y")
        assert m.value_at("seen", 1) == ("x", "z")
