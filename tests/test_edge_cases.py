"""Regression tests for review findings: SELECT * schema, HAVING 3VL with
NULL aggregates, numGroupsLimit on the dense path, literal operands, CAST."""
import numpy as np
import pytest

from pinot_tpu.query.engine import QueryEngine
from pinot_tpu.segment.builder import build_segment
from pinot_tpu.spi.schema import DataType, FieldRole, FieldSpec, Schema
from pinot_tpu.sql.parser import SqlParseError, parse_query


@pytest.fixture(scope="module")
def eng():
    schema = Schema(
        "t",
        [
            FieldSpec("g", DataType.STRING),
            FieldSpec("v", DataType.LONG, role=FieldRole.METRIC, nullable=True),
        ],
    )
    e = QueryEngine()
    e.register_table(schema)
    data = {"g": np.array(["a", "a", "b", "b", "c", "d"], dtype=object), "v": [1, 2, None, None, 99, 5]}
    e.add_segment("t", build_segment(schema, data, "s"))
    return e


def test_select_star_columns_match_rows(eng):
    r = eng.query("SELECT * FROM t LIMIT 3")
    assert r.columns == ["g", "v"]
    assert all(len(row) == 2 for row in r.rows)


def test_having_3vl_null_aggregate_excluded(eng):
    # group 'b' has SUM(v) = NULL; SQL 3VL excludes it under <> and NOT IN
    r = eng.query("SELECT g, SUM(v) FROM t GROUP BY g HAVING SUM(v) <> 99 ORDER BY g LIMIT 10")
    assert [x[0] for x in r.rows] == ["a", "d"]
    r2 = eng.query("SELECT g, SUM(v) FROM t GROUP BY g HAVING SUM(v) NOT IN (99) ORDER BY g LIMIT 10")
    assert [x[0] for x in r2.rows] == ["a", "d"]


def test_num_groups_limit_dense_path(eng):
    r = eng.query("SET numGroupsLimit = 2; SELECT g, COUNT(*) FROM t GROUP BY g LIMIT 10")
    assert len(r.rows) == 2


def test_literal_divisor_and_cast(eng):
    r = eng.query("SELECT SUM(v / 2), SUM(CAST(v AS DOUBLE)) FROM t")
    assert r.rows[0][0] == pytest.approx((1 + 2 + 99 + 5) / 2)
    assert r.rows[0][1] == pytest.approx(107.0)


def test_count_distinct_parses_to_distinctcount():
    ctx = parse_query("SELECT COUNT(DISTINCT g) FROM t")
    assert ctx.select_list[0].function == "distinctcount"


def test_unimplemented_agg_clear_error():
    with pytest.raises(SqlParseError, match="not supported yet"):
        parse_query("SELECT DISTINCTCOUNTRAWHLL(g) FROM t")


def test_sum_of_pure_literal(eng):
    r = eng.query("SELECT SUM(1) FROM t")
    assert r.rows[0][0] == 6
