"""Regression tests for review findings: SELECT * schema, HAVING 3VL with
NULL aggregates, numGroupsLimit on the dense path, literal operands, CAST."""
import numpy as np
import pytest

from pinot_tpu.query.engine import QueryEngine
from pinot_tpu.segment.builder import build_segment
from pinot_tpu.spi.schema import DataType, FieldRole, FieldSpec, Schema
from pinot_tpu.sql.parser import SqlParseError, parse_query


@pytest.fixture(scope="module")
def eng():
    schema = Schema(
        "t",
        [
            FieldSpec("g", DataType.STRING),
            FieldSpec("v", DataType.LONG, role=FieldRole.METRIC, nullable=True),
        ],
    )
    e = QueryEngine()
    e.register_table(schema)
    data = {"g": np.array(["a", "a", "b", "b", "c", "d"], dtype=object), "v": [1, 2, None, None, 99, 5]}
    e.add_segment("t", build_segment(schema, data, "s"))
    return e


def test_select_star_columns_match_rows(eng):
    r = eng.query("SELECT * FROM t LIMIT 3")
    assert r.columns == ["g", "v"]
    assert all(len(row) == 2 for row in r.rows)


def test_having_3vl_null_aggregate_excluded(eng):
    # group 'b' has SUM(v) = NULL; SQL 3VL excludes it under <> and NOT IN
    r = eng.query("SELECT g, SUM(v) FROM t GROUP BY g HAVING SUM(v) <> 99 ORDER BY g LIMIT 10")
    assert [x[0] for x in r.rows] == ["a", "d"]
    r2 = eng.query("SELECT g, SUM(v) FROM t GROUP BY g HAVING SUM(v) NOT IN (99) ORDER BY g LIMIT 10")
    assert [x[0] for x in r2.rows] == ["a", "d"]


def test_num_groups_limit_dense_path(eng):
    r = eng.query("SET numGroupsLimit = 2; SELECT g, COUNT(*) FROM t GROUP BY g LIMIT 10")
    assert len(r.rows) == 2


def test_literal_divisor_and_cast(eng):
    r = eng.query("SELECT SUM(v / 2), SUM(CAST(v AS DOUBLE)) FROM t")
    assert r.rows[0][0] == pytest.approx((1 + 2 + 99 + 5) / 2)
    assert r.rows[0][1] == pytest.approx(107.0)


def test_count_distinct_parses_to_distinctcount():
    ctx = parse_query("SELECT COUNT(DISTINCT g) FROM t")
    assert ctx.select_list[0].function == "distinctcount"


def test_unimplemented_agg_clear_error():
    with pytest.raises(SqlParseError, match="not supported yet"):
        parse_query("SELECT DISTINCTCOUNTRAWHLL(g) FROM t")


def test_sum_of_pure_literal(eng):
    r = eng.query("SELECT SUM(1) FROM t")
    assert r.rows[0][0] == 6


class TestRound4EdgeCases:
    def test_empty_table_paths(self):
        import numpy as np

        from pinot_tpu.query.engine import QueryEngine
        from pinot_tpu.segment.builder import build_segment
        from pinot_tpu.spi.schema import DataType, FieldRole, FieldSpec, Schema

        schema = Schema(
            "e", [FieldSpec("c", DataType.STRING), FieldSpec("v", DataType.LONG, role=FieldRole.METRIC)]
        )
        eng = QueryEngine()
        eng.register_table(schema)
        # no segments at all
        assert eng.query("SELECT COUNT(*) FROM e").rows[0][0] == 0
        assert eng.query("SELECT c, SUM(v) FROM e GROUP BY c").rows == []
        assert eng.query("SELECT c FROM e LIMIT 5").rows == []
        res = eng.query("EXPLAIN PLAN FOR SELECT COUNT(*) FROM e")
        assert res.rows  # explain of an empty table still yields a plan row
        # window + set ops on empty
        assert eng.query("SELECT c, ROW_NUMBER() OVER (ORDER BY v) FROM e LIMIT 5").rows == []
        assert eng.query("SELECT c FROM e UNION SELECT c FROM e LIMIT 5").rows == []

    def test_zero_row_segment(self):
        import numpy as np

        from pinot_tpu.query.engine import QueryEngine
        from pinot_tpu.segment.builder import build_segment
        from pinot_tpu.spi.schema import DataType, FieldRole, FieldSpec, Schema

        schema = Schema(
            "z", [FieldSpec("c", DataType.STRING), FieldSpec("v", DataType.LONG, role=FieldRole.METRIC)]
        )
        seg = build_segment(schema, {"c": np.array([], dtype=object), "v": np.array([], dtype=np.int64)}, "s0")
        eng = QueryEngine()
        eng.register_table(schema)
        eng.add_segment("z", seg)
        assert eng.query("SELECT COUNT(*), SUM(v) FROM z").rows[0][0] == 0

    def test_case_everything_null(self):
        import numpy as np

        from pinot_tpu.query.engine import QueryEngine
        from pinot_tpu.segment.builder import build_segment
        from pinot_tpu.spi.schema import DataType, FieldRole, FieldSpec, Schema

        schema = Schema("n", [FieldSpec("v", DataType.LONG, role=FieldRole.METRIC)])
        eng = QueryEngine()
        eng.register_table(schema)
        eng.add_segment("n", build_segment(schema, {"v": np.arange(10)}, "s0"))
        # no WHEN matches and no ELSE: all NULL -> SUM is NULL, COUNT 0
        res = eng.query("SELECT SUM(CASE WHEN v > 100 THEN v END), COUNT(CASE WHEN v > 100 THEN v END) FROM n")
        assert res.rows[0][0] is None
        assert res.rows[0][1] == 0

    def test_post_agg_divide_by_zero_group(self):
        import numpy as np

        from pinot_tpu.query.engine import QueryEngine
        from pinot_tpu.segment.builder import build_segment
        from pinot_tpu.spi.schema import DataType, FieldRole, FieldSpec, Schema

        schema = Schema(
            "d", [FieldSpec("g", DataType.STRING), FieldSpec("v", DataType.LONG, role=FieldRole.METRIC)]
        )
        eng = QueryEngine()
        eng.register_table(schema)
        eng.add_segment(
            "d", build_segment(schema, {"g": np.array(["a", "b"], dtype=object), "v": np.array([5, 0])}, "s0")
        )
        # SUM(v)/SUM(v) where group b sums to 0 -> NULL, not a crash
        res = eng.query("SELECT g, SUM(v) * 1.0 / SUM(v) FROM d GROUP BY g ORDER BY g")
        assert res.rows[0][1] == 1.0
        assert res.rows[1][1] is None
