"""Test config: force an 8-device virtual CPU mesh BEFORE jax import.

This is the reference's "N logical nodes in one JVM" trick (SURVEY.md section
4.5) in TPU form: multi-chip sharding paths run against
xla_force_host_platform_device_count=8 so tests exercise real Mesh/shard_map
code without TPU hardware."""
import os

# Force-override: the ambient environment may pin JAX_PLATFORMS to real TPU
# hardware (single chip); tests need the virtual 8-device CPU mesh.
os.environ["JAX_PLATFORMS"] = "cpu"
xla_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in xla_flags:
    os.environ["XLA_FLAGS"] = (xla_flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

# The ambient sitecustomize pre-imports jax._src, latching JAX_PLATFORMS
# before this conftest runs — override at the config level too.
jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402
import pytest  # noqa: E402


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "slow: long-running smoke tests excluded from tier-1 (-m 'not slow')"
    )


@pytest.fixture(scope="session")
def rng():
    return np.random.default_rng(42)


@pytest.fixture(autouse=True)
def _reset_metrics():
    """The METRICS registry and the perf ledger are process-global; without
    a reset, counter/histogram assertions and federated per-server series
    see spill-over from whichever tests ran before."""
    from pinot_tpu.utils.metrics import METRICS
    from pinot_tpu.utils.perf import PERF_LEDGER

    METRICS.reset()
    PERF_LEDGER.reset()
    yield


@pytest.fixture(autouse=True)
def _reset_knob_registry():
    """The autopilot KnobRegistry is process-global; a knob override set by
    one test (or a controller it started) must not leak into the env-default
    reads every other test depends on."""
    from pinot_tpu.cluster import autopilot

    autopilot.reset_knobs()
    yield
    autopilot.reset_knobs()


@pytest.fixture(autouse=True)
def _reset_thread_provider():
    """The primitive provider (utils/threads.py) is process-global; a test
    that dies inside a model-checker schedule must not leave the
    deterministic provider installed for whichever test runs next."""
    from pinot_tpu.utils import threads

    threads.reset_provider()
    yield
    threads.reset_provider()
