"""Concurrent serving tier (round 12): cross-query vmap batching.

Same-shape in-flight queries coalesce in the broker's MicroBatcher and
execute as ONE vmapped plan launch; results must be bit-exact vs the
sequential path, per-member stats must SUM to one unbatched run (never
N duplicated copies), and batch-member kills must leave siblings exact.

Determinism: every test injects a fake clock (``broker.batch_clock`` /
``MicroBatcher(clock=...)``) and drives flushes with ``drain_batches()`` /
``pump(now)`` — no real sleeps anywhere.
"""
import numpy as np
import pytest

from pinot_tpu.analysis.compile_audit import SSE_AUDIT
from pinot_tpu.cluster import Broker, Coordinator, ServerInstance
from pinot_tpu.cluster.admission import QueryKilledError
from pinot_tpu.cluster.batcher import MicroBatcher
from pinot_tpu.query import executor as sse_executor
from pinot_tpu.query.safety import Deadline, QueryTimeoutError
from pinot_tpu.segment.builder import build_segment
from pinot_tpu.spi.config import SegmentsConfig, TableConfig
from pinot_tpu.spi.schema import DataType, FieldRole, FieldSpec, Schema
from pinot_tpu.sql.parser import parse_query
from pinot_tpu.utils.metrics import METRICS


def _schema():
    return Schema(
        "t",
        [
            FieldSpec("city", DataType.STRING),
            FieldSpec("v", DataType.LONG, role=FieldRole.METRIC),
            FieldSpec("ts", DataType.TIMESTAMP, role=FieldRole.DATE_TIME),
        ],
    )


def _data(n, seed, t0=1_700_000_000_000):
    rng = np.random.default_rng(seed)
    return {
        "city": rng.choice(["sf", "nyc", "la"], n).astype(object),
        "v": rng.integers(0, 100, n),
        "ts": t0 + rng.integers(0, 86_400_000, n).astype(np.int64),
    }


def _cluster(n_servers=2, replication=2, n_segments=4, rows=200):
    coord = Coordinator(replication=replication)
    for i in range(n_servers):
        coord.register_server(ServerInstance(f"server{i}"))
    coord.add_table(_schema(), TableConfig(name="t", segments=SegmentsConfig(time_column="ts")))
    for i in range(n_segments):
        coord.add_segment("t", build_segment(_schema(), _data(rows, seed=100 + i), f"seg{i}"))
    return coord


def _broker(coord):
    b = Broker(coord)
    b.batch_clock = lambda: 0.0  # deterministic: groups flush only on drain
    return b


SAME_SHAPE = [
    f"SELECT city, COUNT(*), SUM(v) FROM t WHERE v < {40 + i} GROUP BY city ORDER BY city"
    for i in range(5)
]


class TestBitExactness:
    def test_batched_equals_sequential(self):
        coord = _cluster()
        broker = _broker(coord)
        futs = [broker.submit(q) for q in SAME_SHAPE]
        assert broker.drain_batches() >= 1
        batched = [f.result() for f in futs]
        sequential = [broker.query(q) for q in SAME_SHAPE]
        for b, s in zip(batched, sequential):
            assert b.rows == s.rows
        assert METRICS.counter("broker.batches").value >= 1

    def test_query_many_wrapper(self):
        coord = _cluster()
        broker = _broker(coord)
        outs = broker.query_many(SAME_SHAPE)
        for out, q in zip(outs, SAME_SHAPE):
            assert out.rows == broker.query(q).rows


class TestStatsAttribution:
    def test_member_stats_sum_to_one_unbatched_run(self):
        """The regression the issue demands: summing batched member stats
        reproduces ONE unbatched execution — docs exactly, kernel
        bytes/flops to float tolerance — never N duplicated copies."""
        coord = _cluster()
        broker = _broker(coord)
        futs = [broker.submit(q) for q in SAME_SHAPE]
        broker.drain_batches()
        batched = [f.result() for f in futs]

        unbatched = broker.query(SAME_SHAPE[0])
        n = len(SAME_SHAPE)
        assert sum(b.stats.num_docs_scanned for b in batched) == unbatched.stats.num_docs_scanned
        assert sum(b.stats.kernel_bytes for b in batched) == pytest.approx(
            unbatched.stats.kernel_bytes, rel=1e-6
        )
        assert sum(b.stats.kernel_flops for b in batched) == pytest.approx(
            unbatched.stats.kernel_flops, rel=1e-6
        )
        # total_docs reports table size per member (not a cost — undivided)
        for b in batched:
            assert b.stats.total_docs == unbatched.stats.total_docs
        # per-member docs differ by at most 1 (the divmod remainder)
        docs = [b.stats.num_docs_scanned for b in batched]
        assert max(docs) - min(docs) <= 1


class TestCompileBudget:
    def test_at_most_two_compiles_per_shape(self):
        """One base compile (per-segment plan cache) + one vmapped compile
        (batch fn cache) per shape — the acceptance criterion's <=2."""
        coord = _cluster()
        broker = _broker(coord)
        broker.query(SAME_SHAPE[0])  # warm the base plan
        SSE_AUDIT.reset()
        sse_executor.BATCH_AUDIT.reset()
        futs = [broker.submit(q) for q in SAME_SHAPE]
        broker.drain_batches()
        for f in futs:
            f.result()
        base = SSE_AUDIT.summary()
        batch = sse_executor.BATCH_AUDIT.snapshot()
        assert base["compiles_total"] == 0  # base plan already cached
        assert batch["compiles"] <= 1  # exactly one vmapped trace per width
        # second wave of the same shape: zero compiles anywhere
        futs = [broker.submit(q) for q in SAME_SHAPE]
        broker.drain_batches()
        for f in futs:
            f.result()
        assert SSE_AUDIT.summary()["compiles_total"] == 0
        assert sse_executor.BATCH_AUDIT.snapshot()["compiles"] == batch["compiles"]


class TestMixedShapes:
    def test_mixed_shape_storm_never_cross_coalesces(self):
        """Distinct shapes (different group key / aggregate structure) form
        distinct batch groups; every result stays correct."""
        coord = _cluster()
        broker = _broker(coord)
        shapes = [
            "SELECT city, COUNT(*) FROM t WHERE v < 30 GROUP BY city ORDER BY city",
            "SELECT COUNT(*), MAX(v) FROM t WHERE v > 10",
            "SELECT city, SUM(v) FROM t GROUP BY city ORDER BY city LIMIT 2",
        ]
        storm = [q for q in shapes for _ in range(3)]
        b0 = METRICS.counter("broker.batches").value
        futs = [broker.submit(q) for q in storm]
        broker.drain_batches()
        outs = [f.result() for f in futs]
        for out, q in zip(outs, storm):
            assert out.rows == broker.query(q).rows
        # one batch per distinct shape, not one mega-batch
        assert METRICS.counter("broker.batches").value - b0 == len(shapes)

    def test_literal_variants_do_coalesce(self):
        """Same shape, different literals: ONE batch group (the whole point
        of canonicalizing literals into parameter slots)."""
        coord = _cluster()
        broker = _broker(coord)
        b0 = METRICS.counter("broker.batches").value
        futs = [broker.submit(q) for q in SAME_SHAPE]
        broker.drain_batches()
        for f in futs:
            f.result()
        assert METRICS.counter("broker.batches").value - b0 == 1


class TestMemberIsolation:
    def test_killed_member_detaches_siblings_exact(self):
        """server.execute_batch: one member's kill probe fires mid-batch —
        its error records, every sibling's result is bit-exact."""
        coord = _cluster(n_servers=1, replication=1)
        server = coord.servers["server0"]
        seg_names = sorted(coord.external_view("t").keys())
        ctxs = [parse_query(q) for q in SAME_SHAPE]
        kill_idx = 2
        cancels = [
            (lambda: "killed by test") if i == kill_idx else (lambda: None)
            for i in range(len(ctxs))
        ]
        results, stats, errors, _ = server.execute_batch(
            ctxs, seg_names, table_schema=coord.tables["t"].schema, cancels=cancels
        )
        assert isinstance(errors[kill_idx], QueryKilledError)
        for i, q in enumerate(SAME_SHAPE):
            if i == kill_idx:
                continue
            assert errors[i] is None
            ref_res, _ = server.execute(parse_query(q), seg_names,
                                        table_schema=coord.tables["t"].schema)
            from pinot_tpu.query.reduce import reduce_results
            from pinot_tpu.query.result import ExecutionStats

            got = reduce_results(parse_query(q), results[i], ExecutionStats())
            want = reduce_results(parse_query(q), ref_res, ExecutionStats())
            assert got.rows == want.rows

    def test_expired_member_detaches_siblings_exact(self):
        coord = _cluster(n_servers=1, replication=1)
        server = coord.servers["server0"]
        seg_names = sorted(coord.external_view("t").keys())
        ctxs = [parse_query(q) for q in SAME_SHAPE[:3]]
        deadlines = [None, Deadline(0.0), None]  # member 1 born expired
        results, stats, errors, _ = server.execute_batch(
            ctxs, seg_names, table_schema=coord.tables["t"].schema, deadlines=deadlines
        )
        assert isinstance(errors[1], QueryTimeoutError)
        assert errors[0] is None and errors[2] is None
        from pinot_tpu.query.reduce import reduce_results
        from pinot_tpu.query.result import ExecutionStats

        for i in (0, 2):
            ref_res, _ = server.execute(parse_query(SAME_SHAPE[i]), seg_names,
                                        table_schema=coord.tables["t"].schema)
            got = reduce_results(parse_query(SAME_SHAPE[i]), results[i], ExecutionStats())
            want = reduce_results(parse_query(SAME_SHAPE[i]), ref_res, ExecutionStats())
            assert got.rows == want.rows


class TestMicroBatcher:
    def test_bounded_wait_expiry_flushes_singleton(self):
        ran = []
        mb = MicroBatcher(lambda entries: ran.append(len(entries)) or [
            e.future.set_result(e.payload) for e in entries
        ], wait_ms=5, max_batch=8, clock=lambda: 0.0)
        fut = mb.submit("k", "q0")
        assert mb.pump(now=0.004) == 0  # window not yet expired
        assert not fut.done()
        assert mb.pump(now=0.0051) == 1  # expiry flushes the singleton
        assert fut.result() == "q0" and ran == [1]

    def test_full_group_flushes_inline_without_clock(self):
        ran = []
        mb = MicroBatcher(lambda entries: ran.append(len(entries)) or [
            e.future.set_result(i) for i, e in enumerate(entries)
        ], wait_ms=5, max_batch=3, clock=lambda: 0.0)
        futs = [mb.submit("k", f"q{i}") for i in range(3)]
        assert ran == [3]  # flushed at max_batch, no pump needed
        assert [f.result() for f in futs] == [0, 1, 2]
        assert mb.pending() == 0

    def test_keys_never_mix(self):
        groups = []
        mb = MicroBatcher(lambda entries: groups.append([e.payload for e in entries]) or [
            e.future.set_result(None) for e in entries
        ], wait_ms=5, max_batch=8, clock=lambda: 0.0)
        mb.submit("a", "a0"), mb.submit("b", "b0"), mb.submit("a", "a1")
        assert mb.flush() == 2
        assert sorted(map(sorted, groups)) == [["a0", "a1"], ["b0"]]

    def test_wait_zero_bypasses_coalescing(self):
        ran = []
        mb = MicroBatcher(lambda entries: ran.append(len(entries)) or [
            e.future.set_result(None) for e in entries
        ], wait_ms=0, max_batch=8, clock=lambda: 0.0)
        mb.submit("k", "q0"), mb.submit("k", "q1")
        assert ran == [1, 1]  # each ran inline as a singleton

    def test_runner_crash_fails_futures_not_process(self):
        def boom(entries):
            raise RuntimeError("runner died")

        mb = MicroBatcher(boom, wait_ms=5, max_batch=8, clock=lambda: 0.0)
        fut = mb.submit("k", "q0")
        mb.flush()
        with pytest.raises(RuntimeError, match="runner died"):
            fut.result()


class TestBypasses:
    def test_non_batchable_shapes_run_synchronously(self):
        """EXPLAIN and set-op queries bypass the batcher entirely but still
        return completed futures."""
        coord = _cluster()
        broker = _broker(coord)
        fut = broker.submit("EXPLAIN PLAN FOR SELECT city, COUNT(*) FROM t GROUP BY city")
        assert fut.done()  # never queued
        sub = (
            "SELECT city, COUNT(*) FROM t GROUP BY city "
            "UNION ALL SELECT city, COUNT(*) FROM t GROUP BY city"
        )
        fut2 = broker.submit(sub)
        assert fut2.done()
        assert broker.drain_batches() == 0

    def test_parse_error_returns_failed_future(self):
        coord = _cluster()
        broker = _broker(coord)
        fut = broker.submit("SELECT FROM WHERE")
        assert fut.done()
        with pytest.raises(Exception):
            fut.result()
