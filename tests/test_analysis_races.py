"""Lock-discipline race detector (pinot_tpu.analysis.races).

Each rule fires on a minimal seeded-bug fixture package and stays quiet
on the properly-locked counterpart, mirroring the W004-W006 fixture
style: true positive + clean negative per rule."""
import textwrap

from pinot_tpu.analysis.engine import Project, run_passes
from pinot_tpu.analysis.races import RacePass


def _findings(src, check_all_classes=False, **extra):
    files = {"pkg/m.py": textwrap.dedent(src)}
    for name, body in extra.items():
        files[f"pkg/{name}.py"] = textwrap.dedent(body)
    proj = Project.from_sources(files)
    return run_passes(proj, [RacePass(check_all_classes=check_all_classes)])


def _rules(src, **kw):
    return [f.rule for f in _findings(src, **kw)]


class TestW010GuardedAttrAccess:
    def test_flags_read_outside_the_guarding_lock(self):
        src = """
        import threading

        class Stats:
            def __init__(self):
                self._lock = threading.Lock()
                self._total = 0

            def add(self, n):
                with self._lock:
                    self._total += n

            def snapshot(self):
                return self._total
        """
        found = _findings(src)
        assert [f.rule for f in found] == ["W010"]
        assert found[0].symbol == "Stats.snapshot"
        assert "_total" in found[0].message and "_lock" in found[0].message
        assert found[0].hint  # fix hint travels with the finding

    def test_flags_unlocked_write(self):
        src = """
        import threading

        class Stats:
            def __init__(self):
                self._lock = threading.Lock()
                self._items = []

            def add(self, v):
                with self._lock:
                    self._items.append(v)

            def reset(self):
                self._items = []
        """
        found = _findings(src)
        assert [f.rule for f in found] == ["W010"]
        assert found[0].symbol == "Stats.reset"

    def test_quiet_when_every_access_holds_the_lock(self):
        src = """
        import threading

        class Stats:
            def __init__(self):
                self._lock = threading.Lock()
                self._total = 0

            def add(self, n):
                with self._lock:
                    self._total += n

            def snapshot(self):
                with self._lock:
                    return self._total
        """
        assert _rules(src) == []

    def test_quiet_on_init_and_init_only_helpers(self):
        src = """
        import threading

        class Manager:
            def __init__(self):
                self._lock = threading.Lock()
                self._segs = []
                self._recover()

            def _recover(self):
                self._segs = ["recovered"]

            def add(self, s):
                with self._lock:
                    self._segs.append(s)
        """
        assert _rules(src) == []

    def test_locked_helper_convention_counts_as_holding_the_lock(self):
        src = """
        import threading

        class Cache:
            def __init__(self):
                self._lock = threading.Lock()
                self._data = {}

            def put(self, k, v):
                with self._lock:
                    self._data[k] = v
                    self._evict_locked()

            def _evict_locked(self):
                self._data.pop(None, None)
        """
        assert _rules(src) == []

    def test_threaded_reachability_restriction(self):
        # no threading import anywhere: default scope skips the class,
        # check_all_classes=True (the fixture escape hatch) still checks it
        src = """
        class Quiet:
            def add(self, n):
                with self._lock:
                    self._total = self._total + n

            def read(self):
                return self._total
        """
        assert _rules(src) == []
        assert _rules(src, check_all_classes=True) == ["W010"]


class TestW011LockOrderCycles:
    def test_flags_abba_cycle_across_classes(self):
        src = """
        import threading

        class First:
            def __init__(self):
                self._lock = threading.Lock()

            def alpha(self, other):
                with self._lock:
                    Second.beta_only(other)

            def alpha_only(self):
                with self._lock:
                    pass

        class Second:
            def __init__(self):
                self._lock = threading.Lock()

            def beta(self, other):
                with self._lock:
                    First.alpha_only(other)

            def beta_only(self):
                with self._lock:
                    pass
        """
        found = _findings(src)
        assert [f.rule for f in found] == ["W011"]
        assert "lock-order cycle" in found[0].message

    def test_flags_non_reentrant_self_deadlock_through_call_chain(self):
        src = """
        import threading

        class Cache:
            def __init__(self):
                self._lock = threading.Lock()
                self._items = {}

            def put(self, k, v):
                with self._lock:
                    self._items[k] = v
                    self.flush()

            def flush(self):
                with self._lock:
                    self._items.clear()
        """
        found = [f for f in _findings(src) if f.rule == "W011"]
        assert len(found) == 1
        assert "self-deadlock" in found[0].message
        assert found[0].symbol == "Cache.put"

    def test_quiet_on_rlock_reacquisition(self):
        # same shape as the self-deadlock case but the lock is reentrant
        src = """
        import threading

        class Cache:
            def __init__(self):
                self._lock = threading.RLock()
                self._items = {}

            def put(self, k, v):
                with self._lock:
                    self._items[k] = v
                    self.flush()

            def flush(self):
                with self._lock:
                    self._items.clear()
        """
        assert _rules(src) == []

    def test_quiet_on_consistent_one_way_ordering(self):
        src = """
        import threading

        class First:
            def __init__(self):
                self._lock = threading.Lock()

            def alpha(self, other):
                with self._lock:
                    Second.beta_only(other)

        class Second:
            def __init__(self):
                self._lock = threading.Lock()

            def beta_only(self):
                with self._lock:
                    pass
        """
        assert _rules(src) == []


class TestW012BlockingUnderLock:
    def test_flags_direct_sleep_in_locked_region(self):
        src = """
        import threading
        import time

        class Poller:
            def __init__(self):
                self._lock = threading.Lock()
                self._n = 0

            def wait_turn(self):
                with self._lock:
                    self._n += 1
                    time.sleep(0.1)
        """
        found = [f for f in _findings(src) if f.rule == "W012"]
        assert len(found) == 1
        assert "time.sleep" in found[0].message and found[0].symbol == "Poller.wait_turn"

    def test_flags_blocking_call_reached_through_helper(self):
        src = """
        import threading
        import time

        def backoff():
            time.sleep(1.0)

        class Poller:
            def __init__(self):
                self._lock = threading.Lock()
                self._n = 0

            def wait_turn(self):
                with self._lock:
                    self._n += 1
                    backoff()
        """
        found = [f for f in _findings(src) if f.rule == "W012"]
        assert len(found) == 1
        assert "backoff" in found[0].message and "time.sleep" in found[0].message

    def test_flags_device_sync_method_under_lock(self):
        src = """
        import threading

        class Holder:
            def __init__(self):
                self._lock = threading.Lock()
                self._out = None

            def publish(self, fut):
                with self._lock:
                    self._out = fut.block_until_ready()
        """
        found = [f for f in _findings(src) if f.rule == "W012"]
        assert len(found) == 1
        assert "block_until_ready" in found[0].message

    def test_quiet_when_blocking_call_is_hoisted_out(self):
        src = """
        import threading
        import time

        class Poller:
            def __init__(self):
                self._lock = threading.Lock()
                self._n = 0

            def wait_turn(self):
                with self._lock:
                    self._n += 1
                time.sleep(0.1)
        """
        assert _rules(src) == []
