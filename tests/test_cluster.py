"""Cluster-layer tests: assignment, routing, failover, rebalance, retention,
broker-side pruning — the contracts of PinotHelixResourceManager /
TableRebalancer / BrokerRoutingManager, golden-checked against sqlite.
"""
import numpy as np
import pytest

from pinot_tpu.cluster import Broker, Coordinator, ServerInstance
from pinot_tpu.segment.builder import build_segment
from pinot_tpu.spi.config import SegmentsConfig, TableConfig
from pinot_tpu.spi.schema import DataType, FieldRole, FieldSpec, Schema

from golden import assert_same_rows, sqlite_from_data


def _schema():
    return Schema(
        "t",
        [
            FieldSpec("city", DataType.STRING),
            FieldSpec("v", DataType.LONG, role=FieldRole.METRIC),
            FieldSpec("ts", DataType.TIMESTAMP, role=FieldRole.DATE_TIME),
        ],
    )


def _config(**kw):
    return TableConfig(name="t", segments=SegmentsConfig(time_column="ts"), **kw)


def _data(n, seed, t0=1_700_000_000_000):
    rng = np.random.default_rng(seed)
    return {
        "city": rng.choice(["sf", "nyc", "la"], n).astype(object),
        "v": rng.integers(0, 100, n),
        "ts": t0 + rng.integers(0, 86_400_000, n).astype(np.int64),
    }


def _cluster(n_servers=3, replication=2, **cfg_kw):
    coord = Coordinator(replication=replication)
    for i in range(n_servers):
        coord.register_server(ServerInstance(f"server{i}"))
    coord.add_table(_schema(), _config(**cfg_kw))
    return coord


QUERIES = [
    "SELECT COUNT(*), SUM(v) FROM t",
    "SELECT city, COUNT(*), SUM(v) FROM t GROUP BY city",
    "SELECT COUNT(*) FROM t WHERE v > 50 AND city = 'sf'",
]


class TestAssignmentAndRouting:
    def test_replicated_assignment(self):
        coord = _cluster(n_servers=4, replication=2)
        all_data = []
        for i in range(6):
            d = _data(500, seed=i)
            all_data.append(d)
            targets = coord.add_segment("t", build_segment(_schema(), d, f"seg{i}"))
            assert len(targets) == 2  # replication 2 = one per replica group
            groups = {coord.replica_group[s] for s in targets}
            assert len(groups) == 2  # spread across groups
        merged = {k: np.concatenate([d[k] for d in all_data]) for k in all_data[0]}
        conn = sqlite_from_data("t", merged)
        broker = Broker(coord)
        for sql in QUERIES:
            assert_same_rows(broker.query(sql).rows, conn.execute(sql).fetchall())

    def test_kill_server_reroutes(self):
        coord = _cluster(n_servers=4, replication=2)
        all_data = []
        for i in range(4):
            d = _data(400, seed=10 + i)
            all_data.append(d)
            coord.add_segment("t", build_segment(_schema(), d, f"seg{i}"))
        merged = {k: np.concatenate([d[k] for d in all_data]) for k in all_data[0]}
        conn = sqlite_from_data("t", merged)
        broker = Broker(coord)
        before = broker.query(QUERIES[0]).rows
        coord.mark_down("server0")  # replication 2 -> every segment still live
        after = broker.query(QUERIES[0]).rows
        assert_same_rows(before, conn.execute(QUERIES[0]).fetchall())
        assert_same_rows(after, conn.execute(QUERIES[0]).fetchall())

    def test_replica_group_selector(self):
        coord = _cluster(n_servers=4, replication=2)
        for i in range(4):
            coord.add_segment("t", build_segment(_schema(), _data(300, seed=20 + i), f"seg{i}"))
        broker = Broker(coord, selector="replicagroup")
        res = broker.query("SELECT COUNT(*) FROM t")
        assert res.rows[0][0] == 1200

    def test_no_live_replica_raises(self):
        coord = _cluster(n_servers=2, replication=1)
        coord.add_segment("t", build_segment(_schema(), _data(100, seed=1), "seg0"))
        for s in list(coord.live):
            coord.mark_down(s)
        broker = Broker(coord)
        with pytest.raises(RuntimeError, match="no live replica"):
            broker.query("SELECT COUNT(*) FROM t")


class TestRebalance:
    def test_rebalance_repairs_under_replication(self):
        coord = _cluster(n_servers=3, replication=2)
        for i in range(6):
            coord.add_segment("t", build_segment(_schema(), _data(200, seed=30 + i), f"seg{i}"))
        coord.mark_down("server1")
        status = coord.status_report()["t"]
        assert status["underReplicated"]  # some segments lost a replica
        report = coord.rebalance("t")
        assert report["replicasAdded"] > 0
        # every segment now has >= 2 live replicas again (2 live servers)
        view = coord.external_view("t")
        assert all(len(srvs) >= 2 for srvs in view.values())
        broker = Broker(coord)
        assert broker.query("SELECT COUNT(*) FROM t").rows[0][0] == 1200

    def test_rebalance_spreads_to_new_server(self):
        coord = _cluster(n_servers=2, replication=1)
        for i in range(8):
            coord.add_segment("t", build_segment(_schema(), _data(100, seed=40 + i), f"seg{i}"))
        s_new = ServerInstance("server_new")
        coord.register_server(s_new)
        coord.rebalance("t")
        assert s_new.segment_names("t"), "new server received no segments"
        broker = Broker(coord)
        assert broker.query("SELECT COUNT(*) FROM t").rows[0][0] == 800


class TestRetentionAndPruning:
    def test_retention_purges_old_segments(self):
        coord = _cluster(n_servers=2, replication=1)
        cfg = coord.tables["t"].config
        cfg.segments.retention_time_value = 7
        cfg.segments.retention_time_unit = "DAYS"
        now = 1_700_000_000_000 + 30 * 86_400_000
        coord.add_segment("t", build_segment(_schema(), _data(100, seed=1, t0=now - 86_400_000), "fresh", table_config=cfg))
        coord.add_segment("t", build_segment(_schema(), _data(100, seed=2, t0=now - 20 * 86_400_000), "stale", table_config=cfg))
        purged = coord.run_retention(now_ms=now)
        assert purged == ["t/stale"]
        broker = Broker(coord)
        assert broker.query("SELECT COUNT(*) FROM t").rows[0][0] == 100

    def test_time_pruner(self):
        coord = _cluster(n_servers=2, replication=1)
        cfg = coord.tables["t"].config
        t0 = 1_700_000_000_000
        day = 86_400_000
        for i in range(4):
            coord.add_segment(
                "t",
                build_segment(_schema(), _data(100, seed=50 + i, t0=t0 + i * 10 * day), f"seg{i}", table_config=cfg),
            )
        broker = Broker(coord)
        res = broker.query(f"SELECT COUNT(*) FROM t WHERE ts >= {t0 + 30 * day}")
        # only seg3's window can overlap; 3 segments pruned broker-side
        assert res.stats.num_segments_pruned >= 3
        assert res.rows[0][0] == 100

    def test_partition_pruner(self):
        cfg = TableConfig(
            name="t",
            segments=SegmentsConfig(time_column="ts"),
            partition_column="city",
            num_partitions=3,
        )
        coord = Coordinator(replication=1)
        for i in range(2):
            coord.register_server(ServerInstance(f"server{i}"))
        coord.add_table(_schema(), cfg)
        # partition-pure segments: each holds a single city
        counts = {}
        for i, city in enumerate(["sf", "nyc", "la"]):
            d = _data(200, seed=60 + i)
            d["city"] = np.array([city] * 200, dtype=object)
            counts[city] = 200
            coord.add_segment("t", build_segment(_schema(), d, f"seg_{city}", table_config=cfg))
        broker = Broker(coord)
        res = broker.query("SELECT COUNT(*) FROM t WHERE city = 'nyc'")
        assert res.rows[0][0] == 200
        assert res.stats.num_segments_pruned >= 1  # non-nyc partitions pruned broker-side


class TestRealtimeInCluster:
    def test_coordinator_owned_realtime_table(self, tmp_path):
        """Broker serves a REALTIME table's sealed + consuming segments from
        the coordinator-owned manager; RealtimeToOffline then drains it."""
        from pinot_tpu.cluster.minion import MinionTaskManager
        from pinot_tpu.realtime import InMemoryStream
        from pinot_tpu.spi.config import StreamConfig

        coord = Coordinator(replication=1)
        coord.register_server(ServerInstance("s0"))
        stream = InMemoryStream(1)
        cfg = TableConfig(
            name="rt",
            segments=SegmentsConfig(time_column="ts"),
            stream=StreamConfig(stream_type="memory", max_rows_per_segment=40),
        )
        schema = Schema(
            "rt",
            [
                FieldSpec("city", DataType.STRING),
                FieldSpec("v", DataType.LONG, role=FieldRole.METRIC),
                FieldSpec("ts", DataType.TIMESTAMP, role=FieldRole.DATE_TIME),
            ],
        )
        mgr = coord.add_realtime_table(schema, cfg, str(tmp_path / "rt"), stream=stream)
        t0 = 1_700_000_000_000
        rows = [{"city": ["sf", "nyc"][i % 2], "v": i, "ts": t0 + i} for i in range(100)]
        stream.publish_many(rows, partition=0)
        assert coord.run_realtime_consumption() == 100
        broker = Broker(coord)
        res = broker.query("SELECT city, COUNT(*), SUM(v) FROM rt GROUP BY city ORDER BY city")
        assert {r[0]: (r[1], r[2]) for r in res.rows} == {
            "nyc": (50, sum(i for i in range(100) if i % 2)),
            "sf": (50, sum(i for i in range(100) if i % 2 == 0)),
        }
        # drain sealed segments into the offline table via the minion task
        report = MinionTaskManager(coord).run(
            "RealtimeToOfflineSegmentsTask", "rt", realtime_manager=mgr, window_end_ms=t0 + 200
        )
        assert len(report["moved"]) == 2
        total = broker.query("SELECT COUNT(*) FROM rt").rows[0][0]
        offline = broker.query(f"SELECT COUNT(*) FROM {report['offlineTable']}").rows[0][0]
        assert offline == 80 and total == 20  # consuming tail stays realtime


class TestHybridTable:
    def test_time_boundary_split(self, tmp_path):
        """Offline + realtime parts under ONE name: offline serves
        ts <= boundary, realtime serves ts > boundary — rows in both parts
        are never double-counted."""
        from pinot_tpu.realtime import InMemoryStream
        from pinot_tpu.spi.config import StreamConfig

        coord = Coordinator(replication=1)
        coord.register_server(ServerInstance("s0"))
        stream = InMemoryStream(1)
        cfg = TableConfig(
            name="h",
            segments=SegmentsConfig(time_column="ts"),
            stream=StreamConfig(stream_type="memory", max_rows_per_segment=1000),
        )
        schema = Schema(
            "h",
            [
                FieldSpec("city", DataType.STRING),
                FieldSpec("v", DataType.LONG, role=FieldRole.METRIC),
                FieldSpec("ts", DataType.TIMESTAMP, role=FieldRole.DATE_TIME),
            ],
        )
        mgr = coord.add_realtime_table(schema, cfg, str(tmp_path / "h"), stream=stream)
        t0 = 1_700_000_000_000
        # offline segment holds days 0..9 (boundary becomes t0+9)
        off = {
            "city": np.array(["sf"] * 10, dtype=object),
            "v": np.arange(10),
            "ts": (t0 + np.arange(10)).astype(np.int64),
        }
        coord.add_segment("h", build_segment(schema, off, "off0", table_config=cfg))
        # realtime got days 5..19 — rows 5..9 OVERLAP the offline segment
        rows = [{"city": "sf", "v": int(i), "ts": t0 + i} for i in range(5, 20)]
        stream.publish_many(rows, partition=0)
        coord.run_realtime_consumption()
        broker = Broker(coord)
        res = broker.query("SELECT COUNT(*), SUM(v) FROM h")
        # 0..9 from offline + 10..19 from realtime; overlap rows count once
        assert res.rows[0][0] == 20
        assert res.rows[0][1] == sum(range(20))
        # user filters compose with the boundary
        res2 = broker.query(f"SELECT COUNT(*) FROM h WHERE ts >= {t0 + 8}")
        assert res2.rows[0][0] == 12  # 8..19


class TestPeriodicTasks:
    def test_liveness_and_auto_rebalance(self):
        import time as _time

        coord = _cluster(n_servers=3, replication=2)
        for i in range(4):
            coord.add_segment("t", build_segment(_schema(), _data(200, seed=70 + i), f"seg{i}"))
        for s in coord.servers:
            coord.heartbeat(s)
        coord._heartbeats["server2"] = _time.monotonic() - 120  # stale
        report = coord.run_periodic_tasks(heartbeat_timeout_s=30)
        assert report["serversDropped"] == ["server2"]
        assert "t" in report["tablesRebalanced"]
        # after the tick, every segment has 2 live replicas again
        view = coord.external_view("t")
        assert all(len(srvs) >= 2 for srvs in view.values())
        broker = Broker(coord)
        assert broker.query("SELECT COUNT(*) FROM t").rows[0][0] == 800


class TestBrokerExplain:
    def test_explain_via_broker(self):
        coord = _cluster(n_servers=2, replication=1)
        coord.add_segment("t", build_segment(_schema(), _data(200, seed=99), "seg0"))
        res = Broker(coord).query("EXPLAIN PLAN FOR SELECT city, SUM(v) FROM t WHERE city = 'sf' GROUP BY city")
        assert res.columns == ["Operator", "Operator_Id", "Parent_Id"]
        assert any("GROUP_BY" in r[0] for r in res.rows)
