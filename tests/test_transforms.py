"""Transform/scalar function tests: datetime device kernels, dictionary-domain
string functions, expression group-by, expression selection/filters.

Datetime goldens come from python's datetime (UTC); string goldens from
sqlite.  Reference model: DateTruncTransformFunction and the FunctionRegistry
scalar set (pinot-common/.../function/FunctionRegistry.java:73).
"""
import datetime as dt

import numpy as np
import pytest

from pinot_tpu.query.engine import QueryEngine
from pinot_tpu.segment.builder import build_segment
from pinot_tpu.spi.schema import DataType, FieldRole, FieldSpec, Schema

from golden import assert_same_rows, sqlite_from_data

N = 4000


def _schema():
    return Schema(
        "ev",
        [
            FieldSpec("name", DataType.STRING),
            FieldSpec("city", DataType.STRING),
            FieldSpec("v", DataType.LONG, role=FieldRole.METRIC),
            FieldSpec("price", DataType.DOUBLE, role=FieldRole.METRIC),
            FieldSpec("ts", DataType.TIMESTAMP, role=FieldRole.DATE_TIME),
        ],
    )


@pytest.fixture(scope="module")
def data():
    rng = np.random.default_rng(23)
    names = ["Alice Smith", "bob jones", "  pad  ", "Carol", "dave", "Eve Adams"]
    # two years of timestamps at odd offsets
    base = int(dt.datetime(2023, 1, 1, tzinfo=dt.timezone.utc).timestamp() * 1000)
    return {
        "name": rng.choice(names, N).astype(object),
        "city": rng.choice(["sf", "NY", "tokyo"], N).astype(object),
        "v": rng.integers(0, 1000, N),
        "price": np.round(rng.random(N) * 100, 3),
        "ts": base + rng.integers(0, 2 * 365 * 86400_000, N),
    }


@pytest.fixture(scope="module")
def eng(data):
    e = QueryEngine()
    e.register_table(_schema())
    e.add_segment("ev", build_segment(_schema(), data, "s0"))
    return e


@pytest.fixture(scope="module")
def conn(data):
    return sqlite_from_data("ev", data)


def _py_dt(ms):
    return dt.datetime.fromtimestamp(ms / 1000, tz=dt.timezone.utc)


class TestDatetimeDevice:
    def test_year_month_day_extracts(self, eng, data):
        res = eng.query("SELECT ts, YEAR(ts), MONTH(ts), DAYOFMONTH(ts), HOUR(ts), MINUTE(ts), SECOND(ts) FROM ev LIMIT 500")
        for row in res.rows:
            d = _py_dt(row[0])
            assert (row[1], row[2], row[3], row[4], row[5], row[6]) == (
                d.year, d.month, d.day, d.hour, d.minute, d.second
            ), f"mismatch for {d.isoformat()}"

    def test_datetrunc_day_groupby(self, eng, conn):
        sql_p = "SELECT DATETRUNC('day', ts), COUNT(*), SUM(v) FROM ev GROUP BY DATETRUNC('day', ts) ORDER BY DATETRUNC('day', ts) LIMIT 1000"
        sql_l = "SELECT (ts/86400000)*86400000 AS d, COUNT(*), SUM(v) FROM ev GROUP BY d ORDER BY d LIMIT 1000"
        assert_same_rows(eng.query(sql_p).rows, conn.execute(sql_l).fetchall(), ordered=True)

    def test_datetrunc_month_groupby(self, eng, data):
        res = eng.query(
            "SELECT DATETRUNC('month', ts), COUNT(*) FROM ev GROUP BY DATETRUNC('month', ts) ORDER BY DATETRUNC('month', ts) LIMIT 100"
        )
        expected = {}
        for ms in data["ts"]:
            d = _py_dt(int(ms))
            key = int(dt.datetime(d.year, d.month, 1, tzinfo=dt.timezone.utc).timestamp() * 1000)
            expected[key] = expected.get(key, 0) + 1
        got = {int(r[0]): int(r[1]) for r in res.rows}
        assert got == expected

    def test_year_groupby_expression(self, eng, conn):
        sql_p = "SELECT YEAR(ts), COUNT(*), SUM(price) FROM ev GROUP BY YEAR(ts) ORDER BY YEAR(ts)"
        sql_l = (
            "SELECT CAST(strftime('%Y', ts/1000, 'unixepoch') AS INTEGER) AS y, COUNT(*), SUM(price) "
            "FROM ev GROUP BY y ORDER BY y"
        )
        assert_same_rows(eng.query(sql_p).rows, conn.execute(sql_l).fetchall(), ordered=True)

    def test_datetime_filter(self, eng, conn):
        sql_p = "SELECT COUNT(*) FROM ev WHERE YEAR(ts) = 2024 AND MONTH(ts) <= 6"
        sql_l = (
            "SELECT COUNT(*) FROM ev WHERE CAST(strftime('%Y', ts/1000, 'unixepoch') AS INTEGER) = 2024 "
            "AND CAST(strftime('%m', ts/1000, 'unixepoch') AS INTEGER) <= 6"
        )
        assert_same_rows(eng.query(sql_p).rows, conn.execute(sql_l).fetchall())

    def test_timeconvert(self, eng, conn):
        sql_p = "SELECT TIMECONVERT(ts, 'MILLISECONDS', 'DAYS'), COUNT(*) FROM ev GROUP BY TIMECONVERT(ts, 'MILLISECONDS', 'DAYS') ORDER BY TIMECONVERT(ts, 'MILLISECONDS', 'DAYS') LIMIT 1000"
        sql_l = "SELECT ts/86400000 AS d, COUNT(*) FROM ev GROUP BY d ORDER BY d LIMIT 1000"
        assert_same_rows(eng.query(sql_p).rows, conn.execute(sql_l).fetchall(), ordered=True)

    def test_dayofweek_range(self, eng):
        res = eng.query("SELECT DAYOFWEEK(ts), COUNT(*) FROM ev GROUP BY DAYOFWEEK(ts)")
        dows = sorted(int(r[0]) for r in res.rows)
        assert dows == list(range(1, 8))


class TestStringFunctions:
    def test_upper_lower_filter(self, eng, conn):
        for sql in [
            "SELECT COUNT(*) FROM ev WHERE UPPER(city) = 'SF'",
            "SELECT COUNT(*) FROM ev WHERE LOWER(city) = 'ny'",
        ]:
            assert_same_rows(eng.query(sql).rows, conn.execute(sql).fetchall())

    def test_length_in_agg_and_filter(self, eng, conn):
        sql = "SELECT SUM(LENGTH(name)), COUNT(*) FROM ev WHERE LENGTH(name) > 5"
        assert_same_rows(eng.query(sql).rows, conn.execute(sql).fetchall())

    def test_groupby_upper(self, eng, conn):
        sql = "SELECT UPPER(city), COUNT(*), SUM(v) FROM ev GROUP BY UPPER(city) ORDER BY UPPER(city)"
        assert_same_rows(eng.query(sql).rows, conn.execute(sql).fetchall(), ordered=True)

    def test_selection_expressions(self, eng, conn):
        sql_p = "SELECT UPPER(name), LENGTH(name), v * 2 FROM ev WHERE v > 995 ORDER BY v LIMIT 20"
        sql_l = "SELECT UPPER(name), LENGTH(name), v * 2 FROM ev WHERE v > 995 ORDER BY v LIMIT 20"
        assert_same_rows(eng.query(sql_p).rows, conn.execute(sql_l).fetchall())

    def test_substr_replace_trim(self, eng, data):
        res = eng.query("SELECT name, SUBSTR(name, 0, 3), REPLACE(name, ' ', '_'), TRIM(name) FROM ev LIMIT 50")
        for row in res.rows:
            assert row[1] == row[0][0:3]
            assert row[2] == row[0].replace(" ", "_")
            assert row[3] == row[0].strip()

    def test_startswith_contains(self, eng, conn):
        sql_p = "SELECT COUNT(*) FROM ev WHERE STARTSWITH(name, 'A') = 1"
        sql_l = "SELECT COUNT(*) FROM ev WHERE name LIKE 'A%'"
        assert_same_rows(eng.query(sql_p).rows, conn.execute(sql_l).fetchall())


class TestNumericExpressions:
    def test_round_and_arith_selection(self, eng, conn):
        sql = "SELECT v, ROUND(price, 1) FROM ev WHERE v > 990 ORDER BY v LIMIT 30"
        assert_same_rows(eng.query(sql).rows, conn.execute(sql).fetchall())

    def test_mod_groupby(self, eng, conn):
        sql_p = "SELECT MOD(v, 7), COUNT(*) FROM ev GROUP BY MOD(v, 7) ORDER BY MOD(v, 7)"
        sql_l = "SELECT v % 7 AS m, COUNT(*) FROM ev GROUP BY m ORDER BY m"
        assert_same_rows(eng.query(sql_p).rows, conn.execute(sql_l).fetchall(), ordered=True)

    def test_arith_expression_groupby(self, eng, conn):
        sql_p = "SELECT v - MOD(v, 100), COUNT(*) FROM ev GROUP BY v - MOD(v, 100) ORDER BY v - MOD(v, 100)"
        sql_l = "SELECT (v/100)*100 AS b, COUNT(*) FROM ev GROUP BY b ORDER BY b"
        assert_same_rows(eng.query(sql_p).rows, conn.execute(sql_l).fetchall(), ordered=True)


class TestFunctionRegistry:
    """FunctionRegistry analog: user scalar UDFs (round 4)."""

    def test_register_device_function(self, eng, conn):
        import jax.numpy as jnp

        from pinot_tpu.query import scalar

        scalar.register_device_function("clamp100", lambda v: jnp.minimum(v, 100))
        sql_p = "SELECT SUM(CLAMP100(v)) FROM ev WHERE v > 90"
        sql_l = "SELECT SUM(MIN(v, 100)) FROM ev WHERE v > 90"
        from golden import assert_same_rows

        assert_same_rows(eng.query(sql_p).rows, conn.execute(sql_l).fetchall())

    def test_register_dict_function(self, eng, data):
        import numpy as np

        from pinot_tpu.query import scalar

        scalar.register_dict_function(
            "initials",
            lambda values: np.array(
                ["".join(w[0] for w in str(v).split()) for v in values], dtype=object
            ),
            string_result_fn=True,
        )
        res = eng.query("SELECT name, INITIALS(name) FROM ev LIMIT 30")
        for row in res.rows:
            assert row[1] == "".join(w[0] for w in row[0].split())
        # and in a predicate (derived-string table path)
        res2 = eng.query("SELECT COUNT(*) FROM ev WHERE INITIALS(name) = 'AS'")
        expected = sum(
            1 for v in data["name"] if "".join(w[0] for w in v.split()) == "AS"
        )
        assert res2.rows[0][0] == expected

    def test_list_functions(self):
        from pinot_tpu.query import scalar

        fns = scalar.list_functions()
        assert "datetrunc" in fns["device"]
        assert "upper" in fns["dictionary"]
        assert "percentilekll" in fns["aggregation"]


class TestCaseWhen:
    """CASE WHEN ... THEN ... [ELSE ...] END (CaseTransformFunction)."""

    def test_case_in_aggregation(self, eng, conn):
        sql = (
            "SELECT SUM(CASE WHEN v > 500 THEN v ELSE 0 END), "
            "SUM(CASE WHEN city = 'sf' THEN 1 ELSE 0 END) FROM ev"
        )
        assert_same_rows(eng.query(sql).rows, conn.execute(sql).fetchall())

    def test_case_with_in_and_and(self, eng, conn):
        sql = (
            "SELECT SUM(CASE WHEN city IN ('sf', 'NY') AND v >= 100 THEN price ELSE 0 END) FROM ev"
        )
        assert_same_rows(eng.query(sql).rows, conn.execute(sql).fetchall())

    def test_case_in_selection(self, eng, conn):
        sql = (
            "SELECT v, CASE WHEN v > 990 THEN 1 WHEN v > 980 THEN 2 ELSE 3 END FROM ev "
            "WHERE v > 970 ORDER BY v LIMIT 50"
        )
        assert_same_rows(eng.query(sql).rows, conn.execute(sql).fetchall(), ordered=True)

    def test_case_null_else(self, eng, conn):
        sql = "SELECT AVG(CASE WHEN city = 'sf' THEN v END) FROM ev"
        # sqlite: AVG ignores NULLs from the implicit ELSE NULL — same here
        assert_same_rows(eng.query(sql).rows, conn.execute(sql).fetchall())

    def test_case_in_filter(self, eng, conn):
        sql = "SELECT COUNT(*) FROM ev WHERE CASE WHEN city = 'sf' THEN v ELSE 0 END > 500"
        assert_same_rows(eng.query(sql).rows, conn.execute(sql).fetchall())

    def test_case_chosen_branch_nullness(self):
        """A row taking a non-null branch is NOT null even when another
        branch's input is null there (review-caught)."""
        schema = Schema(
            "cn",
            [
                FieldSpec("x", DataType.LONG, role=FieldRole.METRIC),
                FieldSpec("nv", DataType.LONG, role=FieldRole.METRIC, nullable=True),
            ],
        )
        data = {"x": np.array([1, -1, 1, -1]), "nv": np.array([None, None, 5, 7], dtype=object)}
        e = QueryEngine()
        e.register_table(schema)
        e.add_segment("cn", build_segment(schema, data, "s0"))
        # rows 0: x>0 -> nv NULL; 1: else 0; 2: x>0 -> 5; 3: else 0
        res = e.query("SELECT SUM(CASE WHEN x > 0 THEN nv ELSE 0 END), COUNT(CASE WHEN x > 0 THEN nv ELSE 0 END) FROM cn")
        assert res.rows[0][0] == 5    # NULL row skipped, ELSE-0 rows counted as 0
        assert res.rows[0][1] == 3    # one row (row 0) is genuinely NULL


class TestSdfDatetime:
    """FROMDATETIME / TODATETIME (SimpleDateFormat conversions)."""

    def test_fromdatetime_filter_and_groupby(self):
        import datetime as dt2

        schema = Schema(
            "sd",
            [FieldSpec("day", DataType.STRING), FieldSpec("v", DataType.LONG, role=FieldRole.METRIC)],
        )
        rng2 = np.random.default_rng(9)
        days = [f"2024-0{m}-1{d}" for m in range(1, 4) for d in range(3)]
        data = {"day": rng2.choice(days, 2000).astype(object), "v": rng2.integers(0, 10, 2000)}
        e = QueryEngine()
        e.register_table(schema)
        e.add_segment("sd", build_segment(schema, data, "s0"))
        cutoff = int(dt2.datetime(2024, 2, 1, tzinfo=dt2.timezone.utc).timestamp() * 1000)
        res = e.query(f"SELECT COUNT(*) FROM sd WHERE FROMDATETIME(day, 'yyyy-MM-dd') >= {cutoff}")
        expected = sum(1 for s in data["day"] if not s.startswith("2024-01"))
        assert res.rows[0][0] == expected
        # group by the parsed epoch (numeric dict-fn interval bound)
        res2 = e.query(
            "SELECT FROMDATETIME(day, 'yyyy-MM-dd'), COUNT(*) FROM sd "
            "GROUP BY FROMDATETIME(day, 'yyyy-MM-dd') ORDER BY FROMDATETIME(day, 'yyyy-MM-dd') LIMIT 20"
        )
        assert len(res2.rows) == len(set(data["day"]))

    def test_todatetime_selection(self, eng):
        res = eng.query("SELECT ts, TODATETIME(ts, 'yyyy-MM-dd HH:mm:ss') FROM ev LIMIT 20")
        import datetime as dt2

        for row in res.rows:
            d = dt2.datetime.fromtimestamp(row[0] / 1000, tz=dt2.timezone.utc)
            assert row[1] == d.strftime("%Y-%m-%d %H:%M:%S")

    def test_quoted_literal_format_and_millis(self):
        """'T' quoted literal + SSS millis round-trip (review-caught)."""
        from pinot_tpu.query import scalar as sc

        got = sc.to_datetime(np.array([0]), "HHmmssSSS")
        assert got[0] == "000000000"
        parsed = sc._from_datetime(
            np.array(["2024-03-05T06:07:08"], dtype=object), "yyyy-MM-dd'T'HH:mm:ss"
        )
        import datetime as dt2

        assert parsed[0] == int(dt2.datetime(2024, 3, 5, 6, 7, 8, tzinfo=dt2.timezone.utc).timestamp() * 1000)


class TestStringBreadth:
    """String/URL/hash transform breadth (StringFunctions.java,
    UrlFunctions.java, HashFunctions.java, RegexpFunctions)."""

    @pytest.fixture(scope="class")
    def seng(self):
        import numpy as np

        from pinot_tpu.query.engine import QueryEngine
        from pinot_tpu.segment.builder import build_segment
        from pinot_tpu.spi.schema import DataType, FieldRole, FieldSpec, Schema

        schema = Schema(
            "s",
            [
                FieldSpec("path", DataType.STRING),
                FieldSpec("v", DataType.INT, role=FieldRole.METRIC),
            ],
        )
        paths = np.asarray(
            ["/api/users/42?q=a b", "/api/orders/7", "/web/home", "/api/users/9"] * 25,
            dtype=object,
        )
        eng = QueryEngine()
        eng.register_table(schema)
        eng.add_segment(
            "s", build_segment(schema, {"path": paths, "v": np.arange(100, dtype=np.int32)}, "s0")
        )
        return eng

    def test_splitpart_groupby(self, seng):
        r = seng.query(
            "SELECT SPLITPART(path, '/', 1), COUNT(*) FROM s "
            "GROUP BY SPLITPART(path, '/', 1) ORDER BY SPLITPART(path, '/', 1)"
        )
        assert [(a, int(b)) for a, b in r.rows] == [("api", 75), ("web", 25)]

    def test_regexp_extract_filter(self, seng):
        r = seng.query(
            "SELECT COUNT(*) FROM s WHERE REGEXPEXTRACT(path, '/api/([a-z]+)/', 1) = 'users'"
        )
        assert int(r.rows[0][0]) == 50

    def test_regexp_replace(self, seng):
        r = seng.query(
            "SELECT REGEXPREPLACE(path, '[0-9]+', 'N'), COUNT(*) FROM s "
            "GROUP BY REGEXPREPLACE(path, '[0-9]+', 'N') ORDER BY REGEXPREPLACE(path, '[0-9]+', 'N') LIMIT 5"
        )
        names = [a for a, _ in r.rows]
        assert "/api/users/N?q=a b" in names and "/api/orders/N" in names

    def test_url_and_hash(self, seng):
        import hashlib
        from urllib.parse import quote_plus

        r = seng.query(
            "SELECT URLENCODE(path), MD5(path), SHA256(path) FROM s ORDER BY path LIMIT 1"
        )
        enc, md5v, sha = r.rows[0]
        # first path in sorted order
        p = "/api/orders/7"
        assert enc == quote_plus(p)
        assert md5v == hashlib.md5(p.encode()).hexdigest()
        assert sha == hashlib.sha256(p.encode()).hexdigest()

    def test_base64_and_codepoint(self, seng):
        import base64

        r = seng.query("SELECT TOBASE64(path), CODEPOINT(path) FROM s ORDER BY path LIMIT 1")
        assert r.rows[0][0] == base64.b64encode(b"/api/orders/7").decode()
        assert int(r.rows[0][1]) == ord("/")

    def test_splitpart_limit_form(self):
        """4-arg form is (input, delim, limit, index) per StringFunctions."""
        from pinot_tpu.query.scalar import DICT_FNS
        import numpy as np

        vals = np.asarray(["a b c"], dtype=object)
        assert DICT_FNS["splitpart"](vals, " ", 2, 1)[0] == "b c"
        assert DICT_FNS["splitpart"](vals, " ", 2)[0] == "c"
        assert DICT_FNS["splitpart"](vals, " ", 9)[0] == "null"

    def test_regexp_replace_occurrence_and_flags(self):
        from pinot_tpu.query.scalar import DICT_FNS
        import numpy as np

        vals = np.asarray(["a1b2c3"], dtype=object)
        assert DICT_FNS["regexpreplace"](vals, "[0-9]", "N")[0] == "aNbNcN"
        assert DICT_FNS["regexpreplace"](vals, "[0-9]", "N", 0, 1)[0] == "a1bNc3"
        assert DICT_FNS["regexpreplace"](vals, "[0-9]", "N", 2, 0)[0] == "a1bNc3"
        assert DICT_FNS["regexpreplace"](np.asarray(["AxA"], dtype=object), "a", "z", 0, -1, "i")[0] == "zxz"
