"""M3 tests: DISTINCTCOUNT (exact), DISTINCTCOUNTHLL, PERCENTILE sketches —
scalar + grouped, in-process + distributed, golden-checked where exact."""
import numpy as np
import pytest

from pinot_tpu.parallel.engine import DistributedEngine
from pinot_tpu.parallel.stacked import StackedTable
from pinot_tpu.query.engine import QueryEngine
from pinot_tpu.segment.builder import build_segment
from pinot_tpu.spi.schema import DataType, FieldRole, FieldSpec, Schema

from golden import assert_same_rows, sqlite_from_data

N = 8000


def _schema():
    return Schema(
        "t",
        [
            FieldSpec("city", DataType.STRING),
            FieldSpec("user_id", DataType.INT),
            FieldSpec("latency", DataType.DOUBLE, role=FieldRole.METRIC),
        ],
    )


def _data(rng):
    return {
        "city": rng.choice(["sf", "nyc", "chi", "la"], N).astype(object),
        "user_id": rng.integers(0, 900, N).astype(np.int32),
        "latency": np.round(rng.exponential(50, N), 3),
    }


@pytest.fixture(scope="module")
def env():
    rng = np.random.default_rng(3)
    schema = _schema()
    eng = QueryEngine()
    eng.register_table(schema)
    datas = [_data(rng), _data(rng)]
    for i, d in enumerate(datas):
        eng.add_segment("t", build_segment(schema, d, f"s{i}"))
    merged = {k: np.concatenate([d[k] for d in datas]) for k in datas[0]}
    conn = sqlite_from_data("t", merged)
    return eng, conn, merged


def test_distinctcount_exact_scalar(env):
    eng, conn, _ = env
    got = eng.query("SELECT DISTINCTCOUNT(user_id), DISTINCTCOUNT(city) FROM t")
    exp = conn.execute("SELECT COUNT(DISTINCT user_id), COUNT(DISTINCT city) FROM t").fetchall()
    assert_same_rows(got.rows, exp)


def test_count_distinct_sugar(env):
    eng, conn, _ = env
    got = eng.query("SELECT COUNT(DISTINCT user_id) FROM t WHERE city = 'sf'")
    exp = conn.execute("SELECT COUNT(DISTINCT user_id) FROM t WHERE city = 'sf'").fetchall()
    assert_same_rows(got.rows, exp)


def test_distinctcount_grouped(env):
    eng, conn, _ = env
    got = eng.query("SELECT city, DISTINCTCOUNT(user_id) FROM t GROUP BY city ORDER BY city LIMIT 10")
    exp = conn.execute(
        "SELECT city, COUNT(DISTINCT user_id) FROM t GROUP BY city ORDER BY city LIMIT 10"
    ).fetchall()
    assert_same_rows(got.rows, exp, ordered=True)


def test_hll_accuracy(env):
    eng, conn, _ = env
    got = eng.query("SELECT DISTINCTCOUNTHLL(user_id) FROM t").rows[0][0]
    exact = conn.execute("SELECT COUNT(DISTINCT user_id) FROM t").fetchone()[0]
    assert abs(got - exact) / exact < 0.05, (got, exact)


def test_hll_grouped_and_string(env):
    eng, conn, _ = env
    rows = eng.query(
        "SELECT city, DISTINCTCOUNTHLL(user_id) FROM t GROUP BY city ORDER BY city LIMIT 10"
    ).rows
    exp = dict(
        conn.execute("SELECT city, COUNT(DISTINCT user_id) FROM t GROUP BY city").fetchall()
    )
    for city, est in rows:
        assert abs(est - exp[city]) / exp[city] < 0.07, (city, est, exp[city])


def test_percentile_scalar(env):
    eng, _, merged = env
    for rank in (50, 90, 99):
        got = eng.query(f"SELECT PERCENTILE(latency, {rank}) FROM t").rows[0][0]
        exact = np.percentile(merged["latency"], rank)
        binw = (merged["latency"].max() - merged["latency"].min()) / 2048
        assert abs(got - exact) <= max(2 * binw, 0.05 * exact), (rank, got, exact)


def test_percentile_grouped_multisegment(env):
    """Bin edges must align across segments (engine-injected global range)."""
    eng, _, merged = env
    rows = eng.query(
        "SELECT city, PERCENTILETDIGEST(latency, 90) FROM t GROUP BY city ORDER BY city LIMIT 10"
    ).rows
    binw = (merged["latency"].max() - merged["latency"].min()) / 2048
    for city, est in rows:
        sel = merged["latency"][merged["city"] == city]
        exact = np.percentile(sel, 90)
        assert abs(est - exact) <= max(3 * binw, 0.05 * exact), (city, est, exact)


def test_sketches_distributed(env):
    _, conn, merged = env
    st = StackedTable.build(_schema(), merged, 8)
    deng = DistributedEngine()
    deng.register_table("t", st)
    got = deng.query("SELECT city, DISTINCTCOUNT(user_id) FROM t GROUP BY city ORDER BY city LIMIT 10")
    exp = conn.execute(
        "SELECT city, COUNT(DISTINCT user_id) FROM t GROUP BY city ORDER BY city LIMIT 10"
    ).fetchall()
    assert_same_rows(got.rows, exp, ordered=True)
    est = deng.query("SELECT DISTINCTCOUNTHLL(user_id) FROM t").rows[0][0]
    exact = conn.execute("SELECT COUNT(DISTINCT user_id) FROM t").fetchone()[0]
    assert abs(est - exact) / exact < 0.05
    p90 = deng.query("SELECT PERCENTILE(latency, 90) FROM t").rows[0][0]
    exact90 = np.percentile(merged["latency"], 90)
    assert abs(p90 - exact90) <= 0.05 * exact90


def test_distinctcount_having(env):
    eng, conn, _ = env
    got = eng.query(
        "SELECT city, DISTINCTCOUNT(user_id) FROM t GROUP BY city "
        "HAVING DISTINCTCOUNT(user_id) > 0 ORDER BY city LIMIT 10"
    )
    exp = conn.execute(
        "SELECT city, COUNT(DISTINCT user_id) FROM t GROUP BY city ORDER BY city LIMIT 10"
    ).fetchall()
    assert_same_rows(got.rows, exp, ordered=True)


# ---------------------------------------------------------------------------
# Cross-segment alignment regressions (review findings)
# ---------------------------------------------------------------------------
def test_distinctcount_heterogeneous_string_dicts_exact():
    """Misaligned string dictionaries fall back to host value-set union
    (reference DistinctCountAggregationFunction semantics) — still exact."""
    schema = Schema("h1", [FieldSpec("s", DataType.STRING)])
    e = QueryEngine()
    e.register_table(schema)
    e.add_segment("h1", build_segment(schema, {"s": np.array(["a", "b", "c"], dtype=object)}, "s0"))
    e.add_segment("h1", build_segment(schema, {"s": np.array(["b", "c", "d"], dtype=object)}, "s1"))
    assert e.query("SELECT DISTINCTCOUNT(s) FROM h1").rows[0][0] == 4
    # grouped heterogeneous stays unsupported (per-group sets defeat tensors)
    with pytest.raises(NotImplementedError, match="DISTINCTCOUNTHLL"):
        e.query("SELECT s, DISTINCTCOUNT(s) FROM h1 GROUP BY s")
    # HLL is value-based: correct across misaligned dictionaries
    assert e.query("SELECT DISTINCTCOUNTHLL(s) FROM h1").rows[0][0] == 4


def test_distinctcount_heterogeneous_int_dicts_exact():
    """Numeric columns downgrade to a table-global value range: still exact."""
    schema = Schema("h2", [FieldSpec("x", DataType.INT)])
    e = QueryEngine()
    e.register_table(schema)
    e.add_segment("h2", build_segment(schema, {"x": np.array([1, 2, 3], dtype=np.int32)}, "s0"))
    e.add_segment("h2", build_segment(schema, {"x": np.array([2, 3, 9], dtype=np.int32)}, "s1"))
    assert e.query("SELECT DISTINCTCOUNT(x) FROM h2").rows[0][0] == 4


def test_hll_raw_double_no_truncation():
    """HLL on a raw DOUBLE column hashes the value bits, not int32(v)."""
    schema = Schema("h3", [FieldSpec("d", DataType.DOUBLE, role=FieldRole.METRIC)])
    vals = np.random.default_rng(0).random(20000) * 100  # int32 cast would give ~100
    e = QueryEngine()
    e.register_table(schema)
    e.add_segment("h3", build_segment(schema, {"d": vals}, "s0"))
    est = e.query("SELECT DISTINCTCOUNTHLL(d) FROM h3").rows[0][0]
    exact = len(np.unique(vals))
    assert abs(est - exact) / exact < 0.06


def test_sum_distinct_rejected():
    from pinot_tpu.sql.parser import SqlParseError, parse_query

    with pytest.raises(SqlParseError, match="DISTINCT"):
        parse_query("SELECT SUM(DISTINCT x) FROM t")
