"""Macro-batch launch path (round 5, VERDICT r4 #2).

At 1B rows a single shard_map launch OOMs a v5e chip because XLA keeps one
copy of every while-loop-captured column buffer; the engine splits the doc
axis into host-level launches and combines table-sized partials
(parallel/engine.py _batching / device_batches).  These tests force tiny
launch budgets on the 8-device CPU mesh so every query kind crosses batch
boundaries — including a ragged tail (overlap + fresh masking), trailing
padding, sorted doc-range filters (global doc ids via __boff__), and
bitmap-index word slicing.
"""
import numpy as np
import pytest

from pinot_tpu.parallel.engine import DistributedEngine
from pinot_tpu.parallel.stacked import StackedTable
from pinot_tpu.spi.config import IndexingConfig, TableConfig
from pinot_tpu.spi.schema import DataType, FieldRole, FieldSpec, Schema
from pinot_tpu.sql.parser import parse_query

N = 1245  # with 8 shards -> D = 160 (32-aligned), 35 trailing padding rows


def _schema(name: str) -> Schema:
    return Schema(
        name,
        [
            FieldSpec("day", DataType.INT),
            FieldSpec("g", DataType.STRING),
            FieldSpec("v", DataType.INT, role=FieldRole.METRIC),
        ],
    )


def _data(rng):
    return {
        "day": rng.integers(0, 200, N).astype(np.int32),
        "g": np.asarray([f"g{i}" for i in rng.integers(0, 7, N)]),
        "v": rng.integers(-1000, 1000, N).astype(np.int32),
    }


@pytest.fixture(scope="module")
def engines():
    rng = np.random.default_rng(11)
    data = _data(rng)
    cfg = TableConfig(
        "t",
        indexing=IndexingConfig(sorted_column="day", inverted_index_columns=["g"]),
    )

    def build(budget):
        eng = DistributedEngine(launch_bytes=budget)
        eng.register_table(
            "t", StackedTable.build(_schema("t"), dict(data), eng.num_devices, table_config=cfg)
        )
        return eng

    return build


QUERIES = [
    "SELECT COUNT(*), SUM(v), MIN(v), MAX(v) FROM t",
    "SELECT COUNT(*), SUM(v) FROM t WHERE day < 50",
    "SELECT g, COUNT(*), SUM(v) FROM t GROUP BY g ORDER BY g LIMIT 10",
    "SELECT g, SUM(v) FROM t WHERE g = 'g3' GROUP BY g LIMIT 5",
    "SET maxDenseGroups = 2; SELECT g, COUNT(*), SUM(v) FROM t GROUP BY g ORDER BY g LIMIT 10",
    "SELECT day, v FROM t WHERE v > 800 ORDER BY day, v LIMIT 20",
]


def _run_all(eng):
    out = []
    for q in QUERIES:
        r = eng.query(q)
        out.append(r.rows)
    return out


def test_batched_matches_unbatched(engines):
    """Every query kind returns identical rows under forced tiny launches."""
    base = _run_all(engines(None))
    # ~5 bytes/doc * 160 docs/shard = 800 bytes/device; 300 forces 3 launches
    batched_eng = engines(300)
    got = _run_all(batched_eng)
    assert got == base
    # prove batching actually happened (and exercised a ragged tail or not,
    # but at minimum multiple launches)
    ctx = parse_query(QUERIES[0])
    plan = batched_eng._plan(ctx, batched_eng.tables["t"])
    assert len(plan.batch_offsets) >= 2
    assert plan.batch_docs < batched_eng.tables["t"].docs_per_shard


def test_ragged_tail_fresh_masking(engines):
    """When batch width does not divide D, the tail re-launches the last
    full-width window with covered rows masked off — no double counting."""
    eng = engines(300)
    st = eng.tables["t"]
    ctx = parse_query("SELECT COUNT(*), SUM(v) FROM t")
    plan = eng._plan(ctx, st)
    D = st.docs_per_shard
    covered = sorted((off, off + plan.batch_docs) for off, _ in plan.batch_offsets)
    assert covered[0][0] == 0 and covered[-1][1] == D
    # exact COUNT proves no row is counted twice across overlapping windows
    r = eng.query("SELECT COUNT(*) FROM t")
    assert r.rows[0][0] == N
    if any(fresh for _, fresh in plan.batch_offsets):
        # tail overlap present: SUM must still be exact
        v_sum = eng.query("SELECT SUM(v) FROM t").rows[0][0]
        base = engines(None).query("SELECT SUM(v) FROM t").rows[0][0]
        assert v_sum == base


def test_docrange_filter_across_batches(engines):
    """Sorted-column doc ranges are GLOBAL doc ids; the per-launch __boff__
    offset must line them up with each batch's rows."""
    base_eng = engines(None)
    # a filter-only COUNT ships no columns, so the byte estimate is just the
    # 1-byte floor — 100 bytes/launch still forces 2 launches at D=160
    eng = engines(100)
    for hi in (10, 57, 123, 199):
        q = f"SELECT COUNT(*), SUM(v) FROM t WHERE day < {hi}"
        assert eng.query(q).rows == base_eng.query(q).rows
    ctx = parse_query("SELECT COUNT(*) FROM t WHERE day < 57")
    plan = eng._plan(ctx, eng.tables["t"])
    assert ("day", "sorted") in plan.index_uses
    assert len(plan.batch_offsets) >= 2


def test_bitmap_words_slice_per_batch(engines):
    """Inverted-index words ship [ndev, L*Db//32] slices per launch."""
    eng = engines(300)
    st = eng.tables["t"]
    ctx = parse_query("SELECT COUNT(*) FROM t WHERE g = 'g1'")
    plan = eng._plan(ctx, st)
    assert ("g", "inverted") in plan.index_uses
    assert plan.row_sharded_params
    key = next(iter(plan.row_sharded_params))
    ndev = eng.num_devices
    L = st.num_shards // ndev
    assert plan.params[key].shape == (ndev, L, st.docs_per_shard // 32)
    for off, fresh in plan.batch_offsets:
        bp = eng.batch_params(plan, off, fresh)
        assert bp[key].shape == (ndev, L * plan.batch_docs // 32)
        assert bp["__boff__"] == off and bp["__fresh__"] == fresh
    base = engines(None).query("SELECT COUNT(*) FROM t WHERE g = 'g1'").rows
    assert eng.query("SELECT COUNT(*) FROM t WHERE g = 'g1'").rows == base
