"""M0 tests: dictionary, bitmap utils, indexes, segment build/save/load
round-trips — the index reader/writer unit-test tier of the reference
(SURVEY.md section 4.1)."""
import numpy as np
import pytest

from pinot_tpu.indexes.bitmap import pack_mask, unpack_mask, unpack_mask_device
from pinot_tpu.indexes.bloom import BloomFilter
from pinot_tpu.indexes.inverted import InvertedIndex, RangeEncodedIndex
from pinot_tpu.segment.builder import build_segment
from pinot_tpu.segment.dictionary import Dictionary, NULL_DICT_ID, min_code_dtype
from pinot_tpu.segment.segment import ImmutableSegment
from pinot_tpu.spi.config import IndexingConfig, TableConfig
from pinot_tpu.spi.schema import DataType, FieldRole, FieldSpec, Schema


def make_schema():
    return Schema(
        name="t",
        fields=[
            FieldSpec("city", DataType.STRING),
            FieldSpec("year", DataType.INT),
            FieldSpec("ts", DataType.TIMESTAMP, role=FieldRole.DATE_TIME),
            FieldSpec("runs", DataType.LONG, role=FieldRole.METRIC),
            FieldSpec("score", DataType.DOUBLE, role=FieldRole.METRIC, nullable=True),
        ],
    )


def make_data(n=1000, seed=0):
    rng = np.random.default_rng(seed)
    return {
        "city": rng.choice(["sf", "nyc", "chi", "la", "sea"], n).astype(object),
        "year": rng.integers(1990, 2024, n).astype(np.int32),
        "ts": rng.integers(1_500_000_000_000, 1_700_000_000_000, n),
        "runs": rng.integers(0, 100, n),
        "score": np.where(rng.random(n) < 0.1, np.nan, rng.random(n) * 10),
    }


class TestDictionary:
    def test_sorted_and_roundtrip_int(self):
        vals = np.array([5, 3, 5, 9, 3, 1], dtype=np.int32)
        d, codes = Dictionary.build(DataType.INT, vals)
        assert list(d.values) == [1, 3, 5, 9]
        np.testing.assert_array_equal(d.get_values(codes), vals)

    def test_sorted_and_roundtrip_string(self):
        vals = np.array(["b", "a", "c", "a"], dtype=object)
        d, codes = Dictionary.build(DataType.STRING, vals)
        assert list(d.values) == ["a", "b", "c"]
        np.testing.assert_array_equal(d.get_values(codes), vals)

    def test_index_of(self):
        d, _ = Dictionary.build(DataType.INT, np.array([10, 20, 30]))
        assert d.index_of(20) == 1
        assert d.index_of(25) == NULL_DICT_ID
        assert d.insertion_index_of(25) == 2

    def test_encode_rejects_unknown(self):
        d, _ = Dictionary.build(DataType.INT, np.array([10, 20]))
        with pytest.raises(ValueError):
            d.encode(np.array([10, 99]))

    def test_min_code_dtype(self):
        assert min_code_dtype(200) == np.uint8
        assert min_code_dtype(60000) == np.uint16
        assert min_code_dtype(70000) == np.uint32


class TestBitmap:
    def test_pack_unpack(self, rng):
        mask = rng.random(1000) < 0.3
        words = pack_mask(mask)
        np.testing.assert_array_equal(unpack_mask(words, 1000), mask)

    def test_unpack_device(self, rng):
        import jax

        mask = rng.random(100) < 0.5
        words = pack_mask(mask)
        out = np.asarray(unpack_mask_device(jax.numpy.asarray(words), 100))
        np.testing.assert_array_equal(out, mask)


class TestIndexes:
    def test_inverted(self, rng):
        n, card = 500, 7
        codes = rng.integers(0, card, n).astype(np.int32)
        idx = InvertedIndex.build(codes, card, n)
        for v in range(card):
            np.testing.assert_array_equal(unpack_mask(idx.doc_bitmap([v]), n), codes == v)
        got = unpack_mask(idx.doc_bitmap([1, 3]), n)
        np.testing.assert_array_equal(got, (codes == 1) | (codes == 3))

    def test_range_encoded(self, rng):
        n, card = 500, 50
        codes = rng.integers(0, card, n).astype(np.int32)
        idx = RangeEncodedIndex.build(codes, card, n)
        for lo, hi in [(0, 50), (10, 20), (5, 5), (49, 50), (0, 1)]:
            np.testing.assert_array_equal(
                unpack_mask(idx.range_bitmap(lo, hi), n), (codes >= lo) & (codes < hi)
            )

    def test_bloom(self):
        bf = BloomFilter.build(["a", "b", "c", 42])
        assert bf.might_contain("a") and bf.might_contain(42)
        false_hits = sum(bf.might_contain(f"zz{i}") for i in range(200))
        assert false_hits < 30


class TestSegmentBuild:
    def test_build_and_stats(self):
        schema, data = make_schema(), make_data()
        seg = build_segment(schema, data, "seg0")
        assert seg.num_docs == 1000
        c = seg.column("year")
        assert c.has_dictionary and c.codes.dtype == np.uint8
        assert c.stats.min_value == data["year"].min()
        assert c.stats.max_value == data["year"].max()
        runs = seg.column("runs")
        # LONG storage narrows to int32 when the value range fits (TPU has no
        # 64-bit ALU; see builder.narrow_ints) — logical type stays LONG
        assert not runs.has_dictionary and runs.values.dtype == np.int32
        assert runs.data_type.value == "LONG"
        score = seg.column("score")
        assert score.nulls is not None and score.nulls.sum() > 0
        np.testing.assert_array_equal(seg.column("city").decoded(), data["city"])

    def test_sorted_column(self):
        schema, data = make_schema(), make_data()
        cfg = TableConfig(name="t", indexing=IndexingConfig(sorted_column="year"))
        seg = build_segment(schema, data, "seg0", table_config=cfg)
        decoded = seg.column("year").decoded()
        assert (decoded[:-1] <= decoded[1:]).all()
        assert seg.column("year").stats.is_sorted
        # other columns permuted consistently: (year, runs) pairs preserved
        pairs = sorted(zip(data["year"].tolist(), data["runs"].tolist()))
        got = sorted(zip(decoded.tolist(), seg.column("runs").values.tolist()))
        assert pairs == got

    def test_save_load_roundtrip(self, tmp_path):
        schema, data = make_schema(), make_data()
        cfg = TableConfig(
            name="t",
            indexing=IndexingConfig(
                inverted_index_columns=["city"],
                range_index_columns=["year"],
                bloom_filter_columns=["city"],
            ),
        )
        seg = build_segment(schema, data, "seg0", table_config=cfg, output_dir=str(tmp_path / "seg0"))
        loaded = ImmutableSegment.load(str(tmp_path / "seg0"))
        assert loaded.num_docs == seg.num_docs
        assert loaded.schema.column_names == schema.column_names
        for name in schema.column_names:
            a, b = seg.column(name), loaded.column(name)
            np.testing.assert_array_equal(a.decoded(), b.decoded())
            assert a.stats.to_dict() == b.stats.to_dict()
            if a.nulls is not None:
                np.testing.assert_array_equal(a.nulls, b.nulls)
        inv = loaded.indexes["inverted"]["city"]
        np.testing.assert_array_equal(inv.bitmaps, seg.indexes["inverted"]["city"].bitmaps)
        rng_idx = loaded.indexes["range"]["year"]
        np.testing.assert_array_equal(rng_idx.prefix, seg.indexes["range"]["year"].prefix)
        assert loaded.indexes["bloom"]["city"].might_contain("sf")

    def test_to_device(self):
        schema, data = make_schema(), make_data(100)
        seg = build_segment(schema, data, "seg0")
        dev = seg.to_device()
        assert "codes" in dev["city"] and "dict" not in dev["city"]  # string dict host-side
        assert "codes" in dev["year"] and "dict" in dev["year"]
        assert "values" in dev["runs"]
        np.testing.assert_array_equal(np.asarray(dev["year"]["dict"])[np.asarray(dev["year"]["codes"])],
                                      data["year"])

    def test_nullable_object_column(self):
        schema = Schema("t", [FieldSpec("s", DataType.STRING, nullable=True)])
        seg = build_segment(schema, {"s": ["a", None, "b"]}, "s0")
        assert seg.column("s").nulls.tolist() == [False, True, False]


class TestSchemaSerde:
    def test_roundtrip(self):
        s = make_schema()
        s2 = Schema.from_json(s.to_json())
        assert s2.to_dict() == s.to_dict()

    def test_table_config_roundtrip(self):
        cfg = TableConfig(
            name="t",
            indexing=IndexingConfig(inverted_index_columns=["a"], sorted_column="b"),
            partition_column="a",
            num_partitions=8,
        )
        cfg2 = TableConfig.from_json(cfg.to_json())
        assert cfg2.to_dict() == cfg.to_dict()


class TestSchemaEvolution:
    """Schema-added columns read as defaults on older segments
    (defaultColumnHandler analog)."""

    def test_added_columns_query_with_defaults(self):
        import numpy as np

        from pinot_tpu.query.engine import QueryEngine
        from pinot_tpu.segment.builder import build_segment
        from pinot_tpu.spi.schema import DataType, FieldRole, FieldSpec, Schema

        old_schema = Schema(
            "t", [FieldSpec("city", DataType.STRING), FieldSpec("v", DataType.LONG, role=FieldRole.METRIC)]
        )
        rng = np.random.default_rng(5)
        old_seg = build_segment(
            old_schema,
            {"city": rng.choice(["a", "b"], 500).astype(object), "v": rng.integers(0, 10, 500)},
            "old",
        )
        # evolve: add a STRING dimension and an INT metric
        new_schema = Schema(
            "t",
            [
                FieldSpec("city", DataType.STRING),
                FieldSpec("v", DataType.LONG, role=FieldRole.METRIC),
                FieldSpec("tier", DataType.STRING),
                FieldSpec("score", DataType.INT, role=FieldRole.METRIC),
            ],
        )
        new_seg = build_segment(
            new_schema,
            {
                "city": rng.choice(["a", "b"], 300).astype(object),
                "v": rng.integers(0, 10, 300),
                "tier": rng.choice(["gold", "free"], 300).astype(object),
                "score": rng.integers(1, 5, 300),
            },
            "new",
        )
        eng = QueryEngine()
        eng.register_table(new_schema)
        eng.add_segment("t", old_seg)
        eng.add_segment("t", new_seg)
        # old rows read SQL NULL for added columns (documented delta from
        # Pinot's default-VALUE reads; review-caught: placeholder values
        # must not leak into aggregates)
        res = eng.query("SELECT tier, COUNT(*) FROM t GROUP BY tier ORDER BY tier NULLS LAST")
        got = {r[0]: r[1] for r in res.rows}
        assert got[None] == 500  # old segment rows group under NULL
        assert got.get("gold", 0) + got.get("free", 0) == 300
        # filter on the new column drops old (NULL) rows entirely
        res2 = eng.query("SELECT COUNT(*), SUM(v) FROM t WHERE tier = 'gold'")
        assert res2.rows[0][0] == got["gold"]
        # aggregates over the added metric skip NULL (old) rows
        res3 = eng.query("SELECT COUNT(score), SUM(score), MIN(score) FROM t")
        assert res3.rows[0][0] == 300
        assert 300 <= res3.rows[0][1] <= 4 * 300
        assert res3.rows[0][2] >= 1  # the INT_MIN placeholder never leaks
        # SELECT * covers the FULL evolved schema on every segment
        res4 = eng.query("SELECT * FROM t LIMIT 900")
        assert res4.columns == ["city", "v", "tier", "score"]
        assert len(res4.rows) == 800
