"""Multi-value column tests: storage round-trip, ANY-semantics filters,
MV aggregations, ARRAYLENGTH.

Goldens are python-computed (sqlite has no array type).  Reference model:
FixedBitMVForwardIndexReader storage + per-value MV predicate semantics +
SumMV/CountMV/DistinctCountMV aggregation functions.
"""
import numpy as np
import pytest

from pinot_tpu.query.engine import QueryEngine
from pinot_tpu.segment.builder import build_segment
from pinot_tpu.segment.segment import ImmutableSegment
from pinot_tpu.spi.schema import DataType, FieldRole, FieldSpec, Schema

N = 5000
TAGS = ["red", "green", "blue", "gold", "gray"]


def _schema():
    return Schema(
        "mv",
        [
            FieldSpec("city", DataType.STRING),
            FieldSpec("tags", DataType.STRING, single_value=False),
            FieldSpec("scores", DataType.LONG, single_value=False),
            FieldSpec("v", DataType.LONG, role=FieldRole.METRIC),
        ],
    )


@pytest.fixture(scope="module")
def data():
    rng = np.random.default_rng(41)
    tags, scores = [], []
    for i in range(N):
        k = int(rng.integers(0, 4))  # 0..3 elements (empties included)
        tags.append(list(rng.choice(TAGS, size=k, replace=False)))
        scores.append(list(rng.integers(0, 50, size=k)))
    return {
        "city": rng.choice(["sf", "nyc"], N).astype(object),
        "tags": tags,
        "scores": scores,
        "v": rng.integers(0, 100, N),
    }


@pytest.fixture(scope="module")
def eng(data, tmp_path_factory):
    e = QueryEngine()
    e.register_table(_schema())
    seg = build_segment(_schema(), data, "s0")
    # persistence round-trip: MV codes + lengths survive save/load
    path = str(tmp_path_factory.mktemp("mvseg") / "s0")
    seg.save(path)
    e.add_segment("mv", ImmutableSegment.load(path))
    return e


class TestMVStorage:
    def test_roundtrip_decode(self, eng, data):
        seg = eng.table("mv").segments[0]
        dec = seg.column("tags").decoded()
        for i in range(0, N, 997):
            assert list(dec[i]) == list(data["tags"][i])

    def test_any_semantics_eq_filter(self, eng, data):
        res = eng.query("SELECT COUNT(*) FROM mv WHERE tags = 'red'")
        expected = sum(1 for t in data["tags"] if "red" in t)
        assert res.rows[0][0] == expected

    def test_in_and_not_in(self, eng, data):
        res = eng.query("SELECT COUNT(*) FROM mv WHERE tags IN ('red', 'gold')")
        expected = sum(1 for t in data["tags"] if "red" in t or "gold" in t)
        assert res.rows[0][0] == expected
        # NOT_IN with ANY semantics: some element outside the set
        res2 = eng.query("SELECT COUNT(*) FROM mv WHERE tags NOT IN ('red', 'gold')")
        expected2 = sum(1 for t in data["tags"] if any(x not in ("red", "gold") for x in t))
        assert res2.rows[0][0] == expected2

    def test_numeric_mv_range_filter(self, eng, data):
        res = eng.query("SELECT COUNT(*) FROM mv WHERE scores > 40")
        expected = sum(1 for s in data["scores"] if any(x > 40 for x in s))
        assert res.rows[0][0] == expected

    def test_empty_rows_never_match(self, eng, data):
        res = eng.query("SELECT COUNT(*) FROM mv WHERE scores >= 0")
        expected = sum(1 for s in data["scores"] if len(s) > 0)
        assert res.rows[0][0] == expected


class TestMVAggregations:
    def test_countmv_summv(self, eng, data):
        res = eng.query("SELECT COUNTMV(scores), SUMMV(scores), MINMV(scores), MAXMV(scores) FROM mv")
        flat = [x for s in data["scores"] for x in s]
        assert res.rows[0][0] == len(flat)
        assert res.rows[0][1] == sum(flat)
        assert res.rows[0][2] == min(flat)
        assert res.rows[0][3] == max(flat)

    def test_distinctcountmv(self, eng, data):
        res = eng.query("SELECT DISTINCTCOUNTMV(tags) FROM mv")
        assert res.rows[0][0] == len({x for t in data["tags"] for x in t})

    def test_mv_agg_grouped(self, eng, data):
        res = eng.query("SELECT city, SUMMV(scores), COUNTMV(scores) FROM mv GROUP BY city ORDER BY city")
        for row in res.rows:
            rows_in = [s for c, s in zip(data["city"], data["scores"]) if c == row[0]]
            assert row[1] == sum(x for s in rows_in for x in s)
            assert row[2] == sum(len(s) for s in rows_in)

    def test_mv_agg_with_filter(self, eng, data):
        res = eng.query("SELECT SUMMV(scores) FROM mv WHERE tags = 'blue'")
        expected = sum(sum(s) for t, s in zip(data["tags"], data["scores"]) if "blue" in t)
        assert res.rows[0][0] == expected


class TestArrayLength:
    def test_arraylength_filter(self, eng, data):
        res = eng.query("SELECT COUNT(*) FROM mv WHERE ARRAYLENGTH(tags) = 2")
        assert res.rows[0][0] == sum(1 for t in data["tags"] if len(t) == 2)

    def test_arraylength_groupby(self, eng, data):
        res = eng.query("SELECT ARRAYLENGTH(tags), COUNT(*) FROM mv GROUP BY ARRAYLENGTH(tags) ORDER BY ARRAYLENGTH(tags)")
        from collections import Counter

        expected = Counter(len(t) for t in data["tags"])
        got = {int(r[0]): int(r[1]) for r in res.rows}
        assert got == dict(expected)

    def test_arraylength_selection(self, eng, data):
        res = eng.query("SELECT city, ARRAYLENGTH(scores) FROM mv WHERE v > 97 LIMIT 50")
        assert all(isinstance(r[1], (int, np.integer)) for r in res.rows)

    def test_groupby_mv_explode(self, eng, data):
        """GROUP BY on an MV column explodes: each element counts once."""
        from collections import Counter

        res = eng.query("SELECT tags, COUNT(*), SUM(v) FROM mv GROUP BY tags ORDER BY tags LIMIT 100")
        counts = Counter()
        sums = Counter()
        for t_list, v in zip(data["tags"], data["v"]):
            for t in t_list:
                counts[t] += 1
                sums[t] += int(v)
        got = {r[0]: (int(r[1]), int(r[2])) for r in res.rows}
        assert got == {k: (counts[k], sums[k]) for k in counts}

    def test_groupby_mv_with_sv_dim(self, eng, data):
        from collections import Counter

        res = eng.query("SELECT city, tags, COUNT(*) FROM mv GROUP BY city, tags ORDER BY city, tags LIMIT 100")
        expected = Counter()
        for c, t_list in zip(data["city"], data["tags"]):
            for t in t_list:
                expected[(c, t)] += 1
        got = {(r[0], r[1]): int(r[2]) for r in res.rows}
        assert got == dict(expected)

    def test_groupby_mv_with_filter(self, eng, data):
        from collections import Counter

        res = eng.query("SELECT tags, COUNT(*) FROM mv WHERE v > 50 GROUP BY tags ORDER BY tags LIMIT 100")
        expected = Counter()
        for t_list, v in zip(data["tags"], data["v"]):
            if v > 50:
                for t in t_list:
                    expected[t] += 1
        got = {r[0]: int(r[1]) for r in res.rows}
        assert got == dict(expected)


class TestUnnest:
    def test_unnest_explodes_elements(self, eng, data):
        res = eng.query("SELECT city, UNNEST(tags) FROM mv WHERE v > 90 LIMIT 100000")
        expected = []
        for c, t_list, v in zip(data["city"], data["tags"], data["v"]):
            if v > 90:
                for t in t_list:
                    expected.append((c, t))
        assert sorted(map(tuple, res.rows)) == sorted(expected)

    def test_unnest_drops_empty_rows(self, eng, data):
        res = eng.query("SELECT UNNEST(scores) FROM mv LIMIT 1000000")
        expected = sorted(x for s in data["scores"] for x in s)
        assert sorted(r[0] for r in res.rows) == expected

    def test_unnest_limit_after_explode(self, eng, data):
        """Empty-MV rows must not consume LIMIT slots (review-caught:
        the explode runs over all matched rows, the trim at reduce)."""
        res = eng.query("SELECT UNNEST(tags) FROM mv LIMIT 7")
        assert len(res.rows) == 7


class TestDistributedMV:
    def test_stacked_mv_filters_and_aggs(self, data):
        """MV columns ride the distributed stacked path: ANY-semantics
        filters + MV aggregations over the 8-device mesh (round 4)."""
        from pinot_tpu.parallel.engine import DistributedEngine
        from pinot_tpu.parallel.stacked import StackedTable

        st = StackedTable.build(_schema(), data, 8)
        eng = DistributedEngine()
        eng.register_table("mv", st)
        res = eng.query("SELECT COUNT(*) FROM mv WHERE tags = 'red'")
        assert res.rows[0][0] == sum(1 for t in data["tags"] if "red" in t)
        res2 = eng.query("SELECT COUNTMV(scores), SUMMV(scores) FROM mv")
        flat = [x for s in data["scores"] for x in s]
        assert res2.rows[0][0] == len(flat)
        assert res2.rows[0][1] == sum(flat)
        res3 = eng.query("SELECT city, SUMMV(scores) FROM mv WHERE tags != 'gray' GROUP BY city ORDER BY city")
        for row in res3.rows:
            expected = sum(
                sum(s)
                for c, s, t in zip(data["city"], data["scores"], data["tags"])
                if c == row[0] and any(x != "gray" for x in t)
            )
            assert row[1] == expected
