"""SLO autopilot: KnobRegistry + feedback controller (ISSUE 18).

Everything here runs on a fake clock and a fake ledger — tick() is the
whole control law, driven directly.  Covers: clamped/typed knob writes
with the hard static-ceiling invariant, live env-default fallthrough
(autopilot off == pre-registry behavior bit-exact), hysteresis holds,
anti-windup skips, cooldown after every ladder walk, the
breach -> degrade -> recover round trip retracing the ladder, the
oscillation bound, and the satellite-1 regression: a registry write
takes effect on the next decision without rebuilding any consumer."""
import pytest

from pinot_tpu.cluster.autopilot import (
    Autopilot,
    KnobRegistry,
    LADDER,
    autopilot_enabled,
    knobs,
)


class FakeLedger:
    """Minimal PerfLedger stand-in: per-table (p99_ms, qps)."""

    def __init__(self):
        self.tables = {}

    def set(self, table, p99, qps=10.0):
        self.tables[table] = (p99, qps)

    def snapshot(self):
        return {
            "tables": {
                t: {
                    "qps": q,
                    "shapes": {"s": {"latencyMs": {"p99": p, "max": p}}},
                }
                for t, (p, q) in self.tables.items()
            }
        }


def make_pilot(slo_ms=100.0, registry=None):
    sim = [0.0]
    reg = registry if registry is not None else KnobRegistry()
    led = FakeLedger()
    ap = Autopilot(
        registry=reg, ledger=led, clock=lambda: sim[0], tick_s=1.0, slo_ms=slo_ms
    )
    return ap, reg, led, sim


def drive(ap, led, sim, p99, n=1, table="t"):
    """Set the signal, advance the fake clock, tick n times."""
    out = []
    for _ in range(n):
        if p99 is None:
            led.tables.pop(table, None)
        else:
            led.set(table, p99)
        sim[0] += ap.tick_s
        out.append(ap.tick())
    return out


# ---------------------------------------------------------------------------
# registry
# ---------------------------------------------------------------------------


class TestKnobRegistry:
    def test_env_default_read_live(self, monkeypatch):
        """No override => the env var is consulted at decision time, so a
        monkeypatched env (and the autopilot-off path) behaves exactly
        like the pre-registry construction-time read."""
        reg = KnobRegistry()
        assert reg.get("batch_wait_ms") == 2.0
        monkeypatch.setenv("PINOT_TPU_BATCH_WAIT_MS", "5.5")
        assert reg.get("batch_wait_ms") == 5.5

    def test_hard_ceiling_invariant(self):
        """Setters can NEVER exceed the static env-derived clamp bounds."""
        reg = KnobRegistry()
        for name in reg.names():
            lo, hi = reg.bounds(name)
            assert reg.set(name, hi + 1e9) <= hi
            assert reg.set(name, lo - 1e9) >= lo

    def test_integer_knobs_round(self):
        reg = KnobRegistry()
        assert reg.set("pipeline_depth", 1.4) == 1.0
        assert reg.set("degrade_level", 2.6) == 3.0

    def test_set_many_one_atomic_tick(self):
        reg = KnobRegistry()
        applied = reg.set_many({"batch_wait_ms": 4.0, "hedge_budget_pct": 5.0})
        assert applied == {"batch_wait_ms": 4.0, "hedge_budget_pct": 5.0}
        view = reg.view()
        assert view["batch_wait_ms"] == 4.0
        assert view["hedge_budget_pct"] == 5.0

    def test_snapshot_marks_overrides_and_reset_clears(self):
        reg = KnobRegistry()
        reg.set("batch_wait_ms", 4.0)
        snap = reg.snapshot()["knobs"]
        assert snap["batch_wait_ms"]["overridden"] is True
        assert snap["pipeline_depth"]["overridden"] is False
        reg.reset()
        assert reg.snapshot()["knobs"]["batch_wait_ms"]["overridden"] is False
        assert reg.get("batch_wait_ms") == 2.0

    def test_splits_normalized_copy(self):
        reg = KnobRegistry()
        reg.set_splits({"a": 0.75, "b": 0.25})
        s = reg.splits()
        assert s == {"a": 0.75, "b": 0.25}
        s["a"] = 99.0  # caller mutation must not leak in
        assert reg.splits()["a"] == 0.75

    def test_enabled_toggle(self, monkeypatch):
        monkeypatch.delenv("PINOT_TPU_AUTOPILOT", raising=False)
        assert autopilot_enabled() is False
        monkeypatch.setenv("PINOT_TPU_AUTOPILOT", "1")
        assert autopilot_enabled() is True


# ---------------------------------------------------------------------------
# control law
# ---------------------------------------------------------------------------


class TestControlLaw:
    def test_idle_without_traffic(self):
        ap, reg, led, sim = make_pilot()
        (d,) = drive(ap, led, sim, None)
        assert d["action"] == "idle"
        assert reg.view() == {n: reg.initial(n) for n in reg.names()}

    def test_hysteresis_band_holds(self):
        """p99 between recover_ratio*slo and slo: no move, ever."""
        ap, reg, led, sim = make_pilot(slo_ms=100.0)
        for d in drive(ap, led, sim, 85.0, n=10):
            assert d["action"] == "hold"
        assert not reg.snapshot()["knobs"]["hedge_budget_pct"]["overridden"]

    def test_breach_needs_sustained_evidence(self):
        ap, reg, led, sim = make_pilot(slo_ms=100.0)
        d1, d2 = drive(ap, led, sim, 300.0, n=2)
        assert d1["action"] == "breach-pending"
        assert d2["action"] == "degrade"
        # first ladder rung: shed hedges, multiplicative decrease
        assert d2["knob"] == "hedge_budget_pct"
        assert d2["to"] == pytest.approx(5.0)

    def test_one_breach_then_health_resets_streak(self):
        ap, reg, led, sim = make_pilot(slo_ms=100.0)
        drive(ap, led, sim, 300.0)  # breach-pending
        drive(ap, led, sim, 85.0)  # in band: evidence resets
        (d,) = drive(ap, led, sim, 300.0)
        assert d["action"] == "breach-pending"  # streak restarted, no move

    def test_anti_windup_skips_saturated_knob(self):
        ap, reg, led, sim = make_pilot(slo_ms=100.0)
        reg.set("hedge_budget_pct", 0.0)  # pinned at lo: saturated
        _, d = drive(ap, led, sim, 300.0, n=2)
        assert d["action"] == "degrade"
        assert d["knob"] == "batch_wait_ms"  # next rung, not the pinned one

    def test_ladder_walk_sets_cooldown(self):
        ap, reg, led, sim = make_pilot(slo_ms=100.0)
        # saturate every rung before degrade_level (admission inert: env 0)
        reg.set_many(
            {
                "hedge_budget_pct": 0.0,
                "batch_wait_ms": 8.0,
                "pipeline_depth": 1,
                "staging_depth": 1,
            }
        )
        _, d = drive(ap, led, sim, 300.0, n=2)
        assert d["action"] == "degrade"
        assert d["knob"] == "degrade_level"
        assert reg.get("degrade_level") == 1.0
        for d in drive(ap, led, sim, 300.0, n=ap.cooldown_ticks):
            assert d["action"] == "cooldown"
        assert ap.snapshot()["ladderWalks"] == 1

    def test_fully_saturated_reports_not_moves(self):
        ap, reg, led, sim = make_pilot(slo_ms=100.0)
        reg.set_many(
            {
                "hedge_budget_pct": 0.0,
                "batch_wait_ms": 8.0,
                "pipeline_depth": 1,
                "staging_depth": 1,
                "degrade_level": 3,
            }
        )
        _, d = drive(ap, led, sim, 300.0, n=2)
        assert d["action"] == "saturated"
        assert reg.get("degrade_level") == 3.0  # nothing pushed past a clamp

    def test_breach_degrade_recover_round_trip(self):
        """Sustained breach walks down the ladder; sustained health climbs
        back the SAME path until every knob sits at its env initial."""
        ap, reg, led, sim = make_pilot(slo_ms=100.0)
        initials = {n: reg.initial(n) for n in reg.names()}
        moves = [d for d in drive(ap, led, sim, 400.0, n=4) if "knob" in d]
        assert [m["knob"] for m in moves if m["action"] == "degrade"] == [
            "hedge_budget_pct",
            "hedge_budget_pct",
        ]
        assert reg.get("hedge_budget_pct") == pytest.approx(2.5)
        # now healthy: recovery retraces (additive increase) to initial
        recovered = False
        for d in drive(ap, led, sim, 20.0, n=60):
            if d["action"] == "recover":
                assert d["knob"] == "hedge_budget_pct"
                assert d["to"] > d["from"]
            if d["action"] == "recovered":
                recovered = True
                break
        assert recovered
        assert reg.view() == initials
        assert not reg.snapshot()["knobs"]["hedge_budget_pct"]["overridden"] or (
            reg.get("hedge_budget_pct") == initials["hedge_budget_pct"]
        )

    def test_oscillation_bound_caps_changes_per_window(self):
        """At most max_changes_per_window knob moves per change_window
        ticks, no matter how hard the signal whipsaws."""
        ap, reg, led, sim = make_pilot(slo_ms=100.0)
        decisions = drive(ap, led, sim, 400.0, n=3 * ap.change_window)
        move_ticks = [
            d["tick"] for d in decisions if d["action"] in ("degrade", "recover")
        ]
        assert any(d["action"] == "capped" for d in decisions)
        for t in move_ticks:
            in_window = [m for m in move_ticks if t - ap.change_window < m <= t]
            assert len(in_window) <= ap.max_changes_per_window
        assert ap.snapshot()["knobChanges"] == len(move_ticks)

    def test_disabled_when_slo_nonpositive(self):
        ap, reg, led, sim = make_pilot(slo_ms=0.0)
        (d,) = drive(ap, led, sim, 400.0)
        assert d["action"] == "disabled"

    def test_splits_follow_traffic_share(self):
        ap, reg, led, sim = make_pilot(slo_ms=100.0)
        led.set("hot", 50.0, qps=30.0)
        led.set("cold", 50.0, qps=10.0)
        sim[0] += 1.0
        ap.tick()
        s = reg.splits()
        assert s["hot"] == pytest.approx(0.75)
        assert s["cold"] == pytest.approx(0.25)

    def test_single_tenant_keeps_no_splits(self):
        ap, reg, led, sim = make_pilot(slo_ms=100.0)
        drive(ap, led, sim, 50.0, n=3)
        assert reg.splits() == {}

    def test_snapshot_surface(self):
        ap, reg, led, sim = make_pilot(slo_ms=100.0)
        drive(ap, led, sim, 300.0, n=2)
        snap = ap.snapshot()
        assert snap["enabled"] is True
        assert snap["ticks"] == 2
        assert snap["changeBound"] == {"windowTicks": 16, "maxChanges": 4}
        assert snap["decisions"][-1]["action"] == "degrade"
        assert snap["tables"]["t"]["state"] == "breach"
        assert set(LADDER) <= set(snap["knobs"])

    def test_telemetry_failure_holds_not_dies(self):
        class BrokenLedger:
            def snapshot(self):
                raise RuntimeError("ledger down")

        sim = [0.0]
        ap = Autopilot(
            registry=KnobRegistry(),
            ledger=BrokenLedger(),
            clock=lambda: sim[0],
            slo_ms=100.0,
        )
        d = ap.tick()
        assert d["action"] == "idle"  # degraded to no-signal, loop survives

    def test_sensing_backoff_policy(self):
        # steady ticks stretch the cadence geometrically up to the cap;
        # saturated counts as steady (nothing to move until load eases)
        b = 1
        for expect in (2, 4, 8, 8):
            b = Autopilot._next_backoff(b, "hold")
            assert b == expect
        assert Autopilot._next_backoff(8, "saturated") == 8
        assert Autopilot._next_backoff(8, "idle") == 8
        # any evidence, move, or cooldown snaps straight back to tick_s
        for action in ("breach-pending", "degrade", "recover-pending",
                       "recover", "capped", "cooldown"):
            assert Autopilot._next_backoff(8, action) == 1


# ---------------------------------------------------------------------------
# satellite 1: a registry write reaches every consumer on the NEXT decision
# ---------------------------------------------------------------------------


class TestLiveKnobConsumers:
    def test_batcher_wait_ms_live(self):
        from pinot_tpu.cluster.batcher import MicroBatcher

        b = MicroBatcher(runner=lambda entries: None, clock=lambda: 0.0)
        assert b.wait_ms == 2.0
        knobs().set("batch_wait_ms", 6.0)
        assert b.wait_ms == 6.0  # no rebuild
        b.wait_ms = 1.0  # direct assignment pins (pre-registry idiom)
        knobs().set("batch_wait_ms", 7.0)
        assert b.wait_ms == 1.0

    def test_batcher_ctor_value_pins(self):
        from pinot_tpu.cluster.batcher import MicroBatcher

        b = MicroBatcher(runner=lambda entries: None, wait_ms=3.0, clock=lambda: 0.0)
        knobs().set("batch_wait_ms", 6.0)
        assert b.wait_ms == 3.0

    def test_hedge_controller_live(self):
        from pinot_tpu.cluster.broker import HedgeController

        hc = HedgeController()
        assert hc.budget_pct == 10.0
        assert hc.quantile_mult == 1.0
        knobs().set_many({"hedge_budget_pct": 4.0, "hedge_delay_mult": 2.0})
        assert hc.budget_pct == 4.0
        assert hc.quantile_mult == 2.0
        hc.budget_pct = 60.0  # bench/test idiom still pins
        assert hc.budget_pct == 60.0

    def test_engine_pipeline_depth_live(self):
        from pinot_tpu.parallel.engine import DistributedEngine

        eng = object.__new__(DistributedEngine)  # property only, no mesh
        eng._pipeline_depth_override = None
        assert eng.pipeline_depth == 2
        knobs().set("pipeline_depth", 1)
        assert eng.pipeline_depth == 1
        eng.pipeline_depth = 2
        knobs().set("pipeline_depth", 1)
        assert eng.pipeline_depth == 2  # explicit assignment pins

    def test_server_staging_depth_live(self):
        from pinot_tpu.cluster.server import _staging_depth

        assert _staging_depth() == 2
        knobs().set("staging_depth", 1)
        assert _staging_depth() == 1

    def test_admission_rate_live(self, monkeypatch):
        from pinot_tpu.cluster.admission import AdmissionController

        monkeypatch.setenv("PINOT_TPU_ADMISSION_RATE", "100")
        adm = AdmissionController(
            rate_units_per_s=100.0, burst_units=10.0, knob="admission_rate"
        )
        assert adm.snapshot()["rate"] == 100.0
        knobs().set("admission_rate", 40.0)
        assert adm.snapshot()["rate"] == 40.0
        assert adm.snapshot()["staticRate"] == 100.0
        # registry clamp: the controller cannot raise the rate above env
        knobs().set("admission_rate", 500.0)
        assert adm.snapshot()["rate"] == 100.0

    def test_degradation_floor_live(self):
        from pinot_tpu.cluster.admission import DegradationController

        dc = DegradationController()
        assert dc.update(0.0) == 0
        knobs().set("degrade_level", 2)
        assert dc.update(0.0) == 2  # floor holds with zero occupancy
        assert dc.update(0.999) >= 2  # occupancy can push higher, not lower


# ---------------------------------------------------------------------------
# satellite 2: observability surface — GET /debug/autopilot + cli autopilot
# ---------------------------------------------------------------------------


def _small_cluster():
    import numpy as np

    from pinot_tpu.cluster.coordinator import Coordinator
    from pinot_tpu.cluster.server import ServerInstance
    from pinot_tpu.segment.builder import build_segment
    from pinot_tpu.spi.config import SegmentsConfig, TableConfig
    from pinot_tpu.spi.schema import DataType, FieldRole, FieldSpec, Schema

    schema = Schema(
        "t",
        [
            FieldSpec("city", DataType.STRING),
            FieldSpec("v", DataType.LONG, role=FieldRole.METRIC),
            FieldSpec("ts", DataType.TIMESTAMP, role=FieldRole.DATE_TIME),
        ],
    )
    coord = Coordinator(replication=1)
    coord.register_server(ServerInstance("server0"))
    coord.add_table(
        schema, TableConfig(name="t", segments=SegmentsConfig(time_column="ts"))
    )
    rng = np.random.default_rng(3)
    coord.add_segment(
        "t",
        build_segment(
            schema,
            {
                "city": rng.choice(["sf", "nyc"], 64).astype(object),
                "v": rng.integers(0, 100, 64),
                "ts": 1_700_000_000_000 + rng.integers(0, 1_000_000, 64).astype("int64"),
            },
            "s0",
        ),
    )
    return coord


class TestObservability:
    def _get(self, port, path):
        import json
        import urllib.request

        with urllib.request.urlopen(f"http://127.0.0.1:{port}{path}") as resp:
            return resp.status, json.loads(resp.read().decode())

    def test_debug_autopilot_detached(self):
        """Without an attached controller the endpoint still serves the
        registry view (enabled: false) — knob values vs clamp bounds."""
        from pinot_tpu.cluster.broker import Broker
        from pinot_tpu.cluster.rest import QueryServer

        broker = Broker(_small_cluster())
        srv = QueryServer(broker).start()
        try:
            code, payload = self._get(srv.port, "/debug/autopilot")
            assert code == 200
            assert payload["enabled"] is False
            k = payload["knobs"]["batch_wait_ms"]
            assert {"value", "initial", "lo", "hi", "overridden"} <= set(k)
        finally:
            srv.stop()

    def test_debug_autopilot_attached(self):
        from pinot_tpu.cluster.broker import Broker
        from pinot_tpu.cluster.rest import QueryServer

        broker = Broker(_small_cluster())
        broker.attach_autopilot()  # not started: tick() driven manually
        broker.autopilot.tick()
        srv = QueryServer(broker).start()
        try:
            code, payload = self._get(srv.port, "/debug/autopilot")
            assert code == 200
            assert payload["enabled"] is True
            assert payload["ticks"] == 1
            assert payload["decisions"][-1]["action"] in ("idle", "hold")
            assert payload["changeBound"]["maxChanges"] == 4
        finally:
            srv.stop()
            broker.attach_autopilot(controller=None)  # detach leaves no thread

    def test_cli_autopilot_renders(self, capsys):
        from pinot_tpu.cluster.broker import Broker
        from pinot_tpu.cluster.rest import QueryServer
        from pinot_tpu.tools.cli import main as cli_main

        broker = Broker(_small_cluster())
        broker.attach_autopilot()
        broker.autopilot.tick()
        knobs().set("batch_wait_ms", 4.0)
        srv = QueryServer(broker).start()
        try:
            rc = cli_main(["autopilot", "--url", f"http://127.0.0.1:{srv.port}"])
            assert rc == 0
            out = capsys.readouterr().out
            assert "autopilot : ON" in out
            assert "batch_wait_ms" in out and "*" in out  # override marker
            rc = cli_main(
                ["autopilot", "--url", f"http://127.0.0.1:{srv.port}", "--json"]
            )
            assert rc == 0
            import json

            payload = json.loads(capsys.readouterr().out)
            assert payload["knobs"]["batch_wait_ms"]["value"] == 4.0
        finally:
            srv.stop()

    def test_knob_gauges_published(self):
        from pinot_tpu.utils.metrics import METRICS

        knobs().set("batch_wait_ms", 4.0)
        assert METRICS.gauge("autopilot.knob.batch_wait_ms").value == 4.0

    def test_autopilot_env_toggle_attaches(self, monkeypatch):
        from pinot_tpu.cluster.broker import Broker

        monkeypatch.setenv("PINOT_TPU_AUTOPILOT", "1")
        broker = Broker(_small_cluster())
        try:
            assert broker.autopilot is not None
            assert broker.autopilot_snapshot()["enabled"] is True
        finally:
            broker.autopilot.stop()

    def test_autopilot_off_by_default(self, monkeypatch):
        monkeypatch.delenv("PINOT_TPU_AUTOPILOT", raising=False)
        from pinot_tpu.cluster.broker import Broker

        broker = Broker(_small_cluster())
        assert broker.autopilot is None
        assert broker.autopilot_snapshot()["enabled"] is False
