"""Tail-tolerant serving tests (round 15): hedged scatter, gray-failure
(brownout) detection, and the enriched network fault model.

Determinism: fault draws are keyed on (seed, server, call#) so logs are
bit-identical across runs and thread interleavings; jitter rules with
sigma=0 sleep EXACTLY base_ms; brownout/breaker clocks are injected.  The
one real-time test (TestTailAcceptance) uses latency magnitudes chosen so
scheduler noise of several ms cannot flip the asserted ratios.
"""
import statistics
import threading

import numpy as np
import pytest

from pinot_tpu.cluster import (
    Broker,
    Coordinator,
    FaultPlan,
    HedgeController,
    ServerFaultError,
    ServerHealth,
    ServerInstance,
)
from pinot_tpu.cluster.admission import AdmissionController, QueryKilledError
from pinot_tpu.segment.builder import build_segment
from pinot_tpu.spi.config import SegmentsConfig, TableConfig
from pinot_tpu.spi.schema import DataType, FieldRole, FieldSpec, Schema
from pinot_tpu.utils import perf
from pinot_tpu.utils.metrics import METRICS


def _schema():
    return Schema(
        "t",
        [
            FieldSpec("city", DataType.STRING),
            FieldSpec("v", DataType.LONG, role=FieldRole.METRIC),
            FieldSpec("ts", DataType.TIMESTAMP, role=FieldRole.DATE_TIME),
        ],
    )


def _data(n, seed, t0=1_700_000_000_000):
    rng = np.random.default_rng(seed)
    return {
        "city": rng.choice(["sf", "nyc", "la"], n).astype(object),
        "v": rng.integers(0, 100, n),
        "ts": t0 + rng.integers(0, 86_400_000, n).astype(np.int64),
    }


def _cluster(n_servers=2, replication=2, n_segments=4, rows=300):
    coord = Coordinator(replication=replication)
    for i in range(n_servers):
        coord.register_server(ServerInstance(f"server{i}"))
    coord.add_table(_schema(), TableConfig(name="t", segments=SegmentsConfig(time_column="ts")))
    for i in range(n_segments):
        coord.add_segment("t", build_segment(_schema(), _data(rows, seed=100 + i), f"seg{i}"))
    return coord


SQL = "SELECT city, COUNT(*), SUM(v) FROM t GROUP BY city ORDER BY city"


def _hedged(sql, delay_ms=5, budget_pct=100):
    return (
        f"SET hedge = true; SET hedgeDelayMs = {delay_ms}; "
        f"SET hedgeBudgetPct = {budget_pct}; " + sql
    )


def _fake_sleep(plan):
    """Replace plan.sleep with a recorder: clock-free fault tests."""
    slept = []
    plan.sleep = slept.append
    return slept


# ---------------------------------------------------------------------------
# enriched fault model
# ---------------------------------------------------------------------------
class TestFaultModelDeterminism:
    def test_jitter_log_bit_identical_across_runs(self):
        """Same seed -> identical draws, logs, and sleeps; the draw is keyed
        on (seed, server, call#) so thread interleaving can't change it."""
        logs, sleeps = [], []
        for _ in range(2):
            plan = FaultPlan(seed=42).jitter("server0", base_ms=10.0, sigma=0.7)
            s = _fake_sleep(plan)
            for _ in range(20):
                plan.on_execute("server0")
            logs.append(list(plan.log))
            sleeps.append(list(s))
        assert logs[0] == logs[1]
        assert sleeps[0] == sleeps[1]
        # sigma > 0 actually varies the draws (not a constant)
        details = [d for (_, _, kind, d) in logs[0] if kind == "jitter"]
        assert len(set(details)) > 1

    def test_jitter_seed_changes_draws(self):
        def draws(seed):
            plan = FaultPlan(seed=seed).jitter("server0", base_ms=10.0, sigma=0.7)
            _fake_sleep(plan)
            for _ in range(8):
                plan.on_execute("server0")
            return [d for (_, _, k, d) in plan.log if k == "jitter"]

        assert draws(1) != draws(2)

    def test_jitter_sigma_zero_is_exact_and_cap_clamps(self):
        plan = FaultPlan(seed=0).jitter("server0", base_ms=7.0, sigma=0.0)
        plan.jitter("server1", base_ms=100.0, sigma=0.0, cap_ms=9.0)
        s = _fake_sleep(plan)
        plan.on_execute("server0")
        plan.on_execute("server1")
        assert s == [0.007, 0.009]  # lognormvariate(0, 0) == 1.0; cap clamps

    def test_slow_ramp_monotone_then_capped(self):
        plan = FaultPlan(seed=0).slow_ramp("server0", ms_per_call=5.0, cap_ms=12.0)
        _fake_sleep(plan)
        for _ in range(4):
            plan.on_execute("server0")
        assert [d for (_, _, _, d) in plan.log] == [5.0, 10.0, 12.0, 12.0]

    def test_gray_flap_alternates_slow_and_clean(self):
        """period=2: calls 1-2 slow, 3-4 clean (no log entry, no sleep),
        5-6 slow again — the flapping gray failure brownout must chase."""
        plan = FaultPlan(seed=0).gray_flap("server0", slow_ms=8.0, period=2)
        s = _fake_sleep(plan)
        for _ in range(6):
            plan.on_execute("server0")
        assert [n for (_, n, _, _) in plan.log] == [1, 2, 5, 6]
        assert s == [0.008] * 4


class TestOneWayPartition:
    def test_direction_matters(self):
        """broker->server0 drops; server1->server0 (peer traffic) and
        broker->server1 are untouched."""
        plan = FaultPlan(seed=0).partition("broker", "server0")
        _fake_sleep(plan)
        with pytest.raises(ServerFaultError, match="broker->server0"):
            plan.on_execute("server0", source="broker")
        plan.on_execute("server0", source="server1")  # reverse-ish path: fine
        plan.on_execute("server1", source="broker")  # other server: fine

    def test_broker_fails_over_around_one_way_partition(self):
        coord = _cluster()
        clean = Broker(_cluster()).query(SQL)
        plan = FaultPlan(seed=3).partition("broker", "server0").attach(coord)
        _fake_sleep(plan)
        broker = Broker(coord)
        out = broker.query(SQL)
        assert out.rows == clean.rows
        assert any(k == "partition" for (_, _, k, _) in plan.log)


# ---------------------------------------------------------------------------
# hedge delay derivation (HedgeController unit)
# ---------------------------------------------------------------------------
class TestHedgeDelayDerivation:
    def test_delay_is_peer_p95_not_own_window(self):
        """A chronically slow primary must not inflate its own trigger: the
        delay comes from PEER windows only."""
        hc = HedgeController()
        hc.env_delay_ms = None
        hc.min_samples = 8
        for i in range(10):
            hc.observe("t", "slow", 500.0)  # primary's own window: ignored
            hc.observe("t", "fast", float(i + 1))  # peer p95 == 10.0
        assert hc.delay_ms("t", "slow") == pytest.approx(10.0)
        # for the FAST primary the slow peer sets the trigger
        assert hc.delay_ms("t", "fast") == pytest.approx(500.0)

    def test_cold_start_returns_none(self):
        hc = HedgeController()
        hc.env_delay_ms = None
        hc.min_samples = 8
        for _ in range(7):  # one short of min_samples
            hc.observe("t", "peer", 5.0)
        assert hc.delay_ms("t", "primary") is None

    def test_option_and_env_override_order(self):
        hc = HedgeController()
        hc.env_delay_ms = 7.5
        assert hc.delay_ms("t", "p") == 7.5  # env beats derivation
        assert hc.delay_ms("t", "p", {"hedgeDelayMs": 3}) == 3.0  # option beats env

    def test_budget_counter(self):
        hc = HedgeController()
        hc.budget_pct = 50.0
        for _ in range(4):
            hc.note_primary()
        assert hc.try_fire()  # 1 hedge / 4 primaries = 25%
        assert hc.try_fire()  # 50%: exactly at budget
        assert not hc.try_fire()  # 75% would exceed
        hc.unfire()
        assert hc.try_fire()


# ---------------------------------------------------------------------------
# hedged scatter (broker level)
# ---------------------------------------------------------------------------
class TestHedgedScatter:
    def _slow_cluster(self, slow_ms=60.0):
        coord = _cluster()
        FaultPlan(seed=7).jitter("server0", base_ms=slow_ms, sigma=0.0).attach(coord)
        return coord

    @staticmethod
    def _warm(broker, **hedge_kw):
        """Compile the SET-prefixed hedged shape once (different literal) so
        the measured query races sleeps, not a cold compile."""
        broker.query(
            _hedged("SELECT city, COUNT(*) FROM t WHERE v < 1 GROUP BY city", **hedge_kw)
        )

    def test_hedge_fires_backup_wins_loser_cancelled(self):
        clean = Broker(_cluster()).query(_hedged(SQL))
        broker = Broker(self._slow_cluster())
        self._warm(broker)
        out = broker.query(_hedged(SQL))
        assert out.rows == clean.rows
        assert out.stats.hedged >= 1
        assert out.stats.hedge_winner == "server1"
        assert METRICS.counter("broker.hedgesLaunched").value >= 1
        assert METRICS.counter("broker.hedgeWins").value >= 1
        assert broker.hedge_drain() == 0  # no leaked launches
        # every loser settled exactly once: cooperatively cancelled, or it
        # finished too late and was booked as hedge waste — never punished
        launched = METRICS.counter("broker.hedgesLaunched").value
        settled = (
            METRICS.timer("broker.hedgeCancelMs").count
            + METRICS.timer("broker.hedgeWastedMs").count
        )
        assert settled == launched

    def test_loser_cancel_is_not_a_failure(self):
        """Cooperative hedge cancel must not punish the loser: breaker stays
        closed, no quarantine, no scatter-failure accounting — exactly once
        means exactly zero here."""
        broker = Broker(self._slow_cluster())
        self._warm(broker)
        broker.query(_hedged(SQL))
        assert broker.hedge_drain() == 0
        assert broker.health.state("server0") == "closed"
        assert METRICS.counter("broker.scatterServerFailures").value == 0
        assert METRICS.counter("broker.serversQuarantined").value == 0

    def test_slowlog_surfaces_hedge_annotations(self):
        broker = Broker(self._slow_cluster())
        self._warm(broker)
        broker.query(_hedged(SQL))
        broker.hedge_drain()
        entry = broker.slow_queries.snapshot()[0]
        assert entry["hedge"]["hedged"] >= 1
        assert entry["hedge"]["winner"] == "server1"
        assert entry["hedge"]["cancelledMs"] >= 0.0

    def test_budget_zero_denies_hedge(self):
        clean = Broker(_cluster()).query(SQL)
        broker = Broker(self._slow_cluster(slow_ms=20.0))
        self._warm(broker, budget_pct=0)
        out = broker.query(_hedged(SQL, budget_pct=0))
        assert out.rows == clean.rows
        assert METRICS.counter("broker.hedgesLaunched").value == 0
        assert METRICS.counter("broker.hedgesDenied").value >= 1

    def test_disabled_by_default_no_threads(self):
        broker = Broker(self._slow_cluster(slow_ms=5.0))
        out = broker.query(SQL)
        assert out.stats.hedged == 0
        assert METRICS.counter("broker.hedgesLaunched").value == 0
        assert not broker._hedge_threads

    def test_no_spare_replica_runs_inline(self):
        """replication=1: no replica covers the primary's segments, so the
        call runs inline even with hedging enabled (no threads, no denial)."""
        coord = _cluster(replication=1)
        broker = Broker(coord)
        out = broker.query(_hedged(SQL))
        assert out.stats.hedged == 0
        assert METRICS.counter("broker.hedgesLaunched").value == 0
        assert not broker._hedge_threads

    def test_admission_sheds_hedges_before_primaries(self):
        """With the token bucket nearly drained, the primary's admission
        succeeds but the hedge's non-blocking charge fails: the hedge is the
        first thing shed, and the query still completes."""
        from pinot_tpu.cluster.admission import estimate_query_cost
        from pinot_tpu.sql.parser import parse_query

        clean = Broker(_cluster()).query(SQL)
        coord = self._slow_cluster(slow_ms=40.0)
        broker = Broker(coord)
        ctx = parse_query(SQL)
        cost = estimate_query_cost(ctx, coord.tables["t"].segment_meta.values()).units
        adm = AdmissionController(
            rate_units_per_s=1e-9, burst_units=cost + 0.5, max_queue=0
        )
        adm.clock = lambda: 0.0  # pinned: the bucket never refills
        broker.governor.admission = adm
        out = broker.query(_hedged(SQL))
        assert out.rows == clean.rows  # primary admitted and served
        assert METRICS.counter("broker.hedgesLaunched").value == 0
        assert METRICS.counter("broker.hedgesDenied").value >= 1
        assert broker.hedge_drain() == 0

    def test_try_charge_is_nonblocking_token_bucket(self):
        adm = AdmissionController(rate_units_per_s=1.0, burst_units=2.0, max_queue=4)
        now = [0.0]
        adm.clock = lambda: now[0]
        assert adm.try_charge(1.0)
        assert adm.try_charge(1.0)
        assert not adm.try_charge(1.0)  # bucket empty: refuse, never queue
        now[0] = 1.0  # one unit refilled
        assert adm.try_charge(1.0)
        assert not adm.try_charge(1.0)
        # permissive default (rate<=0) always grants
        assert AdmissionController().try_charge(1.0)


# ---------------------------------------------------------------------------
# brownout (gray-failure) detection
# ---------------------------------------------------------------------------
class TestBrownout:
    def _browned_health(self):
        h = ServerHealth(cooldown_s=30.0)
        now = [0.0]
        h.clock = lambda: now[0]
        for _ in range(8):
            h.note_latency("server1", 1.0)
        transitions = [h.note_latency("server0", 30.0) for _ in range(8)]
        return h, now, transitions

    def test_latency_outlier_enters_brownout(self):
        h, _, transitions = self._browned_health()
        assert transitions[-1] == "enter"
        assert transitions[:-1] == [None] * 7  # below min_samples: no verdict
        assert h.in_brownout("server0")
        assert h.brownout_deprioritized("server0")
        assert h.state("server0") == "brownout"
        assert h.available("server0")  # weighted away, never quarantined
        assert not h.in_brownout("server1")
        assert METRICS.counter("broker.serversBrownedOut").value == 1
        assert METRICS.gauge("broker.brownouts").value == 1.0

    def test_sub_floor_latencies_never_brown(self):
        """Microsecond-scale medians stay below brownout_min_ms: a 10x ratio
        on tiny absolute numbers must not shift routing."""
        h = ServerHealth()
        for _ in range(10):
            h.note_latency("server0", 1.0)  # 10x of 0.1 but under the 2ms floor
            h.note_latency("server1", 0.1)
        assert not h.in_brownout("server0")

    def test_breaker_and_brownout_are_independent(self):
        h, _, _ = self._browned_health()
        # breaker trips on top of the brownout; brownout state unmoved
        for _ in range(3):
            h.record_failure("server0")
        assert h.state("server0") == "open"
        assert h.in_brownout("server0")
        # breaker recovery does NOT clear the brownout
        h.record_success("server0")
        assert h.state("server0") == "brownout"
        assert h.in_brownout("server0")
        # and latency feeding never moved the breaker
        assert h.state("server1") == "closed"

    def test_recovery_probe_cycle(self):
        h, now, _ = self._browned_health()
        # inside the cooldown: deprioritized
        now[0] = 29.0
        assert h.brownout_deprioritized("server0")
        # cooldown elapsed: deprioritization lifts (probe window opens)
        # but the server is still marked browned until probes come back fast
        now[0] = 31.0
        assert not h.brownout_deprioritized("server0")
        assert h.in_brownout("server0")
        # a still-slow probe re-stamps the cooldown (failed probe)
        h.note_latency("server0", 30.0)
        assert h.brownout_deprioritized("server0")
        # probe traffic comes back at peer speed: flush the window fast...
        for _ in range(12):
            h.note_latency("server0", 1.0)
        assert h.in_brownout("server0")  # re-stamped cooldown still running
        # ...and once the re-stamped cooldown elapses, the next fast
        # evaluation clears the brownout
        now[0] = 62.0
        assert h.note_latency("server0", 1.0) == "exit"
        assert not h.in_brownout("server0")
        assert h.state("server0") == "closed"
        assert METRICS.counter("broker.brownoutRecoveries").value == 1

    def test_router_weights_away_browned_replica(self):
        coord = _cluster()
        broker = Broker(coord)
        for _ in range(8):
            broker.health.note_latency("server1", 1.0)
            broker.health.note_latency("server0", 30.0)
        assert broker.health.brownout_deprioritized("server0")
        assign = broker._route("t", ["seg0", "seg1", "seg2", "seg3"])
        assert set(assign) == {"server1"}
        # availability wins when EVERY candidate is browned
        for _ in range(32):
            broker.health.note_latency("server1", 31.0)
        if broker.health.in_brownout("server1"):
            assign = broker._route("t", ["seg0", "seg1"])
            assert assign  # still routes somewhere rather than failing


# ---------------------------------------------------------------------------
# batched scatter rides the hedge path
# ---------------------------------------------------------------------------
class TestBatchedHedging:
    def test_batched_hedged_bit_exact_and_losers_cancelled(self):
        sqls = [
            f"SELECT city, COUNT(*), SUM(v) FROM t WHERE v < {40 + i} "
            "GROUP BY city ORDER BY city"
            for i in range(4)
        ]
        clean = Broker(_cluster())
        expected = [clean.query(q) for q in sqls]

        coord = _cluster()
        FaultPlan(seed=7).jitter("server0", base_ms=50.0, sigma=0.0).attach(coord)
        broker = Broker(coord)
        broker.batch_clock = lambda: 0.0
        # warm the batched shape so the hedge races sleeps, not a compile
        broker.query(_hedged(sqls[0]))
        futs = [broker.submit(_hedged(q)) for q in sqls]
        assert broker.drain_batches() >= 1
        outs = [f.result() for f in futs]
        for out, exp in zip(outs, expected):
            assert out.rows == exp.rows  # per-member isolation: exact rows
        launched = METRICS.counter("broker.hedgesLaunched").value
        assert launched >= 1
        assert broker.hedge_drain() == 0
        # every loser reclaimed (batch losers return normally with all
        # members detached as hedge_lost kills) and none punished
        settled = (
            METRICS.timer("broker.hedgeCancelMs").count
            + METRICS.timer("broker.hedgeWastedMs").count
        )
        assert settled == launched
        assert METRICS.counter("broker.scatterServerFailures").value == 0
        assert sum(o.stats.hedged for o in outs) >= 1


# ---------------------------------------------------------------------------
# acceptance: one replica at 10x latency
# ---------------------------------------------------------------------------
class TestTailAcceptance:
    def test_hedged_p99_within_3x_fault_free_unhedged_beyond_8x(self):
        """The ISSUE's headline numbers: with one replica at 10x latency
        under a seeded fault plan, hedging clips the tail to <=3x the
        fault-free p99 while the unhedged tail blows past 8x.  The fault is
        calibrated off the MEASURED fault-free p99 (slow = 10x p99), which
        makes the 8x bound structural — every unhedged query serially waits
        out a sleep that is itself 10x the baseline tail — and leaves the
        3x bound a ~2x margin over scheduler noise."""
        import time as _time

        base_ms, n = 10.0, 8

        def leg(slow_ms, hedge, delay_ms=None):
            coord = _cluster(rows=150)
            plan = FaultPlan(seed=13).jitter("server1", base_ms=base_ms, sigma=0.0)
            plan.jitter("server0", base_ms=slow_ms or base_ms, sigma=0.0)
            plan.attach(coord)
            broker = Broker(coord)
            # warm with the SAME parameterized shape as the measured queries
            # — including the SET prefix, which is part of the fingerprint —
            # so a different literal keeps the result cache cold while the
            # plan/compile caches are hot
            warm = "SELECT city, COUNT(*), SUM(v) FROM t WHERE v < 59 GROUP BY city ORDER BY city"
            broker.query(_hedged(warm, delay_ms=delay_ms) if hedge else warm)
            ts = []
            for i in range(n):
                sql = f"SELECT city, COUNT(*), SUM(v) FROM t WHERE v < {60 + i} GROUP BY city ORDER BY city"
                if hedge:
                    sql = _hedged(sql, delay_ms=delay_ms)
                t0 = _time.perf_counter()
                broker.query(sql)
                ts.append((_time.perf_counter() - t0) * 1000)
            return broker, float(np.percentile(ts, 99))

        _, ff_p99 = leg(slow_ms=None, hedge=False)
        slow_ms = 10.0 * ff_p99  # "one replica at 10x latency"
        _, un_p99 = leg(slow_ms, hedge=False)
        # hedge trigger at ~half the baseline tail: past every healthy reply
        broker, hd_p99 = leg(slow_ms, hedge=True, delay_ms=round(0.5 * ff_p99, 3))

        assert un_p99 >= 8.0 * ff_p99, (un_p99, ff_p99)
        assert hd_p99 <= 3.0 * ff_p99, (hd_p99, ff_p99)
        # budget respected: hedges never exceed 100% of primary launches
        snap = broker.hedge.snapshot()
        assert 1 <= snap["hedges"] <= snap["primaries"]
        # every loser reclaimed, nothing leaked, punish exactly zero times
        assert broker.hedge_drain(timeout_s=10.0) == 0
        launched = METRICS.counter("broker.hedgesLaunched").value
        settled = (
            METRICS.timer("broker.hedgeCancelMs").count
            + METRICS.timer("broker.hedgeWastedMs").count
        )
        assert launched >= n  # the slow replica's half of every query hedged
        assert settled == launched  # one loser per engaged pair, all reclaimed
        assert METRICS.counter("broker.scatterServerFailures").value == 0
        assert broker.health.state("server0") == "closed"


# ---------------------------------------------------------------------------
# perf gate: hedged_p99_ms is lower-is-better
# ---------------------------------------------------------------------------
class TestPerfGateLowerIsBetter:
    @staticmethod
    def _rec(hedged_p99):
        return {
            "schema": 1,
            "bench": "ssb_groupby",
            "backend": "cpu",
            "rows": 1000,
            "metrics": {"kernel_rows_per_sec": 1e9, "hedged_p99_ms": hedged_p99},
        }

    def test_latency_rise_fails_the_gate(self):
        v = perf.check_regression(self._rec(14.0), self._rec(10.0), threshold=0.10)
        assert not v["ok"]
        assert any("hedged_p99_ms" in r for r in v["reasons"])

    def test_latency_drop_passes_the_gate(self):
        v = perf.check_regression(self._rec(8.0), self._rec(10.0), threshold=0.10)
        assert v["ok"]

    def test_bench_record_extracts_tail_section(self):
        rec = perf.bench_record(
            {
                "backend": "cpu",
                "tail_latency": {
                    "hedged": {"p99_ms": 12.5},
                    "unhedged": {"p99_ms": 80.0},
                    "hedge_rate": 0.44,
                },
            }
        )
        assert rec["metrics"]["hedged_p99_ms"] == 12.5
        assert rec["metrics"]["unhedged_p99_ms"] == 80.0
        assert rec["metrics"]["hedge_rate"] == 0.44
