"""Ordered funnel (TIMESTAMPBY) — ADVICE r5: the set-intersection funnel
ignores event order and inflates; the ordered form counts a step only when
it occurs AFTER the chain's previous step (optionally within a window of
the chain's first step).  Golden model: brute-force per-key DP in Python.
"""
import numpy as np
import pytest

from pinot_tpu.query.engine import QueryEngine
from pinot_tpu.segment.builder import build_segment
from pinot_tpu.spi.schema import DataType, FieldSpec, Schema

CONDS = ["/home", "/product", "/cart", "/checkout"]
STEPS_SQL = (
    "STEPS(url = '/home', url = '/product', url = '/cart', url = '/checkout')"
)


def _schema():
    return Schema(
        "events",
        [
            FieldSpec("uid", DataType.LONG),
            FieldSpec("url", DataType.STRING),
            FieldSpec("ts", DataType.LONG),
        ],
    )


def _world(n=4000, keys=120, seed=5, n_segments=1, partition_by_key=False):
    """partition_by_key keeps each uid's events in ONE segment — the regime
    where multi-segment ordered results are exact (reach merges by max)."""
    rng = np.random.default_rng(seed)
    uid = rng.integers(0, keys, n).astype(np.int64)
    url = rng.choice(CONDS, n, p=[0.4, 0.3, 0.2, 0.1])
    ts = rng.integers(0, 100_000, n).astype(np.int64)
    eng = QueryEngine()
    eng.register_table(_schema())
    if n_segments == 1:
        parts = [np.arange(n)]
    elif partition_by_key:
        parts = [np.where(uid % n_segments == i)[0] for i in range(n_segments)]
    else:
        parts = np.array_split(np.arange(n), n_segments)
    for i, idx in enumerate(parts):
        eng.add_segment(
            "events",
            build_segment(
                _schema(),
                {"uid": uid[idx], "url": url[idx], "ts": ts[idx]},
                f"s{i}",
            ),
        )
    return eng, uid, url, ts


def _oracle_reach(uid, url, ts, conds, window=float("inf")):
    """Per-key deepest ordered step: DP over time-sorted events carrying the
    latest chain-start timestamp per step (mirrors the device scan)."""
    S = len(conds)
    state = {}
    for i in np.argsort(ts, kind="stable"):
        u, t = uid[i], ts[i]
        prev = state.setdefault(u, [None] * S)
        new = list(prev)
        if url[i] == conds[0]:
            new[0] = t
        for s in range(1, S):
            if url[i] == conds[s] and prev[s - 1] is not None and t - prev[s - 1] <= window:
                new[s] = prev[s - 1] if prev[s] is None else max(prev[s], prev[s - 1])
        state[u] = new
    return {u: sum(1 for v in st if v is not None) for u, st in state.items()}


def _expected(reach, n_steps):
    counts = [sum(1 for r in reach.values() if r > s) for s in range(n_steps)]
    complete = sum(1 for r in reach.values() if r >= n_steps)
    maxstep = max(reach.values()) if reach else 0
    return counts, complete, maxstep


class TestOrderedFunnel:
    @pytest.mark.parametrize("window_sql,window", [("", float("inf")), (", 20000", 20000)])
    def test_oracle_parity_single_segment(self, window_sql, window):
        eng, uid, url, ts = _world()
        counts, complete, maxstep = _expected(_oracle_reach(uid, url, ts, CONDS, window), 4)
        got = eng.query(
            f"SELECT FUNNELCOUNT({STEPS_SQL}, CORRELATEBY(uid), TIMESTAMPBY(ts){window_sql}) "
            "FROM events"
        ).rows[0][0]
        assert got == counts
        row = eng.query(
            f"SELECT FUNNELCOMPLETECOUNT({STEPS_SQL}, CORRELATEBY(uid), TIMESTAMPBY(ts){window_sql}), "
            f"FUNNELMAXSTEP({STEPS_SQL}, CORRELATEBY(uid), TIMESTAMPBY(ts){window_sql}) FROM events"
        ).rows[0]
        assert int(row[0]) == complete
        assert int(row[1]) == maxstep

    def test_ordered_never_exceeds_set_form(self, ):
        eng, uid, url, ts = _world(seed=9)
        unordered = eng.query(
            f"SELECT FUNNELCOMPLETECOUNT({STEPS_SQL}, CORRELATEBY(uid)) FROM events"
        ).rows[0][0]
        ordered = eng.query(
            f"SELECT FUNNELCOMPLETECOUNT({STEPS_SQL}, CORRELATEBY(uid), TIMESTAMPBY(ts)) "
            "FROM events"
        ).rows[0][0]
        assert int(ordered) <= int(unordered)

    def test_order_actually_enforced(self):
        """One key sees checkout BEFORE the earlier steps: the set form
        counts it complete, the ordered form must not."""
        eng = QueryEngine()
        eng.register_table(_schema())
        data = {
            "uid": np.array([1, 1, 1, 1, 2, 2, 2, 2], dtype=np.int64),
            # uid 1 in order; uid 2 reversed
            "url": np.array(CONDS + CONDS[::-1], dtype=object),
            "ts": np.array([10, 20, 30, 40, 10, 20, 30, 40], dtype=np.int64),
        }
        eng.add_segment("events", build_segment(_schema(), data, "s0"))
        set_form = eng.query(
            f"SELECT FUNNELCOMPLETECOUNT({STEPS_SQL}, CORRELATEBY(uid)) FROM events"
        ).rows[0][0]
        ordered = eng.query(
            f"SELECT FUNNELCOMPLETECOUNT({STEPS_SQL}, CORRELATEBY(uid), TIMESTAMPBY(ts)) "
            "FROM events"
        ).rows[0][0]
        assert int(set_form) == 2  # both keys hit all 4 urls
        assert int(ordered) == 1  # only uid 1 hit them in order

    def test_window_bounds_chain_from_first_step(self):
        eng = QueryEngine()
        eng.register_table(_schema())
        data = {
            "uid": np.array([1, 1, 1, 2, 2, 2], dtype=np.int64),
            "url": np.array(
                ["/home", "/product", "/cart", "/home", "/product", "/cart"], dtype=object
            ),
            # uid 1 finishes within 50 of its start; uid 2 strays past it
            "ts": np.array([0, 20, 50, 0, 20, 51], dtype=np.int64),
        }
        eng.add_segment("events", build_segment(_schema(), data, "s0"))
        q = (
            "SELECT FUNNELCOMPLETECOUNT(STEPS(url = '/home', url = '/product', url = '/cart'), "
            "CORRELATEBY(uid), TIMESTAMPBY(ts), 50) FROM events"
        )
        assert int(eng.query(q).rows[0][0]) == 1

    def test_multi_segment_key_partitioned_exact(self):
        eng, uid, url, ts = _world(seed=13, n_segments=3, partition_by_key=True)
        counts, complete, maxstep = _expected(_oracle_reach(uid, url, ts, CONDS), 4)
        got = eng.query(
            f"SELECT FUNNELCOUNT({STEPS_SQL}, CORRELATEBY(uid), TIMESTAMPBY(ts)) FROM events"
        ).rows[0][0]
        assert got == counts
        row = eng.query(
            f"SELECT FUNNELCOMPLETECOUNT({STEPS_SQL}, CORRELATEBY(uid), TIMESTAMPBY(ts)), "
            f"FUNNELMAXSTEP({STEPS_SQL}, CORRELATEBY(uid), TIMESTAMPBY(ts)) FROM events"
        ).rows[0]
        assert int(row[0]) == complete
        assert int(row[1]) == maxstep

    def test_multi_segment_unpartitioned_never_inflates(self):
        """Chains spanning segments may undercount (documented) but the
        merged result must never exceed the single-segment exact answer."""
        eng1, uid, url, ts = _world(seed=17, n_segments=1)
        eng3, _, _, _ = _world(seed=17, n_segments=3)
        q = f"SELECT FUNNELCOUNT({STEPS_SQL}, CORRELATEBY(uid), TIMESTAMPBY(ts)) FROM events"
        exact = eng1.query(q).rows[0][0]
        merged = eng3.query(q).rows[0][0]
        assert all(m <= e for m, e in zip(merged, exact))
        assert merged[0] == exact[0]  # step 1 needs no ordering — always exact

    def test_grouped_ordered_funnel(self):
        eng, uid, url, ts = _world(seed=21)
        res = eng.query(
            "SELECT uid, FUNNELMAXSTEP(STEPS(url = '/home', url = '/product'), "
            "CORRELATEBY(uid), TIMESTAMPBY(ts)) FROM events GROUP BY uid ORDER BY uid"
        )
        reach = _oracle_reach(uid, url, ts, ["/home", "/product"])
        for u, got in res.rows:
            assert int(got) == reach.get(u, 0), u

    def test_window_without_timestampby_rejected(self):
        from pinot_tpu.sql.parser import SqlParseError, parse_query

        with pytest.raises(SqlParseError):
            parse_query(
                f"SELECT FUNNELCOUNT({STEPS_SQL}, CORRELATEBY(uid), 500) FROM events"
            )
