"""SQL surface breadth: window functions, set operations, IN-subqueries.

sqlite supports all three natively — direct goldens.  Reference model:
WindowAggregateOperator, MSE set operators, Calcite semi-join rewrite.
"""
import numpy as np
import pytest

from pinot_tpu.query.engine import QueryEngine
from pinot_tpu.segment.builder import build_segment
from pinot_tpu.spi.schema import DataType, FieldRole, FieldSpec, Schema

from golden import assert_same_rows, sqlite_from_data

N = 4000


def _schema(name="t"):
    return Schema(
        name,
        [
            FieldSpec("city", DataType.STRING),
            FieldSpec("dept", DataType.STRING),
            FieldSpec("v", DataType.LONG, role=FieldRole.METRIC),
            FieldSpec("score", DataType.DOUBLE, role=FieldRole.METRIC),
        ],
    )


@pytest.fixture(scope="module")
def env():
    rng = np.random.default_rng(53)
    data = {
        "city": rng.choice(["sf", "nyc", "la"], N).astype(object),
        "dept": rng.choice(["eng", "ops", "biz", "hr"], N).astype(object),
        "v": rng.integers(0, 10_000, N),  # effectively unique-ish order key
        "score": np.round(rng.random(N) * 100, 3),
    }
    eng = QueryEngine()
    eng.register_table(_schema())
    # two segments: window/set-op results must merge globally first
    for i, sl in enumerate([slice(0, N // 2), slice(N // 2, N)]):
        chunk = {k: val[sl] for k, val in data.items()}
        eng.add_segment("t", build_segment(_schema(), chunk, f"s{i}"))
    conn = sqlite_from_data("t", data)
    return eng, conn


class TestWindowFunctions:
    def test_row_number_per_partition(self, env):
        eng, conn = env
        sql = (
            "SELECT city, v, ROW_NUMBER() OVER (PARTITION BY city ORDER BY v) FROM t "
            "WHERE v < 200 ORDER BY city, v LIMIT 300"
        )
        assert_same_rows(eng.query(sql).rows, conn.execute(sql).fetchall(), ordered=True)

    def test_rank_dense_rank(self, env):
        eng, conn = env
        sql = (
            "SELECT dept, v, RANK() OVER (PARTITION BY dept ORDER BY v DESC), "
            "DENSE_RANK() OVER (PARTITION BY dept ORDER BY v DESC) FROM t "
            "WHERE v > 9800 ORDER BY dept, v DESC LIMIT 200"
        )
        assert_same_rows(eng.query(sql).rows, conn.execute(sql).fetchall(), ordered=True)

    def test_partition_aggregates(self, env):
        eng, conn = env
        sql_p = (
            "SELECT city, v, SUM(v) OVER (PARTITION BY city), COUNT(*) OVER (PARTITION BY city), "
            "AVG(score) OVER (PARTITION BY city) FROM t WHERE v < 100 ORDER BY city, v LIMIT 100"
        )
        # sqlite computes whole-partition frames for these by default
        assert_same_rows(eng.query(sql_p).rows, conn.execute(sql_p).fetchall(), ordered=True)

    def test_global_window_no_partition(self, env):
        eng, conn = env
        sql = "SELECT v, ROW_NUMBER() OVER (ORDER BY v DESC) FROM t WHERE v > 9950 ORDER BY v DESC LIMIT 60"
        assert_same_rows(eng.query(sql).rows, conn.execute(sql).fetchall(), ordered=True)

    def test_window_spans_segments(self, env):
        """Partition counts must cover rows from BOTH segments."""
        eng, conn = env
        sql_p = "SELECT city, COUNT(*) OVER (PARTITION BY city) FROM t LIMIT 100000"
        got = {(r[0], r[1]) for r in eng.query(sql_p).rows}
        expected = {(r[0], r[1]) for r in conn.execute("SELECT city, COUNT(*) FROM t GROUP BY city").fetchall()}
        assert got == expected


class TestSetOps:
    def test_union_all(self, env):
        eng, conn = env
        sql = "SELECT city FROM t WHERE v > 9990 UNION ALL SELECT city FROM t WHERE v < 10 LIMIT 100"
        p = "SELECT city FROM t WHERE v > 9990 LIMIT 100 UNION ALL SELECT city FROM t WHERE v < 10 LIMIT 100"
        assert_same_rows(eng.query(p).rows, conn.execute(sql).fetchall())

    def test_union_dedupes(self, env):
        eng, conn = env
        p = "SELECT city, dept FROM t WHERE v > 5000 LIMIT 100000 UNION SELECT city, dept FROM t WHERE v <= 5000 LIMIT 100000"
        res = eng.query(p)
        expected = conn.execute("SELECT DISTINCT city, dept FROM t").fetchall()
        assert_same_rows(res.rows, expected)

    def test_intersect_and_except(self, env):
        eng, conn = env
        p_i = "SELECT city FROM t WHERE dept = 'eng' LIMIT 100000 INTERSECT SELECT city FROM t WHERE dept = 'hr' LIMIT 100000"
        expected_i = conn.execute(
            "SELECT city FROM t WHERE dept = 'eng' INTERSECT SELECT city FROM t WHERE dept = 'hr'"
        ).fetchall()
        assert_same_rows(eng.query(p_i).rows, expected_i)
        p_e = "SELECT dept FROM t WHERE city = 'sf' LIMIT 100000 EXCEPT SELECT dept FROM t WHERE v > 9999 LIMIT 100000"
        expected_e = conn.execute(
            "SELECT dept FROM t WHERE city = 'sf' EXCEPT SELECT dept FROM t WHERE v > 9999"
        ).fetchall()
        assert_same_rows(eng.query(p_e).rows, expected_e)


class TestSemiJoin:
    def test_in_subquery(self, env):
        eng, conn = env
        sql = "SELECT COUNT(*) FROM t WHERE dept IN (SELECT dept FROM t WHERE score > 99.8)"
        assert_same_rows(eng.query(sql).rows, conn.execute(sql).fetchall())

    def test_not_in_subquery(self, env):
        eng, conn = env
        sql = "SELECT COUNT(*) FROM t WHERE city NOT IN (SELECT city FROM t WHERE score > 99.97)"
        assert_same_rows(eng.query(sql).rows, conn.execute(sql).fetchall())

    def test_empty_subquery_matches_nothing(self, env):
        eng, conn = env
        sql = "SELECT COUNT(*) FROM t WHERE dept IN (SELECT dept FROM t WHERE v > 10000000)"
        assert_same_rows(eng.query(sql).rows, conn.execute(sql).fetchall())


class TestReviewRegressions:
    """Round-4 review findings pinned."""

    def test_star_plus_window(self, env):
        """SELECT * alongside a window function: correct values, no internal
        column leakage (review finding: placeholder index mismatch)."""
        eng, conn = env
        sql = "SELECT *, ROW_NUMBER() OVER (ORDER BY v DESC) FROM t WHERE v > 9990 ORDER BY v DESC LIMIT 40"
        res = eng.query(sql)
        assert not any(c.startswith("__wx") for c in res.columns)
        expected = conn.execute(
            "SELECT city, dept, v, score, ROW_NUMBER() OVER (ORDER BY v DESC) FROM t WHERE v > 9990 ORDER BY v DESC LIMIT 40"
        ).fetchall()
        assert_same_rows(res.rows, expected, ordered=True)

    def test_intersect_binds_tighter_than_union(self, env):
        eng, conn = env
        p = (
            "SELECT dept FROM t WHERE city = 'sf' LIMIT 100000 "
            "UNION SELECT dept FROM t WHERE city = 'nyc' LIMIT 100000 "
            "INTERSECT SELECT dept FROM t WHERE v > 9995 LIMIT 100000"
        )
        # sqlite itself is left-associative (non-standard), so nest the
        # golden explicitly: a UNION (b INTERSECT c)
        expected = conn.execute(
            "SELECT dept FROM t WHERE city = 'sf' "
            "UNION SELECT * FROM (SELECT dept FROM t WHERE city = 'nyc' "
            "INTERSECT SELECT dept FROM t WHERE v > 9995)"
        ).fetchall()
        assert_same_rows(eng.query(p).rows, expected)

    def test_explain_with_set_ops_is_one_plan(self, env):
        eng, conn = env
        res = eng.query("EXPLAIN PLAN FOR SELECT city FROM t WHERE v > 10 LIMIT 5 UNION SELECT city FROM t LIMIT 5")
        assert res.columns == ["Operator", "Operator_Id", "Parent_Id"]
        ids = [r[1] for r in res.rows]
        assert len(ids) == len(set(ids))  # one coherent plan, not a union of two

    def test_selection_order_by_expression(self, env):
        """ORDER BY <expr> on selection queries (round-2 weak #5 cliff)."""
        eng, conn = env
        sql = "SELECT city, v, score FROM t WHERE v > 9900 ORDER BY v * 2 + score DESC LIMIT 30"
        assert_same_rows(eng.query(sql).rows, conn.execute(sql).fetchall(), ordered=True)

    def test_selection_order_by_string_function(self, env):
        eng, conn = env
        sql = "SELECT dept, v FROM t WHERE v > 9950 ORDER BY UPPER(dept), v LIMIT 40"
        assert_same_rows(eng.query(sql).rows, conn.execute(sql).fetchall(), ordered=True)


class TestPostAggregation:
    """Post-aggregation arithmetic (PostAggregationFunction analog)."""

    def test_select_post_agg_groupby(self, env):
        eng, conn = env
        sql = "SELECT city, SUM(v) * 1.0 / COUNT(*) FROM t GROUP BY city ORDER BY city"
        assert_same_rows(eng.query(sql).rows, conn.execute(sql).fetchall(), ordered=True)

    def test_select_post_agg_scalar(self, env):
        eng, conn = env
        sql = "SELECT SUM(v) * 1.0 / COUNT(*), MAX(v) - MIN(v) FROM t"
        assert_same_rows(eng.query(sql).rows, conn.execute(sql).fetchall())

    def test_having_post_agg(self, env):
        eng, conn = env
        sql = (
            "SELECT dept, COUNT(*) FROM t GROUP BY dept "
            "HAVING SUM(v) * 1.0 / COUNT(*) > 5000 ORDER BY dept"
        )
        assert_same_rows(eng.query(sql).rows, conn.execute(sql).fetchall(), ordered=True)

    def test_order_by_post_agg(self, env):
        eng, conn = env
        sql = "SELECT dept, SUM(score) FROM t GROUP BY dept ORDER BY SUM(score) * 1.0 / COUNT(*) DESC"
        assert_same_rows(eng.query(sql).rows, conn.execute(sql).fetchall(), ordered=True)


class TestRunningFrames:
    """ROWS BETWEEN UNBOUNDED PRECEDING AND CURRENT ROW (running frames)."""

    def test_running_sum_and_count(self, env):
        eng, conn = env
        sql = (
            "SELECT city, v, "
            "SUM(v) OVER (PARTITION BY city ORDER BY v ROWS BETWEEN UNBOUNDED PRECEDING AND CURRENT ROW), "
            "COUNT(*) OVER (PARTITION BY city ORDER BY v ROWS BETWEEN UNBOUNDED PRECEDING AND CURRENT ROW) "
            "FROM t WHERE v < 150 ORDER BY city, v LIMIT 200"
        )
        assert_same_rows(eng.query(sql).rows, conn.execute(sql).fetchall(), ordered=True)

    def test_running_min_avg(self, env):
        eng, conn = env
        sql = (
            "SELECT dept, v, "
            "MIN(score) OVER (PARTITION BY dept ORDER BY v ROWS BETWEEN UNBOUNDED PRECEDING AND CURRENT ROW), "
            "AVG(score) OVER (PARTITION BY dept ORDER BY v ROWS BETWEEN UNBOUNDED PRECEDING AND CURRENT ROW) "
            "FROM t WHERE v > 9900 ORDER BY dept, v LIMIT 120"
        )
        assert_same_rows(eng.query(sql).rows, conn.execute(sql).fetchall(), ordered=True)
