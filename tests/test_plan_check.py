"""Plan-time static checker (pinot_tpu.analysis.plan_check).

Every malformed-plan class must be rejected BEFORE the planner traces into
jax.jit, with a stable machine code; every plan the executors accepted
before the checker existed must still pass."""
import numpy as np
import pytest

from pinot_tpu.analysis.plan_check import (
    PlanCheckError,
    check_plan,
    collect_issues,
)
from pinot_tpu.query.engine import QueryEngine
from pinot_tpu.query.ir import AggregationSpec, Expr, QueryContext
from pinot_tpu.segment.builder import build_segment
from pinot_tpu.spi.schema import DataType, FieldRole, FieldSpec, Schema

N = 500


@pytest.fixture(scope="module")
def eng():
    rng = np.random.default_rng(11)
    schema = Schema(
        "demo",
        [
            FieldSpec("city", DataType.STRING),
            FieldSpec("amount", DataType.DOUBLE, role=FieldRole.METRIC),
            FieldSpec("n", DataType.INT, role=FieldRole.METRIC),
            FieldSpec("big", DataType.LONG, role=FieldRole.METRIC),
            FieldSpec("ts", DataType.TIMESTAMP, role=FieldRole.DATE_TIME),
        ],
    )
    e = QueryEngine()
    e.register_table(schema)
    data = {
        "city": rng.choice(["sf", "nyc", "tokyo"], N).astype(object),
        "amount": np.round(rng.random(N) * 100, 2),
        "n": rng.integers(0, 100, N).astype(np.int32),
        "big": rng.integers(0, 1 << 40, N),
        "ts": 1_700_000_000_000 + rng.integers(0, 30 * 86_400_000, N),
    }
    e.add_segment("demo", build_segment(schema, data, "demo_0"))
    return e


GOOD = [
    "SELECT COUNT(*) FROM demo",
    "SELECT city, SUM(amount) FROM demo GROUP BY city ORDER BY SUM(amount) DESC",
    "SELECT MAX(amount) - MIN(amount) FROM demo",
    "SELECT DATETRUNC('day', ts), COUNT(*) FROM demo GROUP BY DATETRUNC('day', ts)",
    "SELECT city, SUM(n) FROM demo GROUP BY city HAVING SUM(n) > 10",
    "SELECT DISTINCTCOUNTHLL(city) FROM demo",
    "SELECT city AS c, COUNT(*) FROM demo GROUP BY city ORDER BY c",
    "SELECT PERCENTILE(amount, 95) FROM demo",
    "SELECT SUM(amount) FROM demo WHERE n BETWEEN 5 AND 50",
]


@pytest.mark.parametrize("sql", GOOD)
def test_valid_plans_pass(eng, sql):
    res = eng.sql(sql)
    assert res.rows is not None


# (sql, expected machine code) — each a DISTINCT malformed-plan class
BAD = [
    ("SELECT FROBNICATE(amount) FROM demo", "UNKNOWN_FUNCTION"),
    ("SELECT SUM(MAX(amount)) FROM demo", "NESTED_AGGREGATION"),
    ("SELECT city FROM demo WHERE SUM(amount) > 10", "NESTED_AGGREGATION"),
    ("SELECT POWER(n) FROM demo", "BAD_ARITY"),
    ("SELECT COUNT(*) FROM demo WHERE n = 'abc'", "TYPE_MISMATCH"),
    ("SELECT COUNT(*) FROM demo WHERE REGEXP_LIKE(n, 'a.*')", "TYPE_MISMATCH"),
    ("SELECT COUNT(*) FROM demo WHERE n = 99999999999", "INT32_OVERFLOW"),
    ("SELECT nosuchcol FROM demo", "UNKNOWN_COLUMN"),
    ("SELECT COUNT(*) FROM demo WHERE n = 1.5", "WEAK_TYPE_PROMOTION"),
    ("SELECT city, COUNT(*) FROM demo GROUP BY city ORDER BY amount", "BAD_ORDER_BY"),
]


@pytest.mark.parametrize("sql,code", BAD, ids=[c for _, c in BAD])
def test_malformed_plans_rejected(eng, sql, code):
    with pytest.raises(PlanCheckError) as ei:
        eng.sql(sql)
    assert ei.value.code == code
    d = ei.value.to_dict()
    assert d["errorCode"] == code and d["error"]


def test_ungroupable_literal_key():
    # the parser never emits literal group keys; direct IR can
    ctx = QueryContext(
        table="demo",
        select_list=[AggregationSpec(function="count", expr=None)],
        group_by=[Expr.lit(7)],
    )
    with pytest.raises(PlanCheckError) as ei:
        check_plan(ctx)
    assert ei.value.code == "UNGROUPABLE_KEY"


def test_bad_limit_and_offset():
    ctx = QueryContext(table="demo", select_list=[Expr.col("city")], limit=-1)
    with pytest.raises(PlanCheckError) as ei:
        check_plan(ctx)
    assert ei.value.code == "BAD_LIMIT"
    ctx = QueryContext(table="demo", select_list=[Expr.col("city")], offset=-5)
    issues = collect_issues(ctx)
    assert [i.code for i in issues] == ["BAD_LIMIT"]


def test_plan_check_error_is_valueerror():
    # pre-existing callers catch ValueError; the checker must not change that
    assert issubclass(PlanCheckError, ValueError)


def test_collect_issues_reports_all_defects():
    ctx = QueryContext(
        table="demo",
        select_list=[Expr.call("frobnicate", Expr.col("city"))],
        group_by=[Expr.lit(1)],
        limit=-2,
    )
    codes = {i.code for i in collect_issues(ctx)}
    assert {"UNKNOWN_FUNCTION", "UNGROUPABLE_KEY", "BAD_LIMIT"} <= codes


def test_rest_surface_maps_to_structured_400(eng):
    """A statically-rejected plan must surface to HTTP clients as a 400 with
    the machine code — never a 500 tracer traceback."""
    import json
    import urllib.error
    import urllib.request

    from pinot_tpu.cluster.rest import QueryServer

    server = QueryServer(eng).start()
    try:
        body = json.dumps({"sql": "SELECT SUM(MAX(amount)) FROM demo"}).encode()
        req = urllib.request.Request(
            f"http://127.0.0.1:{server.port}/query/sql",
            data=body,
            headers={"Content-Type": "application/json"},
        )
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(req)
        assert ei.value.code == 400
        payload = json.loads(ei.value.read().decode())
        assert payload["errorCode"] == "NESTED_AGGREGATION"
    finally:
        server.stop()
